"""Batched data-parallel training + vectorized evaluation.

The TPU-idiomatic mode the reference lacks (SURVEY.md §7.6): instead of
per-sample SGD with a data-dependent convergence loop, samples are
batched, one steepest-descent step is taken per minibatch on the mean
error, and gradients are allreduced over the mesh's ``data`` axis
(parallel/dp.py).  The update schedule intentionally differs from the
reference's per-sample protocol, so this ships as a distinct opt-in mode
(``train_nn --batch N``) whose acceptance bar is final accuracy, not
bitwise parity.

Evaluation (``run_nn --batch``) is semantics-preserving: the same
argmax-vs-target rules as the per-sample driver (train/driver.py), just
computed with one vmapped forward pass over the whole test set instead
of 10k single-vector dispatches.

Stdout protocol (new tokens, same grep-able style):

    NN: BATCH EPOCH %4i loss= %.10f acc= %7.3f%% (%i/%i)
"""

from __future__ import annotations

import os
import sys

import numpy as np

from hpnn_tpu import obs
from hpnn_tpu.config import NNConf, NNTrain, NNType
from hpnn_tpu.fileio import samples as sample_io
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.parallel import dp, mesh as mesh_mod
from hpnn_tpu.utils import logging as log
from hpnn_tpu.utils import trace as trace_mod


def _compute_dtype():
    from hpnn_tpu.train.driver import _compute_dtype as cd

    return cd()


def default_mesh(spec: str | None = None):
    """``data×model`` mesh from a "DxM" spec string, or all devices on
    the data axis (pure DP) by default.

    Slice-aware: when the spec covers every attached device (or no spec
    is given), the mesh comes from ``dist.hybrid_mesh`` so that on a
    multi-slice pod the data axis rides DCN and the model axis stays
    inside a slice; a sub-mesh spec falls back to a contiguous mesh.
    """
    import jax

    from hpnn_tpu.parallel import dist

    if spec:
        d, m = (int(v) for v in spec.lower().split("x"))
        if d * m == jax.device_count():
            return dist.hybrid_mesh(n_model=m)
        return mesh_mod.make_mesh(n_data=d, n_model=m)
    return dist.hybrid_mesh(n_model=1)


def _model_of(conf: NNConf) -> str:
    return "snn" if conf.type in (NNType.SNN, NNType.LNN) else "ann"


def _resolve_seed(conf: NNConf) -> None:
    """Materialize a ``[seed] 0`` conf seed (rank-0 clock broadcast —
    see dist.resolve_time_seed; the shuffle replay depends on it,
    ref: src/libhpnn.c:1218-1229).  Usually a no-op: ``[init] generate``
    confs already materialized the seed at conf load."""
    from hpnn_tpu.parallel import dist

    conf.seed = dist.resolve_time_seed(conf.seed)


def make_eval_fn(*, model: str, out_sharding=None):
    """Jitted vmapped forward over a batch of inputs.

    Matmul precision is pinned to HIGHEST: the vmapped forward lowers
    to MXU matmuls which default to bf16-truncated inputs on TPU,
    while the per-sample M=1 matvec path stays full f32 on the VPU —
    without the pin the two eval streams would disagree on near-tie
    argmaxes and on SNN's printed probabilities.

    ``out_sharding``: pass a replicated NamedSharding when the weights
    are (possibly cross-process) mesh-sharded, so the host count always
    sees every output row."""
    import jax

    from hpnn_tpu.models import ann, snn

    mod = snn if model == "snn" else ann

    def ev(weights, X):
        with jax.default_matmul_precision("float32"):
            return jax.vmap(lambda x: mod.run(weights, x))(X)

    if out_sharding is not None:
        return jax.jit(ev, out_shardings=out_sharding)
    return jax.jit(ev)


def _count_correct(xp, out, T, model: str):
    """Argmax-vs-target quirk rules, shared by the host
    (:func:`accuracy_counts`, xp=numpy) and device
    (:func:`make_device_count_fn`, xp=jax.numpy) counters so the
    quirks can never drift between them."""
    n_out = T.shape[1]
    rev = T[:, ::-1]
    if model == "ann":
        # probe=-1 quirk (driver._first_argmax): if no output exceeds
        # -1.0 the guess stays out of range and can never PASS
        guess = xp.where(
            out.max(axis=1) > -1.0, xp.argmax(out, axis=1), n_out
        )
        above = T > 0.5
        is_ok = xp.where(
            above.any(axis=1),
            n_out - 1 - xp.argmax(rev > 0.5, axis=1),
            1,  # C quirk: is_ok starts at TRUE==1 (ref: src/libhpnn.c:1443)
        )
    else:
        # SNN probe starts at 0 and keeps index 0 unless out > 0
        guess = xp.where((out > 0).any(axis=1), xp.argmax(out, axis=1), 0)
        above = T > 0.1
        is_ok = xp.where(
            above.any(axis=1),
            n_out - 1 - xp.argmax(rev > 0.1, axis=1),
            0,
        )
    return xp.sum(guess == is_ok)


def make_device_count_fn(*, model: str):
    """On-device twin of eval + :func:`accuracy_counts` (same quirks,
    same HIGHEST-precision forward): count_fn(weights, X, T) -> int32
    scalar of correct samples.  Lets whole multi-epoch training runs
    stay on device — only per-epoch (loss, count) scalars come back.

    ``HPNN_FAST_COUNT=1`` drops the HIGHEST pin on THIS in-training
    progress count only (default-precision MXU matmuls run ~6× the
    pinned rate — the per-epoch eval is the largest remaining
    non-step cost in the r05 floor accounting, BASELINE.md): the
    printed per-epoch acc can then differ by a few near-tie counts
    from the pinned eval.  ``run_nn``'s eval (make_eval_fn) keeps the
    pin unconditionally — only the progress metric is relaxed."""
    import jax
    import jax.numpy as jnp

    from hpnn_tpu.models import ann, snn

    mod = snn if model == "snn" else ann
    fast = os.environ.get("HPNN_FAST_COUNT", "") == "1"

    def count(weights, X, T):
        fwd = jax.vmap(lambda x: mod.run(weights, x))
        if fast:
            out = fwd(X)
        else:
            with jax.default_matmul_precision("float32"):
                out = fwd(X)
        return _count_correct(jnp, out, T, model).astype(jnp.int32)

    return count


def make_multi_epoch_fn(step_fn, count_fn):
    """Many whole epochs in ONE dispatch: an outer ``lax.scan`` over
    epochs (each an inner scan over minibatches gathered by index from
    the on-device bank, then a bank-wide accuracy count).

    run(weights, dw, X, T, idx[E, S, B]) ->
        (weights, dw, losses[E, S], counts[E])

    Single-data-shard only (the bank lives replicated on device); the
    sharded-data-axis mode keeps its per-epoch host permute.
    """
    import jax
    from jax import lax

    def run(weights, dw, X, T, idx):
        def epoch(carry, ix_e):
            w, m = carry

            def body(c, ix):
                w2, m2 = c
                w2, m2, l = step_fn(w2, m2, X[ix], T[ix])
                return (w2, m2), l

            (w, m), losses = lax.scan(body, (w, m), ix_e)
            return (w, m), (losses, count_fn(w, X, T))

        (weights, dw), (losses, counts) = lax.scan(epoch, (weights, dw), idx)
        return weights, dw, losses, counts

    return jax.jit(run)


def make_multi_epoch_bank_fn(step_fn, count_fn, n_steps: int, *,
                             banked: bool):
    """Bank-mode twin of :func:`make_multi_epoch_fn` — the r05
    roofline lever.  The per-step ``X[ix]`` gather (6.4 MB/step of
    read+write at the MNIST shape) is replaced by (a) a device-side
    bank permutation once per REFRESH GROUP of epochs and (b) a
    per-epoch random block ORDER, so the steps read whole B-row
    blocks with no per-step gather.  Paired slope measurements
    (BASELINE.md r05): the per-epoch-permute variant costs exactly
    what the per-step gather did (same bytes), while the block-order
    path runs within ~3% of the no-shuffle floor — +24–26% over the
    r04 default at the MNIST shape.

    run(weights, dw, X, T, perms[G, n_rows], orders[G, R, S]) ->
        (weights, dw, losses[G·R, S], counts[G·R])

    Group g trains epochs [g·R, (g+1)·R) on ``X[perms[g]]``; epoch r
    visits blocks in the ``orders[g, r]`` sequence.  With R=1 and
    sequential orders this is EXACTLY the legacy gather trajectory
    (``bank[perm][kB:(k+1)B] == X[idx_k]`` bitwise) — the parity
    configuration; R>1 trades composition-refresh frequency for
    bandwidth (the SGD schedule changes; acceptance bar is final
    accuracy, like everything in batch mode).

    ``banked="grid"``: step_fn(w, m, Xp, Tp, ord_e) runs the WHOLE
    epoch as one Mosaic launch (pallas_train.train_epoch_grid_banked
    — block fetches pipelined behind compute, weights VMEM-resident
    across steps; +28% paired over the per-step-launch variant).
    ``banked="dbuf"``: same call convention, but step_fn is the
    explicit double-buffered DMA epoch
    (pallas_train.train_epoch_dbuf_banked — the kernel owns the
    HBM→VMEM pipeline instead of the implicit grid prefetch;
    HPNN_BANK_DBUF=1, paired delta reported by tools/bench_bank.py).
    ``banked=True``: step_fn(w, m, Xp, Tp, k) is the per-step Pallas
    kernel reading block ``k`` straight from the HBM bank via a
    scalar-prefetched index_map (pallas_train.train_step_fused_banked)
    — zero per-step copy.  ``banked=False``: the XLA step on the
    block-indexed slice of the reshaped ``(S, B, n)`` bank.
    """
    import jax
    from jax import lax

    def run(weights, dw, X, T, perms, orders):
        def group(carry, pe):
            w, m = carry
            perm_g, ord_g = pe
            Xp = X[perm_g]
            Tp = T[perm_g]
            if not banked:
                Xs = Xp.reshape(n_steps, -1, X.shape[1])
                Ts = Tp.reshape(n_steps, -1, T.shape[1])

            def epoch(c, ord_e):
                w2, m2 = c
                if banked in ("grid", "dbuf"):
                    w2, m2, losses = step_fn(w2, m2, Xp, Tp, ord_e)
                    return (w2, m2), (losses, count_fn(w2, X, T))

                def body(cc, k):
                    w3, m3 = cc
                    if banked:
                        w3, m3, l = step_fn(w3, m3, Xp, Tp, k)
                    else:
                        w3, m3, l = step_fn(w3, m3, Xs[k], Ts[k])
                    return (w3, m3), l

                (w2, m2), losses = lax.scan(body, (w2, m2), ord_e)
                return (w2, m2), (losses, count_fn(w2, X, T))

            (w, m), (losses, counts) = lax.scan(epoch, (w, m), ord_g)
            return (w, m), (losses, counts)

        (weights, dw), (losses, counts) = lax.scan(
            group, (weights, dw), (perms, orders))
        n_epochs = losses.shape[0] * losses.shape[1]
        return (weights, dw,
                losses.reshape(n_epochs, -1), counts.reshape(n_epochs))

    return jax.jit(run)


def accuracy_counts(out: np.ndarray, T: np.ndarray, model: str) -> int:
    """Vectorized argmax-vs-target, same rules as the per-sample eval
    (train/driver.py: _first_argmax / _last_above quirks)."""
    return int(_count_correct(np, out, T, model))


def fused_vmem_bytes(weights, B: int, *, momentum: bool,
                     use_bank: bool) -> int:
    """f32 VMEM footprint of one fused Pallas batch step at block size
    ``B`` — the gate that decides whether the kernel may run under the
    12 MiB budget.  Counts the resident block (X + T), the acts+deltas
    scratch (2·B·Σ out_l), the weights (aliased in-place, once; twice
    with momentum), and — on the banked grid-epoch kernel — the
    double-buffered NEXT block of X and T that the grid pipeline keeps
    in flight while the current block computes.  Underestimating that
    last term let near-limit shapes pass the gate and then demote
    silently at Mosaic compile time."""
    n_outs = sum(int(w.shape[0]) for w in weights)
    n_in = int(weights[0].shape[1])
    n_out = int(weights[-1].shape[0])
    n_w = sum(int(np.asarray(w).size) for w in weights)
    vmem = 4 * (
        B * (n_in + n_out)                      # X + T
        + 2 * B * n_outs                        # acts + deltas scratch
        + n_w * (2 if momentum else 1)
    )
    if use_bank:
        vmem += 4 * B * (n_in + n_out)          # next block, in flight
    return vmem


def _batch_state_key(sample_dir, model, momentum, shapes, B, lr, epochs,
                     init_key="", names=None):
    """Round identity for batch-mode crash-resume checkpoints: the
    fused-round scheme (driver._fuse_state_key — census + network +
    starting-weights identity) extended with the batch hyperparameters
    (a checkpoint from a different B/lr/epoch-count protocol is a
    different run).  ``names`` threads the census the run actually
    trained over — without it the key would re-list the dir, and a file
    created/removed between crash and resume would silently restart
    instead of resuming."""
    from hpnn_tpu.train.driver import _fuse_state_key

    return _fuse_state_key(
        sample_dir, model, momentum, shapes,
        f"batch/B{B}/lr{lr}/E{epochs}/{init_key}",
        names=names,
    )


def train_kernel_batched(
    conf: NNConf,
    batch_size: int,
    epochs: int,
    mesh_spec: str | None = None,
    lr: float | None = None,
) -> bool:
    """Minibatch-SGD training round over ``conf.samples``.

    ``lr=None`` keeps the reference's per-sample learning rate for the
    model/mode (ann.BP_LEARN_RATE etc.); ``--lr`` overrides it — batch
    gradients are means over B samples, so tasks with many outputs
    (e.g. the 230-class XRD protocol, where the one-hot signal is
    diluted 1:229 and tanh saturates) need a larger step than the
    per-sample protocol's η to escape the all-negative plateau.
    """
    import jax
    import jax.numpy as jnp

    if conf.kernel is None or conf.samples is None or conf.type == NNType.UKN:
        return False
    if conf.train not in (NNTrain.BP, NNTrain.BPM):
        return True  # CG/SPLX parse but are unimplemented (reference parity)
    # the census collective must run on EVERY rank before any
    # filesystem-dependent early return, or a rank whose dir is
    # missing/empty would exit while its peers block in the gather;
    # a missing dir hashes as a marker so missing-vs-empty ranks
    # disagree here (both erroring) rather than down-stream
    have_dir = os.path.isdir(conf.samples)
    all_files = sample_io.list_sample_files(conf.samples) if have_dir else []
    names, X, T = (
        sample_io.read_dir(conf.samples, files=all_files)
        if have_dir else ([], None, None)
    )
    from hpnn_tpu.parallel import dist

    # the census hashes the raw listing PLUS the readable-sample count:
    # a rank that lists the same files but fails to read some (torn
    # write, permission skew) would otherwise build a differently-sized
    # bank and diverge far downstream in the sharded batch math.  The
    # \x00 marker can't collide with a real filename (readdir never
    # returns NUL) — same trick as the missing-dir marker.
    census = (all_files + ["\x00readable=%d" % len(names)]
              if have_dir else ["\x00missing"])
    if not dist.census_consistent(census):
        log.nn_error(
            sys.stderr,
            "sample dir %s differs across processes "
            "(count, order, or readable set)!\n",
            conf.samples,
        )
        return False
    if not have_dir:
        log.nn_error(sys.stderr, "can't open sample directory: %s\n", conf.samples)
        return False
    n = len(names)
    if n == 0:
        log.nn_error(sys.stderr, "no samples in %s\n", conf.samples)
        return False

    dtype = _compute_dtype()
    model = _model_of(conf)
    momentum = conf.train == NNTrain.BPM
    try:
        mesh = default_mesh(mesh_spec)
    except ValueError as exc:
        log.nn_error(sys.stderr, "bad mesh: %s\n", exc)
        return False
    n_data = mesh.shape[mesh_mod.DATA_AXIS]
    B = max(batch_size, n_data)
    B += (-B) % n_data  # divisible by the data axis

    weights = tuple(
        jnp.asarray(np.asarray(w), dtype=dtype) for w in conf.kernel.weights
    )
    # resolve the learning rate ONCE, before anything keys on it (the
    # crash-resume checkpoint key binds it; the two dispatch paths must
    # agree on the resolved value, not one on None)
    if lr is None:
        lr = dp.default_lr(model, momentum)
    # one dispatch per EPOCH (lax.scan over minibatches): the per-step
    # dispatch floor (~100 ms host round-trip vs ~1 ms device work on
    # the MNIST topology) would otherwise dominate.  Single data shard:
    # samples live on device once, batches gather by index; sharded
    # data axis: host permutes and uploads per epoch.
    gather = n_data == 1
    # Bank data path (single data shard): the bank is permuted
    # device-side once per REFRESH GROUP of HPNN_BANK_REFRESH epochs
    # (default 8) and each epoch visits whole B-row blocks in a fresh
    # random order — no per-step ``X[ix]`` gather.  Paired slope
    # measurements (BASELINE.md r05): per-epoch permutation costs
    # exactly what the per-step gather did (same bytes), while the
    # block-order path runs within ~3% of the no-shuffle floor —
    # +24–26% over the r04 default at the MNIST shape.  The SGD
    # schedule differs from the legacy gather (composition refreshes
    # every R epochs instead of every epoch; order reshuffles every
    # epoch) — validated at 60k protocol scale (BASELINE.md).
    # HPNN_BANK=0 forces the legacy per-step gather;
    # HPNN_BANK_REFRESH=1 refreshes composition every epoch with
    # sequential block order — EXACTLY the legacy trajectories
    # (``bank[perm][kB:(k+1)B] == X[idx_k]`` bitwise, parity-tested).
    use_bank = gather and os.environ.get("HPNN_BANK", "1") != "0"
    bank_refresh = (
        max(1, int(os.environ.get("HPNN_BANK_REFRESH", "8")))
        if use_bank else 0
    )
    # HPNN_BANK_DBUF=1 swaps the grid-epoch kernel for the explicit
    # double-buffered DMA epoch (pallas_train.train_epoch_dbuf_banked)
    # — same math, kernel-owned HBM→VMEM pipeline; opt-in until the
    # paired bench (tools/bench_bank.py dbufR-vs-bankR) crowns it
    use_dbuf = use_bank and os.environ.get("HPNN_BANK_DBUF", "") == "1"
    # Fused Pallas batch step: default for ANN, opt-in for SNN — the
    # r05 paired slope measurements at realistic bank sizes
    # (BASELINE.md): on the bank path the kernel matches XLA at the
    # MNIST shape and wins +15-20% at the XRD shape, so ANN keeps it.
    # SNN defaults to the XLA scan: the kernel's trajectories diverge
    # slowly from the parity-pinned math step on hardware, and the r05
    # root-cause isolation (BASELINE.md "SNN kernel divergence")
    # pinned it to Mosaic-vs-XLA ROW-SUM REDUCTION ORDER in the
    # softmax denominator — exp/log/tanh and the dots are bitwise
    # identical on hardware; ANN has no row reduction in its forward,
    # hence its bitwise-equal trajectories.  Neither order is more
    # correct (each is <=1-ulp-per-sum rounding; measured bound:
    # ~8.5e-5 mean loss gap after 4k steps, identical accuracy), but
    # only one can match the recorded XLA token stream, so the pinned
    # step stays the SNN default.  HPNN_PALLAS=1 forces the kernel
    # on, =0 forces the scan.  Kernel parity itself is proven in
    # tests/test_pallas.py (interpret mode, where reductions agree).
    # VMEM gate (fused_vmem_bytes): batch X/T, acts+deltas scratch,
    # weights, plus the banked kernel's double-buffered next block
    vmem_bytes = fused_vmem_bytes(
        weights, B, momentum=momentum, use_bank=use_bank)
    pallas_env = os.environ.get("HPNN_PALLAS", "")
    use_pallas = (
        gather
        and mesh.devices.size == 1
        and jax.default_backend() == "tpu"
        and dtype == jnp.float32  # fused kernel is f32-only
        and vmem_bytes <= 12 * 2**20
        and (
            pallas_env == "1"
            or (pallas_env != "0" and model == "ann")
        )
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    pad = (-n) % B
    n_steps = (n + pad) // B
    if gather:
        # single data shard: fuse MANY epochs per dispatch — the inner
        # step is the fused Pallas kernel or dp.train_step_math, the
        # per-epoch eval+accuracy runs on device too, and only the
        # per-epoch (losses, count) scalars come home
        def _math_step(w, m, Xb, Tb):
            return dp.train_step_math(
                w, m, Xb, Tb, model=model, momentum=momentum,
                lr=lr, alpha=0.2,
            )

        count_fn = make_device_count_fn(model=model)

        def _build_multi_fn(with_pallas):
            if with_pallas:
                from hpnn_tpu.ops import pallas_train

                if use_bank:
                    # the grid-epoch kernel: one Mosaic launch per
                    # epoch (+28% paired over per-step launches, r05);
                    # HPNN_BANK_DBUF=1 selects the explicit
                    # double-buffered DMA twin instead
                    epoch_kernel = (
                        pallas_train.train_epoch_dbuf_banked if use_dbuf
                        else pallas_train.train_epoch_grid_banked)

                    def step_fn(w, m, Xp, Tp, ord_e):
                        return epoch_kernel(
                            w, m, Xp, Tp, ord_e, batch=B, model=model,
                            momentum=momentum, lr=lr, alpha=0.2,
                        )
                else:
                    def step_fn(w, m, Xb, Tb):
                        return pallas_train.train_step_fused_batch(
                            w, m, Xb, Tb, model=model, momentum=momentum,
                            lr=lr, alpha=0.2,
                        )
            else:
                step_fn = _math_step
            if use_bank:
                return make_multi_epoch_bank_fn(
                    step_fn, count_fn, n_steps,
                    banked=(("dbuf" if use_dbuf else "grid")
                            if with_pallas else False),
                )
            return make_multi_epoch_fn(step_fn, count_fn)

        multi_fn = _build_multi_fn(use_pallas)
    else:
        epoch_fn = dp.make_gspmd_epoch_fn(
            mesh, weights, model=model, momentum=momentum, lr=lr, alpha=0.2,
            gather=gather,
        )
        eval_fn = make_eval_fn(model=model, out_sharding=rep)

    w_sh = dp.place_kernel(weights, mesh)
    dw_sh = dp.place_kernel(
        tuple(np.zeros_like(np.asarray(w)) for w in weights), mesh
    ) if momentum else ()

    from hpnn_tpu.utils import debug

    debug.device_alloc_report(tuple(w_sh) + tuple(dw_sh))

    Xd = X.astype(dtype)
    Td = T.astype(dtype)
    if gather:
        X_dev = dp.global_put(Xd, rep)
        T_dev = dp.global_put(Td, rep)
    else:
        # eval bank, placed once (replicated) instead of re-uploaded
        # per epoch
        X_eval = dp.global_put(Xd, rep)
    # crash-resume checkpoints (HPNN_FUSE_STATE, the fused-round
    # pattern, driver.py): persist (completed epochs, weights[, dw])
    # after every dispatch so a worker crash mid-protocol loses at most
    # one dispatch block, not the whole run.  The RNG fast-forwards by
    # replaying `done` epoch permutations from the stored seed.
    # Single-process only: under multi-process the ranks would need a
    # shared filesystem AND a resume barrier — out of scope, so the
    # checkpoint quietly stays off there.
    from hpnn_tpu.train.driver import (
        _init_identity, _load_fuse_state, _save_fuse_state,
    )

    state_path = os.environ.get("HPNN_FUSE_STATE")
    if state_path and jax.process_count() > 1:
        state_path = None
    state_key = None
    state = None

    def _make_state_key(with_pallas):
        # the key binds the dispatch path too: ANN Pallas/XLA
        # trajectories are token-identical in practice (measured at
        # 60k scale) but not guaranteed bit-identical, so a resumed
        # run must continue on the dispatch that wrote the checkpoint
        # — by refusing the other dispatch's checkpoint outright.
        # The bank/gather data path is tagged too (same batches
        # bitwise, but the XLA fusion of slice-vs-gather is not
        # guaranteed identical), and the census names are threaded so
        # the key never re-lists the dir (advisor r4).
        return _batch_state_key(
            conf.samples, model, momentum,
            tuple(tuple(int(d) for d in w.shape) for w in weights),
            B, lr, epochs,
            ("pallas" if with_pallas else "xla")
            + ("-dbuf" if (with_pallas and use_dbuf) else "")
            + (f"-bank{bank_refresh}/" if use_bank else "/")
            + _init_identity(conf, [np.asarray(w) for w in weights]),
            names=names,
        )

    if state_path:
        state_key = _make_state_key(use_pallas)
        state = _load_fuse_state(state_path, state_key)
        if gather and state is None and use_pallas:
            # a crashed predecessor may have hit the Mosaic-failure
            # fallback mid-run and re-keyed its checkpoint to the XLA
            # dispatch: adopt it AND stay on that dispatch, so the
            # resumed trajectory provably continues on the dispatch
            # that wrote it (advisor r4).  Seed-checked BEFORE the
            # dispatch flip: a fresh explicitly-seeded run must not be
            # silently demoted to XLA by a stale checkpoint it is
            # about to discard anyway.
            alt_key = _make_state_key(False)
            alt = _load_fuse_state(state_path, alt_key)
            if alt is not None and conf.seed in (0, int(alt["seed"])):
                state_key, state, use_pallas = alt_key, alt, False
                multi_fn = _build_multi_fn(False)
        if state is not None and conf.seed not in (0, int(state["seed"])):
            state = None  # different seeded run requested: start over
    done_epochs = 0
    cap_hint = 0  # gather-path epochs-per-dispatch cap carried in the
    # checkpoint's chunk field; halved when a resume finds zero
    # progress since the last resume (SIGKILLed over-budget dispatch —
    # the batch twin of the fused-round stall halving)
    if state is not None:
        conf.seed = int(state["seed"])
        done_epochs = int(state["done"])
        cap_hint = int(state["chunk"])
        obs.count("resume.restore", done=done_epochs, chunk=cap_hint,
                  path="batch", body="pallas" if use_pallas else "xla")
        if int(state["resume_done"]) == done_epochs and cap_hint:
            halved = max(1, cap_hint // 2)
            obs.count("batch.cap_halved", reason="resume_stall",
                      done=done_epochs, old=cap_hint, new=halved)
            cap_hint = halved
        saved = tuple(
            np.asarray(w, dtype=dtype) for w in state["weights"]
        )
        n_l = len(weights)
        w_sh = dp.place_kernel(saved[:n_l], mesh)
        if momentum:
            dw_sh = dp.place_kernel(saved[n_l:], mesh)
    _resolve_seed(conf)
    rng = np.random.RandomState(conf.seed & 0x7FFFFFFF)

    def _save_state(epoch_now, cap=0, resume_done=-1):
        if not state_path:
            return
        host = [dp.host_fetch(w, mesh) for w in w_sh]
        host += [dp.host_fetch(m, mesh) for m in dw_sh] if momentum else []
        _save_fuse_state(
            state_path, state_key, conf.seed, epoch_now, cap, host,
            resume_done=resume_done)

    loss = float("nan")
    if pad:
        # no silent caps: the tail wrap re-trains `pad` sample slots
        # per epoch so every jitted batch keeps its static shape.
        # stderr, like every other warning — stdout is the grep-able
        # metrics token stream (SURVEY.md §5)
        log.nn_warn(
            sys.stderr,
            "batch wrap: %i duplicate sample slots per epoch "
            "(n=%i, batch=%i)\n",
            pad, n, B,
        )
    def print_epoch(epoch, loss, okc):
        log.nn_out(
            sys.stdout,
            "BATCH EPOCH %4i loss= %.10f acc= %7.3f%% (%i/%i)\n",
            epoch,
            loss,
            100.0 * okc / n,
            okc,
            n,
        )
        log.flush()
        if obs.enabled():
            obs.gauge("batch.loss", loss, epoch=epoch)
            obs.gauge("batch.acc", okc / n, epoch=epoch, ok=okc, n=n)

    obs.event("round.start", mode="batch", samples=n, batch=B,
              epochs=epochs, body="pallas" if use_pallas else "xla",
              bank=bank_refresh, data_shards=n_data,
              resumed=state is not None)
    round_span = obs.spans.start("train.round", mode="batch")

    # most recent bank permutation: a sub-R dispatch block (shrunken
    # survival cap) can start mid-refresh-group and must reuse the
    # group's permutation instead of drawing a new one
    cur_perm = [None]

    def draw_perm():
        order = rng.permutation(n)
        # wrap the tail so every batch is full (static shapes for jit);
        # np.resize repeats the permutation as needed even when B > 2n
        p = np.resize(order, n + pad) if pad else order
        cur_perm[0] = p
        return p

    def draw_order():
        # bank mode's per-epoch block order; at refresh=1 the freshly
        # permuted bank makes any fixed order a random batching, so
        # sequential blocks keep the legacy-gather trajectory exactly
        if bank_refresh == 1:
            return np.arange(n_steps)
        return rng.permutation(n_steps)

    def replay_epoch(e):
        # consume exactly the RNG draws epoch ``e`` consumed, so a
        # resume (or the Mosaic-fallback rewind) shuffles the
        # remaining epochs exactly as the original run would have
        if use_bank:
            if e % bank_refresh == 0:
                draw_perm()
            if bank_refresh > 1:
                draw_order()
        else:
            draw_perm()

    for e in range(done_epochs):
        # resume: tokens for these were already printed by the
        # crashed run
        replay_epoch(e)
    if gather:
        # cap the epochs per dispatch (the tunneled worker kills very
        # long dispatches, ~100 s observed).  The first blocks use a
        # step-count heuristic; once a clean (compile-free) block has
        # been timed, the cap re-derives from the measured rate so
        # slower topologies stay under the budget too.  The cap is
        # then FROZEN — every distinct block shape is a recompile.
        import time as _time

        e_cap = max(1, 65536 // max(1, n_steps))
        if cap_hint:
            e_cap = min(e_cap, cap_hint)
        if use_bank and e_cap >= bank_refresh:
            # whole refresh groups per dispatch block while the cap
            # allows; a cap shrunk below R (stall halving) stays AS IS
            # — clamping it back up would retry the same over-budget
            # block forever, defeating the halving escape.  Sub-R
            # blocks never straddle a group boundary (see the block
            # builder), so the replay's e % R rule still holds.
            e_cap = (e_cap // bank_refresh) * bank_refresh
        # mark this position as resumed (and cover a SIGKILL during
        # the very first dispatch): a next resume that finds `done`
        # unchanged halves the cap instead of retrying the same
        # over-budget block forever
        _save_state(done_epochs, cap=e_cap, resume_done=done_epochs)
        budget_s = float(os.environ.get("HPNN_DISPATCH_BUDGET_S", "60"))
        epoch = done_epochs
        block_i = 0
        timed_cap = None
        while epoch < epochs:
            e_block = min(e_cap, epochs - epoch)
            if use_bank:
                start_off = epoch % bank_refresh
                if start_off:
                    # sub-R survival cap left us mid-group: finish the
                    # CURRENT group (bank permutation = cur_perm, no
                    # fresh draw — the replay rule draws only at group
                    # boundaries) without straddling the boundary
                    r_eff = min(e_block, bank_refresh - start_off)
                    n_groups, e_block = 1, r_eff
                    perms_l = [cur_perm[0]]
                    orders_l = [[draw_order() for _ in range(r_eff)]]
                elif e_block >= bank_refresh:
                    # aligned: whole groups; a sub-R tail runs as its
                    # own dispatch on the next loop pass
                    n_groups = e_block // bank_refresh
                    r_eff = bank_refresh
                    e_block = n_groups * r_eff
                    perms_l, orders_l = [], []
                    for _g in range(n_groups):
                        perms_l.append(draw_perm())
                        orders_l.append(
                            [draw_order() for _ in range(r_eff)])
                else:
                    # aligned sub-R block (shrunken cap or short tail)
                    n_groups, r_eff = 1, e_block
                    perms_l = [draw_perm()]
                    orders_l = [[draw_order() for _ in range(r_eff)]]
                data_args = (
                    dp.global_put(np.asarray(perms_l, dtype=np.int32), rep),
                    dp.global_put(np.asarray(orders_l, dtype=np.int32), rep),
                )
            else:
                data_args = (dp.global_put(
                    np.stack([draw_perm() for _ in range(e_block)]
                             ).astype(np.int32).reshape(e_block, n_steps, B),
                    rep,
                ),)
            if obs.cost.enabled() and block_i == 0:
                # catalog the multi-epoch executable once (a separate
                # introspection compile — the dispatch path and its
                # donation discipline are untouched); per-block perf
                # gauges scale the cost by each block's epoch count
                obs.cost.analyze_fn(
                    "batch.multi_epoch", multi_fn, w_sh, dw_sh,
                    X_dev, T_dev, *data_args, units=e_block,
                    body="pallas" if use_pallas else "xla")
            bspan = obs.spans.start("batch.block", parent=round_span,
                                    i=block_i, epoch=epoch,
                                    epochs=e_block)
            t0 = _time.monotonic()
            try:
                with obs.step_annotation("hpnn.batch_block", block_i), \
                        obs.timer("batch.block_dispatch", epoch=epoch,
                                  epochs=e_block,
                                  body="pallas" if use_pallas else "xla"):
                    w_sh, dw_sh, losses, counts = multi_fn(
                        w_sh, dw_sh, X_dev, T_dev, *data_args)
                    losses = dp.host_fetch(losses, mesh)
                    counts = dp.host_fetch(counts, mesh)
            except Exception as exc:
                obs.spans.finish(bspan, failed=type(exc).__name__)
                if (
                    block_i == 0
                    and use_pallas
                    and "UNAVAILABLE" not in str(exc)
                ):
                    # Mosaic failed to compile the fused kernel for
                    # this shape/topology (the VMEM heuristic is not a
                    # compiler): rebuild on the XLA step and retry the
                    # same block.  UNAVAILABLE = worker crash, not a
                    # compile problem — let it propagate.
                    log.nn_warn(
                        sys.stderr,
                        "fused batch kernel failed (%s); "
                        "falling back to the XLA step\n",
                        type(exc).__name__,
                    )
                    obs.count("fallback.mosaic_refusal", path="batch",
                              epoch=epoch, exc=type(exc).__name__)
                    multi_fn = _build_multi_fn(False)
                    use_pallas = False
                    # re-key the checkpoint to the dispatch actually
                    # running from here on and persist immediately:
                    # a resume must NOT recompute use_pallas=True,
                    # adopt the old key, and continue an XLA-trained
                    # trajectory on the Pallas dispatch (advisor r4)
                    if state_path:
                        state_key = _make_state_key(False)
                        _save_state(epoch, cap=e_cap)
                    # rewind the RNG so the retried block reuses the
                    # SAME permutations the failed dispatch consumed
                    rng = np.random.RandomState(conf.seed & 0x7FFFFFFF)
                    for e in range(epoch):
                        replay_epoch(e)
                    continue
                raise
            dt = _time.monotonic() - t0
            obs.spans.finish(bspan)
            if obs.cost.enabled():
                # dt was already measured for the dispatch-budget cap
                obs.cost.record_dispatch("batch.multi_epoch", dt,
                                         units=e_block)
            if block_i == 1 and timed_cap is None:
                # first compile-free block: freeze the time-based cap
                timed_cap = max(1, int(budget_s * e_block / max(dt, 1e-3)))
                e_cap = min(e_cap, timed_cap)
                if use_bank and e_cap >= bank_refresh:
                    e_cap = (e_cap // bank_refresh) * bank_refresh
            block_i += 1
            for e in range(e_block):
                epoch += 1
                loss = float(losses[e].mean())
                print_epoch(epoch, loss, int(counts[e]))
            if obs.probes.enabled():
                # per-BLOCK numerics check (the scan returns only the
                # final weights); placed OUTSIDE the dispatch try so a
                # sentinel abort propagates honestly
                obs.probes.check_weights(w_sh, step=epoch,
                                         where="batch_block")
            # per-BLOCK weight trace (the multi-epoch scan returns only
            # the final weights; per-epoch snapshots would defeat the
            # fused dispatch).  enabled() gate BEFORE the host_fetch —
            # the fetch is the cost the knob controls
            if trace_mod.enabled():
                trace_mod.trace(f"w@{epoch}", [dp.host_fetch(w, mesh)
                                               for w in w_sh])
            _save_state(epoch, cap=e_cap)
    else:
        import time as _time

        for epoch in range(done_epochs + 1, epochs + 1):
            order = draw_perm()
            Xe = Xd[order].reshape(n_steps, B, -1)
            Te = Td[order].reshape(n_steps, B, -1)
            Xs, Ts = dp.shard_batch_steps(Xe, Te, mesh)
            if obs.cost.enabled():
                # memo hit after the first epoch (catalog keyed by name)
                obs.cost.analyze_fn("batch.epoch_fn", epoch_fn,
                                    w_sh, dw_sh, Xs, Ts, units=1,
                                    body="xla")
            bspan = obs.spans.start("batch.block", parent=round_span,
                                    epoch=epoch, epochs=1)
            t0 = _time.monotonic()
            with obs.timer("batch.block_dispatch", epoch=epoch,
                           epochs=1, body="xla"):
                w_sh, dw_sh, losses = epoch_fn(w_sh, dw_sh, Xs, Ts)
                losses = dp.host_fetch(losses, mesh)
            obs.spans.finish(bspan)
            if obs.cost.enabled():
                obs.cost.record_dispatch("batch.epoch_fn",
                                         _time.monotonic() - t0)
            loss = float(jnp.mean(losses))
            out = np.asarray(eval_fn(w_sh, X_eval))
            okc = accuracy_counts(out, T, model)
            print_epoch(epoch, loss, okc)
            if obs.probes.enabled():
                obs.probes.check_weights(w_sh, step=epoch,
                                         where="batch_epoch")
            if trace_mod.enabled():
                trace_mod.trace(f"w@{epoch}", [dp.host_fetch(w, mesh)
                                               for w in w_sh])
            _save_state(epoch)
    jax.block_until_ready(w_sh)
    conf.kernel = kernel_mod.Kernel(
        tuple(dp.host_fetch(w, mesh).astype(np.float64) for w in w_sh)
    )
    # run completed: drop this run's checkpoint (unrelated keys are
    # left alone, same discipline as the fused-round driver)
    if state_path and _load_fuse_state(state_path, state_key) is not None:
        os.remove(state_path)
    obs.event("round.end", mode="batch", epochs=epochs, loss=loss,
              body="pallas" if use_pallas else "xla")
    obs.spans.finish(round_span, epochs=epochs)
    obs.summary()
    return True


def run_kernel_batched(conf: NNConf) -> None:
    """Vectorized eval over ``conf.tests``: one vmapped forward pass,
    then the per-sample token protocol printed in the SAME seeded
    shuffle order as the per-sample driver (ref: src/libhpnn.c:
    1218-1229) — the stream is drop-in comparable for the same seed.
    Unreadable/malformed files print their TESTING FILE header with no
    verdict, exactly like the per-sample path."""
    import jax.numpy as jnp

    if conf.kernel is None or conf.tests is None or conf.type == NNType.UKN:
        return
    # census collective before any filesystem-dependent early return
    # (see train_kernel_batched).  The census covers the FULL listing
    # (readable or not) and that same listing later drives the shuffle
    # — one readdir for all three uses, mirroring driver.run_kernel
    # (advisor r4: a re-list for the shuffle could race file creation
    # and diverge across ranks).
    have_dir = os.path.isdir(conf.tests)
    all_files = sample_io.list_sample_files(conf.tests) if have_dir else []
    names, X, T = (
        sample_io.read_dir(conf.tests, files=all_files)
        if have_dir else ([], None, None)
    )
    from hpnn_tpu.parallel import dist

    # raw listing + readable count, as in train_kernel_batched: ranks
    # agreeing on the listing but not on what they could READ must
    # fail here, not in the sharded eval math
    census = (all_files + ["\x00readable=%d" % len(names)]
              if have_dir else ["\x00missing"])
    if not dist.census_consistent(census):
        log.nn_error(
            sys.stderr,
            "test dir %s differs across processes "
            "(count, order, or readable set)!\n",
            conf.tests,
        )
        return
    if not have_dir:
        log.nn_error(sys.stderr, "can't open test directory: %s\n", conf.tests)
        return
    if not names:
        return
    dtype = _compute_dtype()
    model = _model_of(conf)
    weights = tuple(
        jnp.asarray(np.asarray(w), dtype=dtype) for w in conf.kernel.weights
    )
    eval_fn = make_eval_fn(model=model)

    from hpnn_tpu.utils import debug

    debug.device_alloc_report(weights)
    if obs.cost.enabled():
        obs.cost.analyze_fn("batch.eval_forward", eval_fn, weights,
                            jnp.asarray(X.astype(dtype)),
                            units=len(names))
    with obs.spans.span("eval.batch_forward", files=len(names)), \
            obs.annotate("hpnn.eval_forward"), \
            obs.timer("eval.batch_forward", size=len(names)):
        out = np.asarray(eval_fn(weights, jnp.asarray(X.astype(dtype))))
    obs.event("eval.round", files=len(all_files), batched=len(names),
              odd=0, unreadable=len(all_files) - len(names), tp=False)

    from hpnn_tpu.train.driver import print_verdict
    from hpnn_tpu.utils.glibc_random import shuffled_order

    _resolve_seed(conf)
    row_of = {name: i for i, name in enumerate(names)}
    for idx in shuffled_order(conf.seed, len(all_files)):
        name = all_files[idx]
        log.nn_out(sys.stdout, "TESTING FILE: %16.16s\t", name)
        i = row_of.get(name)
        if i is None:  # unreadable/malformed: header only, no verdict
            continue
        print_verdict(out[i], T[i], model)
        trace_mod.trace(f"out@{name}", [out[i]])
    log.flush()
    obs.summary()
