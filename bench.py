#!/usr/bin/env python
"""Headline benchmark: per-sample BP training throughput, MNIST-shaped.

Protocol (mirrors the reference MNIST tutorial shape and training mode,
ref: /root/reference/tutorials/mnist/tutorial.bash:125-137): a
784-300-10 ANN, `[train] BP`, seed 10958, and 64 synthetic MNIST-like
samples (sparse 0..255 pixels, one-hot ±1 targets, fixed RNG) each
trained to the reference's convergence criterion (δ=1e-6, 31..102399
iterations, ref: include/libhpnn.h:67-74).

Baseline: the SAME workload run by a locally-built reference
(gcc -O2 -fopenmp -D_OMP, the best build this toolchain allows — no
cblas headers, no MPI) with the tutorial's `-O4 -B4` flags.  Measured
2026-07-29: 64 samples / 70.3 s = 0.910 samples/s, 137,926 total inner
iterations (ours: 139,066 — within 1%, so wall-clock per sample is an
apples-to-apples work comparison).  See BASELINE.md.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_SAMPLES_PER_SEC = 0.910  # measured reference, see module docstring
N_SAMPLES = 64


def make_workload():
    rng = np.random.RandomState(12345)
    samples = []
    for i in range(N_SAMPLES):
        x = np.zeros(784)
        nz = rng.choice(784, size=150, replace=False)
        x[nz] = rng.uniform(0, 255, size=150)
        t = np.full(10, -1.0)
        t[i % 10] = 1.0
        samples.append((x, t))
    return samples


def main() -> None:
    import jax
    import jax.numpy as jnp

    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.train import loop

    dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    samples = make_workload()
    k, _ = kernel_mod.generate(10958, 784, [300], 10)
    weights0 = tuple(jnp.asarray(np.asarray(w), dtype=dtype) for w in k.weights)

    def one(weights, x, t):
        return loop.train_sample(
            weights,
            (),
            jnp.asarray(x, dtype=dtype),
            jnp.asarray(t, dtype=dtype),
            0.2,
            loop.DELTA_BP,
            model="ann",
            momentum=False,
            min_iter=loop.MIN_BP_ITER,
            max_iter=loop.MAX_BP_ITER,
        )

    # warmup: compile the while_loop trainer for this topology
    r = one(weights0, *samples[0])
    jax.block_until_ready(r.weights)

    weights = weights0
    total_iters = 0
    t0 = time.perf_counter()
    for x, t in samples:
        r = one(weights, x, t)
        weights = r.weights
        total_iters += int(r.n_iter)  # host sync, like the token prints
    jax.block_until_ready(weights)
    dt = time.perf_counter() - t0

    sps = N_SAMPLES / dt
    print(
        json.dumps(
            {
                "metric": "mnist_synth_bp_train_throughput",
                "value": round(sps, 3),
                "unit": "samples/s",
                "vs_baseline": round(sps / BASELINE_SAMPLES_PER_SEC, 3),
                "total_inner_iters": total_iters,
                "wall_s": round(dt, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
