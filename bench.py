#!/usr/bin/env python
"""Headline benchmarks: per-sample BP throughput + batched MXU mode.

Two measurements, both MNIST-shaped (784-300-10 ANN, the reference
tutorial topology, ref: /root/reference/tutorials/mnist/tutorial.bash:
125-137):

* **per-sample** — 64 synthetic samples each trained to the reference's
  convergence criterion (δ=1e-6, 31..102399 iters,
  ref: include/libhpnn.h:67-74).  Faithful-protocol number, directly
  comparable with the locally-built reference binary on the same
  workload.
* **batch** — the TPU-idiomatic minibatch DP/GSPMD mode
  (train/batch.py): one steepest-descent step per minibatch.  Reports
  samples/s, steps/s, achieved FLOP/s and %-of-peak.  This is the mode
  that feeds the MXU; the reference has no equivalent (its per-sample
  protocol caps it at matvec scale).

Methodology (regression-sensitive): every timed section runs REPEATS
times; the JSON carries min/median/spread.  The headline `value` is
the per-sample FUSED-EPOCH median samples/s — what the train_nn driver
executes since round 3.  BENCH_r01/r02's headline was the per-sample-
dispatch number; that series continues unchanged under
`per_sample.per_sample_dispatch` (r02: 7.756), so cross-round
comparisons should use it, not `value`, across the r02→r03 boundary.

Since r04, A/B comparisons are PAIRED: fused-vs-streaming repeats are
interleaved (per-round ratio), and the batch section adds slope
timing — us/step from (t(big) − t(small))/Δsteps, which cancels the
~65–110 ms per-dispatch tunnel round-trip.  Slope variants run
interleaved round-robin: MNIST-shape Pallas/XLA at B=1024 (13120 vs
320 steps) and XLA at B=2048 everywhere, plus — on TPU only, where
the paired counterpart exists — the XRD shape (851-230-230 BPM,
B=256, 51520 vs 320 steps: its ~3x-faster step needs longer
dispatches to resolve).  Each pair reports a per-round paired delta
(`paired_pallas_vs_xla_pct`, `paired_xrd_pallas_vs_xla_pct`).  The
absolute 8000-step scan numbers continue the r01–r03 series (they
include ~8 us/step of amortized tunnel cost).

Baseline: a locally-built reference (gcc -O2 -fopenmp -D_OMP, best
this toolchain allows — no cblas, no MPI) with the tutorial's -O4 -B4
flags on the same 64-sample workload.  When gcc + /root/reference are
available the baseline is RE-MEASURED in-run (--no-ref skips it);
otherwise the frozen 2026-07-29 measurement (0.910 samples/s, 137,926
inner iters) is used.  See BASELINE.md.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/s", "vs_baseline": N, ...}
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import time

import numpy as np

FROZEN_BASELINE_SPS = 0.910  # measured 2026-07-29, see module docstring
N_SAMPLES = 64
REPEATS = 3
BATCH_B = 1024
BATCH_STEPS = 200       # per-step-dispatch mode (each step a host dispatch)
SCAN_STEPS = 8000       # absolute scan mode (one dispatch for the chain;
                        # kept for r01-r03 series continuity — includes
                        # ~8 us/step of amortized tunnel round-trip)
SCAN_REPEATS = 5
# slope mode: us/step = (t(E_BIG) - t(E_SMALL)) / d_steps cancels the
# constant per-dispatch tunnel round-trip (~65-110 ms on this link)
SLOPE_S = 64            # steps per scanned epoch
SLOPE_E_SMALL = 5
SLOPE_E_BIG = 205
SLOPE_REPEATS = 5
# v5e single-chip peak: 394 TFLOP/s bf16 (default matmul precision
# feeds the MXU bf16 inputs with f32 accumulation)
V5E_PEAK_FLOPS = 394e12


def make_workload():
    rng = np.random.RandomState(12345)
    samples = []
    for i in range(N_SAMPLES):
        x = np.zeros(784)
        nz = rng.choice(784, size=150, replace=False)
        x[nz] = rng.uniform(0, 255, size=150)
        t = np.full(10, -1.0)
        t[i % 10] = 1.0
        samples.append((x, t))
    return samples


def _stats(vals):
    return {
        "min": round(min(vals), 3),
        "median": round(statistics.median(vals), 3),
        "max": round(max(vals), 3),
        "spread_pct": round(
            100.0 * (max(vals) - min(vals)) / statistics.median(vals), 1
        ),
        "n": len(vals),
    }


def bench_per_sample():
    """Per-sample convergence-loop training over the 64-sample
    workload, two dispatch modes:

    * **fused epoch** (headline) — the whole round as one
      ``loop.train_epoch_lax`` scan, what the train_nn driver executes;
    * **per-sample dispatch** — one jit call + n_iter readback per
      sample, the streaming fallback path (and the r01/r02 headline,
      kept for continuity)."""
    import jax
    import jax.numpy as jnp

    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.train import loop

    dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    samples = make_workload()
    k, _ = kernel_mod.generate(10958, 784, [300], 10)
    weights0 = tuple(jnp.asarray(np.asarray(w), dtype=dtype) for w in k.weights)

    X = jnp.asarray(np.stack([s[0] for s in samples]), dtype=dtype)
    T = jnp.asarray(np.stack([s[1] for s in samples]), dtype=dtype)
    kw = dict(model="ann", momentum=False,
              min_iter=loop.MIN_BP_ITER, max_iter=loop.MAX_BP_ITER)

    def one(weights, x, t):
        return loop.train_sample(
            weights, (),
            jnp.asarray(x, dtype=dtype), jnp.asarray(t, dtype=dtype),
            0.2, loop.DELTA_BP, **kw,
        )

    # the headline measures the driver's ACTUAL round dispatch
    # (loop.train_epoch): the Mosaic-kernel scan body on TPU/f32 since
    # r05, the lax body elsewhere; when the kernel body is active the
    # lax body is timed too, interleaved, for a paired body comparison
    epoch_body = "pallas" if loop._pallas_epoch_default(weights0) else "lax"

    # warm all paths
    w, stats = loop.train_epoch(
        weights0, (), X, T, 0.2, loop.DELTA_BP, **kw)
    np.asarray(stats[1][-1:])
    if epoch_body == "pallas":
        w, stats = loop.train_epoch_lax(
            weights0, (), X, T, 0.2, loop.DELTA_BP, **kw)
        np.asarray(stats[1][-1:])
    r = one(weights0, *samples[0])
    int(r.n_iter)

    # INTERLEAVED repeats: each round measures fused then streaming
    # under the same link conditions, so the fused-vs-streaming ratio
    # is a paired statistic (VERDICT r3 item 4).  Iteration counts are
    # recorded PER repeat (advisor r4: a single overwritten count could
    # silently disagree with the median throughput if repeats varied —
    # determinism across repeats is itself worth recording).
    fused_sps, sps_runs, fused_iters, disp_iters = [], [], [], []
    lax_sps, lax_iters = [], []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        w, stats = loop.train_epoch(
            weights0, (), X, T, 0.2, loop.DELTA_BP, **kw)
        fused_iters.append(int(np.asarray(stats[1]).sum()))  # fence
        fused_sps.append(N_SAMPLES / (time.perf_counter() - t0))

        if epoch_body == "pallas":
            t0 = time.perf_counter()
            w, stats = loop.train_epoch_lax(
                weights0, (), X, T, 0.2, loop.DELTA_BP, **kw)
            lax_iters.append(int(np.asarray(stats[1]).sum()))
            lax_sps.append(N_SAMPLES / (time.perf_counter() - t0))

        weights = weights0
        total_iters = 0
        t0 = time.perf_counter()
        for x, t in samples:
            r = one(weights, x, t)
            weights = r.weights
            total_iters += int(r.n_iter)  # host sync, like the token prints
        sps_runs.append(N_SAMPLES / (time.perf_counter() - t0))
        disp_iters.append(total_iters)
    paired_ratio = [round(f / s, 2) for f, s in zip(fused_sps, sps_runs)]
    out = {
        "epoch_body": epoch_body,
        "samples_per_s": _stats(fused_sps),
        "total_inner_iters": fused_iters[-1],
        "total_inner_iters_per_repeat": fused_iters,
        "per_sample_dispatch": {
            "samples_per_s": _stats(sps_runs),
            "total_inner_iters": disp_iters[-1],
            "total_inner_iters_per_repeat": disp_iters,
        },
        "paired_fused_vs_streaming_ratio": {
            "per_round": paired_ratio,
            "median": round(statistics.median(paired_ratio), 2),
        },
    }
    if lax_sps:
        deltas = [round(100.0 * (p - x) / x, 1)
                  for p, x in zip(fused_sps, lax_sps)]
        out["epoch_lax"] = {
            "samples_per_s": _stats(lax_sps),
            "total_inner_iters": lax_iters[-1],
        }
        out["paired_pallas_epoch_vs_lax_pct"] = {
            "per_round": deltas,
            "median": round(statistics.median(deltas), 1),
        }
    return out


def bench_batch():
    """Batched GSPMD DP mode, measured two ways:

    * **scan** (headline) — BATCH_STEPS steps fused into ONE dispatch
      via the scan-per-epoch trainer (`dp.make_gspmd_epoch_fn`,
      gather mode), exactly what `train_nn --batch` executes.  This is
      device-bound.
    * **per-step dispatch** — the same step jitted and dispatched from
      the host each time; kept as a secondary stat so the JSON records
      the dispatch floor the scan removes.
    """
    import jax
    import jax.numpy as jnp

    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.parallel import dp, mesh as mesh_mod

    k, _ = kernel_mod.generate(10958, 784, [300], 10)
    dtype = jnp.float32
    weights = tuple(jnp.asarray(np.asarray(w), dtype=dtype) for w in k.weights)
    n_params = sum(int(np.asarray(w).size) for w in weights)

    rng = np.random.RandomState(7)
    X = rng.uniform(0, 255, size=(BATCH_B, 784)).astype(np.float32)
    T = np.full((BATCH_B, 10), -1.0, dtype=np.float32)
    T[np.arange(BATCH_B), rng.randint(0, 10, BATCH_B)] = 1.0

    mesh = mesh_mod.make_mesh(n_data=1, n_model=1)
    w_sh = dp.place_kernel(weights, mesh)

    # -- scan mode: the bank lives on device, each scan step gathers
    # its (shuffled) batch by index — one dispatch per BATCH_STEPS
    epoch_fn = dp.make_gspmd_epoch_fn(
        mesh, weights, model="ann", momentum=False, gather=True,
        donate=False,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    X_dev = jax.device_put(jnp.asarray(X), rep)
    T_dev = jax.device_put(jnp.asarray(T), rep)
    idx = jnp.asarray(
        np.stack([np.random.RandomState(s).permutation(BATCH_B)
                  for s in range(SCAN_STEPS)]),
        dtype=jnp.int32,
    )
    # NOTE sync discipline: on the tunneled TPU platform
    # block_until_ready can return before execution completes; a host
    # transfer of one loss element is the reliable fence, so every
    # timed run below ends with np.asarray(...) of a scalar.
    def _timed_runs(run, steps, repeats):
        """run() -> loss scalar array (the transfer fence); returns
        (samples/s list, steps/s list, last loss)."""
        loss = run()  # warmup/compile
        np.asarray(loss)
        sps, stps = [], []
        for _ in range(repeats):
            t0 = time.perf_counter()
            loss = run()
            np.asarray(loss)
            dt = time.perf_counter() - t0
            stps.append(steps / dt)
            sps.append(BATCH_B * steps / dt)
        return sps, stps, float(np.asarray(loss).ravel()[-1])

    scan_sps, scan_stps, final_loss = _timed_runs(
        lambda: epoch_fn(w_sh, (), X_dev, T_dev, idx)[2][-1:],
        SCAN_STEPS, SCAN_REPEATS,
    )

    # -- per-step dispatch mode (the old measurement)
    step = dp.make_gspmd_train_step(mesh, weights, model="ann", momentum=False)
    Xs, Ts = dp.shard_batch(X, T, mesh)

    def _dispatch_chain():
        nonlocal w_sh
        dw = ()
        for _ in range(BATCH_STEPS):
            w_sh, dw, l = step(w_sh, dw, Xs, Ts)
        return l
    disp_sps, disp_stps, _ = _timed_runs(
        _dispatch_chain, BATCH_STEPS, REPEATS,
    )

    # -- slope-timed paired section: Pallas vs XLA, plus B=2048 -------
    # Each sample times one small and one big multi-epoch dispatch and
    # takes dt/d_steps; variants are interleaved round-robin so every
    # repeat is a PAIRED comparison under the same link conditions
    # (the r03 best-of-N comparison was retracted for exactly this).
    from jax import lax

    def make_multi(step_math):
        @jax.jit
        def fn(state, X, T, idx_all):
            def epoch(c, ix_e):
                def body(cc, ix):
                    w2, m2, l = step_math(cc[0], cc[1], X[ix], T[ix])
                    return (w2, m2), l
                return lax.scan(body, c, ix_e)
            return lax.scan(epoch, state, idx_all)
        return fn

    def xla_step(momentum, lr):
        def f(w, m, Xb, Tb):
            return dp.train_step_math(w, m, Xb, Tb, model="ann",
                                      momentum=momentum, lr=lr, alpha=0.2)
        return f

    def pal_step(momentum, lr):
        from hpnn_tpu.ops import pallas_train

        def f(w, m, Xb, Tb):
            return pallas_train.train_step_fused_batch(
                w, m, Xb, Tb, model="ann", momentum=momentum, lr=lr,
                alpha=0.2)
        return f

    def slope_setup(ws, B, n_in_t, n_out_t, step_math, momentum,
                    e_big=SLOPE_E_BIG):
        dw = tuple(jnp.zeros_like(w) for w in ws) if momentum else ()
        rngb = np.random.RandomState(11)
        Xb = jnp.asarray(rngb.uniform(0, 255, (B, n_in_t)).astype(np.float32))
        Tb_np = np.full((B, n_out_t), -1.0, dtype=np.float32)
        Tb_np[np.arange(B), rngb.randint(0, n_out_t, B)] = 1.0
        Tb = jnp.asarray(Tb_np)

        def mk_idx(E):
            return jnp.asarray(
                np.stack([np.stack([
                    np.random.RandomState(e * 101 + s).permutation(B)
                    for s in range(SLOPE_S)]) for e in range(E)]),
                dtype=jnp.int32)

        fn = make_multi(step_math)
        i_s, i_b = mk_idx(SLOPE_E_SMALL), mk_idx(e_big)

        def once(ix):
            t0 = time.perf_counter()
            r = fn((ws, dw), Xb, Tb, ix)
            np.asarray(r[1]).ravel()
            return time.perf_counter() - t0

        once(i_s)
        once(i_b)  # warm both shapes
        d = (e_big - SLOPE_E_SMALL) * SLOPE_S

        def sample():
            return 1e6 * (once(i_b) - once(i_s)) / d

        return B, sample

    variants = {
        "xla_B1024": slope_setup(
            weights, BATCH_B, 784, 10, xla_step(False, 0.001), False),
        "xla_B2048": slope_setup(
            weights, 2 * BATCH_B, 784, 10, xla_step(False, 0.001), False),
    }
    if jax.default_backend() == "tpu":
        # the XRD pair (851-230-230 BPM, B=256) exists for the
        # Pallas-vs-XLA comparison at the shape where the kernel wins
        # — paired, so TPU-only (off-TPU it would be an expensive
        # unpaired workload with no counterpart); longer dispatches
        # because its ~3x-faster step would under-resolve the delta
        kx, _ = kernel_mod.generate(10958, 851, [230], 230)
        w_xrd = tuple(
            jnp.asarray(np.asarray(w), dtype=jnp.float32)
            for w in kx.weights
        )
        XRD_B, XRD_E_BIG = 256, 805
        variants["pallas_B1024"] = slope_setup(
            weights, BATCH_B, 784, 10, pal_step(False, 0.001), False)
        variants["xrd_xla_B256"] = slope_setup(
            w_xrd, XRD_B, 851, 230, xla_step(True, 0.4), True,
            e_big=XRD_E_BIG)
        variants["xrd_pallas_B256"] = slope_setup(
            w_xrd, XRD_B, 851, 230, pal_step(True, 0.4), True,
            e_big=XRD_E_BIG)
    slope_us = {k: [] for k in variants}
    for _ in range(SLOPE_REPEATS):
        for k, (_B, sample) in variants.items():  # interleaved: paired
            slope_us[k].append(sample())
    slope = {
        k: {"us_per_step": [round(v, 2) for v in vals],
            "median_us": round(statistics.median(vals), 2),
            "samples_per_s_M": round(
                variants[k][0] / statistics.median(vals), 2)}
        for k, vals in slope_us.items()
    }
    for tag, a_key, b_key in (
        ("paired_pallas_vs_xla_pct", "pallas_B1024", "xla_B1024"),
        ("paired_xrd_pallas_vs_xla_pct", "xrd_pallas_B256", "xrd_xla_B256"),
    ):
        if a_key in slope_us:
            deltas = [
                round(100.0 * (b - a) / b, 2)
                for a, b in zip(slope_us[a_key], slope_us[b_key])
            ]  # + = pallas faster per paired round
            slope[tag] = {
                "per_round": deltas,
                "median": round(statistics.median(deltas), 2),
            }

    # -- r05: PRODUCTION data-path slope at realistic bank size -------
    # The slope variants above keep their one-batch bank for r01–r04
    # series continuity, but a 3.2 MB bank can go VMEM-resident and
    # delete the HBM traffic being modeled (BASELINE.md r05
    # correction).  This section measures what `train_nn --batch`
    # actually dispatches — 60-step epochs over an S·B-row HBM bank,
    # per-epoch on-device accuracy eval included — for the r04 default
    # (per-step gather) and the r05 default (bankR=8 + block order).
    prod_slope = None
    if jax.default_backend() == "tpu":
        import contextlib
        import io
        import sys as _sys

        _sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import bench_bank

        with contextlib.redirect_stdout(io.StringIO()):
            prod_slope = bench_bank.run_shape(
                "mnist-prod", n_in=784, n_hidden=300, n_out=10,
                B=BATCH_B, S=60, momentum=False,
                e_small=8, e_big=208, repeats=SLOPE_REPEATS,
                variants={"gather-pallas", "bankR-pallas"},
            )

    # FLOPs/step: fwd 2PB + bwd 4PB + loss re-forward 2PB = 8PB.
    # Achieved rate from the XLA-scan SLOPE (at this MNIST shape the
    # two dispatches measure identical — slope section — so the
    # XLA figure stands for both; the absolute-mode number keeps
    # ~8 us/step of tunnel amortization and is reported separately
    # for series continuity).
    flops_per_step = 8 * n_params * BATCH_B
    slope_med_us = slope["xla_B1024"]["median_us"]
    achieved = flops_per_step / (slope_med_us * 1e-6)
    # bandwidth-bound ceiling (BASELINE.md roofline): ~11.6 MB/step at
    # ~819 GB/s -> the %-peak figure is reported against BOTH bounds
    hbm_bytes_per_step = (3 * 4 * BATCH_B * 784 + 2 * 4 * n_params
                          + 3 * 4 * BATCH_B * 10)
    bw_ceiling_flops = flops_per_step / (hbm_bytes_per_step / 819e9)
    out = {
        "batch_size": BATCH_B,
        # what THIS section measured (the slope section covers both
        # dispatches; at this shape they are identical)
        "dispatch_measured": "xla_scan",
        # what production uses by default since r04 (BASELINE.md)
        "production_default": "ann=pallas_fused snn=xla_scan",
        "samples_per_s": _stats(scan_sps),
        "steps_per_s": _stats(scan_stps),
        "slope": slope,
        "achieved_tflops": round(achieved / 1e12, 3),
        "pct_v5e_bf16_peak": round(100.0 * achieved / V5E_PEAK_FLOPS, 3),
        "pct_hbm_bound_ceiling": round(
            100.0 * achieved / bw_ceiling_flops, 1),
        "final_loss": final_loss,
        "per_step_dispatch": {
            "samples_per_s": _stats(disp_sps),
            "steps_per_s": _stats(disp_stps),
        },
    }
    if prod_slope is not None:
        out["prod_slope_60k_bank"] = prod_slope
    return out


def _tiny_round_conf(d: str):
    """The check_tokens 6-sample 8->5->2 shape: writes the sample set
    under ``d`` and returns a fresh-conf factory for paired rounds."""
    from hpnn_tpu.config import NNConf, NNTrain, NNType
    from hpnn_tpu.models import kernel as kernel_mod

    rng = np.random.RandomState(0)
    sdir = os.path.join(d, "samples")
    os.makedirs(sdir)
    for i in range(6):
        c = i % 2
        x = (1 - 2 * c) * np.r_[np.ones(4), -np.ones(4)] \
            + 0.1 * rng.normal(size=8)
        t = np.full(2, -1.0)
        t[c] = 1.0
        with open(os.path.join(sdir, f"s{i:05d}.txt"), "w") as fp:
            fp.write("[input] 8\n"
                     + " ".join(f"{v:.5f}" for v in x) + "\n")
            fp.write("[output] 2\n"
                     + " ".join(f"{v:.1f}" for v in t) + "\n")

    def conf():
        k, _ = kernel_mod.generate(7, 8, [5], 2)
        return NNConf(name="b", type=NNType.ANN, seed=1, kernel=k,
                      train=NNTrain.BP, samples=sdir, tests=sdir)

    return conf


def bench_obs_overhead(repeats: int = 5):
    """Paired measurement of the obs subsystem's cost: the SAME tiny
    fused train round (the check_tokens 6-sample 8->5->2 shape) with
    ``HPNN_METRICS`` pointed at a fresh sink vs unset, interleaved so
    each pair shares machine conditions.  Quantifies the design claim
    that instrumentation is cheap when on and free when off."""
    from hpnn_tpu import obs
    from hpnn_tpu.train import driver

    prev_sink = obs.sink_path() if obs.enabled() else None
    d = tempfile.mkdtemp(prefix="hpnn_obs_bench_")
    try:
        conf = _tiny_round_conf(d)

        # warm both paths (compile caches, sink open)
        obs.configure(None)
        driver.train_kernel(conf())
        obs.configure(os.path.join(d, "warm.jsonl"))
        driver.train_kernel(conf())

        on_s, off_s = [], []
        for r in range(repeats):
            obs.configure(None)
            t0 = time.perf_counter()
            driver.train_kernel(conf())
            off_s.append(time.perf_counter() - t0)
            obs.configure(os.path.join(d, f"r{r}.jsonl"))
            t0 = time.perf_counter()
            driver.train_kernel(conf())
            on_s.append(time.perf_counter() - t0)
        deltas = [round(100.0 * (a - b) / b, 2)
                  for a, b in zip(on_s, off_s)]
        return {
            "round_s_metrics_off": _stats([round(v, 4) for v in off_s]),
            "round_s_metrics_on": _stats([round(v, 4) for v in on_s]),
            "paired_overhead_pct": {
                "per_round": deltas,
                "median": round(statistics.median(deltas), 2),
            },
        }
    finally:
        obs.configure(prev_sink)
        shutil.rmtree(d, ignore_errors=True)


def bench_collector_overhead(repeats: int = 5):
    """Paired measurement of the fleet telemetry plane's MARGINAL
    cost: the same tiny fused round with the JSONL sink armed in BOTH
    legs, plus — in the "on" leg only — a live collector receiving
    the push client's batches and an ``HPNN_ALERTS`` threshold rule
    that actually fires on the round's own ``fuse.chunk_size`` gauge.
    Quantifies the ISSUE 12 claim that telemetry never backpressures
    the hot path (tools/bench_gate.py gates
    ``collector_overhead_pct``)."""
    from hpnn_tpu import obs
    from hpnn_tpu.obs import collector as collector_mod
    from hpnn_tpu.train import driver

    prev_sink = obs.sink_path() if obs.enabled() else None
    d = tempfile.mkdtemp(prefix="hpnn_coll_bench_")
    server = collector_mod.start_collector()
    port = server.server_address[1]
    saved = {k: os.environ.pop(k, None)
             for k in ("HPNN_COLLECTOR", "HPNN_COLLECTOR_FLUSH_S",
                       "HPNN_ALERTS")}

    def arm(on: bool, sink: str) -> None:
        # obs.configure re-runs the reset chain, so the collector
        # client + alert rules re-read the env on the next emit
        if on:
            os.environ["HPNN_COLLECTOR"] = f"http://127.0.0.1:{port}"
            os.environ["HPNN_ALERTS"] = \
                "bench_chunk@fuse.chunk_size>0:cooldown=0"
        else:
            os.environ.pop("HPNN_COLLECTOR", None)
            os.environ.pop("HPNN_ALERTS", None)
        obs.configure(sink)

    try:
        conf = _tiny_round_conf(d)

        # warm both legs (compile caches, sink open, client thread)
        arm(False, os.path.join(d, "warm_off.jsonl"))
        driver.train_kernel(conf())
        arm(True, os.path.join(d, "warm_on.jsonl"))
        driver.train_kernel(conf())

        on_s, off_s = [], []
        for r in range(repeats):
            arm(False, os.path.join(d, f"off{r}.jsonl"))
            t0 = time.perf_counter()
            driver.train_kernel(conf())
            off_s.append(time.perf_counter() - t0)
            arm(True, os.path.join(d, f"on{r}.jsonl"))
            t0 = time.perf_counter()
            driver.train_kernel(conf())
            on_s.append(time.perf_counter() - t0)
        deltas = [round(100.0 * (a - b) / b, 2)
                  for a, b in zip(on_s, off_s)]
        return {
            "round_s_collector_off": _stats([round(v, 4) for v in off_s]),
            "round_s_collector_on": _stats([round(v, 4) for v in on_s]),
            "paired_overhead_pct": {
                "per_round": deltas,
                "median": round(statistics.median(deltas), 2),
            },
            # the proof the "on" leg measured a LIVE pipeline, not a
            # dead URL shedding batches
            "collector_records_total": server.collector.records_total,
        }
    finally:
        obs.configure(None)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        obs.configure(prev_sink)
        collector_mod.stop_collector(server)
        shutil.rmtree(d, ignore_errors=True)


def bench_sampler_overhead(iters: int = 200, repeats: int = 5):
    """Paired measurement of the tail sampler's MARGINAL cost on the
    serve hot path: the same ``Session.infer`` loop with the JSONL
    sink armed in BOTH legs, plus — in the "on" leg only —
    ``HPNN_SAMPLE=1`` (every request minted and span-recorded, the
    worst case; production rates are 0.01–0.05).  Quantifies the
    claim that always-on tail sampling is affordable
    (docs/observability.md "Forensics"; tools/bench_gate.py gates
    ``sampler_overhead_pct``)."""
    from hpnn_tpu import obs, serve
    from hpnn_tpu.models import kernel as kernel_mod

    prev_sink = obs.sink_path() if obs.enabled() else None
    d = tempfile.mkdtemp(prefix="hpnn_sampler_bench_")
    saved = os.environ.pop("HPNN_SAMPLE", None)

    def arm(on: bool, sink: str) -> None:
        # obs.configure re-runs the reset chain, so the sampler memo
        # re-reads HPNN_SAMPLE on the next request
        if on:
            os.environ["HPNN_SAMPLE"] = "1"
        else:
            os.environ.pop("HPNN_SAMPLE", None)
        obs.configure(sink)

    n_in, n_hid, n_out = FLEET_SHAPE
    kern = kernel_mod.generate(4242, n_in, [n_hid], n_out)[0]
    x = np.random.RandomState(2).normal(size=n_in)
    sess = None
    try:
        sess = serve.Session(max_batch=8, n_buckets=2,
                             max_wait_ms=0.5)
        sess.register_kernel("bench", kern)

        # warm both legs (compile, sink open, sampler memo)
        arm(False, os.path.join(d, "warm_off.jsonl"))
        for _ in range(10):
            sess.infer("bench", x)
        arm(True, os.path.join(d, "warm_on.jsonl"))
        for _ in range(10):
            sess.infer("bench", x)

        on_s, off_s = [], []
        for r in range(repeats):
            arm(False, os.path.join(d, f"off{r}.jsonl"))
            t0 = time.perf_counter()
            for _ in range(iters):
                sess.infer("bench", x)
            off_s.append(time.perf_counter() - t0)
            arm(True, os.path.join(d, f"on{r}.jsonl"))
            t0 = time.perf_counter()
            for _ in range(iters):
                sess.infer("bench", x)
            on_s.append(time.perf_counter() - t0)
        obs.configure(None)  # close the last sink so the count below
        # is over flushed bytes

        # the proof the "on" leg actually sampled: every request of
        # the last on-leg must have minted a serve.request span
        sampled = 0
        with open(os.path.join(d, f"on{repeats - 1}.jsonl")) as fp:
            for ln in fp:
                sampled += ('"span.end"' in ln
                            and '"serve.request"' in ln)
        deltas = [round(100.0 * (a - b) / b, 2)
                  for a, b in zip(on_s, off_s)]
        return {
            "iters": iters,
            "infer_s_sampler_off": _stats([round(v, 4) for v in off_s]),
            "infer_s_sampler_on": _stats([round(v, 4) for v in on_s]),
            "paired_overhead_pct": {
                "per_round": deltas,
                "median": round(statistics.median(deltas), 2),
            },
            "sampled_requests_last_round": sampled,
        }
    finally:
        if sess is not None:
            sess.close()
        obs.configure(None)
        if saved is None:
            os.environ.pop("HPNN_SAMPLE", None)
        else:
            os.environ["HPNN_SAMPLE"] = saved
        obs.configure(prev_sink)
        shutil.rmtree(d, ignore_errors=True)


def bench_blame_overhead(iters: int = 200, repeats: int = 5):
    """Paired measurement of the online blame engine's MARGINAL cost
    on the serve hot path: the same ``Session.infer`` loop with the
    JSONL sink AND ``HPNN_SAMPLE=1`` armed in BOTH legs (blame only
    sees sampler-emitted request roots, so the sampler must run in
    both to isolate blame's delta), plus — in the "on" leg only —
    ``HPNN_BLAME=1`` (every root's subtree classified and folded into
    the rolling window).  Quantifies the claim that live per-phase
    blame attribution is affordable (docs/selftuning.md;
    tools/bench_gate.py gates ``blame_overhead_pct``)."""
    from hpnn_tpu import obs, serve
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.obs import blame

    prev_sink = obs.sink_path() if obs.enabled() else None
    d = tempfile.mkdtemp(prefix="hpnn_blame_bench_")
    saved = {k: os.environ.pop(k, None)
             for k in ("HPNN_SAMPLE", "HPNN_BLAME")}

    def arm(on: bool, sink: str) -> None:
        # obs.configure re-runs the reset chain, so the sampler and
        # blame memos re-read their knobs on the next request
        os.environ["HPNN_SAMPLE"] = "1"
        if on:
            os.environ["HPNN_BLAME"] = "1"
        else:
            os.environ.pop("HPNN_BLAME", None)
        obs.configure(sink)

    n_in, n_hid, n_out = FLEET_SHAPE
    kern = kernel_mod.generate(4243, n_in, [n_hid], n_out)[0]
    x = np.random.RandomState(3).normal(size=n_in)
    sess = None
    try:
        sess = serve.Session(max_batch=8, n_buckets=2,
                             max_wait_ms=0.5)
        sess.register_kernel("bench", kern)

        # warm both legs (compile, sink open, sampler + blame memos)
        arm(False, os.path.join(d, "warm_off.jsonl"))
        for _ in range(10):
            sess.infer("bench", x)
        arm(True, os.path.join(d, "warm_on.jsonl"))
        for _ in range(10):
            sess.infer("bench", x)

        on_s, off_s = [], []
        roots_seen = 0
        for r in range(repeats):
            arm(False, os.path.join(d, f"off{r}.jsonl"))
            t0 = time.perf_counter()
            for _ in range(iters):
                sess.infer("bench", x)
            off_s.append(time.perf_counter() - t0)
            arm(True, os.path.join(d, f"on{r}.jsonl"))
            t0 = time.perf_counter()
            for _ in range(iters):
                sess.infer("bench", x)
            on_s.append(time.perf_counter() - t0)
            # the proof the "on" leg actually classified: the rolling
            # window must have folded this leg's request roots
            roots_seen = blame.health_doc().get("roots_seen", 0)
        obs.configure(None)

        deltas = [round(100.0 * (a - b) / b, 2)
                  for a, b in zip(on_s, off_s)]
        return {
            "iters": iters,
            "infer_s_blame_off": _stats([round(v, 4) for v in off_s]),
            "infer_s_blame_on": _stats([round(v, 4) for v in on_s]),
            "paired_overhead_pct": {
                "per_round": deltas,
                "median": round(statistics.median(deltas), 2),
            },
            "roots_seen_last_round": roots_seen,
        }
    finally:
        if sess is not None:
            sess.close()
        obs.configure(None)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        obs.configure(prev_sink)
        shutil.rmtree(d, ignore_errors=True)


def bench_drift_overhead(iters: int = 200, repeats: int = 5):
    """Paired measurement of the drift plane's MARGINAL cost on the
    two hot paths it taps: the same ``Session.infer`` +
    ``SampleBuffer.feed`` loop with the JSONL sink armed in BOTH
    legs, plus — in the "on" leg only — ``HPNN_DRIFT=1`` (every
    dispatch folded into the prediction sketch, every feed into the
    ingest sketch).  Quantifies the claim that armed sketches are
    affordable on the hot path (docs/observability.md "Drift
    detection"; tools/bench_gate.py gates ``drift_overhead_pct``)."""
    from hpnn_tpu import obs, serve
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.online import ingest as ingest_mod

    prev_sink = obs.sink_path() if obs.enabled() else None
    d = tempfile.mkdtemp(prefix="hpnn_drift_bench_")
    saved = {k: os.environ.pop(k, None)
             for k in ("HPNN_DRIFT", "HPNN_DRIFT_WINDOW",
                       "HPNN_DRIFT_Z")}

    def arm(on: bool, sink: str) -> None:
        # the drift memo caches the armed config, so each leg resets
        # it; the small window makes the sketches actually SCORE
        # inside a leg (reference frozen at 64 rows, live scoring
        # from row 80 of the 200)
        if on:
            os.environ["HPNN_DRIFT"] = "1"
            os.environ["HPNN_DRIFT_WINDOW"] = "64"
        else:
            os.environ.pop("HPNN_DRIFT", None)
            os.environ.pop("HPNN_DRIFT_WINDOW", None)
        obs.drift._reset_for_tests()
        obs.configure(sink)

    n_in, n_hid, n_out = FLEET_SHAPE
    kern = kernel_mod.generate(4242, n_in, [n_hid], n_out)[0]
    rng = np.random.RandomState(2)
    Xs = rng.normal(size=(iters, n_in))
    t = np.full(n_out, -1.0)
    t[0] = 1.0
    sess = None
    try:
        sess = serve.Session(max_batch=8, n_buckets=2,
                             max_wait_ms=0.5)
        sess.register_kernel("bench", kern)
        buf = ingest_mod.SampleBuffer(capacity=max(64, iters))

        def leg() -> None:
            for i in range(iters):
                sess.infer("bench", Xs[i])
                buf.feed(Xs[i], t)

        # warm both legs (compile, sink open, drift memo)
        arm(False, os.path.join(d, "warm_off.jsonl"))
        leg()
        arm(True, os.path.join(d, "warm_on.jsonl"))
        leg()

        on_s, off_s = [], []
        for r in range(repeats):
            arm(False, os.path.join(d, f"off{r}.jsonl"))
            t0 = time.perf_counter()
            leg()
            off_s.append(time.perf_counter() - t0)
            arm(True, os.path.join(d, f"on{r}.jsonl"))
            t0 = time.perf_counter()
            leg()
            on_s.append(time.perf_counter() - t0)
        obs.configure(None)  # close the last sink so the scan below
        # is over flushed bytes

        # the proof the "on" leg actually sketched: the last on-leg
        # must carry drift gauges from both taps
        scored = {"pred": 0, "ingest": 0}
        with open(os.path.join(d, f"on{repeats - 1}.jsonl")) as fp:
            for ln in fp:
                scored["pred"] += '"drift.pred_shift"' in ln
                scored["ingest"] += ('"drift.score"' in ln
                                     and '"ingest"' in ln)
        deltas = [round(100.0 * (a - b) / b, 2)
                  for a, b in zip(on_s, off_s)]
        return {
            "iters": iters,
            "loop_s_drift_off": _stats([round(v, 4) for v in off_s]),
            "loop_s_drift_on": _stats([round(v, 4) for v in on_s]),
            "paired_overhead_pct": {
                "per_round": deltas,
                "median": round(statistics.median(deltas), 2),
            },
            "drift_gauges_last_round": scored,
        }
    finally:
        if sess is not None:
            sess.close()
        obs.configure(None)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        from hpnn_tpu.obs import drift as _drift_mod

        _drift_mod._reset_for_tests()
        obs.configure(prev_sink)
        shutil.rmtree(d, ignore_errors=True)


def bench_meter_overhead(iters: int = 300, repeats: int = 7,
                         tenants: int = 4):
    """Paired measurement of the metering plane's MARGINAL cost on
    the serve hot path it taps: the same round-robin
    ``Session.infer`` loop over ``tenants`` tenant-scoped kernels
    with the JSONL sink armed in BOTH legs, plus — in the "on" leg
    only — ``HPNN_METER=1`` (every dispatch folded into the
    space-saving sketches, throttled ``meter.sketch`` emissions).
    Quantifies the claim that armed metering is affordable on the
    hot path (docs/observability.md "Tenant metering";
    tools/bench_gate.py gates ``meter_overhead_pct``)."""
    from hpnn_tpu import obs, serve
    from hpnn_tpu.models import kernel as kernel_mod

    prev_sink = obs.sink_path() if obs.enabled() else None
    d = tempfile.mkdtemp(prefix="hpnn_meter_bench_")
    saved = {k: os.environ.pop(k, None)
             for k in ("HPNN_METER", "HPNN_METER_TOPK")}

    def arm(on: bool, sink: str) -> None:
        # the meter memo caches the armed config, so each leg resets
        # it through the programmatic twin
        if on:
            os.environ["HPNN_METER"] = "1"
        else:
            os.environ.pop("HPNN_METER", None)
        obs.meter._reset_for_tests()
        obs.configure(sink)

    n_in, n_hid, n_out = FLEET_SHAPE
    kern = kernel_mod.generate(4243, n_in, [n_hid], n_out)[0]
    rng = np.random.RandomState(3)
    Xs = rng.normal(size=(iters, n_in))
    names = [f"t{j}:bench" for j in range(tenants)]
    sess = None
    try:
        sess = serve.Session(max_batch=8, n_buckets=2,
                             max_wait_ms=0.5)
        for name in names:
            sess.register_kernel(name, kern)

        def leg() -> None:
            for i in range(iters):
                sess.infer(names[i % tenants], Xs[i])

        # warm both legs (compile, sink open, meter memo)
        arm(False, os.path.join(d, "warm_off.jsonl"))
        leg()
        arm(True, os.path.join(d, "warm_on.jsonl"))
        leg()

        on_s, off_s = [], []
        for r in range(repeats):
            arm(False, os.path.join(d, f"off{r}.jsonl"))
            t0 = time.perf_counter()
            leg()
            off_s.append(time.perf_counter() - t0)
            arm(True, os.path.join(d, f"on{r}.jsonl"))
            t0 = time.perf_counter()
            leg()
            on_s.append(time.perf_counter() - t0)
            obs.meter.emit_sketch()  # unthrottled proof, outside the
            # timed region
        obs.configure(None)  # close the last sink so the scan below
        # is over flushed bytes

        # the proof the "on" leg actually metered: the last on-leg
        # sink must carry meter.sketch records
        sketches = 0
        with open(os.path.join(d, f"on{repeats - 1}.jsonl")) as fp:
            for ln in fp:
                sketches += '"meter.sketch"' in ln
        deltas = [round(100.0 * (a - b) / b, 2)
                  for a, b in zip(on_s, off_s)]
        return {
            "iters": iters,
            "tenants": tenants,
            "loop_s_meter_off": _stats([round(v, 4) for v in off_s]),
            "loop_s_meter_on": _stats([round(v, 4) for v in on_s]),
            "paired_overhead_pct": {
                "per_round": deltas,
                "median": round(statistics.median(deltas), 2),
            },
            "meter_sketches_last_round": sketches,
        }
    finally:
        if sess is not None:
            sess.close()
        obs.configure(None)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        from hpnn_tpu.obs import meter as _meter_mod

        _meter_mod._reset_for_tests()
        obs.configure(prev_sink)
        shutil.rmtree(d, ignore_errors=True)


def bench_conn_overhead(iters: int = 150, repeats: int = 5):
    """Paired measurement of the connection plane's MARGINAL cost on
    the HTTP serve path (docs/serving.md "Connection plane"): the
    same keep-alive ``POST /v1/infer`` loop against two ``make_server``
    front ends over ONE warm Session — one with every ``HPNN_CONN_*``
    guard armed (raw-I/O byte accounting, read deadlines, per-IP
    bookkeeping, the byte-rate watchdog), one unarmed (the strict
    no-op path).  The env memo is re-pointed before each leg so the
    handler-side knob reads match the server being driven.  Quantifies
    the claim that always-on socket telemetry is affordable — the bar
    is ≤ 5%; tools/bench_gate.py gates ``conn_overhead_pct``."""
    import http.client
    import socket
    import threading

    from hpnn_tpu import serve
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve import conn as conn_mod

    conn_keys = ("HPNN_CONN_HDR_MS", "HPNN_CONN_BODY_MS",
                 "HPNN_CONN_PER_IP", "HPNN_CONN_MIN_BPS",
                 "HPNN_CONN_TABLE")
    saved = {k: os.environ.pop(k, None) for k in conn_keys}

    def arm(on: bool) -> None:
        if on:
            os.environ["HPNN_CONN_HDR_MS"] = "10000"
            os.environ["HPNN_CONN_BODY_MS"] = "10000"
            os.environ["HPNN_CONN_PER_IP"] = "64"
            os.environ["HPNN_CONN_MIN_BPS"] = "1"
            os.environ["HPNN_CONN_TABLE"] = "64"
        else:
            for k in conn_keys:
                os.environ.pop(k, None)
        conn_mod._reset_for_tests()

    n_in, n_hid, n_out = FLEET_SHAPE
    kern = kernel_mod.generate(4244, n_in, [n_hid], n_out)[0]
    body = json.dumps(
        {"kernel": "bench",
         "inputs": [[0.1] * n_in], "timeout_s": 10.0}).encode()
    hdrs = {"Content-Type": "application/json"}

    def drive(port: int, n: int) -> tuple[float, int]:
        client = http.client.HTTPConnection("127.0.0.1", port,
                                            timeout=10.0)
        client.connect()
        client.sock.setsockopt(socket.IPPROTO_TCP,
                               socket.TCP_NODELAY, 1)
        bad = 0
        try:
            t0 = time.perf_counter()
            for _ in range(n):
                client.request("POST", "/v1/infer", body=body,
                               headers=hdrs)
                resp = client.getresponse()
                resp.read()
                bad += resp.status != 200
            return time.perf_counter() - t0, bad
        finally:
            client.close()

    sess = None
    servers: list = []
    try:
        sess = serve.Session(max_batch=8, n_buckets=2,
                             max_wait_ms=0.5)
        sess.register_kernel("bench", kern)
        arm(False)
        server_off = serve.make_server(sess, port=0)
        servers.append(server_off)
        arm(True)
        server_on = serve.make_server(sess, port=0)
        servers.append(server_on)
        for server in servers:
            threading.Thread(target=server.serve_forever,
                             daemon=True).start()
        port_off = server_off.server_address[1]
        port_on = server_on.server_address[1]

        # warm both legs (compile, route, thread pools)
        arm(False)
        drive(port_off, 10)
        arm(True)
        drive(port_on, 10)

        on_s, off_s = [], []
        errors = 0
        for _ in range(repeats):
            arm(False)
            dt, bad = drive(port_off, iters)
            off_s.append(dt)
            errors += bad
            arm(True)
            dt, bad = drive(port_on, iters)
            on_s.append(dt)
            errors += bad
        # the proof the "on" leg was actually guarded: its census
        # must have admitted and request-counted the driver
        census = conn_mod.connz_doc(server_on)
        deltas = [round(100.0 * (a - b) / b, 2)
                  for a, b in zip(on_s, off_s)]
        return {
            "iters": iters,
            "http_s_conn_off": _stats([round(v, 4) for v in off_s]),
            "http_s_conn_on": _stats([round(v, 4) for v in on_s]),
            "paired_overhead_pct": {
                "per_round": deltas,
                "median": round(statistics.median(deltas), 2),
            },
            "errors": errors,
            "guarded_conns_opened": census.get("opened", 0),
        }
    finally:
        for server in servers:
            server.shutdown()
            server.server_close()
        if sess is not None:
            sess.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        conn_mod._reset_for_tests()


FLEET_MEMBERS = 64
FLEET_SHAPE = (32, 16, 4)   # HPNN-sized: the paper's natural workload
FLEET_TICKS = 30
FLEET_REPEATS = 3


def bench_fleet(members: int = FLEET_MEMBERS, ticks: int = FLEET_TICKS,
                repeats: int = FLEET_REPEATS):
    """Aggregate train samples/s of an N-member fleet of HPNN-sized
    kernels under the streaming per-arrival workload (PAPER.md §0:
    many small nets riding a scientific calculation, one new sample
    per tick each), dispatched two ways over the SAME math and data:

    * **sequential** — the per-kernel loop: one
      ``fleet.make_member_epoch_fn`` dispatch per member per tick
      (N dispatches/tick), the pre-fleet serving pattern;
    * **fleet** — ``fleet.make_fleet_epoch_fn``: the members' weights
      stacked on a leading axis, ONE vmapped dispatch per tick.

    At this shape the per-dispatch math is a few us, so the sequential
    loop is pure dispatch overhead — the fleet's one-dispatch
    amortization is the measured win (≥5x is the ISSUE 6 acceptance
    bar; tools/bench_gate.py gates ``fleet_speedup_x`` /
    ``fleet_agg_sps``).  At MNIST size (784-300-10) on a 1-core CPU
    host the ratio inverts (the stacked matmul is compute-bound, see
    docs/fleet.md) — the fleet lever is dispatch amortization, and
    this workload is the one that is dispatch-bound.
    """
    import jax

    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.train import fleet as fleet_mod

    n_in, n_hid, n_out = FLEET_SHAPE
    B = 1  # per-arrival streaming: each tick trains on one new sample
    kernels = [
        kernel_mod.generate(1000 + i, n_in, [n_hid], n_out,
                            dtype=np.float32)[0]
        for i in range(members)
    ]
    rng = np.random.RandomState(0)
    X = rng.normal(size=(B, n_in)).astype(np.float32)
    T = np.where(np.eye(n_out)[rng.randint(0, n_out, B)] > 0,
                 1.0, -1.0).astype(np.float32)
    seeds = list(range(members))
    # one "epoch" per tick over the B-row buffer: n_steps=1, so each
    # dispatch is exactly one train step (count off — the progress
    # count is identical per member on both sides and would dilute
    # the dispatch-bound regime this workload models)
    member_fn = fleet_mod.make_member_epoch_fn(1, model="ann",
                                               count=False)
    fleet_fn = fleet_mod.make_fleet_epoch_fn(1, model="ann",
                                             count=False)
    import jax.numpy as jnp

    Xd, Td = jnp.asarray(X), jnp.asarray(T)
    plans = [fleet_mod.member_plan(s, n_rows=B, batch=B, epochs=1)
             for s in seeds]
    member_idx = [(jnp.asarray(p), jnp.asarray(o)) for p, o in plans]
    fperms, forders = fleet_mod.fleet_plan(seeds, n_rows=B, batch=B,
                                           epochs=1)
    fperms, forders = jnp.asarray(fperms), jnp.asarray(forders)
    stacked = fleet_mod.stack_kernels(kernels)
    member_w = [tuple(jnp.asarray(w) for w in k.weights)
                for k in kernels]

    # warm both dispatch paths
    member_fn(member_w[0], (), Xd, Td, *member_idx[0])
    jax.block_until_ready(fleet_fn(stacked, (), Xd, Td, fperms,
                                   forders)[0])

    seq_s, fleet_s = [], []
    for _ in range(repeats):
        ws = list(member_w)
        t0 = time.perf_counter()
        for _t in range(ticks):
            for i in range(members):
                ws[i], _, _, _ = member_fn(ws[i], (), Xd, Td,
                                           *member_idx[i])
        jax.block_until_ready(ws)
        seq_s.append(time.perf_counter() - t0)

        sw = stacked
        t0 = time.perf_counter()
        for _t in range(ticks):
            sw, _, _, _ = fleet_fn(sw, (), Xd, Td, fperms, forders)
        jax.block_until_ready(sw)
        fleet_s.append(time.perf_counter() - t0)

    agg = members * B * ticks  # samples per measured loop
    speedups = [round(s / f, 3) for s, f in zip(seq_s, fleet_s)]
    return {
        "members": members,
        "shape": f"{n_in}-{n_hid}-{n_out}",
        "batch_per_member": B,
        "ticks": ticks,
        "sequential_agg_sps": _stats(
            [round(agg / s, 1) for s in seq_s]),
        "fleet_agg_sps": _stats([round(agg / f, 1) for f in fleet_s]),
        "paired_speedup_x": {
            "per_repeat": speedups,
            "median": round(statistics.median(speedups), 3),
        },
    }


MULTIROUND_K = 32
MULTIROUND_MEMBERS = 4   # an online-trainer topology group, not the
                         # 64-member sweep: the scan amortizes the
                         # dispatch floor, which only dominates when
                         # the per-round math is a few us


def bench_multiround(members: int = MULTIROUND_MEMBERS,
                     k: int = MULTIROUND_K,
                     repeats: int = FLEET_REPEATS):
    """Effective per-step cost of K training rounds dispatched two
    ways over the SAME math, data and per-round RNG plans
    (docs/performance.md "Multi-round-per-dispatch"):

    * **single** — ``fleet.make_fleet_epoch_fn``: K separate
      one-round dispatches (the pre-scan pattern, K dispatch taxes);
    * **multi** — ``fleet.make_fleet_multi_round_fn``: all K rounds
      scanned inside ONE ``jit(vmap(scan))`` executable.

    The shape models the online trainer's per-tick group dispatch
    (``HPNN_ONLINE_SCAN_K``): a small same-topology group streaming
    one-sample rounds, where the ~20 us dispatch tax dwarfs the
    few-us math — exactly the regime the scan exists for.  The
    scanned path's amortization of that floor is the measured win —
    ``multiround_amortization_x`` >= 5x at K=32 is the ISSUE 11
    acceptance bar, and ``tools/bench_gate.py`` gates the effective
    us/step against the trajectory.  The two paths are bitwise-equal
    on the f64 CPU backend (tests/test_quant.py), so this is a
    pure-overhead comparison, not a numerics trade.
    """
    import jax
    import jax.numpy as jnp

    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.train import fleet as fleet_mod

    n_in, n_hid, n_out = FLEET_SHAPE
    B = 1  # per-arrival streaming: one step per round
    kernels = [
        kernel_mod.generate(2000 + i, n_in, [n_hid], n_out,
                            dtype=np.float32)[0]
        for i in range(members)
    ]
    rng = np.random.RandomState(1)
    X = rng.normal(size=(B, n_in)).astype(np.float32)
    T = np.where(np.eye(n_out)[rng.randint(0, n_out, B)] > 0,
                 1.0, -1.0).astype(np.float32)
    Xd, Td = jnp.asarray(X), jnp.asarray(T)
    seed_rounds = [[r * members + i for i in range(members)]
                   for r in range(k)]
    single_fn = fleet_mod.make_fleet_epoch_fn(1, model="ann",
                                              count=False)
    multi_fn = fleet_mod.make_fleet_multi_round_fn(1, model="ann",
                                                   count=False)
    round_plans = [fleet_mod.fleet_plan(s, n_rows=B, batch=B, epochs=1)
                   for s in seed_rounds]
    round_plans = [(jnp.asarray(p), jnp.asarray(o))
                   for p, o in round_plans]
    mperms, morders = fleet_mod.multi_round_plan(
        seed_rounds, n_rows=B, batch=B, epochs=1)
    mperms, morders = jnp.asarray(mperms), jnp.asarray(morders)
    stacked = fleet_mod.stack_kernels(kernels)

    # warm both dispatch paths
    jax.block_until_ready(single_fn(stacked, (), Xd, Td,
                                    *round_plans[0])[0])
    jax.block_until_ready(multi_fn(stacked, (), Xd, Td, mperms,
                                   morders)[0])

    single_s, multi_s = [], []
    for _ in range(repeats):
        sw = stacked
        t0 = time.perf_counter()
        for p, o in round_plans:
            sw, _, _, _ = single_fn(sw, (), Xd, Td, p, o)
        jax.block_until_ready(sw)
        single_s.append(time.perf_counter() - t0)

        mw = stacked
        t0 = time.perf_counter()
        mw, _, _, _ = multi_fn(mw, (), Xd, Td, mperms, morders)
        jax.block_until_ready(mw)
        multi_s.append(time.perf_counter() - t0)

    amort = [round(s / m, 3) for s, m in zip(single_s, multi_s)]
    return {
        "members": members,
        "shape": f"{n_in}-{n_hid}-{n_out}",
        "k": k,
        "single_us_per_step": _stats(
            [round(s / k * 1e6, 1) for s in single_s]),
        "effective_us_per_step": _stats(
            [round(m / k * 1e6, 1) for m in multi_s]),
        "paired_amortization_x": {
            "per_repeat": amort,
            "median": round(statistics.median(amort), 3),
        },
    }


def bench_serve_bf16(rows: int = 64, iters: int = 40,
                     repeats: int = FLEET_REPEATS):
    """Paired goodput of the compiled serve engine under the bf16
    precision policy vs f32, on the SAME kernel, buckets and row
    blocks (docs/performance.md "Low-precision serving") — plus the
    warmup probe's measured ``max |bf16 - f64 reference|`` bound
    (``serve_bf16_max_abs_err``), so the gate watches the error next
    to the speed: a goodput regression OR an error-bound growth fails.
    On a CPU host bf16 is emulated (cast-and-widen, no bf16 ALU) so
    the ratio sits below 1x — the gate guards the per-host trajectory,
    not an absolute bar; on TPU the MXU's native bf16 mode is where
    the >=1x gain lands.
    """
    from hpnn_tpu import serve
    from hpnn_tpu.models import kernel as kernel_mod

    n_in, n_hid, n_out = FLEET_SHAPE
    kern = kernel_mod.generate(4242, n_in, [n_hid], n_out,
                               dtype=np.float32)[0]
    rng = np.random.RandomState(2)
    X = rng.normal(size=(rows, n_in)).astype(np.float32)

    engines = {}
    for prec in ("f32", "bf16"):
        reg = serve.Registry()
        reg.register("bench", kern)
        reg.set_precision("bench", prec)
        eng = serve.Engine(reg, mode="compiled", max_batch=rows,
                           n_buckets=3)
        eng.warmup()
        entry = reg.get("bench")
        eng.run_rows(entry, X)  # warm the dispatch path itself
        engines[prec] = (eng, entry)

    rps = {"f32": [], "bf16": []}
    for _ in range(repeats):
        for prec, (eng, entry) in engines.items():
            t0 = time.perf_counter()
            for _i in range(iters):
                eng.run_rows(entry, X)
            rps[prec].append(rows * iters
                             / (time.perf_counter() - t0))
    ratio = [round(b / f, 3)
             for b, f in zip(rps["bf16"], rps["f32"])]
    doc = engines["bf16"][0].precision_doc()["kernels"]["bench"]
    return {
        "shape": f"{n_in}-{n_hid}-{n_out}",
        "rows": rows,
        "f32_rps": _stats([round(v, 1) for v in rps["f32"]]),
        "bf16_rps": _stats([round(v, 1) for v in rps["bf16"]]),
        "goodput_vs_f32": {
            "per_repeat": ratio,
            "median": round(statistics.median(ratio), 3),
        },
        # the warmup probe's measured bound, not an assumption —
        # docs/performance.md documents < 1e-1 for paper-scale nets
        "max_abs_err": doc.get("quant_err"),
    }


def measure_reference(timeout_s: int = 600):
    """Build the reference serial+OMP and run the SAME 64-sample
    workload with the tutorial's -O4 -B4; returns samples/s or None."""
    ref = "/root/reference"
    if not (os.path.isdir(ref) and shutil.which("gcc")):
        return None
    d = tempfile.mkdtemp(prefix="hpnn_refbench_")
    try:
        exe = os.path.join(d, "train_nn_ref")
        build = subprocess.run(
            ["gcc", "-O2", "-fopenmp", "-D_OMP", f"-I{ref}/include",
             f"{ref}/src/libhpnn.c", f"{ref}/src/ann.c", f"{ref}/src/snn.c",
             f"{ref}/tests/train_nn.c", "-lm", "-o", exe],
            capture_output=True, text=True,
        )
        if build.returncode != 0:
            return None
        sdir = os.path.join(d, "samples")
        os.mkdir(sdir)
        for i, (x, t) in enumerate(make_workload()):
            with open(os.path.join(sdir, f"s{i:05d}.txt"), "w") as fp:
                fp.write("[input] 784\n" + " ".join("%7.5f" % v for v in x) + "\n")
                fp.write("[output] 10\n" + " ".join("%.1f" % v for v in t) + "\n")
        with open(os.path.join(d, "nn.conf"), "w") as fp:
            fp.write(
                "[name] B\n[type] ANN\n[init] generate\n[seed] 10958\n"
                "[input] 784\n[hidden] 300\n[output] 10\n[train] BP\n"
                "[sample_dir] ./samples\n[test_dir] ./samples\n"
            )
        try:
            t0 = time.perf_counter()
            res = subprocess.run(
                [exe, "-v", "-v", "-O", "4", "-B", "4", "nn.conf"],
                cwd=d, capture_output=True, text=True, timeout=timeout_s,
            )
            dt = time.perf_counter() - t0
        except subprocess.TimeoutExpired:
            return None
    finally:
        shutil.rmtree(d, ignore_errors=True)
    if res.returncode != 0:
        return None
    iters = sum(
        int(ln.split("N_ITER=")[1].split()[0])
        for ln in res.stdout.splitlines()
        if "N_ITER=" in ln
    )
    return {"samples_per_s": round(N_SAMPLES / dt, 3),
            "total_inner_iters": iters, "wall_s": round(dt, 2)}


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", action="store_true",
                    help="batch-mode benchmark only")
    ap.add_argument("--per-sample", action="store_true",
                    help="per-sample benchmark only")
    ap.add_argument("--no-ref", action="store_true",
                    help="skip in-run reference re-measurement")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet benchmark only: skip the MNIST-sized "
                         "per-sample/batch sections (hours on a small "
                         "CPU host) and headline the embedded-scale "
                         "fleet figures instead")
    args = ap.parse_args(argv)
    do_ps = (not args.batch or args.per_sample) and not args.fleet
    do_b = (not args.per_sample or args.batch) and not args.fleet

    out = {"metric": "mnist_synth_bp_train_throughput", "unit": "samples/s"}
    # in-run reference re-measurement only where it is apples-to-apples
    # (the per-sample protocol); a batch-only run uses the frozen figure
    # instead of paying ~5 min of reference training for one ratio
    ref = None if (args.no_ref or not do_ps) else measure_reference()
    base_sps = (ref or {}).get("samples_per_s", FROZEN_BASELINE_SPS)
    out["baseline_samples_per_s"] = base_sps
    out["baseline_source"] = "measured_in_run" if ref else "frozen_2026-07-29"
    if ref:
        out["baseline_detail"] = ref

    if do_ps:
        ps = bench_per_sample()
        out["value"] = ps["samples_per_s"]["median"]
        out["vs_baseline"] = round(out["value"] / base_sps, 3)
        out["per_sample"] = ps
    if do_b:
        b = bench_batch()
        out["batch"] = b
        out["batch_vs_baseline"] = round(
            b["samples_per_s"]["median"] / base_sps, 1
        )
        if not do_ps:
            out["metric"] = "mnist_synth_batch_train_throughput"
            out["value"] = b["samples_per_s"]["median"]
            out["vs_baseline"] = out["batch_vs_baseline"]

    # obs overhead: the same tiny fused round with metrics on vs off,
    # paired per repeat — best-effort, and BEFORE the sink fold-in
    # below (the measurement re-points the sink and then restores it)
    if not os.environ.get("HPNN_BENCH_NO_OBS_OVERHEAD"):
        try:
            out["obs_overhead"] = bench_obs_overhead()
        except Exception as exc:
            out["obs_overhead_error"] = repr(exc)

    # fleet telemetry overhead: the same paired shape with the sink
    # armed in both legs and a live collector + firing alert rule in
    # one (docs/observability.md "Fleet telemetry") — rides the same
    # skip knob, best-effort like the other fold-ins
    if not os.environ.get("HPNN_BENCH_NO_OBS_OVERHEAD"):
        try:
            out["collector_overhead"] = bench_collector_overhead()
        except Exception as exc:
            out["collector_overhead_error"] = repr(exc)

    # tail-sampler overhead: the same paired shape on the SERVE hot
    # path, HPNN_SAMPLE=1 in one leg (docs/observability.md
    # "Forensics") — rides the same skip knob, best-effort
    if not os.environ.get("HPNN_BENCH_NO_OBS_OVERHEAD"):
        try:
            out["sampler_overhead"] = bench_sampler_overhead()
        except Exception as exc:
            out["sampler_overhead_error"] = repr(exc)

    # online-blame overhead: the same paired shape on the SERVE hot
    # path with the sampler armed in both legs, HPNN_BLAME=1 in one
    # (docs/selftuning.md) — rides the same skip knob, best-effort
    if not os.environ.get("HPNN_BENCH_NO_OBS_OVERHEAD"):
        try:
            out["blame_overhead"] = bench_blame_overhead()
        except Exception as exc:
            out["blame_overhead_error"] = repr(exc)

    # drift-sketch overhead: the same paired shape on the serve +
    # ingest hot paths, HPNN_DRIFT=1 in one leg (docs/observability.md
    # "Drift detection") — rides the same skip knob, best-effort
    if not os.environ.get("HPNN_BENCH_NO_OBS_OVERHEAD"):
        try:
            out["drift_overhead"] = bench_drift_overhead()
        except Exception as exc:
            out["drift_overhead_error"] = repr(exc)

    # meter-sketch overhead: the same paired shape on the SERVE hot
    # path over tenant-scoped kernels, HPNN_METER=1 in one leg
    # (docs/observability.md "Tenant metering") — rides the same skip
    # knob, best-effort
    if not os.environ.get("HPNN_BENCH_NO_OBS_OVERHEAD"):
        try:
            out["meter_overhead"] = bench_meter_overhead()
        except Exception as exc:
            out["meter_overhead_error"] = repr(exc)

    # connection-plane overhead: the same paired shape over the HTTP
    # serve path, every HPNN_CONN_* guard armed in one leg
    # (docs/serving.md "Connection plane") — rides the same skip
    # knob, best-effort
    if not os.environ.get("HPNN_BENCH_NO_OBS_OVERHEAD"):
        try:
            out["conn_overhead"] = bench_conn_overhead()
        except Exception as exc:
            out["conn_overhead_error"] = repr(exc)

    # HPNN_METRICS: the bench subprocesses/rounds inherit the knob, so
    # the run's structured events land in the sink — record where, and
    # fold obs_report's machine summary in (best-effort: a torn sink
    # must not sink the benchmark figures)
    from hpnn_tpu import obs

    if obs.enabled():
        out["obs_metrics_file"] = obs.sink_path()
        obs.flush()
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import obs_report

            out["obs_summary"] = obs_report.summarize(
                obs_report.load_events(obs.sink_path()))
        except Exception as exc:
            out["obs_summary_error"] = repr(exc)

    # Fleet batching: aggregate samples/s of the 64-member HPNN-sized
    # fleet, one vmapped dispatch vs the sequential per-kernel loop —
    # best-effort like the other fold-ins.  HPNN_BENCH_NO_FLEET=1
    # skips it.
    if args.fleet or not os.environ.get("HPNN_BENCH_NO_FLEET"):
        try:
            out["fleet"] = bench_fleet()
        except Exception as exc:
            out["fleet_error"] = repr(exc)
    if args.fleet and "fleet" in out:
        # fleet-only run: rename the headline so the entry is honest
        # about what ran, but leave "value" unset — the MNIST
        # throughput and the fleet aggregate are not comparable under
        # one gate key (tools/bench_gate.py skips missing metrics)
        out["metric"] = "hpnn_fleet_agg_train_throughput"

    # Dispatch floor + low precision (docs/performance.md): the
    # K=32 multi-round scanned dispatch vs 32 single-round dispatches,
    # and the compiled engine's bf16 policy vs f32 with the measured
    # error bound — best-effort like the other fold-ins.
    # HPNN_BENCH_NO_QUANT=1 skips both.
    if not os.environ.get("HPNN_BENCH_NO_QUANT"):
        try:
            out["multiround"] = bench_multiround()
        except Exception as exc:
            out["multiround_error"] = repr(exc)
        try:
            out["serve_bf16"] = bench_serve_bf16()
        except Exception as exc:
            out["serve_bf16_error"] = repr(exc)

    # Serving smoke (tools/bench_serve.py --smoke): p50/p99 latency +
    # throughput of the resident serving stack on a tiny kernel —
    # best-effort like the obs fold-in (a serving hiccup must not sink
    # the training figures).  HPNN_BENCH_NO_SERVE=1 skips it.
    if not os.environ.get("HPNN_BENCH_NO_SERVE"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import bench_serve

            out["serve_smoke"] = bench_serve.run_smoke()
        except Exception as exc:
            out["serve_smoke_error"] = repr(exc)

    # Multi-replica scale-out (tools/bench_serve.py run_bench_replicas):
    # mixed-load goodput at 1/2/4 router replicas (head-of-line
    # isolation on CPU threads), the N-replica bitwise-parity proof,
    # and warm-vs-cold replica boot over the persistent compile cache
    # (docs/serving.md#scale-out).  HPNN_BENCH_NO_REPLICAS=1 skips it.
    if not os.environ.get("HPNN_BENCH_NO_REPLICAS"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import bench_serve

            out["replicas"] = bench_serve.run_bench_replicas()
        except Exception as exc:
            out["replicas_error"] = repr(exc)

    # Load + SLO (tools/loadgen.py run_bench_load): saturation probe,
    # then 2x-saturation open-loop against an SLO-armed shedding
    # server — records goodput vs the plateau and the windowed p99 of
    # accepted requests (docs/observability.md "SLOs and load").
    # Best-effort; HPNN_BENCH_NO_LOAD=1 skips it.
    if not os.environ.get("HPNN_BENCH_NO_LOAD"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import loadgen

            out["load"] = loadgen.run_bench_load()
        except Exception as exc:
            out["load_error"] = repr(exc)

    # Train-while-serve (tools/bench_online.py): idle-serve goodput
    # plateau vs goodput with the background trainer promoting
    # candidates in-process, plus promotion latency (docs/online.md).
    # Best-effort; HPNN_BENCH_NO_ONLINE=1 skips it.
    if not os.environ.get("HPNN_BENCH_NO_ONLINE"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import bench_online

            out["online"] = bench_online.run_bench_online()
        except Exception as exc:
            out["online_error"] = repr(exc)

    # Chaos drill (tools/chaos_drill.py run_bench_drill): SIGKILL a
    # live online_nn child mid-traffic after a WAL-committed
    # promotion, restart, and record recovery time / goodput dip /
    # lost requests + the bitwise-restore verdict (docs/resilience.md).
    # Spawns subprocesses and takes ~30 s — HPNN_BENCH_NO_DRILL=1
    # skips it; best-effort like the other fold-ins.
    if not os.environ.get("HPNN_BENCH_NO_DRILL"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import chaos_drill

            out["drill"] = chaos_drill.run_bench_drill()
        except Exception as exc:
            out["drill_error"] = repr(exc)

    # Replica chaos drill (tools/chaos_drill.py run_bench_replica_drill):
    # kill one router replica of 3 under open-loop load, prove the
    # router sheds around it — bounded goodput dip, zero lost requests
    # after the kill lands on survivors (docs/resilience.md).  Rides
    # the same HPNN_BENCH_NO_DRILL knob (in-process, a few seconds).
    if not os.environ.get("HPNN_BENCH_NO_DRILL"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import chaos_drill

            out["replica_drill"] = chaos_drill.run_bench_replica_drill()
        except Exception as exc:
            out["replica_drill_error"] = repr(exc)

    # Alert drill (tools/chaos_drill.py run_bench_alert_drill): kill a
    # router replica under load with a threshold rule armed on the
    # router.ready_replicas gauge, prove alert.fire (flight dump
    # attached) then alert.resolve after the respawn
    # (docs/observability.md "Fleet telemetry").  Rides the same
    # HPNN_BENCH_NO_DRILL knob (in-process, a few seconds).
    if not os.environ.get("HPNN_BENCH_NO_DRILL"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import chaos_drill

            out["alert_drill"] = chaos_drill.run_bench_alert_drill()
        except Exception as exc:
            out["alert_drill_error"] = repr(exc)

    # Worker chaos drill (tools/chaos_drill.py run_bench_worker_drill):
    # SIGKILL one of two WorkerSupervisor-managed worker PROCESSES
    # behind the ClusterRouter under load — bounded dip, zero survivor
    # losses, bitwise survivor answers, and a bounded replacement
    # latency (docs/resilience.md).  Rides the same HPNN_BENCH_NO_DRILL
    # knob (spawns subprocesses, ~15 s).
    if not os.environ.get("HPNN_BENCH_NO_DRILL"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import chaos_drill

            out["worker_drill"] = chaos_drill.run_bench_worker_drill()
        except Exception as exc:
            out["worker_drill_error"] = repr(exc)

    # Capsule drill (tools/chaos_drill.py run_bench_capsule_drill):
    # inject a deterministic delay at the serve.dispatch seam under
    # sampled load with an slo.p99_ms alert armed, prove the alert
    # fires, the capture capsule lands (spans + profiler window), and
    # tools/tail_report.py blames the dispatch phase for the tail
    # (docs/observability.md "Tail-latency forensics").  Rides the
    # same HPNN_BENCH_NO_DRILL knob (in-process, a few seconds).
    if not os.environ.get("HPNN_BENCH_NO_DRILL"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import chaos_drill

            out["capsule_drill"] = chaos_drill.run_bench_capsule_drill()
        except Exception as exc:
            out["capsule_drill_error"] = repr(exc)

    # Drift drill (tools/chaos_drill.py run_bench_drift_drill): learn
    # a clean label-shifted-MNIST stream, arm the sketches on the
    # converged plateau, shift the labels under live load, and prove
    # the sentinel breaches, the drift alert fires, and the capture
    # capsule lands with drift.json — while serving keeps answering
    # (docs/observability.md "Drift detection").  Rides the same
    # HPNN_BENCH_NO_DRILL knob (in-process, tens of seconds).
    if not os.environ.get("HPNN_BENCH_NO_DRILL"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import chaos_drill

            out["drift_drill"] = chaos_drill.run_bench_drift_drill()
        except Exception as exc:
            out["drift_drill_error"] = repr(exc)

    # Autoscale ramp (tools/bench_autoscale.py): a loadgen ramp past
    # the single-worker plateau that the SLO-driven autoscaler rides —
    # width 1→N under overdrive, windowed goodput vs the plateau,
    # bounded p99, width back to 1 after the ramp (docs/serving.md
    # "Cross-host fleet").  HPNN_BENCH_NO_AUTOSCALE=1 skips it (spawns
    # worker subprocesses, ~30 s).
    if not os.environ.get("HPNN_BENCH_NO_AUTOSCALE"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import bench_autoscale

            out["autoscale"] = bench_autoscale.run_bench_autoscale()
        except Exception as exc:
            out["autoscale_error"] = repr(exc)

    # Multi-tenant hosting (tools/loadgen.py run_bench_tenant): one
    # TenantSession hosting 10k kernels across 8 tenants under a
    # 256-kernel LRU paging cap, driven by Zipf traffic — registration
    # throughput at scale, bounded RSS growth, measured cold-hit
    # paging p99, goodput, and the quota-shed census of the hottest
    # tenant (docs/tenancy.md).  HPNN_BENCH_NO_TENANT=1 skips it
    # (in-process, ~15 s).
    if not os.environ.get("HPNN_BENCH_NO_TENANT"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import loadgen

            out["tenant"] = loadgen.run_bench_tenant()
        except Exception as exc:
            out["tenant_error"] = repr(exc)

    # Quota drill (tools/chaos_drill.py run_bench_quota_drill): a
    # hostile tenant offers 10x its admission budget against a shared
    # TenantSession while well-behaved tenants keep their traffic —
    # prove the victims' goodput and p99 hold, every refusal is a
    # clean `shed reason=quota` on the offender, and the per-tenant
    # shed-rate alert fires (docs/tenancy.md).  Rides the same
    # HPNN_BENCH_NO_DRILL knob (in-process, a few seconds).
    if not os.environ.get("HPNN_BENCH_NO_DRILL"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import chaos_drill

            out["quota_drill"] = chaos_drill.run_bench_quota_drill()
        except Exception as exc:
            out["quota_drill_error"] = repr(exc)

    # Hog drill (tools/chaos_drill.py run_bench_hog_drill): one tenant
    # offers 20x the zipf head's rate under an armed meter — prove the
    # fleet-merged top-K names the hog within a bounded window,
    # tenant_report blames it for the majority of device-seconds, the
    # shed-rate alert fires, and the capsule carries meter.json
    # (docs/observability.md "Tenant metering").  Rides the same
    # HPNN_BENCH_NO_DRILL knob (in-process, a few seconds).
    if not os.environ.get("HPNN_BENCH_NO_DRILL"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import chaos_drill

            out["hog_drill"] = chaos_drill.run_bench_hog_drill()
        except Exception as exc:
            out["hog_drill_error"] = repr(exc)

    # Tune drill (tools/chaos_drill.py run_bench_tune_drill): per
    # blame class, a dominant synthetic window must move the matching
    # knob through the real actuators, recover through the watch, and
    # roll two bad moves back bitwise (docs/selftuning.md).  Rides
    # the same HPNN_BENCH_NO_DRILL knob (in-process, deterministic).
    if not os.environ.get("HPNN_BENCH_NO_DRILL"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import chaos_drill

            out["tune_drill"] = chaos_drill.run_bench_tune_drill()
        except Exception as exc:
            out["tune_drill_error"] = repr(exc)

    # Torn-network drill (tools/chaos_drill.py run_bench_torn_drill):
    # slowloris/torn-body/fuzz clients attack a conn-guarded server
    # while clean traffic flows — prove the guards kill the attackers,
    # account every hostile close, fire the alert and capsule, and
    # keep clean goodput intact (docs/resilience.md).  Rides the same
    # HPNN_BENCH_NO_DRILL knob (in-process, a few seconds).
    if not os.environ.get("HPNN_BENCH_NO_DRILL"):
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import chaos_drill

            out["torn_drill"] = chaos_drill.run_bench_torn_drill()
        except Exception as exc:
            out["torn_drill_error"] = repr(exc)

    # The driver records only a ~4 kB tail of stdout (BENCH_r04.json
    # lost its headline to exactly this): the full detail goes to a
    # file, stdout ends with ONE compact line that always fits.
    detail_path = os.environ.get("HPNN_BENCH_DETAIL", "bench_detail.json")
    try:
        with open(detail_path, "w") as fp:
            json.dump(out, fp, indent=1)
    except OSError as exc:
        # never lose the measurements to an unwritable CWD: the
        # compact line below still prints
        print(f"bench: can't write {detail_path}: {exc}", file=sys.stderr)
        detail_path = None
    compact = {
        "metric": out["metric"],
        "value": out.get("value"),
        "unit": out["unit"],
        "vs_baseline": out.get("vs_baseline"),
        "baseline_samples_per_s": out["baseline_samples_per_s"],
        "baseline_source": out["baseline_source"],
    }
    if "per_sample" in out:
        compact["per_sample_dispatch_sps"] = (
            out["per_sample"]["per_sample_dispatch"]["samples_per_s"]["median"]
        )
        compact["fused_total_inner_iters"] = out["per_sample"]["total_inner_iters"]
        compact["epoch_body"] = out["per_sample"]["epoch_body"]
        if "paired_pallas_epoch_vs_lax_pct" in out["per_sample"]:
            compact["paired_pallas_epoch_vs_lax_pct"] = (
                out["per_sample"]["paired_pallas_epoch_vs_lax_pct"]["median"]
            )
    if "batch" in out:
        b = out["batch"]
        compact["batch_sps_median"] = b["samples_per_s"]["median"]
        compact["batch_vs_baseline"] = out["batch_vs_baseline"]
        compact["slope_us_per_step"] = {
            k: v["median_us"] for k, v in b["slope"].items()
            if isinstance(v, dict) and "median_us" in v
        }
        for tag, v in b["slope"].items():
            if isinstance(v, dict) and "median" in v and "median_us" not in v:
                compact[tag] = v["median"]
        if "prod_slope_60k_bank" in b:
            compact["prod_us_per_step"] = {
                k: v["us_per_step_median"]
                for k, v in b["prod_slope_60k_bank"].items()
            }
    if "fleet" in out:
        fl = out["fleet"]
        compact["fleet_members"] = fl["members"]
        compact["fleet_agg_sps"] = fl["fleet_agg_sps"]["median"]
        compact["fleet_speedup_x"] = fl["paired_speedup_x"]["median"]
    if "multiround" in out:
        mr = out["multiround"]
        compact["multiround_effective_us_per_step"] = (
            mr["effective_us_per_step"]["median"])
        compact["multiround_amortization_x"] = (
            mr["paired_amortization_x"]["median"])
    if "serve_bf16" in out:
        sb = out["serve_bf16"]
        compact["serve_bf16_goodput_vs_f32"] = (
            sb["goodput_vs_f32"]["median"])
        compact["serve_bf16_max_abs_err"] = sb["max_abs_err"]
    if "serve_smoke" in out:
        sm = out["serve_smoke"]
        compact["serve_p50_ms"] = sm["latency_ms"]["p50"]
        compact["serve_p99_ms"] = sm["latency_ms"]["p99"]
        compact["serve_rps"] = sm["throughput_rps"]
    if "load" in out:
        ld = out["load"]
        compact["load_goodput_rps"] = ld["goodput_rps"]
        compact["load_p99_ms"] = ld["p99_under_load_ms"]
        compact["load_goodput_vs_saturation"] = (
            ld["goodput_vs_saturation"])
    if "online" in out:
        on = out["online"]
        compact["online_goodput_rps"] = on["online_goodput_rps"]
        compact["online_goodput_vs_idle"] = (
            on["online_goodput_vs_idle"])
        compact["online_promotions"] = on["promotions"]
        compact["online_promote_latency_ms"] = (
            on["promote_latency_ms"])
    if "replicas" in out and "goodput" in out["replicas"]:
        rp = out["replicas"]
        compact["replica_goodput_rps"] = {
            k: v["rps"] for k, v in rp["goodput"].items()}
        compact["replica_scaling_x2"] = rp["scaling_x"].get("r2")
        compact["replica_parity_ok"] = rp["parity"]["ok"]
        wb = rp["warm_boot"]
        compact["replica_warm_hit_rate"] = wb["warm"]["hit_rate"]
        compact["replica_warm_ready_s"] = wb["warm"]["ready_s"]
        compact["replica_warm_speedup_x"] = wb["speedup_x"]
    if "drill" in out and out["drill"].get("recovery_s") is not None:
        dr = out["drill"]
        compact["drill_recovery_s"] = dr["recovery_s"]
        compact["drill_goodput_dip_pct"] = dr["goodput_dip_pct"]
        compact["drill_lost_requests"] = dr["lost_requests"]
        compact["drill_restored_bitwise"] = dr["restored_bitwise"]
    if ("replica_drill" in out
            and out["replica_drill"].get("goodput_dip_pct") is not None):
        rd = out["replica_drill"]
        compact["drill_replica_dip_pct"] = rd["goodput_dip_pct"]
        compact["drill_replica_survivors_lost"] = rd["survivors_lost"]
    if ("alert_drill" in out
            and out["alert_drill"].get("fire_s") is not None):
        ad = out["alert_drill"]
        compact["drill_alert_fire_s"] = ad["fire_s"]
        compact["drill_alert_resolved"] = ad["resolved"]
    if ("worker_drill" in out
            and out["worker_drill"].get("replaced_s") is not None):
        wd = out["worker_drill"]
        compact["drill_worker_dip_pct"] = wd["goodput_dip_pct"]
        compact["drill_worker_replaced_s"] = wd["replaced_s"]
    if ("capsule_drill" in out
            and out["capsule_drill"].get("capture_s") is not None):
        cd = out["capsule_drill"]
        compact["drill_capsule_capture_s"] = cd["capture_s"]
        compact["drill_capsule_blame_pct"] = cd["dispatch_blame_pct"]
    if ("drift_drill" in out
            and out["drift_drill"].get("detect_s") is not None):
        dd = out["drift_drill"]
        compact["drill_drift_detect_s"] = dd["detect_s"]
        compact["drill_drift_rounds"] = dd["rounds_to_detect"]
        compact["drill_drift_lost"] = dd["lost"]
    if "tenant" in out:
        tn = out["tenant"]
        compact["tenant_register_krps"] = tn["register_krps"]
        compact["tenant_rss_growth_mb"] = tn["rss_growth_mb"]
        compact["tenant_cold_p99_ms"] = tn["cold_p99_ms"]
        compact["tenant_goodput_rps"] = tn["goodput_rps"]
        compact["tenant_resident_cap_ok"] = tn["resident_cap_ok"]
        compact["tenant_quota_shed"] = tn["quota_shed"]
    if ("quota_drill" in out
            and out["quota_drill"].get("victim_p99_ms") is not None):
        qd = out["quota_drill"]
        compact["drill_quota_victim_p99_ms"] = qd["victim_p99_ms"]
        compact["drill_quota_victim_goodput_ratio"] = (
            qd["victim_goodput_ratio"])
        compact["drill_quota_offender_shed"] = qd["offender_shed"]
        compact["drill_quota_alert_fired"] = qd["alert_fired"]
    if ("hog_drill" in out
            and out["hog_drill"].get("blame_pct") is not None):
        hd = out["hog_drill"]
        compact["drill_hog_blame_pct"] = hd["blame_pct"]
        compact["drill_hog_detect_s"] = hd["detect_s"]
        compact["drill_hog_alert_fired"] = hd["alert_fired"]
    if ("tune_drill" in out
            and out["tune_drill"].get("applies") is not None):
        td = out["tune_drill"]
        compact["drill_tune_applies"] = td["applies"]
        compact["drill_tune_rollback_bitwise"] = td["rollback_bitwise"]
    if ("torn_drill" in out
            and out["torn_drill"].get("dip_pct") is not None):
        tn = out["torn_drill"]
        compact["drill_torn_dip_pct"] = tn["dip_pct"]
        compact["drill_torn_clean_lost"] = tn["clean_lost"]
    if ("autoscale" in out
            and out["autoscale"].get("goodput_x") is not None):
        asc = out["autoscale"]
        compact["autoscale_goodput_x"] = asc["goodput_x"]
        compact["autoscale_p99_ms"] = asc["p99_ms"]
        compact["autoscale_settle_s"] = asc["settle_s"]
        compact["autoscale_scaled_to"] = asc["scaled_to"]
    if "obs_overhead" in out:
        compact["obs_overhead_pct"] = (
            out["obs_overhead"]["paired_overhead_pct"]["median"]
        )
    if "collector_overhead" in out:
        compact["collector_overhead_pct"] = (
            out["collector_overhead"]["paired_overhead_pct"]["median"]
        )
    if "sampler_overhead" in out:
        compact["sampler_overhead_pct"] = (
            out["sampler_overhead"]["paired_overhead_pct"]["median"]
        )
    if "blame_overhead" in out:
        compact["blame_overhead_pct"] = (
            out["blame_overhead"]["paired_overhead_pct"]["median"]
        )
    if "drift_overhead" in out:
        compact["drift_overhead_pct"] = (
            out["drift_overhead"]["paired_overhead_pct"]["median"]
        )
    if "meter_overhead" in out:
        compact["meter_overhead_pct"] = (
            out["meter_overhead"]["paired_overhead_pct"]["median"]
        )
    if "conn_overhead" in out:
        compact["conn_overhead_pct"] = (
            out["conn_overhead"]["paired_overhead_pct"]["median"]
        )
    compact["detail_file"] = detail_path
    if "obs_metrics_file" in out:
        compact["obs_metrics_file"] = out["obs_metrics_file"]

    # Trajectory file for tools/bench_gate.py: one line per run, the
    # compact summary stamped with when/what ran.  Append-only JSONL so
    # a torn write can only cost its own line; best-effort like every
    # other side channel here.  HPNN_BENCH_HISTORY= (empty) disables.
    history_path = os.environ.get("HPNN_BENCH_HISTORY",
                                  "bench_history.jsonl")
    if history_path:
        entry = dict(compact)
        entry["when"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
        try:
            sha = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            entry["git_sha"] = (sha.stdout.strip()
                                if sha.returncode == 0 else None)
        except (OSError, subprocess.SubprocessError):
            entry["git_sha"] = None
        try:
            with open(history_path, "a") as fp:
                fp.write(json.dumps(entry) + "\n")
        except OSError as exc:
            print(f"bench: can't append {history_path}: {exc}",
                  file=sys.stderr)

    print(json.dumps(compact))


if __name__ == "__main__":
    main()
