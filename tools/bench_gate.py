#!/usr/bin/env python3
"""Bench regression gate over the ``bench_history.jsonl`` trajectory.

``bench.py`` appends its compact summary (plus git SHA + timestamp) to
``bench_history.jsonl`` on every run.  This tool compares a candidate
run against the trajectory and exits non-zero on regression, so CI can
gate a change on measured performance:

    python bench.py --quick | tail -1 > cand.json
    python tools/bench_gate.py --candidate cand.json

Candidate selection, in order: ``--candidate FILE`` (``-`` = stdin);
``--run`` (invoke a fresh ``bench.py`` — args after ``--`` pass
through — and take its final stdout line); else the LAST history line
(gating the most recent run against the ones before it).

Baseline: per metric, the median over the newest ``--window`` prior
entries that carry it (median, not last — one noisy run must not move
the bar).  A metric missing from the candidate or from every baseline
entry is skipped, not failed: bench sections are best-effort and a
skipped serve smoke must not fail the gate.

Tolerances are per-metric fractions of the baseline (see
``TOLERANCES``; ``--tolerance`` overrides all).  Direction is per
metric: throughputs regress downward, latencies/slopes upward.

Exit codes: 0 no regression, 1 regression(s) found, 2 usage/IO error.
stdlib-only so the gate runs anywhere the history file does.
"""

from __future__ import annotations

import argparse
import json
import statistics
import subprocess
import sys

# gate metric -> (direction, default tolerance fraction).
# "higher": regression when candidate < baseline * (1 - tol)
# "lower":  regression when candidate > baseline * (1 + tol)
# Nested dict metrics (variant -> number) are flattened to
# "metric.variant" and inherit the base metric's row.
GATE_METRICS = {
    "value": ("higher", 0.30),
    "batch_sps_median": ("higher", 0.30),
    "per_sample_dispatch_sps": ("higher", 0.30),
    "serve_rps": ("higher", 0.40),
    "fleet_agg_sps": ("higher", 0.40),
    "fleet_speedup_x": ("higher", 0.30),
    "slope_us_per_step": ("lower", 0.50),
    "prod_us_per_step": ("lower", 0.50),
    "serve_p50_ms": ("lower", 0.60),
    "serve_p99_ms": ("lower", 1.00),
    "obs_overhead_pct": ("lower", 2.00),
    # loadgen fold-in (tools/loadgen.py run_bench_load): goodput under
    # 2x-saturation offered load, the windowed p99 of accepted
    # requests, and how close the shed goodput held to the saturation
    # plateau — latency under load is a guarded surface now too
    "load_goodput_rps": ("higher", 0.40),
    "load_p99_ms": ("lower", 1.00),
    "load_goodput_vs_saturation": ("higher", 0.20),
    # train-while-serve fold-in (tools/bench_online.py): serving
    # goodput while the background trainer promotes candidates, the
    # fraction of the idle-serve plateau it holds, and how long a
    # gate-passed candidate takes to become resident (install +
    # bucket-menu warmup)
    "online_goodput_rps": ("higher", 0.40),
    "online_goodput_vs_idle": ("higher", 0.25),
    "online_promote_latency_ms": ("lower", 1.00),
    # chaos-drill fold-in (tools/chaos_drill.py run_bench_drill):
    # kill -9 a live online_nn child mid-traffic, restart, measure
    # the blast radius.  Recovery and dip are timing-noisy subprocess
    # measurements, so the tolerances are generous; lost counts
    # in-flight requests the kill destroyed (baseline 0 is skipped by
    # the gate's zero-baseline rule, so this arms once a baseline
    # run records any loss)
    "drill_recovery_s": ("lower", 1.50),
    "drill_goodput_dip_pct": ("lower", 1.00),
    "drill_lost_requests": ("lower", 2.00),
    # multi-replica scale-out fold-in (tools/bench_serve.py
    # run_bench_replicas): goodput scaling at 2 replicas vs 1 on the
    # mixed light/heavy load (head-of-line isolation — the acceptance
    # floor is 1.7x, the gate guards the measured trajectory), and the
    # warm persistent-compile-cache boot: hit rate against a warm
    # HPNN_COMPILE_CACHE_DIR and time-to-ready, both direction-aware
    "replica_scaling_x2": ("higher", 0.30),
    "replica_warm_hit_rate": ("higher", 0.50),
    "replica_warm_ready_s": ("lower", 1.00),
    "replica_warm_speedup_x": ("higher", 0.50),
    # replica chaos drill (tools/chaos_drill.py drill_replica): kill
    # one of N router replicas under load; the goodput dip must stay
    # bounded and survivors must lose nothing (survivors_lost rides
    # the zero-baseline skip rule like drill_lost_requests)
    "drill_replica_dip_pct": ("lower", 1.00),
    "drill_replica_survivors_lost": ("lower", 2.00),
    # dispatch-floor + low-precision fold-in (bench.py
    # bench_multiround / bench_serve_bf16; docs/performance.md): the
    # K=32 scanned dispatch's effective us/step (the amortized floor —
    # the acceptance bar is >=5x amortization, the gate guards the
    # measured trajectory), the paired bf16-vs-f32 serve goodput ratio
    # (near 1x on CPU where bf16 is emulated, so the tolerance is
    # wide), and the measured bf16 error bound, which must never
    # *grow* past its trajectory — the "measured, never assumed"
    # contract made regression-proof
    "multiround_effective_us_per_step": ("lower", 0.50),
    "multiround_amortization_x": ("higher", 0.30),
    "serve_bf16_goodput_vs_f32": ("higher", 0.30),
    "serve_bf16_max_abs_err": ("lower", 1.00),
    # fleet telemetry fold-in (bench.py bench_collector_overhead +
    # tools/chaos_drill.py run_bench_alert_drill;
    # docs/observability.md "Fleet telemetry"): the paired marginal
    # cost of a live collector + firing alert rule over the
    # sink-only round (acceptance bar <=5% — the gate guards the
    # measured trajectory; overhead medians hover near zero so the
    # tolerance is wide like obs_overhead_pct), time-to-fire of the
    # replica-down alert under load, and whether the drill's alert
    # resolved after the respawn (1.0/0.0 — any drop below a 1.0
    # baseline fails)
    "collector_overhead_pct": ("lower", 2.00),
    "drill_alert_fire_s": ("lower", 1.50),
    "drill_alert_resolved": ("higher", 0.01),
    # tail-latency forensics fold-in (bench.py bench_sampler_overhead
    # + tools/chaos_drill.py run_bench_capsule_drill;
    # docs/observability.md "Forensics"): the paired marginal cost of
    # the tail sampler at rate 1 on the serve hot path (acceptance
    # bar <=5% — medians hover near zero, so the tolerance is wide
    # like the other overhead gates), time from the alert trigger to
    # a landed capsule manifest, and the share of tail time
    # tail_report pins on the delayed dispatch seam (the drill
    # injects there, so the blame must not drift away from it)
    "sampler_overhead_pct": ("lower", 2.00),
    "drill_capsule_capture_s": ("lower", 1.50),
    "drill_capsule_blame_pct": ("higher", 0.30),
    # drift fold-ins (bench.py bench_drift_overhead +
    # tools/chaos_drill.py run_bench_drift_drill;
    # docs/observability.md "Drift detection"): the paired marginal
    # cost of armed sketches on the serve + ingest hot paths
    # (acceptance bar <=5% — medians hover near zero, so the
    # tolerance is wide like the other overhead gates), and time
    # from the label shift to the drift alert firing in the drill
    "drift_overhead_pct": ("lower", 2.00),
    "drill_drift_detect_s": ("lower", 1.50),
    # cross-host fleet fold-ins (tools/chaos_drill.py
    # run_bench_worker_drill + tools/bench_autoscale.py;
    # docs/serving.md "Cross-host fleet"): the worker-process kill
    # drill's goodput dip and supervisor replacement latency (both
    # subprocess-timing-noisy, so generous), and the autoscale ramp
    # ride — windowed goodput over the 1-worker plateau (the
    # acceptance floor is 1.5x, the gate guards the trajectory),
    # windowed p99 under the scaled fleet, and ramp-end → min-width
    # settle time
    "drill_worker_dip_pct": ("lower", 1.00),
    "drill_worker_replaced_s": ("lower", 1.50),
    "autoscale_goodput_x": ("higher", 0.30),
    "autoscale_p99_ms": ("lower", 1.00),
    "autoscale_settle_s": ("lower", 1.50),
    # multi-tenant hosting fold-ins (tools/loadgen.py
    # run_bench_tenant + tools/chaos_drill.py run_bench_quota_drill;
    # docs/tenancy.md): registration throughput at 10k-kernel scale,
    # RSS growth under the resident cap (the bounded-memory claim —
    # mostly allocator/import noise, so generous), the measured
    # cold-hit paging p99, goodput under Zipf traffic, and the quota
    # drill's victim-protection surfaces: the victims' p99 and their
    # goodput as a fraction of the undisturbed plateau while a
    # hostile tenant offers 10x its budget
    "tenant_register_krps": ("higher", 0.40),
    "tenant_rss_growth_mb": ("lower", 1.00),
    "tenant_cold_p99_ms": ("lower", 1.00),
    "tenant_goodput_rps": ("higher", 0.40),
    "drill_quota_victim_p99_ms": ("lower", 1.50),
    "drill_quota_victim_goodput_ratio": ("higher", 0.30),
    # tenant metering fold-ins (bench.py bench_meter_overhead +
    # tools/chaos_drill.py run_bench_hog_drill;
    # docs/observability.md "Tenant metering"): the paired marginal
    # cost of armed per-tenant sketches on the serve hot path
    # (acceptance bar <=5% — medians hover near zero, so the
    # tolerance is wide like the other overhead gates), the share of
    # fleet device-seconds tenant_report blames on the drill's 20x
    # hog (the attribution must keep naming the offender — the
    # acceptance floor is 50%, the gate guards the trajectory), and
    # how long the fleet-merged top-K takes to name it
    "meter_overhead_pct": ("lower", 2.00),
    "drill_hog_blame_pct": ("higher", 0.30),
    "drill_hog_detect_s": ("lower", 1.50),
    # self-tuning fold-ins (bench.py bench_blame_overhead +
    # tools/chaos_drill.py run_bench_tune_drill; docs/selftuning.md):
    # the paired marginal cost of the online blame classifier over a
    # sampler-armed serve hot path (acceptance bar <=5% — medians
    # hover near zero, so the tolerance is wide like the other
    # overhead gates), the fraction of blame classes whose dominant
    # window moved the MATCHING knob (acceptance floor 1.0 — every
    # class must map to its remediation), and whether both deliberate
    # bad moves restored the displaced config bitwise
    "blame_overhead_pct": ("lower", 2.00),
    "drill_tune_applies": ("higher", 0.01),
    "drill_tune_rollback_bitwise": ("higher", 0.01),
    # connection-plane fold-ins (bench.py bench_conn_overhead +
    # tools/chaos_drill.py run_bench_torn_drill; docs/serving.md
    # "Connection plane", docs/resilience.md): the paired marginal
    # cost of the armed socket guards on the HTTP serve path
    # (acceptance bar <=5% — medians hover near zero, so the
    # tolerance is wide like the other overhead gates), the clean
    # traffic's goodput dip while hostile clients attack (acceptance
    # ceiling 10%), and how many clean requests were LOST outright
    # (acceptance is zero; the wide tolerance only tolerates noise
    # around an already-zero baseline)
    "conn_overhead_pct": ("lower", 2.00),
    "drill_torn_dip_pct": ("lower", 1.00),
    "drill_torn_clean_lost": ("lower", 2.00),
}


def flatten(entry: dict) -> dict[str, float]:
    """Project one compact-summary dict onto the gate metrics,
    flattening nested variant dicts to ``metric.variant``."""
    flat: dict[str, float] = {}
    for key in GATE_METRICS:
        v = entry.get(key)
        if isinstance(v, dict):
            for sub, val in sorted(v.items()):
                if isinstance(val, (int, float)):
                    flat[f"{key}.{sub}"] = float(val)
        elif isinstance(v, (int, float)):
            flat[key] = float(v)
    return flat


def _rule(metric: str) -> tuple[str, float]:
    base = metric.split(".", 1)[0]
    return GATE_METRICS[base]


def load_history(path: str) -> list[dict]:
    entries = []
    with open(path) as fp:
        for line in fp:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line
            if isinstance(rec, dict):
                entries.append(rec)
    return entries


def baseline(history: list[dict], window: int) -> dict[str, float]:
    """Per-metric median over the newest ``window`` entries that
    carry the metric."""
    flats = [flatten(e) for e in history]
    out: dict[str, float] = {}
    names = {name for f in flats for name in f}
    for name in names:
        vals = [f[name] for f in flats if name in f][-window:]
        if vals:
            out[name] = statistics.median(vals)
    return out


def gate(cand: dict[str, float], base: dict[str, float],
         tolerance: float | None = None) -> list[dict]:
    """Compare candidate metrics against the baseline; returns the
    regression list (empty = pass)."""
    regressions = []
    for name, cval in sorted(cand.items()):
        bval = base.get(name)
        if bval is None or bval == 0:
            continue
        direction, tol = _rule(name)
        if tolerance is not None:
            tol = tolerance
        if direction == "higher":
            bad = cval < bval * (1.0 - tol)
        else:
            bad = cval > bval * (1.0 + tol)
        if bad:
            regressions.append({
                "metric": name, "candidate": cval, "baseline": bval,
                "direction": direction, "tolerance": tol,
                "ratio": cval / bval,
            })
    return regressions


def _read_candidate(args) -> dict | None:
    if args.run:
        cmd = [sys.executable, args.bench] + args.bench_args
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            sys.stderr.write("bench_gate: bench run failed:\n"
                             + proc.stderr[-2000:] + "\n")
            return None
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        if not lines:
            sys.stderr.write("bench_gate: bench produced no output\n")
            return None
        try:
            return json.loads(lines[-1])
        except json.JSONDecodeError:
            sys.stderr.write("bench_gate: bench's last stdout line "
                             "is not the compact JSON summary\n")
            return None
    if args.candidate:
        try:
            if args.candidate == "-":
                return json.loads(sys.stdin.read())
            with open(args.candidate) as fp:
                return json.load(fp)
        except (OSError, json.JSONDecodeError) as exc:
            sys.stderr.write(f"bench_gate: candidate: {exc}\n")
            return None
    return {}  # sentinel: take the last history entry


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Gate a bench run against bench_history.jsonl")
    ap.add_argument("--history", default="bench_history.jsonl",
                    help="trajectory JSONL (default "
                         "bench_history.jsonl)")
    ap.add_argument("--candidate", metavar="FILE",
                    help="candidate compact summary JSON "
                         "('-' = stdin); default: last history line")
    ap.add_argument("--run", action="store_true",
                    help="run a fresh bench.py as the candidate "
                         "(args after -- pass through)")
    ap.add_argument("--bench", default="bench.py",
                    help="bench script for --run (default bench.py)")
    ap.add_argument("--window", type=int, default=5,
                    help="baseline = per-metric median over the "
                         "newest N prior entries (default 5)")
    ap.add_argument("--tolerance", type=float, default=None,
                    metavar="FRAC",
                    help="override every per-metric tolerance with "
                         "one fraction (e.g. 0.3)")
    ap.add_argument("--json", action="store_true",
                    help="print the verdict as JSON")
    if argv is None:
        argv = sys.argv[1:]
    if "--" in argv:
        split = argv.index("--")
        argv, bench_args = argv[:split], argv[split + 1:]
    else:
        bench_args = []
    args = ap.parse_args(argv)
    args.bench_args = bench_args

    try:
        history = load_history(args.history)
    except OSError as exc:
        sys.stderr.write(f"bench_gate: history: {exc}\n")
        return 2
    cand_entry = _read_candidate(args)
    if cand_entry is None:
        return 2
    if not cand_entry:  # default: last history line vs the rest
        if not history:
            sys.stderr.write("bench_gate: empty history and no "
                             "candidate\n")
            return 2
        cand_entry, history = history[-1], history[:-1]
    if not history:
        sys.stderr.write("bench_gate: no baseline entries — nothing "
                         "to gate against (pass)\n")
        return 0

    cand = flatten(cand_entry)
    base = baseline(history, args.window)
    regressions = gate(cand, base, tolerance=args.tolerance)
    verdict = {
        "pass": not regressions,
        "baseline_entries": len(history),
        "metrics_compared": sorted(set(cand) & set(base)),
        "regressions": regressions,
    }
    if args.json:
        json.dump(verdict, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        n = len(verdict["metrics_compared"])
        if regressions:
            sys.stdout.write(f"bench_gate: FAIL — "
                             f"{len(regressions)} regression(s) over "
                             f"{n} compared metric(s)\n")
            for r in regressions:
                arrow = ("below" if r["direction"] == "higher"
                         else "above")
                sys.stdout.write(
                    f"  {r['metric']}: {r['candidate']:.6g} vs "
                    f"baseline {r['baseline']:.6g} "
                    f"({r['ratio']:.2f}x, {arrow} "
                    f"{r['tolerance']:.0%} tolerance)\n")
        else:
            sys.stdout.write(f"bench_gate: PASS — {n} metric(s) "
                             f"within tolerance\n")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
