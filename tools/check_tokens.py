#!/usr/bin/env python3
"""Byte-stability lint for the stdout token protocol.

The ``NN: `` / ``NN(WARN): `` / ``NN(ERR): `` / ``#DBG: acc[`` token
lines are the reference's de-facto metrics API — tutorial monitors grep
them, so the structured obs subsystem (``hpnn_tpu/obs/``) must never
perturb them.  This lint proves it the direct way: it runs the same
tiny train+eval round TWICE in-process — once with ``HPNN_METRICS``
unset, once with it pointed at a JSONL sink — and asserts

1. the two stdout captures are **byte-identical**,
2. the token lines match the golden shapes (``TRAINING FILE``,
   ``init=``/``end=``/``iter=``, ``TESTING FILE``, PASS/FAIL verdicts),
3. no line smells of JSON or obs vocabulary (the sink never leaks),
4. the instrumented run's sink is non-empty and carries the tentpole
   events (dispatch timer, chunk gauge, n_iter histogram, round
   events, device telemetry).

The instrumented run enables the whole surface at once — JSONL sink,
flight-recorder ring (``HPNN_FLIGHT``), device telemetry, numerics
probes + sentinel + checksum ledger (``HPNN_PROBES`` /
``HPNN_NUMERICS`` / ``HPNN_LEDGER``), lifecycle spans + compiled-cost
attribution (``HPNN_SPANS`` / ``HPNN_COST``), the SLO tracker
(``HPNN_SLO_MS`` — load shedding is additionally exercised to an
actual Shed rejection in the serve section below, and the serve
section also routes a 2-replica Router round trip with the
persistent compile cache armed, ``HPNN_COMPILE_CACHE_DIR``, and a
2-worker cross-host ``ClusterRouter`` round trip over real HTTP —
fan-out infers plus a fenced ``CheckpointPublisher`` install re-read
by both workers over ``/v1/reload``), the whole
``HPNN_ONLINE_*`` train-while-serve knob family (inert outside
``hpnn_tpu/online/``; a full feed → train → gate → rollback round is
additionally exercised to silence below), the chaos + durability
knobs (``HPNN_CHAOS`` / ``HPNN_CHAOS_SEED`` / ``HPNN_WAL_DIR``,
docs/resilience.md — the train path carries no injection seams and
never touches the WAL, so an armed plan must stay inert here), the
fleet telemetry plane (``HPNN_COLLECTOR`` pointed at a LIVE
in-process collector on an ephemeral port, plus an ``HPNN_ALERTS``
rule that actually fires on the round's own ``fuse.chunk_size``
gauge — docs/observability.md "Fleet telemetry"), the tail-latency
forensics plane (``HPNN_SAMPLE`` at rate 1 plus ``HPNN_CAPSULE_DIR``
— the firing alert must pull the capture trigger and land a capsule
manifest, while stdout stays frozen), the drift-detection plane
(``HPNN_DRIFT`` — its taps live in online ingest, serve dispatch, and
the online trainer's holdout evals, none on the train path, so armed
sketches must stay inert here), the online blame engine + the
self-tuning remediation plane (``HPNN_BLAME`` / ``HPNN_TUNE``,
docs/selftuning.md — blame taps the forensics sampler's request
roots and the tuner rides serve ``Session`` construction, neither of
which a plain train round touches, so armed they must stay inert
here), and a
live export server whose
``/metrics`` endpoint is scraped inside the capture window — so
"byte-frozen" is proven against the maximal configuration, not the
minimal one.  The collector must come out the other end having
actually received the pushed records — silence alone would also be
the signature of a dead push path.  A final ledger-only run proves the probes are
zero-perturbation: its checksum ledger must equal the probed run's
row for row (equal abs-sums on the f64 CPU parity path mean equal
weights — enabling probes did not move the trajectory).

Run standalone (exit code for CI)::

    JAX_PLATFORMS=cpu python tools/check_tokens.py

or via the tier-1 suite (tests/test_check_tokens.py).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import re
import sys
import tempfile
import threading
import time

TOKEN_PREFIXES = ("NN: ", "NN(WARN): ", "NN(ERR): ", "NN(DBG): ",
                  "#DBG: acc[")

# every stdout line of a -vv ANN train+eval round must match one of
# these (ref token formats: driver._print_train_tokens/print_verdict)
GOLDEN = [
    re.compile(r"^NN: TRAINING FILE: .{1,16}\t"
               r" init= *[0-9.+-]+ (OK|NO) N_ITER= *\d+"
               r" final= *[0-9.+-]+( (SUCCESS!|FAIL!))?$"),
    re.compile(r"^NN: TESTING FILE: .{1,16}\t"
               r"( BEST CLASS idx=\d+ P= *[0-9.+-]+)?"
               r" \[(PASS|FAIL( idx=\d+)?)\]$"),
    re.compile(r"^NN\((WARN|ERR|DBG)\): .*$"),
    re.compile(r"^#DBG: acc\[.+\]=[0-9.]+$"),
    re.compile(r"^$"),
]


def _tiny_conf(tmpdir: str):
    """A 6-sample 8->5->2 ANN BP round (the test_trace.py shape)."""
    import numpy as np

    from hpnn_tpu.config import NNConf, NNTrain, NNType
    from hpnn_tpu.models import kernel as kernel_mod

    rng = np.random.RandomState(0)
    sdir = os.path.join(tmpdir, "samples")
    os.makedirs(sdir, exist_ok=True)
    for i in range(6):
        c = i % 2
        x = (1 - 2 * c) * np.r_[np.ones(4), -np.ones(4)] \
            + 0.1 * rng.normal(size=8)
        t = np.full(2, -1.0)
        t[c] = 1.0
        with open(os.path.join(sdir, f"s{i:05d}.txt"), "w") as fp:
            fp.write("[input] 8\n"
                     + " ".join(f"{v:.5f}" for v in x) + "\n")
            fp.write("[output] 2\n"
                     + " ".join(f"{v:.1f}" for v in t) + "\n")
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    return NNConf(name="t", type=NNType.ANN, seed=1, kernel=k,
                  train=NNTrain.BP, samples=sdir, tests=sdir)


def _run_round(tmpdir: str, metrics_path: str | None,
               probe=None) -> str:
    """One train+eval round, stdout captured; returns the capture.

    ``probe`` (optional) runs after the round while stdout is still
    redirected and the obs state is still live — the hook the export
    check uses to scrape /metrics inside the capture window."""
    from hpnn_tpu import obs
    from hpnn_tpu.train import driver
    from hpnn_tpu.utils import logging as log

    obs.configure(metrics_path)  # sets/clears HPNN_METRICS + memo
    conf = _tiny_conf(tmpdir)
    log.set_verbose(2)
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            if not driver.train_kernel(conf):
                raise RuntimeError("train_kernel failed")
            driver.run_kernel(conf)
            if probe is not None:
                probe()
    finally:
        log.set_verbose(0)
        obs.configure(None)
    return buf.getvalue()


def check(tmpdir: str) -> list[str]:
    """Run the lint; returns a list of failure strings (empty = pass)."""
    failures = []
    sink = os.path.join(tmpdir, "obs.jsonl")
    plain = _run_round(os.path.join(tmpdir, "a"), None)

    # the instrumented run turns EVERYTHING on at once: the JSONL sink,
    # the flight recorder ring, the device-telemetry samples (they ride
    # obs.enabled()), and a live export server scraped mid-capture —
    # stdout must still not move by a byte
    scraped = {}

    def probe():
        from urllib.request import urlopen

        from hpnn_tpu.obs import export

        server = export.start_export_server(port=0)
        try:
            port = server.server_address[1]
            scraped["metrics"] = urlopen(
                f"http://127.0.0.1:{port}/metrics",
                timeout=10).read().decode()
        finally:
            export.stop_export_server(server)

    # the HPNN_ONLINE_* family (docs/online.md) is read only inside
    # hpnn_tpu/online/ — setting it during a plain train+eval round
    # must be inert: not a byte, not an event
    _ONLINE_KNOBS = (("HPNN_ONLINE_BUFFER", "64"),
                     ("HPNN_ONLINE_RESERVOIR", "8"),
                     ("HPNN_ONLINE_HOLDOUT", "4"),
                     ("HPNN_ONLINE_ROWS", "16"),
                     ("HPNN_ONLINE_BATCH", "4"),
                     ("HPNN_ONLINE_EPOCHS", "2"),
                     ("HPNN_ONLINE_INTERVAL_S", "60"),
                     ("HPNN_ONLINE_MARGIN", "0.0"),
                     ("HPNN_ONLINE_WATCH_S", "5"),
                     # K-rounds-per-dispatch (docs/performance.md):
                     # read only by the online trainer, so arming it
                     # during a plain round must be inert
                     ("HPNN_ONLINE_SCAN_K", "4"),
                     # low-precision serve policy (docs/performance.md)
                     # — read only by serve.Engine at construction;
                     # rides the HPNN_ONLINE_* inertness proof and the
                     # ledger run-pair below proves the bf16 knob is
                     # zero-perturbation on the train path when armed
                     ("HPNN_SERVE_DTYPE", "bf16"))
    # chaos + durability (docs/resilience.md) ride the same proof: an
    # ARMED plan whose seams never trigger on the train path (the
    # delay fault targets a real serve seam; the train round never
    # dispatches through it) plus a live WAL dir the round never
    # commits to — not a byte, not a file
    from hpnn_tpu import chaos as chaos_mod
    from hpnn_tpu.online import wal as wal_mod

    # the fleet telemetry plane rides the same proof, LIVE: a real
    # collector on an ephemeral port with the push client armed at an
    # aggressive flush cadence, plus an alert rule that actually fires
    # on the round's own fuse.chunk_size gauge (flight dump attached) —
    # none of it may move stdout by a byte, and the collector must
    # come out the other end having actually received the records
    from hpnn_tpu.obs import collector as collector_mod

    # the connection plane (docs/serving.md "Connection plane") rides
    # the same proof: every HPNN_CONN_* knob armed BEFORE the
    # collector starts, so the round's live push traffic flows
    # through the guarded socket path — deadlines, per-IP cap, byte-
    # rate watchdog all attached — without moving stdout a byte
    from hpnn_tpu.serve import conn as conn_mod

    os.environ["HPNN_CONN_HDR_MS"] = "5000"
    os.environ["HPNN_CONN_BODY_MS"] = "5000"
    os.environ["HPNN_CONN_PER_IP"] = "64"
    os.environ["HPNN_CONN_MIN_BPS"] = "1"
    os.environ["HPNN_CONN_TABLE"] = "64"
    conn_mod._reset_for_tests()

    coll_out = os.path.join(tmpdir, "collector_merged.jsonl")
    coll_server = collector_mod.start_collector(path=coll_out)
    coll_port = coll_server.server_address[1]

    wal_dir = os.path.join(tmpdir, "wal")
    ledger_b = os.path.join(tmpdir, "ledger_b.jsonl")
    os.environ["HPNN_FLIGHT"] = os.path.join(tmpdir, "flight.jsonl")
    os.environ["HPNN_PROBES"] = "1"
    os.environ["HPNN_NUMERICS"] = "warn"
    os.environ["HPNN_LEDGER"] = ledger_b
    os.environ["HPNN_SPANS"] = "1"
    os.environ["HPNN_COST"] = "1"
    os.environ["HPNN_SLO_MS"] = "50"
    os.environ["HPNN_CHAOS"] = "delay@serve.dispatch:ms=0"
    os.environ["HPNN_CHAOS_SEED"] = "1"
    os.environ["HPNN_WAL_DIR"] = wal_dir
    os.environ["HPNN_COLLECTOR"] = f"http://127.0.0.1:{coll_port}"
    os.environ["HPNN_COLLECTOR_FLUSH_S"] = "0.05"
    os.environ["HPNN_ALERTS"] = "lint_chunk@fuse.chunk_size>0:cooldown=0"
    # tail-latency forensics (docs/observability.md "Forensics") ride
    # the same proof: the sampler armed at rate 1 (the train path has
    # no request spans, so it must stay inert) plus a capsule dir the
    # firing alert rule above must actually capture into — async, with
    # the profiler window off so the capsule is just files
    from hpnn_tpu.obs import drift as drift_mod
    from hpnn_tpu.obs import forensics as forensics_mod
    from hpnn_tpu.obs import meter as meter_mod
    from hpnn_tpu.obs import triggers as triggers_mod

    capsule_dir = os.path.join(tmpdir, "capsules")
    os.environ["HPNN_SAMPLE"] = "1"
    os.environ["HPNN_CAPSULE_DIR"] = capsule_dir
    os.environ["HPNN_CAPSULE_PROFILE_MS"] = "0"
    os.environ["HPNN_CAPSULE_COOLDOWN_S"] = "0"
    # drift detection (docs/observability.md "Drift detection") rides
    # the same proof: the sketches tap online ingest / serve dispatch /
    # the online trainer's holdout evals, none of which a plain train
    # round touches — armed, it must stay inert on stdout and the sink
    os.environ["HPNN_DRIFT"] = "1"
    # per-tenant metering (docs/observability.md "Tenant metering")
    # rides the same proof: taps sit on serve dispatch / the batcher
    # queue edge / tenant admission, none of which a plain train round
    # touches — armed, it must stay inert on stdout and the sink
    os.environ["HPNN_METER"] = "1"
    # online blame + self-tuning (docs/selftuning.md) ride the same
    # proof: blame only sees sampler-emitted request roots and the
    # tuner only starts inside a serve Session, so a plain train
    # round must not move a byte with both armed
    from hpnn_tpu import tune as tune_mod
    from hpnn_tpu.obs import blame as blame_mod

    os.environ["HPNN_BLAME"] = "1"
    os.environ["HPNN_TUNE"] = "1"
    for knob, val in _ONLINE_KNOBS:
        os.environ[knob] = val
    chaos_mod._reset_for_tests()
    wal_mod._reset_for_tests()
    forensics_mod._reset_for_tests()
    triggers_mod._reset_for_tests()
    drift_mod._reset_for_tests()
    meter_mod._reset_for_tests()
    blame_mod._reset_for_tests()
    tune_mod._reset_for_tests()
    try:
        instrumented = _run_round(os.path.join(tmpdir, "b"), sink,
                                  probe=probe)
    finally:
        for knob in ("HPNN_FLIGHT", "HPNN_PROBES", "HPNN_NUMERICS",
                     "HPNN_LEDGER", "HPNN_SPANS", "HPNN_COST",
                     "HPNN_SLO_MS", "HPNN_CHAOS", "HPNN_CHAOS_SEED",
                     "HPNN_WAL_DIR", "HPNN_COLLECTOR",
                     "HPNN_COLLECTOR_FLUSH_S", "HPNN_ALERTS",
                     "HPNN_SAMPLE", "HPNN_CAPSULE_DIR",
                     "HPNN_CAPSULE_PROFILE_MS",
                     "HPNN_CAPSULE_COOLDOWN_S", "HPNN_DRIFT",
                     "HPNN_METER", "HPNN_BLAME", "HPNN_TUNE",
                     "HPNN_CONN_HDR_MS", "HPNN_CONN_BODY_MS",
                     "HPNN_CONN_PER_IP", "HPNN_CONN_MIN_BPS",
                     "HPNN_CONN_TABLE") \
                + tuple(k for k, _ in _ONLINE_KNOBS):
            os.environ.pop(knob, None)
        conn_mod._reset_for_tests()
        chaos_mod._reset_for_tests()
        wal_mod._reset_for_tests()
        forensics_mod._reset_for_tests()
        triggers_mod._reset_for_tests()
        drift_mod._reset_for_tests()
        meter_mod._reset_for_tests()
        blame_mod._reset_for_tests()
        tune_mod._reset_for_tests()

    if plain != instrumented:
        failures.append(
            "stdout is NOT byte-identical with HPNN_METRICS + "
            "HPNN_FLIGHT + HPNN_PROBES + HPNN_NUMERICS + HPNN_LEDGER + "
            "HPNN_SPANS + HPNN_COST + HPNN_SLO_MS + HPNN_CHAOS + "
            "HPNN_WAL_DIR + HPNN_COLLECTOR (live push) + HPNN_ALERTS "
            "(firing rule) + HPNN_SAMPLE + HPNN_CAPSULE_DIR "
            "(alert-triggered capture) + HPNN_DRIFT (armed "
            "sketches) + HPNN_METER (armed metering) + "
            "HPNN_BLAME + HPNN_TUNE (armed blame/tuning) + "
            "HPNN_CONN_* (guarded collector sockets) + "
            "HPNN_ONLINE_* (incl. "
            "HPNN_ONLINE_SCAN_K) + "
            "HPNN_SERVE_DTYPE=bf16 + export server all enabled "
            f"(plain {len(plain)}B vs instrumented {len(instrumented)}B)")
    if os.path.exists(os.path.join(wal_dir, wal_mod.WAL_NAME)):
        failures.append(
            "a plain train round wrote the promotion WAL — "
            "HPNN_WAL_DIR must be inert outside hpnn_tpu/online/")
    # the push client's final drain ran inside _run_round's
    # obs.configure(None); give the collector's consumer thread a beat
    # to absorb the last batch, then the received count must be real
    coll = coll_server.collector
    deadline = time.monotonic() + 5.0
    while coll.records_total == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    fleet_doc = coll.fleetz()
    collector_mod.stop_collector(coll_server)
    if coll.records_total <= 0:
        failures.append(
            "live collector received NO telemetry with HPNN_COLLECTOR "
            "armed — the push path is dead")
    elif not fleet_doc.get("workers"):
        failures.append(
            "collector /fleetz lists no workers after a pushed round "
            f"(records_total={coll.records_total})")
    # the firing alert rule must ALSO have pulled the capture trigger:
    # an async capsule with a manifest must land under HPNN_CAPSULE_DIR
    # (assembly runs on a daemon thread; give it the same grace the
    # collector drain gets)
    manifest_path = None
    deadline = time.monotonic() + 5.0
    while manifest_path is None and time.monotonic() < deadline:
        for dirpath, _dirs, files in os.walk(capsule_dir):
            if "manifest.json" in files:
                manifest_path = os.path.join(dirpath, "manifest.json")
                break
        else:
            time.sleep(0.05)
    if manifest_path is None:
        failures.append(
            "no capture capsule landed with HPNN_CAPSULE_DIR + a "
            "firing alert rule armed — the alert->capture hook is "
            "dead")
    else:
        with open(manifest_path) as fp:
            man = json.load(fp)
        if not str(man.get("reason", "")).startswith("alert:"):
            failures.append(
                f"capsule manifest reason {man.get('reason')!r} is "
                "not alert-attributed")
        if "spans.jsonl" not in man.get("files", []):
            failures.append(
                "capsule manifest lists no spans.jsonl — the "
                "sampler ring never reached the capsule")

    body = scraped.get("metrics", "")
    if "# TYPE" not in body or "hpnn_" not in body:
        failures.append(
            "live /metrics scrape is not Prometheus text exposition "
            f"(got {body[:80]!r})")
    if not plain.strip():
        failures.append("no stdout captured — the round emitted nothing")

    for line in plain.splitlines():
        if not any(g.match(line) for g in GOLDEN):
            failures.append(f"unexpected stdout line shape: {line!r}")
        if line and not line.startswith(TOKEN_PREFIXES):
            failures.append(f"non-token stdout line: {line!r}")
        if '"ev"' in line or '"kind"' in line or line.startswith("{"):
            failures.append(f"obs JSON leaked into stdout: {line!r}")

    # The serving subsystem must be a bystander to the token protocol:
    # importing it — and actually serving a request through the full
    # stack (registry → batcher → bucketed engine) — must leave the
    # next train+eval round's stdout byte-identical.  The session is
    # exercised BEFORE the round so its jit/compile-cache residue is
    # live while the round prints.  The fleet path (docs/fleet.md)
    # rides the same proof: a fleet-mode session over two
    # same-topology kernels plus a vmapped train_fleet round, all
    # with the obs knobs off — neither may add a stdout byte.
    import numpy as np

    from hpnn_tpu import serve
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.train import fleet as fleet_mod

    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    sess.register_kernel("lint", k)
    sess.infer("lint", np.zeros(8))
    sess.close()

    # SLO tracking + load shedding (HPNN_SLO_MS / HPNN_SHED_AGE_MS,
    # obs/slo.py + serve/batcher.py) are serve-side features riding
    # the same silence contract: arm both, serve a request, and force
    # an actual Shed rejection on a fake-clock batcher — none of it
    # may write a stdout byte even while the knobs are ON.
    from hpnn_tpu import obs as obs_mod
    from hpnn_tpu.serve import batcher as batcher_mod

    os.environ["HPNN_SLO_MS"] = "50"
    os.environ["HPNN_SHED_AGE_MS"] = "5"
    obs_mod.slo._reset_for_tests()
    shed_buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(shed_buf):
            ssess = serve.Session(max_batch=8, n_buckets=2,
                                  max_wait_ms=1.0)
            ssess.register_kernel("lint_slo", k)
            ssess.infer("lint_slo", np.zeros(8))
            ssess.close()
            fake = [0.0]
            b = batcher_mod.Batcher(lambda p: p, clock=lambda: fake[0],
                                    name="lint_shed", start=False)
            b.submit(np.zeros((1, 8)))
            fake[0] = 1.0  # oldest waiter now 1000ms > 5ms threshold
            try:
                b.submit(np.zeros((1, 8)))
                raise RuntimeError("expected Shed")
            except batcher_mod.Shed:
                pass
            b.close()
    finally:
        os.environ.pop("HPNN_SLO_MS", None)
        os.environ.pop("HPNN_SHED_AGE_MS", None)
        obs_mod.slo._reset_for_tests()
    if shed_buf.getvalue():
        failures.append(
            "SLO tracking / load shedding wrote stdout: "
            f"{shed_buf.getvalue()[:120]!r}")

    fsess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0,
                          fleet=True)
    k2, _ = kernel_mod.generate(11, 8, [5], 2)
    fsess.register_kernel("lint_a", k)
    fsess.register_kernel("lint_b", k2)
    fsess.infer("lint_a", np.zeros(8))
    fsess.infer("lint_b", np.zeros(8))
    # force one genuinely coalesced two-member group through the
    # stacked executable (sequential infers usually drain solo)
    fsess.engine.dispatch_fleet([("lint_a", np.zeros((1, 8))),
                                 ("lint_b", np.zeros((1, 8)))])
    fsess.close()

    rng = np.random.RandomState(5)
    Xf = rng.uniform(-1, 1, (4, 8)).astype(np.float64)
    Tf = np.full((4, 2), -1.0)
    Tf[np.arange(4), rng.randint(0, 2, 4)] = 1.0
    fleet_mod.train_fleet([k, k2], Xf, Tf, epochs=1, batch=2,
                          seeds=[1, 2])

    # Train-while-serve (hpnn_tpu/online/, docs/online.md) rides the
    # same silence contract: with the WHOLE HPNN_ONLINE_* knob family
    # set (so the env-reading paths run, not just the defaults), feed
    # a stream, run a synchronous training round through the promotion
    # gate, serve a query, and roll back — not one stdout byte; the
    # online.* audit trail lands in the sink instead.
    from hpnn_tpu import online as online_mod

    online_sink = os.path.join(tmpdir, "online.jsonl")
    for knob, val in _ONLINE_KNOBS:
        os.environ[knob] = val
    online_buf = io.StringIO()
    try:
        obs_mod.configure(online_sink)
        with contextlib.redirect_stdout(online_buf):
            osess = online_mod.OnlineSession(
                serve_kwargs=dict(max_batch=8, n_buckets=2,
                                  max_wait_ms=1.0))
            osess.add_kernel("lint_online", k)
            orng = np.random.RandomState(3)
            Xo = orng.uniform(0.0, 1.0, (48, 8))
            osess.feed(Xo, np.tanh(Xo[:, :2]))
            osess.tick()
            osess.infer("lint_online", np.zeros(8))
            osess.rollback("lint_online")
            osess.close()
    finally:
        obs_mod.configure(None)
        for knob, _ in _ONLINE_KNOBS:
            os.environ.pop(knob, None)
    if online_buf.getvalue():
        failures.append(
            "online train-while-serve round wrote stdout: "
            f"{online_buf.getvalue()[:120]!r}")
    with open(online_sink) as fp:
        onames = {json.loads(ln).get("ev") for ln in fp if ln.strip()}
    for want in ("online.ingest", "online.buffer_depth",
                 "online.staleness_s", "online.round",
                 "online.train_loss", "online.candidate_loss",
                 "online.resident_loss"):
        if want not in onames:
            failures.append(f"online sink missing event {want!r}")
    if not {"online.promote", "online.reject"} & onames:
        failures.append(
            "online sink carries neither online.promote nor "
            "online.reject — the gate never ruled")

    # Multi-replica scale-out (serve/router.py, docs/serving.md
    # "Scale-out") rides the same silence contract: a 2-replica Router
    # in compiled mode with the persistent compile cache ARMED
    # (HPNN_COMPILE_CACHE_DIR — the warm-boot path writes executables
    # to disk and counts hits/misses), fan-out register, routed
    # infers (single vector + row block), a fenced install_kernel
    # promotion — not one stdout byte from any of it.
    from hpnn_tpu.serve import compile_cache as cc_mod

    cache_dir = os.path.join(tmpdir, "xla_cache")
    os.environ[cc_mod.ENV_DIR] = cache_dir
    router_buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(router_buf):
            router = serve.Router(2, max_batch=8, n_buckets=1,
                                  max_wait_ms=1.0, mode="compiled")
            router.register_kernel("lint_router", k)
            router.infer("lint_router", np.zeros(8))
            router.infer("lint_router", np.zeros((3, 8)))
            k3, _ = kernel_mod.generate(13, 8, [5], 2)
            router.install_kernel("lint_router", k3)
            router.infer("lint_router", np.zeros(8))
            router.close()
    finally:
        os.environ.pop(cc_mod.ENV_DIR, None)
        cc_mod._reset_for_tests()
    if router_buf.getvalue():
        failures.append(
            "2-replica Router round trip wrote stdout: "
            f"{router_buf.getvalue()[:120]!r}")

    # Cross-host fleet (hpnn_tpu/fleet/, docs/serving.md "Cross-host
    # fleet") rides the same silence contract: TWO in-process HTTP
    # workers (Session + make_server on ephemeral ports — the same
    # wire surface a real worker process exposes), WorkerHandles, a
    # ClusterRouter fanning infers over them, and a fenced
    # CheckpointPublisher install_kernel promotion re-read by both
    # workers over /v1/reload — not one stdout byte from any of it
    # (worker HTTP request logs go to stderr by design).
    from hpnn_tpu.fileio import checkpoint as fileio_ckpt
    from hpnn_tpu.fleet.client import WorkerHandle
    from hpnn_tpu.fleet.router import CheckpointPublisher, ClusterRouter
    from hpnn_tpu.serve.server import make_server

    cluster_buf = io.StringIO()
    cluster_path = os.path.join(tmpdir, "lint_cluster.ckpt")
    fileio_ckpt.dump_checkpoint(cluster_path, "lint_cluster",
                                k.weights, version=1)
    with contextlib.redirect_stdout(cluster_buf):
        sessions, servers, handles = [], [], []
        try:
            for rank in range(2):
                sess = serve.Session(max_batch=8, n_buckets=1,
                                     max_wait_ms=0.5)
                sess.load_kernel("lint_cluster", cluster_path)
                srv = make_server(sess, port=0)
                threading.Thread(target=srv.serve_forever,
                                 daemon=True).start()
                sessions.append(sess)
                servers.append(srv)
                handles.append(WorkerHandle(
                    rank, "127.0.0.1", srv.server_address[1]))
            cluster = ClusterRouter(
                workers=handles,
                publisher=CheckpointPublisher(
                    {"lint_cluster": cluster_path},
                    versions={"lint_cluster": 1}))
            cluster.infer("lint_cluster", np.zeros(8))
            cluster.infer("lint_cluster", np.zeros((3, 8)))
            k4, _ = kernel_mod.generate(17, 8, [5], 2)
            cluster.install_kernel("lint_cluster", k4)
            cluster.infer("lint_cluster", np.zeros(8))
            cluster.close()
        finally:
            for srv in servers:
                srv.shutdown()
                srv.server_close()
            for sess in sessions:
                sess.close()
    if cluster_buf.getvalue():
        failures.append(
            "2-worker ClusterRouter round trip wrote stdout: "
            f"{cluster_buf.getvalue()[:120]!r}")

    with_serve = _run_round(os.path.join(tmpdir, "c"), None)
    if plain != with_serve:
        failures.append(
            "stdout is NOT byte-identical after importing/exercising "
            "hpnn_tpu.serve (per-kernel + fleet + 2-replica Router "
            "with the persistent compile cache armed + 2-worker "
            "ClusterRouter over HTTP), train.fleet, "
            f"and hpnn_tpu.online (plain {len(plain)}B vs "
            f"with-serve {len(with_serve)}B)")

    # The zero-perturbation proof for the numerics probes: a run with
    # ONLY the ledger on (no probes, no metrics) must print the same
    # bytes AND record the same checksums as the fully-probed run b —
    # the probes' stats dispatch is a separate executable, so enabling
    # it cannot move the training trajectory (f64 CPU runs of the same
    # seed are bit-identical; equal abs-sums here mean equal weights).
    # Run b also had HPNN_SERVE_DTYPE=bf16 and HPNN_ONLINE_SCAN_K=4
    # armed, so checksum equality here is ALSO the proof that the
    # low-precision serve policy and the K-round scan knob are
    # zero-perturbation when their subsystems aren't in the path.
    ledger_d = os.path.join(tmpdir, "ledger_d.jsonl")
    os.environ["HPNN_LEDGER"] = ledger_d
    try:
        ledger_only = _run_round(os.path.join(tmpdir, "d"), None)
    finally:
        os.environ.pop("HPNN_LEDGER", None)
    if plain != ledger_only:
        failures.append(
            "stdout is NOT byte-identical with HPNN_LEDGER enabled "
            f"(plain {len(plain)}B vs ledger-only {len(ledger_only)}B)")

    def _rounds(path):
        if not os.path.exists(path):
            return None
        with open(path) as fp:
            return [
                {k: rec[k] for k in ("row", "step", "where", "nan",
                                     "inf", "checksums", "shapes")}
                for rec in (json.loads(ln) for ln in fp if ln.strip())
                if rec.get("ev") == "ledger.round"
            ]

    rounds_b, rounds_d = _rounds(ledger_b), _rounds(ledger_d)
    if not rounds_b or not rounds_d:
        failures.append(
            f"ledger missing or empty (b={rounds_b and len(rounds_b)}, "
            f"d={rounds_d and len(rounds_d)})")
    elif rounds_b != rounds_d:
        failures.append(
            "probes are NOT zero-perturbation: probed ledger differs "
            f"from ledger-only ledger ({rounds_b} vs {rounds_d})")

    if not os.path.exists(sink):
        failures.append("instrumented run produced no metrics sink")
        return failures
    with open(sink) as fp:
        recs = [json.loads(ln) for ln in fp if ln.strip()]
    if not recs:
        failures.append("metrics sink is empty")
    names = {r.get("ev") for r in recs}
    for want in ("round.start", "driver.chunk_dispatch", "train.n_iter",
                 "fuse.chunk_size", "round.end", "obs.summary",
                 "device.live_arrays", "numerics.probe",
                 "numerics.checksum", "span.end", "compile.cost",
                 "perf.flops_per_s", "alert.fire", "collector.push",
                 "forensics.capture"):
        if want not in names:
            failures.append(f"metrics sink missing event {want!r}")
    return failures


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # standalone invocation (python tools/check_tokens.py): make the
    # repo root importable like the test runner does
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    with tempfile.TemporaryDirectory() as tmpdir:
        failures = check(tmpdir)
    if failures:
        for f in failures:
            sys.stderr.write(f"check_tokens: FAIL: {f}\n")
        return 1
    sys.stderr.write("check_tokens: OK — stdout tokens byte-stable, "
                     "sink populated\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
