#!/usr/bin/env python3
"""Closed-loop load generator for the serving subsystem (docs/serving.md).

N client threads each issue R sequential ``Session.infer`` calls with
mixed row counts (closed loop: a client's next request starts when its
previous one returns), against a freshly generated kernel behind the
full stack — registry → micro-batcher → bucketed engine.  Reports
per-request latency (p50/p99/mean), request and row throughput, and
the compile-cache census (the steady-state invariant: executable
count == bucket count after warmup).

Two presets:

* default — the MNIST tutorial shape (784-300-10), 16 clients ×
  25 requests: the headline serving figure;
* ``--smoke`` — a tiny 8-5-2 kernel, 8 × 8 requests: seconds on CPU,
  wired into ``bench.py``'s detail JSON (``serve_smoke``) and usable
  as a tier-1 sanity load.

Prints ONE JSON line (the bench.py convention); detail keys only, no
stdout tokens.  Structured events ride ``HPNN_METRICS`` as usual.

    JAX_PLATFORMS=cpu python tools/bench_serve.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

# latency definitions are shared with the open-loop generator so the
# closed-loop bench and loadgen report identically-defined numbers
_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)
from loadgen import latency_summary, percentile_ms  # noqa: E402


def run_serve_bench(
    *, n_in: int, hiddens: list[int], n_out: int,
    n_clients: int = 16, n_requests: int = 25,
    max_batch: int = 64, n_buckets: int = 4, max_wait_ms: float = 2.0,
    mixed_rows=(1, 2, 4, 8), seed: int = 11, timeout_s: float = 30.0,
) -> dict:
    """One closed-loop measurement; returns the result dict."""
    from hpnn_tpu import serve
    from hpnn_tpu.models import kernel as kernel_mod

    k, _ = kernel_mod.generate(seed, n_in, hiddens, n_out)
    session = serve.Session(max_batch=max_batch, n_buckets=n_buckets,
                            max_wait_ms=max_wait_ms)
    t0 = time.perf_counter()
    session.register_kernel("bench", k)          # includes warmup
    warmup_s = time.perf_counter() - t0
    compiled_after_warmup = session.engine.compiled_count()

    lats: list[list[float]] = [[] for _ in range(n_clients)]
    rows_done = [0] * n_clients
    rejected = [0] * n_clients
    errors: list[str] = []

    def client(ci: int):
        rng = np.random.RandomState(1000 + ci)
        for j in range(n_requests):
            rows = mixed_rows[(ci + j) % len(mixed_rows)]
            x = rng.uniform(-1.0, 1.0, size=(rows, n_in))
            t_req = time.perf_counter()
            try:
                session.infer("bench", x, timeout_s=timeout_s)
            except serve.QueueFull:
                rejected[ci] += 1
                continue
            except Exception as exc:  # a failed load run must say why
                errors.append(repr(exc))
                return
            lats[ci].append(time.perf_counter() - t_req)
            rows_done[ci] += rows

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    session.close()

    lat = [v for client_l in lats for v in client_l]
    out = {
        "metric": "serve_infer_latency",
        "kernel_shape": f"{n_in}-{'-'.join(map(str, hiddens))}-{n_out}",
        "n_clients": n_clients,
        "requests_per_client": n_requests,
        "requests_served": len(lat),
        "requests_rejected": int(sum(rejected)),
        "rows_served": int(sum(rows_done)),
        "wall_s": round(wall_s, 3),
        "throughput_rps": round(len(lat) / wall_s, 1) if wall_s else 0.0,
        "rows_per_s": round(sum(rows_done) / wall_s, 1) if wall_s else 0.0,
        "latency_ms": latency_summary(lat),
        "warmup_s": round(warmup_s, 3),
        "buckets": list(session.engine.buckets),
        "compiled_after_warmup": compiled_after_warmup,
        # the steady-state invariant: serving compiled NOTHING beyond
        # the warmed menu (one executable per bucket)
        "compiled_after_load": session.engine.compiled_count(),
    }
    if errors:
        out["errors"] = errors[:5]
    return out


def run_smoke() -> dict:
    """The tiny preset bench.py folds into its detail JSON."""
    return run_serve_bench(
        n_in=8, hiddens=[5], n_out=2, n_clients=8, n_requests=8,
        max_batch=16, n_buckets=3, max_wait_ms=1.0, seed=7,
    )


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny 8-5-2 preset (seconds on CPU)")
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--requests", type=int, default=25)
    args = ap.parse_args(argv)
    if args.smoke:
        out = run_smoke()
    else:
        out = run_serve_bench(
            n_in=784, hiddens=[300], n_out=10,
            n_clients=args.clients, n_requests=args.requests,
        )
    print(json.dumps(out))
    return 1 if out.get("errors") else 0


if __name__ == "__main__":
    sys.exit(main())
