#!/usr/bin/env python3
"""Closed-loop load generator for the serving subsystem (docs/serving.md).

N client threads each issue R sequential ``Session.infer`` calls with
mixed row counts (closed loop: a client's next request starts when its
previous one returns), against a freshly generated kernel behind the
full stack — registry → micro-batcher → bucketed engine.  Reports
per-request latency (p50/p99/mean), request and row throughput, and
the compile-cache census (the steady-state invariant: executable
count == bucket count after warmup).

Two presets:

* default — the MNIST tutorial shape (784-300-10), 16 clients ×
  25 requests: the headline serving figure;
* ``--smoke`` — a tiny 8-5-2 kernel, 8 × 8 requests: seconds on CPU,
  wired into ``bench.py``'s detail JSON (``serve_smoke``) and usable
  as a tier-1 sanity load.

Prints ONE JSON line (the bench.py convention); detail keys only, no
stdout tokens.  Structured events ride ``HPNN_METRICS`` as usual.

    JAX_PLATFORMS=cpu python tools/bench_serve.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

# latency definitions are shared with the open-loop generator so the
# closed-loop bench and loadgen report identically-defined numbers
_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)
from loadgen import latency_summary, percentile_ms  # noqa: E402


def run_serve_bench(
    *, n_in: int, hiddens: list[int], n_out: int,
    n_clients: int = 16, n_requests: int = 25,
    max_batch: int = 64, n_buckets: int = 4, max_wait_ms: float = 2.0,
    mixed_rows=(1, 2, 4, 8), seed: int = 11, timeout_s: float = 30.0,
) -> dict:
    """One closed-loop measurement; returns the result dict."""
    from hpnn_tpu import serve
    from hpnn_tpu.models import kernel as kernel_mod

    k, _ = kernel_mod.generate(seed, n_in, hiddens, n_out)
    session = serve.Session(max_batch=max_batch, n_buckets=n_buckets,
                            max_wait_ms=max_wait_ms)
    t0 = time.perf_counter()
    session.register_kernel("bench", k)          # includes warmup
    warmup_s = time.perf_counter() - t0
    compiled_after_warmup = session.engine.compiled_count()

    lats: list[list[float]] = [[] for _ in range(n_clients)]
    rows_done = [0] * n_clients
    rejected = [0] * n_clients
    errors: list[str] = []

    def client(ci: int):
        rng = np.random.RandomState(1000 + ci)
        for j in range(n_requests):
            rows = mixed_rows[(ci + j) % len(mixed_rows)]
            x = rng.uniform(-1.0, 1.0, size=(rows, n_in))
            t_req = time.perf_counter()
            try:
                session.infer("bench", x, timeout_s=timeout_s)
            except serve.QueueFull:
                rejected[ci] += 1
                continue
            except Exception as exc:  # a failed load run must say why
                errors.append(repr(exc))
                return
            lats[ci].append(time.perf_counter() - t_req)
            rows_done[ci] += rows

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    session.close()

    lat = [v for client_l in lats for v in client_l]
    out = {
        "metric": "serve_infer_latency",
        "kernel_shape": f"{n_in}-{'-'.join(map(str, hiddens))}-{n_out}",
        "n_clients": n_clients,
        "requests_per_client": n_requests,
        "requests_served": len(lat),
        "requests_rejected": int(sum(rejected)),
        "rows_served": int(sum(rows_done)),
        "wall_s": round(wall_s, 3),
        "throughput_rps": round(len(lat) / wall_s, 1) if wall_s else 0.0,
        "rows_per_s": round(sum(rows_done) / wall_s, 1) if wall_s else 0.0,
        "latency_ms": latency_summary(lat),
        "warmup_s": round(warmup_s, 3),
        "buckets": list(session.engine.buckets),
        "compiled_after_warmup": compiled_after_warmup,
        # the steady-state invariant: serving compiled NOTHING beyond
        # the warmed menu (one executable per bucket)
        "compiled_after_load": session.engine.compiled_count(),
    }
    if errors:
        out["errors"] = errors[:5]
    return out


def run_smoke() -> dict:
    """The tiny preset bench.py folds into its detail JSON."""
    return run_serve_bench(
        n_in=8, hiddens=[5], n_out=2, n_clients=8, n_requests=8,
        max_batch=16, n_buckets=3, max_wait_ms=1.0, seed=7,
    )


# --------------------------------------------------------------------
# multi-replica scale-out bench (serve/router.py; docs/serving.md)
# --------------------------------------------------------------------

def _goodput(router, *, light_clients: int, light_requests: int,
             heavy_clients: int, light_rows: int, heavy_rows: int,
             n_in: int, timeout_s: float) -> dict:
    """Closed-loop goodput through a Router under a MIXED load: a few
    heavy clients stream oversized row blocks (each chunks through the
    top bucket for many sequential dispatches) while light clients
    issue small requests.  On one replica everything shares one FIFO
    batcher, so light requests stall behind every heavy dispatch
    chain; the router's least-outstanding placement isolates the heavy
    streams onto their own replicas and the light traffic flows.  This
    is the scaling axis that exists even on CI's CPU threads —
    per-replica queues kill head-of-line blocking — and on real
    multi-device hardware it compounds with compute parallelism."""
    from hpnn_tpu import serve

    rng = np.random.RandomState(4242)
    x_light = rng.uniform(-1.0, 1.0, size=(light_rows, n_in))
    x_heavy = rng.uniform(-1.0, 1.0, size=(heavy_rows, n_in))
    served_light = [0] * light_clients
    served_heavy = [0] * heavy_clients
    rejected = [0] * (light_clients + heavy_clients)
    lat: list[list[float]] = [[] for _ in range(light_clients)]
    errors: list[str] = []
    stop = threading.Event()

    def light(ci: int):
        for _ in range(light_requests):
            t_req = time.perf_counter()
            try:
                router.infer("bench", x_light, timeout_s=timeout_s)
            except serve.QueueFull:
                rejected[ci] += 1
                continue
            except Exception as exc:
                errors.append(repr(exc))
                return
            lat[ci].append(time.perf_counter() - t_req)
            served_light[ci] += 1

    def heavy(ci: int):
        while not stop.is_set():
            try:
                router.infer("bench", x_heavy, timeout_s=timeout_s)
            except serve.QueueFull:
                rejected[light_clients + ci] += 1
                continue
            except Exception as exc:
                errors.append(repr(exc))
                return
            served_heavy[ci] += 1

    lights = [threading.Thread(target=light, args=(ci,))
              for ci in range(light_clients)]
    heavies = [threading.Thread(target=heavy, args=(ci,))
               for ci in range(heavy_clients)]
    t0 = time.perf_counter()
    for t in heavies + lights:
        t.start()
    for t in lights:
        t.join()
    wall_s = time.perf_counter() - t0
    stop.set()
    for t in heavies:
        t.join()
    flat = [v for client_l in lat for v in client_l]
    n_light = int(sum(served_light))
    n_heavy = int(sum(served_heavy))
    rows_total = n_light * light_rows + n_heavy * heavy_rows
    out = {
        "requests": n_light + n_heavy,
        "light_requests": n_light,
        "heavy_requests": n_heavy,
        "rejected": int(sum(rejected)),
        "wall_s": round(wall_s, 3),
        "rps": (round((n_light + n_heavy) / wall_s, 1)
                if wall_s else 0.0),
        "rows_per_s": (round(rows_total / wall_s, 1)
                       if wall_s else 0.0),
        "light_latency_ms": latency_summary(flat),
    }
    if errors:
        out["errors"] = errors[:5]
    return out


def _replica_parity(n_replicas: int = 3, *, seed: int = 7) -> dict:
    """Bitwise proof: every registry kernel answered by an N-replica
    router equals the single-Session answer exactly (parity mode —
    the CPU bitwise contract extends across the fleet)."""
    from hpnn_tpu import serve
    from hpnn_tpu.models import kernel as kernel_mod

    specs = {"ann": ("ann", seed), "snn": ("snn", seed + 13)}
    router = serve.Router(n_replicas, max_batch=16, n_buckets=3,
                          max_wait_ms=0.5, mode="parity")
    single = serve.Session(max_batch=16, n_buckets=3, max_wait_ms=0.5,
                           mode="parity")
    try:
        for name, (model, s) in specs.items():
            k, _ = kernel_mod.generate(s, 8, [5], 2)
            router.register_kernel(name, k, model=model)
            single.register_kernel(name, k, model=model)
        rng = np.random.RandomState(99)
        kernels = {}
        for name in specs:
            ok = True
            for rows in (1, 3, 8, 21):
                x = rng.uniform(0.0, 1.0, size=(rows, 8))
                a = router.infer(name, x, timeout_s=30.0)
                b = single.infer(name, x, timeout_s=30.0)
                ok = ok and bool(np.array_equal(a, b))
            kernels[name] = ok
        return {"ok": all(kernels.values()), "replicas": n_replicas,
                "kernels": kernels}
    finally:
        router.close()
        single.close()


def _boot_once(cache_dir: str, *, n_replicas: int, n_in: int,
               hiddens: list[int], n_out: int, max_batch: int,
               n_buckets: int, seed: int) -> dict:
    """One compiled-mode router boot against ``cache_dir``; returns
    time-to-ready and the persistent-cache hit/miss delta."""
    from hpnn_tpu import serve
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve import compile_cache

    k, _ = kernel_mod.generate(seed, n_in, hiddens, n_out)
    os.environ["HPNN_COMPILE_CACHE_DIR"] = cache_dir
    try:
        h0, m0 = compile_cache.counters()
        t0 = time.perf_counter()
        router = serve.Router(n_replicas, max_batch=max_batch,
                              n_buckets=n_buckets, max_wait_ms=0.5,
                              mode="compiled")
        router.register_kernel("bench", k)     # warms the full menu
        ready_s = time.perf_counter() - t0
        h1, m1 = compile_cache.counters()
        x = np.random.RandomState(3).uniform(-1, 1, (4, n_in))
        y = np.asarray(router.infer("bench", x, timeout_s=30.0))
        router.close()
    finally:
        os.environ.pop("HPNN_COMPILE_CACHE_DIR", None)
    hits, misses = h1 - h0, m1 - m0
    total = hits + misses
    return {
        "ready_s": round(ready_s, 3),
        "cache_hits": hits,
        "cache_misses": misses,
        "hit_rate": round(hits / total, 4) if total else None,
        "probe_sum": float(np.sum(y)),
    }


def run_bench_replicas(
    *, replicas=(1, 2, 4), n_in: int = 784, hiddens=None,
    n_out: int = 10, light_clients: int = 6,
    light_requests: int = 150, heavy_clients: int = 1,
    light_rows: int = 1, heavy_rows: int = 512,
    max_batch: int = 64, max_wait_ms: float = 0.5, seed: int = 11,
    timeout_s: float = 120.0,
) -> dict:
    """The scale-out headline: mixed-load goodput vs replica count
    (compiled mode; see :func:`_goodput` for why the mixed load is
    the honest CPU-thread scaling axis), the N-replica bitwise-parity
    proof, and the warm-vs-cold boot comparison over a persistent
    compile cache."""
    import tempfile

    from hpnn_tpu import serve
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve import compile_cache

    hiddens = [300] if hiddens is None else hiddens
    k, _ = kernel_mod.generate(seed, n_in, hiddens, n_out)
    goodput: dict = {}
    for n in replicas:
        router = serve.Router(n, max_batch=max_batch, n_buckets=1,
                              max_wait_ms=max_wait_ms, mode="compiled")
        router.register_kernel("bench", k)
        goodput[f"r{n}"] = _goodput(
            router, light_clients=light_clients,
            light_requests=light_requests,
            heavy_clients=heavy_clients, light_rows=light_rows,
            heavy_rows=heavy_rows, n_in=n_in, timeout_s=timeout_s)
        router.close()
    base = goodput[f"r{replicas[0]}"]["rps"] or 1.0
    scaling = {f"r{n}": round(goodput[f"r{n}"]["rps"] / base, 2)
               for n in replicas[1:]}

    parity = _replica_parity()

    # warm vs cold boot: same executables, fresh cache dir; the second
    # boot must come off disk (hit rate > 0, faster time-to-ready)
    boot_kw = dict(n_replicas=2, n_in=n_in, hiddens=hiddens,
                   n_out=n_out, max_batch=max_batch, n_buckets=2,
                   seed=seed)
    with tempfile.TemporaryDirectory() as cache_dir:
        compile_cache._reset_for_tests()
        cold = _boot_once(cache_dir, **boot_kw)
        warm = _boot_once(cache_dir, **boot_kw)
        compile_cache._reset_for_tests()
    bitwise_boot = cold.pop("probe_sum") == warm.pop("probe_sum")

    return {
        "metric": "serve_replicas",
        "kernel_shape": f"{n_in}-{'-'.join(map(str, hiddens))}-{n_out}",
        "mode": "compiled",
        "load": {"light_clients": light_clients,
                 "light_rows": light_rows,
                 "heavy_clients": heavy_clients,
                 "heavy_rows": heavy_rows},
        "goodput": goodput,
        "scaling_x": scaling,
        "parity": parity,
        "warm_boot": {
            "cold": cold,
            "warm": warm,
            "speedup_x": (round(cold["ready_s"] / warm["ready_s"], 2)
                          if warm["ready_s"] else None),
            "bitwise_equal": bool(bitwise_boot),
        },
    }


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny 8-5-2 preset (seconds on CPU)")
    ap.add_argument("--replicas", type=str, default=None,
                    metavar="1,2,4",
                    help="scale-out bench: goodput at each replica "
                         "count + N-replica parity + warm/cold boot")
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--requests", type=int, default=25)
    args = ap.parse_args(argv)
    if args.replicas:
        counts = tuple(int(p) for p in args.replicas.split(","))
        out = run_bench_replicas(replicas=counts)
    elif args.smoke:
        out = run_smoke()
    else:
        out = run_serve_bench(
            n_in=784, hiddens=[300], n_out=10,
            n_clients=args.clients, n_requests=args.requests,
        )
    print(json.dumps(out))
    return 1 if out.get("errors") else 0


if __name__ == "__main__":
    sys.exit(main())
