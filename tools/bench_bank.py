"""Paired slope A/B of batch-mode data paths (r05 roofline work,
VERDICT r04 #1).

Variants (all through the production step implementations):

* ``gather-xla`` / ``gather-pallas`` — today's per-step ``X[ix]``
  gather (train/batch.make_multi_epoch_fn).
* ``bank-xla`` / ``bank-pallas`` — the VERDICT-prescribed per-epoch
  device-side permutation into a scan-ordered bank
  (make_multi_epoch_bank_fn).  Arithmetically the permute (full-bank
  read+write once per epoch) costs exactly what the per-step gather
  did, so this can only win on per-step op overhead.
* ``bankRdbuf-pallas`` — the refresh-group bank epoch with the
  double-buffered HBM→VMEM DMA pipeline kernel
  (train_epoch_dbuf_banked); reported against ``bankR-pallas`` as a
  paired per-repeat delta (``paired_dbuf_vs_grid_pct``).
* ``order-xla`` / ``order-pallas`` — shuffle-once bank + per-epoch
  random block ORDER: zero per-epoch data movement; the Pallas banked
  kernel block-fetches straight from HBM (the only true traffic
  reduction).  Changes the SGD schedule: batch composition is fixed
  at upload (order + boundary rotation only).
* ``seq-xla`` / ``seq-pallas`` — no shuffle at all (sequential
  blocks): the step-cost floor.

Method: production multi-epoch dispatches at two epoch counts with
index arrays pre-placed on device; Δt/Δsteps per repeat cancels the
tunnel's per-dispatch round trip (BASELINE.md timing discipline);
variants interleave round-robin for paired per-repeat deltas; fences
are host transfers.  The per-epoch on-device eval (count_fn) is
included — production pays it.

Run on the real chip:  python tools/bench_bank.py [--quick] [--mnist-only]
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def make_variants(*, n_in, n_hidden, n_out, B, S, momentum, model="ann"):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.ops import pallas_train
    from hpnn_tpu.parallel import dp
    from hpnn_tpu.train import batch as batch_mod

    k, _ = kernel_mod.generate(10958, n_in, [n_hidden], n_out)
    weights = tuple(jnp.asarray(np.asarray(w), jnp.float32) for w in k.weights)
    dw = tuple(jnp.zeros_like(w) for w in weights) if momentum else ()
    lr = dp.default_lr(model, momentum)

    def math_step(w, m, Xb, Tb):
        return dp.train_step_math(w, m, Xb, Tb, model=model,
                                  momentum=momentum, lr=lr, alpha=0.2)

    def pallas_step(w, m, Xb, Tb):
        return pallas_train.train_step_fused_batch(
            w, m, Xb, Tb, model=model, momentum=momentum, lr=lr, alpha=0.2)

    def banked_step(w, m, Xp, Tp, kk):
        return pallas_train.train_step_fused_banked(
            w, m, Xp, Tp, kk, batch=B, model=model, momentum=momentum,
            lr=lr, alpha=0.2)

    def grid_epoch(w, m, Xp, Tp, ord_e):
        return pallas_train.train_epoch_grid_banked(
            w, m, Xp, Tp, ord_e, batch=B, model=model, momentum=momentum,
            lr=lr, alpha=0.2)

    def dbuf_epoch(w, m, Xp, Tp, ord_e):
        return pallas_train.train_epoch_dbuf_banked(
            w, m, Xp, Tp, ord_e, batch=B, model=model, momentum=momentum,
            lr=lr, alpha=0.2)

    count_fn = batch_mod.make_device_count_fn(model=model)

    def make_order_fn(banked):
        """orders (E, S) int32 block ids; no per-epoch data movement."""

        def run(weights, dw, X, T, orders):
            Xr = X.reshape(S, B, n_in)
            Tr = T.reshape(S, B, n_out)

            def epoch(carry, ord_e):
                w, m = carry

                def body(c, kk):
                    w2, m2 = c
                    if banked:
                        w2, m2, l = banked_step(w2, m2, X, T, kk)
                    else:
                        w2, m2, l = math_step(w2, m2, Xr[kk], Tr[kk])
                    return (w2, m2), l

                (w, m), losses = lax.scan(body, (w, m), ord_e)
                return (w, m), (losses, count_fn(w, X, T))

            (weights, dw), (losses, counts) = lax.scan(
                epoch, (weights, dw), orders)
            return weights, dw, losses, counts

        return jax.jit(run)

    def make_seq_fn(banked):
        """No shuffle at all: the step-cost floor.  idx is a dummy
        (E,) epoch counter so the harness shape logic stays shared."""

        def run(weights, dw, X, T, epochs_dummy):
            Xr = X.reshape(S, B, n_in)
            Tr = T.reshape(S, B, n_out)

            def epoch(carry, _e):
                w, m = carry
                if banked:
                    def body(c, kk):
                        w2, m2 = c
                        w2, m2, l = banked_step(w2, m2, X, T, kk)
                        return (w2, m2), l

                    (w, m), losses = lax.scan(
                        body, (w, m), jnp.arange(S, dtype=jnp.int32))
                else:
                    def body2(c, xt):
                        w2, m2 = c
                        w2, m2, l = math_step(w2, m2, xt[0], xt[1])
                        return (w2, m2), l

                    (w, m), losses = lax.scan(body2, (w, m), (Xr, Tr))
                return (w, m), (losses, count_fn(w, X, T))

            (weights, dw), (losses, counts) = lax.scan(
                epoch, (weights, dw), epochs_dummy)
            return weights, dw, losses, counts

        return jax.jit(run)

    fns = {
        "gather-xla": batch_mod.make_multi_epoch_fn(math_step, count_fn),
        "gather-pallas": batch_mod.make_multi_epoch_fn(pallas_step, count_fn),
        # the PRODUCTION r05 path: refresh groups of R epochs (perms
        # (G, n_rows) + orders (G, R, S)); R is encoded in the idx
        # arrays, so the same jit serves any R.  bankR-pallas is the
        # grid-epoch kernel (the production ANN dispatch);
        # bankRscan-pallas keeps the per-step-launch variant it
        # replaced for comparison
        "bankR-xla": batch_mod.make_multi_epoch_bank_fn(
            math_step, count_fn, S, banked=False),
        "bankR-pallas": batch_mod.make_multi_epoch_bank_fn(
            grid_epoch, count_fn, S, banked="grid"),
        # same bank/refresh schedule, but the epoch kernel streams its
        # blocks through a double-buffered HBM->VMEM DMA pipeline
        # (train_epoch_dbuf_banked) instead of grid BlockSpec fetches
        "bankRdbuf-pallas": batch_mod.make_multi_epoch_bank_fn(
            dbuf_epoch, count_fn, S, banked="dbuf"),
        "bankRscan-pallas": batch_mod.make_multi_epoch_bank_fn(
            banked_step, count_fn, S, banked=True),
        "order-xla": make_order_fn(False),
        "order-pallas": make_order_fn(True),
        "seq-xla": make_seq_fn(False),
        "seq-pallas": make_seq_fn(True),
    }

    rng = np.random.RandomState(7)
    n_rows = S * B
    X = jnp.asarray(rng.uniform(-1, 1, (n_rows, n_in)), jnp.float32)
    T = np.full((n_rows, n_out), -1.0, np.float32)
    T[np.arange(n_rows), rng.randint(0, n_out, n_rows)] = 1.0
    T = jnp.asarray(T)
    return weights, dw, X, T, fns


def run_shape(label, *, n_in, n_hidden, n_out, B, S, momentum,
              e_small, e_big, repeats, variants=None):
    import jax
    import jax.numpy as jnp

    weights, dw, X, T, fns = make_variants(
        n_in=n_in, n_hidden=n_hidden, n_out=n_out, B=B, S=S,
        momentum=momentum)
    if variants:
        fns = {k: v for k, v in fns.items() if k in variants}
    n_rows = S * B
    rng = np.random.RandomState(3)

    REFRESH = 8  # production default (HPNN_BANK_REFRESH)

    def put_idx(E, name):
        if name.startswith("bankR"):
            # the slope math assumes exactly E epochs execute
            assert E % REFRESH == 0, (
                f"bankR variants need E % {REFRESH} == 0, got {E}")
            g = E // REFRESH
            perms = np.stack([rng.permutation(n_rows) for _ in range(g)])
            orders = np.stack([
                np.stack([rng.permutation(S) for _ in range(REFRESH)])
                for _ in range(g)
            ])
            return (jax.device_put(jnp.asarray(perms.astype(np.int32))),
                    jax.device_put(jnp.asarray(orders.astype(np.int32))))
        if name.startswith("gather"):
            arr = np.stack([rng.permutation(n_rows).reshape(S, B)
                            for _ in range(E)])
        elif name.startswith("order"):
            arr = np.stack([rng.permutation(S) for _ in range(E)])
        else:  # seq
            arr = np.arange(E)
        return (jax.device_put(jnp.asarray(arr.astype(np.int32))),)

    idx = {
        name: {E: put_idx(E, name) for E in (e_small, e_big)}
        for name in fns
    }

    def timed(fn, E, name):
        t0 = time.perf_counter()
        w, m, losses, counts = fn(weights, dw, X, T, *idx[name][E])
        np.asarray(counts[-1])  # host-transfer fence
        return time.perf_counter() - t0

    # warm both shapes of every variant (compile excluded from timing)
    for name in list(fns):
        for E in (e_small, e_big):
            try:
                timed(fns[name], E, name)
            except Exception as exc:
                print(f"{label} {name}: FAILED {type(exc).__name__}: {exc}",
                      file=sys.stderr)
                fns[name] = None
    fns = {n: f for n, f in fns.items() if f is not None}

    d_steps = (e_big - e_small) * S
    slopes = {n: [] for n in fns}
    for _ in range(repeats):
        for name, fn in fns.items():
            ts = timed(fn, e_small, name)
            tb = timed(fn, e_big, name)
            slopes[name].append((tb - ts) / d_steps * 1e6)
    out = {}
    for name, ss in slopes.items():
        ss_s = sorted(ss)
        out[name] = {
            "us_per_step_median": round(ss_s[len(ss_s) // 2], 3),
            "us_per_step_all": [round(v, 3) for v in ss_s],
        }
    base = slopes.get("gather-pallas")
    if base:
        for name, ss in slopes.items():
            if name == "gather-pallas":
                continue
            deltas = sorted((b - a) / b * 100.0 for a, b in zip(ss, base))
            out[name]["paired_gain_vs_gather_pallas_pct"] = [
                round(d, 1) for d in deltas
            ]
            out[name]["paired_gain_median_pct"] = round(
                deltas[len(deltas) // 2], 1)
    # the double-buffered vs single-buffered banked epoch, as a paired
    # per-repeat delta (same discipline as the gather-pallas baseline):
    # positive % = the DMA pipeline is faster than grid BlockSpec fetch
    sbuf, dbuf = slopes.get("bankR-pallas"), slopes.get("bankRdbuf-pallas")
    if sbuf and dbuf:
        deltas = sorted((b - a) / b * 100.0 for a, b in zip(dbuf, sbuf))
        out["bankRdbuf-pallas"]["paired_dbuf_vs_grid_pct"] = [
            round(d, 1) for d in deltas
        ]
        out["bankRdbuf-pallas"]["paired_dbuf_vs_grid_median_pct"] = round(
            deltas[len(deltas) // 2], 1)
    print(json.dumps({"shape": label, "B": B, "steps_per_epoch": S,
                      "results": out}, indent=1), flush=True)
    return out


def main():
    quick = "--quick" in sys.argv
    rep = 2 if quick else 5
    # epoch counts are multiples of REFRESH so the bankR variants
    # cover exactly E epochs (G·R == E)
    run_shape("mnist 784-300-10 BP", n_in=784, n_hidden=300, n_out=10,
              B=1024, S=60, momentum=False,
              e_small=8, e_big=56 if quick else 224, repeats=rep)
    if "--mnist-only" not in sys.argv:
        run_shape("xrd 851-230-230 BPM", n_in=851, n_hidden=230, n_out=230,
                  B=256, S=15, momentum=True,
                  e_small=24, e_big=224 if quick else 896, repeats=rep)


if __name__ == "__main__":
    main()
