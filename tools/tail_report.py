#!/usr/bin/env python3
"""Slowest-N tail forensics over one or more obs sinks.

``obs_report --spans`` renders every span tree; this tool answers the
on-call question instead: *which requests were slowest, and which
phase of the serving pipeline is to blame?*  It ingests the sinks the
fleet already writes (worker ``HPNN_METRICS`` files and/or the
collector's merged stream — several paths are joined with
``obs_report.merge_events``'s skew-tolerant ordering), reconstructs
the request span trees (sampled/promoted roots from ``HPNN_SAMPLE``
work exactly like full ``HPNN_SPANS`` trees), and prints:

* the **slowest-N request roots** (``serve.request`` /
  ``cluster.request`` — outermost per trace), each with its per-phase
  blame split;
* the **aggregate blame** across every root — where the fleet's tail
  time goes overall;
* with ``--baseline``, a **paired comparison**: the same aggregates
  over a second sink set and the per-phase delta, so "the regression
  is queueing, not device time" is one command
  (``tools/bench_gate.py`` is the CI twin for scalar metrics; this is
  the forensic twin for phase attribution).

Phase classification is by span name over the emitted tree:

=============  ====================================================
phase          span names
=============  ====================================================
queue          ``*.queue`` (batcher admission-to-pop wait)
dispatch       ``*dispatch*`` (device forward, coalesced batch)
spill          ``*spill*`` (host spill/reload traffic)
shed_retry     any span that ended ``failed=Shed|QueueFull`` —
               time burned on a rejected attempt before a retry
other          any other instrumented descendant
gap            root ``dt`` minus the subtree's covered time —
               uninstrumented wall time: network hops, HTTP
               parse, queue-to-thread handoff
=============  ====================================================

Each descendant is charged its **exclusive** time (its ``dt`` minus
its own children's) so nested spans never double-count; the root's
uncovered remainder is the ``gap``.

Usage::

    python tools/tail_report.py run.jsonl [more.jsonl ...]
    python tools/tail_report.py run.jsonl --top 20 --root serve.request
    python tools/tail_report.py run.jsonl --baseline before.jsonl
    python tools/tail_report.py run.jsonl --json

stdlib-only (rides tools/obs_report.py's loaders): the report must
render on a login node with no jax installed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

TOOLS = os.path.dirname(os.path.abspath(__file__))
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)
REPO = os.path.dirname(TOOLS)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import obs_report  # noqa: E402  (sibling tool, loaders reused)

# the classifier core is SHARED with the in-process streaming engine
# (hpnn_tpu/obs/blame.py, HPNN_BLAME): one phase_of / one
# exclusive-time split / one analyze, so the online rolling gauges and
# this offline report can never drift apart.  The package import is
# preferred (one module instance when hpnn_tpu is importable); the
# file-path fallback keeps this report rendering on a login node
# where hpnn_tpu's dependencies are absent — blame.py's core is
# import-clean stdlib, its registry hook deferred to the armed
# publish path.  tests/test_blame.py pins the analyze output against
# a golden sink to hold the refactor behavior-identical.
try:
    from hpnn_tpu.obs import blame as _core
except ImportError:  # bare login node: load the core standalone
    import importlib.util

    _spec = importlib.util.spec_from_file_location(
        "hpnn_tpu_obs_blame",
        os.path.join(REPO, "hpnn_tpu", "obs", "blame.py"))
    _core = importlib.util.module_from_spec(_spec)
    _spec.loader.exec_module(_core)

ROOT_NAMES = _core.ROOT_NAMES
PHASES = _core.PHASES

# rejected-attempt markers (serve/batcher.py raises, spans record the
# exception class in the ``failed`` field)
_SHED_FAILS = _core.SHED_FAILS

_phase_of = _core.phase_of
request_roots = _core.request_roots
blame = _core.split
analyze = _core.analyze


def load_spans(paths: list[str]) -> list[dict]:
    """All spans from the given sinks, cross-process refs resolved
    (several paths go through the skew-tolerant rank merge)."""
    if len(paths) == 1:
        events = obs_report.load_events(paths[0])
    else:
        events = obs_report.merge_events(paths)
    return obs_report.collect_spans(events)


def compare(rep: dict, base: dict) -> dict:
    """The paired ``--baseline`` digest: per-phase percentage-point
    shifts plus the mean-root-latency ratio — "what got slower, and
    is the extra time queueing or device work"."""

    def _mean(r):
        n = r["requests"]
        return (sum(r["blame_total_s"].values()) / n) if n else 0.0

    mean_run, mean_base = _mean(rep), _mean(base)
    return {
        "requests": {"run": rep["requests"], "baseline": base["requests"]},
        "mean_root_s": {"run": round(mean_run, 6),
                        "baseline": round(mean_base, 6),
                        "ratio": (round(mean_run / mean_base, 3)
                                  if mean_base > 0 else None)},
        "blame_pct_delta": {
            p: round(rep["blame_pct"][p] - base["blame_pct"][p], 2)
            for p in PHASES},
    }


def _fmt_phases(phases: dict, dt: float) -> str:
    parts = []
    for p in PHASES:
        v = phases.get(p, 0.0)
        if v <= 0.0:
            continue
        pct = 100.0 * v / dt if dt > 0 else 0.0
        parts.append(f"{p} {pct:4.1f}%")
    return "  ".join(parts)


def render(rep: dict, cmp_doc: dict | None = None) -> str:
    out: list[str] = []
    w = out.append
    w("== tail report ==")
    w(f"spans: {rep['spans']}   request roots: {rep['requests']}")
    if not rep["requests"]:
        w("  (no request roots — was HPNN_SAMPLE or HPNN_SPANS set "
          "on the serving path?)")
        return "\n".join(out) + "\n"
    w("")
    w(f"-- slowest {len(rep['slowest'])} --")
    w(f"  {'dt_ms':>9s} {'name':16s} {'req_id':>14s} {'trace':>17s}"
      f"  blame")
    for r in rep["slowest"]:
        tag = ("P" if r["promoted"] else
               "S" if r["sampled"] else " ")
        flag = f" FAILED({r['failed']})" if r["failed"] else ""
        w(f"  {r['dt'] * 1e3:9.3f} {r['name']:16s}"
          f" {str(r['req_id'] or '-'):>14s}"
          f" {str(r['trace'] or '-'):>17s} {tag}"
          f" {_fmt_phases(r['phases'], r['dt'])}{flag}")
    w("")
    w("-- aggregate blame (all roots) --")
    for p in PHASES:
        w(f"  {p:10s} {rep['blame_total_s'][p]:10.6f} s"
          f"  {rep['blame_pct'][p]:6.2f}%")
    if cmp_doc is not None:
        w("")
        w("-- vs baseline --")
        m = cmp_doc["mean_root_s"]
        ratio = m["ratio"]
        w(f"  roots: {cmp_doc['requests']['run']} vs "
          f"{cmp_doc['requests']['baseline']} baseline")
        w(f"  mean root: {m['run'] * 1e3:.3f} ms vs"
          f" {m['baseline'] * 1e3:.3f} ms"
          + (f"  ({ratio:.2f}x)" if ratio else ""))
        for p in PHASES:
            d = cmp_doc["blame_pct_delta"][p]
            if abs(d) >= 0.01:
                w(f"  {p:10s} {d:+6.2f} pp")
    return "\n".join(out) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Slowest-N request table with per-phase blame "
                    "over HPNN_METRICS sinks")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="metrics JSONL sink(s); several are merged "
                         "into one skew-tolerant timeline")
    ap.add_argument("--top", type=int, default=10, metavar="N",
                    help="rows in the slowest table (default 10)")
    ap.add_argument("--root", action="append", metavar="NAME",
                    help="request-root span name(s) (default: "
                         "serve.request + cluster.request)")
    ap.add_argument("--baseline", nargs="+", metavar="path",
                    help="baseline sink(s): append a paired "
                         "comparison (phase blame deltas)")
    ap.add_argument("--json", action="store_true",
                    help="machine form instead of text")
    args = ap.parse_args(argv)
    roots = tuple(args.root) if args.root else ROOT_NAMES
    try:
        rep = analyze(load_spans(args.paths), top=args.top,
                      root_names=roots)
        cmp_doc = None
        if args.baseline:
            base = analyze(load_spans(args.baseline), top=args.top,
                           root_names=roots)
            cmp_doc = compare(rep, base)
    except OSError as exc:
        sys.stderr.write(f"tail_report: {exc}\n")
        return 1
    if args.json:
        doc = dict(rep)
        if cmp_doc is not None:
            doc["baseline"] = cmp_doc
        json.dump(doc, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render(rep, cmp_doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
