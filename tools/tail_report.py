#!/usr/bin/env python3
"""Slowest-N tail forensics over one or more obs sinks.

``obs_report --spans`` renders every span tree; this tool answers the
on-call question instead: *which requests were slowest, and which
phase of the serving pipeline is to blame?*  It ingests the sinks the
fleet already writes (worker ``HPNN_METRICS`` files and/or the
collector's merged stream — several paths are joined with
``obs_report.merge_events``'s skew-tolerant ordering), reconstructs
the request span trees (sampled/promoted roots from ``HPNN_SAMPLE``
work exactly like full ``HPNN_SPANS`` trees), and prints:

* the **slowest-N request roots** (``serve.request`` /
  ``cluster.request`` — outermost per trace), each with its per-phase
  blame split;
* the **aggregate blame** across every root — where the fleet's tail
  time goes overall;
* with ``--baseline``, a **paired comparison**: the same aggregates
  over a second sink set and the per-phase delta, so "the regression
  is queueing, not device time" is one command
  (``tools/bench_gate.py`` is the CI twin for scalar metrics; this is
  the forensic twin for phase attribution).

Phase classification is by span name over the emitted tree:

=============  ====================================================
phase          span names
=============  ====================================================
queue          ``*.queue`` (batcher admission-to-pop wait)
dispatch       ``*dispatch*`` (device forward, coalesced batch)
spill          ``*spill*`` (host spill/reload traffic)
shed_retry     any span that ended ``failed=Shed|QueueFull`` —
               time burned on a rejected attempt before a retry
other          any other instrumented descendant
gap            root ``dt`` minus the subtree's covered time —
               uninstrumented wall time: network hops, HTTP
               parse, queue-to-thread handoff
=============  ====================================================

Each descendant is charged its **exclusive** time (its ``dt`` minus
its own children's) so nested spans never double-count; the root's
uncovered remainder is the ``gap``.

Usage::

    python tools/tail_report.py run.jsonl [more.jsonl ...]
    python tools/tail_report.py run.jsonl --top 20 --root serve.request
    python tools/tail_report.py run.jsonl --baseline before.jsonl
    python tools/tail_report.py run.jsonl --json

stdlib-only (rides tools/obs_report.py's loaders): the report must
render on a login node with no jax installed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

TOOLS = os.path.dirname(os.path.abspath(__file__))
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

import obs_report  # noqa: E402  (sibling tool, loaders reused)

ROOT_NAMES = ("serve.request", "cluster.request")
PHASES = ("queue", "dispatch", "spill", "shed_retry", "other", "gap")

# rejected-attempt markers (serve/batcher.py raises, spans record the
# exception class in the ``failed`` field)
_SHED_FAILS = ("Shed", "QueueFull")


def _phase_of(span: dict) -> str:
    """Classify one descendant span into a blame phase by name (the
    shed/retry check wins: a failed dispatch attempt is retry waste,
    not useful device time)."""
    if span["fields"].get("failed") in _SHED_FAILS:
        return "shed_retry"
    name = span["name"] or ""
    if name.endswith(".queue") or ".queue" in name:
        return "queue"
    if "dispatch" in name:
        return "dispatch"
    if "spill" in name:
        return "spill"
    return "other"


def load_spans(paths: list[str]) -> list[dict]:
    """All spans from the given sinks, cross-process refs resolved
    (several paths go through the skew-tolerant rank merge)."""
    if len(paths) == 1:
        events = obs_report.load_events(paths[0])
    else:
        events = obs_report.merge_events(paths)
    return obs_report.collect_spans(events)


def request_roots(spans: list[dict],
                  root_names=ROOT_NAMES) -> list[dict]:
    """The outermost request spans: named like a request root AND not
    nested under another collected span (a ``serve.request`` under a
    ``cluster.request`` blames into its parent, not the table)."""
    by_ref = {s["ref"]: s for s in spans if s["ref"] is not None}
    return [s for s in spans
            if s["name"] in root_names
            and by_ref.get(s["parent_ref"]) is None]


def _descendants(root: dict, children_of: dict) -> list[dict]:
    out: list[dict] = []
    stack = [root]
    while stack:
        for child in children_of.get(stack.pop()["ref"], ()):
            out.append(child)
            stack.append(child)
    return out


def blame(root: dict, children_of: dict) -> dict:
    """The per-phase wall-time split of one request root: exclusive
    descendant time charged per phase, the uncovered remainder as
    ``gap``.  Values in seconds; they sum to ``root['dt']`` up to
    clock skew on remote children (each clamped at 0)."""
    phases = {p: 0.0 for p in PHASES}
    for d in _descendants(root, children_of):
        kids = children_of.get(d["ref"], ())
        exclusive = max(0.0, d["dt"] - sum(c["dt"] for c in kids))
        phases[_phase_of(d)] += exclusive
    covered = sum(phases.values())
    phases["gap"] = max(0.0, root["dt"] - covered)
    return phases


def analyze(spans: list[dict], *, top: int = 10,
            root_names=ROOT_NAMES) -> dict:
    """The machine-form report: slowest-N roots with per-phase blame
    plus the aggregate split over every root."""
    children_of: dict = {}
    by_ref = {s["ref"]: s for s in spans if s["ref"] is not None}
    for s in spans:
        parent = by_ref.get(s["parent_ref"])
        if parent is not None and parent is not s:
            children_of.setdefault(parent["ref"], []).append(s)
    roots = request_roots(spans, root_names)
    agg = {p: 0.0 for p in PHASES}
    rows = []
    for root in roots:
        phases = blame(root, children_of)
        for p, v in phases.items():
            agg[p] += v
        rows.append({
            "name": root["name"],
            "ref": root["ref"],
            "dt": root["dt"],
            "req_id": root["fields"].get("req_id"),
            "trace": root["fields"].get("trace"),
            "sampled": bool(root["fields"].get("sampled")),
            "promoted": bool(root["fields"].get("promoted")),
            "failed": root["fields"].get("failed"),
            "phases": {p: round(v, 6) for p, v in phases.items()},
        })
    rows.sort(key=lambda r: -r["dt"])
    total = sum(agg.values())
    return {
        "spans": len(spans),
        "requests": len(roots),
        "slowest": rows[:top],
        "blame_total_s": {p: round(v, 6) for p, v in agg.items()},
        "blame_pct": {p: round(100.0 * v / total, 2) if total else 0.0
                      for p, v in agg.items()},
    }


def compare(rep: dict, base: dict) -> dict:
    """The paired ``--baseline`` digest: per-phase percentage-point
    shifts plus the mean-root-latency ratio — "what got slower, and
    is the extra time queueing or device work"."""

    def _mean(r):
        n = r["requests"]
        return (sum(r["blame_total_s"].values()) / n) if n else 0.0

    mean_run, mean_base = _mean(rep), _mean(base)
    return {
        "requests": {"run": rep["requests"], "baseline": base["requests"]},
        "mean_root_s": {"run": round(mean_run, 6),
                        "baseline": round(mean_base, 6),
                        "ratio": (round(mean_run / mean_base, 3)
                                  if mean_base > 0 else None)},
        "blame_pct_delta": {
            p: round(rep["blame_pct"][p] - base["blame_pct"][p], 2)
            for p in PHASES},
    }


def _fmt_phases(phases: dict, dt: float) -> str:
    parts = []
    for p in PHASES:
        v = phases.get(p, 0.0)
        if v <= 0.0:
            continue
        pct = 100.0 * v / dt if dt > 0 else 0.0
        parts.append(f"{p} {pct:4.1f}%")
    return "  ".join(parts)


def render(rep: dict, cmp_doc: dict | None = None) -> str:
    out: list[str] = []
    w = out.append
    w("== tail report ==")
    w(f"spans: {rep['spans']}   request roots: {rep['requests']}")
    if not rep["requests"]:
        w("  (no request roots — was HPNN_SAMPLE or HPNN_SPANS set "
          "on the serving path?)")
        return "\n".join(out) + "\n"
    w("")
    w(f"-- slowest {len(rep['slowest'])} --")
    w(f"  {'dt_ms':>9s} {'name':16s} {'req_id':>14s} {'trace':>17s}"
      f"  blame")
    for r in rep["slowest"]:
        tag = ("P" if r["promoted"] else
               "S" if r["sampled"] else " ")
        flag = f" FAILED({r['failed']})" if r["failed"] else ""
        w(f"  {r['dt'] * 1e3:9.3f} {r['name']:16s}"
          f" {str(r['req_id'] or '-'):>14s}"
          f" {str(r['trace'] or '-'):>17s} {tag}"
          f" {_fmt_phases(r['phases'], r['dt'])}{flag}")
    w("")
    w("-- aggregate blame (all roots) --")
    for p in PHASES:
        w(f"  {p:10s} {rep['blame_total_s'][p]:10.6f} s"
          f"  {rep['blame_pct'][p]:6.2f}%")
    if cmp_doc is not None:
        w("")
        w("-- vs baseline --")
        m = cmp_doc["mean_root_s"]
        ratio = m["ratio"]
        w(f"  roots: {cmp_doc['requests']['run']} vs "
          f"{cmp_doc['requests']['baseline']} baseline")
        w(f"  mean root: {m['run'] * 1e3:.3f} ms vs"
          f" {m['baseline'] * 1e3:.3f} ms"
          + (f"  ({ratio:.2f}x)" if ratio else ""))
        for p in PHASES:
            d = cmp_doc["blame_pct_delta"][p]
            if abs(d) >= 0.01:
                w(f"  {p:10s} {d:+6.2f} pp")
    return "\n".join(out) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Slowest-N request table with per-phase blame "
                    "over HPNN_METRICS sinks")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="metrics JSONL sink(s); several are merged "
                         "into one skew-tolerant timeline")
    ap.add_argument("--top", type=int, default=10, metavar="N",
                    help="rows in the slowest table (default 10)")
    ap.add_argument("--root", action="append", metavar="NAME",
                    help="request-root span name(s) (default: "
                         "serve.request + cluster.request)")
    ap.add_argument("--baseline", nargs="+", metavar="path",
                    help="baseline sink(s): append a paired "
                         "comparison (phase blame deltas)")
    ap.add_argument("--json", action="store_true",
                    help="machine form instead of text")
    args = ap.parse_args(argv)
    roots = tuple(args.root) if args.root else ROOT_NAMES
    try:
        rep = analyze(load_spans(args.paths), top=args.top,
                      root_names=roots)
        cmp_doc = None
        if args.baseline:
            base = analyze(load_spans(args.baseline), top=args.top,
                           root_names=roots)
            cmp_doc = compare(rep, base)
    except OSError as exc:
        sys.stderr.write(f"tail_report: {exc}\n")
        return 1
    if args.json:
        doc = dict(rep)
        if cmp_doc is not None:
            doc["baseline"] = cmp_doc
        json.dump(doc, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render(rep, cmp_doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
