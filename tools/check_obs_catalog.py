#!/usr/bin/env python3
"""Event-catalog drift lint for the obs subsystem.

docs/observability.md (plus the serving catalog in docs/serving.md
and the fleet catalog in docs/fleet.md) promises a complete
event-name catalog.  That promise rots silently:
an instrumented site added without a docs row leaves operators grepping
a name the docs never mention.  This lint closes the loop — it greps
every ``obs.event/count/gauge/observe/timer`` call site (and the raw
``"ev": "name"`` records the registry/flight emit directly) under
``hpnn_tpu/`` for **literal dotted event names**, collects every
backticked dotted name from the docs pages, and fails when an emitted
name is missing from the docs.

The check is one-directional on purpose: the docs may document names
that only fire on TPU hardware or in multi-process runs (emitted ⊆
documented, not ==).  A documented prefix wildcard like ``serve.*``
covers the whole family.

Dynamic names (a variable first argument) are invisible to the grep —
the emitting style in this repo is literal-names-only precisely so
this lint stays sound.

The tool also carries the checksum-ledger schema lint
(:func:`lint_ledger`): the ledger (``HPNN_LEDGER``,
hpnn_tpu/obs/ledger.py) is a comparison artifact with a FROZEN row
schema — ``tools/ledger_diff.py`` and external tooling parse it — so
any drift is a contract break, not a cosmetic change.

It also carries the performance-attribution schema lint
(:func:`lint_perf`): ``span.end`` / ``compile.cost`` / ``perf.*``
records (HPNN_SPANS / HPNN_COST, hpnn_tpu/obs/{spans,cost}.py) feed
``tools/obs_report.py --spans`` and external dashboards, so their row
shapes — and the child-inside-parent span nesting the latency tree
depends on — are checked the same way the ledger rows are.

And the SLO/shedding schema lint (:func:`lint_slo`): ``slo.*``
gauges (HPNN_SLO_MS, hpnn_tpu/obs/slo.py), ``serve.shed`` counts and
the request-id span attributes feed the load harness
(tools/loadgen.py) and /healthz verdicts, so their shapes are checked
too.

And the chaos/durability schema lint (:func:`lint_chaos`): the
``chaos.inject`` counts (HPNN_CHAOS, hpnn_tpu/chaos/), the promotion
WAL records (``wal.commit`` / ``wal.skip``, HPNN_WAL_DIR,
hpnn_tpu/online/wal.py), the checkpoint/restore/drain events, and the
``drill.*`` rows ``tools/chaos_drill.py`` writes are the audit trail
for *deliberate* failures — a drill row that can't say what it
injected, what was lost, or whether the restart resumed bitwise makes
the whole exercise theater, so their shapes are frozen like the
ledger rows (docs/resilience.md).

And the online-learning schema lint (:func:`lint_online`): the
``online.*`` records (hpnn_tpu/online/, docs/online.md) are the audit
trail for *weight promotions in a live serving process* — a promote
event with a non-monotone version, a reject without a reason, or a
rollback that doesn't say which version it restored makes an incident
unreconstructable, so their shapes (and the promote/rollback version
bookkeeping) are frozen the same way the ledger rows are.

And the low-precision / multi-round schema lint (:func:`lint_quant`):
the ``numerics.quant_err`` gauges, ``serve.precision`` events,
``fleet.multi_round`` events and ``train.multi_round`` spans
(train/fleet.py, serve/engine.py, serve/registry.py,
docs/performance.md) are how an operator proves a bf16/int8 policy or
a K-round scanned dispatch is behaving — a quant-err gauge that can go
NaN unnoticed, or a multi-round event that can't say K, defeats the
"measured, never assumed" error-bound contract, so their shapes are
frozen too.

And the multi-replica routing schema lint
(:func:`lint_serve_replicas`): the ``router.*`` / ``replica.*``
records (hpnn_tpu/serve/router.py, docs/serving.md "Scale-out") are
how an operator reconstructs a placement decision — a route count
without a rank, a shed-around without a reason, or an outstanding
gauge that can go negative makes a capacity incident unattributable,
so their shapes are frozen too.

And the fleet-telemetry schema lint (:func:`lint_fleet`): the
``trace.adopt`` counts (obs/propagate.py), the ``collector.*``
push/drop/recv accounting (obs/collector.py) and the ``alert.fire`` /
``alert.resolve`` events (obs/alerts.py, HPNN_ALERTS) are how an
operator reconstructs a fleet incident — an alert that double-fires
or resolves thin air, a shed without a reason, or a worker record
without a finite pid makes the telemetry plane itself untrustworthy,
so their shapes (and the per-rule fire/resolve pairing) are frozen
too (docs/observability.md "Fleet telemetry").

And the cross-host cluster schema lint (:func:`lint_cluster`): the
``fleet.worker_up`` / ``fleet.worker_down`` membership edges
(hpnn_tpu/fleet/worker.py) and the ``fleet.scale_up`` /
``fleet.scale_down`` autoscaler actions (hpnn_tpu/fleet/autoscaler.py,
docs/serving.md "Cross-host fleet") are how an operator reconstructs a
width change — a worker death without a paired admission, a spawn
without its latency, or a scale event with an infinite or shrinking
"grow" width makes a capacity incident unauditable, so their shapes
(and the per-rank up/down pairing) are frozen too.

And the tail-latency forensics schema lint (:func:`lint_forensics`):
the ``forensics.capture`` / ``forensics.capture_done`` capsule edges
(obs/triggers.py, HPNN_CAPSULE_DIR), the ``forensics.capture_skipped``
suppression census, the ``forensics.tail_promote`` retro-promotion
counts (obs/forensics.py, HPNN_SAMPLE) and the exemplar blocks inside
``obs.summary`` aggregates are how an operator goes from a bad
histogram bucket to the one request that produced it — a capture that
never finishes, a skip without a reason, or an exemplar with a NaN
value severs that link, so their shapes (and the per-process
capture/capture_done pairing) are frozen too (docs/observability.md
"Forensics").

And the drift-detection schema lint (:func:`lint_drift`): the
``drift.score`` / ``drift.pred_shift`` / ``drift.eval_decay`` gauges
and ``online.drift`` breach events (obs/drift.py, HPNN_DRIFT), plus
the ``online.eval_resident`` sentinel food (online/trainer.py), are
how an operator proves a stream moved — a NaN score can never cross
an alert rule, a breach event that can't say which detector or
kernel is unactionable, and a drift-alert capsule without its
``drift.json`` sketch dump severs the alert→evidence link — so
their shapes are frozen too (docs/observability.md "Drift
detection").

And the multi-tenant hosting schema lint (:func:`lint_tenant`): the
``tenant.page_in`` / ``tenant.page_out`` paging edges and
``tenant.page_in_ms`` cold-hit histogram (hpnn_tpu/tenant/pager.py),
the ``tenant.resident`` gauge with its cap-bounded residency
invariant, the ``tenant.p99_ms`` / ``tenant.shed_rate`` per-tenant
SLO gauges (tenant/quota.py), and ``serve.shed reason=quota``
refusals that must name their tenant are how an operator audits a
10k-kernel host — an over-cap residency gauge or an anonymous quota
shed makes the bounded-memory and isolation claims unverifiable, so
their shapes are frozen too (docs/tenancy.md).

And the tenant-metering schema lint (:func:`lint_meter`): the
``meter.sketch`` records (obs/meter.py, HPNN_METER) carry the
per-worker space-saving sketches the fleet merge and the
``tenant_report`` blame table are reconstructed from — a governed
``export`` view that exceeds its own top-K bound re-opens the
cardinality hole the governor exists to close, a missing ``_other``
rollup when tenants outnumber K silently drops the long tail's mass,
a non-finite accumulator or a ``count < err`` entry poisons every
downstream merge, and an export that doesn't conserve the axis total
makes the "every column sums to the fleet total" contract a lie — so
their shapes are frozen too (docs/observability.md "Tenant
metering").

And the self-tuning schema lint (:func:`lint_tune`): the
``tune.apply`` / ``tune.rollback`` / ``tune.decision`` records
(hpnn_tpu/tune/engine.py, HPNN_TUNE) are the audit trail of a plane
that moves *production serving knobs on its own* — an apply outside
the action enum, a rollback that pairs no apply id, a decision whose
verdict is off the closed enum, or a blame-share gauge outside
[0, 100] makes the "every autonomous change is attributable and
reversible" claim unverifiable, so their shapes are frozen too
(docs/selftuning.md).

And the connection-plane schema lint (:func:`lint_conn`): the
``conn.open`` / ``conn.close`` / ``conn.guard_kill`` records
(hpnn_tpu/serve/conn.py, HPNN_CONN_*) are the wire-level account of
who connected and how it ended — an open without its paired close
(same ``id``) is a leaked connection the census can't see, a close
reason off the closed enum is an unclassifiable death, a guard kill
outside slowloris/stall is a guard nobody documented, and a
non-finite ``conn.active`` / ``conn.oldest_s`` gauge poisons the
alert rules watching them — so their shapes are frozen too
(docs/serving.md "Connection plane").

Run standalone (exit code for CI)::

    python tools/check_obs_catalog.py [--ledger PATH] [--perf PATH]
        [--slo PATH] [--online PATH] [--quant PATH] [--chaos PATH]
        [--serve-replicas PATH] [--fleet PATH] [--cluster PATH]
        [--forensics PATH] [--drift PATH] [--tenant PATH]
        [--meter PATH] [--tune PATH] [--conn PATH]

or via the tier-1 suite (tests/test_obs_catalog.py).  stdlib-only.
"""

from __future__ import annotations

import os
import re
import sys

# obs.event("a.b", ...), count/gauge/observe/timer — any dotted-prefix
# caller spelling (obs.timer, registry.event, plain event) counts
CALL_RE = re.compile(
    r"(?:[\w.]+\.)?(?:event|count|gauge|observe|timer)\(\s*"
    r"[\"']([a-z0-9_]+(?:\.[a-z0-9_]+)+)[\"']"
)
# records built by hand: {"ev": "obs.open", ...}
RAW_RE = re.compile(
    r"[\"']ev[\"']\s*:\s*[\"']([a-z0-9_]+(?:\.[a-z0-9_]+)+)[\"']"
)
# docs side: every `backticked.dotted.name`; `family.*` is a wildcard
DOC_RE = re.compile(
    r"`([a-z0-9_]+(?:\.(?:[a-z0-9_]+|\*))+)`"
)

DOC_PAGES = ("docs/observability.md", "docs/serving.md",
             "docs/fleet.md", "docs/online.md", "docs/resilience.md",
             "docs/performance.md", "docs/analysis.md",
             "docs/tenancy.md", "docs/selftuning.md")
SRC_DIR = "hpnn_tpu"


def emitted_names(root: str) -> dict[str, list[str]]:
    """name -> ["path:line", ...] for every literal emission site."""
    names: dict[str, list[str]] = {}
    src = os.path.join(root, SRC_DIR)
    for dirpath, _dirs, files in os.walk(src):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            with open(path) as fp:
                for lineno, line in enumerate(fp, 1):
                    for rx in (CALL_RE, RAW_RE):
                        for m in rx.finditer(line):
                            names.setdefault(m.group(1), []).append(
                                f"{rel}:{lineno}")
    return names


def documented_names(root: str) -> set[str]:
    names: set[str] = set()
    for page in DOC_PAGES:
        path = os.path.join(root, page)
        try:
            with open(path) as fp:
                text = fp.read()
        except OSError:
            continue
        names.update(DOC_RE.findall(text))
    return names


def _covered(name: str, documented: set[str]) -> bool:
    if name in documented:
        return True
    # `serve.*` in the docs covers serve.request, serve.compile, ...
    parts = name.split(".")
    for i in range(len(parts) - 1, 0, -1):
        if ".".join(parts[:i]) + ".*" in documented:
            return True
    return False


def check(root: str) -> list[str]:
    """Run the lint; returns a list of failure strings (empty = pass)."""
    emitted = emitted_names(root)
    documented = documented_names(root)
    if not emitted:
        return [f"no emission sites found under {SRC_DIR}/ — "
                "the call-site regex is broken"]
    if not documented:
        return ["no documented names found in "
                + " / ".join(DOC_PAGES)]
    failures = []
    for name in sorted(emitted):
        if not _covered(name, documented):
            sites = ", ".join(emitted[name][:3])
            failures.append(
                f"event {name!r} (emitted at {sites}) is missing from "
                f"the docs catalog ({' / '.join(DOC_PAGES)})")
    return failures


# the frozen ledger.round row contract (obs/ledger.py docstring)
LEDGER_REQUIRED = {"ts", "ev", "row", "step", "where", "rank", "nan",
                   "inf", "checksums", "shapes"}


def lint_ledger(path: str) -> list[str]:
    """Schema-lint one checksum-ledger file; returns failure strings.

    Checks: every line is a JSON object; the first is a ``ledger.open``
    header carrying path/pid/rank; every ``ledger.round`` row has the
    required keys, a ``row`` index monotone from 0, name→number
    checksums and name→shape-list shapes over the SAME tensor set, and
    non-negative integer nan/inf censuses."""
    import json

    failures = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read ledger {path!r}: {exc}"]
    if not lines:
        return [f"ledger {path!r} is empty"]
    recs = []
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError as exc:
            failures.append(f"line {i + 1}: not JSON ({exc})")
            continue
        if not isinstance(rec, dict):
            failures.append(f"line {i + 1}: not a JSON object")
            continue
        recs.append(rec)
    if recs and recs[0].get("ev") != "ledger.open":
        failures.append(
            f"first record is {recs[0].get('ev')!r}, want 'ledger.open'")
    elif recs:
        for key in ("ts", "path", "pid", "rank"):
            if key not in recs[0]:
                failures.append(f"ledger.open header missing {key!r}")
    next_row = 0
    for i, rec in enumerate(recs):
        if rec.get("ev") != "ledger.round":
            continue
        at = f"record {i + 1}"
        missing = LEDGER_REQUIRED - set(rec)
        if missing:
            failures.append(f"{at}: missing keys {sorted(missing)}")
            continue
        if rec["row"] != next_row:
            failures.append(
                f"{at}: row {rec['row']!r} not monotone (want {next_row})")
        else:
            next_row += 1
        cs, sh = rec["checksums"], rec["shapes"]
        if not isinstance(cs, dict) or not cs:
            failures.append(f"{at}: checksums is not a non-empty object")
            continue
        if not isinstance(sh, dict) or set(sh) != set(cs):
            failures.append(
                f"{at}: shapes keys {sorted(sh) if isinstance(sh, dict) else sh!r} "
                f"!= checksums keys {sorted(cs)}")
        for name, v in cs.items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                failures.append(f"{at}: checksum {name!r} is not a number")
        if isinstance(sh, dict):
            for name, v in sh.items():
                if (not isinstance(v, list) or not v
                        or not all(isinstance(d, int) and d >= 1
                                   for d in v)):
                    failures.append(
                        f"{at}: shape {name!r} is not a list of "
                        "positive ints")
        for census in ("nan", "inf"):
            v = rec[census]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                failures.append(
                    f"{at}: {census} census is not a non-negative int")
    return failures


# the performance-attribution record contracts (obs/spans.py,
# obs/cost.py; docs/observability.md "Performance attribution")
SPAN_REQUIRED = {"ts", "ev", "kind", "span", "parent", "name", "t0",
                 "dt"}
COST_REQUIRED = {"ts", "ev", "kind", "exe", "units"}
PERF_GAUGES = ("perf.flops_per_s", "perf.mfu", "perf.bytes_per_s")
# span t0/dt round to 1 µs on emission; allow that much slack per edge
# when checking child containment
_SPAN_EPS = 2e-6


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def lint_perf(path: str) -> list[str]:
    """Schema-lint the span/cost/perf records of one metrics sink.

    Checks, per record kind:

    * ``span.end`` — required keys present; ``span`` a positive
      unique int; ``parent`` null or an int; ``t0``/``dt``
      non-negative numbers; and when the parent span is in the same
      file, the child's [t0, t0+dt] interval sits inside the
      parent's (honest nesting is what makes child-sum ≤ parent hold
      in the report).
    * ``compile.cost`` — required keys present; ``exe`` a unique
      string (the catalog is first-call-wins, so a duplicate means
      double emission); ``flops``/``bytes_accessed`` numbers when
      present and not an error record; ``units`` a positive int.
    * ``perf.*`` gauges — ``kind == "gauge"``, finite non-negative
      ``value``, and an ``exe`` field attributing the rate.
    * ``fleet.*`` records — gauges (``fleet.size``) carry a finite
      ``value`` ≥ 1 (an empty fleet is a grouping bug); and every
      fleet-named span (``name`` containing ``fleet``) carries a
      ``members`` count ≥ 1, so dashboards can always attribute a
      fleet dispatch to its width (docs/fleet.md).

    Other records pass through untouched — the sink interleaves every
    obs family.  Returns failure strings (empty = pass).
    """
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]
    spans: dict[int, dict] = {}
    span_recs: list[tuple[str, dict]] = []
    cost_exes: set[str] = set()
    n_perf = 0
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if ev == "span.end":
            missing = SPAN_REQUIRED - set(rec)
            if missing:
                failures.append(
                    f"{at}: span.end missing keys {sorted(missing)}")
                continue
            sid = rec["span"]
            if not isinstance(sid, int) or isinstance(sid, bool) \
                    or sid < 1:
                failures.append(
                    f"{at}: span id {sid!r} is not a positive int")
                continue
            if sid in spans:
                failures.append(f"{at}: span id {sid} emitted twice")
            parent = rec["parent"]
            if parent is not None and (not isinstance(parent, int)
                                       or isinstance(parent, bool)):
                failures.append(
                    f"{at}: parent {parent!r} is not null or an int")
            if not _num(rec["t0"]) or rec["t0"] < 0:
                failures.append(f"{at}: t0 is not a non-negative "
                                "number")
                continue
            if not _num(rec["dt"]) or rec["dt"] < 0:
                failures.append(f"{at}: dt is not a non-negative "
                                "number")
                continue
            spans[sid] = rec
            span_recs.append((at, rec))
            # fleet-named spans must say how wide the fleet was
            name = rec.get("name")
            if isinstance(name, str) and "fleet" in name:
                members = rec.get("members")
                if not isinstance(members, int) \
                        or isinstance(members, bool) or members < 1:
                    failures.append(
                        f"{at}: fleet span {name!r} members "
                        f"{members!r} is not an int >= 1")
        elif ev == "compile.cost":
            missing = COST_REQUIRED - set(rec)
            if missing:
                failures.append(
                    f"{at}: compile.cost missing keys "
                    f"{sorted(missing)}")
                continue
            exe = rec["exe"]
            if not isinstance(exe, str) or not exe:
                failures.append(f"{at}: exe is not a string")
                continue
            if exe in cost_exes:
                failures.append(
                    f"{at}: duplicate compile.cost for exe {exe!r} "
                    "(the catalog is first-call-wins)")
            cost_exes.add(exe)
            units = rec["units"]
            if not isinstance(units, int) or isinstance(units, bool) \
                    or units < 1:
                failures.append(
                    f"{at}: units {units!r} is not a positive int")
            if "error" not in rec:
                for key in ("flops", "bytes_accessed"):
                    v = rec.get(key)
                    if v is not None and not _num(v):
                        failures.append(
                            f"{at}: {key} {v!r} is not a number")
        elif isinstance(ev, str) and ev.startswith("perf."):
            n_perf += 1
            if rec.get("kind") != "gauge":
                failures.append(
                    f"{at}: {ev} kind {rec.get('kind')!r} != 'gauge'")
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 0:
                failures.append(
                    f"{at}: {ev} value {v!r} is not a finite "
                    "non-negative number")
            if "exe" not in rec:
                failures.append(
                    f"{at}: {ev} has no exe field — the rate is "
                    "unattributable")
        elif isinstance(ev, str) and ev.startswith("fleet.") \
                and rec.get("kind") == "gauge":
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 1:
                failures.append(
                    f"{at}: {ev} value {v!r} is not a finite number "
                    ">= 1 (an empty fleet is a grouping bug)")
    # nesting: a child whose parent finished in this file must sit
    # inside the parent's interval (both clocks are the same
    # time.perf_counter, so the comparison is meaningful)
    for at, rec in span_recs:
        parent = spans.get(rec["parent"])
        if parent is None:
            continue
        lo = parent["t0"] - _SPAN_EPS
        hi = parent["t0"] + parent["dt"] + _SPAN_EPS
        if rec["t0"] < lo or rec["t0"] + rec["dt"] > hi:
            failures.append(
                f"{at}: span {rec['span']} ({rec['name']!r}) "
                f"[{rec['t0']}, {rec['t0'] + rec['dt']}] escapes "
                f"parent {rec['parent']} "
                f"[{parent['t0']}, {parent['t0'] + parent['dt']}]")
    if not spans and not cost_exes and not n_perf:
        failures.append(
            f"sink {path!r} has no span.end / compile.cost / perf.* "
            "records — were HPNN_SPANS / HPNN_COST set?")
    return failures


# the SLO/shedding record contracts (obs/slo.py, serve/batcher.py;
# docs/observability.md "SLOs and load")
SLO_GAUGES = ("slo.p50_ms", "slo.p99_ms", "slo.attainment",
              "slo.burn_rate", "slo.window_requests")


def lint_slo(path: str) -> list[str]:
    """Schema-lint the SLO/shedding records of one metrics sink.

    Checks, per record:

    * ``slo.*`` gauges — ``kind == "gauge"``, finite ``value``;
      ``slo.attainment`` in [0, 1]; ``slo.burn_rate`` and the
      latency/window gauges non-negative.
    * ``serve.shed`` — ``kind == "count"``; non-empty string
      ``batcher`` and ``reason``; ``req_id``, when present, a
      non-empty string.
    * ``span.end`` records named ``serve.request``/``serve.queue`` —
      a ``req_id`` field, when present, is a non-empty string (the
      edge-minted id contract that ``obs_report --spans --req``
      relies on).

    A sink with neither ``slo.*`` gauges nor ``serve.shed`` records
    fails — this lint only makes sense on a run where the SLO layer
    was armed (``HPNN_SLO_MS`` + shed thresholds).  Returns failure
    strings (empty = pass).
    """
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]
    n_slo = 0
    n_shed = 0
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if isinstance(ev, str) and ev.startswith("slo."):
            n_slo += 1
            if rec.get("kind") != "gauge":
                failures.append(
                    f"{at}: {ev} kind {rec.get('kind')!r} != 'gauge'")
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v):
                failures.append(
                    f"{at}: {ev} value {v!r} is not a finite number")
                continue
            if ev == "slo.attainment" and not 0.0 <= v <= 1.0:
                failures.append(
                    f"{at}: slo.attainment {v!r} outside [0, 1]")
            elif ev != "slo.attainment" and v < 0:
                failures.append(
                    f"{at}: {ev} value {v!r} is negative")
        elif ev == "serve.shed":
            n_shed += 1
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: serve.shed kind {rec.get('kind')!r} "
                    "!= 'count'")
            for key in ("batcher", "reason"):
                v = rec.get(key)
                if not isinstance(v, str) or not v:
                    failures.append(
                        f"{at}: serve.shed {key} {v!r} is not a "
                        "non-empty string")
            rid = rec.get("req_id")
            if rid is not None and (not isinstance(rid, str)
                                    or not rid):
                failures.append(
                    f"{at}: serve.shed req_id {rid!r} is not a "
                    "non-empty string")
        elif ev == "span.end" and rec.get("name") in ("serve.request",
                                                      "serve.queue"):
            rid = rec.get("req_id")
            if rid is not None and (not isinstance(rid, str)
                                    or not rid):
                failures.append(
                    f"{at}: {rec.get('name')} span req_id {rid!r} is "
                    "not a non-empty string")
    if not n_slo and not n_shed:
        failures.append(
            f"sink {path!r} has no slo.* gauges or serve.shed records "
            "— were HPNN_SLO_MS and the shed thresholds set?")
    return failures


# the online-learning record contracts (hpnn_tpu/online/,
# serve/registry.py install; docs/online.md "Event catalog")
ONLINE_GAUGES = ("online.buffer_depth", "online.staleness_s",
                 "online.train_loss", "online.candidate_loss",
                 "online.resident_loss", "online.promote_latency_ms")
ONLINE_COUNTS = ("online.ingest", "online.drop", "online.round_failed")
REJECT_REASONS = ("sentinel", "margin", "eval")


def _pos_int(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool) and v >= 1


def lint_online(path: str) -> list[str]:
    """Schema-lint the online-learning records of one metrics sink.

    Checks, per record:

    * ``online.*`` gauges — ``kind == "gauge"``, finite ``value``;
      depth / staleness / promote-latency gauges non-negative.
    * ``online.ingest`` / ``online.drop`` / ``online.round_failed``
      counts — ``kind == "count"``, positive increment ``n``.
    * ``online.round`` — ``members``/``groups``/``rows`` ints >= 1
      (a round event only fires when something trained), non-negative
      int ``promoted``/``rejected``/``rolled_back`` tallies, and a
      non-negative ``train_s``.
    * ``online.promote`` — non-empty ``kernel``; int versions with
      ``to_version > from_version`` (promotion always bumps); finite
      ``cand_loss`` strictly below ``res_loss`` (the margin gate can
      never promote a non-improvement).
    * ``online.reject`` — non-empty ``kernel``; ``reason`` one of
      ``sentinel`` / ``margin`` / ``eval``.
    * ``online.rollback`` — non-empty ``kernel``; int versions with
      ``to_version > from_version`` (rollback *re-installs*, it never
      rewinds the version counter); int ``restored`` (the version
      whose weights came back); non-empty ``reason``.
    * ``serve.install`` counts — ``kind == "count"``, non-empty
      ``kernel``, ``version`` an int >= 1.
    * ``span.end`` records named ``online.train_round`` — ``members``
      and ``rows`` ints >= 1, so a slow round is attributable.

    A sink with no ``online.*`` records fails — this lint only makes
    sense on a run where the online layer actually fed / trained /
    gated.  Returns failure strings (empty = pass).
    """
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]
    n_online = 0
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if isinstance(ev, str) and ev.startswith("online."):
            n_online += 1
        if ev in ONLINE_GAUGES:
            if rec.get("kind") != "gauge":
                failures.append(
                    f"{at}: {ev} kind {rec.get('kind')!r} != 'gauge'")
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v):
                failures.append(
                    f"{at}: {ev} value {v!r} is not a finite number")
            elif ev in ("online.buffer_depth", "online.staleness_s",
                        "online.promote_latency_ms") and v < 0:
                failures.append(f"{at}: {ev} value {v!r} is negative")
        elif ev in ONLINE_COUNTS:
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: {ev} kind {rec.get('kind')!r} != 'count'")
            if not _pos_int(rec.get("n")):
                failures.append(
                    f"{at}: {ev} increment {rec.get('n')!r} is not a "
                    "positive int")
        elif ev == "online.round":
            for key in ("members", "groups", "rows"):
                if not _pos_int(rec.get(key)):
                    failures.append(
                        f"{at}: online.round {key} {rec.get(key)!r} "
                        "is not an int >= 1")
            for key in ("promoted", "rejected", "rolled_back"):
                v = rec.get(key)
                if not isinstance(v, int) or isinstance(v, bool) \
                        or v < 0:
                    failures.append(
                        f"{at}: online.round {key} {v!r} is not a "
                        "non-negative int")
            ts = rec.get("train_s")
            if not _num(ts) or ts < 0:
                failures.append(
                    f"{at}: online.round train_s {ts!r} is not a "
                    "non-negative number")
        elif ev in ("online.promote", "online.rollback"):
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: {ev} kernel {k!r} is not a non-empty "
                    "string")
            fv, tv = rec.get("from_version"), rec.get("to_version")
            if not _pos_int(tv) or not isinstance(fv, int) \
                    or isinstance(fv, bool) or not tv > fv:
                failures.append(
                    f"{at}: {ev} versions {fv!r} -> {tv!r} do not "
                    "bump (install always advances the counter)")
            if ev == "online.promote":
                cl, rl = rec.get("cand_loss"), rec.get("res_loss")
                if not _num(cl) or not _num(rl) \
                        or not math.isfinite(cl) or not cl < rl:
                    failures.append(
                        f"{at}: online.promote cand_loss {cl!r} is "
                        f"not finitely below res_loss {rl!r}")
            else:
                if not isinstance(rec.get("restored"), int) \
                        or isinstance(rec.get("restored"), bool):
                    failures.append(
                        f"{at}: online.rollback restored "
                        f"{rec.get('restored')!r} is not an int")
                r = rec.get("reason")
                if not isinstance(r, str) or not r:
                    failures.append(
                        f"{at}: online.rollback reason {r!r} is not "
                        "a non-empty string")
        elif ev == "online.reject":
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: online.reject kernel {k!r} is not a "
                    "non-empty string")
            if rec.get("reason") not in REJECT_REASONS:
                failures.append(
                    f"{at}: online.reject reason "
                    f"{rec.get('reason')!r} not in "
                    f"{'/'.join(REJECT_REASONS)}")
        elif ev == "serve.install":
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: serve.install kind {rec.get('kind')!r} "
                    "!= 'count'")
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: serve.install kernel {k!r} is not a "
                    "non-empty string")
            if not _pos_int(rec.get("version")):
                failures.append(
                    f"{at}: serve.install version "
                    f"{rec.get('version')!r} is not an int >= 1")
        elif ev == "span.end" and rec.get("name") == "online.train_round":
            for key in ("members", "rows"):
                if not _pos_int(rec.get(key)):
                    failures.append(
                        f"{at}: online.train_round span {key} "
                        f"{rec.get(key)!r} is not an int >= 1")
    if not n_online:
        failures.append(
            f"sink {path!r} has no online.* records — did the online "
            "layer feed / train / gate during this run?")
    return failures


# the low-precision / multi-round record contracts (train/fleet.py,
# serve/engine.py, serve/registry.py; docs/performance.md)
QUANT_PRECISIONS = ("bf16", "f32", "f64", "int8", "native")
QUANT_WHERES = ("serve", "fleet")
PRECISION_SOURCES = ("set", "warmup")


def lint_quant(path: str) -> list[str]:
    """Schema-lint the low-precision / multi-round records of one
    metrics sink.

    Checks, per record:

    * ``numerics.quant_err`` — ``kind == "gauge"``, finite
      NON-NEGATIVE ``value`` (it is a max-abs error: NaN/inf or a
      negative reading means the probe itself is broken), and a
      ``where`` of ``serve`` (engine warmup probe) or ``fleet``
      (:func:`quant_probe_fleet`).
    * ``serve.precision`` events — ``kind == "event"``, non-empty
      ``kernel``, ``precision`` one of
      ``bf16/f32/f64/int8/native``, ``version`` an int >= 0, and
      ``source`` ``set`` (registry retag) or ``warmup`` (engine).
    * ``fleet.multi_round`` events — ``members``/``k``/``epochs``
      ints >= 1 (the whole point of the scanned dispatch is K >= 1
      rounds over a live fleet) and a non-negative ``dispatch_s``.
    * ``span.end`` records named ``train.multi_round`` — ``members``
      and ``k`` ints >= 1, so a slow scanned dispatch is
      attributable to its round count.

    A sink with none of these records fails — this lint only makes
    sense on a run where the multi-round scan or a low-precision
    policy was actually armed (``HPNN_ONLINE_SCAN_K`` /
    ``HPNN_SERVE_DTYPE`` / a per-entry precision).  Returns failure
    strings (empty = pass).
    """
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]
    n_quant = 0
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if ev == "numerics.quant_err":
            n_quant += 1
            if rec.get("kind") != "gauge":
                failures.append(
                    f"{at}: numerics.quant_err kind "
                    f"{rec.get('kind')!r} != 'gauge'")
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 0:
                failures.append(
                    f"{at}: numerics.quant_err value {v!r} is not a "
                    "finite non-negative number")
            if rec.get("where") not in QUANT_WHERES:
                failures.append(
                    f"{at}: numerics.quant_err where "
                    f"{rec.get('where')!r} not in "
                    f"{'/'.join(QUANT_WHERES)}")
        elif ev == "serve.precision":
            n_quant += 1
            if rec.get("kind") != "event":
                failures.append(
                    f"{at}: serve.precision kind "
                    f"{rec.get('kind')!r} != 'event'")
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: serve.precision kernel {k!r} is not a "
                    "non-empty string")
            if rec.get("precision") not in QUANT_PRECISIONS:
                failures.append(
                    f"{at}: serve.precision precision "
                    f"{rec.get('precision')!r} not in "
                    f"{'/'.join(QUANT_PRECISIONS)}")
            v = rec.get("version")
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                failures.append(
                    f"{at}: serve.precision version {v!r} is not a "
                    "non-negative int")
            if rec.get("source") not in PRECISION_SOURCES:
                failures.append(
                    f"{at}: serve.precision source "
                    f"{rec.get('source')!r} not in "
                    f"{'/'.join(PRECISION_SOURCES)}")
        elif ev == "fleet.multi_round":
            n_quant += 1
            for key in ("members", "k", "epochs"):
                if not _pos_int(rec.get(key)):
                    failures.append(
                        f"{at}: fleet.multi_round {key} "
                        f"{rec.get(key)!r} is not an int >= 1")
            ds = rec.get("dispatch_s")
            if not _num(ds) or ds < 0:
                failures.append(
                    f"{at}: fleet.multi_round dispatch_s {ds!r} is "
                    "not a non-negative number")
        elif ev == "span.end" and rec.get("name") == "train.multi_round":
            n_quant += 1
            for key in ("members", "k"):
                if not _pos_int(rec.get(key)):
                    failures.append(
                        f"{at}: train.multi_round span {key} "
                        f"{rec.get(key)!r} is not an int >= 1")
    if not n_quant:
        failures.append(
            f"sink {path!r} has no multi-round / precision records — "
            "were HPNN_ONLINE_SCAN_K / HPNN_SERVE_DTYPE (or a "
            "per-entry precision) armed during this run?")
    return failures


# the chaos/durability record contracts (hpnn_tpu/chaos/,
# hpnn_tpu/online/wal.py, tools/chaos_drill.py; docs/resilience.md)
CHAOS_ACTIONS = ("kill", "raise", "delay", "nan")
WAL_SKIP_REASONS = ("sig", "torn", "magic")
DRILL_EVS = ("drill.kill9", "drill.reload", "drill.sentinel",
             "drill.replica", "drill.alert", "drill.worker",
             "drill.capsule", "drill.drift", "drill.torn")


def lint_chaos(path: str) -> list[str]:
    """Schema-lint the chaos/durability records of one JSONL file —
    a metrics sink from a chaos-armed run, a promotion WAL, a drill
    output, or any interleaving of the three.

    Checks, per record:

    * ``chaos.inject`` counts — ``kind == "count"``, positive ``n``,
      non-empty ``seam``, ``action`` one of kill/raise/delay/nan (an
      injection that can't say what it did where is unauditable).
    * ``wal.commit`` — non-empty ``kernel``; ``version`` an int >= 1;
      ``ckpt`` a non-empty ``.ckpt`` basename; ``sig`` a 2-list of
      ints (the registry staleness signature); non-empty ``reason``.
    * ``wal.skip`` counts — ``reason`` one of sig/torn/magic.
    * ``online.checkpoint`` / ``online.restore`` — non-empty
      ``kernel``, version int >= 1, non-empty ``ckpt``;
      ``online.checkpoint_failed`` counts a non-empty ``reason``.
    * ``serve.drain`` — an int ``signal``; ``serve.unready`` — a
      non-empty ``reason``.
    * ``drill.*`` rows — a bool ``ok``; a passing kill9 row must
      carry ``restored_bitwise`` true, a non-negative ``recovery_s``,
      and non-negative int ``lost``/``requests`` tallies.

    A file with none of these record families fails.  Returns failure
    strings (empty = pass)."""
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read {path!r}: {exc}"]
    n_seen = 0
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if ev == "chaos.inject":
            n_seen += 1
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: chaos.inject kind {rec.get('kind')!r} "
                    "!= 'count'")
            if not _pos_int(rec.get("n")):
                failures.append(
                    f"{at}: chaos.inject increment {rec.get('n')!r} "
                    "is not a positive int")
            seam = rec.get("seam")
            if not isinstance(seam, str) or not seam:
                failures.append(
                    f"{at}: chaos.inject seam {seam!r} is not a "
                    "non-empty string")
            if rec.get("action") not in CHAOS_ACTIONS:
                failures.append(
                    f"{at}: chaos.inject action {rec.get('action')!r} "
                    f"not in {'/'.join(CHAOS_ACTIONS)}")
        elif ev == "wal.commit":
            n_seen += 1
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: wal.commit kernel {k!r} is not a "
                    "non-empty string")
            if not _pos_int(rec.get("version")):
                failures.append(
                    f"{at}: wal.commit version "
                    f"{rec.get('version')!r} is not an int >= 1")
            ckpt = rec.get("ckpt")
            if not isinstance(ckpt, str) or not ckpt.endswith(".ckpt"):
                failures.append(
                    f"{at}: wal.commit ckpt {ckpt!r} is not a .ckpt "
                    "basename")
            sig = rec.get("sig")
            if (not isinstance(sig, list) or len(sig) != 2
                    or not all(isinstance(v, int)
                               and not isinstance(v, bool)
                               for v in sig)):
                failures.append(
                    f"{at}: wal.commit sig {sig!r} is not a 2-list "
                    "of ints")
            r = rec.get("reason")
            if not isinstance(r, str) or not r:
                failures.append(
                    f"{at}: wal.commit reason {r!r} is not a "
                    "non-empty string")
        elif ev == "wal.skip":
            n_seen += 1
            if rec.get("reason") not in WAL_SKIP_REASONS:
                failures.append(
                    f"{at}: wal.skip reason {rec.get('reason')!r} not "
                    f"in {'/'.join(WAL_SKIP_REASONS)}")
        elif ev in ("online.checkpoint", "online.restore"):
            n_seen += 1
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: {ev} kernel {k!r} is not a non-empty "
                    "string")
            vkey = ("wal_version" if ev == "online.restore"
                    else "version")
            if not _pos_int(rec.get(vkey)):
                failures.append(
                    f"{at}: {ev} {vkey} {rec.get(vkey)!r} is not an "
                    "int >= 1")
            ckpt = rec.get("ckpt")
            if not isinstance(ckpt, str) or not ckpt:
                failures.append(
                    f"{at}: {ev} ckpt {ckpt!r} is not a non-empty "
                    "string")
        elif ev == "online.checkpoint_failed":
            n_seen += 1
            r = rec.get("reason")
            if not isinstance(r, str) or not r:
                failures.append(
                    f"{at}: online.checkpoint_failed reason {r!r} is "
                    "not a non-empty string")
        elif ev == "serve.drain":
            n_seen += 1
            sig = rec.get("signal")
            if not isinstance(sig, int) or isinstance(sig, bool):
                failures.append(
                    f"{at}: serve.drain signal {sig!r} is not an int")
        elif ev == "serve.unready":
            n_seen += 1
            r = rec.get("reason")
            if not isinstance(r, str) or not r:
                failures.append(
                    f"{at}: serve.unready reason {r!r} is not a "
                    "non-empty string")
        elif isinstance(ev, str) and ev.startswith("drill."):
            n_seen += 1
            if ev not in DRILL_EVS:
                failures.append(
                    f"{at}: unknown drill row {ev!r} (want one of "
                    f"{'/'.join(DRILL_EVS)})")
                continue
            ok = rec.get("ok")
            if not isinstance(ok, bool):
                failures.append(
                    f"{at}: {ev} ok {ok!r} is not a bool")
            for key in ("lost", "requests"):
                v = rec.get(key)
                if v is not None and (not isinstance(v, int)
                                      or isinstance(v, bool)
                                      or v < 0):
                    failures.append(
                        f"{at}: {ev} {key} {v!r} is not a "
                        "non-negative int")
            if ev == "drill.kill9" and ok:
                if rec.get("restored_bitwise") is not True:
                    failures.append(
                        f"{at}: passing drill.kill9 without "
                        "restored_bitwise=true — the restart was "
                        "never proven bitwise")
                rs = rec.get("recovery_s")
                if not _num(rs) or not math.isfinite(rs) or rs < 0:
                    failures.append(
                        f"{at}: passing drill.kill9 recovery_s "
                        f"{rs!r} is not a non-negative number")
            if ev in ("drill.replica", "drill.worker") and ok:
                # the route-around contract: a passing replica/worker
                # drill PROVED zero loss on survivors and bitwise
                # answers
                if rec.get("survivors_lost") != 0:
                    failures.append(
                        f"{at}: passing {ev} with "
                        f"survivors_lost "
                        f"{rec.get('survivors_lost')!r} != 0")
                if rec.get("survivor_bitwise") is not True:
                    failures.append(
                        f"{at}: passing {ev} without "
                        "survivor_bitwise=true — survivors were "
                        "never proven bitwise")
                rs = rec.get("recovery_s")
                if not _num(rs) or not math.isfinite(rs) or rs < 0:
                    failures.append(
                        f"{at}: passing {ev} recovery_s "
                        f"{rs!r} is not a non-negative number")
            if ev == "drill.worker" and ok:
                # a passing worker drill must also prove the dead
                # worker was REPLACED (the supervisor restart policy)
                rp = rec.get("replaced_s")
                if not _num(rp) or not math.isfinite(rp) or rp < 0:
                    failures.append(
                        f"{at}: passing drill.worker replaced_s "
                        f"{rp!r} is not a non-negative number")
            if ev == "drill.drift" and ok:
                # a passing drift drill must say how long detection
                # took and that the capsule carried the sketches
                ds = rec.get("detect_s")
                if not _num(ds) or not math.isfinite(ds) or ds < 0:
                    failures.append(
                        f"{at}: passing drill.drift detect_s {ds!r} "
                        "is not a non-negative number")
                sk = rec.get("sketches")
                if not (isinstance(sk, dict)
                        and sk.get("reference") and sk.get("live")):
                    failures.append(
                        f"{at}: passing drill.drift sketches {sk!r} "
                        "do not show both reference and live — the "
                        "capsule's drift.json was never proven")
    if not n_seen:
        failures.append(
            f"{path!r} has no chaos.* / wal.* / drill.* / "
            "drain records — was HPNN_CHAOS or HPNN_WAL_DIR set, or "
            "is this not a drill output?")
    return failures


# the multi-replica routing record contracts (serve/router.py,
# serve/replica.py, serve/compile_cache.py; docs/serving.md
# "Scale-out")
ROUTER_COUNTS = ("router.route", "router.shed_around", "router.spill")
WARM_COUNTS = ("serve.compile_warm_hit", "serve.compile_warm_miss")


def lint_serve_replicas(path: str) -> list[str]:
    """Schema-lint the multi-replica routing records of one metrics
    sink (a run against a :class:`~hpnn_tpu.serve.router.Router`).

    Checks, per record:

    * ``router.route`` counts — ``kind == "count"``; ``rank`` a
      non-negative int (the placement decision must be attributable
      to a replica); non-empty ``kernel``; ``rows`` an int >= 1.
    * ``router.shed_around`` counts — ``rank`` a non-negative int and
      a non-empty ``reason`` (a route-around that can't say who
      refused or why is undebuggable).
    * ``router.spill`` counts — non-empty ``kernel``, ``rows`` an
      int >= 1 (the TP spill must say how big the block was).
    * ``router.fence`` events — non-empty ``op`` and ``kernel``;
      ``replicas`` an int >= 1; ``to_version``, when not null, an
      int >= 0 (versions start at 0 on first register; the version
      edge is the old-or-new proof).
    * ``router.replica_up`` / ``router.replica_down`` events —
      ``rank`` a non-negative int.
    * ``replica.outstanding`` gauges — ``rank`` a non-negative int,
      finite ``value`` >= 0 (in-flight row depth can't go negative).
    * ``serve.compile_warm_hit`` / ``_miss`` counts — ``kind ==
      "count"``, positive ``n``.

    A sink with no ``router.*`` / ``replica.*`` records fails — this
    lint only makes sense on a run that actually routed through a
    Router.  Returns failure strings (empty = pass)."""
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]

    def _rank_ok(v) -> bool:
        return isinstance(v, int) and not isinstance(v, bool) and v >= 0

    n_router = 0
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if isinstance(ev, str) and (ev.startswith("router.")
                                    or ev.startswith("replica.")):
            n_router += 1
        if ev in ROUTER_COUNTS:
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: {ev} kind {rec.get('kind')!r} != 'count'")
            if ev in ("router.route", "router.shed_around") \
                    and not _rank_ok(rec.get("rank")):
                failures.append(
                    f"{at}: {ev} rank {rec.get('rank')!r} is not a "
                    "non-negative int")
            if ev in ("router.route", "router.spill"):
                k = rec.get("kernel")
                if not isinstance(k, str) or not k:
                    failures.append(
                        f"{at}: {ev} kernel {k!r} is not a non-empty "
                        "string")
                if not _pos_int(rec.get("rows")):
                    failures.append(
                        f"{at}: {ev} rows {rec.get('rows')!r} is not "
                        "an int >= 1")
            if ev == "router.shed_around":
                r = rec.get("reason")
                if not isinstance(r, str) or not r:
                    failures.append(
                        f"{at}: router.shed_around reason {r!r} is "
                        "not a non-empty string")
        elif ev == "router.fence":
            for key in ("op", "kernel"):
                v = rec.get(key)
                if not isinstance(v, str) or not v:
                    failures.append(
                        f"{at}: router.fence {key} {v!r} is not a "
                        "non-empty string")
            if not _pos_int(rec.get("replicas")):
                failures.append(
                    f"{at}: router.fence replicas "
                    f"{rec.get('replicas')!r} is not an int >= 1")
            tv = rec.get("to_version")
            if tv is not None and (not isinstance(tv, int)
                                   or isinstance(tv, bool) or tv < 0):
                failures.append(
                    f"{at}: router.fence to_version {tv!r} is not "
                    "null or an int >= 0")
        elif ev in ("router.replica_up", "router.replica_down"):
            if not _rank_ok(rec.get("rank")):
                failures.append(
                    f"{at}: {ev} rank {rec.get('rank')!r} is not a "
                    "non-negative int")
        elif ev == "replica.outstanding":
            if rec.get("kind") != "gauge":
                failures.append(
                    f"{at}: replica.outstanding kind "
                    f"{rec.get('kind')!r} != 'gauge'")
            if not _rank_ok(rec.get("rank")):
                failures.append(
                    f"{at}: replica.outstanding rank "
                    f"{rec.get('rank')!r} is not a non-negative int")
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 0:
                failures.append(
                    f"{at}: replica.outstanding value {v!r} is not a "
                    "finite non-negative number")
        elif ev in WARM_COUNTS:
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: {ev} kind {rec.get('kind')!r} != 'count'")
            if not _pos_int(rec.get("n")):
                failures.append(
                    f"{at}: {ev} increment {rec.get('n')!r} is not a "
                    "positive int")
    if not n_router:
        failures.append(
            f"sink {path!r} has no router.* / replica.* records — "
            "did this run route through a Router?")
    return failures


def lint_fleet(path: str) -> list[str]:
    """Schema-lint the fleet-telemetry records of one metrics sink
    (trace propagation, collector traffic, alerting —
    docs/observability.md "Fleet telemetry").

    Checks, per record:

    * ``alert.fire`` / ``alert.resolve`` events — non-empty ``rule``
      and ``gauge``; ``severity`` in info|warn|crit; finite numeric
      ``value``; ``alert.resolve`` additionally a finite
      ``duration_s`` >= 0.  Per rule, the stream must PAIR: a resolve
      with no prior unresolved fire, or two fires with no resolve
      between them, fails (an alert plane that can double-fire or
      resolve thin air is un-auditable).
    * ``collector.push`` / ``collector.drop`` / ``collector.recv``
      counts — ``kind == "count"``, positive int ``n``;
      ``collector.drop`` a non-empty ``reason`` (queue_full |
      push_error | recv_queue_full — a shed that can't say why is
      undebuggable); ``collector.recv`` a non-negative int ``pid``
      (worker identity must be finite, never a float or null).
    * ``collector.listen`` events — non-empty ``host``, ``port`` an
      int in [1, 65535].
    * ``trace.adopt`` counts — ``kind == "count"``, positive int
      ``n``.

    A sink with no ``trace.*`` / ``collector.*`` / ``alert.*``
    records fails — this lint only makes sense on a run with the
    telemetry plane armed.  Returns failure strings (empty = pass)."""
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]

    n_fleet = 0
    active: dict[str, int] = {}   # rule -> unresolved fire count
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if isinstance(ev, str) and ev.startswith(("trace.",
                                                  "collector.",
                                                  "alert.")):
            n_fleet += 1
        if ev in ("alert.fire", "alert.resolve"):
            for key in ("rule", "gauge"):
                v = rec.get(key)
                if not isinstance(v, str) or not v:
                    failures.append(
                        f"{at}: {ev} {key} {v!r} is not a non-empty "
                        "string")
            sev = rec.get("severity")
            if sev not in ("info", "warn", "crit"):
                failures.append(
                    f"{at}: {ev} severity {sev!r} is not "
                    "info|warn|crit")
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v):
                failures.append(
                    f"{at}: {ev} value {v!r} is not a finite number")
            rule = rec.get("rule")
            if ev == "alert.fire":
                if isinstance(rule, str) and active.get(rule, 0) > 0:
                    failures.append(
                        f"{at}: alert.fire for rule {rule!r} while "
                        "already active (no resolve in between)")
                if isinstance(rule, str):
                    active[rule] = active.get(rule, 0) + 1
            else:
                d = rec.get("duration_s")
                if not _num(d) or not math.isfinite(d) or d < 0:
                    failures.append(
                        f"{at}: alert.resolve duration_s {d!r} is "
                        "not a finite non-negative number")
                if isinstance(rule, str):
                    if active.get(rule, 0) < 1:
                        failures.append(
                            f"{at}: alert.resolve for rule {rule!r} "
                            "with no unresolved alert.fire before it")
                    else:
                        active[rule] -= 1
        elif ev in ("collector.push", "collector.drop",
                    "collector.recv", "trace.adopt"):
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: {ev} kind {rec.get('kind')!r} != 'count'")
            if not _pos_int(rec.get("n")):
                failures.append(
                    f"{at}: {ev} increment {rec.get('n')!r} is not a "
                    "positive int")
            if ev == "collector.drop":
                r = rec.get("reason")
                if not isinstance(r, str) or not r:
                    failures.append(
                        f"{at}: collector.drop reason {r!r} is not a "
                        "non-empty string")
            if ev == "collector.recv":
                pid = rec.get("pid")
                if (not isinstance(pid, int) or isinstance(pid, bool)
                        or pid < 0):
                    failures.append(
                        f"{at}: collector.recv pid {pid!r} is not a "
                        "non-negative int")
        elif ev == "collector.listen":
            h = rec.get("host")
            if not isinstance(h, str) or not h:
                failures.append(
                    f"{at}: collector.listen host {h!r} is not a "
                    "non-empty string")
            p = rec.get("port")
            if (not isinstance(p, int) or isinstance(p, bool)
                    or not 1 <= p <= 65535):
                failures.append(
                    f"{at}: collector.listen port {p!r} is not an "
                    "int in [1, 65535]")
    if not n_fleet:
        failures.append(
            f"sink {path!r} has no trace.* / collector.* / alert.* "
            "records — was the telemetry plane armed?")
    return failures


# the cross-host cluster record contracts (hpnn_tpu/fleet/,
# docs/serving.md "Cross-host fleet")
SCALE_EVS = ("fleet.scale_up", "fleet.scale_down")


def lint_cluster(path: str) -> list[str]:
    """Schema-lint the cross-host fleet records of one JSONL file — a
    metrics sink from a supervisor/autoscaler run (bench autoscale
    demo, worker drill, or a live fleet edge).

    Checks, per record:

    * ``fleet.worker_up`` events — ``rank`` a non-negative int,
      ``port`` an int in [1, 65535], ``pid`` a positive int, and a
      finite non-negative ``spawn_s`` (a worker admission that can't
      say how long the boot took hides the warm-boot regression the
      shared compile cache exists to prevent).
    * ``fleet.worker_down`` events — ``rank`` a non-negative int, a
      non-empty ``reason``, a finite non-negative ``alive_s``.
    * **Pairing** — a ``worker_down`` for a rank never admitted, or a
      second ``worker_up`` for a rank still up, fails (ranks are
      never reused by the supervisor); workers still up at EOF are
      fine (a live fleet).
    * ``fleet.scale_up`` / ``fleet.scale_down`` events — finite int
      widths >= 1 with ``to_width`` strictly greater (up) / smaller
      (down) than ``from_width``, and a non-empty ``reason``.
    * ``fleet.width`` gauges — finite ``value`` >= 1 (an empty fleet
      gauge is a supervisor bug).
    * ``cluster.route`` / ``cluster.shed_around`` counts and
      ``cluster.outstanding`` gauges — the ``router.*`` twins: an
      attributable non-negative ``rank``; a non-empty ``kernel`` /
      ``reason``; a finite non-negative outstanding value.
    * ``cluster.fence`` events — non-empty ``op`` and ``kernel``,
      ``workers`` an int >= 1.

    A file with no ``fleet.worker_*`` / ``fleet.scale_*`` records
    fails — this lint only makes sense on a run that actually managed
    a fleet.  Returns failure strings (empty = pass)."""
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read {path!r}: {exc}"]

    def _rank_ok(v) -> bool:
        return isinstance(v, int) and not isinstance(v, bool) and v >= 0

    def _width_ok(v) -> bool:
        return (isinstance(v, int) and not isinstance(v, bool)
                and v >= 1)

    n_cluster = 0
    up_ranks: set = set()
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if ev == "fleet.worker_up":
            n_cluster += 1
            rank = rec.get("rank")
            if not _rank_ok(rank):
                failures.append(
                    f"{at}: fleet.worker_up rank {rank!r} is not a "
                    "non-negative int")
            elif rank in up_ranks:
                failures.append(
                    f"{at}: fleet.worker_up rank {rank} admitted "
                    "twice without a worker_down between (ranks are "
                    "never reused)")
            else:
                up_ranks.add(rank)
            p = rec.get("port")
            if (not isinstance(p, int) or isinstance(p, bool)
                    or not 1 <= p <= 65535):
                failures.append(
                    f"{at}: fleet.worker_up port {p!r} is not an int "
                    "in [1, 65535]")
            if not _pos_int(rec.get("pid")):
                failures.append(
                    f"{at}: fleet.worker_up pid {rec.get('pid')!r} is "
                    "not a positive int")
            sp = rec.get("spawn_s")
            if not _num(sp) or not math.isfinite(sp) or sp < 0:
                failures.append(
                    f"{at}: fleet.worker_up spawn_s {sp!r} is not a "
                    "finite non-negative number — spawn latency is a "
                    "required field")
        elif ev == "fleet.worker_down":
            n_cluster += 1
            rank = rec.get("rank")
            if not _rank_ok(rank):
                failures.append(
                    f"{at}: fleet.worker_down rank {rank!r} is not a "
                    "non-negative int")
            elif rank not in up_ranks:
                failures.append(
                    f"{at}: fleet.worker_down rank {rank} was never "
                    "admitted (no paired fleet.worker_up)")
            else:
                up_ranks.discard(rank)
            r = rec.get("reason")
            if not isinstance(r, str) or not r:
                failures.append(
                    f"{at}: fleet.worker_down reason {r!r} is not a "
                    "non-empty string")
            al = rec.get("alive_s")
            if not _num(al) or not math.isfinite(al) or al < 0:
                failures.append(
                    f"{at}: fleet.worker_down alive_s {al!r} is not a "
                    "finite non-negative number")
        elif ev in SCALE_EVS:
            n_cluster += 1
            fw, tw = rec.get("from_width"), rec.get("to_width")
            if not _width_ok(fw) or not _width_ok(tw):
                failures.append(
                    f"{at}: {ev} widths {fw!r} -> {tw!r} are not "
                    "ints >= 1")
            elif ev == "fleet.scale_up" and tw <= fw:
                failures.append(
                    f"{at}: fleet.scale_up to_width {tw} <= "
                    f"from_width {fw} — not a scale-up")
            elif ev == "fleet.scale_down" and tw >= fw:
                failures.append(
                    f"{at}: fleet.scale_down to_width {tw} >= "
                    f"from_width {fw} — not a scale-down")
            r = rec.get("reason")
            if not isinstance(r, str) or not r:
                failures.append(
                    f"{at}: {ev} reason {r!r} is not a non-empty "
                    "string")
        elif ev == "fleet.width" and rec.get("kind") == "gauge":
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 1:
                failures.append(
                    f"{at}: fleet.width gauge {v!r} is not a finite "
                    "number >= 1")
        elif ev == "cluster.route":
            if not _rank_ok(rec.get("rank")):
                failures.append(
                    f"{at}: cluster.route rank {rec.get('rank')!r} is "
                    "not a non-negative int")
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: cluster.route kernel {k!r} is not a "
                    "non-empty string")
        elif ev == "cluster.shed_around":
            if not _rank_ok(rec.get("rank")):
                failures.append(
                    f"{at}: cluster.shed_around rank "
                    f"{rec.get('rank')!r} is not a non-negative int")
            r = rec.get("reason")
            if not isinstance(r, str) or not r:
                failures.append(
                    f"{at}: cluster.shed_around reason {r!r} is not a "
                    "non-empty string")
        elif ev == "cluster.outstanding" and rec.get("kind") == "gauge":
            if not _rank_ok(rec.get("rank")):
                failures.append(
                    f"{at}: cluster.outstanding rank "
                    f"{rec.get('rank')!r} is not a non-negative int")
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 0:
                failures.append(
                    f"{at}: cluster.outstanding gauge {v!r} is not a "
                    "finite number >= 0")
        elif ev == "cluster.fence":
            for key in ("op", "kernel"):
                v = rec.get(key)
                if not isinstance(v, str) or not v:
                    failures.append(
                        f"{at}: cluster.fence {key} {v!r} is not a "
                        "non-empty string")
            if not _pos_int(rec.get("workers")):
                failures.append(
                    f"{at}: cluster.fence workers "
                    f"{rec.get('workers')!r} is not an int >= 1")
    if not n_cluster:
        failures.append(
            f"{path!r} has no fleet.worker_* / fleet.scale_* records "
            "— was a WorkerSupervisor/Autoscaler active during this "
            "run?")
    return failures


# the tail-latency forensics record contracts (obs/forensics.py,
# obs/triggers.py; docs/observability.md "Forensics")
SKIP_REASONS = ("in_flight", "cooldown", "io_error")


def lint_forensics(path: str) -> list[str]:
    """Schema-lint the tail-latency forensics records of one metrics
    sink (a run with ``HPNN_SAMPLE`` and/or ``HPNN_CAPSULE_DIR``
    armed — docs/observability.md "Forensics").

    Checks, per record:

    * ``forensics.capture`` events — non-empty ``reason`` and
      ``capsule`` path; per process, at most one capture in flight (a
      second begin before the previous ``capture_done`` means the
      admission gate is broken) and no capsule path reused.
    * ``forensics.capture_done`` events — same ``reason``/``capsule``
      shape; the capsule must pair with a prior unfinished capture;
      finite non-negative ``duration_s``; non-negative int ``files``
      and ``spans`` tallies; a bool ``profile`` flag.  Captures still
      in flight at EOF are fine (the process may have been snapping
      when the sink closed).
    * ``forensics.capture_skipped`` counts — ``kind == "count"``,
      positive ``n``, ``reason`` one of in_flight/cooldown/io_error
      (a suppressed trigger that can't say why is undebuggable).
    * ``forensics.tail_promote`` counts — ``kind == "count"``,
      positive ``n``, finite non-negative ``dt`` (the latency that
      crossed the threshold), non-empty ``root`` span name.
    * ``obs.summary`` aggregates — every ``exemplars`` block maps
      int-parseable bucket keys to ``{trace_id, value}`` objects with
      a non-empty string trace id and a finite number value (a NaN
      exemplar severs the histogram→trace link /metrics exists to
      provide).

    A sink with no ``forensics.*`` records and no exemplar blocks
    fails — this lint only makes sense on a forensics-armed run.
    Returns failure strings (empty = pass)."""
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]
    n_forensics = 0
    in_flight: dict = {}     # pid -> open capsule path
    seen_capsules: set = set()
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if isinstance(ev, str) and ev.startswith("forensics."):
            n_forensics += 1
        if ev in ("forensics.capture", "forensics.capture_done"):
            r = rec.get("reason")
            if not isinstance(r, str) or not r:
                failures.append(
                    f"{at}: {ev} reason {r!r} is not a non-empty "
                    "string")
            cap = rec.get("capsule")
            if not isinstance(cap, str) or not cap:
                failures.append(
                    f"{at}: {ev} capsule {cap!r} is not a non-empty "
                    "string")
                continue
            # collector-merged streams tag the sender pid; a raw
            # single-process sink has none — one shared slot then
            pid = rec.get("pid")
            if ev == "forensics.capture":
                if in_flight.get(pid) is not None:
                    failures.append(
                        f"{at}: forensics.capture for {cap!r} while "
                        f"{in_flight[pid]!r} is still in flight (the "
                        "at-most-one admission gate is broken)")
                if cap in seen_capsules:
                    failures.append(
                        f"{at}: capsule path {cap!r} reused")
                seen_capsules.add(cap)
                in_flight[pid] = cap
            else:
                if in_flight.get(pid) != cap:
                    failures.append(
                        f"{at}: forensics.capture_done for {cap!r} "
                        "with no paired unfinished forensics.capture")
                else:
                    in_flight[pid] = None
                d = rec.get("duration_s")
                if not _num(d) or not math.isfinite(d) or d < 0:
                    failures.append(
                        f"{at}: capture_done duration_s {d!r} is not "
                        "a finite non-negative number")
                for key in ("files", "spans"):
                    v = rec.get(key)
                    if not isinstance(v, int) or isinstance(v, bool) \
                            or v < 0:
                        failures.append(
                            f"{at}: capture_done {key} {v!r} is not a "
                            "non-negative int")
                if not isinstance(rec.get("profile"), bool):
                    failures.append(
                        f"{at}: capture_done profile "
                        f"{rec.get('profile')!r} is not a bool")
        elif ev == "forensics.capture_skipped":
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: capture_skipped kind {rec.get('kind')!r} "
                    "!= 'count'")
            if not _pos_int(rec.get("n")):
                failures.append(
                    f"{at}: capture_skipped increment "
                    f"{rec.get('n')!r} is not a positive int")
            if rec.get("reason") not in SKIP_REASONS:
                failures.append(
                    f"{at}: capture_skipped reason "
                    f"{rec.get('reason')!r} not in "
                    f"{'/'.join(SKIP_REASONS)}")
        elif ev == "forensics.tail_promote":
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: tail_promote kind {rec.get('kind')!r} "
                    "!= 'count'")
            if not _pos_int(rec.get("n")):
                failures.append(
                    f"{at}: tail_promote increment {rec.get('n')!r} "
                    "is not a positive int")
            dt = rec.get("dt")
            if not _num(dt) or not math.isfinite(dt) or dt < 0:
                failures.append(
                    f"{at}: tail_promote dt {dt!r} is not a finite "
                    "non-negative number")
            root = rec.get("root")
            if not isinstance(root, str) or not root:
                failures.append(
                    f"{at}: tail_promote root {root!r} is not a "
                    "non-empty string")
        elif ev == "obs.summary":
            aggs = rec.get("aggregates")
            if not isinstance(aggs, dict):
                continue
            for name, agg in aggs.items():
                ex = agg.get("exemplars") if isinstance(agg, dict) \
                    else None
                if ex is None:
                    continue
                n_forensics += 1
                if not isinstance(ex, dict):
                    failures.append(
                        f"{at}: aggregate {name!r} exemplars is not "
                        "an object")
                    continue
                for bucket, cell in ex.items():
                    try:
                        int(bucket)
                    except (TypeError, ValueError):
                        failures.append(
                            f"{at}: aggregate {name!r} exemplar "
                            f"bucket {bucket!r} is not an int key")
                    if not isinstance(cell, dict):
                        failures.append(
                            f"{at}: aggregate {name!r} exemplar "
                            f"{bucket!r} is not an object")
                        continue
                    t = cell.get("trace_id")
                    if not isinstance(t, str) or not t:
                        failures.append(
                            f"{at}: aggregate {name!r} exemplar "
                            f"{bucket!r} trace_id {t!r} is not a "
                            "non-empty string")
                    v = cell.get("value")
                    if not _num(v) or not math.isfinite(v):
                        failures.append(
                            f"{at}: aggregate {name!r} exemplar "
                            f"{bucket!r} value {v!r} is not a finite "
                            "number")
    if not n_forensics:
        failures.append(
            f"sink {path!r} has no forensics.* records or exemplar "
            "blocks — were HPNN_SAMPLE / HPNN_CAPSULE_DIR armed "
            "during this run?")
    return failures


# the drift-detection record contracts (obs/drift.py,
# online/trainer.py; docs/observability.md "Drift detection")
DRIFT_DETECTORS = ("ingest", "pred", "eval")


def lint_drift(path: str) -> list[str]:
    """Schema-lint the drift-detection records of one metrics sink (a
    run with ``HPNN_DRIFT`` armed — docs/observability.md "Drift
    detection").

    Checks, per record:

    * ``drift.score`` gauges — ``kind == "gauge"``; a finite
      non-negative ``value`` (the normalized score; a NaN score can
      never cross an alert rule, so drift would rot invisibly);
      ``detector`` one of ingest/pred/eval; a non-empty ``kernel``.
    * ``drift.pred_shift`` gauges — finite non-negative ``value``
      (a PSI), non-empty ``kernel``.
    * ``drift.eval_decay`` gauges — finite ``value`` (the *signed*
      sentinel z), non-empty ``kernel``.
    * ``online.drift`` events — ``detector`` one of
      ingest/pred/eval; non-empty ``kernel``; finite ``score`` >= 1
      (the event is the rising edge of the breach bound); ``window``
      an int >= 1; finite ``raw`` statistic.
    * ``online.eval_resident`` gauges — finite ``value``, non-empty
      ``kernel`` (the sentinel's food; a NaN resident eval starves
      it).
    * capsule linkage — for every ``forensics.capture_done`` whose
      ``reason`` is ``alert:<rule>`` where some ``alert.fire`` shows
      that rule watching a ``drift.*`` gauge, the capsule directory
      must contain ``drift.json`` (checked only when the directory
      still exists — drill temp dirs may be gone).

    A sink with no drift records fails — this lint only makes sense
    on a drift-armed run.  Returns failure strings (empty = pass)."""
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]
    n_drift = 0
    drift_rules: set = set()
    for ln in lines:
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        if (isinstance(rec, dict) and rec.get("ev") == "alert.fire"
                and str(rec.get("gauge", "")).startswith("drift.")):
            drift_rules.add(rec.get("rule"))
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if ev == "drift.score":
            n_drift += 1
            if rec.get("kind") != "gauge":
                failures.append(
                    f"{at}: drift.score kind {rec.get('kind')!r} "
                    "!= 'gauge'")
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 0:
                failures.append(
                    f"{at}: drift.score value {v!r} is not a finite "
                    "non-negative number")
            if rec.get("detector") not in DRIFT_DETECTORS:
                failures.append(
                    f"{at}: drift.score detector "
                    f"{rec.get('detector')!r} not in "
                    f"{'/'.join(DRIFT_DETECTORS)}")
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: drift.score kernel {k!r} is not a "
                    "non-empty string")
        elif ev in ("drift.pred_shift", "drift.eval_decay"):
            n_drift += 1
            v = rec.get("value")
            bad = (not _num(v) or not math.isfinite(v)
                   or (ev == "drift.pred_shift" and v < 0))
            if bad:
                want = ("finite non-negative number"
                        if ev == "drift.pred_shift"
                        else "finite number")
                failures.append(
                    f"{at}: {ev} value {v!r} is not a {want}")
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: {ev} kernel {k!r} is not a non-empty "
                    "string")
        elif ev == "online.drift":
            n_drift += 1
            if rec.get("detector") not in DRIFT_DETECTORS:
                failures.append(
                    f"{at}: online.drift detector "
                    f"{rec.get('detector')!r} not in "
                    f"{'/'.join(DRIFT_DETECTORS)}")
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: online.drift kernel {k!r} is not a "
                    "non-empty string")
            s = rec.get("score")
            if not _num(s) or not math.isfinite(s) or s < 1.0:
                failures.append(
                    f"{at}: online.drift score {s!r} is not a finite "
                    "number >= 1 (the event is the breach edge)")
            w = rec.get("window")
            if not _pos_int(w):
                failures.append(
                    f"{at}: online.drift window {w!r} is not an "
                    "int >= 1")
            raw = rec.get("raw")
            if not _num(raw) or not math.isfinite(raw):
                failures.append(
                    f"{at}: online.drift raw {raw!r} is not a finite "
                    "number")
        elif ev == "online.eval_resident":
            n_drift += 1
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v):
                failures.append(
                    f"{at}: online.eval_resident value {v!r} is not "
                    "a finite number")
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: online.eval_resident kernel {k!r} is not "
                    "a non-empty string")
        elif ev == "forensics.capture_done" and drift_rules:
            reason = str(rec.get("reason", ""))
            rule = (reason[len("alert:"):]
                    if reason.startswith("alert:") else None)
            cap = rec.get("capsule")
            if (rule in drift_rules and isinstance(cap, str)
                    and os.path.isdir(cap)
                    and not os.path.exists(
                        os.path.join(cap, "drift.json"))):
                failures.append(
                    f"{at}: capsule {cap!r} captured for drift alert "
                    f"{rule!r} has no drift.json — the sketch dump "
                    "the capture exists to preserve")
    if not n_drift:
        failures.append(
            f"sink {path!r} has no drift records — was HPNN_DRIFT "
            "armed during this run?")
    return failures


TENANT_CLASSES = ("gold", "silver", "bronze")


def lint_tenant(path: str) -> list[str]:
    """Schema-lint the multi-tenant hosting records of one metrics
    sink (a run against a ``TenantSession`` — docs/tenancy.md).

    Checks, per record:

    * ``tenant.page_in`` / ``tenant.page_out`` counts — ``kind ==
      "count"``; a non-empty ``kernel`` (an anonymous paging event
      cannot be attributed to a tenant's working set).
    * ``tenant.page_in_ms`` — ``kind == "hist"`` (the cold-hit
      latency distribution the bench gates on).
    * ``tenant.resident`` gauges — finite ``value`` >= 0, and when a
      positive ``cap`` rides along, ``value <= cap + pinned``: the
      LRU's bounded-residency invariant, made lintable (pins hold
      in-flight kernels over cap by design).
    * ``tenant.p99_ms`` gauges — finite non-negative ``value``,
      non-empty ``tenant``, ``slo_class`` one of gold/silver/bronze.
    * ``tenant.shed_rate`` gauges — ``value`` in [0, 1], non-empty
      ``tenant`` (an anonymous shed rate can't drive a per-tenant
      alert).
    * ``serve.shed`` counts with ``reason == "quota"`` — a non-empty
      ``tenant`` (the refusal must name whose budget it enforced).

    A sink with no tenant records fails — this lint only makes sense
    on a tenancy-armed run.  Returns failure strings (empty = pass)."""
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]
    n_tenant = 0
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if ev in ("tenant.page_in", "tenant.page_out"):
            n_tenant += 1
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: {ev} kind {rec.get('kind')!r} "
                    "!= 'count'")
            k = rec.get("kernel")
            if not isinstance(k, str) or not k:
                failures.append(
                    f"{at}: {ev} kernel {k!r} is not a non-empty "
                    "string")
        elif ev == "tenant.page_in_ms":
            n_tenant += 1
            if rec.get("kind") != "hist":
                failures.append(
                    f"{at}: tenant.page_in_ms kind "
                    f"{rec.get('kind')!r} != 'hist'")
        elif ev == "tenant.resident":
            n_tenant += 1
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 0:
                failures.append(
                    f"{at}: tenant.resident value {v!r} is not a "
                    "finite non-negative number")
            cap = rec.get("cap")
            pinned = rec.get("pinned")
            slack = pinned if _num(pinned) and pinned > 0 else 0
            if (_num(v) and math.isfinite(v) and _num(cap)
                    and cap > 0 and v > cap + slack):
                failures.append(
                    f"{at}: tenant.resident value {v!r} exceeds its "
                    f"cap {cap!r} (+{slack} pinned) — the paging "
                    "LRU's bounded-residency invariant is broken")
        elif ev == "tenant.p99_ms":
            n_tenant += 1
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 0:
                failures.append(
                    f"{at}: tenant.p99_ms value {v!r} is not a "
                    "finite non-negative number")
            t = rec.get("tenant")
            if not isinstance(t, str) or not t:
                failures.append(
                    f"{at}: tenant.p99_ms tenant {t!r} is not a "
                    "non-empty string")
            if rec.get("slo_class") not in TENANT_CLASSES:
                failures.append(
                    f"{at}: tenant.p99_ms slo_class "
                    f"{rec.get('slo_class')!r} not in "
                    f"{'/'.join(TENANT_CLASSES)}")
        elif ev == "tenant.shed_rate":
            n_tenant += 1
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or not 0 <= v <= 1:
                failures.append(
                    f"{at}: tenant.shed_rate value {v!r} is not a "
                    "number in [0, 1]")
            t = rec.get("tenant")
            if not isinstance(t, str) or not t:
                failures.append(
                    f"{at}: tenant.shed_rate tenant {t!r} is not a "
                    "non-empty string")
        elif ev == "serve.shed" and rec.get("reason") == "quota":
            n_tenant += 1
            t = rec.get("tenant")
            if not isinstance(t, str) or not t:
                failures.append(
                    f"{at}: serve.shed reason=quota tenant {t!r} is "
                    "not a non-empty string — a quota refusal must "
                    "name whose budget it enforced")
    if not n_tenant:
        failures.append(
            f"sink {path!r} has no tenant records — did this run "
            "host kernels through a TenantSession?")
    return failures


def lint_meter(path: str) -> list[str]:
    """Schema-lint the per-tenant metering records of one metrics
    sink (a run with ``HPNN_METER`` armed — docs/observability.md
    "Tenant metering").

    Checks, per ``meter.sketch`` record:

    * ``k`` — a positive integer (the governor's top-K width).
    * every ``export`` axis — at most ``k`` named tenants plus
      ``_other`` (the O(K) cardinality bound, held in the sink, not
      just at render time); all values finite and >= 0.
    * ``_other`` present whenever that axis's raw ``entries`` hold
      more tenants than ``k`` (the long tail must roll up, not
      vanish).
    * every ``axes`` sketch — finite ``total`` >= 0; every entry a
      finite ``[count, err]`` pair with ``count >= err >= 0`` (the
      space-saving invariant every merge and lower-bound estimate
      rests on).
    * conservation — ``sum(export[axis].values())`` equals the axis
      ``total`` (the export is a partition of the fleet mass, not a
      sample of it).

    A sink with no ``meter.sketch`` records fails — this lint only
    makes sense on a meter-armed run.  Returns failure strings
    (empty = pass)."""
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]
    n_meter = 0
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict) or rec.get("ev") != "meter.sketch":
            continue
        n_meter += 1
        at = f"record {i + 1}"
        k = rec.get("k")
        if not isinstance(k, int) or isinstance(k, bool) or k < 1:
            failures.append(
                f"{at}: meter.sketch k {k!r} is not a positive int")
            continue
        axes = rec.get("axes")
        export = rec.get("export")
        if not isinstance(axes, dict) or not isinstance(export, dict):
            failures.append(
                f"{at}: meter.sketch axes/export are not objects")
            continue
        for ax, doc in sorted(axes.items()):
            total = (doc or {}).get("total")
            if not _num(total) or not math.isfinite(total) or total < 0:
                failures.append(
                    f"{at}: axis {ax} total {total!r} is not a "
                    "finite non-negative number")
                continue
            entries = (doc or {}).get("entries") or {}
            bad = False
            for t, ce in sorted(entries.items()):
                try:
                    c, e = float(ce[0]), float(ce[1])
                except (TypeError, ValueError, IndexError):
                    failures.append(
                        f"{at}: axis {ax} tenant {t!r} entry {ce!r} "
                        "is not a [count, err] pair")
                    bad = True
                    continue
                if (not math.isfinite(c) or not math.isfinite(e)
                        or not c >= e >= 0):
                    failures.append(
                        f"{at}: axis {ax} tenant {t!r} entry "
                        f"[{c!r}, {e!r}] breaks count >= err >= 0 — "
                        "the space-saving invariant")
                    bad = True
            exp = export.get(ax)
            if not isinstance(exp, dict):
                failures.append(
                    f"{at}: axis {ax} has a sketch but no export "
                    "view")
                continue
            named = [t for t in exp if t != "_other"]
            if len(named) > k:
                failures.append(
                    f"{at}: axis {ax} export names {len(named)} "
                    f"tenants > k={k} — the cardinality governor's "
                    "O(K) bound is broken in the sink")
            if len(entries) > k and "_other" not in exp:
                failures.append(
                    f"{at}: axis {ax} tracks {len(entries)} tenants "
                    f"> k={k} but exports no _other rollup — the "
                    "long tail's mass vanished")
            s = 0.0
            for t, v in sorted(exp.items()):
                if not _num(v) or not math.isfinite(v) or v < 0:
                    failures.append(
                        f"{at}: axis {ax} export {t!r} value {v!r} "
                        "is not a finite non-negative number")
                    bad = True
                    continue
                s += v
            if not bad and abs(s - total) > 1e-6 + 1e-6 * abs(total):
                failures.append(
                    f"{at}: axis {ax} export sums to {s!r} != total "
                    f"{total!r} — the top-K + _other partition does "
                    "not conserve the fleet mass")
    if not n_meter:
        failures.append(
            f"sink {path!r} has no meter.sketch records — was "
            "HPNN_METER armed during this run?")
    return failures


# closed enums the self-tuning plane (hpnn_tpu/tune/engine.py) is
# allowed to emit — kept in lockstep with RULE_OF / VERDICTS there
TUNE_ACTIONS = ("scale_up", "precision_down", "grow_buckets",
                "quota_squeeze")
TUNE_VERDICTS = ("apply", "veto", "dry_run", "no_actuator",
                 "watch_active", "cooldown", "burn_ok", "no_dominant",
                 "thin_window", "no_sensor")
TUNE_PHASES = ("queue", "dispatch", "spill", "shed_retry")
BLAME_PCT_GAUGES = ("blame.queue_pct", "blame.dispatch_pct",
                    "blame.spill_pct", "blame.shed_pct",
                    "blame.other_pct", "blame.gap_pct")


def lint_tune(path: str) -> list[str]:
    """Schema-lint the self-tuning audit trail of one metrics sink
    (a run with ``HPNN_TUNE`` + ``HPNN_BLAME`` armed —
    docs/selftuning.md).

    The remediation plane moves production serving knobs on its own;
    these records are the only proof every move was attributable and
    reversible, so their shapes are frozen:

    * ``tune.apply`` — non-empty ``id``; ``action`` in the closed
      enum; ``phase`` a blame class; ``pct`` finite in [0, 100];
      ``prior`` and ``applied`` both present (no prior snapshot = no
      rollback target); ``cooldown_s``/``watch_s`` finite >= 0.
    * ``tune.rollback`` — its ``id`` must pair a *previously seen*
      apply (an orphan rollback restored nothing anyone applied);
      ``action`` in the enum; non-empty ``reason``; ``restored``
      present.
    * ``tune.decision`` — ``verdict`` on the closed enum; ``roots``
      a non-negative int; ``burn`` None or finite.
    * ``blame.*_pct`` gauges — finite shares in [0, 100];
      ``blame.window_roots`` finite >= 0.

    A sink with no ``tune.*`` records fails — this lint only makes
    sense on a tune-armed run.  Returns failure strings
    (empty = pass)."""
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]
    n_tune = 0
    apply_ids: set[str] = set()
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if ev == "tune.apply":
            n_tune += 1
            aid = rec.get("id")
            if not isinstance(aid, str) or not aid:
                failures.append(
                    f"{at}: tune.apply id {aid!r} is not a non-empty "
                    "string — an unnamed apply cannot be paired with "
                    "its rollback")
            else:
                apply_ids.add(aid)
            if rec.get("action") not in TUNE_ACTIONS:
                failures.append(
                    f"{at}: tune.apply action {rec.get('action')!r} "
                    f"not in {'/'.join(TUNE_ACTIONS)}")
            if rec.get("phase") not in TUNE_PHASES:
                failures.append(
                    f"{at}: tune.apply phase {rec.get('phase')!r} is "
                    "not an actionable blame class "
                    f"({'/'.join(TUNE_PHASES)})")
            pct = rec.get("pct")
            if (not _num(pct) or not math.isfinite(pct)
                    or not 0.0 <= pct <= 100.0):
                failures.append(
                    f"{at}: tune.apply pct {pct!r} is not a finite "
                    "share in [0, 100]")
            for key in ("prior", "applied"):
                if key not in rec:
                    failures.append(
                        f"{at}: tune.apply has no {key} field — "
                        "without the prior snapshot the move is not "
                        "reversible, without applied it is not "
                        "auditable")
            for key in ("cooldown_s", "watch_s"):
                v = rec.get(key)
                if not _num(v) or not math.isfinite(v) or v < 0:
                    failures.append(
                        f"{at}: tune.apply {key} {v!r} is not a "
                        "finite non-negative number")
        elif ev == "tune.rollback":
            n_tune += 1
            rid = rec.get("id")
            if not isinstance(rid, str) or not rid:
                failures.append(
                    f"{at}: tune.rollback id {rid!r} is not a "
                    "non-empty string")
            elif rid not in apply_ids:
                failures.append(
                    f"{at}: tune.rollback id {rid!r} pairs no "
                    "preceding tune.apply — an orphan rollback "
                    "restored nothing anyone applied")
            if rec.get("action") not in TUNE_ACTIONS:
                failures.append(
                    f"{at}: tune.rollback action "
                    f"{rec.get('action')!r} not in "
                    f"{'/'.join(TUNE_ACTIONS)}")
            reason = rec.get("reason")
            if not isinstance(reason, str) or not reason:
                failures.append(
                    f"{at}: tune.rollback reason {reason!r} is not a "
                    "non-empty string — an unexplained undo is not "
                    "an audit trail")
            if "restored" not in rec:
                failures.append(
                    f"{at}: tune.rollback has no restored field — "
                    "cannot verify the prior config came back")
        elif ev == "tune.decision":
            n_tune += 1
            if rec.get("verdict") not in TUNE_VERDICTS:
                failures.append(
                    f"{at}: tune.decision verdict "
                    f"{rec.get('verdict')!r} not in the closed enum "
                    f"({'/'.join(TUNE_VERDICTS)})")
            roots = rec.get("roots")
            if (not isinstance(roots, int) or isinstance(roots, bool)
                    or roots < 0):
                failures.append(
                    f"{at}: tune.decision roots {roots!r} is not a "
                    "non-negative int")
            burn = rec.get("burn")
            if burn is not None and (not _num(burn)
                                     or not math.isfinite(burn)):
                failures.append(
                    f"{at}: tune.decision burn {burn!r} is neither "
                    "None nor a finite number")
        elif ev == "tune.error":
            n_tune += 1
            err = rec.get("error")
            if not isinstance(err, str) or not err:
                failures.append(
                    f"{at}: tune.error error {err!r} is not a "
                    "non-empty string")
        elif ev in BLAME_PCT_GAUGES and rec.get("kind") == "gauge":
            v = rec.get("value")
            if (not _num(v) or not math.isfinite(v)
                    or not 0.0 <= v <= 100.0):
                failures.append(
                    f"{at}: {ev} value {v!r} is not a finite share "
                    "in [0, 100]")
        elif ev == "blame.window_roots" and rec.get("kind") == "gauge":
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 0:
                failures.append(
                    f"{at}: blame.window_roots value {v!r} is not a "
                    "finite non-negative number")
    if not n_tune:
        failures.append(
            f"sink {path!r} has no tune.* records — was HPNN_TUNE "
            "armed during this run?")
    return failures


# the connection-plane record contracts (hpnn_tpu/serve/conn.py,
# HPNN_CONN_*; docs/serving.md "Connection plane")
CONN_CLOSE_REASONS = ("eof", "timeout", "reset", "torn_body", "fuzz",
                      "drain", "guard")
CONN_KILL_REASONS = ("slowloris", "stall")
CONN_GAUGES = ("conn.active", "conn.oldest_s", "conn.guard_kills")


def lint_conn(path: str) -> list[str]:
    """Schema-lint the connection-plane records of one metrics sink
    (a run with any ``HPNN_CONN_*`` knob armed — docs/serving.md
    "Connection plane").

    Checks, per record:

    * ``conn.open`` — ``kind == "count"``, positive ``n``, non-empty
      string ``id`` never opened before (a reused id merges two
      connections' ledgers), non-empty ``ip``/``plane``.
    * ``conn.close`` — same count shape; its ``id`` must pair a
      previously opened, not-yet-closed connection (an orphan close
      accounts a connection nobody admitted; a double close counts
      one death twice); ``reason`` on the closed enum
      eof/timeout/reset/torn_body/fuzz/drain/guard;
      ``bytes_in``/``bytes_out``/``requests`` non-negative ints and
      ``duration_s`` finite >= 0 when present (per-IP-cap refusals
      close at admission with none of them).
    * ``conn.guard_kill`` — count shape; ``reason`` in
      slowloris/stall; its ``id`` must name an opened connection.
    * ``conn.active`` / ``conn.oldest_s`` / ``conn.guard_kills``
      gauges — finite non-negative values (a NaN here poisons the
      alert rules watching the census).

    Connections still open at EOF fail: ``_Table.close`` pairs every
    leftover with a ``drain`` close on server shutdown, so an
    unpaired open means the sink lost a death.  A sink with no
    ``conn.*`` records fails — this lint only makes sense on a
    conn-armed run.  Returns failure strings (empty = pass)."""
    import json
    import math

    failures: list[str] = []
    try:
        with open(path) as fp:
            lines = [ln for ln in fp if ln.strip()]
    except OSError as exc:
        return [f"cannot read sink {path!r}: {exc}"]
    n_conn = 0
    opened: set[str] = set()
    closed: set[str] = set()
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line — load_events skips these too
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        at = f"record {i + 1}"
        if ev in ("conn.open", "conn.close", "conn.guard_kill"):
            n_conn += 1
            if rec.get("kind") != "count":
                failures.append(
                    f"{at}: {ev} kind {rec.get('kind')!r} != 'count'")
            if not _pos_int(rec.get("n")):
                failures.append(
                    f"{at}: {ev} increment {rec.get('n')!r} is not a "
                    "positive int")
            cid = rec.get("id")
            if not isinstance(cid, str) or not cid:
                failures.append(
                    f"{at}: {ev} id {cid!r} is not a non-empty "
                    "string")
                continue
            if ev == "conn.open":
                if cid in opened:
                    failures.append(
                        f"{at}: conn.open id {cid!r} reused — two "
                        "connections share one ledger")
                opened.add(cid)
                for key in ("ip", "plane"):
                    v = rec.get(key)
                    if not isinstance(v, str) or not v:
                        failures.append(
                            f"{at}: conn.open {key} {v!r} is not a "
                            "non-empty string")
            elif ev == "conn.close":
                if cid not in opened:
                    failures.append(
                        f"{at}: conn.close id {cid!r} pairs no "
                        "conn.open — an unadmitted death")
                elif cid in closed:
                    failures.append(
                        f"{at}: conn.close id {cid!r} closed twice")
                closed.add(cid)
                r = rec.get("reason")
                if r not in CONN_CLOSE_REASONS:
                    failures.append(
                        f"{at}: conn.close reason {r!r} not in "
                        f"{'/'.join(CONN_CLOSE_REASONS)}")
                for key in ("bytes_in", "bytes_out", "requests"):
                    v = rec.get(key)
                    if v is None:
                        continue  # per-IP-cap refusal: admit-time close
                    if (not isinstance(v, int) or isinstance(v, bool)
                            or v < 0):
                        failures.append(
                            f"{at}: conn.close {key} {v!r} is not a "
                            "non-negative int")
                d = rec.get("duration_s")
                if d is not None and (not _num(d)
                                      or not math.isfinite(d)
                                      or d < 0):
                    failures.append(
                        f"{at}: conn.close duration_s {d!r} is not a "
                        "finite non-negative number")
            else:
                if rec.get("reason") not in CONN_KILL_REASONS:
                    failures.append(
                        f"{at}: conn.guard_kill reason "
                        f"{rec.get('reason')!r} not in "
                        f"{'/'.join(CONN_KILL_REASONS)}")
                if cid not in opened:
                    failures.append(
                        f"{at}: conn.guard_kill id {cid!r} names no "
                        "opened connection")
        elif ev in CONN_GAUGES:
            n_conn += 1
            if rec.get("kind") != "gauge":
                failures.append(
                    f"{at}: {ev} kind {rec.get('kind')!r} != 'gauge'")
            v = rec.get("value")
            if not _num(v) or not math.isfinite(v) or v < 0:
                failures.append(
                    f"{at}: {ev} value {v!r} is not a finite "
                    "non-negative number")
    leaked = opened - closed
    if leaked:
        sample = ", ".join(sorted(leaked)[:4])
        failures.append(
            f"sink {path!r}: {len(leaked)} conn.open without a "
            f"paired conn.close ({sample}…) — every admitted "
            "connection must account its death (server shutdown "
            "drains leftovers with reason=drain)")
    if not n_conn:
        failures.append(
            f"sink {path!r} has no conn.* records — was any "
            "HPNN_CONN_* knob armed during this run?")
    return failures


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    failures = check(root)
    if "--ledger" in argv:
        i = argv.index("--ledger")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --ledger needs a path\n")
            return 2
        failures += lint_ledger(argv[i + 1])
    if "--perf" in argv:
        i = argv.index("--perf")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --perf needs a path\n")
            return 2
        failures += lint_perf(argv[i + 1])
    if "--slo" in argv:
        i = argv.index("--slo")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --slo needs a path\n")
            return 2
        failures += lint_slo(argv[i + 1])
    if "--online" in argv:
        i = argv.index("--online")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --online needs a "
                             "path\n")
            return 2
        failures += lint_online(argv[i + 1])
    if "--quant" in argv:
        i = argv.index("--quant")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --quant needs a "
                             "path\n")
            return 2
        failures += lint_quant(argv[i + 1])
    if "--chaos" in argv:
        i = argv.index("--chaos")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --chaos needs a "
                             "path\n")
            return 2
        failures += lint_chaos(argv[i + 1])
    if "--serve-replicas" in argv:
        i = argv.index("--serve-replicas")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --serve-replicas "
                             "needs a path\n")
            return 2
        failures += lint_serve_replicas(argv[i + 1])
    if "--fleet" in argv:
        i = argv.index("--fleet")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --fleet needs a "
                             "path\n")
            return 2
        failures += lint_fleet(argv[i + 1])
    if "--cluster" in argv:
        i = argv.index("--cluster")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --cluster needs a "
                             "path\n")
            return 2
        failures += lint_cluster(argv[i + 1])
    if "--forensics" in argv:
        i = argv.index("--forensics")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --forensics needs a "
                             "path\n")
            return 2
        failures += lint_forensics(argv[i + 1])
    if "--drift" in argv:
        i = argv.index("--drift")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --drift needs a "
                             "path\n")
            return 2
        failures += lint_drift(argv[i + 1])
    if "--tenant" in argv:
        i = argv.index("--tenant")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --tenant needs a "
                             "path\n")
            return 2
        failures += lint_tenant(argv[i + 1])
    if "--meter" in argv:
        i = argv.index("--meter")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --meter needs a "
                             "path\n")
            return 2
        failures += lint_meter(argv[i + 1])
    if "--tune" in argv:
        i = argv.index("--tune")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --tune needs a "
                             "path\n")
            return 2
        failures += lint_tune(argv[i + 1])
    if "--conn" in argv:
        i = argv.index("--conn")
        if i + 1 >= len(argv):
            sys.stderr.write("check_obs_catalog: --conn needs a "
                             "path\n")
            return 2
        failures += lint_conn(argv[i + 1])
    if failures:
        for f in failures:
            sys.stderr.write(f"check_obs_catalog: FAIL: {f}\n")
        return 1
    n = len(emitted_names(root))
    sys.stderr.write(f"check_obs_catalog: OK — {n} emitted names all "
                     "documented\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
