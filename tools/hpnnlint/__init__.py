"""hpnnlint — the repo-native static analysis suite.

The runtime lints (tools/check_obs_catalog.py, tools/check_tokens.py)
prove properties of what a run *emitted*; anything tier-1 never
exercises ships unseen.  hpnnlint closes that class statically: an
AST pass over ``hpnn_tpu/`` + ``tools/`` that enforces the repo's
cross-cutting contracts before any traffic exists to violate them.

Rules (tools/hpnnlint/rules/, catalog in docs/analysis.md):

* ``obs-catalog``    — every literal event name fed to
  ``event/count/gauge/observe/timer/span/start`` (and raw
  ``"ev": ...`` records) is documented, and every docs catalog-table
  row names an event the source can actually emit;
* ``knob-registry``  — every ``HPNN_*`` environ knob referenced in
  source is declared in the central ``hpnn_tpu.config.KNOBS`` table
  (default + owning doc page), the owning page mentions it, and
  neither the table nor the docs carry rows for knobs no longer read;
* ``lock-discipline`` — fields annotated ``# guarded: <lock>`` are
  only written inside a ``with <lock>`` block, and bare
  ``.acquire()`` calls without a try/finally release are flagged;
* ``swallow``        — ``except Exception: pass`` must narrow the
  type, emit an obs count, or carry a reasoned pragma;
* ``trace-purity``   — no ``time.time()`` / ``os.environ`` /
  ``np.random`` / host ``.item()`` reachable (one call-graph hop)
  inside functions handed to ``jit``/``vmap``/``scan``/
  ``pallas_call``.

Suppression: ``# hpnnlint: ignore[rule] -- reason`` on the finding
line (or alone on the line above).  The reason is mandatory — a bare
pragma is itself a finding (rule ``pragma``).

Run::

    python -m tools.hpnnlint hpnn_tpu tools [--json]

Exit 0 = clean, 1 = findings, 2 = usage/internal error.  The runtime
complement — the lock-order watchdog the ``lock-discipline`` rule
pairs with — is ``hpnn_tpu/obs/lockwatch.py`` (``HPNN_LOCKWATCH``).
stdlib-only.
"""

from tools.hpnnlint.engine import Finding, main, run

__all__ = ["Finding", "main", "run"]
