"""lock-discipline: annotated fields only change under their lock.

A class declares its locking contract inline, in ``__init__``::

    self._lock = threading.Lock()
    self._cool: dict[int, float] = {}   # guarded: _cool_lock

Every later write to an annotated field — plain assignment, augmented
assignment, item store, ``del``, or a mutating method call
(``append``/``pop``/``update``/...) — must sit inside a
``with self.<lock>`` block.  ``threading.Condition(self._lock)``
aliases are understood: holding the condition holds the lock.

Separately, any bare ``<x>.acquire()`` is flagged unless the matching
``release()`` is in a ``finally`` (same statement list or an
enclosing try), or the enclosing function is itself a lock-protocol
method (``acquire``/``release``/``__enter__``/``__exit__``/
``_is_owned`` — the lockwatch wrapper delegates there).

``__init__`` writes are exempt: no other thread can hold a reference
yet.  The runtime complement is hpnn_tpu/obs/lockwatch.py.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tools.hpnnlint.engine import FileCtx, Finding, Rule
from tools.hpnnlint.rules.base import dotted, terminal

GUARD_RE = re.compile(r"#\s*guarded:\s*(\w+)")
LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
              "BoundedSemaphore", "lock"}
MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
            "update", "setdefault", "discard", "add", "popleft",
            "appendleft", "sort"}
LOCK_PROTOCOL_FUNCS = {"acquire", "release", "__enter__", "__exit__",
                       "_is_owned", "locked"}


def _self_attr(node: ast.AST) -> str | None:
    """``self.<attr>`` -> attr name, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _ClassInfo:
    def __init__(self) -> None:
        self.guards: dict[str, tuple[str, int]] = {}  # field->(lock,ln)
        self.locks: set[str] = set()
        self.alias: dict[str, str] = {}  # condition attr -> lock attr


def _scan_init(cls: ast.ClassDef, ctx: FileCtx) -> _ClassInfo:
    info = _ClassInfo()
    init = next((n for n in cls.body
                 if isinstance(n, ast.FunctionDef)
                 and n.name == "__init__"), None)
    if init is None:
        return info
    for stmt in ast.walk(init):
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        attrs = [a for a in map(_self_attr, targets) if a]
        if not attrs:
            continue
        value = stmt.value
        if isinstance(value, ast.Call):
            fn = terminal(value.func)
            if fn in LOCK_CTORS:
                info.locks.update(attrs)
                if fn == "Condition" and value.args:
                    under = _self_attr(value.args[0])
                    if under:
                        for a in attrs:
                            info.alias[a] = under
        end = getattr(stmt, "end_lineno", stmt.lineno)
        for lineno in range(stmt.lineno, end + 1):
            if lineno > len(ctx.lines):
                break
            m = GUARD_RE.search(ctx.lines[lineno - 1])
            if m:
                for a in attrs:
                    info.guards[a] = (m.group(1), lineno)
                break
    return info


class LockDisciplineRule(Rule):
    name = "lock-discipline"

    def check_file(self, ctx: FileCtx) -> Iterable[Finding]:
        out: list[Finding] = []
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            info = _scan_init(cls, ctx)
            for field, (lock, lineno) in sorted(info.guards.items()):
                if lock not in info.locks and lock not in info.alias:
                    out.append(Finding(
                        self.name, ctx.rel, lineno,
                        f"`# guarded: {lock}` on self.{field} names "
                        "a lock never constructed in __init__ — "
                        "typo?"))
            if info.guards:
                for meth in cls.body:
                    if (isinstance(meth, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                            and meth.name != "__init__"):
                        self._check_method(ctx, info, meth, out)
        self._check_acquire(ctx, out)
        return out

    # --- guarded-field writes -------------------------------------

    def _canon(self, info: _ClassInfo, lock: str) -> str:
        return info.alias.get(lock, lock)

    def _held_from_with(self, info: _ClassInfo,
                        node: ast.With) -> set[str]:
        held: set[str] = set()
        for item in node.items:
            ce = item.context_expr
            if isinstance(ce, ast.Call):
                ce = ce.func  # e.g. with self._lock: vs timer()(..)
            attr = _self_attr(ce)
            if attr:
                held.add(self._canon(info, attr))
        return held

    def _check_method(self, ctx: FileCtx, info: _ClassInfo,
                      meth: ast.AST, out: list[Finding]) -> None:
        rule = self

        def written_fields(stmt: ast.stmt) -> list[tuple[str, int]]:
            hits: list[tuple[str, int]] = []

            def tgt(node: ast.AST) -> None:
                if isinstance(node, (ast.Tuple, ast.List)):
                    for elt in node.elts:
                        tgt(elt)
                    return
                base = node
                if isinstance(node, ast.Subscript):
                    base = node.value
                attr = _self_attr(base)
                if attr and attr in info.guards:
                    hits.append((attr, node.lineno))

            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (stmt.targets
                           if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    tgt(t)
            elif isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    tgt(t)
            elif isinstance(stmt, ast.Expr):
                call = stmt.value
                if (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr in MUTATORS):
                    attr = _self_attr(call.func.value)
                    if attr and attr in info.guards:
                        hits.append((attr, stmt.lineno))
            return hits

        def visit(stmts: list[ast.stmt], held: frozenset[str]) -> None:
            for stmt in stmts:
                for field, lineno in written_fields(stmt):
                    lock = info.guards[field][0]
                    if rule._canon(info, lock) not in held:
                        out.append(Finding(
                            rule.name, ctx.rel, lineno,
                            f"self.{field} is `# guarded: {lock}` "
                            f"but written here outside "
                            f"`with self.{lock}`"))
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    now = held | rule._held_from_with(info, stmt)
                    visit(stmt.body, frozenset(now))
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    # a closure may run on another thread — it must
                    # take the lock itself
                    visit(stmt.body, frozenset())
                else:
                    for block in ("body", "orelse", "finalbody",
                                  "handlers"):
                        sub = getattr(stmt, block, None)
                        if not sub:
                            continue
                        if block == "handlers":
                            for h in sub:
                                visit(h.body, held)
                        else:
                            visit(sub, held)

        visit(meth.body, frozenset())

    # --- bare .acquire() ------------------------------------------

    def _check_acquire(self, ctx: FileCtx,
                       out: list[Finding]) -> None:
        def released_in(finalbody: list[ast.stmt]) -> set[str]:
            rel: set[str] = set()
            for node in finalbody:
                for call in ast.walk(node):
                    if (isinstance(call, ast.Call)
                            and isinstance(call.func, ast.Attribute)
                            and call.func.attr == "release"):
                        chain = dotted(call.func.value)
                        if chain:
                            rel.add(chain)
            return rel

        def acquire_chain(stmt: ast.stmt) -> str | None:
            value = getattr(stmt, "value", None)
            if (isinstance(stmt, (ast.Expr, ast.Assign))
                    and isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Attribute)
                    and value.func.attr == "acquire"):
                return dotted(value.func.value) or "<expr>"
            return None

        def visit(stmts: list[ast.stmt], ok: frozenset[str],
                  fname: str) -> None:
            for i, stmt in enumerate(stmts):
                chain = acquire_chain(stmt)
                if chain is not None and fname not in \
                        LOCK_PROTOCOL_FUNCS:
                    nxt = stmts[i + 1] if i + 1 < len(stmts) else None
                    follows = (isinstance(nxt, ast.Try)
                               and chain in released_in(nxt.finalbody))
                    if chain not in ok and not follows:
                        out.append(Finding(
                            self.name, ctx.rel, stmt.lineno,
                            f"bare {chain}.acquire() without a "
                            "try/finally release — use `with` (or "
                            "obs.lockwatch.lock for named locks)"))
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    visit(stmt.body, frozenset(), stmt.name)
                    continue
                now = ok
                if isinstance(stmt, ast.Try):
                    now = ok | released_in(stmt.finalbody)
                for block in ("body", "orelse", "finalbody",
                              "handlers"):
                    sub = getattr(stmt, block, None)
                    if not sub:
                        continue
                    if block == "handlers":
                        for h in sub:
                            visit(h.body, now, fname)
                    else:
                        visit(sub, now, fname)

        visit(ctx.tree.body, frozenset(), "<module>")
