"""trace-purity: no host effects inside traced functions.

A function handed to ``jit``/``vmap``/``scan``/``pallas_call`` (or
decorated with one) runs under tracing: host reads like
``time.time()``, ``os.environ``, ``np.random`` and device syncs like
``.item()`` either burn into the compiled artifact as stale
constants or silently destroy async dispatch.  This rule finds the
traced roots in each file — decorator form, call form, and the
control-flow primitives (``scan``/``cond``/``while_loop``/
``fori_loop``/``switch``) — and scans each root plus every same-file
function it directly calls (one call-graph hop) for impure sites.

Trace-time-constant reads that are genuinely intended (a debug knob
burned in at compile time) carry
``# hpnnlint: ignore[trace-purity] -- reason``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.hpnnlint.engine import FileCtx, Finding, Rule
from tools.hpnnlint.rules.base import dotted, terminal

TRACE_DECOS = {"jit", "vmap", "pmap", "remat", "checkpoint",
               "custom_jvp", "custom_vjp"}
TRACE_CALLS = TRACE_DECOS | {"grad", "value_and_grad", "scan",
                             "fori_loop", "while_loop", "cond",
                             "switch", "shard_map", "pallas_call"}
IMPURE_CALLS = {"time.time", "time.monotonic", "time.perf_counter",
                "time.time_ns", "time.sleep", "os.getenv",
                "os.urandom"}
IMPURE_HEADS = {"os.environ", "np.random", "numpy.random"}


def _impurities(fn: ast.AST) -> list[tuple[int, str]]:
    out: set[tuple[int, str]] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = dotted(node.func)
            if chain in IMPURE_CALLS:
                out.add((node.lineno, f"{chain}()"))
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                out.add((node.lineno, ".item() host sync"))
        elif isinstance(node, ast.Attribute):
            chain = dotted(node)
            if chain:
                head = ".".join(chain.split(".")[:2])
                if head in IMPURE_HEADS:
                    out.add((node.lineno, head))
    return sorted(out)


def _is_traced_deco(deco: ast.AST) -> bool:
    if isinstance(deco, ast.Call):
        fn = terminal(deco.func)
        if fn in TRACE_DECOS:
            return True
        if fn == "partial" and deco.args:
            return terminal(deco.args[0]) in TRACE_DECOS
        return False
    return terminal(deco) in TRACE_DECOS


class TracePurityRule(Rule):
    name = "trace-purity"

    def check_file(self, ctx: FileCtx) -> Iterable[Finding]:
        module_funcs: dict[str, ast.AST] = {
            n.name: n for n in ctx.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        methods: dict[str, list[ast.AST]] = {}
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                for n in cls.body:
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        methods.setdefault(n.name, []).append(n)

        def resolve(node: ast.AST) -> tuple[str, ast.AST] | None:
            """A callable expression -> (label, FunctionDef/Lambda)."""
            if isinstance(node, ast.Lambda):
                return "<lambda>", node
            if isinstance(node, ast.Name):
                fn = module_funcs.get(node.id)
                return (node.id, fn) if fn is not None else None
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                cands = methods.get(node.attr, [])
                if len(cands) == 1:  # ambiguous across classes: skip
                    return "self." + node.attr, cands[0]
            return None

        roots: dict[int, tuple[str, ast.AST]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                if any(_is_traced_deco(d) for d in node.decorator_list):
                    roots.setdefault(id(node), (node.name, node))
            elif (isinstance(node, ast.Call)
                    and terminal(node.func) in TRACE_CALLS):
                for arg in node.args:
                    hit = resolve(arg)
                    if hit is not None:
                        roots.setdefault(id(hit[1]), hit)

        out: list[Finding] = []
        seen: set[tuple[int, str]] = set()

        def report(lineno: int, desc: str, label: str,
                   via: str | None) -> None:
            if (lineno, desc) in seen:
                return
            seen.add((lineno, desc))
            path = (f"traced `{label}` (via `{via}`)"
                    if via else f"traced `{label}`")
            out.append(Finding(
                self.name, ctx.rel, lineno,
                f"host-impure {desc} reachable inside {path} — "
                "hoist it out of the traced region"))

        for label, fn in roots.values():
            for lineno, desc in _impurities(fn):
                report(lineno, desc, label, None)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = resolve(node.func)
                if callee is None or id(callee[1]) in roots:
                    continue
                for lineno, desc in _impurities(callee[1]):
                    report(lineno, desc, label, callee[0])
        return out
