"""Shared AST helpers for hpnnlint rules."""

from __future__ import annotations

import ast


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal(func: ast.AST) -> str | None:
    """Last component of a call target: ``obs.count`` -> ``count``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def functions(tree: ast.AST):
    """Every (Async)FunctionDef/Lambda in the tree, any depth."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            yield node
