"""swallow: no silent broad excepts.

``except Exception: pass`` (or bare ``except:``) hides real failures
behind best-effort cleanup.  A broad handler must do at least one of:

* narrow the type (``except (OSError, ValueError):``),
* do *something* observable — emit an obs count, log, re-raise —
  i.e. contain any call or ``raise``,
* carry ``# hpnnlint: ignore[swallow] -- reason`` explaining why
  silence is correct (crash paths, interpreter teardown).
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.hpnnlint.engine import FileCtx, Finding, Rule

BROAD = {"Exception", "BaseException"}


def _is_broad(type_node: ast.AST | None) -> bool:
    if type_node is None:
        return True  # bare except:
    if isinstance(type_node, ast.Name):
        return type_node.id in BROAD
    if isinstance(type_node, ast.Attribute):
        return type_node.attr in BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    return False


def _is_silent(body: list[ast.stmt]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Call, ast.Raise)):
                return False
    return True


class SwallowRule(Rule):
    name = "swallow"

    def check_file(self, ctx: FileCtx) -> Iterable[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node.type) and _is_silent(node.body):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "broad except swallows silently — narrow the "
                    "type, emit an obs count, or pragma with a "
                    "reason"))
        return out
