"""obs-catalog: event names and the docs catalog agree, both ways.

Forward (same contract as tools/check_obs_catalog.py, here as an AST
pass): every literal event name handed to ``event/count/gauge/
observe/timer`` or ``spans.start`` in ``hpnn_tpu/``, and every raw
``{"ev": ...}`` record, must be documented in a catalog page
(wildcard ``family.*`` rows cover the family).

Reverse (new): every catalog *table row* — lines shaped
``| `name` | kind | ...`` with kind in event/count/gauge/timer/hist/
span/summary — must name an event the source can still emit.  A name
counts as emittable when it appears as a string literal anywhere in
``hpnn_tpu/`` (raw records and registries included) or extends a
literal dotted prefix (f-strings / concatenation build the tail).
Only table rows are held to this — prose may mention retired names
while explaining history.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from tools.hpnnlint.engine import FileCtx, Finding, Rule
from tools.hpnnlint.rules.base import dotted, str_const, terminal

EMIT_FUNCS = {"event", "count", "gauge", "observe", "timer"}
NAME_RE = re.compile(r"[a-z0-9_]+(?:\.[a-z0-9_]+)+")
DOC_RE = re.compile(r"`([a-z0-9_]+(?:\.(?:[a-z0-9_]+|\*))+)`")
ROW_RE = re.compile(
    r"^\|\s*`([a-z0-9_]+(?:\.(?:[a-z0-9_]+|\*))+)`\s*\|\s*"
    r"(event|count|gauge|timer|hist|span|summary)\s*\|")

DOC_PAGES = ("docs/observability.md", "docs/serving.md",
             "docs/fleet.md", "docs/online.md", "docs/resilience.md",
             "docs/performance.md", "docs/analysis.md",
             "docs/tenancy.md", "docs/selftuning.md")


def _covered(name: str, documented: set[str]) -> bool:
    if name in documented:
        return True
    parts = name.split(".")
    for i in range(len(parts) - 1, 0, -1):
        if ".".join(parts[:i]) + ".*" in documented:
            return True
    return False


class ObsCatalogRule(Rule):
    name = "obs-catalog"

    def __init__(self) -> None:
        # emitted event name -> first (file, line) seen
        self.emitted: dict[str, tuple[str, int]] = {}
        # every dotted-name string literal in hpnn_tpu (evidence that
        # a documented name is still reachable, e.g. via raw records)
        self.literals: set[str] = set()
        # literal dotted prefixes ("serve.", f-string heads) — a
        # documented name extending one counts as dynamically built
        self.prefixes: set[str] = set()

    def check_file(self, ctx: FileCtx) -> Iterable[Finding]:
        if not ctx.rel.startswith("hpnn_tpu" + os.sep):
            return ()
        for node in ast.walk(ctx.tree):
            s = str_const(node)
            if s is not None:
                if NAME_RE.fullmatch(s):
                    self.literals.add(s)
                elif (s.endswith(".")
                        and NAME_RE.fullmatch(s + "x")):
                    self.prefixes.add(s)
            if isinstance(node, ast.JoinedStr) and node.values:
                head = str_const(node.values[0])
                if head and "." in head:
                    self.prefixes.add(head)
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    ev = str_const(v)
                    if (k is not None and str_const(k) == "ev"
                            and ev and NAME_RE.fullmatch(ev)):
                        self.emitted.setdefault(
                            ev, (ctx.rel, node.lineno))
            if not isinstance(node, ast.Call):
                continue
            fn = terminal(node.func)
            chain = dotted(node.func) or ""
            is_emit = fn in EMIT_FUNCS or chain.endswith("spans.start")
            if not is_emit or not node.args:
                continue
            ev = str_const(node.args[0])
            if ev and NAME_RE.fullmatch(ev):
                self.emitted.setdefault(ev, (ctx.rel, node.lineno))
        return ()

    def finalize(self, root: str) -> Iterable[Finding]:
        documented: set[str] = set()
        rows: list[tuple[str, str, int]] = []  # (name, page, line)
        pages_seen = 0
        for page in DOC_PAGES:
            try:
                with open(os.path.join(root, page),
                          encoding="utf-8") as fp:
                    lines = fp.read().splitlines()
            except OSError:
                continue
            pages_seen += 1
            for lineno, line in enumerate(lines, 1):
                documented.update(DOC_RE.findall(line))
                m = ROW_RE.match(line)
                if m:
                    rows.append((m.group(1), page, lineno))
        out: list[Finding] = []
        if not self.emitted or not pages_seen:
            # only meaningful when linting the real tree; a fixture
            # tree without obs calls or docs is vacuously fine
            return out
        for ev in sorted(self.emitted):
            if not _covered(ev, documented):
                rel, lineno = self.emitted[ev]
                out.append(Finding(
                    self.name, rel, lineno,
                    f"event `{ev}` is emitted here but missing from "
                    f"the docs catalog ({', '.join(DOC_PAGES[:1])} "
                    "et al.) — add a catalog row"))
        evidence = self.literals | set(self.emitted)
        for name, page, lineno in rows:
            if name.endswith(".*"):
                fam = name[:-1]
                if any(e.startswith(fam) for e in evidence):
                    continue
            elif name in evidence:
                continue
            elif any(name.startswith(p) for p in self.prefixes):
                continue
            out.append(Finding(
                self.name, page, lineno,
                f"catalog row documents `{name}` but no emission "
                "site in hpnn_tpu/ can produce it — retire the row "
                "or restore the emitter"))
        return out
