"""knob-registry: every ``HPNN_*`` knob is declared, documented, read.

The central table is ``hpnn_tpu.config.KNOBS`` — a pure-literal dict
(``{"HPNN_X": {"default": ..., "doc": "docs/page.md", "desc": ...}}``)
so this rule can ``ast.literal_eval`` it without importing jax.

Checks:
* every knob-name string literal in linted source is a KNOBS key;
* every KNOBS entry has ``default``/``doc``/``desc``, its doc page
  exists, that page actually mentions the knob, and some source file
  still reads it;
* every ``HPNN_*`` token in the doc pages is a declared knob
  (``HPNN_FAMILY_*`` wildcards cover the family).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from tools.hpnnlint.engine import FileCtx, Finding, Rule
from tools.hpnnlint.rules.base import str_const

KNOB_RE = re.compile(r"HPNN_[A-Z][A-Z0-9_]*")
CONFIG_REL = os.path.join("hpnn_tpu", "config.py")
DOC_PAGES = ("docs/observability.md", "docs/serving.md",
             "docs/fleet.md", "docs/online.md", "docs/resilience.md",
             "docs/performance.md", "docs/analysis.md",
             "docs/api.md", "docs/tenancy.md", "docs/selftuning.md")
REQUIRED_KEYS = ("default", "doc", "desc")


class KnobRegistryRule(Rule):
    name = "knob-registry"

    def __init__(self) -> None:
        # knob -> first (file, line) that reads it
        self.used: dict[str, tuple[str, int]] = {}
        self.table: dict | None = None
        self.table_line = 1
        self.table_err: str | None = None
        self.saw_config = False

    def _load_table(self, ctx: FileCtx) -> None:
        self.saw_config = True
        for node in ctx.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if "KNOBS" not in names:
                continue
            self.table_line = node.lineno
            try:
                self.table = ast.literal_eval(node.value)
            except ValueError:
                self.table_err = ("KNOBS must be a pure literal dict "
                                  "(ast.literal_eval-able)")
            return node.lineno, node.end_lineno
        self.table_err = "no `KNOBS = {...}` assignment found"
        return None

    def check_file(self, ctx: FileCtx) -> Iterable[Finding]:
        skip_span = None
        if ctx.rel == CONFIG_REL:
            skip_span = self._load_table(ctx)
        for node in ast.walk(ctx.tree):
            s = str_const(node)
            if s is None or not KNOB_RE.fullmatch(s):
                continue
            if (skip_span
                    and skip_span[0] <= node.lineno <= skip_span[1]):
                continue  # the declaration itself is not a use
            self.used.setdefault(s, (ctx.rel, node.lineno))
        return ()

    def finalize(self, root: str) -> Iterable[Finding]:
        out: list[Finding] = []
        if not self.saw_config:
            return out  # fixture tree without a config module
        if self.table is None:
            out.append(Finding(
                self.name, CONFIG_REL, self.table_line,
                self.table_err or "KNOBS table unreadable"))
            return out
        declared = set(self.table)
        for knob in sorted(self.used):
            if knob not in declared:
                rel, lineno = self.used[knob]
                out.append(Finding(
                    self.name, rel, lineno,
                    f"knob `{knob}` is read here but not declared in "
                    "hpnn_tpu.config.KNOBS — add a row (default, "
                    "doc page, description)"))
        for knob in sorted(declared):
            entry = self.table[knob]
            if (not isinstance(entry, dict)
                    or any(k not in entry for k in REQUIRED_KEYS)):
                out.append(Finding(
                    self.name, CONFIG_REL, self.table_line,
                    f"KNOBS[{knob!r}] must be a dict with keys "
                    f"{REQUIRED_KEYS}"))
                continue
            if knob not in self.used:
                # a knob read outside the lint scope (bench.py, the
                # test harness) declares its reader explicitly, and
                # we verify the claim against that file's text
                reader = entry.get("reader")
                ok = False
                if reader:
                    try:
                        with open(os.path.join(root, reader),
                                  encoding="utf-8") as fp:
                            ok = knob in fp.read()
                    except OSError:
                        ok = False
                if not ok:
                    out.append(Finding(
                        self.name, CONFIG_REL, self.table_line,
                        f"KNOBS declares `{knob}` but no linted "
                        "source (nor its declared 'reader' file) "
                        "reads it — retire the row"))
            page = entry["doc"]
            path = os.path.join(root, page)
            if not os.path.isfile(path):
                out.append(Finding(
                    self.name, CONFIG_REL, self.table_line,
                    f"KNOBS[{knob!r}] points at missing doc page "
                    f"{page!r}"))
                continue
            with open(path, encoding="utf-8") as fp:
                text = fp.read()
            hits = set(KNOB_RE.findall(text))
            fams = {h for h in hits
                    if text.count(h + "*")}  # HPNN_FAM_* wildcard
            if knob not in hits and not any(
                    knob.startswith(f) for f in fams):
                out.append(Finding(
                    self.name, CONFIG_REL, self.table_line,
                    f"KNOBS[{knob!r}] names {page!r} as its doc page "
                    "but the page never mentions the knob"))
        for page in DOC_PAGES:
            try:
                with open(os.path.join(root, page),
                          encoding="utf-8") as fp:
                    lines = fp.read().splitlines()
            except OSError:
                continue
            for lineno, line in enumerate(lines, 1):
                for m in KNOB_RE.finditer(line):
                    tok = m.group(0)
                    rest = line[m.end():]
                    if rest.startswith("*") or tok.endswith("_"):
                        fam = tok.rstrip("_")
                        if any(d.startswith(fam) for d in declared):
                            continue
                    elif tok in declared:
                        continue
                    out.append(Finding(
                        self.name, page, lineno,
                        f"docs mention `{tok}` but it is not in "
                        "hpnn_tpu.config.KNOBS — declare it or drop "
                        "the stale mention"))
        return out
