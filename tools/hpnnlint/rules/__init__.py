"""Rule registry for hpnnlint — one module per rule."""

from __future__ import annotations

from tools.hpnnlint.rules.knob_registry import KnobRegistryRule
from tools.hpnnlint.rules.lock_discipline import LockDisciplineRule
from tools.hpnnlint.rules.obs_catalog import ObsCatalogRule
from tools.hpnnlint.rules.swallow import SwallowRule
from tools.hpnnlint.rules.trace_purity import TracePurityRule


def all_rules():
    return [
        ObsCatalogRule(),
        KnobRegistryRule(),
        LockDisciplineRule(),
        SwallowRule(),
        TracePurityRule(),
    ]
