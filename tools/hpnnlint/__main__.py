import sys

from tools.hpnnlint.engine import main

sys.exit(main(sys.argv[1:]))
