"""The hpnnlint engine: file walking, pragma grammar, rule driving.

A :class:`Rule` sees every linted file once (:meth:`Rule.check_file`)
and then gets one :meth:`Rule.finalize` call for cross-file checks
(doc catalogs, the knob table).  Findings carry ``rule``/``file``/
``line``/``msg``; the engine owns suppression, ordering, rendering,
and exit codes so rules stay pure.

Pragma grammar (docs/analysis.md)::

    # hpnnlint: ignore[rule1,rule2] -- why this is safe

The reason text after the bracket is mandatory; a reasonless pragma
is reported under the (unsuppressable) ``pragma`` rule.  A pragma
suppresses findings on its own line, or — when it is a comment-only
line — on the line below.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from typing import Iterable, NamedTuple

PRAGMA_RE = re.compile(
    r"#\s*hpnnlint:\s*ignore\[([a-z\-, ]+)\]\s*(?:--|:)?\s*(\S.*)?$")

SKIP_DIRS = {"__pycache__", ".git"}


class Finding(NamedTuple):
    rule: str
    file: str       # repo-relative path
    line: int       # 1-based
    msg: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.msg}"


class FileCtx:
    """One parsed source file: text, AST, and its pragma index."""

    def __init__(self, root: str, rel: str, text: str,
                 tree: ast.Module):
        self.root = root
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        # line -> set of rule names suppressed there
        self.pragmas: dict[int, set[str]] = {}
        self.bad_pragma_lines: list[int] = []
        self._index_pragmas()

    def _index_pragmas(self) -> None:
        for lineno, line in enumerate(self.lines, 1):
            m = PRAGMA_RE.search(line)
            if m is None:
                continue
            rules = {r.strip() for r in m.group(1).split(",")
                     if r.strip()}
            reason = (m.group(2) or "").strip()
            if not rules or not reason:
                self.bad_pragma_lines.append(lineno)
                continue
            target = lineno
            if line.lstrip().startswith("#"):
                # comment-only pragma line covers the next line too
                self.pragmas.setdefault(lineno + 1, set()).update(rules)
            self.pragmas.setdefault(target, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        return rule in self.pragmas.get(line, ())


class Rule:
    """Base rule: override ``check_file`` and/or ``finalize``."""

    name = "rule"

    def check_file(self, ctx: FileCtx) -> Iterable[Finding]:
        return ()

    def finalize(self, root: str) -> Iterable[Finding]:
        return ()


def _default_rules() -> list[Rule]:
    from tools.hpnnlint.rules import all_rules

    return all_rules()


def iter_py_files(root: str, paths: list[str]) -> list[str]:
    """Repo-relative .py files under the given relative paths."""
    out: list[str] = []
    for path in paths:
        full = os.path.join(root, path)
        if os.path.isfile(full):
            out.append(path)
            continue
        for dirpath, dirs, files in os.walk(full):
            dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
            for fn in sorted(files):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fn), root))
    return sorted(set(out))


def run(root: str, paths: list[str],
        rules: list[Rule] | None = None) -> tuple[list[Finding], int]:
    """Lint ``paths`` (repo-relative) under ``root``; returns
    (findings, files_linted).  Findings are pragma-filtered and
    sorted (file, line, rule)."""
    if rules is None:
        rules = _default_rules()
    ctxs: dict[str, FileCtx] = {}
    findings: list[Finding] = []
    files = iter_py_files(root, paths)
    for rel in files:
        full = os.path.join(root, rel)
        try:
            with open(full, encoding="utf-8") as fp:
                text = fp.read()
            tree = ast.parse(text, filename=rel)
        except (OSError, SyntaxError) as exc:
            findings.append(Finding("parse", rel, 1,
                                    f"cannot lint: {exc}"))
            continue
        ctx = FileCtx(root, rel, text, tree)
        ctxs[rel] = ctx
        for lineno in ctx.bad_pragma_lines:
            findings.append(Finding(
                "pragma", rel, lineno,
                "pragma without a reason — write "
                "'# hpnnlint: ignore[rule] -- why'"))
        for rule in rules:
            findings.extend(rule.check_file(ctx))
    for rule in rules:
        findings.extend(rule.finalize(root))
    kept = []
    for f in findings:
        ctx = ctxs.get(f.file)
        if (f.rule not in ("pragma", "parse") and ctx is not None
                and ctx.suppressed(f.rule, f.line)):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.file, f.line, f.rule))
    return kept, len(files)


def to_json(findings: list[Finding], n_files: int) -> dict:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "version": 1,
        "files": n_files,
        "findings": [f._asdict() for f in findings],
        "counts": counts,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hpnnlint",
        description="repo-native static analysis (docs/analysis.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="repo-relative dirs/files (default: "
                         "hpnn_tpu tools)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto from this file)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only the named rule(s)")
    try:
        args = ap.parse_args(argv)
    except SystemExit as exc:
        return 2 if exc.code not in (0, None) else 0
    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    paths = args.paths or ["hpnn_tpu", "tools"]
    rules = _default_rules()
    if args.rule:
        known = {r.name for r in rules}
        bad = set(args.rule) - known
        if bad:
            print(f"hpnnlint: unknown rule(s) {sorted(bad)} "
                  f"(have {sorted(known)})", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in set(args.rule)]
    try:
        findings, n_files = run(root, paths, rules)
    except Exception as exc:  # an engine crash is exit 2, not "clean"
        print(f"hpnnlint: internal error: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(to_json(findings, n_files), indent=2,
                         sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        print(f"hpnnlint: {len(findings)} finding(s) over "
              f"{n_files} file(s)")
    return 1 if findings else 0
