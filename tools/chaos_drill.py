#!/usr/bin/env python3
"""Chaos drills: scripted disasters against a live train-while-serve
process, with the blast radius measured, not guessed.

Each drill is a supervisor: it spawns a real ``online_nn`` server as
a child process, drives it with ``tools/loadgen.py`` open-loop
traffic (the ``lost`` outcome class — connection refused/reset/torn
response — is this tool's raw material), injures it on purpose, and
reports how far the damage spread and how fast it healed.  The drill
catalog (docs/resilience.md):

* **kill9** — SIGKILL mid-traffic after at least one promotion has
  committed to the WAL (``HPNN_WAL_DIR``), then restart on the same
  port and WAL dir.  Asserts the restarted process resumes the last
  *committed* weights bitwise (``/healthz`` ``weights_sha`` vs the
  supervisor's own read of the WAL checkpoint), that the readiness
  gate (``/readyz`` 503 + Retry-After) holds traffic while the WAL
  replays, and measures goodput dip %, recovery seconds, and lost
  requests from the loadgen record stream.
* **reload** — hot-reload under load: the supervisor rewrites the
  served checkpoint file and POSTs ``/v1/reload`` while traffic
  flows, with ``HPNN_CHAOS="raise@registry.reload:times=1"`` armed in
  the child so the FIRST attempt fails (500, retriable, resident
  version kept) and the retry lands.  Asserts the new weights are
  served, nothing was lost, and goodput held.
* **sentinel** — ``HPNN_CHAOS="nan@train.round"`` corrupts every
  trained candidate; the promotion gate's sentinel must reject all of
  them while serving stays clean (version pinned, zero lost).
* **replica** — multi-replica scale-out under fire: an in-process
  :class:`~hpnn_tpu.serve.router.Router` over N replicas behind the
  real HTTP front end, loadgen traffic flowing, then
  ``kill_replica(0)`` mid-stream.  Asserts the router routes around
  the corpse (goodput dips boundedly, recovers), that NO request
  arriving after the kill settles is lost (``survivors_lost`` — the
  router's route-around is supposed to make a replica death invisible
  at the edge), and that survivors answer bitwise-identically to the
  pre-kill fleet.
* **alert** — the telemetry plane's live proof: a
  ``router.ready_replicas`` threshold rule (``HPNN_ALERTS``,
  obs/alerts.py) armed over the same in-process Router, then
  ``kill_replica(0)`` under traffic.  Asserts ``alert.fire`` lands
  (flight-recorder dump attached) within a bounded window and that
  ``spawn_replica()`` resolves it (``alert.resolve``).
* **worker** — the cross-host twin of *replica*: a
  :class:`~hpnn_tpu.fleet.worker.WorkerSupervisor` over N real
  ``online_nn`` worker PROCESSES sharing one WAL, a
  :class:`~hpnn_tpu.fleet.router.ClusterRouter` as the HTTP edge,
  then SIGKILL one worker mid-stream.  Asserts the router routes
  around the corpse (bounded dip, zero ``survivors_lost``, bitwise
  survivor answers) and that the supervisor restart policy REPLACES
  it — readiness-gated — within a bounded ``replaced_s``.

* **capsule** — the tail-latency forensics plane's live proof
  (docs/observability.md "Forensics"): tail sampler armed
  (``HPNN_SAMPLE``), a chaos delay injected at the
  ``serve.dispatch`` seam, an ``slo.p99_ms`` threshold rule and a
  capsule dir (``HPNN_CAPSULE_DIR``).  Load drives p99 over the
  bound; asserts the fired alert landed a capture capsule (spans +
  a real profiler window) within a bounded window, and that
  ``tools/tail_report.py`` over the run's sink blames the dispatch
  phase — the injected seam — for most of the tail.

* **drift** — the drift observability plane's live proof
  (docs/observability.md "Drift detection"): an in-process
  train-while-serve session learns the clean synthetic-MNIST
  stream, ``HPNN_DRIFT`` is armed (references freeze, the decay
  sentinel warms up), then the stream's labels are remapped
  (``streams.label_shift``).  Asserts the held-out decay drives
  ``drift.score`` over a threshold rule → ``alert.fire`` → a
  capsule whose ``drift.json`` carries both the reference and the
  post-shift sketches, with serving answering throughout (zero
  lost) and detection latency bounded.

* **quota** — the multi-tenant isolation proof (docs/tenancy.md):
  two gold victims and one rate-capped bronze offender share an
  in-process TenantSession; the offender overdrives its admission
  budget 10x.  Asserts the victims' goodput and p99 hold, every
  refusal is a clean ``shed reason=quota`` naming its tenant, and
  the ``tenant.shed_rate`` threshold rule fires.

* **hog** — the per-tenant metering proof (docs/observability.md
  "Tenant metering"): a zipf tenant population under ``HPNN_METER``,
  then one rate-capped tenant offers 20x the zipf head's rate.
  Asserts the fleet-merged top-K from the sink's ``meter.sketch``
  stream names the hog within a bounded window,
  ``tools/tenant_report.py`` blames it for the majority of
  device-seconds, the shed-rate rule fires, and the alert-triggered
  capsule carries ``meter.json``.

* **tune** — the self-tuning remediation plane's proof
  (docs/selftuning.md): per blame class, a synthetic span stream
  makes that class dominate the REAL online blame window
  (obs/blame.py), and a scripted-clock/scripted-p99
  :class:`~hpnn_tpu.tune.engine.Tuner` over real actuator targets
  (a live compiled serve Session, an Autoscaler over an in-memory
  supervisor, a QuotaEnforcer) must apply the MATCHING action —
  ``tune.apply`` in the sink — and see the tail recover through its
  watch window.  Two deliberately bad moves then prove rollback:
  a p99 regression inside the watch and a direct bad-action
  rollback, each restoring the displaced config bitwise (precision
  version chain strictly monotone, quota specs tuple-identical),
  with ``tools/check_obs_catalog.py --tune`` passing over the
  drill's own sink.

* **torn** — the connection plane's live proof (docs/serving.md
  "Connection plane"): a conn-guarded server (``HPNN_CONN_*``) under
  clean loadgen traffic is attacked by ``loadgen.run_hostile``
  slowloris / torn-body / fuzz clients with a ``conn.guard_kills``
  threshold rule and a capsule dir armed.  Asserts clean goodput
  dips ≤ 10% with ZERO clean lost, every hostile connection is
  accounted by close reason (``guard``/``torn_body``/``fuzz``/
  ``timeout``/``reset``), every slowloris is guard-killed and the
  kill fires the alert → a capsule carrying ``conn.json``, the
  drill's own sink passes ``check_obs_catalog.py --conn``, and no
  attacker thread is left hung.

Outcome rows are JSONL (``--out``) with ``ev`` = ``drill.kill9`` |
``drill.reload`` | ``drill.sentinel`` | ``drill.replica`` |
``drill.alert`` | ``drill.worker`` | ``drill.capsule`` |
``drill.drift`` | ``drill.quota`` | ``drill.hog`` | ``drill.tune`` |
``drill.torn``; :func:`run_bench_drill` /
:func:`run_bench_replica_drill` / :func:`run_bench_alert_drill` /
:func:`run_bench_worker_drill` / :func:`run_bench_capsule_drill` /
:func:`run_bench_drift_drill` / :func:`run_bench_quota_drill` /
:func:`run_bench_hog_drill` / :func:`run_bench_tune_drill` /
:func:`run_bench_torn_drill` are
the bench.py fold-ins (compact keys ``drill_recovery_s`` /
``drill_goodput_dip_pct`` / ``drill_lost_requests`` /
``drill_replica_dip_pct`` / ``drill_replica_survivors_lost`` /
``drill_alert_fire_s`` / ``drill_alert_resolved`` /
``drill_worker_dip_pct`` / ``drill_worker_replaced_s`` /
``drill_capsule_capture_s`` / ``drill_capsule_blame_pct`` /
``drill_drift_detect_s`` / ``drill_quota_victim_goodput_ratio`` /
``drill_hog_blame_pct`` / ``drill_hog_detect_s`` /
``drill_tune_applies`` / ``drill_tune_rollback_bitwise`` /
``drill_torn_dip_pct`` / ``drill_torn_clean_lost``, gated by
``tools/bench_gate.py``).  Skips cleanly (``"skipped"``) when the
child cannot start.

    JAX_PLATFORMS=cpu python tools/chaos_drill.py --drill kill9
    python tools/chaos_drill.py --drill all --out drills.jsonl
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)
TOOLS = os.path.dirname(os.path.abspath(__file__))
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

KERNEL = "drill"

CONF = (f"[name] {KERNEL}\n[type] ANN\n[init] generate\n[seed] 7\n"
        "[input] 8\n[hidden] 5\n[output] 2\n[train] BP\n")


# ------------------------------------------------------------ plumbing


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def http_get(port: int, path: str, timeout_s: float = 2.0):
    """-> (status, parsed-json-or-None); (None, None) when nothing
    answered (refused/reset — the connection-level loss class)."""
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        data = resp.read()
        try:
            return resp.status, json.loads(data)
        except ValueError:
            return resp.status, None
    except (OSError, http.client.HTTPException):
        return None, None
    finally:
        conn.close()


def http_post(port: int, path: str, body: dict,
              timeout_s: float = 5.0):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout_s)
    try:
        conn.request("POST", path, body=json.dumps(body).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        try:
            return resp.status, json.loads(data)
        except ValueError:
            return resp.status, None
    except (OSError, http.client.HTTPException):
        return None, None
    finally:
        conn.close()


def weights_sha(weights) -> str:
    """Bitwise identity of a weight tuple — the same digest the
    online session publishes per kernel in ``/healthz``."""
    sha = hashlib.sha256()
    for w in weights:
        sha.update(np.ascontiguousarray(np.asarray(w)).tobytes())
    return sha.hexdigest()[:16]


class Child:
    """One ``online_nn`` child process under supervision."""

    def __init__(self, workdir: str, port: int, *, wal_dir=None,
                 chaos=None, interval_s: float = 0.2,
                 rows: int = 16, batch: int = 8, epochs: int = 2,
                 margin: float = -0.5, log_name: str = "child.log"):
        self.workdir = workdir
        self.port = port
        conf_path = os.path.join(workdir, "nn.conf")
        if not os.path.exists(conf_path):
            with open(conf_path, "w") as fp:
                fp.write(CONF)
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.pop("HPNN_CHAOS", None)
        env.pop("HPNN_CHAOS_SEED", None)
        env.pop("HPNN_WAL_DIR", None)
        if wal_dir:
            env["HPNN_WAL_DIR"] = str(wal_dir)
        if chaos:
            env["HPNN_CHAOS"] = chaos
        self.log_path = os.path.join(workdir, log_name)
        self._log = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "hpnn_tpu.cli.online_nn",
             "--port", str(port),
             "--interval-s", str(interval_s),
             "--rows", str(rows), "--batch", str(batch),
             "--epochs", str(epochs), "--margin", str(margin),
             conf_path],
            cwd=ROOT, env=env, stdin=subprocess.DEVNULL,
            stdout=self._log, stderr=self._log)

    def wait_ready(self, timeout_s: float = 90.0) -> dict:
        """Poll ``/readyz`` until 200; returns
        ``{"ready": bool, "gated": saw-a-503, "waited_s": ...}``."""
        t0 = time.monotonic()
        gated = False
        while time.monotonic() - t0 < timeout_s:
            if self.proc.poll() is not None:
                break
            code, _doc = http_get(self.port, "/readyz",
                                  timeout_s=1.0)
            if code == 200:
                return {"ready": True, "gated": gated,
                        "waited_s": round(time.monotonic() - t0, 3)}
            if code == 503:
                gated = True
            time.sleep(0.05)
        return {"ready": False, "gated": gated,
                "waited_s": round(time.monotonic() - t0, 3)}

    def health(self) -> dict | None:
        code, doc = http_get(self.port, "/healthz")
        return doc if code == 200 else None

    def kill9(self) -> None:
        try:
            self.proc.send_signal(signal.SIGKILL)
        except OSError:
            pass
        self.proc.wait(timeout=10)
        self._close_log()

    def terminate(self, timeout_s: float = 10.0) -> int | None:
        """SIGTERM (the graceful-drain path) and wait; returns the
        exit code (0 proves the drain handler ran to completion)."""
        if self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)
        self._close_log()
        return self.proc.returncode

    def _close_log(self):
        try:
            self._log.close()
        except OSError:
            pass


# -------------------------------------------------------- measurement


def goodput_bins(records: list[dict], *, bin_s: float = 0.5) -> dict:
    """Per-bin ok counts keyed by bin start offset (seconds on the
    loadgen clock)."""
    bins: dict[float, int] = {}
    for r in records:
        b = round(int(r["t"] / bin_s) * bin_s, 3)
        bins.setdefault(b, 0)
        if r["status"] == "ok":
            bins[b] += 1
    return bins


def blast_radius(records: list[dict], t_kill: float, *,
                 bin_s: float = 0.5,
                 recovered_frac: float = 0.8) -> dict:
    """Goodput dip/recovery around a disruption at ``t_kill`` (same
    clock as the records' ``t``): baseline = median ok-count of the
    pre-kill bins, recovery = first post-kill bin back at
    ``recovered_frac`` of baseline."""
    bins = goodput_bins(records, bin_s=bin_s)
    pre = [n for b, n in sorted(bins.items()) if b + bin_s <= t_kill]
    base = float(np.median(pre)) if pre else 0.0
    post = [(b, n) for b, n in sorted(bins.items()) if b >= t_kill]
    recovery_s = None
    dip = base
    for b, n in post:
        dip = min(dip, n)
        if base > 0 and n >= recovered_frac * base:
            recovery_s = round(b + bin_s - t_kill, 3)
            break
    dip_pct = (round(100.0 * (base - dip) / base, 1) if base > 0
               else None)
    lost = sum(1 for r in records if r["status"] == "lost")
    shed = sum(1 for r in records if r["status"] == "shed")
    return {
        "baseline_ok_per_bin": base,
        "bin_s": bin_s,
        "goodput_dip_pct": dip_pct,
        "recovery_s": recovery_s,
        "lost": lost,
        "shed": shed,
        "requests": len(records),
    }


class _Load:
    """Background loadgen run with live record capture + early stop."""

    def __init__(self, port: int, *, rate: float = 40.0,
                 duration_s: float = 240.0, ingest_frac: float = 0.5,
                 retries: int = 3, seed: int = 0,
                 kernels: tuple = (KERNEL,), n_in: int = 8,
                 n_out: int = 2):
        import loadgen

        self.records: list[dict] = []
        self.stop = threading.Event()
        self.summary: dict | None = None
        self.t0 = time.perf_counter()

        def run():
            self.summary = loadgen.run_open_loop(
                f"http://127.0.0.1:{port}", rate_rps=rate,
                duration_s=duration_s, process="poisson",
                kernels=tuple(kernels), rows_choices=(1, 2),
                n_in=n_in, timeout_s=2.0, max_retries=retries,
                retry_cap_s=0.25, n_workers=8, seed=seed,
                ingest_frac=ingest_frac, n_out=n_out, stop=self.stop,
                on_record=self.records.append)

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()

    def now(self) -> float:
        """Offset on the records' ``t`` clock (same perf_counter
        epoch, modulo loadgen's own setup time — well under a bin)."""
        return time.perf_counter() - self.t0

    def finish(self, settle_s: float = 0.0) -> list[dict]:
        if settle_s > 0:
            time.sleep(settle_s)
        self.stop.set()
        self.thread.join(timeout=30)
        return list(self.records)


def _wait(pred, timeout_s: float, interval_s: float = 0.1):
    """Poll ``pred()`` until truthy; returns its value or None."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        v = pred()
        if v:
            return v
        time.sleep(interval_s)
    return None


# ------------------------------------------------------------- drills


def _shield_sigpipe() -> None:
    """SIGPIPE back to ignored (Python's startup default) before any
    drill traffic: the supervisor deliberately kills children that
    hold live sockets, and a host process that ran one of the CLI
    mains in-process would otherwise carry their SIG_DFL disposition
    — turning the drill's own measurement (a torn write, recorded as
    ``lost``) into supervisor death.  Must run on the main thread;
    loadgen's worker threads inherit the process disposition."""
    try:
        signal.signal(signal.SIGPIPE, signal.SIG_IGN)
    except (ValueError, AttributeError):  # non-main thread / platform
        pass


def drill_kill9(workdir: str, *, rate: float = 40.0,
                promote_timeout_s: float = 60.0,
                ready_timeout_s: float = 90.0,
                seed: int = 0) -> dict:
    """SIGKILL mid-traffic after a WAL-committed promotion, restart
    on the same port + WAL dir, prove bitwise resume + measure the
    blast radius."""
    from hpnn_tpu.online import wal as wal_mod

    _shield_sigpipe()
    out: dict = {"ev": "drill.kill9", "ok": False}
    wal_dir = os.path.join(workdir, "wal")
    port = free_port()
    child = Child(workdir, port, wal_dir=wal_dir,
                  log_name="kill9-a.log")
    try:
        ready = child.wait_ready(ready_timeout_s)
        if not ready["ready"]:
            out["skipped"] = "child never became ready"
            return out
        load = _Load(port, rate=rate, ingest_frac=0.5, seed=seed)

        def promoted():
            doc = child.health()
            if doc is None:
                return None
            on = doc.get("online", {})
            if on.get("promoter", {}).get("promoted", 0) < 1:
                return None
            w = wal_mod.PromotionWAL(wal_dir)
            return w.last_committed(KERNEL)

        rec = _wait(promoted, promote_timeout_s, interval_s=0.2)
        if rec is None:
            load.finish()
            out["skipped"] = "no WAL-committed promotion in time"
            return out
        # let post-promotion goodput establish the baseline bins
        time.sleep(1.5)
        t_kill = load.now()
        child.kill9()
        # ground truth from the supervisor's own read of the WAL
        restored = wal_mod.PromotionWAL(wal_dir).restore(KERNEL)
        if restored is None:
            load.finish()
            out["error"] = "WAL unreadable after kill"
            return out
        ws, rec = restored
        expect_sha = weights_sha(ws)
        child = Child(workdir, port, wal_dir=wal_dir,
                      log_name="kill9-b.log")
        ready = child.wait_ready(ready_timeout_s)
        out["readyz_gated"] = ready["gated"]
        out["restart_ready_s"] = ready["waited_s"]
        if not ready["ready"]:
            load.finish()
            out["error"] = "restarted child never became ready"
            return out
        # read the resident digest at the readiness edge, BEFORE the
        # settle traffic: the restarted trainer starts promoting new
        # versions within a round or two, and those are supposed to
        # differ from the restored checkpoint
        doc = child.health() or {}
        kdoc = doc.get("online", {}).get("kernels", {}).get(KERNEL, {})
        got_sha = kdoc.get("weights_sha")
        records = load.finish(settle_s=2.0)
        out.update(blast_radius(records, t_kill))
        out["wal_version"] = int(rec.get("version", -1))
        out["weights_sha"] = {"expect": expect_sha, "got": got_sha}
        out["restored_bitwise"] = bool(got_sha == expect_sha)
        out["restored_doc"] = (doc.get("online", {}).get("wal", {})
                               .get("restored"))
        out["ok"] = bool(out["restored_bitwise"]
                         and out["recovery_s"] is not None)
        return out
    finally:
        child.terminate()


def drill_reload(workdir: str, *, rate: float = 40.0,
                 ready_timeout_s: float = 90.0,
                 seed: int = 1) -> dict:
    """Hot-reload under load, first attempt chaos-failed: rewrite the
    served checkpoint, POST /v1/reload twice (raise@registry.reload
    armed for one firing), prove the new weights landed with zero
    lost requests."""
    from hpnn_tpu.fileio import checkpoint as ckpt_mod
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.online import wal as wal_mod

    _shield_sigpipe()
    out: dict = {"ev": "drill.reload", "ok": False}
    wal_dir = os.path.join(workdir, "wal")
    # seed the WAL so the child's kernel is checkpoint-backed (the
    # hot-reload path needs a file to watch)
    k1, _ = kernel_mod.generate(11, 8, [5], 2)
    wal = wal_mod.PromotionWAL(wal_dir)
    rec = wal.commit(KERNEL, k1.weights, version=1, reason="seed")
    ckpt_path = os.path.join(wal_dir, rec["ckpt"])
    port = free_port()
    child = Child(workdir, port, wal_dir=wal_dir,
                  chaos="raise@registry.reload:times=1",
                  interval_s=60.0,  # trainer parked: reload is the act
                  log_name="reload.log")
    try:
        ready = child.wait_ready(ready_timeout_s)
        if not ready["ready"]:
            out["skipped"] = "child never became ready"
            return out
        load = _Load(port, rate=rate, ingest_frac=0.0, seed=seed)
        time.sleep(1.5)           # baseline bins
        k2, _ = kernel_mod.generate(13, 8, [5], 2)
        ckpt_mod.dump_checkpoint(ckpt_path, KERNEL, k2.weights,
                                 version=2, meta={"reason": "drill"})
        t_act = load.now()
        code1, _ = http_post(port, "/v1/reload", {"kernel": KERNEL})
        code2, _ = http_post(port, "/v1/reload", {"kernel": KERNEL})
        records = load.finish(settle_s=1.5)
        doc = child.health() or {}
        kdoc = doc.get("online", {}).get("kernels", {}).get(KERNEL, {})
        out.update(blast_radius(records, t_act))
        out["reload_codes"] = [code1, code2]
        out["chaos_failed_first"] = bool(code1 == 500)
        out["weights_sha"] = {"expect": weights_sha(k2.weights),
                              "got": kdoc.get("weights_sha")}
        out["reloaded_bitwise"] = (out["weights_sha"]["got"]
                                   == out["weights_sha"]["expect"])
        out["ok"] = bool(out["chaos_failed_first"]
                         and code2 == 200
                         and out["reloaded_bitwise"]
                         and out["lost"] == 0)
        return out
    finally:
        child.terminate()


def drill_sentinel(workdir: str, *, rate: float = 40.0,
                   ready_timeout_s: float = 90.0,
                   reject_timeout_s: float = 60.0,
                   seed: int = 2) -> dict:
    """Sentinel abort under load: every candidate is NaN-corrupted
    (``nan@train.round``); the gate must reject them all while the
    resident version keeps serving untouched."""
    _shield_sigpipe()
    out: dict = {"ev": "drill.sentinel", "ok": False}
    port = free_port()
    child = Child(workdir, port, chaos="nan@train.round",
                  log_name="sentinel.log")
    try:
        ready = child.wait_ready(ready_timeout_s)
        if not ready["ready"]:
            out["skipped"] = "child never became ready"
            return out
        doc0 = child.health() or {}
        k0 = doc0.get("online", {}).get("kernels", {}).get(KERNEL, {})
        sha0, v0 = k0.get("weights_sha"), k0.get("version")
        load = _Load(port, rate=rate, ingest_frac=0.5, seed=seed)

        def rejected():
            doc = child.health()
            if doc is None:
                return None
            on = doc.get("online", {})
            return (on.get("promoter", {}).get("rejected", 0) >= 2
                    and on.get("trainer", {}).get("trained", 0) >= 2
                    or None)

        saw = _wait(rejected, reject_timeout_s, interval_s=0.2)
        records = load.finish(settle_s=0.5)
        doc = child.health() or {}
        on = doc.get("online", {})
        k1 = on.get("kernels", {}).get(KERNEL, {})
        out["rejected"] = on.get("promoter", {}).get("rejected", 0)
        out["promoted"] = on.get("promoter", {}).get("promoted", 0)
        out["version"] = {"before": v0, "after": k1.get("version")}
        out["weights_sha"] = {"before": sha0,
                              "after": k1.get("weights_sha")}
        out["lost"] = sum(1 for r in records
                          if r["status"] == "lost")
        out["requests"] = len(records)
        out["ok"] = bool(saw
                         and out["promoted"] == 0
                         and k1.get("version") == v0
                         and k1.get("weights_sha") == sha0
                         and out["lost"] == 0)
        return out
    finally:
        child.terminate()


def drill_replica(workdir: str, *, rate: float = 80.0,
                  n_replicas: int = 3, seed: int = 3) -> dict:
    """Kill one of N router replicas under load: an in-process
    Router behind ``make_server``, loadgen flowing, then
    ``kill_replica(0)``.  The route-around contract: bounded goodput
    dip, full recovery, zero lost requests among arrivals after the
    kill settles, and bitwise-identical answers from survivors."""
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve import make_server
    from hpnn_tpu.serve.router import Router

    _shield_sigpipe()
    out: dict = {"ev": "drill.replica", "ok": False,
                 "replicas": n_replicas, "killed_rank": 0}
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    probe = np.linspace(-1.0, 1.0, 8)
    router = Router(n_replicas, max_batch=16, max_wait_ms=0.5)
    server = None
    try:
        router.register_kernel(KERNEL, k)
        before = np.asarray(router.infer(KERNEL, probe))
        server = make_server(router)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever,
                                  daemon=True)
        thread.start()
        load = _Load(port, rate=rate, ingest_frac=0.0, seed=seed)
        time.sleep(1.5)           # baseline bins
        t_kill = load.now()
        router.kill_replica(0)
        records = load.finish(settle_s=2.5)
        after = np.asarray(router.infer(KERNEL, probe))
        doc = router.health()
        out.update(blast_radius(records, t_kill))
        # the router is supposed to make the death invisible at the
        # edge: once the kill has settled (in-flight victims re-routed
        # or failed within a beat), NOTHING may be lost on survivors
        out["survivors_lost"] = sum(
            1 for r in records
            if r["status"] == "lost" and r["t"] >= t_kill + 0.25)
        out["live_replicas"] = doc["router"]["live_replicas"]
        out["survivor_bitwise"] = bool(np.array_equal(before, after))
        out["ok"] = bool(out["recovery_s"] is not None
                         and out["survivors_lost"] == 0
                         and out["live_replicas"] == n_replicas - 1
                         and out["survivor_bitwise"])
        return out
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        router.close()


def drill_alert(workdir: str, *, rate: float = 60.0,
                n_replicas: int = 2, seed: int = 4) -> dict:
    """Prove the alert plane live: a threshold rule on the router's
    ``router.ready_replicas`` gauge (obs/alerts.py), loadgen flowing,
    then ``kill_replica(0)``.  The gauge re-emits on the kill, the
    rule breaches, ``alert.fire`` lands with the flight-recorder dump
    attached; ``spawn_replica()`` re-emits a healthy value and the
    rule resolves.  Asserts the fire/resolve pair is in the sink, the
    dump file exists, and both transitions happened within a bounded
    window (``drill_alert_fire_s`` / ``drill_alert_resolved`` in
    bench_gate.py)."""
    from hpnn_tpu import obs
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve import make_server
    from hpnn_tpu.serve.router import Router

    _shield_sigpipe()
    out: dict = {"ev": "drill.alert", "ok": False,
                 "replicas": n_replicas, "killed_rank": 0}
    sink = os.path.join(workdir, "alert-drill.metrics.jsonl")
    flight_path = os.path.join(workdir, "alert-flight.jsonl")
    env_keys = ("HPNN_ALERTS", "HPNN_FLIGHT", "HPNN_METRICS")
    prev_env = {key: os.environ.get(key) for key in env_keys}
    os.environ["HPNN_ALERTS"] = (
        f"replicas_down@router.ready_replicas<{n_replicas - 0.5}:"
        "for=0,cooldown=0,severity=crit")
    os.environ["HPNN_FLIGHT"] = flight_path
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    router = server = None
    try:
        obs.configure(sink)   # re-reads every knob, arms rule + ring
        router = Router(n_replicas, max_batch=16, max_wait_ms=0.5)
        router.register_kernel(KERNEL, k)
        server = make_server(router)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        load = _Load(port, rate=rate, ingest_frac=0.0, seed=seed)
        time.sleep(1.5)           # baseline bins under healthy fleet
        t_kill = load.now()
        router.kill_replica(0)    # gauge drops below the bound
        fired = _wait(lambda: (obs.alerts.health_doc().get("active")
                               or None), 5.0, interval_s=0.02)
        t_fire = load.now()
        router.spawn_replica()    # gauge back to healthy
        resolved = _wait(
            lambda: (obs.alerts.health_doc().get("active") == 0
                     or None), 5.0, interval_s=0.02)
        t_resolve = load.now()
        records = load.finish(settle_s=0.5)
        census = obs.alerts.health_doc()
        # close the sink so the audit reads a complete stream
        obs.configure(None)
        events = []
        with open(sink) as fp:
            for line in fp:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        fires = [r for r in events if r.get("ev") == "alert.fire"
                 and r.get("rule") == "replicas_down"]
        resolves = [r for r in events if r.get("ev") == "alert.resolve"
                    and r.get("rule") == "replicas_down"]
        out.update(blast_radius(records, t_kill))
        out["fire_s"] = round(t_fire - t_kill, 3) if fired else None
        out["resolve_s"] = (round(t_resolve - t_kill, 3)
                            if resolved else None)
        out["resolved"] = bool(resolved and resolves)
        out["fired_total"] = census.get("fired_total", 0)
        out["flight_attached"] = bool(
            fires and fires[-1].get("flight")
            and os.path.exists(fires[-1]["flight"]))
        out["ok"] = bool(fired and fires
                         and out["resolved"]
                         and out["flight_attached"])
        return out
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if router is not None:
            router.close()
        obs.configure(None)
        for key, val in prev_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val


def drill_worker(workdir: str, *, rate: float = 60.0,
                 n_workers: int = 2, seed: int = 5,
                 ready_timeout_s: float = 90.0) -> dict:
    """Kill one of N worker PROCESSES behind a ClusterRouter under
    load: a WorkerSupervisor over real ``online_nn`` children sharing
    one WAL, the router as the HTTP edge, then SIGKILL one worker
    mid-stream.  The cross-host route-around contract: bounded goodput
    dip, zero ``survivors_lost``, bitwise survivor answers, and the
    corpse REPLACED by the supervisor restart policy within a bounded
    ``replaced_s``."""
    from hpnn_tpu.fleet import ClusterRouter, WorkerSupervisor
    from hpnn_tpu.fleet.router import CheckpointPublisher
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.online import wal as wal_mod
    from hpnn_tpu.serve import make_server

    _shield_sigpipe()
    out: dict = {"ev": "drill.worker", "ok": False,
                 "workers": n_workers}
    conf_path = os.path.join(workdir, "nn.conf")
    with open(conf_path, "w") as fp:
        fp.write(CONF)
    wal_dir = os.path.join(workdir, "wal")
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    # seed the shared WAL so every worker restores (and can reload)
    # the same resident weights
    wal_mod.PromotionWAL(wal_dir).commit(KERNEL, k.weights, version=1,
                                         reason="seed")
    probe = np.linspace(-1.0, 1.0, 8)
    sup = WorkerSupervisor(
        conf_path, workdir=workdir, kind="online", wal_dir=wal_dir,
        args=("--interval-s", "600"),   # trainer parked: the drill
                                        # injures processes, not weights
        ready_timeout_s=ready_timeout_s)
    router = server = None
    try:
        try:
            for _ in range(n_workers):
                sup.spawn()
        except (RuntimeError, OSError) as exc:
            out["skipped"] = f"worker cannot start: {exc}"
            return out
        router = ClusterRouter(
            supervisor=sup,
            publisher=CheckpointPublisher(wal_dir=wal_dir))
        before = np.asarray(router.infer(KERNEL, probe, timeout_s=10.0))
        server = make_server(router)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        load = _Load(port, rate=rate, ingest_frac=0.0, seed=seed)
        time.sleep(1.5)           # baseline bins
        victim = sup.ranks()[0]
        out["killed_rank"] = victim
        t_kill = load.now()
        sup.kill9(victim)
        # the supervisor restart policy, timed: reap the corpse and
        # spawn its replacement (readiness-gated, so "replaced" means
        # SERVING, not just forked)
        replaced = _wait(lambda: sup.replace_dead() or None, 30.0,
                         interval_s=0.05)
        t_replaced = load.now()
        records = load.finish(settle_s=2.5)
        after = np.asarray(router.infer(KERNEL, probe, timeout_s=10.0))
        out.update(blast_radius(records, t_kill))
        # the router is supposed to make the worker death invisible at
        # the edge: after the kill settles, nothing may be lost
        out["survivors_lost"] = sum(
            1 for r in records
            if r["status"] == "lost" and r["t"] >= t_kill + 0.25)
        out["replaced_s"] = (round(t_replaced - t_kill, 3)
                             if replaced else None)
        out["width_after"] = sup.width()
        out["survivor_bitwise"] = bool(np.array_equal(before, after))
        out["ok"] = bool(out["recovery_s"] is not None
                         and out["survivors_lost"] == 0
                         and out["replaced_s"] is not None
                         and out["width_after"] == n_workers
                         and out["survivor_bitwise"])
        return out
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if router is not None:
            router.close()
        sup.close()


def drill_capsule(workdir: str, *, rate: float = 12.0,
                  seed: int = 6, delay_ms: float = 40.0) -> dict:
    """The tail-latency forensics plane's live proof
    (docs/observability.md "Forensics"): an in-process serve Session
    with the tail sampler armed (``HPNN_SAMPLE=0.5``), a chaos delay
    injected at the ``serve.dispatch`` seam, an ``slo.p99_ms``
    threshold rule, and a capsule dir.  Loadgen traffic drives p99
    over the bound → the alert fires → the capture trigger lands a
    capsule (spans + gauges + a real ``jax.profiler`` window).
    Asserts the capsule manifest landed within a bounded window of
    the fire with a non-empty span ring and profile, and that
    ``tools/tail_report.py`` over the run's sink blames the
    **dispatch** phase for most of the tail — the injected seam, not
    a neighbor (``drill_capsule_capture_s`` /
    ``drill_capsule_blame_pct`` in bench_gate.py)."""
    from hpnn_tpu import chaos as chaos_mod
    from hpnn_tpu import obs
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve import Session, make_server

    import tail_report

    _shield_sigpipe()
    out: dict = {"ev": "drill.capsule", "ok": False,
                 "delay_ms": delay_ms}
    # Keep the offered inter-arrival above the injected delay so
    # batches stay singletons: a backlogged queue would launder the
    # dispatch delay into queue time (and, for non-batch[0] members,
    # into cross-request gap) and the blame assertion below would
    # point at the wrong phase.
    rate = min(rate, 500.0 / max(delay_ms, 1.0))
    sink = os.path.join(workdir, "capsule-drill.metrics.jsonl")
    capsule_dir = os.path.join(workdir, "capsules")
    env_keys = ("HPNN_SAMPLE", "HPNN_CAPSULE_DIR",
                "HPNN_CAPSULE_PROFILE_MS", "HPNN_CAPSULE_COOLDOWN_S",
                "HPNN_ALERTS", "HPNN_SLO_MS", "HPNN_CHAOS",
                "HPNN_CHAOS_SEED", "HPNN_METRICS")
    prev_env = {key: os.environ.get(key) for key in env_keys}
    os.environ["HPNN_SAMPLE"] = "0.5"
    os.environ["HPNN_CAPSULE_DIR"] = capsule_dir
    os.environ["HPNN_CAPSULE_PROFILE_MS"] = "50"
    os.environ["HPNN_CAPSULE_COOLDOWN_S"] = "0"
    os.environ["HPNN_SLO_MS"] = str(delay_ms / 2.0)
    # for=1.0 holds the fire until the breach has been true for a
    # second of traffic, so the span ring has sampled roots by the
    # time the capsule's spans.jsonl is written (a for=0 rule can
    # fire off the very first completed request, before any sampled
    # root has closed, and capture an empty ring).
    os.environ["HPNN_ALERTS"] = (
        f"tail_p99@slo.p99_ms>{delay_ms / 2.0}:"
        "for=1.0,cooldown=0,severity=warn")
    os.environ["HPNN_CHAOS"] = f"delay@serve.dispatch:ms={delay_ms}"
    os.environ["HPNN_CHAOS_SEED"] = "1"
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    session = server = None

    def _manifest():
        for dirpath, _dirs, files in os.walk(capsule_dir):
            if "manifest.json" in files:
                return os.path.join(dirpath, "manifest.json")
        return None

    try:
        # warm compile BEFORE arming obs: the first-request JIT stall
        # (hundreds of ms) would otherwise spike slo.p99_ms and fire
        # the alert off the warmup, not the injected seam
        session = Session(max_batch=16, n_buckets=2, max_wait_ms=0.5)
        session.register_kernel(KERNEL, k)
        warm = np.linspace(-1.0, 1.0, 8)
        for _ in range(3):
            session.infer(KERNEL, warm, timeout_s=10.0)
        obs.configure(sink)   # re-reads every knob: sampler, rule,
        chaos_mod._reset_for_tests()  # capsule hook, chaos plan
        server = make_server(session)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        load = _Load(port, rate=rate, ingest_frac=0.0, seed=seed)
        fired = _wait(lambda: (obs.alerts.health_doc().get("active")
                               or None), 15.0, interval_s=0.02)
        t_fire = load.now()
        manifest_path = _wait(_manifest, 10.0, interval_s=0.05)
        t_capsule = load.now()
        records = load.finish(settle_s=0.2)
        census = obs.triggers.health_doc()
        obs.configure(None)   # close the sink for the audit below
        out["requests"] = len(records)
        out["fired"] = bool(fired)
        out["capture_s"] = (round(t_capsule - t_fire, 3)
                            if fired and manifest_path else None)
        man = {}
        if manifest_path:
            with open(manifest_path) as fp:
                man = json.load(fp)
        out["capsule"] = man.get("capsule")
        out["capsule_spans"] = man.get("spans", 0)
        profile = man.get("profile") or {}
        out["profile_files"] = profile.get("files", 0)
        out["captures_total"] = census.get("captures", 0)
        # the forensic half: the report over the run's own sink must
        # pin the tail on the injected seam
        rep = tail_report.analyze(tail_report.load_spans([sink]),
                                  top=5)
        out["sampled_roots"] = rep["requests"]
        out["blame_pct"] = rep["blame_pct"]
        out["dispatch_blame_pct"] = rep["blame_pct"].get("dispatch",
                                                         0.0)
        out["ok"] = bool(fired and manifest_path
                         and str(man.get("reason", "")
                                 ).startswith("alert:tail_p99")
                         and out["capsule_spans"] > 0
                         and out["profile_files"] > 0
                         and rep["requests"] > 0
                         and out["dispatch_blame_pct"] > 50.0)
        return out
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if session is not None:
            session.close()
        obs.configure(None)
        for key, val in prev_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        chaos_mod._reset_for_tests()


def drill_drift(workdir: str, *, rate: float = 20.0,
                seed: int = 7) -> dict:
    """The drift plane's live proof (docs/observability.md "Drift
    detection"): an in-process train-while-serve session on the
    synthetic-MNIST stream, loadgen inference traffic flowing, a
    ``drift.score`` threshold rule and a capsule dir armed.  The
    session first *learns* the clean stream (a label shift is only
    visible to a model that learned the mapping), then ``HPNN_DRIFT``
    is armed so the sketch references freeze on the converged
    steady-state and the decay sentinel warms up, then the stream's
    labels are remapped (``streams.label_shift``).  The resident's
    held-out loss ramps, the sentinel z breaches ``HPNN_DRIFT_Z``,
    the normalized score crosses the rule → ``alert.fire`` → a
    capture capsule whose ``drift.json`` holds both the reference
    and the post-shift sketches — while serving answers throughout
    (zero lost).  Detection latency is the gateable
    ``drill_drift_detect_s``."""
    from hpnn_tpu import obs
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.online import streams
    from hpnn_tpu.online.session import OnlineSession
    from hpnn_tpu.serve import make_server

    _shield_sigpipe()
    out: dict = {"ev": "drill.drift", "ok": False}
    sink = os.path.join(workdir, "drift-drill.metrics.jsonl")
    capsule_dir = os.path.join(workdir, "capsules")
    env_keys = ("HPNN_DRIFT", "HPNN_DRIFT_WINDOW", "HPNN_DRIFT_Z",
                "HPNN_ALERTS", "HPNN_CAPSULE_DIR",
                "HPNN_CAPSULE_PROFILE_MS", "HPNN_CAPSULE_COOLDOWN_S",
                "HPNN_METRICS")
    prev_env = {key: os.environ.get(key) for key in env_keys}
    os.environ.pop("HPNN_DRIFT", None)  # armed mid-drill, below
    os.environ["HPNN_ALERTS"] = ("drift@drift.score>1:"
                                 "for=0,cooldown=0,severity=warn")
    os.environ["HPNN_CAPSULE_DIR"] = capsule_dir
    os.environ["HPNN_CAPSULE_PROFILE_MS"] = "0"
    os.environ["HPNN_CAPSULE_COOLDOWN_S"] = "0"
    # Phase lengths, in trainer rounds of FEEDS stream samples each:
    # CONVERGE clean rounds to learn the mapping, WARMUP armed clean
    # rounds (sketch references freeze, sentinel EWMA seeds), then
    # shifted rounds until the alert fires.  The sentinel z asymptote
    # against a ramp is ~2 (obs/drift.py), so the drill arms
    # HPNN_DRIFT_Z below that.
    converge, warmup, max_shifted, feeds = 25, 12, 15, 80
    stream = streams.label_shift(
        streams.mnist_stream(7), (converge + warmup) * feeds,
        {i: (i + 1) % 10 for i in range(10)})
    session = server = None

    def _manifest():
        for dirpath, _dirs, files in os.walk(capsule_dir):
            if "manifest.json" in files:
                return os.path.join(dirpath, "manifest.json")
        return None

    def _round():
        for _ in range(feeds):
            x, t = next(stream)
            session.feed(x, t)
        session.tick()

    try:
        obs.configure(sink)  # alert rule + capsule trigger armed
        session = OnlineSession(rows=64, batch=8, epochs=16,
                                holdout=4, seed=0, start=False)
        kern, _ = kernel_mod.generate(1, streams.MNIST_N_IN, [32],
                                      streams.MNIST_N_OUT)
        session.add_kernel("mnist", kern)
        server = make_server(session.serve)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        load = _Load(port, rate=rate, ingest_frac=0.0, seed=seed,
                     kernels=("mnist",), n_in=streams.MNIST_N_IN,
                     n_out=streams.MNIST_N_OUT)
        for _ in range(converge):     # learn the clean mapping
            _round()
        obs.drift.configure("1", window=64, z=1.2)
        for _ in range(warmup):       # freeze references, seed EWMA
            _round()
        # poll fired_total, not "active": drift.score is a
        # multi-series gauge (one emission per detector), so the
        # name-keyed threshold rule resolves the instant a calm
        # detector's low score lands after the breaching one
        if obs.alerts.health_doc().get("fired_total", 0) > 0:
            load.finish()
            out["error"] = "alert fired before the shift"
            return out
        t_shift = load.now()
        rounds = None
        for i in range(max_shifted):  # labels now lie
            _round()
            if obs.alerts.health_doc().get("fired_total", 0) > 0:
                rounds = i + 1
                break
        t_fire = load.now()
        manifest_path = (_wait(_manifest, 10.0, interval_s=0.05)
                         if rounds is not None else None)
        records = load.finish(settle_s=0.2)
        health = obs.drift.health_doc()
        obs.configure(None)   # close the sink for the audit below
        events = []
        with open(sink) as fp:
            for line in fp:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        fires = [r for r in events if r.get("ev") == "alert.fire"
                 and r.get("rule") == "drift"]
        drifts = [r for r in events if r.get("ev") == "online.drift"]
        man, sketches = {}, None
        if manifest_path:
            with open(manifest_path) as fp:
                man = json.load(fp)
            dj = os.path.join(os.path.dirname(manifest_path),
                              "drift.json")
            if os.path.exists(dj):
                with open(dj) as fp:
                    sketches = json.load(fp)
        ingest = (sketches or {}).get("ingest") or {}
        out["detect_s"] = (round(t_fire - t_shift, 3)
                           if rounds is not None else None)
        out["rounds_to_detect"] = rounds
        out["requests"] = len(records)
        out["lost"] = sum(1 for r in records if r["status"] == "lost")
        out["capsule"] = man.get("capsule")
        out["capsule_reason"] = man.get("reason")
        out["drift_events"] = sorted(
            {f"{r.get('detector')}:{r.get('kernel')}" for r in drifts})
        out["eval_z"] = (health.get("eval", {}).get("mnist", {})
                         .get("z"))
        out["sketches"] = {"reference": bool(ingest.get("reference")),
                           "live": bool(ingest.get("live"))}
        out["ok"] = bool(rounds is not None and fires and drifts
                         and manifest_path
                         and str(man.get("reason", "")
                                 ).startswith("alert:drift")
                         and ingest.get("reference")
                         and ingest.get("live")
                         and out["requests"] > 0
                         and out["lost"] == 0)
        return out
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if session is not None:
            session.close()
        obs.configure(None)
        for key, val in prev_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val


def drill_quota(workdir: str, *, rate: float = 100.0, seed: int = 9,
                phase_s: float = 1.5,
                offender_rate_rps: float = 40.0,
                overdrive: float = 10.0) -> dict:
    """Hostile-tenant drill (docs/tenancy.md): two well-behaved
    "gold" victims and one rate-capped "bronze" offender share one
    in-process TenantSession; after an undisturbed baseline the
    offender offers ``overdrive``x its admission budget.  Proves
    per-tenant isolation end to end: the victims' goodput and p99
    hold against their own baseline, every refusal the offender sees
    is a clean ``shed reason=quota`` carrying its tenant in the sink,
    and the ``tenant.shed_rate`` threshold rule fires — the PR 12
    alert grammar watching a per-tenant gauge
    (``drill_quota_victim_p99_ms`` / ``drill_quota_victim_goodput_
    ratio`` in bench_gate.py)."""
    from hpnn_tpu import obs
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve.batcher import QueueFull
    from hpnn_tpu.tenant import TenantSession, TenantSpec

    _shield_sigpipe()
    out: dict = {"ev": "drill.quota", "ok": False,
                 "offender_rate_rps": float(offender_rate_rps),
                 "overdrive": float(overdrive)}
    sink = os.path.join(workdir, "quota-drill.metrics.jsonl")
    env_keys = ("HPNN_ALERTS", "HPNN_METRICS")
    prev_env = {key: os.environ.get(key) for key in env_keys}
    os.environ["HPNN_ALERTS"] = ("quota_breach@tenant.shed_rate>0.5:"
                                 "for=0,cooldown=0,severity=warn")
    victims = ("v-gold-a", "v-gold-b")
    offender = "hog"
    specs = {v: TenantSpec(v, "gold") for v in victims}
    specs[offender] = TenantSpec(offender, "bronze",
                                 rate_rps=float(offender_rate_rps))
    session = None
    try:
        obs.configure(sink)   # re-reads every knob, arms the rule
        session = TenantSession(mode="parity", fleet=True,
                                max_wait_ms=0.5, tenants=specs)
        k, _ = kernel_mod.generate(seed + 1, 8, [5], 2)
        for tn in (*victims, offender):
            # same topology on purpose: the fleet batcher stacks the
            # tenants' dispatches, so isolation is enforced at
            # admission, not by accidental executable separation
            session.register_for(tn, KERNEL, k)
        x = np.random.RandomState(seed).standard_normal((2, 8))
        session.infer_for(victims[0], KERNEL, x)  # discarded warmup

        def paced(tenant: str, rate_rps: float, duration_s: float,
                  res: dict):
            period = 1.0 / max(rate_rps, 1e-6)
            t0 = time.perf_counter()
            i = 0
            while i * period < duration_s:
                due = t0 + i * period
                i += 1
                delay = due - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                t_req = time.perf_counter()
                try:
                    session.infer_for(tenant, KERNEL, x,
                                      timeout_s=2.0)
                except QueueFull as exc:  # Shed subclass
                    res["shed"] += 1
                    reason = getattr(exc, "reason", None) or "?"
                    res["reasons"][reason] = (
                        res["reasons"].get(reason, 0) + 1)
                except Exception as exc:
                    res["errors"] += 1
                    res["error_sample"] = repr(exc)
                else:
                    res["ok"] += 1
                    res["lat"].append(time.perf_counter() - t_req)

        def fresh():
            return {"ok": 0, "shed": 0, "errors": 0,
                    "reasons": {}, "lat": []}

        def victim_wave(duration_s: float) -> dict:
            res = {v: fresh() for v in victims}
            threads = [threading.Thread(
                target=paced, args=(v, rate / len(victims),
                                    duration_s, res[v]),
                daemon=True) for v in victims]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return res

        base = victim_wave(phase_s)
        hog_res = fresh()
        hog_thread = threading.Thread(
            target=paced,
            args=(offender, offender_rate_rps * overdrive,
                  phase_s, hog_res),
            daemon=True)
        hog_thread.start()
        attack = victim_wave(phase_s)
        hog_thread.join()

        def agg(res: dict) -> tuple[int, list[float]]:
            return (sum(r["ok"] for r in res.values()),
                    [s for r in res.values() for s in r["lat"]])

        base_ok, base_lat = agg(base)
        atk_ok, atk_lat = agg(attack)
        census = obs.alerts.health_doc()
        obs.configure(None)   # close the sink for a complete audit
        events = []
        with open(sink) as fp:
            for line in fp:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        fires = [r for r in events if r.get("ev") == "alert.fire"
                 and r.get("rule") == "quota_breach"]
        quota_sheds = [r for r in events
                       if r.get("ev") == "serve.shed"
                       and r.get("reason") == "quota"]
        victim_quota_sheds = [r for r in quota_sheds
                              if r.get("tenant") != offender]
        p99 = (lambda lat: round(float(
            np.percentile(np.asarray(lat) * 1e3, 99)), 3)
            if lat else None)
        out["baseline_goodput_rps"] = round(base_ok / phase_s, 1)
        out["victim_goodput_rps"] = round(atk_ok / phase_s, 1)
        out["victim_goodput_ratio"] = (
            round(atk_ok / base_ok, 4) if base_ok else None)
        out["baseline_p99_ms"] = p99(base_lat)
        out["victim_p99_ms"] = p99(atk_lat)
        out["victim_shed"] = sum(r["shed"] for r in attack.values())
        out["offender_offered"] = (hog_res["ok"] + hog_res["shed"]
                                   + hog_res["errors"])
        out["offender_ok"] = hog_res["ok"]
        out["offender_shed"] = hog_res["shed"]
        out["offender_shed_reasons"] = dict(
            sorted(hog_res["reasons"].items()))
        out["victim_quota_sheds_in_sink"] = len(victim_quota_sheds)
        out["alert_fired"] = bool(fires)
        out["fired_total"] = census.get("fired_total", 0)
        out["ok"] = bool(
            base_ok and atk_ok
            and out["victim_goodput_ratio"] is not None
            and out["victim_goodput_ratio"] >= 0.75
            and out["victim_shed"] == 0
            and hog_res["shed"] > 0
            and set(hog_res["reasons"]) == {"quota"}
            and not victim_quota_sheds
            and fires)
        return out
    finally:
        if session is not None:
            session.close()
        obs.configure(None)
        for key, val in prev_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val


def drill_hog(workdir: str, *, rate: float = 12.0, seed: int = 11,
              phase_s: float = 1.5, warm_s: float = 0.6,
              zipf_x: float = 20.0,
              hog_cap_rps: float | None = None) -> dict:
    """Resource-hog attribution drill (docs/observability.md "Tenant
    metering"): a small zipf-weighted tenant population shares one
    in-process TenantSession with ``HPNN_METER`` armed; after an
    undisturbed warm phase one rate-capped tenant ("hog") offers
    ``zipf_x`` times the heaviest victim's rate.  Proves the metering
    plane end to end: the fleet-merged top-K (the sink's own
    cumulative ``meter.sketch`` stream, merged exactly as the
    collector's ``/meterz`` does) names the hog within a bounded
    detection window (gateable ``drill_hog_detect_s``),
    ``tools/tenant_report.py`` over the same sink blames it for the
    majority of device-seconds (``drill_hog_blame_pct``, checked
    against the drill's own admitted-request ground truth), the
    ``tenant.shed_rate`` threshold rule fires on the hog's refusals,
    and the alert-triggered capsule carries ``meter.json`` — the
    attribution evidence frozen at fire time."""
    import tenant_report

    from hpnn_tpu import obs
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve.batcher import QueueFull
    from hpnn_tpu.tenant import TenantSession, TenantSpec

    _shield_sigpipe()
    if hog_cap_rps is None:
        # admit the hog at ~2.5x the victims' combined offered load:
        # enough to dominate the device-seconds blame table, while
        # the 20x offered overdrive keeps its shed rate over the
        # alert rule's 0.5 threshold at any --rate
        hog_cap_rps = 2.5 * rate
    out: dict = {"ev": "drill.hog", "ok": False,
                 "zipf_x": float(zipf_x),
                 "hog_cap_rps": float(hog_cap_rps)}
    sink = os.path.join(workdir, "hog-drill.metrics.jsonl")
    capsule_dir = os.path.join(workdir, "capsules")
    env_keys = ("HPNN_ALERTS", "HPNN_METRICS", "HPNN_METER",
                "HPNN_METER_TOPK", "HPNN_CAPSULE_DIR",
                "HPNN_CAPSULE_PROFILE_MS", "HPNN_CAPSULE_COOLDOWN_S")
    prev_env = {key: os.environ.get(key) for key in env_keys}
    os.environ["HPNN_ALERTS"] = ("hog_shed@tenant.shed_rate>0.5:"
                                 "for=0,cooldown=0,severity=warn")
    os.environ["HPNN_METER"] = "1"
    os.environ.pop("HPNN_METER_TOPK", None)
    os.environ["HPNN_CAPSULE_DIR"] = capsule_dir
    os.environ["HPNN_CAPSULE_PROFILE_MS"] = "0"
    os.environ["HPNN_CAPSULE_COOLDOWN_S"] = "0"
    victims = tuple(f"v-{i:02d}" for i in range(4))
    hog = "hog"
    weights = [1.0 / (i + 1) for i in range(len(victims))]
    scale = rate / sum(weights)
    victim_rates = [w * scale for w in weights]
    hog_rate = zipf_x * victim_rates[0]
    specs = {v: TenantSpec(v, "gold") for v in victims}
    specs[hog] = TenantSpec(hog, "bronze",
                            rate_rps=float(hog_cap_rps))
    session = None

    def _manifest():
        for dirpath, _dirs, files in os.walk(capsule_dir):
            if "manifest.json" in files:
                return os.path.join(dirpath, "manifest.json")
        return None

    def _sink_top_device():
        """(latest ``meter.sketch`` record, its device_s leader) from
        the live sink — the same cumulative stream a collector
        merges for ``/meterz``."""
        latest = None
        try:
            with open(sink) as fp:
                for line in fp:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line mid-run
                    if rec.get("ev") == "meter.sketch":
                        latest = rec
        except OSError:
            return None, None
        if latest is None:
            return None, None
        merged = obs.meter.merge_sketch_docs([latest])
        top = (merged.get("axes", {}).get("device_s", {})
               .get("top") or {})
        named = {t: v for t, v in top.items() if t != "_other"}
        if not named:
            return latest, None
        return latest, max(named, key=lambda t: (named[t], t))

    def paced(tenant: str, rate_rps: float, duration_s: float,
              res: dict):
        period = 1.0 / max(rate_rps, 1e-6)
        t0 = time.perf_counter()
        i = 0
        while i * period < duration_s:
            due = t0 + i * period
            i += 1
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                session.infer_for(tenant, KERNEL, x, timeout_s=2.0)
            except QueueFull:  # Shed subclass
                res["shed"] += 1
            except Exception as exc:
                res["errors"] += 1
                res["error_sample"] = repr(exc)
            else:
                res["ok"] += 1

    def fresh():
        return {"ok": 0, "shed": 0, "errors": 0}

    try:
        obs.configure(sink)   # arms sink + rule + capsule + meter
        session = TenantSession(mode="parity", fleet=True,
                                max_wait_ms=0.5, tenants=specs)
        k, _ = kernel_mod.generate(seed + 1, 8, [5], 2)
        for tn in (*victims, hog):
            session.register_for(tn, KERNEL, k)
        x = np.random.RandomState(seed).standard_normal((2, 8))
        for tn in (*victims, hog):
            session.infer_for(tn, KERNEL, x)  # compile warmup
        # zero the sketches: the one-time executable builds above cost
        # orders of magnitude more than a steady-state dispatch and
        # would drown the traffic signal the drill attributes
        obs.meter.configure("1")

        res = {tn: fresh() for tn in (*victims, hog)}
        threads = [threading.Thread(
            target=paced, args=(v, r, warm_s + phase_s, res[v]),
            daemon=True) for v, r in zip(victims, victim_rates)]
        for t in threads:
            t.start()
        time.sleep(warm_s)
        t_attack = time.time()   # registry record ts is time.time()
        hog_thread = threading.Thread(
            target=paced, args=(hog, hog_rate, phase_s, res[hog]),
            daemon=True)
        hog_thread.start()
        detect_ts = None
        deadline = time.monotonic() + phase_s
        while time.monotonic() < deadline:
            rec, top = _sink_top_device()
            if top == hog:
                detect_ts = rec.get("ts")
                break
            time.sleep(0.02)
        for t in threads:
            t.join()
        hog_thread.join()
        obs.meter.emit_sketch()  # final cumulative sketch, unthrottled
        manifest_path = _wait(_manifest, 10.0, interval_s=0.05)
        census = obs.alerts.health_doc()
        obs.configure(None)   # close the sink for a complete audit
        events = []
        with open(sink) as fp:
            for line in fp:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        fires = [r for r in events if r.get("ev") == "alert.fire"
                 and r.get("rule") == "hog_shed"]
        rep = tenant_report.analyze(
            tenant_report.load_meter_docs([sink]), top=3)
        rows = {r["tenant"]: r for r in rep["tenants"]}
        blame_pct = float((rows.get(hog) or {}).get("share_pct")
                          or 0.0)
        admitted = {tn: r["ok"] for tn, r in res.items()}
        total_ok = sum(admitted.values())
        truth_pct = (round(100.0 * admitted[hog] / total_ok, 2)
                     if total_ok else 0.0)
        man, meter_json = {}, None
        if manifest_path:
            with open(manifest_path) as fp:
                man = json.load(fp)
            mj = os.path.join(os.path.dirname(manifest_path),
                              "meter.json")
            if os.path.exists(mj):
                with open(mj) as fp:
                    meter_json = json.load(fp)
        out["detect_s"] = (round(detect_ts - t_attack, 3)
                           if detect_ts is not None else None)
        out["ranked_top"] = (rep["tenants"][0]["tenant"]
                             if rep["tenants"] else None)
        out["blame_pct"] = round(blame_pct, 2)
        out["truth_pct"] = truth_pct
        out["hog_ok"] = admitted[hog]
        out["hog_shed"] = res[hog]["shed"]
        out["victims_ok"] = total_ok - admitted[hog]
        out["errors"] = sum(r["errors"] for r in res.values())
        out["alert_fired"] = bool(fires)
        out["fired_total"] = census.get("fired_total", 0)
        out["capsule"] = man.get("capsule")
        out["capsule_reason"] = man.get("reason")
        out["capsule_meter_axes"] = sorted(
            (meter_json or {}).get("axes", {}))
        out["ok"] = bool(
            out["detect_s"] is not None and out["detect_s"] <= 1.0
            and out["ranked_top"] == hog
            and blame_pct >= 50.0
            and res[hog]["shed"] > 0
            and fires
            and manifest_path
            and meter_json is not None
            and meter_json.get("axes")
            and meter_json.get("export"))
        return out
    finally:
        if session is not None:
            session.close()
        obs.configure(None)
        for key, val in prev_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val


def drill_tune(workdir: str, *, rate: float = 0.0, seed: int = 13,
               n_roots: int = 24) -> dict:
    """The self-tuning plane's proof (docs/selftuning.md): drive the
    REAL online blame engine with a synthetic span stream per blame
    class, and a scripted-clock :class:`~hpnn_tpu.tune.engine.Tuner`
    over real actuator targets must move the MATCHING knob, watch the
    tail recover, and roll a bad move back bitwise.

    Deterministic and in-process — no child, no wall-clock races
    (``rate`` is accepted for :func:`run_drills` signature parity and
    unused).  Per class: inject ``n_roots`` request roots whose
    subtree charges ~90% of the root time to that class, tick, assert
    ``tune.apply`` names ``RULE_OF[class]``, script the p99 down, and
    let the watch expire clean (``watch_pass``).  Then two bad moves:
    a second ``precision_down`` whose scripted p99 regresses past the
    rollback ratio inside the watch (restore must be the prior
    precision tag, registry version chain strictly monotone), and a
    second ``quota_squeeze`` rolled back directly (restored spec must
    be the exact pre-apply :class:`TenantSpec` tuple).  Finally
    ``tools/check_obs_catalog.py --tune`` must pass over the drill's
    own sink."""
    import itertools

    import check_obs_catalog

    from hpnn_tpu import obs, serve, tune
    from hpnn_tpu.fleet import autoscaler as autoscaler_mod
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.obs import blame
    from hpnn_tpu.tenant.quota import QuotaEnforcer, TenantSpec

    _shield_sigpipe()
    out: dict = {"ev": "drill.tune", "ok": False}
    sink = os.path.join(workdir, "tune-drill.metrics.jsonl")
    env_keys = ("HPNN_METRICS", "HPNN_BLAME", "HPNN_BLAME_WINDOW",
                "HPNN_TUNE")
    prev_env = {key: os.environ.get(key) for key in env_keys}

    class _MemFleet:
        """width()/ranks()/spawn()/drain_and_kill() in memory — the
        request_up/request_down surface with no real processes."""

        def __init__(self, width: int):
            self._ranks = list(range(width))
            self._next = width

        def width(self):
            return len(self._ranks)

        def ranks(self):
            return list(self._ranks)

        def spawn(self):
            self._ranks.append(self._next)
            self._next += 1

        def drain_and_kill(self, rank):
            self._ranks.remove(rank)

    ids = itertools.count(1)
    child_name = {"queue": "serve.batch.queue",
                  "dispatch": "serve.dispatch",
                  "spill": "serve.spill_reload",
                  "shed_retry": "serve.retry_wait"}

    def inject(phase: str) -> None:
        """``n_roots`` fresh request roots, ~90% of each charged to
        ``phase`` — fed through the real ``note_record`` tap
        (children close before their root, as in the span
        lifecycle)."""
        for _ in range(n_roots):
            root = f"r{next(ids)}"
            child = {"span": f"c{next(ids)}", "parent": root,
                     "name": child_name[phase], "t0": 0.0, "dt": 0.9}
            if phase == "shed_retry":
                child["failed"] = "Shed"
            blame.note_record(child)
            blame.note_record({"span": root, "parent": None,
                               "name": "serve.request", "t0": 0.0,
                               "dt": 1.0, "kernel": KERNEL})

    sess = None
    try:
        obs.configure(sink)
        sess = serve.Session(max_batch=8, n_buckets=2,
                             max_wait_ms=0.5, mode="compiled")
        k, _ = kernel_mod.generate(seed, 8, [5], 2)
        sess.register_kernel(KERNEL, k)
        scaler = autoscaler_mod.Autoscaler(
            _MemFleet(2), None,
            policy=autoscaler_mod.Policy(min_width=1, max_width=4,
                                         up_step=1))
        quota = QuotaEnforcer(
            {"bronze": TenantSpec("bronze", "bronze", rate_rps=40.0)})
        policy = tune.Policy(cooldown_s=5.0, watch_s=2.0)
        clock = {"t": 1000.0}
        p99 = {"v": 100.0}

        def fresh_tuner():
            return tune.Tuner(
                sess, autoscaler=scaler, quota=quota, policy=policy,
                clock=lambda: clock["t"], p99_fn=lambda: p99["v"],
                burn_fn=lambda: 3.0)

        def one_round(phase: str, *, regress: bool) -> dict:
            """inject → tick → scripted watch; returns the round's
            verdict/action plus what check_watch did."""
            blame.configure("1", window=16)  # fresh window per class
            inject(phase)
            p99["v"] = 100.0
            tuner = fresh_tuner()
            t_apply = clock["t"]
            d = tuner.tick()
            if regress:
                clock["t"] = t_apply + policy.watch_s / 2
                p99["v"] = 300.0  # past before * 1.25 inside watch
            else:
                clock["t"] = t_apply + policy.watch_s + 0.1
                p99["v"] = 40.0   # recovered: watch expires clean
            rolled = tuner.check_watch()
            return {"verdict": d.get("verdict"),
                    "action": d.get("action"), "id": d.get("id"),
                    "rolled_back": rolled, "tuner": tuner}

        from hpnn_tpu.tune.engine import RULE_OF

        rounds: dict = {}
        for phase in ("queue", "dispatch", "spill", "shed_retry"):
            rounds[phase] = one_round(phase, regress=False)
        width_after = scaler.supervisor.width()
        buckets_after = tuple(sess.engine.buckets)
        squeezed_rate = quota.spec("bronze").rate_rps

        # bad move 1: a second precision notch (f32 -> bf16) whose
        # scripted p99 regresses inside the watch -> rollback must
        # restore the prior tag as a NEW version (chain monotone)
        v_before_bad = sess.registry.get(KERNEL).version
        prec_before_bad = sess.registry.get(KERNEL).precision
        bad_prec = one_round("dispatch", regress=True)
        ent = sess.registry.get(KERNEL)
        prec_restored = ent.precision == prec_before_bad
        versions_monotone = ent.version > v_before_bad

        # bad move 2: a second quota squeeze rolled back directly (the
        # bad-action path drills exercise) — the restored spec must be
        # the exact pre-apply tuple
        spec_before_bad = quota.spec("bronze")
        blame.configure("1", window=16)
        inject("shed_retry")
        p99["v"] = 100.0
        bad_quota_tuner = fresh_tuner()
        bad_quota = bad_quota_tuner.tick()
        bad_quota_rolled = bad_quota_tuner.rollback("drill_bad_action")
        quota_restored = quota.spec("bronze") == spec_before_bad

        blame.flush()
        obs.configure(None)  # close the sink for a complete audit

        events = []
        with open(sink) as fp:
            for line in fp:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        applies = [r for r in events if r.get("ev") == "tune.apply"]
        rollbacks = [r for r in events
                     if r.get("ev") == "tune.rollback"]
        scale_ups = [r for r in events
                     if r.get("ev") == "fleet.scale_up"
                     and r.get("reason") == "tune:queue"]
        # warmup re-emits the resident version; the chain claim is
        # over the retags themselves (source=set): every move — the
        # two downshifts AND the rollback's restore — a fresh version
        prec_versions = [r.get("version") for r in events
                         if r.get("ev") == "serve.precision"
                         and r.get("source") == "set"]

        matched = sum(
            1 for phase in rounds
            if rounds[phase]["verdict"] == "apply"
            and rounds[phase]["action"] == RULE_OF[phase])
        out["applies"] = round(matched / 4.0, 3)
        out["actions"] = {p: rounds[p]["action"] for p in rounds}
        out["recovered"] = sum(
            1 for p in rounds if rounds[p]["rolled_back"] is None)
        out["width_after"] = width_after
        out["buckets_after"] = list(buckets_after)
        out["squeezed_rate_rps"] = squeezed_rate
        out["bad_prec_rolled_back"] = bad_prec["rolled_back"]
        out["precision_restored_bitwise"] = prec_restored
        out["version_chain_monotone"] = bool(
            versions_monotone
            and prec_versions == sorted(prec_versions)
            and len(set(prec_versions)) == len(prec_versions))
        out["bad_quota_rolled_back"] = bad_quota_rolled
        out["quota_restored_bitwise"] = quota_restored
        out["rollback_bitwise"] = (
            1.0 if (prec_restored and quota_restored) else 0.0)
        out["applies_in_sink"] = len(applies)
        out["rollbacks_in_sink"] = len(rollbacks)
        rollback_pairs_ok = (
            len(rollbacks) == 2
            and {r.get("id") for r in rollbacks}
            <= {a.get("id") for a in applies}
            and {r.get("reason") for r in rollbacks}
            == {"p99_regression", "drill_bad_action"})
        out["rollback_pairs_ok"] = rollback_pairs_ok
        lint = check_obs_catalog.lint_tune(sink)
        out["lint_failures"] = lint
        out["ok"] = bool(
            matched == 4
            and out["recovered"] == 4
            and width_after == 3            # scale_up: 2 -> 3
            and len(buckets_after) == 3     # grow_buckets: 2 -> 3
            and squeezed_rate == 20.0       # quota_squeeze: 40 -> 20
            and bad_prec["rolled_back"] == "precision_down"
            and bad_quota["verdict"] == "apply"
            and bad_quota_rolled == "quota_squeeze"
            and out["rollback_bitwise"] == 1.0
            and out["version_chain_monotone"]
            and scale_ups
            and rollback_pairs_ok
            and not lint)
        return out
    finally:
        if sess is not None:
            sess.close()
        obs.configure(None)
        blame.configure(None)
        for key, val in prev_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val


def drill_torn(workdir: str, *, rate: float = 30.0, seed: int = 8,
               n_hostile: int = 3) -> dict:
    """The connection plane's live proof (docs/serving.md "Connection
    plane"): a conn-guarded in-process serve Session under clean
    loadgen traffic, then ``n_hostile`` attackers of EACH hostile
    class at once — slowloris header-tricklers (the byte-rate guard's
    prey), torn-body clients (Content-Length declared, peer hangs up
    mid-body), and fuzz clients (garbage request lines).  Asserts the
    blast radius stayed on the attackers: clean goodput dip ≤ 10%
    with zero clean ``lost``, every hostile connection accounted by
    close reason in the drill's own sink, every slowloris
    guard-killed (``conn.guard_kill reason=slowloris``) with the
    armed ``conn.guard_kills`` rule firing → a capture capsule whose
    ``conn.json`` carries the census, ``/connz`` live throughout,
    the sink passing ``check_obs_catalog.py --conn``, and no
    attacker thread hung (``drill_torn_dip_pct`` /
    ``drill_torn_clean_lost`` in bench_gate.py)."""
    from hpnn_tpu import obs
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve import Session, conn as conn_mod, make_server

    import check_obs_catalog
    import loadgen

    _shield_sigpipe()
    out: dict = {"ev": "drill.torn", "ok": False,
                 "n_hostile": 3 * int(n_hostile)}
    sink = os.path.join(workdir, "torn-drill.metrics.jsonl")
    capsule_dir = os.path.join(workdir, "capsules")
    env_keys = ("HPNN_CONN_HDR_MS", "HPNN_CONN_BODY_MS",
                "HPNN_CONN_PER_IP", "HPNN_CONN_MIN_BPS",
                "HPNN_CONN_TABLE", "HPNN_ALERTS", "HPNN_CAPSULE_DIR",
                "HPNN_CAPSULE_COOLDOWN_S", "HPNN_METRICS")
    prev_env = {key: os.environ.get(key) for key in env_keys}
    # generous deadlines + per-IP room: every guard must be armed,
    # but CLEAN traffic (8 keep-alive loadgen workers, same IP as
    # the attackers) must never trip one — the drill measures guard
    # selectivity, not just guard existence
    os.environ["HPNN_CONN_HDR_MS"] = "4000"
    os.environ["HPNN_CONN_BODY_MS"] = "4000"
    os.environ["HPNN_CONN_PER_IP"] = "64"
    os.environ["HPNN_CONN_MIN_BPS"] = "256"
    os.environ["HPNN_CONN_TABLE"] = "256"
    os.environ["HPNN_CAPSULE_DIR"] = capsule_dir
    os.environ["HPNN_CAPSULE_COOLDOWN_S"] = "0"
    os.environ["HPNN_ALERTS"] = ("conn_guard@conn.guard_kills>0:"
                                 "for=0,cooldown=0,severity=warn")
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    session = server = None

    def _manifest():
        for dirpath, _dirs, files in os.walk(capsule_dir):
            if "manifest.json" in files:
                return os.path.join(dirpath, "manifest.json")
        return None

    try:
        # warm compile BEFORE arming obs, the drill_capsule discipline
        session = Session(max_batch=16, n_buckets=2, max_wait_ms=0.5)
        session.register_kernel(KERNEL, k)
        warm = np.linspace(-1.0, 1.0, 8)
        for _ in range(3):
            session.infer(KERNEL, warm, timeout_s=10.0)
        obs.configure(sink)           # re-arms alerts + capsule hook
        conn_mod._reset_for_tests()   # re-reads the HPNN_CONN_* knobs
        server = make_server(session)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        url = f"http://127.0.0.1:{port}"
        load = _Load(port, rate=rate, ingest_frac=0.0, seed=seed)
        time.sleep(2.5)               # clean baseline bins
        t_attack = load.now()
        hostile: dict[str, dict] = {}
        h_lock = threading.Lock()

        def attack(mode: str):
            s = loadgen.run_hostile(
                url, mode=mode, n_conns=n_hostile, duration_s=6.0,
                interval_s=0.3, seed=seed)
            with h_lock:
                hostile[mode] = s

        attackers = [threading.Thread(target=attack, args=(m,),
                                      daemon=True)
                     for m in loadgen.HOSTILE_MODES]
        for t in attackers:
            t.start()
        fired = _wait(
            lambda: (obs.alerts.health_doc().get("fired_total")
                     or None), 15.0, interval_s=0.05)
        t_fire = load.now()
        manifest_path = _wait(_manifest, 10.0, interval_s=0.05)
        for t in attackers:
            t.join(timeout=15.0)
        code, connz = http_get(port, "/connz", timeout_s=2.0)
        records = load.finish(settle_s=1.0)
        server.shutdown()             # table.close drains leftovers,
        server.server_close()         # pairing every open in the sink
        server = None
        obs.configure(None)           # close the sink for the audit
        out.update(blast_radius(records, t_attack))
        out["clean_lost"] = out.pop("lost")
        out["hostile"] = hostile
        out["hung"] = sum(s.get("hung", 0) for s in hostile.values())
        out["fired"] = bool(fired)
        out["fire_s"] = round(t_fire - t_attack, 3) if fired else None
        out["connz_active"] = (connz or {}).get("active")
        man = {}
        if manifest_path:
            with open(manifest_path) as fp:
                man = json.load(fp)
            out["capsule"] = man.get("capsule")
            conn_json = os.path.join(os.path.dirname(manifest_path),
                                     "conn.json")
            out["capsule_conn"] = os.path.exists(conn_json)
        else:
            out["capsule_conn"] = False
        closes: dict[str, int] = {}
        kills: dict[str, int] = {}
        with open(sink) as fp:
            for line in fp:
                rec = json.loads(line)
                if rec.get("ev") == "conn.close":
                    r = rec.get("reason", "?")
                    closes[r] = closes.get(r, 0) + 1
                elif rec.get("ev") == "conn.guard_kill":
                    r = rec.get("reason", "?")
                    kills[r] = kills.get(r, 0) + 1
        out["close_reasons"] = dict(sorted(closes.items()))
        out["guard_kills"] = dict(sorted(kills.items()))
        # every hostile connection must land in a hostile close class
        # (clean keep-alive conns close eof/drain); reset absorbs the
        # races where the peer's FIN beats the short body read
        hostile_accounted = sum(
            closes.get(r, 0) for r in
            ("guard", "torn_body", "fuzz", "timeout", "reset"))
        out["hostile_accounted"] = hostile_accounted
        lint = check_obs_catalog.lint_conn(sink)
        out["lint_failures"] = lint
        slow = hostile.get("slowloris", {}).get("outcomes", {})
        out["ok"] = bool(
            out["goodput_dip_pct"] is not None
            and out["goodput_dip_pct"] <= 10.0
            and out["clean_lost"] == 0
            and out["hung"] == 0
            and hostile_accounted >= 3 * int(n_hostile)
            and slow.get("killed", 0) == int(n_hostile)
            and kills.get("slowloris", 0) >= int(n_hostile)
            and out["fired"]
            and out["capsule_conn"]
            and isinstance(out["connz_active"], int)
            and not lint)
        return out
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if session is not None:
            session.close()
        obs.configure(None)
        for key, val in prev_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        conn_mod._reset_for_tests()


DRILLS = {
    "kill9": drill_kill9,
    "reload": drill_reload,
    "sentinel": drill_sentinel,
    "replica": drill_replica,
    "alert": drill_alert,
    "worker": drill_worker,
    "capsule": drill_capsule,
    "drift": drill_drift,
    "quota": drill_quota,
    "hog": drill_hog,
    "tune": drill_tune,
    "torn": drill_torn,
}


def run_drills(names, *, workdir: str | None = None,
               rate: float = 40.0) -> list[dict]:
    rows = []
    for name in names:
        with tempfile.TemporaryDirectory() as tmp:
            wd = workdir or tmp
            os.makedirs(wd, exist_ok=True)
            rows.append(DRILLS[name](wd, rate=rate))
    return rows


# -------------------------------------------------------------- bench


def run_bench_drill(*, rate: float = 40.0) -> dict:
    """The bench.py fold-in: the kill9 drill's blast radius as three
    gateable numbers.  ``skipped`` (never an exception) when the
    child cannot come up in this environment."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as tmp:
        row = drill_kill9(tmp, rate=rate)
    out = {
        "metric": "chaos_drill",
        "drill": row,
        "recovery_s": row.get("recovery_s"),
        "goodput_dip_pct": row.get("goodput_dip_pct"),
        "lost_requests": row.get("lost"),
        "restored_bitwise": row.get("restored_bitwise"),
        "ok": row.get("ok", False),
    }
    if "skipped" in row:
        out["skipped"] = row["skipped"]
    return out


def run_bench_alert_drill(*, rate: float = 60.0,
                          n_replicas: int = 2) -> dict:
    """The bench.py fold-in for the alert drill: kill + respawn one
    of N replicas under load with a ``router.ready_replicas``
    threshold rule armed, and report fire/resolve latency as
    gateable numbers (``drill_alert_fire_s`` /
    ``drill_alert_resolved``)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as tmp:
        row = drill_alert(tmp, rate=rate, n_replicas=n_replicas)
    out = {
        "metric": "alert_drill",
        "drill": row,
        "fire_s": row.get("fire_s"),
        "resolve_s": row.get("resolve_s"),
        "resolved": 1.0 if row.get("resolved") else 0.0,
        "flight_attached": row.get("flight_attached"),
        "ok": row.get("ok", False),
    }
    if "skipped" in row:
        out["skipped"] = row["skipped"]
    return out


def run_bench_replica_drill(*, rate: float = 80.0,
                            n_replicas: int = 3) -> dict:
    """The bench.py fold-in for the replica drill: kill 1 of N under
    load and report the blast radius as gateable numbers
    (``drill_replica_dip_pct`` / ``drill_replica_survivors_lost``)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as tmp:
        row = drill_replica(tmp, rate=rate, n_replicas=n_replicas)
    out = {
        "metric": "replica_drill",
        "drill": row,
        "goodput_dip_pct": row.get("goodput_dip_pct"),
        "recovery_s": row.get("recovery_s"),
        "lost": row.get("lost"),
        "survivors_lost": row.get("survivors_lost"),
        "survivor_bitwise": row.get("survivor_bitwise"),
        "ok": row.get("ok", False),
    }
    if "skipped" in row:
        out["skipped"] = row["skipped"]
    return out


def run_bench_worker_drill(*, rate: float = 60.0,
                           n_workers: int = 2) -> dict:
    """The bench.py fold-in for the worker drill: SIGKILL 1 of N
    worker processes behind a ClusterRouter under load and report the
    blast radius + replacement latency as gateable numbers
    (``drill_worker_dip_pct`` / ``drill_worker_replaced_s``)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as tmp:
        row = drill_worker(tmp, rate=rate, n_workers=n_workers)
    out = {
        "metric": "worker_drill",
        "drill": row,
        "goodput_dip_pct": row.get("goodput_dip_pct"),
        "recovery_s": row.get("recovery_s"),
        "replaced_s": row.get("replaced_s"),
        "survivors_lost": row.get("survivors_lost"),
        "survivor_bitwise": row.get("survivor_bitwise"),
        "ok": row.get("ok", False),
    }
    if "skipped" in row:
        out["skipped"] = row["skipped"]
    return out


def run_bench_drift_drill(*, rate: float = 20.0) -> dict:
    """The bench.py fold-in for the drift drill: a label-shifted
    stream under live traffic, detection latency as the gateable
    ``drill_drift_detect_s``."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as tmp:
        row = drill_drift(tmp, rate=rate)
    out = {
        "metric": "drift_drill",
        "drill": row,
        "detect_s": row.get("detect_s"),
        "rounds_to_detect": row.get("rounds_to_detect"),
        "lost": row.get("lost"),
        "ok": row.get("ok", False),
    }
    if "skipped" in row:
        out["skipped"] = row["skipped"]
    return out


def run_bench_capsule_drill(*, rate: float = 60.0) -> dict:
    """The bench.py fold-in for the capsule drill: sampler + delayed
    dispatch seam + firing p99 rule under load, reported as gateable
    numbers (``drill_capsule_capture_s`` /
    ``drill_capsule_blame_pct``)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as tmp:
        row = drill_capsule(tmp, rate=rate)
    out = {
        "metric": "capsule_drill",
        "drill": row,
        "capture_s": row.get("capture_s"),
        "dispatch_blame_pct": row.get("dispatch_blame_pct"),
        "capsule_spans": row.get("capsule_spans"),
        "profile_files": row.get("profile_files"),
        "ok": row.get("ok", False),
    }
    if "skipped" in row:
        out["skipped"] = row["skipped"]
    return out


def run_bench_quota_drill(*, rate: float = 100.0) -> dict:
    """The bench.py fold-in for the quota drill: a hostile tenant at
    10x its admission budget against a shared TenantSession, reported
    as gateable numbers (``drill_quota_victim_p99_ms`` /
    ``drill_quota_victim_goodput_ratio``)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as tmp:
        row = drill_quota(tmp, rate=rate)
    out = {
        "metric": "quota_drill",
        "drill": row,
        "victim_p99_ms": row.get("victim_p99_ms"),
        "victim_goodput_ratio": row.get("victim_goodput_ratio"),
        "offender_shed": row.get("offender_shed"),
        "alert_fired": row.get("alert_fired"),
        "ok": row.get("ok", False),
    }
    if "skipped" in row:
        out["skipped"] = row["skipped"]
    return out


def run_bench_tune_drill(*, rate: float = 0.0) -> dict:
    """The bench.py fold-in for the tune drill: the self-tuning
    plane's per-blame-class apply/recover/rollback proof, reported
    as gateable numbers (``drill_tune_applies`` — the fraction of
    blame classes whose dominant window moved the matching knob —
    and ``drill_tune_rollback_bitwise`` — 1.0 when both bad moves
    restored the displaced config bitwise)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as tmp:
        row = drill_tune(tmp, rate=rate)
    out = {
        "metric": "tune_drill",
        "drill": row,
        "applies": row.get("applies"),
        "rollback_bitwise": row.get("rollback_bitwise"),
        "version_chain_monotone": row.get("version_chain_monotone"),
        "ok": row.get("ok", False),
    }
    if "skipped" in row:
        out["skipped"] = row["skipped"]
    return out


def run_bench_torn_drill(*, rate: float = 30.0) -> dict:
    """The bench.py fold-in for the torn drill: the hostile-network
    attack classes against a conn-guarded server under clean load,
    reported as gateable numbers (``drill_torn_dip_pct`` — clean
    goodput dip while under attack — and ``drill_torn_clean_lost``)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as tmp:
        row = drill_torn(tmp, rate=rate)
    out = {
        "metric": "torn_drill",
        "drill": row,
        "dip_pct": row.get("goodput_dip_pct"),
        "clean_lost": row.get("clean_lost"),
        "hostile_accounted": row.get("hostile_accounted"),
        "guard_kills": row.get("guard_kills"),
        "ok": row.get("ok", False),
    }
    if "skipped" in row:
        out["skipped"] = row["skipped"]
    return out


def run_bench_hog_drill(*, rate: float = 12.0) -> dict:
    """The bench.py fold-in for the hog drill: one tenant at 20x the
    zipf head's rate under an armed meter, reported as gateable
    numbers (``drill_hog_blame_pct`` / ``drill_hog_detect_s``)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as tmp:
        row = drill_hog(tmp, rate=rate)
    out = {
        "metric": "hog_drill",
        "drill": row,
        "detect_s": row.get("detect_s"),
        "blame_pct": row.get("blame_pct"),
        "truth_pct": row.get("truth_pct"),
        "alert_fired": row.get("alert_fired"),
        "ok": row.get("ok", False),
    }
    if "skipped" in row:
        out["skipped"] = row["skipped"]
    return out


# --------------------------------------------------------------- main


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="chaos drills against a live online_nn child "
                    "(kill9 / reload / sentinel / replica / alert / "
                    "worker / capsule / drift / quota / hog / tune / "
                    "torn)")
    ap.add_argument("--drill", default="all",
                    choices=("all", "kill9", "reload", "sentinel",
                             "replica", "alert", "worker", "capsule",
                             "drift", "quota", "hog", "tune", "torn"))
    ap.add_argument("--rate", type=float, default=40.0,
                    help="loadgen offered load during the drill")
    ap.add_argument("--workdir",
                    help="keep child conf/logs/WAL here (default: "
                         "a temp dir per drill)")
    ap.add_argument("--out", help="append drill JSONL rows here")
    args = ap.parse_args(argv)
    names = (list(DRILLS) if args.drill == "all" else [args.drill])
    rows = run_drills(names, workdir=args.workdir, rate=args.rate)
    if args.out:
        with open(args.out, "a") as fp:
            for row in rows:
                fp.write(json.dumps(row) + "\n")
    for row in rows:
        sys.stderr.write(f"{row['ev']}: "
                         f"{'ok' if row.get('ok') else row}\n")
    print(json.dumps({"drills": rows,
                      "ok": all(r.get("ok") for r in rows)}))
    return 0 if all(r.get("ok") for r in rows) else 1


if __name__ == "__main__":
    sys.exit(main())
