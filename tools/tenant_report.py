#!/usr/bin/env python3
"""Per-tenant resource blame table over one or more obs sinks.

The meter plane (``HPNN_METER``, obs/meter.py) writes throttled
``meter.sketch`` records — cumulative per-worker space-saving sketches
of device dispatch seconds, FLOPs, bytes, queue-wait seconds, rows
served, and shed counts, attributed to tenants.  This tool ingests
the sinks the fleet already writes (worker ``HPNN_METRICS`` files
and/or the collector's merged stream), keeps each worker's **latest**
sketch (they are cumulative — summing a worker against itself would
double-count), merges them with the same commutative rule the
collector's ``/meterz`` uses (totals add, shared tenants sum count
and error), and prints the per-tenant blame table: device-seconds,
FLOPs, bytes, queue-seconds, rows, sheds, and each tenant's
share-of-fleet device time.  The long tail past ``--top`` rolls into
``_other`` with every column conserving the fleet total exactly —
this is the programmatic input ROADMAP item 5's quota-pressure
remediation consumes, and the drill's "name the hog" oracle
(``tools/chaos_drill.py --drill hog``).

With ``--baseline``, a second sink set renders a paired comparison —
per-axis fleet deltas and per-tenant device-second shifts — so "the
new release doubled tenant X's device share" is one command.

Per-tenant values are space-saving **lower bounds** (``count - err``;
exact for tenants that never left the sketch), so a reported share
can understate but never invent mass; the ``_other`` remainder
absorbs the difference.

Usage::

    python tools/tenant_report.py run.jsonl [more.jsonl ...]
    python tools/tenant_report.py run.jsonl --top 10
    python tools/tenant_report.py run.jsonl --baseline before.jsonl
    python tools/tenant_report.py run.jsonl --json

stdlib-only: the report must render on a login node with no jax
installed (the merge is re-implemented here rather than imported
from ``hpnn_tpu.obs.meter``; tests/test_meter.py pins the two
implementations equal).
"""

from __future__ import annotations

import argparse
import json
import sys

AXES = ("device_s", "flops", "bytes", "queue_s", "rows", "sheds")
OTHER = "_other"


def load_meter_docs(paths: list[str]) -> list[dict]:
    """The latest ``meter.sketch`` record per worker across the sink
    set.  Worker identity is ``(path, pid, rank)`` — a collector's
    merged stream tags every record with the sender's pid/rank, a
    worker's own sink may not (then the file stands for the worker)."""
    latest: dict = {}
    for path in paths:
        with open(path) as fp:
            for ln in fp:
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue  # torn tail line
                if not isinstance(rec, dict) \
                        or rec.get("ev") != "meter.sketch":
                    continue
                key = (path, rec.get("pid"), rec.get("rank"))
                latest[key] = rec  # later line wins: cumulative
    return [latest[k] for k in sorted(latest, key=str)]


def merge_docs(docs: list[dict]) -> dict:
    """Commutative fleet merge of ``meter.sketch`` docs — same rule as
    ``meter.merge_sketch_docs``: per axis, totals add and shared
    tenants sum ``[count, err]``.  Returns ``{"k", "tenants_seen",
    "axes": {axis: {"total", "entries"}}}``."""
    k = max([int(d.get("k") or 32) for d in docs] or [32])
    seen = 0
    axes: dict[str, dict] = {}
    for d in docs:
        seen = max(seen, int(d.get("tenants_seen") or 0))
        for ax, doc in (d.get("axes") or {}).items():
            m = axes.setdefault(ax, {"total": 0.0, "entries": {}})
            m["total"] += float(doc.get("total") or 0.0)
            for t, ce in (doc.get("entries") or {}).items():
                try:
                    c, e = float(ce[0]), float(ce[1])
                except (TypeError, ValueError, IndexError):
                    continue
                cur = m["entries"].get(t)
                if cur is None:
                    m["entries"][t] = [c, e]
                else:
                    cur[0] += c
                    cur[1] += e
    return {"k": k, "tenants_seen": seen, "axes": axes}


def analyze(docs: list[dict], *, top: int = 10) -> dict:
    """The machine-form blame table: the top-``top`` tenants ranked by
    estimated device-seconds (falling back to rows, then any axis,
    for meter streams with no dispatch traffic), one row per tenant
    with every axis's lower-bound estimate, the tail as ``_other``,
    per-axis fleet totals conserved exactly."""
    merged = merge_docs(docs)
    axes = merged["axes"]

    def _est(ax: str, tenant: str) -> float:
        ce = axes.get(ax, {}).get("entries", {}).get(tenant)
        return max(0.0, ce[0] - ce[1]) if ce else 0.0

    rank_ax = next((ax for ax in ("device_s", "rows") if axes.get(ax)),
                   None) or next(iter(sorted(axes)), "device_s")
    candidates = set()
    for ax in axes:
        candidates.update(axes[ax].get("entries", ()))
    ranked = sorted(candidates,
                    key=lambda t: (-_est(rank_ax, t), t))[:max(1, top)]

    totals = {ax: float(axes.get(ax, {}).get("total") or 0.0)
              for ax in AXES}
    dev_total = totals.get("device_s") or 0.0
    rows = []
    for t in ranked:
        row = {"tenant": t}
        for ax in AXES:
            row[ax] = round(_est(ax, t), 9)
        row["share_pct"] = (round(100.0 * row["device_s"] / dev_total, 2)
                            if dev_total > 0 else 0.0)
        rows.append(row)
    other = {"tenant": OTHER}
    for ax in AXES:
        rest = totals[ax] - sum(r[ax] for r in rows)
        other[ax] = round(max(rest, 0.0), 9)
    other["share_pct"] = (round(100.0 * other["device_s"] / dev_total, 2)
                          if dev_total > 0 else 0.0)
    if candidates or any(totals.values()):
        rows.append(other)
    return {
        "workers": len(docs),
        "k": merged["k"],
        "tenants_seen": merged["tenants_seen"],
        "ranked_by": rank_ax,
        "totals": {ax: round(v, 9) for ax, v in totals.items()},
        "tenants": rows,
    }


def compare(rep: dict, base: dict) -> dict:
    """The paired ``--baseline`` digest: per-axis fleet-total deltas
    plus per-tenant device-second / share shifts for every tenant
    named in either report."""
    run_rows = {r["tenant"]: r for r in rep["tenants"]}
    base_rows = {r["tenant"]: r for r in base["tenants"]}
    tenants = {}
    for t in sorted(set(run_rows) | set(base_rows)):
        r = run_rows.get(t, {})
        b = base_rows.get(t, {})
        tenants[t] = {
            "device_s": {"run": r.get("device_s", 0.0),
                         "baseline": b.get("device_s", 0.0),
                         "delta": round(r.get("device_s", 0.0)
                                        - b.get("device_s", 0.0), 9)},
            "share_pct_delta": round(r.get("share_pct", 0.0)
                                     - b.get("share_pct", 0.0), 2),
        }
    return {
        "totals_delta": {
            ax: round(rep["totals"][ax] - base["totals"][ax], 9)
            for ax in AXES},
        "tenants": tenants,
    }


def _num(v: float) -> str:
    if v == 0:
        return "0"
    if abs(v) >= 1e6 or (0 < abs(v) < 1e-3):
        return f"{v:.3g}"
    return f"{v:.4f}".rstrip("0").rstrip(".")


def render(rep: dict, cmp_doc: dict | None = None) -> str:
    out: list[str] = []
    w = out.append
    w("== tenant report ==")
    w(f"workers: {rep['workers']}   tenants seen: "
      f"{rep['tenants_seen']}   top-K (governor): {rep['k']}")
    if not rep["tenants"]:
        w("  (no meter.sketch records — was HPNN_METER armed on the "
          "serving path?)")
        return "\n".join(out) + "\n"
    w("")
    w(f"  {'tenant':16s} {'device_s':>11s} {'share':>7s} {'flops':>11s}"
      f" {'bytes':>11s} {'queue_s':>10s} {'rows':>9s} {'sheds':>7s}")
    for r in rep["tenants"]:
        w(f"  {r['tenant']:16s} {_num(r['device_s']):>11s}"
          f" {r['share_pct']:6.2f}% {_num(r['flops']):>11s}"
          f" {_num(r['bytes']):>11s} {_num(r['queue_s']):>10s}"
          f" {_num(r['rows']):>9s} {_num(r['sheds']):>7s}")
    w("")
    w("-- fleet totals --")
    for ax in AXES:
        w(f"  {ax:10s} {_num(rep['totals'][ax]):>14s}")
    if cmp_doc is not None:
        w("")
        w("-- vs baseline --")
        for ax in AXES:
            d = cmp_doc["totals_delta"][ax]
            if d:
                w(f"  {ax:10s} {d:+.6g}")
        for t, doc in cmp_doc["tenants"].items():
            d = doc["device_s"]["delta"]
            pp = doc["share_pct_delta"]
            if d or pp:
                w(f"  {t:16s} device_s {d:+.6g}   share {pp:+.2f} pp")
    return "\n".join(out) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-tenant resource blame table over HPNN_METER "
                    "sketches in HPNN_METRICS sinks")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="metrics JSONL sink(s); latest sketch per "
                         "worker, merged fleet-wide")
    ap.add_argument("--top", type=int, default=10, metavar="N",
                    help="tenants ranked before the _other rollup "
                         "(default 10)")
    ap.add_argument("--baseline", nargs="+", metavar="path",
                    help="baseline sink(s): append a paired "
                         "comparison (per-tenant deltas)")
    ap.add_argument("--json", action="store_true",
                    help="machine form instead of text")
    args = ap.parse_args(argv)
    try:
        rep = analyze(load_meter_docs(args.paths), top=args.top)
        cmp_doc = None
        if args.baseline:
            base = analyze(load_meter_docs(args.baseline),
                           top=args.top)
            cmp_doc = compare(rep, base)
    except OSError as exc:
        sys.stderr.write(f"tenant_report: {exc}\n")
        return 1
    if args.json:
        doc = dict(rep)
        if cmp_doc is not None:
            doc["baseline"] = cmp_doc
        json.dump(doc, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render(rep, cmp_doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
