"""Repo tooling: benches, drills, lints.

Most entries are standalone scripts (``python tools/bench_gate.py``);
``tools/hpnnlint/`` is a package so the static-analysis suite runs as
``python -m tools.hpnnlint`` (docs/analysis.md).
"""
