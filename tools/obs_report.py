#!/usr/bin/env python3
"""Render an ``HPNN_METRICS`` JSONL sink into a run report.

Usage::

    python tools/obs_report.py run.metrics.jsonl          # text report
    python tools/obs_report.py run.metrics.jsonl --json   # machine form
    python tools/obs_report.py run.metrics.jsonl --spans  # span tree
    python tools/obs_report.py --merge run.0.jsonl run.1.jsonl \
        [--out merged.jsonl]                              # cross-rank
    python tools/obs_report.py run.metrics.jsonl --follow [--for S]
                                                          # live tail

Reads the event stream produced by ``hpnn_tpu.obs`` (schema:
docs/observability.md) and prints, in order: the run header, lifecycle
events, counter totals, timer stats, histograms (with ASCII log2-bucket
bars), the fused-round chunk-dispatch timeline, and the
fallback/resume event log in emission order.

``--merge`` joins the per-rank sinks a ``{rank}`` path produced into
one cross-rank timeline: every record is tagged with its rank (taken
from the stream's ``obs.open`` line, else the file position), per-rank
timestamps are clamped monotone (a stepped host clock must not reorder
one rank's own emission order), and the streams are stably merged by
``(ts, rank, seq)`` — skew between hosts cannot interleave a rank
against itself, only shift it against its peers.

Span ids are process-local, so the span report keys every span by a
global ``"<pid-hex>:<id>"`` ref (pid from the record's own tag or the
stream's ``obs.open`` line) and resolves ``remote_parent`` fields
(obs/propagate.py) across processes: feed ``--merge --spans --req
<id>`` the sinks of a client, an edge and its replicas, and ONE
request renders as one tree spanning all of them (docs/observability.md
"Fleet telemetry").  ``--follow`` live-tails a growing sink.

stdlib-only on purpose: the report must render on a login node with no
jax installed, and ``bench.py`` imports :func:`summarize` in-process.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# kinds whose per-line records we keep verbatim for the ordered logs
_FALLBACK_EVS = (
    "fallback.",
    "fuse.chunk_halved",
    "batch.cap_halved",
    "resume.restore",
    "round.abort",
)


def load_events(path: str) -> list[dict]:
    """Parse the JSONL sink, skipping lines a crash may have truncated."""
    events = []
    with open(path) as fp:
        for line in fp:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail line from a crashed writer
    return events


def merge_events(paths: list[str]) -> list[dict]:
    """Join per-rank JSONL sinks into one skew-tolerant timeline.

    Each file's records are tagged ``rank`` (from its ``obs.open``
    line when present, else the argument position) and kept in their
    original emission order: per-rank timestamps are clamped monotone
    non-decreasing before the merge, so a host clock stepping backwards
    mid-run cannot reorder a rank against itself.  The streams are then
    stably sorted by ``(ts, rank, seq)``.
    """
    tagged = []
    for pos, path in enumerate(paths):
        events = load_events(path)
        rank, pid = pos, None
        for rec in events:
            if rec.get("ev") == "obs.open":
                if "rank" in rec:
                    rank = int(rec["rank"])
                if "pid" in rec:
                    pid = int(rec["pid"])
                break
        last_ts = 0.0
        for seq, rec in enumerate(events):
            ts = rec.get("ts")
            ts = float(ts) if isinstance(ts, (int, float)) else last_ts
            ts = max(ts, last_ts)
            last_ts = ts
            rec = dict(rec)
            rec.setdefault("rank", rank)
            if pid is not None:
                rec.setdefault("pid", pid)
            tagged.append((ts, rank, seq, rec))
    tagged.sort(key=lambda t: t[:3])
    return [rec for _ts, _rank, _seq, rec in tagged]


def _follow_line(rec: dict) -> str:
    """One compact human line per live-tailed record."""
    ts = rec.get("ts")
    head = f"{ts:12.3f}" if isinstance(ts, (int, float)) else " " * 12
    ev = rec.get("ev", "?")
    fields = ", ".join(
        f"{k}={v}" for k, v in rec.items()
        if k not in ("ts", "ev", "kind") and not isinstance(v, (dict,
                                                                list)))
    return f"{head}  {ev:<28s}" + (f" {fields}" if fields else "")


def follow(path: str, duration_s: float | None = None,
           out=None, poll_s: float = 0.25) -> int:
    """Live-tail a growing JSONL sink (``--follow``): print one
    compact line per record as it lands, from the start of the file.
    A not-yet-created file is waited for; a torn tail line is held
    back until its newline arrives (the crash-safe writer appends
    whole lines, so a partial read is mid-write, not corruption).
    Runs until ``duration_s`` elapses (forever when None — ^C stops
    it); returns the number of records printed."""
    out = out or sys.stdout
    t0 = time.monotonic()
    fp, buf, n = None, "", 0
    try:
        while True:
            if fp is None:
                try:
                    fp = open(path)
                except OSError:
                    pass
            if fp is not None:
                chunk = fp.read()
                if chunk:
                    buf += chunk
                    while "\n" in buf:
                        line, buf = buf.split("\n", 1)
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        out.write(_follow_line(rec) + "\n")
                        out.flush()
                        n += 1
            if (duration_s is not None
                    and time.monotonic() - t0 >= duration_s):
                return n
            time.sleep(poll_s)
    except KeyboardInterrupt:
        return n
    finally:
        if fp is not None:
            fp.close()


def _merge_hist(dst: dict, rec: dict) -> None:
    n = int(rec.get("n", 0))
    dst["n"] = dst.get("n", 0) + n
    if not n:
        return
    dst["sum"] = dst.get("sum", 0.0) + float(rec.get("sum", 0.0))
    for k, pick in (("min", min), ("max", max)):
        v = rec.get(k)
        if v is not None:
            dst[k] = pick(dst[k], v) if k in dst else v


def summarize(events: list[dict]) -> dict:
    """Fold the stream into one report dict (the --json output)."""
    rep = {
        "events": {},       # point-event name -> occurrences
        "counters": {},     # counter name -> final running total
        "timers": {},       # timer name -> {n, total, mean, min, max}
        "histograms": {},   # hist name -> merged batch stats
        "gauges": {},       # gauge name -> last value
        "chunk_timeline": [],   # fused-round dispatch latency timeline
        "fallback_log": [],     # ordered fallback/resume/halving records
        "summary": None,        # LAST obs.summary record (cumulative)
        "rounds": [],           # round.start/round.end/eval.round events
        "numerics": {           # numerics.* probe/sentinel digest
            "checks": 0,
            "last_checksums": None,
            "alerts": [],       # numerics.nan / numerics.divergence
        },
    }
    for rec in events:
        ev = rec.get("ev", "?")
        kind = rec.get("kind", "event")
        if kind == "summary":
            rep["summary"] = rec
            continue
        if kind == "count":
            rep["counters"][ev] = rec.get("total", 0)
        elif kind == "gauge":
            rep["gauges"][ev] = rec.get("value")
        elif kind == "timer":
            t = rep["timers"].setdefault(ev, {"n": 0, "total": 0.0})
            dt = float(rec.get("dt", 0.0))
            t["n"] += 1
            t["total"] += dt
            t["min"] = min(t.get("min", dt), dt)
            t["max"] = max(t.get("max", dt), dt)
            if ev == "driver.chunk_dispatch":
                rep["chunk_timeline"].append({
                    "done": rec.get("done"),
                    "size": rec.get("size"),
                    "body": rec.get("body"),
                    "dt": dt,
                    "failed": rec.get("failed"),
                })
        elif kind == "hist":
            _merge_hist(rep["histograms"].setdefault(ev, {}), rec)
        else:
            rep["events"][ev] = rep["events"].get(ev, 0) + 1
            if ev.startswith(("round.", "eval.")):
                rep["rounds"].append(rec)
            elif ev == "numerics.checksum":
                rep["numerics"]["checks"] += 1
                rep["numerics"]["last_checksums"] = rec.get("checksums")
            elif ev in ("numerics.nan", "numerics.divergence"):
                rep["numerics"]["alerts"].append(rec)
        if ev.startswith(_FALLBACK_EVS[0]) or ev in _FALLBACK_EVS[1:]:
            rep["fallback_log"].append(rec)
    for t in rep["timers"].values():
        t["mean"] = t["total"] / t["n"] if t["n"] else 0.0
    # the cumulative aggregates in the last summary carry the exact
    # per-name log2 buckets — surface them beside the per-line merges
    if rep["summary"]:
        for name, agg in rep["summary"].get("aggregates", {}).items():
            if name in rep["histograms"]:
                rep["histograms"][name]["log2_buckets"] = agg.get(
                    "log2_buckets", {})
                rep["histograms"][name]["mean"] = agg.get("mean")
    return rep


_SPAN_META = ("ts", "ev", "kind", "span", "parent", "name", "t0", "dt",
              "remote_parent", "pid", "rank")


def collect_spans(events: list[dict]) -> list[dict]:
    """Pull the ``span.end`` records (HPNN_SPANS) out of the stream.

    Each span carries its own local id, its local parent id (or None),
    a monotonic start ``t0`` and duration ``dt``.  Ids are only unique
    *within* one process, so every span also gains a globally-unique
    ``ref`` = ``"<pid-hex>:<id>"`` — the pid comes from the record
    itself (collector-merged streams tag each record), from the
    stream's ``obs.open`` line, or defaults to 0 for a legacy
    single-process sink.  ``parent_ref`` resolves in that key space:
    a local parent id stays within the span's own process, while a
    ``remote_parent`` field (obs/propagate.py — a ref minted in
    ANOTHER process and carried over the wire in ``X-Parent-Span``)
    crosses it, which is what lets one request's tree span N sinks.
    Returned in ``t0`` order (meaningful within a process; across
    processes it is only a display order).
    """
    # pre-pass: last obs.open pid per rank, for streams whose records
    # are not individually pid-tagged
    pid_of_rank: dict = {}
    default_pid = 0
    for rec in events:
        if rec.get("ev") == "obs.open" and "pid" in rec:
            default_pid = int(rec["pid"])
            pid_of_rank[rec.get("rank")] = default_pid
    spans = []
    for rec in events:
        if rec.get("ev") != "span.end":
            continue
        pid = rec.get("pid")
        if pid is None:
            pid = pid_of_rank.get(rec.get("rank"), default_pid)
        pid = int(pid)
        sid = rec.get("span")
        parent = rec.get("parent")
        if parent is not None:
            parent_ref = f"{pid:x}:{parent}"
        else:
            parent_ref = rec.get("remote_parent")
        spans.append({
            "span": sid,
            "parent": parent,
            "pid": pid,
            "ref": None if sid is None else f"{pid:x}:{sid}",
            "parent_ref": parent_ref,
            "name": rec.get("name", "?"),
            "t0": float(rec.get("t0", 0.0)),
            "dt": float(rec.get("dt", 0.0)),
            "fields": {k: v for k, v in rec.items()
                       if k not in _SPAN_META},
        })
    spans.sort(key=lambda s: s["t0"])
    return spans


def filter_spans_req(spans: list[dict], req_id: str) -> list[dict]:
    """Keep only the spans belonging to one request: every span whose
    fields carry ``req_id == <id>`` (the edge-minted id the HTTP layer
    threads through ``serve.request``/``serve.queue``), plus their
    ancestors and descendants — so ``--req`` reconstructs the full
    queue/dispatch breakdown of a single request from a busy sink.
    Ancestry follows ``parent_ref``, so with trace propagation armed
    the kept set crosses process boundaries (client → edge → replica).
    """
    by_id = {s["ref"]: s for s in spans if s["ref"] is not None}
    keep: set = set()
    for s in spans:
        if s["fields"].get("req_id") != req_id:
            continue
        cur = s
        while cur is not None and cur["ref"] not in keep:
            keep.add(cur["ref"])
            cur = by_id.get(cur["parent_ref"])
    changed = True
    while changed:
        changed = False
        for s in spans:
            if s["ref"] in keep:
                continue
            parent = by_id.get(s["parent_ref"])
            if parent is not None and parent["ref"] in keep:
                keep.add(s["ref"])
                changed = True
    return [s for s in spans if s["ref"] in keep]


def span_tree(spans: list[dict]) -> list[dict]:
    """Arrange spans into root trees (children nested under parents).

    Parent links resolve by global ``ref``, so a remote parent (trace
    propagation) nests its children exactly like a local one.  A span
    whose parent never finished in any provided sink (e.g. a truncated
    or missing file) is promoted to a root rather than dropped.
    Children stay in ``t0`` order.  Returns the list of roots; each
    node gains ``children`` and ``child_s`` (the sum of its direct
    children's durations — by construction ≤ the parent's own ``dt``
    when nesting is honest AND the child ran in the parent's process;
    a remote child's clock is its own).
    """
    by_id = {s["ref"]: s for s in spans if s["ref"] is not None}
    roots: list[dict] = []
    for s in spans:
        s.setdefault("children", [])
        parent = by_id.get(s["parent_ref"])
        if parent is None or parent is s:
            roots.append(s)
        else:
            parent.setdefault("children", []).append(s)
    for s in spans:
        s["child_s"] = sum(c["dt"] for c in s["children"])
    return roots


def _render_span_node(w, node: dict, depth: int,
                      show_pid: bool = False) -> None:
    pad = "  " * depth
    extra = ""
    if node["children"]:
        extra = (f"  (children {node['child_s']:.6f}s,"
                 f" self {max(node['dt'] - node['child_s'], 0.0):.6f}s)")
    tag = f" @{node['pid']:x}" if show_pid else ""
    fields = ", ".join(f"{k}={v}" for k, v in
                       sorted(node["fields"].items()))
    w(f"  {pad}{node['name']:<{max(28 - 2 * depth, 8)}s}"
      f" {node['dt']:10.6f}s{tag}{extra}"
      + (f"  [{fields}]" if fields else ""))
    for child in node["children"]:
        _render_span_node(w, child, depth + 1, show_pid)


def render_spans(events: list[dict], top: int = 10,
                 req_id: str | None = None) -> str:
    """The --spans report: latency-breakdown tree + slowest-N table.

    The tree nests each span under its parent so queue wait
    (``serve.queue``) reads separately from device time
    (``serve.dispatch``) inside one ``serve.request``, and each parent
    shows its children-sum vs. self time.  ``req_id`` narrows the
    report to one request's spans (--req).
    """
    spans = collect_spans(events)
    if req_id is not None:
        spans = filter_spans_req(spans, req_id)
    out: list[str] = []
    w = out.append
    w("== span report ==")
    if req_id is not None:
        w(f"req_id: {req_id}")
    if not spans:
        if req_id is not None:
            w(f"  (no spans carry req_id={req_id!r})")
        else:
            w("  (no span.end records — was HPNN_SPANS set?)")
        return "\n".join(out) + "\n"
    w(f"spans: {len(spans)}")
    pids = sorted({s["pid"] for s in spans})
    multi = len(pids) > 1
    if multi:
        w("processes: " + ", ".join(f"{p:x}" for p in pids))
    w("")
    w("-- latency tree (t0 order; dt seconds) --")
    for root in span_tree(spans):
        _render_span_node(w, root, 0, show_pid=multi)
    w("")
    w(f"-- slowest {min(top, len(spans))} --")
    w(f"  {'name':28s} {'dt_s':>10s} {'span':>12s} {'parent':>12s}")
    for s in sorted(spans, key=lambda s: -s["dt"])[:top]:
        parent = s["parent_ref"] or "-"
        flag = (f"  FAILED({s['fields']['failed']})"
                if s["fields"].get("failed") else "")
        w(f"  {s['name']:28s} {s['dt']:10.6f} {str(s['ref']):>12s}"
          f" {parent:>12s}{flag}")
    return "\n".join(out) + "\n"


def _bar(count: int, peak: int, width: int = 30) -> str:
    if peak <= 0:
        return ""
    return "#" * max(1, int(round(width * count / peak)))


def _bucket_label(k: int) -> str:
    # bucket k holds values in (2^(k-1), 2^k]; k=0 holds v <= 0
    return "<=0" if k == 0 else f"<=2^{k}"


def render(rep: dict) -> str:
    out = []
    w = out.append
    w("== hpnn obs report ==")
    s = rep.get("summary")
    if s:
        w(f"uptime: {s.get('uptime_s', '?')} s"
          f"   (summary lines use the cumulative aggregates)")
    ranks = rep.get("ranks")
    if ranks:
        w("ranks: " + ", ".join(
            f"{k}: {v} events" for k, v in ranks.items()))
    for rec in rep["rounds"]:
        fields = {k: v for k, v in rec.items()
                  if k not in ("ts", "ev", "kind")}
        w(f"  {rec['ev']}: " + ", ".join(
            f"{k}={v}" for k, v in fields.items()))
    if rep["events"]:
        w("")
        w("-- events --")
        for name, n in sorted(rep["events"].items()):
            w(f"  {name:32s} x{n}")
    if rep["counters"]:
        w("")
        w("-- counters (final totals) --")
        for name, total in sorted(rep["counters"].items()):
            w(f"  {name:32s} {total}")
    if rep["gauges"]:
        w("")
        w("-- gauges (last value) --")
        for name, v in sorted(rep["gauges"].items()):
            w(f"  {name:32s} {v}")
    if rep["timers"]:
        w("")
        w("-- timers --")
        w(f"  {'name':32s} {'n':>6s} {'total_s':>10s} {'mean_s':>10s}"
          f" {'min_s':>10s} {'max_s':>10s}")
        for name, t in sorted(rep["timers"].items()):
            w(f"  {name:32s} {t['n']:6d} {t['total']:10.4f}"
              f" {t['mean']:10.4f} {t.get('min', 0.0):10.4f}"
              f" {t.get('max', 0.0):10.4f}")
    for name, h in sorted(rep["histograms"].items()):
        w("")
        w(f"-- histogram {name} --")
        n = h.get("n", 0)
        mean = h.get("mean")
        if mean is None and n:
            mean = h.get("sum", 0.0) / n
        w(f"  n={n}  mean={mean if mean is None else round(mean, 4)}"
          f"  min={h.get('min')}  max={h.get('max')}")
        buckets = h.get("log2_buckets") or {}
        if buckets:
            peak = max(buckets.values())
            for k in sorted(buckets, key=int):
                c = buckets[k]
                w(f"  {_bucket_label(int(k)):>8s} {c:8d} "
                  f"{_bar(c, peak)}")
    if rep["chunk_timeline"]:
        w("")
        w("-- fused chunk timeline --")
        w(f"  {'done':>8s} {'size':>6s} {'body':>7s} {'dt_s':>9s}")
        for c in rep["chunk_timeline"]:
            flag = f"  FAILED({c['failed']})" if c.get("failed") else ""
            w(f"  {str(c['done']):>8s} {str(c['size']):>6s}"
              f" {str(c['body']):>7s} {c['dt']:9.4f}{flag}")
    num = rep.get("numerics") or {}
    if num.get("checks") or num.get("alerts"):
        w("")
        w("-- numerics --")
        w(f"  checks: {num.get('checks', 0)}"
          f"   alerts: {len(num.get('alerts', []))}")
        cs = num.get("last_checksums")
        if cs:
            w("  last checksums (abs-sums):")
            for name, v in sorted(cs.items()):
                w(f"    {name:8s} {v!r}")
        for rec in num.get("alerts", []):
            fields = {k: v for k, v in rec.items()
                      if k not in ("ts", "ev", "kind", "detail")}
            w(f"  ALERT {rec['ev']}: " + ", ".join(
                f"{k}={v}" for k, v in fields.items()))
    if rep["fallback_log"]:
        w("")
        w("-- fallback / resume log (emission order) --")
        for rec in rep["fallback_log"]:
            fields = {k: v for k, v in rec.items()
                      if k not in ("ts", "ev", "kind", "total")}
            w(f"  {rec['ev']}: " + ", ".join(
                f"{k}={v}" for k, v in fields.items()))
    return "\n".join(out) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize an HPNN_METRICS JSONL sink")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="metrics JSONL file (several with --merge)")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON instead of text")
    ap.add_argument("--spans", action="store_true",
                    help="render the HPNN_SPANS latency-breakdown "
                         "tree and slowest-N table instead of the "
                         "aggregate report")
    ap.add_argument("--top", type=int, default=10, metavar="N",
                    help="with --spans: rows in the slowest table "
                         "(default 10)")
    ap.add_argument("--req", metavar="ID",
                    help="with --spans: only the spans of one request "
                         "(the X-Request-Id the serve layer minted; "
                         "ancestors/descendants included)")
    ap.add_argument("--merge", action="store_true",
                    help="join several {rank}-expanded sinks into one "
                         "cross-rank timeline (skew-tolerant ordering)")
    ap.add_argument("--out", metavar="FILE",
                    help="with --merge: also write the merged JSONL "
                         "timeline to FILE")
    ap.add_argument("--follow", action="store_true",
                    help="live-tail ONE growing sink: one compact "
                         "line per record as it lands (^C stops)")
    ap.add_argument("--for", dest="follow_s", type=float, metavar="S",
                    help="with --follow: stop after S seconds "
                         "(default: run until interrupted)")
    args = ap.parse_args(argv)
    if args.follow:
        if (args.merge or args.spans or args.json
                or len(args.paths) > 1):
            sys.stderr.write("obs_report: --follow takes one path and "
                             "no other mode\n")
            return 2
        follow(args.paths[0], duration_s=args.follow_s)
        return 0
    if args.follow_s is not None:
        sys.stderr.write("obs_report: --for needs --follow\n")
        return 2
    if len(args.paths) > 1 and not args.merge:
        sys.stderr.write("obs_report: several paths need --merge\n")
        return 2
    if args.out and not args.merge:
        sys.stderr.write("obs_report: --out needs --merge\n")
        return 2
    try:
        if args.merge:
            events = merge_events(args.paths)
        else:
            events = load_events(args.paths[0])
    except OSError as exc:
        sys.stderr.write(f"obs_report: {exc}\n")
        return 1
    if args.req and not args.spans:
        sys.stderr.write("obs_report: --req needs --spans\n")
        return 2
    if args.spans:
        if args.json:
            spans = collect_spans(events)
            if args.req:
                spans = filter_spans_req(spans, args.req)
            json.dump(spans, sys.stdout, indent=2, default=str)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(render_spans(events, top=args.top,
                                          req_id=args.req))
        return 0
    rep = summarize(events)
    if args.merge:
        ranks: dict = {}
        for rec in events:
            r = rec.get("rank")
            ranks[r] = ranks.get(r, 0) + 1
        rep["ranks"] = {str(k): ranks[k]
                        for k in sorted(ranks, key=str)}
        if args.out:
            try:
                with open(args.out, "w") as fp:
                    for rec in events:
                        fp.write(json.dumps(rec, default=str) + "\n")
            except OSError as exc:
                sys.stderr.write(f"obs_report: {exc}\n")
                return 1
    if args.json:
        json.dump(rep, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
