#!/usr/bin/env python3
"""Train-while-serve bench: goodput under background training vs the
idle-serve plateau, plus promotion latency.

The bench.py fold-in for the online-learning layer (docs/online.md):

1. stand up an in-process ``OnlineSession`` over a tiny kernel and
   pre-feed its stream buffer;
2. **idle phase** — closed-loop infer traffic with the trainer
   stopped: the idle-serve goodput plateau;
3. **online phase** — start the background trainer (tight cadence)
   and rerun the same closed loop with an ingest mix
   (``loadgen --mix``): goodput while candidates train and promote
   in the same process;
4. report ``goodput_vs_idle`` (how much serving throughput background
   training costs), the promotion count, and the measured promotion
   latency (gate pass → new version warmed and resident).

Usage: ``python tools/bench_online.py`` prints the result as one JSON
line; ``bench.py`` imports :func:`run_bench_online` (best-effort,
``HPNN_BENCH_NO_ONLINE=1`` skips) and ``tools/bench_gate.py`` gates
``online_goodput_rps`` / ``online_goodput_vs_idle`` /
``online_promote_latency_ms``.
"""

from __future__ import annotations

import json
import os
import sys
import threading


def run_bench_online(*, seed: int = 11, idle_s: float = 1.2,
                     online_s: float = 1.5, n_clients: int = 4,
                     ingest_frac: float = 0.25) -> dict:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    tools = os.path.dirname(os.path.abspath(__file__))
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import numpy as np

    import loadgen
    from hpnn_tpu import online, serve
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve.server import make_server

    n_in, n_out = 8, 2
    k, _ = kernel_mod.generate(seed, n_in, [6], n_out)
    osess = None
    server = None
    try:
        osess = online.OnlineSession(
            serve_kwargs=dict(max_batch=16, n_buckets=3,
                              max_wait_ms=1.0, max_depth=128),
            rows=32, batch=8, epochs=4, interval_s=0.05, holdout=4,
            gate=online.Gate(margin=0.0, watch_s=5.0), seed=seed)
        osess.add_kernel("bench", k)
        # pre-feed: a learnable synthetic stream (targets a smooth
        # function of the inputs) so the gate has real improvements
        # to promote during the online phase
        rng = np.random.RandomState(seed)
        X = rng.uniform(0.0, 1.0, size=(192, n_in))
        T = np.tanh(X[:, :n_out])
        osess.feed(X, T)
        # pay the one-time epoch-fn + eval compiles outside the
        # measured phases (a real resident process compiles once)
        osess.tick()
        server = make_server(osess.serve, port=0)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        common = dict(kernels=("bench",), rows_choices=(1, 2, 4),
                      n_in=n_in, timeout_s=2.0, max_retries=0)
        # discarded warmup (first requests pay tracing)
        loadgen.run_closed_loop(url, n_clients=2, duration_s=0.3,
                                seed=seed, **common)
        idle = loadgen.run_closed_loop(url, n_clients=n_clients,
                                       duration_s=idle_s, seed=seed,
                                       **common)
        promoted_before = osess.promoter.stats["promoted"]
        osess.start()
        mix = loadgen.run_closed_loop(
            url, n_clients=n_clients, duration_s=online_s,
            seed=seed + 1, ingest_frac=ingest_frac, n_out=n_out,
            **common)
        osess.trainer.close()
        promotions = (osess.promoter.stats["promoted"]
                      - promoted_before)
        lat_s = osess.promoter.last_promote_latency_s
        idle_rps = idle["goodput_rps"]
        vs_idle = (mix["goodput_rps"] / idle_rps if idle_rps
                   else None)
        return {
            "metric": "online_train_while_serve",
            "idle_goodput_rps": idle_rps,
            "online_goodput_rps": mix["goodput_rps"],
            "online_goodput_vs_idle": (None if vs_idle is None
                                       else round(vs_idle, 4)),
            "ingest_frac": ingest_frac,
            "promotions": promotions,
            "rollbacks": osess.promoter.stats["rollbacks"],
            "promote_latency_ms": (None if lat_s is None
                                   else round(lat_s * 1e3, 3)),
            "trainer_rounds": osess.trainer.stats["rounds"],
            "idle": idle,
            "online": mix,
        }
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if osess is not None:
            osess.close()


def main(argv=None) -> int:
    out = run_bench_online()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
