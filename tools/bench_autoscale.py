#!/usr/bin/env python3
"""End-to-end autoscale demo: a loadgen ramp the fleet rides.

The acceptance measurement for the cross-host fleet (docs/serving.md
"Cross-host fleet"): real ``serve_nn`` worker PROCESSES behind a
:class:`~hpnn_tpu.fleet.router.ClusterRouter` HTTP edge, an
:class:`~hpnn_tpu.fleet.autoscaler.Autoscaler` closing the loop on the
router's own gauges, and ``tools/loadgen.py`` offering an open-loop
ramp at ≥2x the single-worker plateau:

1. spawn ONE worker, measure its saturation plateau closed-loop;
2. offer ~2x that open-loop for ``ramp_s`` — the worker sheds, the
   router's ``shed_total`` climbs, the autoscaler scales 1→N
   (``fleet.scale_up``, readiness-gated spawns on a shared compile
   cache);
3. windowed goodput over the ramp's tail must reach ≥1.5x the
   1-worker plateau with bounded p99 (``autoscale_goodput_x`` /
   ``autoscale_p99_ms``);
4. after the ramp the sheds age out of the calm window and the
   autoscaler drains back to min width — ``autoscale_settle_s`` is
   ramp-end → width 1.

Per-worker capacity is pinned by ``HPNN_SERVE_RATE_CAP`` (the serve
edge's admission token bucket, injected into the workers' env only):
on a shared-core host N processes cannot multiply *CPU*, and an
unbounded tiny-kernel worker would serve any offered rate from one
core — capacity-bound workers are the regime autoscaling exists for,
and the cap makes the 1→N goodput scaling REAL (each admitted request
still runs the full HTTP + batcher + engine path).

:func:`run_bench_autoscale` is the bench.py fold-in (compact keys
``autoscale_goodput_x`` / ``autoscale_p99_ms`` /
``autoscale_settle_s``, gated by tools/bench_gate.py); ``--json``
prints the full document.  Skips cleanly (``"skipped"``) when the
first worker cannot start.

    JAX_PLATFORMS=cpu python tools/bench_autoscale.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

KERNEL = "auto"
CONF = (f"[name] {KERNEL}\n[type] ANN\n[init] generate\n[seed] 7\n"
        "[input] 8\n[hidden] 5\n[output] 2\n[train] BP\n")


def run_bench_autoscale(*, cap_rps: float = 60.0, max_width: int = 3,
                        ramp_s: float = 16.0, window_s: float = 4.0,
                        seed: int = 9,
                        ready_timeout_s: float = 90.0) -> dict:
    """One full ramp ride (module doc); returns the result document."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import loadgen

    from hpnn_tpu.fleet.autoscaler import Autoscaler, Policy
    from hpnn_tpu.fleet.router import ClusterRouter
    from hpnn_tpu.fleet.worker import WorkerSupervisor
    from hpnn_tpu.serve.server import make_server

    loadgen.shield_sigpipe()
    out: dict = {"metric": "autoscale", "cap_rps": float(cap_rps),
                 "max_width": int(max_width)}
    workdir = tempfile.mkdtemp(prefix="hpnn_autoscale_")
    conf_path = os.path.join(workdir, "nn.conf")
    with open(conf_path, "w") as fp:
        fp.write(CONF)

    sup = WorkerSupervisor(
        conf_path, workdir=workdir, kind="serve",
        ready_timeout_s=ready_timeout_s,
        env={"JAX_PLATFORMS": "cpu",
             # capacity-bound workers (module doc): the cap lives in
             # the WORKERS' env only — the router edge in this
             # process stays uncapped
             "HPNN_SERVE_RATE_CAP": str(cap_rps)})
    router = None
    server = None
    scaler = None
    try:
        try:
            sup.spawn()
        except (RuntimeError, OSError) as exc:
            out["skipped"] = f"worker spawn failed: {exc}"
            out["ok"] = False
            return out
        router = ClusterRouter(supervisor=sup)
        server = make_server(router, port=0)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        common = dict(kernels=(KERNEL,), rows_choices=(1,), n_in=8,
                      timeout_s=2.0)

        # ---- 1-worker plateau: closed loop, Retry-After honored so
        # the probe measures the admitted rate, not a 429 storm
        loadgen.run_closed_loop(url, n_clients=2, duration_s=0.4,
                                seed=seed, max_retries=2,
                                retry_cap_s=0.2, **common)  # warmup
        sat = loadgen.run_closed_loop(
            url, n_clients=4, duration_s=2.0, seed=seed,
            max_retries=2, retry_cap_s=0.2, **common)
        sat_rps = sat["goodput_rps"]
        out["sat_rps_1worker"] = sat_rps
        if not sat_rps:
            out["skipped"] = "no goodput from the 1-worker probe"
            out["ok"] = False
            return out

        # ---- the autoscaler rides the router's own gauges
        policy = Policy(min_width=1, max_width=int(max_width),
                        up_step=max(1, int(max_width) - 1),
                        up_cooldown_s=2.0, down_for_s=3.0,
                        down_cooldown_s=3.0)
        scaler = Autoscaler(sup, router, policy=policy,
                            interval_s=0.5)
        widths: list[tuple[float, int]] = [(0.0, sup.width())]
        stop_sampler = threading.Event()
        t0 = time.monotonic()

        def sampler():
            while not stop_sampler.is_set():
                w = sup.width()
                if w != widths[-1][1]:
                    widths.append((round(time.monotonic() - t0, 3), w))
                stop_sampler.wait(0.1)

        sampler_t = threading.Thread(target=sampler, daemon=True)
        sampler_t.start()
        scaler.start()

        # ---- the ramp: well past 2x the plateau — above even the
        # FULL fleet's capacity, so max width sheds steadily and the
        # fleet holds wide instead of flapping calm/shed at the
        # capacity boundary; width shrinks only after the ramp ends
        offered = max(10.0, 3.3 * sat_rps)
        records: list[dict] = []
        rec_lock = threading.Lock()

        def on_record(rec):
            with rec_lock:
                records.append(rec)

        load = loadgen.run_open_loop(
            url, rate_rps=offered, duration_s=ramp_s,
            process="poisson", n_workers=16, seed=seed + 1,
            max_retries=0, on_record=on_record, **common)
        t_ramp_end = time.monotonic()
        out["offered_rps"] = load["offered_rps"]
        out["load"] = load

        # ---- windowed goodput + p99 over the ramp's tail (scale-up
        # has settled by then; rec["t"] is the scheduled arrival)
        lo = ramp_s - window_s
        tail_ok = [r for r in records
                   if r["status"] == "ok" and r["t"] >= lo]
        goodput_win = len(tail_ok) / window_s
        lat_s = [r["latency_ms"] / 1e3 for r in tail_ok]
        out["window_s"] = window_s
        out["goodput_rps"] = round(goodput_win, 1)
        out["goodput_x"] = round(goodput_win / sat_rps, 3)
        out["p99_ms"] = (loadgen.percentile_ms(lat_s, 99)
                         if lat_s else None)

        # ---- scale back down once the ramp's sheds age out
        settle_deadline = t_ramp_end + 45.0
        while (sup.width() > policy.min_width
               and time.monotonic() < settle_deadline):
            time.sleep(0.1)
        settled = sup.width() == policy.min_width
        out["settle_s"] = (round(time.monotonic() - t_ramp_end, 3)
                           if settled else None)
        scaler.stop()
        stop_sampler.set()
        sampler_t.join(timeout=5.0)
        out["width_timeline"] = widths
        out["scaled_to"] = max(w for _t, w in widths)
        up_t = [t for t, w in widths if w > 1]
        out["react_s"] = round(up_t[0], 3) if up_t else None
        out["ok"] = bool(
            out["scaled_to"] >= 2
            and out["goodput_x"] >= 1.5
            and out["p99_ms"] is not None
            and out["p99_ms"] < 2000.0
            and settled)
        return out
    finally:
        if scaler is not None:
            scaler.stop()
        if server is not None:
            server.shutdown()
            server.server_close()
        if router is not None:
            router.close()
        sup.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="autoscaler ramp ride over real worker processes")
    ap.add_argument("--cap", type=float, default=60.0,
                    help="per-worker HPNN_SERVE_RATE_CAP (rps)")
    ap.add_argument("--max-width", type=int, default=3)
    ap.add_argument("--ramp-s", type=float, default=16.0)
    ap.add_argument("--seed", type=int, default=9)
    args = ap.parse_args(argv)
    out = run_bench_autoscale(cap_rps=args.cap,
                              max_width=args.max_width,
                              ramp_s=args.ramp_s, seed=args.seed)
    print(json.dumps(out))
    return 0 if out.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
