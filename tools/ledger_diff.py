#!/usr/bin/env python3
"""Compare two checksum ledgers under the reference tolerances.

The ledger (``HPNN_LEDGER``, hpnn_tpu/obs/ledger.py) records the
abs-sum of every weight tensor once per numerics check.  This tool
diffs two such files row by row and reports whether the runs agree
under the reference library's cross-backend consistency criterion:
absolute sums equal to **1e-14 for vectors** and **1e-12 for weight
matrices** (reference ChangeLog:33-38 — the CUDA-port validation
note).  The tensor's recorded shape picks its tolerance: a tensor with
at least two dims of extent > 1 is a matrix.

Usage::

    python tools/ledger_diff.py A.jsonl B.jsonl [--json]
        [--vec-tol 1e-14] [--mat-tol 1e-12]

Rows are paired by their ``row`` index (both ledgers auto-increment
from 0), never by timestamp.  A row present in only one ledger, a NaN
checksum, a ``nan``/``inf`` census > 0, or a shape/tensor-set mismatch
all count as divergence.  Exit status: 0 clean, 1 divergent, 2 usage
or I/O error.  ``--json`` prints one machine-readable report document
instead of text (for CI, like ``pdif --json``).

Deliberately stdlib-only and self-contained (no hpnn_tpu import): it
must run on a login node or in CI against ledgers scp'd from anywhere.
"""

from __future__ import annotations

import json
import math
import sys

VEC_TOL = 1e-14
MAT_TOL = 1e-12


def tolerance_for(shape) -> float:
    """1e-14 for vector-like tensors, 1e-12 for real matrices — the
    same rule as hpnn_tpu/obs/probes.py (duplicated on purpose: this
    file must not import the package)."""
    dims = [int(d) for d in shape]
    if len([d for d in dims if d > 1]) >= 2:
        return MAT_TOL
    return VEC_TOL


def load_rounds(path: str) -> list[dict]:
    """The ``ledger.round`` rows of one ledger file, in file order."""
    rows = []
    with open(path) as fp:
        for ln in fp:
            ln = ln.strip()
            if not ln:
                continue
            rec = json.loads(ln)
            if rec.get("ev") == "ledger.round":
                rows.append(rec)
    return rows


def compare(rows_a: list[dict], rows_b: list[dict], *,
            vec_tol: float = VEC_TOL, mat_tol: float = MAT_TOL) -> dict:
    """Pairwise row comparison; returns the report dict."""
    divergent = []
    max_abs_diff = 0.0
    n = min(len(rows_a), len(rows_b))
    if len(rows_a) != len(rows_b):
        divergent.append({
            "row": None,
            "tensor": None,
            "reason": "row_count",
            "detail": f"{len(rows_a)} rows vs {len(rows_b)} rows",
        })
    for i in range(n):
        ra, rb = rows_a[i], rows_b[i]
        ca, cb = ra.get("checksums", {}), rb.get("checksums", {})
        if set(ca) != set(cb):
            divergent.append({
                "row": i, "tensor": None, "reason": "tensor_set",
                "detail": f"{sorted(ca)} vs {sorted(cb)}",
            })
            continue
        sa, sb = ra.get("shapes", {}), rb.get("shapes", {})
        for name in sorted(ca):
            if sa.get(name) != sb.get(name):
                divergent.append({
                    "row": i, "tensor": name, "reason": "shape",
                    "detail": f"{sa.get(name)} vs {sb.get(name)}",
                })
                continue
            va, vb = float(ca[name]), float(cb[name])
            if math.isnan(va) or math.isnan(vb):
                divergent.append({
                    "row": i, "tensor": name, "reason": "nan_checksum",
                    "a": va, "b": vb,
                })
                continue
            shape = sa.get(name) or sb.get(name) or []
            tol = mat_tol if tolerance_for(shape) == MAT_TOL else vec_tol
            diff = abs(va - vb)
            max_abs_diff = max(max_abs_diff, diff)
            if diff > tol:
                divergent.append({
                    "row": i, "tensor": name, "reason": "tolerance",
                    "a": va, "b": vb, "diff": diff, "tol": tol,
                })
        for census in ("nan", "inf"):
            bad = int(ra.get(census, 0)) + int(rb.get(census, 0))
            if bad:
                divergent.append({
                    "row": i, "tensor": None, "reason": f"{census}_census",
                    "detail": f"{bad} non-finite values recorded",
                })
    return {
        "rows_a": len(rows_a),
        "rows_b": len(rows_b),
        "compared": n,
        "vec_tol": vec_tol,
        "mat_tol": mat_tol,
        "max_abs_diff": max_abs_diff,
        "divergent": divergent,
        "clean": not divergent,
    }


def _render_text(report: dict, path_a: str, path_b: str) -> str:
    lines = [f"ledger_diff: {path_a} vs {path_b}",
             f"  rows: {report['rows_a']} vs {report['rows_b']} "
             f"({report['compared']} compared)",
             f"  tolerances: vec={report['vec_tol']:.0e} "
             f"mat={report['mat_tol']:.0e}",
             f"  max |a-b|: {report['max_abs_diff']:.3e}"]
    for d in report["divergent"]:
        where = f"row {d['row']}" if d.get("row") is not None else "global"
        name = d.get("tensor") or "-"
        if d["reason"] == "tolerance":
            lines.append(
                f"  DIVERGENT {where} {name}: |{d['a']!r} - {d['b']!r}| "
                f"= {d['diff']:.3e} > {d['tol']:.0e}")
        else:
            lines.append(
                f"  DIVERGENT {where} {name}: {d['reason']} "
                f"({d.get('detail', '')})".rstrip(" ()"))
    lines.append("  verdict: " + ("CLEAN" if report["clean"]
                                  else "DIVERGENT"))
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    if as_json:
        argv.remove("--json")
    vec_tol, mat_tol = VEC_TOL, MAT_TOL
    paths = []
    i = 0
    try:
        while i < len(argv):
            a = argv[i]
            if a == "--vec-tol":
                vec_tol = float(argv[i + 1])
                i += 2
            elif a == "--mat-tol":
                mat_tol = float(argv[i + 1])
                i += 2
            elif a.startswith("-"):
                raise IndexError(a)
            else:
                paths.append(a)
                i += 1
    except (IndexError, ValueError):
        sys.stderr.write("ledger_diff: bad arguments\n")
        return 2
    if len(paths) != 2:
        sys.stderr.write(
            "usage: ledger_diff.py A.jsonl B.jsonl [--json] "
            "[--vec-tol X] [--mat-tol Y]\n")
        return 2
    try:
        rows_a = load_rounds(paths[0])
        rows_b = load_rounds(paths[1])
    except (OSError, json.JSONDecodeError) as exc:
        sys.stderr.write(f"ledger_diff: cannot read ledger: {exc}\n")
        return 2
    report = compare(rows_a, rows_b, vec_tol=vec_tol, mat_tol=mat_tol)
    if as_json:
        report["a"] = paths[0]
        report["b"] = paths[1]
        sys.stdout.write(json.dumps(report) + "\n")
    else:
        sys.stdout.write(_render_text(report, paths[0], paths[1]))
    return 0 if report["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
