#!/usr/bin/env python3
"""Open/closed-loop HTTP traffic generator for the serving stack.

Where bench_serve.py measures the in-process Session under a closed
loop (a client's next request starts when its previous one returns —
arrival rate adapts to service rate, so saturation hides), this tool
drives the HTTP front end (serve/server.py) the way production
traffic does:

* **open loop** — requests fire at scheduled arrival times regardless
  of completions: a Poisson process (``--process poisson``) or an
  on/off burst process (``--process burst``, Poisson-within-bursts
  scaled so the long-run average equals ``--rate``).  A bounded
  worker pool issues them (concurrency caps at ``--workers``, making
  this formally a partly-open loop);
* **closed loop** — ``--closed``: N clients in sequential loops for
  the duration (the saturation probe).

Request mix: kernel names (``--kernels a,b``, fleet-mode servers
coalesce same-topology kernels transparently) and row counts
(``--rows 1,2,4``) are drawn per request.  ``--mix FRAC`` makes that
fraction of requests ``POST /ingest`` sample feeds (``--n-out`` sets
the target width), so ONE loadgen run drives the full
train-while-serve loop against an ``online_nn`` server
(docs/online.md).  429 and 503 responses are retried
honoring ``Retry-After`` (capped; ``--retries 0`` records the shed
instead), 504/timeouts are terminal per request; connection-level
failures (refused, reset, incomplete response) are a distinct
``lost`` class — the blast-radius metric the chaos drills in
``tools/chaos_drill.py`` gate on (docs/resilience.md).  The server's
``X-Request-Id`` is recorded per outcome, so any row in the JSONL
(``--out``) cross-correlates with the span sink via
``tools/obs_report.py --spans --req <id>``.  With spans armed
(``HPNN_SPANS``) each request additionally opens a client-side
``loadgen.request`` span and carries its trace context in
``X-Trace-Id`` / ``X-Parent-Span`` headers (obs/propagate.py), so the
server-side spans parent across the process boundary and the report
stitches one client → edge → replica tree per request
(docs/observability.md "Fleet telemetry").

Hostile traffic: ``--hostile slowloris|torn|fuzz`` turns the tool
into an attack-class generator against a conn-guarded server
(``HPNN_CONN_*``, docs/serving.md "Connection plane") — raw sockets,
no HTTP client library, because the whole point is to misbehave below
the request layer.  ``slowloris`` trickles header bytes forever (one
bogus header line per ``--interval``), ``torn`` declares a
Content-Length and hangs up mid-body, ``fuzz`` sprays garbage where a
request line should be.  Each mode reports its own outcome classes
(slowloris: ``killed``/``answered``/``survived``; torn: ``torn``;
fuzz: ``rejected``/``dropped``/``ignored``; all: ``refused``), plus a
``hung`` count of attacker threads that failed to finish — the
torn-network chaos drill (``tools/chaos_drill.py --drill torn``,
docs/resilience.md) asserts ``survived == 0`` and ``hung == 0``
while clean traffic keeps flowing.

Multi-tenant traffic: ``--tenants N`` spreads requests over N
synthetic tenants (``t000``..) drawn from a Zipf distribution
(``--zipf S``, heavier S = hotter head — real tenant populations are
head-heavy, and that skew is exactly what exercises a host's paging
LRU and per-tenant quotas, docs/tenancy.md).  Each request carries
its tenant in the ``X-Tenant`` header and its outcome row; the
summary adds a per-tenant census.

Outcome rows: ``{"t", "kernel", "rows", "status": ok|shed|timeout|
error|lost, "code", "latency_ms", "req_id", "attempts"}`` (plus
``tenant`` under ``--tenants``); the summary
(ONE JSON line on stdout, the bench.py convention) reports
p50/p99/p99.9 of *served* latencies, goodput vs offered load, and
shed/timeout rates.  :func:`run_bench_load` is the self-contained
bench.py fold-in: measure saturation closed-loop, then offer 2x that
open-loop against an SLO-armed, shedding server and report whether
goodput held and the windowed p99 stayed within the objective
(docs/observability.md "SLOs and load").

    JAX_PLATFORMS=cpu python tools/loadgen.py --bench
    python tools/loadgen.py --url http://127.0.0.1:8000 \
        --rate 200 --duration 10 --process burst --out run.jsonl
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import queue
import signal
import socket
import sys
import threading
import time
import urllib.parse

import numpy as np


def shield_sigpipe() -> None:
    """Put SIGPIPE back to Python's own default (ignored, so a write
    to a dead peer raises BrokenPipeError instead of killing us).  A
    load generator's target dying mid-write is an OUTCOME to record
    (``lost``), never a reason to die — but the embedded CLIs install
    SIG_DFL for shell-pipeline manners, and a host process that ran
    one of their mains would otherwise carry that disposition into
    the run.  No-op off the main thread."""
    try:
        signal.signal(signal.SIGPIPE, signal.SIG_IGN)
    except (ValueError, AttributeError):  # non-main thread / platform
        pass


# ------------------------------------------------------------ summaries


def percentile_ms(lat_s: list[float], q: float) -> float:
    """Percentile ``q`` (in percent) of latencies given in seconds,
    answered in milliseconds — the shared definition for bench_serve
    and loadgen (linear interpolation, numpy default)."""
    return round(float(np.percentile(np.asarray(lat_s) * 1e3, q)), 3)


def latency_summary(lat_s: list[float]) -> dict:
    """p50/p99/p99.9/mean/max (ms) of latencies in seconds; None-
    filled when there were no served requests."""
    if not lat_s:
        return {"p50": None, "p99": None, "p999": None,
                "mean": None, "max": None}
    return {
        "p50": percentile_ms(lat_s, 50),
        "p99": percentile_ms(lat_s, 99),
        "p999": percentile_ms(lat_s, 99.9),
        "mean": round(float(np.mean(lat_s)) * 1e3, 3),
        "max": round(float(np.max(lat_s)) * 1e3, 3),
    }


def summarize(records: list[dict], duration_s: float, *,
              offered_rps: float | None = None) -> dict:
    """Aggregate one run's outcome rows: counts per status, goodput
    (served requests per second) vs offered load, shed/timeout rates,
    and the latency summary of *served* requests only."""
    n = len(records)
    counts = {s: 0 for s in ("ok", "shed", "timeout", "error", "lost")}
    ops: dict[str, int] = {}
    for r in records:
        counts[r["status"]] = counts.get(r["status"], 0) + 1
        op = r.get("op", "infer")
        ops[op] = ops.get(op, 0) + 1
    ok_lat_s = [r["latency_ms"] / 1e3 for r in records
                if r["status"] == "ok"]
    goodput = counts["ok"] / duration_s if duration_s else 0.0
    if offered_rps is None:
        offered_rps = n / duration_s if duration_s else 0.0
    by_tenant: dict[str, dict] = {}
    tenant_lat_s: dict[str, list[float]] = {}
    for r in records:
        t = r.get("tenant")
        if t is None:
            continue
        d = by_tenant.setdefault(
            t, {"requests": 0, "ok": 0, "shed": 0})
        d["requests"] += 1
        if r["status"] in d:
            d[r["status"]] += 1
        if r["status"] == "ok":
            tenant_lat_s.setdefault(t, []).append(
                r["latency_ms"] / 1e3)
    for t, d in by_tenant.items():
        # per-tenant served-latency tail so drill assertions can
        # check victim-vs-offender p99 without scraping /metrics
        lat = tenant_lat_s.get(t)
        d["p50_ms"] = percentile_ms(lat, 50) if lat else None
        d["p99_ms"] = percentile_ms(lat, 99) if lat else None
    out = {
        "requests": n,
        "duration_s": round(duration_s, 3),
        "offered_rps": round(offered_rps, 1),
        "ok": counts["ok"],
        "shed": counts["shed"],
        "timeout": counts["timeout"],
        "error": counts["error"],
        "lost": counts["lost"],
        "goodput_rps": round(goodput, 1),
        "goodput_vs_offered": (round(goodput / offered_rps, 4)
                               if offered_rps else None),
        "shed_rate": round(counts["shed"] / n, 4) if n else 0.0,
        "timeout_rate": round(counts["timeout"] / n, 4) if n else 0.0,
        "lost_rate": round(counts["lost"] / n, 4) if n else 0.0,
        "ops": ops,
        "latency_ms": latency_summary(ok_lat_s),
    }
    if by_tenant:
        out["by_tenant"] = dict(sorted(by_tenant.items()))
    return out


def write_jsonl(path: str, records: list[dict], summary: dict) -> None:
    """One row per request outcome, then the summary as a final
    ``{"summary": ...}`` row."""
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
        f.write(json.dumps({"summary": summary}) + "\n")


# ------------------------------------------------------------ arrivals


def poisson_arrivals(rate_rps: float, duration_s: float,
                     rng: np.random.RandomState) -> list[float]:
    """Arrival offsets (seconds) of a homogeneous Poisson process."""
    if rate_rps <= 0:
        return []
    out, t = [], 0.0
    while True:
        t += float(rng.exponential(1.0 / rate_rps))
        if t >= duration_s:
            return out
        out.append(t)


def burst_arrivals(rate_rps: float, duration_s: float,
                   rng: np.random.RandomState, *,
                   on_s: float = 0.5, off_s: float = 0.5) -> list[float]:
    """On/off burst process: Poisson arrivals during ``on_s`` phases,
    silence during ``off_s`` phases, with the on-phase rate scaled so
    the long-run average still equals ``rate_rps``."""
    if rate_rps <= 0:
        return []
    burst_rate = rate_rps * (on_s + off_s) / on_s
    out, t = [], 0.0
    while t < duration_s:
        end_on, tt = min(t + on_s, duration_s), t
        while True:
            tt += float(rng.exponential(1.0 / burst_rate))
            if tt >= end_on:
                break
            out.append(tt)
        t += on_s + off_s
    return out


def make_arrivals(process: str, rate_rps: float, duration_s: float,
                  rng: np.random.RandomState) -> list[float]:
    if process == "poisson":
        return poisson_arrivals(rate_rps, duration_s, rng)
    if process == "burst":
        return burst_arrivals(rate_rps, duration_s, rng)
    raise ValueError(f"unknown arrival process {process!r}")


# ------------------------------------------------------------ tenants


def tenant_names(n: int) -> tuple[str, ...]:
    """``t000``.. — the synthetic tenant namespace of ``--tenants``."""
    return tuple(f"t{i:03d}" for i in range(int(n)))


def zipf_cdf(n: int, s: float) -> np.ndarray:
    """Cumulative Zipf(s) weights over ranks 0..n-1: item ``i`` draws
    with probability proportional to ``1/(i+1)^s``.  A draw is
    ``searchsorted(cdf, uniform())`` — O(log n) per request, so a 10k
    kernel namespace costs the generator nothing."""
    if n < 1:
        raise ValueError("zipf_cdf needs n >= 1")
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64),
                       float(s))
    return np.cumsum(w / w.sum())


def zipf_pick(cdf: np.ndarray, rng: np.random.RandomState) -> int:
    return int(np.searchsorted(cdf, rng.uniform(), side="right"))


# ------------------------------------------------------------ client


# Lazy handle on the obs propagation modules: None = not probed yet,
# False = spans disarmed (or package unavailable) — probed once, so
# the common un-instrumented run never pays a per-request check.
_TRACE_MODS = None


def _trace_mods():
    """(propagate, spans) when ``HPNN_SPANS`` is armed, else None."""
    global _TRACE_MODS
    if _TRACE_MODS is None:
        _TRACE_MODS = False
        try:
            from hpnn_tpu.obs import propagate, spans
        except ImportError:
            root = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
            if root not in sys.path:
                sys.path.insert(0, root)
            try:
                from hpnn_tpu.obs import propagate, spans
            except ImportError:
                return None
        if spans.enabled():
            _TRACE_MODS = (propagate, spans)
    return _TRACE_MODS or None


class _Client:
    """One keep-alive HTTP connection with reconnect-on-disconnect
    and the per-request retry policy (429 + ``Retry-After``)."""

    def __init__(self, url: str, timeout_s: float):
        u = urllib.parse.urlparse(
            url if "//" in url else "http://" + url)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.timeout_s = float(timeout_s)
        self._conn: http.client.HTTPConnection | None = None

    def close(self):
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def _post(self, path: str, body: bytes, headers: dict | None = None):
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        # one silent reconnect: a keep-alive peer may have gone away
        for attempt in (0, 1):
            try:
                if self._conn is None:
                    self._conn = http.client.HTTPConnection(
                        self.host, self.port,
                        timeout=self.timeout_s + 1.0)
                    self._conn.connect()
                    # measurement hygiene: without TCP_NODELAY a
                    # Nagle/delayed-ACK stall adds ~40 ms to loopback
                    # latencies and caps the generator's offered rate
                    self._conn.sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._conn.request(
                    "POST", path, body=body, headers=hdrs)
                resp = self._conn.getresponse()
                data = resp.read()
                return resp.status, dict(resp.getheaders()), data
            except socket.timeout:
                self.close()
                raise
            except (http.client.HTTPException, OSError):
                self.close()
                if attempt:
                    raise
        raise OSError("unreachable")

    def request(self, kernel: str, rows: int, body: bytes, *,
                max_retries: int = 2, retry_cap_s: float = 1.0,
                path: str = "/v1/infer", op: str = "infer",
                tenant: str | None = None) -> dict:
        """Issue one logical request (with 429/503 retries); returns
        its outcome row (latency spans all attempts, sleeps included).
        ``path``/``op`` route the mixed-traffic mode: infer requests
        hit ``/v1/infer``, ingest feeds hit ``/ingest``.

        Outcome classes: 429 exhausted -> ``shed`` (the server chose
        to refuse), 503 exhausted -> ``shed`` too (not-ready/draining
        is admission control, not failure), 504/timeout ->
        ``timeout``, connection refused/reset/incomplete response ->
        ``lost`` (nothing answered — the blast-radius class the chaos
        drills count), other codes -> ``error``."""
        attempts, code, req_id, status = 0, None, None, "error"
        span, hdrs, trace = None, None, None
        mods = _trace_mods()
        if mods is not None:
            propagate, spans = mods
            span = spans.start("loadgen.request", kernel=kernel,
                               rows=rows, op=op)
            ctx = propagate.ctx_from(span)
            if ctx is not None:
                trace = ctx.trace
                hdrs = propagate.inject({}, ctx)
        if tenant is not None:
            hdrs = dict(hdrs or {})
            hdrs["X-Tenant"] = tenant
        t_start = time.perf_counter()
        while True:
            attempts += 1
            try:
                code, headers, _data = self._post(path, body,
                                                  headers=hdrs)
            except socket.timeout:
                status, code = "timeout", None
                break
            except (http.client.HTTPException, OSError):
                # connection-level loss: refused (restart gap), reset
                # (kill -9 mid-flight), or a torn response — distinct
                # from shed (429/503) and expired (504)
                status, code = "lost", None
                break
            req_id = headers.get("X-Request-Id") or req_id
            if code == 200:
                status = "ok"
                break
            if code in (429, 503):
                if attempts > max_retries:
                    status = "shed"
                    break
                retry_s = 1.0
                try:
                    retry_s = float(headers.get("Retry-After", "1"))
                except ValueError:
                    pass
                time.sleep(min(max(retry_s, 0.0), retry_cap_s))
                continue
            status = "timeout" if code == 504 else "error"
            break
        if span is not None:
            done = {"status": status}
            if req_id is not None:
                done["req_id"] = req_id
            mods[1].finish(span, **done)
        rec = {
            "kernel": kernel,
            "rows": rows,
            "op": op,
            "status": status,
            "code": code,
            "latency_ms": round(
                (time.perf_counter() - t_start) * 1e3, 3),
            "req_id": req_id,
            "attempts": attempts,
        }
        if tenant is not None:
            rec["tenant"] = tenant
        if trace is not None:
            rec["trace"] = trace
        return rec


def _request_bodies(kernels, rows_choices, n_in: int,
                    timeout_s: float) -> dict:
    """Pre-serialized request bodies per (kernel, rows): payload
    values are irrelevant to load, so encode each combination once."""
    bodies = {}
    for k in kernels:
        for r in rows_choices:
            inputs = [[0.1] * int(n_in)] * int(r)
            bodies[(k, r)] = json.dumps(
                {"kernel": k, "inputs": inputs,
                 "timeout_s": timeout_s}).encode()
    return bodies


def _ingest_bodies(kernels, rows_choices, n_in: int, n_out: int,
                   seed: int = 0) -> dict:
    """Pre-serialized ``POST /ingest`` bodies per (kernel, rows) for
    the ``--mix`` mode.  Sample values are drawn once per combination
    (deterministic per seed): the online buffer just needs plausible
    finite rows, and re-encoding per request would bottleneck the
    generator, not the server."""
    rng = np.random.RandomState(seed)
    bodies = {}
    for k in kernels:
        for r in rows_choices:
            X = rng.uniform(0.0, 1.0, size=(int(r), int(n_in)))
            T = rng.uniform(0.0, 1.0, size=(int(r), int(n_out)))
            bodies[(k, r)] = json.dumps(
                {"kernel": k, "inputs": X.round(4).tolist(),
                 "targets": T.round(4).tolist()}).encode()
    return bodies


# ------------------------------------------------------------ hostile


HOSTILE_MODES = ("slowloris", "torn", "fuzz")


def _hostile_target(url: str) -> tuple[str, int]:
    u = urllib.parse.urlparse(url if "//" in url else "http://" + url)
    return u.hostname or "127.0.0.1", u.port or 80


def _attack_slowloris(host: str, port: int, *, duration_s: float,
                      interval_s: float,
                      stop: "threading.Event | None") -> str:
    """Trickle header bytes and never finish the request.  Against an
    unguarded server this pins a handler thread for ``duration_s``;
    against ``HPNN_CONN_MIN_BPS`` / ``HPNN_CONN_HDR_MS`` the server
    kills us first.  The recv timeout doubles as the trickle pacing:
    per-recv socket timeouts never fire for a client that always sends
    one more byte in time — which is exactly the defence bypass the
    byte-rate floor exists to close."""
    try:
        sock = socket.create_connection((host, port), timeout=2.0)
    except OSError:
        return "refused"
    try:
        sock.sendall(b"POST /v1/infer HTTP/1.1\r\nHost: lg\r\n")
        deadline = time.perf_counter() + duration_s
        i = 0
        while time.perf_counter() < deadline:
            if stop is not None and stop.is_set():
                break
            sock.sendall(f"X-Slow-{i}: y\r\n".encode())
            i += 1
            sock.settimeout(max(0.05, interval_s))
            try:
                data = sock.recv(256)
            except socket.timeout:
                continue
            # the server spoke first: an empty read is a guard/deadline
            # kill, bytes are an early error response — either way the
            # attack failed to pin the thread
            return "killed" if not data else "answered"
        return "survived"
    except (BrokenPipeError, ConnectionResetError, OSError):
        return "killed"
    finally:
        try:
            sock.close()
        except OSError:  # already torn down
            pass


def _attack_torn(host: str, port: int, *, body_claim: int = 400,
                 body_sent: int = 24) -> str:
    """Declare a Content-Length, send a fraction of it, hang up.  The
    server's body read comes up short (close reason ``torn_body``)."""
    try:
        sock = socket.create_connection((host, port), timeout=2.0)
    except OSError:
        return "refused"
    try:
        hdr = (b"POST /v1/infer HTTP/1.1\r\nHost: lg\r\n"
               b"Content-Type: application/json\r\n"
               b"Content-Length: " + str(int(body_claim)).encode()
               + b"\r\n\r\n")
        sock.sendall(hdr + b'{"kernel": "'
                     + b"x" * max(0, int(body_sent) - 13) + b'"')
        time.sleep(0.05)  # let the body read start before the tear
        return "torn"
    except (BrokenPipeError, ConnectionResetError, OSError):
        return "torn"
    finally:
        try:
            sock.close()
        except OSError:
            pass


def _attack_fuzz(host: str, port: int, *, seed: int = 0) -> str:
    """Spray bytes that are not HTTP where a request line should be;
    a healthy front end answers 400 (``rejected``) or drops the
    connection (``dropped``) — never hangs (``ignored``)."""
    rng = np.random.RandomState(seed)
    try:
        sock = socket.create_connection((host, port), timeout=2.0)
    except OSError:
        return "refused"
    try:
        junk = bytes(rng.randint(1, 255, size=64, dtype=np.uint8))
        sock.sendall(junk + b"\r\n\r\n")
        sock.settimeout(2.0)
        try:
            data = sock.recv(512)
        except socket.timeout:
            return "ignored"
        if not data:
            return "dropped"
        return "rejected" if data.startswith(b"HTTP/") else "dropped"
    except (BrokenPipeError, ConnectionResetError, OSError):
        return "dropped"
    finally:
        try:
            sock.close()
        except OSError:
            pass


def run_hostile(url: str, *, mode: str, n_conns: int = 8,
                duration_s: float = 3.0, interval_s: float = 0.4,
                seed: int = 0,
                stop: "threading.Event | None" = None) -> dict:
    """Launch ``n_conns`` concurrent attackers of one mode and report
    the per-mode outcome census.  Every attacker thread is joined (with
    a margin past ``duration_s``); stragglers count as ``hung`` — the
    drill's no-hung-threads witness."""
    if mode not in HOSTILE_MODES:
        raise ValueError(f"unknown hostile mode {mode!r}")
    shield_sigpipe()
    host, port = _hostile_target(url)
    outcomes: list[str] = []
    lock = threading.Lock()

    def attacker(ci: int):
        if mode == "slowloris":
            out = _attack_slowloris(host, port, duration_s=duration_s,
                                    interval_s=interval_s, stop=stop)
        elif mode == "torn":
            out = _attack_torn(host, port)
        else:
            out = _attack_fuzz(host, port, seed=seed + ci)
        with lock:
            outcomes.append(out)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=attacker, args=(ci,),
                                daemon=True)
               for ci in range(max(1, int(n_conns)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration_s + 5.0)
    hung = sum(1 for t in threads if t.is_alive())
    census: dict[str, int] = {}
    with lock:
        for o in outcomes:
            census[o] = census.get(o, 0) + 1
    return {
        "mode": mode,
        "conns": int(n_conns),
        "outcomes": dict(sorted(census.items())),
        "hung": hung,
        "duration_s": round(time.perf_counter() - t0, 3),
    }


# ------------------------------------------------------------ runners


def run_open_loop(url: str, *, rate_rps: float, duration_s: float,
                  process: str = "poisson",
                  kernels=("default",), rows_choices=(1,),
                  n_in: int = 8, timeout_s: float = 2.0,
                  max_retries: int = 2, retry_cap_s: float = 1.0,
                  n_workers: int = 16, seed: int = 0,
                  ingest_frac: float = 0.0, n_out: int = 2,
                  tenants: int = 0, zipf_s: float = 1.1,
                  out_path: str | None = None,
                  stop: "threading.Event | None" = None,
                  on_record=None) -> dict:
    """Offered-load run: arrivals are scheduled up front and fired on
    time by a worker pool whether or not earlier requests finished.
    ``ingest_frac`` of the arrivals become ``POST /ingest`` sample
    feeds (the ``--mix`` mode).  ``stop`` (an Event) ends the run
    early — the chaos drills schedule a generous duration and stop
    once recovery is confirmed; ``on_record`` observes each outcome
    row as it lands.  Returns the summary dict (and writes the JSONL
    to ``out_path``)."""
    shield_sigpipe()
    rng = np.random.RandomState(seed)
    arrivals = make_arrivals(process, rate_rps, duration_s, rng)
    bodies = _request_bodies(kernels, rows_choices, n_in, timeout_s)
    feed_bodies = (_ingest_bodies(kernels, rows_choices, n_in, n_out,
                                  seed) if ingest_frac > 0 else {})
    tnames = tenant_names(tenants) if tenants > 0 else ()
    tcdf = zipf_cdf(len(tnames), zipf_s) if tnames else None
    specs: "queue.Queue[tuple]" = queue.Queue()
    for t in arrivals:
        k = kernels[int(rng.randint(len(kernels)))]
        r = int(rows_choices[int(rng.randint(len(rows_choices)))])
        op = ("ingest" if ingest_frac > 0
              and rng.uniform() < ingest_frac else "infer")
        tn = tnames[zipf_pick(tcdf, rng)] if tnames else None
        specs.put((t, k, r, op, tn))
    records: list[dict] = []
    rec_lock = threading.Lock()
    t0 = time.perf_counter()

    def worker():
        client = _Client(url, timeout_s)
        try:
            while True:
                if stop is not None and stop.is_set():
                    return
                try:
                    t_due, k, r, op, tn = specs.get_nowait()
                except queue.Empty:
                    return
                delay = t0 + t_due - time.perf_counter()
                if delay > 0:
                    if stop is not None:
                        if stop.wait(delay):
                            return
                    else:
                        time.sleep(delay)
                if op == "ingest":
                    rec = client.request(
                        k, r, feed_bodies[(k, r)],
                        max_retries=max_retries,
                        retry_cap_s=retry_cap_s,
                        path="/ingest", op="ingest", tenant=tn)
                else:
                    rec = client.request(k, r, bodies[(k, r)],
                                         max_retries=max_retries,
                                         retry_cap_s=retry_cap_s,
                                         tenant=tn)
                rec["t"] = round(t_due, 6)
                with rec_lock:
                    records.append(rec)
                if on_record is not None:
                    on_record(rec)
        finally:
            client.close()

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, int(n_workers)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    if stop is None or not stop.is_set():
        wall_s = max(wall_s, duration_s)
    summary = summarize(records, wall_s, offered_rps=rate_rps)
    summary["process"] = process
    if out_path:
        write_jsonl(out_path, records, summary)
    return summary


def run_closed_loop(url: str, *, n_clients: int = 4,
                    duration_s: float = 2.0,
                    kernels=("default",), rows_choices=(1,),
                    n_in: int = 8, timeout_s: float = 2.0,
                    max_retries: int = 0, retry_cap_s: float = 1.0,
                    seed: int = 0, ingest_frac: float = 0.0,
                    n_out: int = 2,
                    tenants: int = 0, zipf_s: float = 1.1,
                    out_path: str | None = None) -> dict:
    """Saturation probe: N clients in sequential request loops for the
    duration.  Offered load equals achieved load by construction.
    ``ingest_frac`` of the requests become ``POST /ingest`` feeds."""
    shield_sigpipe()
    records: list[dict] = []
    rec_lock = threading.Lock()
    tnames = tenant_names(tenants) if tenants > 0 else ()
    tcdf = zipf_cdf(len(tnames), zipf_s) if tnames else None
    t0 = time.perf_counter()

    def client_loop(ci: int):
        rng = np.random.RandomState(seed + 1000 + ci)
        client = _Client(url, timeout_s)
        bodies = _request_bodies(kernels, rows_choices, n_in,
                                 timeout_s)
        feed_bodies = (_ingest_bodies(kernels, rows_choices, n_in,
                                      n_out, seed + ci)
                       if ingest_frac > 0 else {})
        try:
            while time.perf_counter() - t0 < duration_s:
                k = kernels[int(rng.randint(len(kernels)))]
                r = int(rows_choices[int(
                    rng.randint(len(rows_choices)))])
                tn = tnames[zipf_pick(tcdf, rng)] if tnames else None
                if ingest_frac > 0 and rng.uniform() < ingest_frac:
                    rec = client.request(
                        k, r, feed_bodies[(k, r)],
                        max_retries=max_retries,
                        retry_cap_s=retry_cap_s,
                        path="/ingest", op="ingest", tenant=tn)
                else:
                    rec = client.request(k, r, bodies[(k, r)],
                                         max_retries=max_retries,
                                         retry_cap_s=retry_cap_s,
                                         tenant=tn)
                rec["t"] = round(time.perf_counter() - t0, 6)
                with rec_lock:
                    records.append(rec)
        finally:
            client.close()

    threads = [threading.Thread(target=client_loop, args=(ci,),
                                daemon=True)
               for ci in range(max(1, int(n_clients)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    summary = summarize(records, wall_s)
    summary["n_clients"] = int(n_clients)
    if out_path:
        write_jsonl(out_path, records, summary)
    return summary


# ------------------------------------------------------------ bench


def run_bench_load(*, slo_ms: float = 50.0, seed: int = 7,
                   saturation_s: float = 1.5,
                   load_s: float = 3.0) -> dict:
    """The bench.py fold-in: stand up an in-process SLO-armed server
    over a tiny kernel, measure saturation closed-loop, then offer 2x
    that open-loop and report whether shedding held goodput near the
    plateau and the server-side windowed p99 of accepted requests
    within the objective."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from hpnn_tpu import obs, serve
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.serve.server import make_server

    env_keys = (obs.slo.ENV_KNOB, obs.slo.ENV_WINDOW,
                obs.slo.ENV_TARGET)
    prev_env = {k: os.environ.get(k) for k in env_keys}
    obs.slo.configure(slo_ms, window_s=max(30.0, load_s * 4))
    session = None
    server = None
    try:
        k, _ = kernel_mod.generate(seed, 8, [5], 2)
        session = serve.Session(
            max_batch=16, n_buckets=3, max_wait_ms=1.0, max_depth=64,
            shed_age_ms=max(1.0, slo_ms / 4.0))
        session.register_kernel("bench", k)
        server = make_server(session, port=0)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        common = dict(kernels=("bench",), rows_choices=(1, 2, 4),
                      n_in=8, timeout_s=2.0, max_retries=0)
        # discarded warmup: the first requests pay eager-path tracing
        # and would depress the saturation estimate
        run_closed_loop(url, n_clients=2, duration_s=0.3, seed=seed,
                        **common)
        sat = run_closed_loop(url, n_clients=8,
                              duration_s=saturation_s, seed=seed,
                              **common)
        sat_rps = sat["goodput_rps"]
        offered = max(10.0, 2.0 * sat_rps)
        load = run_open_loop(url, rate_rps=offered,
                             duration_s=load_s, process="poisson",
                             n_workers=16, seed=seed + 1, **common)
        slo_doc = obs.slo.health_doc()
        vs_sat = (load["goodput_rps"] / sat_rps if sat_rps else None)
        return {
            "metric": "serve_load",
            "slo_ms": float(slo_ms),
            "saturation_rps": sat_rps,
            "offered_rps": load["offered_rps"],
            "goodput_rps": load["goodput_rps"],
            "goodput_vs_saturation": (None if vs_sat is None
                                      else round(vs_sat, 4)),
            "p99_under_load_ms": slo_doc.get("p99_ms"),
            "slo_attainment": slo_doc.get("attainment"),
            "saturation": sat,
            "load": load,
            "slo": slo_doc,
        }
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if session is not None:
            session.close()
        for key, val in prev_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        obs.slo._reset_for_tests()


def vm_rss_mb() -> float | None:
    """Resident-set size of THIS process in MiB (Linux /proc; None
    elsewhere) — the bounded-memory witness of the tenant bench."""
    try:
        with open("/proc/self/status", encoding="ascii") as fp:
            for line in fp:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except (OSError, ValueError, IndexError):
        pass
    return None


def run_bench_tenant(*, n_kernels: int = 10_000, resident: int = 256,
                     n_tenants: int = 8, zipf_s: float = 1.2,
                     traffic_s: float = 2.0, n_threads: int = 4,
                     hot_rate_rps: float = 50.0,
                     seed: int = 11) -> dict:
    """The multi-tenant hosting fold-in (docs/tenancy.md): one
    in-process :class:`~hpnn_tpu.tenant.TenantSession` hosting
    ``n_kernels`` kernels across ``n_tenants`` tenants with a
    ``resident``-kernel LRU paging cap, driven by Zipf(``zipf_s``)
    traffic — the head-heavy mix that makes paging and quotas earn
    their keep.  Reports registration throughput at 10k scale, RSS
    growth under the cap (the bounded-memory claim), measured cold-hit
    paging latency (p50/p99), goodput, and the quota-shed census (the
    hottest tenant runs with a ``hot_rate_rps`` budget so admission
    control demonstrably bites)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import tempfile

    from hpnn_tpu.models.kernel import Kernel
    from hpnn_tpu.tenant import TenantSession, TenantSpec

    tnames = tenant_names(n_tenants)
    specs = {tnames[0]: TenantSpec(tnames[0], "silver",
                                   rate_rps=float(hot_rate_rps))}
    rng = np.random.RandomState(seed)
    n_in, hid, n_out = 6, 4, 2
    session = None
    page_dir = tempfile.mkdtemp(prefix="hpnn_tenant_bench_")
    rss0 = vm_rss_mb()
    try:
        session = TenantSession(
            mode="parity", fleet=True, max_wait_ms=0.5,
            shards=16, resident_max=int(resident),
            page_dir=page_dir, tenants=specs, page_warmup=False)
        by_tenant: dict[str, list[str]] = {t: [] for t in tnames}
        t_reg = time.perf_counter()
        for j in range(int(n_kernels)):
            k = Kernel((
                rng.standard_normal((hid, n_in)),
                rng.standard_normal((n_out, hid))))
            tn = tnames[j % n_tenants]
            kn = f"k{j}"
            session.register_for(tn, kn, k, warmup=False)
            by_tenant[tn].append(kn)
        register_s = time.perf_counter() - t_reg
        kcdf = {t: zipf_cdf(len(ks), zipf_s)
                for t, ks in by_tenant.items()}
        tcdf = zipf_cdf(n_tenants, zipf_s)
        x = rng.standard_normal((2, n_in))
        # discarded warmup: the very first dispatch pays the eager-path
        # tracing stall and would otherwise dominate the measured p99
        session.infer_for(tnames[-1], by_tenant[tnames[-1]][0], x)
        counts_lock = threading.Lock()
        counts = {"ok": 0, "shed": 0, "error": 0}
        shed_by_tenant = {t: 0 for t in tnames}
        errors: list[str] = []
        lat_s: list[float] = []
        t0 = time.perf_counter()

        def tenant_loop(ti: int):
            from hpnn_tpu.serve.batcher import QueueFull
            trng = np.random.RandomState(seed + 100 + ti)
            while time.perf_counter() - t0 < traffic_s:
                tn = tnames[zipf_pick(tcdf, trng)]
                kn = by_tenant[tn][zipf_pick(kcdf[tn], trng)]
                t_req = time.perf_counter()
                try:
                    session.infer_for(tn, kn, x, timeout_s=2.0)
                except QueueFull:  # Shed subclass: quota or queue
                    with counts_lock:
                        counts["shed"] += 1
                        shed_by_tenant[tn] += 1
                    continue
                except Exception as exc:
                    with counts_lock:
                        counts["error"] += 1
                        errors.append(repr(exc))
                    continue
                dt = time.perf_counter() - t_req
                with counts_lock:
                    counts["ok"] += 1
                    lat_s.append(dt)

        threads = [threading.Thread(target=tenant_loop, args=(ti,),
                                    daemon=True)
                   for ti in range(max(1, int(n_threads)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.perf_counter() - t0
        rss1 = vm_rss_mb()
        pager_doc = session.pager.health_doc()
        quota_doc = session.quota.health_doc()
        cap_ok = (pager_doc["resident"] <= int(resident))
        rss_growth = (round(rss1 - rss0, 1)
                      if rss0 is not None and rss1 is not None
                      else None)
        return {
            "metric": "tenant_host",
            "n_kernels": int(n_kernels),
            "n_tenants": int(n_tenants),
            "zipf_s": float(zipf_s),
            "resident_cap": int(resident),
            "register_s": round(register_s, 3),
            "register_krps": round(n_kernels / register_s / 1e3, 2),
            "rss_before_mb": rss0,
            "rss_after_mb": rss1,
            "rss_growth_mb": rss_growth,
            "resident": pager_doc["resident"],
            "paged": pager_doc["paged"],
            "resident_cap_ok": bool(cap_ok),
            "page_ins": pager_doc["page_ins"],
            "page_outs": pager_doc["page_outs"],
            "cold_p50_ms": pager_doc["cold_p50_ms"],
            "cold_p99_ms": pager_doc["cold_p99_ms"],
            "requests": sum(counts.values()),
            "ok": counts["ok"],
            "shed": counts["shed"],
            "errors": counts["error"],
            "error_sample": errors[:4],
            "goodput_rps": round(counts["ok"] / wall_s, 1)
                           if wall_s else 0.0,
            "p99_ms": (percentile_ms(lat_s, 99) if lat_s else None),
            "hot_tenant": tnames[0],
            "hot_rate_rps": float(hot_rate_rps),
            "quota_shed": shed_by_tenant[tnames[0]],
            "shed_by_tenant": {t: n for t, n in
                               sorted(shed_by_tenant.items()) if n},
            "tenants": quota_doc,
        }
    finally:
        if session is not None:
            session.close()
        import shutil

        shutil.rmtree(page_dir, ignore_errors=True)


# ------------------------------------------------------------ main


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open/closed-loop load generator for the HTTP "
                    "serving front end")
    ap.add_argument("--url", help="server base url "
                                  "(e.g. http://127.0.0.1:8000)")
    ap.add_argument("--bench", action="store_true",
                    help="self-contained in-process bench "
                         "(saturation probe + 2x open-loop)")
    ap.add_argument("--rate", type=float, default=50.0,
                    help="offered load, requests/s (open loop)")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--process", choices=("poisson", "burst"),
                    default="poisson")
    ap.add_argument("--closed", action="store_true",
                    help="closed loop (saturation probe) instead of "
                         "offered load")
    ap.add_argument("--clients", type=int, default=4,
                    help="closed-loop client count")
    ap.add_argument("--workers", type=int, default=16,
                    help="open-loop worker pool size")
    ap.add_argument("--kernels", default="default",
                    help="comma-separated kernel names")
    ap.add_argument("--rows", default="1",
                    help="comma-separated row counts to mix")
    ap.add_argument("--n-in", type=int, default=8,
                    help="input width of the target kernels")
    ap.add_argument("--mix", type=float, default=0.0, metavar="FRAC",
                    help="fraction of requests sent as POST /ingest "
                         "sample feeds (train-while-serve traffic; "
                         "needs an online_nn server)")
    ap.add_argument("--n-out", type=int, default=2,
                    help="target width of --mix ingest samples")
    ap.add_argument("--tenants", type=int, default=0, metavar="N",
                    help="spread requests over N synthetic tenants "
                         "(t000..) via the X-Tenant header")
    ap.add_argument("--zipf", type=float, default=1.1, metavar="S",
                    help="Zipf skew of the tenant draw (--tenants)")
    ap.add_argument("--hostile", choices=HOSTILE_MODES,
                    help="attack-class mode: raw-socket slowloris / "
                         "torn-body / fuzz clients instead of clean "
                         "traffic (docs/resilience.md)")
    ap.add_argument("--conns", type=int, default=8,
                    help="concurrent attacker connections (--hostile)")
    ap.add_argument("--interval", type=float, default=0.4,
                    help="slowloris trickle interval, seconds")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-request timeout_s")
    ap.add_argument("--retries", type=int, default=2,
                    help="max 429/503 retries per request (0: "
                         "record the shed)")
    ap.add_argument("--retry-cap", type=float, default=1.0,
                    help="cap on honored Retry-After sleeps")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", help="write per-request JSONL here")
    args = ap.parse_args(argv)

    if args.bench:
        out = run_bench_load(seed=args.seed or 7)
        print(json.dumps(out))
        return 0
    if not args.url:
        ap.error("--url is required (or use --bench)")
    if args.hostile:
        summary = run_hostile(args.url, mode=args.hostile,
                              n_conns=args.conns,
                              duration_s=args.duration,
                              interval_s=args.interval,
                              seed=args.seed)
        print(json.dumps(summary))
        return 0 if not summary["hung"] else 1
    kernels = tuple(s for s in args.kernels.split(",") if s)
    rows = tuple(int(s) for s in args.rows.split(",") if s)
    if not 0.0 <= args.mix <= 1.0:
        ap.error("--mix must be in [0, 1]")
    if args.tenants < 0:
        ap.error("--tenants must be >= 0")
    common = dict(kernels=kernels, rows_choices=rows,
                  n_in=args.n_in, timeout_s=args.timeout,
                  max_retries=args.retries,
                  retry_cap_s=args.retry_cap, seed=args.seed,
                  ingest_frac=args.mix, n_out=args.n_out,
                  tenants=args.tenants, zipf_s=args.zipf,
                  out_path=args.out)
    if args.closed:
        summary = run_closed_loop(args.url, n_clients=args.clients,
                                  duration_s=args.duration, **common)
    else:
        summary = run_open_loop(args.url, rate_rps=args.rate,
                                duration_s=args.duration,
                                process=args.process,
                                n_workers=args.workers, **common)
    print(json.dumps(summary))
    return 0 if summary["requests"] else 1


if __name__ == "__main__":
    sys.exit(main())
