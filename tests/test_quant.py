"""Multi-round-per-dispatch + low-precision acceptance tests.

ISSUE 11 acceptance criteria, proved here:

* the K-round fleet scan (``make_fleet_multi_round_fn`` /
  ``train_fleet_multi``) is **bitwise equal** on the f64 CPU backend
  to K sequential ``train_fleet`` dispatches with the same per-round
  seeds, and the paired parity ledgers diff clean under the reference
  1e-14/1e-12 tolerances;
* the fleet double-buffered DMA epoch extends to the stacked bank
  (``train_fleet_epoch_dbuf_banked``) with bitwise interpret-mode
  parity against N per-member pipelines;
* the bf16/int8 serve policies stay inside the tolerances
  docs/performance.md documents, the int8 error bound is monotone in
  bit width, and a bf16 training ledger needs *widened*
  ``ledger_diff`` tolerances (the default bitwise tolerances must
  reject it — low precision is visible, never silent);
* the promotion gate rejects a quantization-degraded candidate on
  margin like any other regression — precision is not exempt;
* the new record shapes pass ``check_obs_catalog --quant``.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from hpnn_tpu import obs, online, serve
from hpnn_tpu.models import ann, kernel as kernel_mod
from hpnn_tpu.serve.engine import Engine, quantize_weights
from hpnn_tpu.serve.registry import Registry, RegistryError
from hpnn_tpu.train import fleet

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _kernels(n, seed0=7, n_in=8, hiddens=(5,), n_out=2):
    return [kernel_mod.generate(seed0 + i, n_in, list(hiddens), n_out)[0]
            for i in range(n)]


def _data(n_rows=8, n_in=8, n_out=2, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.uniform(-1, 1, (n_rows, n_in))
    T = np.full((n_rows, n_out), -1.0)
    T[np.arange(n_rows), rng.randint(0, n_out, n_rows)] = 1.0
    return X, T


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


# --------------------------------------------- K-round scan parity
def test_multi_round_scan_bitwise_vs_sequential_and_ledger_clean(
        tmp_path, monkeypatch):
    """AC: one K-round scanned dispatch == K chained ``train_fleet``
    dispatches, bitwise on CPU f64 — weights AND per-round losses —
    and the paired parity ledgers diff clean under the reference
    tolerances.  The sequential ledger is armed only for the LAST
    round: ``train_fleet_multi`` writes its rows once from the final
    weights, so the two ledgers pair row-for-row."""
    n, rounds = 4, 3
    ks = _kernels(n)
    X, T = _data()
    seed_rounds = [[100 * r + i for i in range(n)]
                   for r in range(rounds)]
    led_m = tmp_path / "multi.jsonl"
    led_s = tmp_path / "seq.jsonl"

    monkeypatch.setenv("HPNN_LEDGER", str(led_m))
    obs._reset_for_tests()
    out_m, loss_m, cnt_m = fleet.train_fleet_multi(
        ks, X, T, rounds=rounds, epochs=2, batch=2,
        seed_rounds=seed_rounds)
    monkeypatch.delenv("HPNN_LEDGER", raising=False)
    obs._reset_for_tests()

    assert loss_m.shape == (n, rounds, 2, 4)
    assert cnt_m.shape == (n, rounds, 2)

    cur = ks
    for r in range(rounds):
        if r == rounds - 1:
            monkeypatch.setenv("HPNN_LEDGER", str(led_s))
            obs._reset_for_tests()
        cur, loss_r, _ = fleet.train_fleet(
            cur, X, T, epochs=2, batch=2, seeds=seed_rounds[r])
        # round r of the scanned run drew the same plan, so its loss
        # slab matches the standalone round bitwise too
        assert np.array_equal(np.asarray(loss_m[:, r]),
                              np.asarray(loss_r))
    monkeypatch.delenv("HPNN_LEDGER", raising=False)
    obs._reset_for_tests()

    for km, kseq in zip(out_m, cur):
        for wa, wb in zip(km.weights, kseq.weights):
            assert np.array_equal(np.asarray(wa), np.asarray(wb))

    ld = _load_tool("ledger_diff")
    rows_m = ld.load_rounds(str(led_m))
    rows_s = ld.load_rounds(str(led_s))
    assert len(rows_m) == n and len(rows_s) == n
    assert {r["where"] for r in rows_m} == {"fleet_round"}
    report = ld.compare(rows_m, rows_s)
    assert report["clean"], report["divergent"]
    assert ld.main([str(led_m), str(led_s)]) == 0
    cat = _load_tool("check_obs_catalog")
    assert cat.lint_ledger(str(led_m)) == []


def test_multi_round_plan_stacks_per_round_fleet_plans():
    seed_rounds = [[1, 2], [3, 4], [5, 6]]
    perms, orders = fleet.multi_round_plan(
        seed_rounds, n_rows=8, batch=2, epochs=2)
    assert perms.shape == (2, 3, 2, 8)      # (N, K, G, n_rows)
    assert orders.shape[:2] == (2, 3)       # (N, K, ...)
    for r, seeds in enumerate(seed_rounds):
        fp, fo = fleet.fleet_plan(seeds, n_rows=8, batch=2, epochs=2)
        assert np.array_equal(perms[:, r], fp)
        assert np.array_equal(orders[:, r], fo)
    with pytest.raises(ValueError, match="member"):
        fleet.multi_round_plan([[1, 2], [3]], n_rows=8, batch=2,
                               epochs=2)


def test_online_trainer_scan_k_consumes_k_rounds(tmp_path, monkeypatch):
    """HPNN_ONLINE_SCAN_K=4: one tick trains the K-round scanned
    dispatch (a ``train.multi_round`` span with ``k``), advances the
    round counter by K so the per-round RNG streams line up with
    unscanned rounds, and the sink passes the ``--quant`` lint."""
    sink = tmp_path / "scan.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    monkeypatch.setenv("HPNN_SPANS", "1")
    monkeypatch.setenv("HPNN_ONLINE_SCAN_K", "4")
    obs._reset_for_tests()
    try:
        osess = online.OnlineSession(
            serve_kwargs=dict(max_batch=8, n_buckets=2,
                              max_wait_ms=1.0),
            rows=16, batch=8, epochs=2, interval_s=60.0, holdout=4,
            gate=online.Gate(margin=0.0, watch_s=30.0), seed=5)
        try:
            assert osess.trainer.scan_k == 4
            osess.add_kernel("k", _kernels(1)[0])
            rng = np.random.RandomState(3)
            X = rng.uniform(0.0, 1.0, size=(32, 8))
            for x, t in zip(X, np.tanh(X[:, :2])):
                osess.feed(x, t)
            summary = osess.tick()
            assert summary["trained"] == 1
            assert osess.trainer._round == 4
        finally:
            osess.close()
    finally:
        monkeypatch.delenv("HPNN_METRICS", raising=False)
        monkeypatch.delenv("HPNN_SPANS", raising=False)
        monkeypatch.delenv("HPNN_ONLINE_SCAN_K", raising=False)
        obs._reset_for_tests()
    spans = [r for r in _read(sink)
             if r.get("ev") == "span.end"
             and r.get("name") == "train.multi_round"]
    assert spans and spans[0]["k"] == 4 and spans[0]["members"] == 1
    cat = _load_tool("check_obs_catalog")
    assert cat.lint_quant(str(sink)) == []


def test_trainer_rejects_bad_scan_k():
    with pytest.raises(ValueError, match="scan_k"):
        online.OnlineTrainer(None, None, None, scan_k=0)


# ------------------------------------------- fleet dbuf DMA epoch
@pytest.mark.parametrize("momentum", [False, True])
def test_fleet_dbuf_epoch_matches_per_member_dbuf_interpret(momentum):
    """The fleet-stacked double-buffered DMA epoch computes exactly N
    per-member ``train_epoch_dbuf_banked`` epochs (interpret mode;
    bitwise f32)."""
    import jax.numpy as jnp

    from hpnn_tpu.ops import pallas_train

    N, B, S = 3, 4, 3
    ks = _kernels(N)
    rng = np.random.RandomState(0)
    X_banks = rng.uniform(-1, 1, (N, S * B, 8)).astype(np.float32)
    T_banks = np.where(
        rng.rand(N, S * B, 2) > 0.5, 1.0, -1.0).astype(np.float32)
    orders = np.stack([rng.permutation(S) for _ in range(N)]
                      ).astype(np.int32)

    stacked = tuple(jnp.asarray(w, jnp.float32)
                    for w in fleet.stack_kernels(ks))
    dw = (tuple(jnp.zeros_like(w) for w in stacked)
          if momentum else ())
    wf, dwf, lf = pallas_train.train_fleet_epoch_dbuf_banked(
        stacked, dw, X_banks, T_banks, jnp.asarray(orders),
        batch=B, momentum=momentum, interpret=True)
    assert np.asarray(lf).shape == (N, S)

    for i in range(N):
        wi = tuple(jnp.asarray(np.asarray(w), jnp.float32)
                   for w in ks[i].weights)
        dwi = (tuple(jnp.zeros_like(w) for w in wi)
               if momentum else ())
        we, dwe, le = pallas_train.train_epoch_dbuf_banked(
            wi, dwi, jnp.asarray(X_banks[i]), jnp.asarray(T_banks[i]),
            jnp.asarray(orders[i]), batch=B, momentum=momentum,
            interpret=True)
        for a, b in zip(we, wf):
            assert np.array_equal(np.asarray(a), np.asarray(b)[i])
        for a, b in zip(dwe, dwf):
            assert np.array_equal(np.asarray(a), np.asarray(b)[i])
        assert np.array_equal(np.asarray(le), np.asarray(lf)[i])


# ------------------------------------------------ serve precision
def _eager_f64(kernel, X):
    w64 = tuple(np.asarray(w, dtype=np.float64)
                for w in kernel.weights)
    return np.stack([np.asarray(ann.run(w64, x))
                     for x in np.asarray(X, dtype=np.float64)])


def test_serve_bf16_compiled_within_documented_tolerance(tmp_path,
                                                         monkeypatch):
    """AC: the bf16 compiled path stays under the documented 1e-1
    bound vs the eager f64 reference (docs/performance.md), the
    warmup probe measures + publishes it, and the metrics sink passes
    the ``--quant`` lint."""
    sink = tmp_path / "bf16.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    try:
        k = _kernels(1, n_in=16, hiddens=(8,), n_out=4)[0]
        k = k.astype(np.float32)
        reg = Registry()
        reg.register("m", k)
        entry = reg.set_precision("m", "bf16")
        assert entry.precision == "bf16"
        eng = Engine(reg, mode="compiled", max_batch=8, n_buckets=2)
        eng.warmup()
        doc = eng.precision_doc()
        assert doc["kernels"]["m"]["precision"] == "bf16"
        assert 0.0 <= doc["kernels"]["m"]["quant_err"] < 1e-1

        rng = np.random.RandomState(0)
        X = rng.uniform(-1, 1, (8, 16)).astype(np.float32)
        got = eng.run_rows(reg.get("m"), X)
        assert got.dtype == np.float32  # host IO stays native
        err = np.max(np.abs(got.astype(np.float64) - _eager_f64(k, X)))
        assert err < 1e-1
    finally:
        monkeypatch.delenv("HPNN_METRICS", raising=False)
        obs._reset_for_tests()
    cat = _load_tool("check_obs_catalog")
    assert cat.lint_quant(str(sink)) == []
    evs = {r.get("ev") for r in _read(sink)}
    assert {"serve.precision", "numerics.quant_err"} <= evs


def test_serve_int8_error_bound_and_monotone_bits():
    """int8-weight serving stays under the documented 2e-1 bound, and
    the quantization error is monotone in bit width (4-bit >= 8-bit)
    — the property that makes the bound a dial, not a cliff."""
    k = _kernels(1, n_in=16, hiddens=(8,), n_out=4)[0]
    k = k.astype(np.float32)

    def dequant_err(bits):
        quants, scales = quantize_weights(k.weights, bits=bits)
        err = 0.0
        for w, q, s in zip(k.weights, quants, scales):
            assert q.dtype == np.int8
            err = max(err, float(np.max(np.abs(
                np.asarray(w, np.float64) -
                q.astype(np.float64) * s))))
        return err

    err8, err4 = dequant_err(8), dequant_err(4)
    assert err4 >= err8 > 0.0
    with pytest.raises(ValueError):
        quantize_weights(k.weights, bits=1)

    reg = Registry()
    reg.register("m", k, precision="int8")
    eng = Engine(reg, mode="compiled", max_batch=8, n_buckets=2)
    rng = np.random.RandomState(1)
    X = rng.uniform(-1, 1, (8, 16)).astype(np.float32)
    got = eng.run_rows(reg.get("m"), X)
    err = np.max(np.abs(got.astype(np.float64) - _eager_f64(k, X)))
    assert err < 2e-1


def test_precision_policy_validation_and_stickiness():
    k = _kernels(1)[0]
    reg = Registry()
    with pytest.raises(RegistryError, match="precision"):
        reg.register("m", k, precision="fp4")
    reg.register("m", k, precision="bf16")
    with pytest.raises(RegistryError, match="precision"):
        reg.set_precision("m", "fp4")
    # the policy survives reloads/installs (a hot-reload must not
    # silently dequantize); set_precision(None) clears it
    v0 = reg.get("m").version
    reg.register("m", k)
    assert reg.get("m").precision == "bf16"
    assert reg.get("m").version == v0 + 1
    entry = reg.set_precision("m", None)
    assert entry.precision is None and entry.version == v0 + 2


def test_engine_rejects_bogus_serve_dtype(monkeypatch):
    monkeypatch.setenv("HPNN_SERVE_DTYPE", "fp8")
    reg = Registry()
    with pytest.raises(ValueError, match="HPNN_SERVE_DTYPE"):
        Engine(reg, mode="compiled")


def test_parity_mode_ignores_precision_policy(monkeypatch):
    """The CPU parity engine's contract is bitwise equality with the
    embedded caller — a precision policy must not perturb it (this is
    also why check_tokens can arm HPNN_SERVE_DTYPE=bf16 in its
    byte-freeze run)."""
    monkeypatch.setenv("HPNN_SERVE_DTYPE", "bf16")
    k = _kernels(1)[0]
    reg = Registry()
    reg.register("m", k)
    eng = Engine(reg, mode="parity", max_batch=8, n_buckets=2)
    rng = np.random.RandomState(2)
    X = rng.uniform(-1, 1, (5, 8))
    got = eng.run_rows(reg.get("m"), X)
    w = tuple(np.asarray(wl) for wl in k.weights)
    want = np.stack([np.asarray(ann.run(w, x)) for x in X])
    assert np.array_equal(got, want)


# --------------------------------------- bf16 train + ledger story
def test_bf16_fleet_train_ledger_needs_widened_tolerances(
        tmp_path, monkeypatch):
    """AC: a bf16 training run's ledger vs the f64 reference FAILS
    ``ledger_diff`` under the default bitwise tolerances (low
    precision must be visible) and passes once ``--vec-tol/--mat-tol``
    are widened to the documented quantization scale."""
    ks = _kernels(4)
    X, T = _data()
    seeds = list(range(4))
    led_ref = tmp_path / "f64.jsonl"
    led_bf16 = tmp_path / "bf16.jsonl"

    monkeypatch.setenv("HPNN_LEDGER", str(led_ref))
    obs._reset_for_tests()
    fleet.train_fleet(ks, X, T, epochs=2, batch=2, seeds=seeds)
    monkeypatch.setenv("HPNN_LEDGER", str(led_bf16))
    obs._reset_for_tests()
    fleet.train_fleet(ks, X, T, epochs=2, batch=2, seeds=seeds,
                      dtype="bf16")
    monkeypatch.delenv("HPNN_LEDGER", raising=False)
    obs._reset_for_tests()

    ld = _load_tool("ledger_diff")
    # default (bitwise) tolerances: the bf16 run must be visible
    assert ld.main([str(led_ref), str(led_bf16)]) == 1
    # widened to the quantization scale: clean
    assert ld.main([str(led_ref), str(led_bf16),
                    "--vec-tol", "1.0", "--mat-tol", "1.0"]) == 0


def test_quant_probe_fleet_measures_small_bf16_error(tmp_path,
                                                     monkeypatch):
    sink = tmp_path / "probe.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    try:
        ks = _kernels(3)
        X, T = _data()
        out_low, out_ref, err = fleet.quant_probe_fleet(
            ks, X, T, epochs=2, batch=2, dtype="bf16")
        assert len(out_low) == len(out_ref) == 3
        assert np.isfinite(err) and 0.0 < err < 1e-1
        with pytest.raises(ValueError, match="dtype"):
            fleet.train_fleet(ks, X, T, epochs=1, batch=2,
                              dtype="int3")
    finally:
        monkeypatch.delenv("HPNN_METRICS", raising=False)
        obs._reset_for_tests()
    gauges = [r for r in _read(sink)
              if r.get("ev") == "numerics.quant_err"]
    assert gauges and gauges[0]["where"] == "fleet"
    assert gauges[0]["value"] == pytest.approx(err)


# ---------------------------------------------- gate + quant lint
def test_promotion_gate_rejects_quantization_regressed_candidate(
        tmp_path, monkeypatch):
    """AC: a candidate degraded by coarse quantization whose held-out
    loss regresses past the margin is rejected on "margin" — the
    promotion gate is the last line of defense and precision is not
    exempt from it."""
    sink = tmp_path / "gate.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    try:
        osess = online.OnlineSession(
            serve_kwargs=dict(max_batch=8, n_buckets=2,
                              max_wait_ms=1.0),
            gate=online.Gate(margin=0.01, watch_s=30.0))
        try:
            k = _kernels(1)[0]
            osess.add_kernel("k", k)
            # a brutally coarse (2-bit) quantization of the resident:
            # same shapes, badly regressed eval loss
            quants, scales = quantize_weights(k.weights, bits=2)
            cand = tuple(q.astype(np.float64) * s
                         for q, s in zip(quants, scales))
            X, T = _data(n_rows=16)
            verdict = osess.promoter.consider("k", cand, (X, T),
                                              step=0)
            assert verdict == "margin"
            # the resident stayed resident
            got = osess.serve.registry.get("k")
            for wa, wb in zip(got.kernel.weights, k.weights):
                assert np.array_equal(np.asarray(wa), np.asarray(wb))
        finally:
            osess.close()
    finally:
        monkeypatch.delenv("HPNN_METRICS", raising=False)
        obs._reset_for_tests()
    rejects = [r for r in _read(sink)
               if r.get("ev") == "online.reject"]
    assert rejects and rejects[0]["reason"] == "margin"


def test_lint_quant_schema_failures(tmp_path):
    """The --quant lint rejects malformed records: a NaN quant-err
    gauge, a bogus precision name, a multi-round event without k,
    and an empty sink."""
    cat = _load_tool("check_obs_catalog")
    bad = tmp_path / "bad.jsonl"
    bad.write_text("\n".join([
        json.dumps({"ev": "numerics.quant_err", "kind": "gauge",
                    "value": float("nan"), "where": "serve"}),
        json.dumps({"ev": "serve.precision", "kind": "event",
                    "kernel": "", "precision": "fp4", "version": -1,
                    "source": "elsewhere"}),
        json.dumps({"ev": "fleet.multi_round", "kind": "event",
                    "members": 2, "epochs": 1, "dispatch_s": -0.5}),
    ]) + "\n")
    failures = cat.lint_quant(str(bad))
    assert any("not a finite" in f for f in failures)
    assert any("precision" in f for f in failures)
    assert any("k " in f for f in failures)
    assert any("dispatch_s" in f for f in failures)
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"ev": "online.ingest",
                                 "kind": "count", "n": 1}) + "\n")
    assert any("no multi-round / precision records" in f
               for f in cat.lint_quant(str(empty)))
