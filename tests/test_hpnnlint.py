"""hpnnlint static analysis suite (tools/hpnnlint, docs/analysis.md).

Two halves:

* the **repo-clean gate** — the engine runs in-process over
  ``hpnn_tpu/`` + ``tools/`` and any finding fails tier-1, so the
  tree is lint-clean by construction;
* **accept/break ladders** per rule over tmp fixture trees — each
  seeded single-rule violation must produce exactly the expected
  finding (and a non-zero exit), each compliant twin must pass.

Plus the pragma grammar (reason mandatory, bare pragma is itself a
finding), the ``--json`` schema, and the 0/1/2 exit-code contract.
The engine is stdlib-only: no jax anywhere in this file's imports.
"""

import json
import os
import subprocess
import sys
import textwrap

from tools.hpnnlint import engine
from tools.hpnnlint.rules import all_rules
from tools.hpnnlint.rules.lock_discipline import LockDisciplineRule
from tools.hpnnlint.rules.swallow import SwallowRule
from tools.hpnnlint.rules.trace_purity import TracePurityRule

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(tmp_path, files):
    """Write a fixture tree; returns its root as str."""
    for rel, src in files.items():
        full = tmp_path / rel
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(textwrap.dedent(src), encoding="utf-8")
    return str(tmp_path)


def _lint(tmp_path, files, paths=("pkg",), rules=None):
    root = _tree(tmp_path, files)
    findings, _n = engine.run(root, list(paths), rules=rules)
    return findings


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------ repo-clean gate
def test_repo_is_lint_clean():
    """THE gate: any finding anywhere in hpnn_tpu/ or tools/ fails
    tier-1 with the rendered file:line evidence."""
    findings, n_files = engine.run(REPO_ROOT, ["hpnn_tpu", "tools"])
    assert n_files > 50
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_cli_module_entry_point_clean_and_json():
    """`python -m tools.hpnnlint hpnn_tpu tools --json` — the exact
    command docs/analysis.md ships — exits 0 with the v1 schema."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.hpnnlint", "hpnn_tpu", "tools",
         "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == 1
    assert doc["findings"] == [] and doc["counts"] == {}
    assert doc["files"] > 50


# ------------------------------------------------------------- swallow
BROKEN_SWALLOW = """\
    def f():
        try:
            risky()
        except Exception:
            pass
"""


def test_swallow_breaks_on_silent_broad_except(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": BROKEN_SWALLOW})
    assert [f.rule for f in findings] == ["swallow"]
    assert findings[0].file == os.path.join("pkg", "m.py")
    assert findings[0].line == 4          # the `except` line


def test_swallow_breaks_on_bare_except_and_silent_return(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": """\
        def f():
            try:
                risky()
            except:
                return None
    """})
    assert [f.rule for f in findings] == ["swallow"]


def test_swallow_accepts_narrow_observable_or_raising(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": """\
        def f():
            try:
                risky()
            except OSError:          # narrow: fine silent
                pass
            try:
                risky()
            except Exception as exc:
                record(exc)          # observable
            try:
                risky()
            except Exception:
                raise RuntimeError("ctx")   # re-raise
    """})
    assert findings == []


# ------------------------------------------------------------- pragma
def test_pragma_same_line_suppresses(tmp_path):
    src = BROKEN_SWALLOW.replace(
        "except Exception:",
        "except Exception:  # hpnnlint: ignore[swallow] -- demo waiver")
    assert _lint(tmp_path, {"pkg/m.py": src}) == []


def test_pragma_comment_line_above_suppresses(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": """\
        def f():
            try:
                risky()
            # hpnnlint: ignore[swallow] -- benign by design (demo)
            except Exception:
                pass
    """})
    assert findings == []


def test_pragma_without_reason_is_itself_a_finding(tmp_path):
    src = BROKEN_SWALLOW.replace(
        "except Exception:",
        "except Exception:  # hpnnlint: ignore[swallow]")
    findings = _lint(tmp_path, {"pkg/m.py": src})
    # the mute button doesn't work AND the bad pragma is reported
    assert _rules_of(findings) == ["pragma", "swallow"]


def test_pragma_wrong_rule_does_not_suppress(tmp_path):
    src = BROKEN_SWALLOW.replace(
        "except Exception:",
        "except Exception:  # hpnnlint: ignore[trace-purity] -- wrong")
    findings = _lint(tmp_path, {"pkg/m.py": src})
    assert [f.rule for f in findings] == ["swallow"]


# ----------------------------------------------------- lock-discipline
LOCKED_CLASS = """\
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)
            self._items = []        # guarded: _lock
            self.n = 0              # guarded: _lock

        def ok_with(self, x):
            with self._lock:
                self._items.append(x)
                self.n += 1

        def ok_alias(self, x):
            with self._cond:        # Condition(lock) == the lock
                self._items = [x]
"""


def test_lock_discipline_accepts_guarded_writes(tmp_path):
    assert _lint(tmp_path, {"pkg/m.py": LOCKED_CLASS}) == []


def test_lock_discipline_breaks_on_off_lock_writes(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": LOCKED_CLASS + """\

        def bad_plain(self, x):
            self._items = [x]

        def bad_mutator(self, x):
            self._items.append(x)

        def bad_aug(self):
            self.n += 1
    """})
    assert [f.rule for f in findings] == ["lock-discipline"] * 3
    assert all("guarded: _lock" in f.msg for f in findings)


def test_lock_discipline_breaks_on_subscript_and_closure(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._map = {}      # guarded: _lock

            def bad_item(self, k, v):
                self._map[k] = v

            def bad_closure(self, k):
                with self._lock:
                    def cb():       # may run on another thread
                        self._map[k] = 1
                    return cb
    """})
    assert [f.rule for f in findings] == ["lock-discipline"] * 2


def test_lock_discipline_flags_guard_typo(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []    # guarded: _locck
    """})
    assert [f.rule for f in findings] == ["lock-discipline"]
    assert "typo" in findings[0].msg


def test_lock_discipline_bare_acquire(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": """\
        import threading
        LK = threading.Lock()

        def bad():
            LK.acquire()
            work()
            LK.release()

        def good():
            LK.acquire()
            try:
                work()
            finally:
                LK.release()
    """})
    assert [f.rule for f in findings] == ["lock-discipline"]
    assert "bare LK.acquire()" in findings[0].msg


# -------------------------------------------------------- trace-purity
def test_trace_purity_breaks_on_host_calls_in_jit(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": """\
        import time
        import jax

        @jax.jit
        def step(x):
            t = time.time()
            return x + t
    """})
    assert [f.rule for f in findings] == ["trace-purity"]
    assert "time.time" in findings[0].msg


def test_trace_purity_sees_one_hop_into_scan_body(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": """\
        import os
        import jax

        def helper(c):
            return c, os.environ.get("X")

        def body(c, x):
            return helper(c)

        def run(xs):
            return jax.lax.scan(body, 0, xs)
    """})
    assert [f.rule for f in findings] == ["trace-purity"]
    assert "os.environ" in findings[0].msg
    assert "helper" in findings[0].msg        # the one-hop context


def test_trace_purity_accepts_pure_traced_fn(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": """\
        import time
        import jax

        @jax.jit
        def step(x):
            return x * 2

        def host_side():
            return time.time()      # not traced: fine
    """})
    assert findings == []


# --------------------------------------------------------- obs-catalog
OBS_FIXTURE_SRC = """\
    from hpnn_tpu.obs import registry

    def f():
        registry.count("serve.hit", n=1)
"""


def test_obs_catalog_breaks_both_directions(tmp_path):
    findings = _lint(tmp_path, {
        "hpnn_tpu/m.py": OBS_FIXTURE_SRC + """\

        def g():
            registry.event("serve.nope")
        """,
        "docs/observability.md": """\
            | name | kind | meaning |
            |---|---|---|
            | `serve.hit` | count | ok |
            | `serve.ghost` | event | emitter retired |
        """,
    }, paths=("hpnn_tpu",))
    assert [f.rule for f in findings] == ["obs-catalog"] * 2
    by_file = {f.file: f for f in findings}
    emit = by_file[os.path.join("hpnn_tpu", "m.py")]
    assert "`serve.nope`" in emit.msg and "missing" in emit.msg
    row = by_file["docs/observability.md"]
    assert "`serve.ghost`" in row.msg and row.line == 4


def test_obs_catalog_accepts_documented_and_wildcard(tmp_path):
    findings = _lint(tmp_path, {
        "hpnn_tpu/m.py": OBS_FIXTURE_SRC + """\

        def g(i):
            registry.gauge(f"fleet.worker{i}.depth", v=1)
        """,
        "docs/observability.md": """\
            | `serve.hit` | count | ok |
            | `fleet.*` | gauge | per-worker family |
        """,
    }, paths=("hpnn_tpu",))
    assert findings == []


# ------------------------------------------------------- knob-registry
def _knob_tree(knobs_literal, module_src, doc_text):
    return {
        "hpnn_tpu/config.py": f"KNOBS = {knobs_literal}\n",
        "hpnn_tpu/m.py": module_src,
        "docs/observability.md": doc_text,
    }


GOOD_KNOBS = ('{"HPNN_DEMO": {"default": "0", '
              '"doc": "docs/observability.md", "desc": "demo knob"}}')
READS_DEMO = 'import os\nV = os.environ.get("HPNN_DEMO", "0")\n'


def test_knob_registry_accepts_full_contract(tmp_path):
    findings = _lint(
        tmp_path,
        _knob_tree(GOOD_KNOBS, READS_DEMO, "set HPNN_DEMO=1 to demo\n"),
        paths=("hpnn_tpu",))
    assert findings == []


def test_knob_registry_breaks_on_undeclared_read(tmp_path):
    findings = _lint(
        tmp_path,
        _knob_tree(GOOD_KNOBS,
                   READS_DEMO + 'W = os.environ.get("HPNN_ROGUE")\n',
                   "set HPNN_DEMO=1\n"),
        paths=("hpnn_tpu",))
    assert [f.rule for f in findings] == ["knob-registry"]
    assert "`HPNN_ROGUE`" in findings[0].msg
    assert findings[0].file == os.path.join("hpnn_tpu", "m.py")


def test_knob_registry_breaks_on_dead_row(tmp_path):
    findings = _lint(
        tmp_path,
        _knob_tree(GOOD_KNOBS, "X = 1\n", "set HPNN_DEMO=1\n"),
        paths=("hpnn_tpu",))
    assert [f.rule for f in findings] == ["knob-registry"]
    assert "retire the row" in findings[0].msg


def test_knob_registry_breaks_on_undocumented_knob(tmp_path):
    findings = _lint(
        tmp_path,
        _knob_tree(GOOD_KNOBS, READS_DEMO, "no knobs here\n"),
        paths=("hpnn_tpu",))
    assert [f.rule for f in findings] == ["knob-registry"]
    assert "never mentions the knob" in findings[0].msg


def test_knob_registry_breaks_on_stale_doc_mention(tmp_path):
    findings = _lint(
        tmp_path,
        _knob_tree(GOOD_KNOBS, READS_DEMO,
                   "set HPNN_DEMO=1; HPNN_GONE was removed\n"),
        paths=("hpnn_tpu",))
    assert [f.rule for f in findings] == ["knob-registry"]
    assert "`HPNN_GONE`" in findings[0].msg


def test_knob_registry_breaks_on_non_literal_table(tmp_path):
    findings = _lint(
        tmp_path,
        _knob_tree("dict(x=1)", READS_DEMO, ""),
        paths=("hpnn_tpu",))
    assert any(f.rule == "knob-registry"
               and "pure literal" in f.msg for f in findings)


# ------------------------------------------------- engine / exit codes
def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    findings = _lint(tmp_path, {"pkg/m.py": "def broken(:\n"})
    assert [f.rule for f in findings] == ["parse"]


def test_rule_selection_runs_only_named_rule(tmp_path):
    files = {"pkg/m.py": BROKEN_SWALLOW + """\

        import threading
        LK = threading.Lock()

        def also_bad():
            LK.acquire()
            work()
    """}
    both = _lint(tmp_path, dict(files))
    assert _rules_of(both) == ["lock-discipline", "swallow"]
    only = _lint(tmp_path, dict(files), rules=[SwallowRule()])
    assert _rules_of(only) == ["swallow"]


def test_findings_sorted_and_rendered(tmp_path):
    findings = _lint(tmp_path, {
        "pkg/a.py": BROKEN_SWALLOW,
        "pkg/b.py": BROKEN_SWALLOW,
    })
    assert [f.file for f in findings] == [
        os.path.join("pkg", "a.py"), os.path.join("pkg", "b.py")]
    assert findings[0].render() == (
        f"{os.path.join('pkg', 'a.py')}:4: [swallow] "
        f"{findings[0].msg}")


def test_main_exit_codes(tmp_path, capsys):
    root = _tree(tmp_path, {
        "clean/m.py": "X = 1\n",
        "dirty/m.py": BROKEN_SWALLOW,
    })
    assert engine.main(["--root", root, "clean"]) == 0
    assert engine.main(["--root", root, "dirty"]) == 1
    assert engine.main(["--root", root, "--rule", "nonsense",
                        "clean"]) == 2
    assert engine.main(["--totally-bogus-flag"]) == 2
    capsys.readouterr()


def test_main_json_schema_on_findings(tmp_path, capsys):
    root = _tree(tmp_path, {"dirty/m.py": BROKEN_SWALLOW})
    assert engine.main(["--root", root, "--json", "dirty"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1 and doc["files"] == 1
    assert doc["counts"] == {"swallow": 1}
    f = doc["findings"][0]
    assert set(f) == {"rule", "file", "line", "msg"}
    assert f["rule"] == "swallow" and f["line"] == 4


def test_all_rules_have_unique_names():
    rules = all_rules()
    names = [r.name for r in rules]
    assert len(set(names)) == len(names) == 5
    assert {"obs-catalog", "knob-registry", "lock-discipline",
            "swallow", "trace-purity"} == set(names)
    assert isinstance(rules[2], LockDisciplineRule)
    assert isinstance(rules[4], TracePurityRule)
