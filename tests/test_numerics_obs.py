"""Numerics observability: probes, the checksum ledger, and the
cross-rank divergence sentinel (ISSUE 4 acceptance criteria).

Covers: inactive-by-default no-ops, same-seed ledger reproducibility
under the reference 1e-14/1e-12 tolerances, `tools/ledger_diff.py`
verdicts at the tolerance boundaries, a NaN injected mid-round being
caught within one round (warn continues / abort raises, flight dump
carries the last clean checksums), the sentinel firing on simulated
rank disagreement, the CLI abort path exiting non-zero, and the serve
/healthz numerics verdict."""

import importlib.util
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from hpnn_tpu import obs
from hpnn_tpu.config import NNConf, NNTrain, NNType
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import ledger, probes
from hpnn_tpu.obs.probes import NumericsError

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _conf(tmp_path, n=6):
    rng = np.random.RandomState(0)
    sdir = tmp_path / "samples"
    sdir.mkdir(exist_ok=True)
    for i in range(n):
        c = i % 2
        x = (1 - 2 * c) * np.r_[np.ones(4), -np.ones(4)] \
            + 0.1 * rng.normal(size=8)
        t = np.full(2, -1.0)
        t[c] = 1.0
        with open(sdir / f"s{i:05d}.txt", "w") as fp:
            fp.write("[input] 8\n" + " ".join(f"{v:.5f}" for v in x) + "\n")
            fp.write("[output] 2\n" + " ".join(f"{v:.1f}" for v in t) + "\n")
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    return NNConf(name="t", type=NNType.ANN, seed=1, kernel=k,
                  train=NNTrain.BP, samples=str(sdir), tests=str(sdir))


def _kernel():
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    return k


# ------------------------------------------------------------ basics
def test_inactive_by_default(tmp_path, monkeypatch):
    for knob in ("HPNN_PROBES", "HPNN_NUMERICS", "HPNN_LEDGER",
                 "HPNN_METRICS"):
        monkeypatch.delenv(knob, raising=False)
    obs._reset_for_tests()
    assert not probes.enabled()
    assert probes.mode() == "off"
    assert not ledger.enabled()
    assert ledger.last_row() is None
    assert probes.check_weights(_kernel().weights, step=0,
                                where="unit") is None
    assert probes.health_doc(["k"]) == {"mode": "off"}
    assert list(tmp_path.iterdir()) == []


def test_weight_names_and_named_weights():
    assert kernel_mod.weight_names(3) == ("w0", "w1", "w2")
    k = _kernel()
    named = kernel_mod.named_weights(k.weights)
    assert list(named) == ["w0", "w1"]
    assert named["w1"].shape == (2, 5)


def test_tolerance_rule():
    # matrix iff >= 2 dims of extent > 1 (reference ChangeLog:33-38)
    assert probes.tolerance_for([5, 8]) == 1e-12
    assert probes.tolerance_for([8]) == 1e-14
    assert probes.tolerance_for([1, 8]) == 1e-14
    assert probes.tolerance_for([8, 1]) == 1e-14
    ld = _load_tool("ledger_diff")
    for shape in ([5, 8], [8], [1, 8]):
        assert ld.tolerance_for(shape) == probes.tolerance_for(shape)


def test_check_weights_emits_and_records(tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.setenv("HPNN_PROBES", "1")
    monkeypatch.setenv("HPNN_LEDGER", str(tmp_path / "led.jsonl"))
    obs._reset_for_tests()
    k = _kernel()
    v = probes.check_weights(k.weights, step=3, where="unit")
    assert v["clean"] and v["nan"] == 0 and v["row"] == 0
    recs = _read(tmp_path / "m.jsonl")
    by = {}
    for r in recs:
        by.setdefault(r["ev"], []).append(r)
    assert len(by["numerics.probe"]) == 2          # one per tensor
    p0 = by["numerics.probe"][0]
    assert p0["tensor"] == "w0" and p0["abs_sum"] > 0
    assert p0["l2"] > 0 and p0["nan"] == 0
    ck = by["numerics.checksum"][0]
    assert ck["clean"] is True
    assert set(ck["checksums"]) == {"w0", "w1"}
    for g in ("numerics.nan_count", "numerics.inf_count",
              "numerics.absmax"):
        assert g in by
    rows = [r for r in _read(tmp_path / "led.jsonl")
            if r["ev"] == "ledger.round"]
    assert rows[0]["checksums"]["w0"] == pytest.approx(
        float(np.abs(np.asarray(k.weights[0])).sum()), abs=1e-13)
    assert rows[0]["shapes"] == {"w0": [5, 8], "w1": [2, 5]}
    assert ledger.last_row() == 0


# ------------------------------------------------- ledger + diff tool
def _train_with_ledger(tmp_path, subdir, monkeypatch):
    from hpnn_tpu.train import driver

    led = tmp_path / f"ledger_{subdir}.jsonl"
    monkeypatch.setenv("HPNN_LEDGER", str(led))
    obs._reset_for_tests()
    work = tmp_path / subdir
    work.mkdir()
    conf = _conf(work)
    assert driver.train_kernel(conf)
    driver.run_kernel(conf)
    obs._reset_for_tests()      # close the ledger file
    return led


def test_same_seed_runs_diff_clean(tmp_path, monkeypatch, capsys):
    """AC: two independent same-seed CPU runs produce ledgers that
    ledger_diff reports clean under the reference tolerances."""
    led_a = _train_with_ledger(tmp_path, "a", monkeypatch)
    led_b = _train_with_ledger(tmp_path, "b", monkeypatch)
    monkeypatch.delenv("HPNN_LEDGER", raising=False)
    obs._reset_for_tests()
    ld = _load_tool("ledger_diff")
    rows_a, rows_b = ld.load_rounds(str(led_a)), ld.load_rounds(str(led_b))
    assert rows_a and len(rows_a) == len(rows_b)
    assert {r["where"] for r in rows_a} >= {"fused_chunk", "eval"}
    report = ld.compare(rows_a, rows_b)
    assert report["clean"], report["divergent"]
    assert ld.main([str(led_a), str(led_b)]) == 0
    out = capsys.readouterr().out
    assert "verdict: CLEAN" in out
    # the ledgers also pass the frozen-schema lint
    cat = _load_tool("check_obs_catalog")
    assert cat.lint_ledger(str(led_a)) == []


def test_ledger_diff_divergent_and_json(tmp_path, monkeypatch, capsys):
    led_a = _train_with_ledger(tmp_path, "a", monkeypatch)
    monkeypatch.delenv("HPNN_LEDGER", raising=False)
    obs._reset_for_tests()
    tampered = tmp_path / "tampered.jsonl"
    with open(led_a) as fp, open(tampered, "w") as out:
        for ln in fp:
            rec = json.loads(ln)
            if rec.get("ev") == "ledger.round":
                rec["checksums"]["w0"] += 1e-6
            out.write(json.dumps(rec) + "\n")
    ld = _load_tool("ledger_diff")
    assert ld.main([str(led_a), str(tampered), "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert not report["clean"]
    assert report["divergent"][0]["tensor"] == "w0"
    assert report["divergent"][0]["reason"] == "tolerance"
    assert report["max_abs_diff"] == pytest.approx(1e-6, rel=1e-3)
    # a loosened tolerance accepts the same pair
    assert ld.main([str(led_a), str(tampered),
                    "--mat-tol", "1e-3", "--vec-tol", "1e-3"]) == 0


def _synth_ledger(path, checksums, shape):
    with open(path, "w") as fp:
        fp.write(json.dumps({"ts": 0, "ev": "ledger.open", "path": path,
                             "pid": 1, "rank": 0}) + "\n")
        fp.write(json.dumps({
            "ts": 0, "ev": "ledger.round", "row": 0, "step": 1,
            "where": "t", "rank": 0, "nan": 0, "inf": 0,
            "checksums": checksums,
            "shapes": {k: shape for k in checksums}}) + "\n")


def test_ledger_diff_tolerance_boundaries(tmp_path):
    ld = _load_tool("ledger_diff")
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    # matrix: 5e-13 passes the 1e-12 bar, 2e-12 fails it
    _synth_ledger(a, {"w0": 1.0}, [5, 8])
    _synth_ledger(b, {"w0": 1.0 + 5e-13}, [5, 8])
    assert ld.compare(ld.load_rounds(a), ld.load_rounds(b))["clean"]
    _synth_ledger(b, {"w0": 1.0 + 2e-12}, [5, 8])
    assert not ld.compare(ld.load_rounds(a), ld.load_rounds(b))["clean"]
    # vector: 5e-15 passes the 1e-14 bar, 2e-14 fails it
    _synth_ledger(a, {"v": 1.0}, [8])
    _synth_ledger(b, {"v": 1.0 + 5e-15}, [8])
    assert ld.compare(ld.load_rounds(a), ld.load_rounds(b))["clean"]
    _synth_ledger(b, {"v": 1.0 + 2e-14}, [8])
    report = ld.compare(ld.load_rounds(a), ld.load_rounds(b))
    assert not report["clean"]
    assert report["divergent"][0]["tol"] == 1e-14
    # row-count mismatch is divergence, not silence
    with open(b, "a") as fp:
        fp.write(json.dumps({
            "ts": 0, "ev": "ledger.round", "row": 1, "step": 2,
            "where": "t", "rank": 0, "nan": 0, "inf": 0,
            "checksums": {"v": 1.0}, "shapes": {"v": [8]}}) + "\n")
    reasons = [d["reason"] for d in
               ld.compare(ld.load_rounds(a), ld.load_rounds(b))["divergent"]]
    assert "row_count" in reasons


def test_ledger_schema_lint_catches_drift(tmp_path):
    cat = _load_tool("check_obs_catalog")
    bad = tmp_path / "bad.jsonl"
    with open(bad, "w") as fp:
        fp.write(json.dumps({"ts": 0, "ev": "ledger.open", "path": "x",
                             "pid": 1, "rank": 0}) + "\n")
        # row index jumps, shapes key set mismatches, nan negative
        fp.write(json.dumps({
            "ts": 0, "ev": "ledger.round", "row": 3, "step": 1,
            "where": "t", "rank": 0, "nan": -1, "inf": 0,
            "checksums": {"w0": 1.0},
            "shapes": {"w1": [2, 5]}}) + "\n")
        fp.write("not json\n")
    failures = cat.lint_ledger(str(bad))
    text = "\n".join(failures)
    assert "not monotone" in text
    assert "shapes keys" in text
    assert "nan census" in text
    assert "not JSON" in text


# --------------------------------------------- NaN injection (the AC)
def _poison_second_chunk(monkeypatch):
    """Monkeypatch the fused-epoch body so the SECOND chunk returns
    weights with one NaN planted — the mid-round corruption of the
    acceptance criterion."""
    import jax.numpy as jnp

    from hpnn_tpu.train import loop

    orig = loop.train_epoch_lax
    calls = {"n": 0}

    def poisoned(w, m0, Xc, Tc, *args, **kwargs):
        out_w, stats = orig(w, m0, Xc, Tc, *args, **kwargs)
        calls["n"] += 1
        if calls["n"] == 2:
            out_w = (out_w[0].at[0, 0].set(jnp.nan),) + tuple(out_w[1:])
        return out_w, stats

    monkeypatch.setattr(loop, "train_epoch_lax", poisoned)
    return calls


def test_nan_injection_abort_with_postmortem(tmp_path, monkeypatch):
    """AC: a NaN injected mid-round is detected within one round under
    abort mode — NumericsError raised, flight dump written, the last
    CLEAN checksums recoverable from the dump, ledger row 0 clean."""
    from hpnn_tpu.train import driver

    dump = tmp_path / "flight.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.setenv("HPNN_FLIGHT", str(dump))
    monkeypatch.setenv("HPNN_LEDGER", str(tmp_path / "led.jsonl"))
    monkeypatch.setenv("HPNN_NUMERICS", "abort")
    monkeypatch.setenv("HPNN_FUSE_CHUNK", "2")     # 6 samples, 3 chunks
    obs._reset_for_tests()
    _poison_second_chunk(monkeypatch)
    with pytest.raises(NumericsError, match="NaN"):
        driver.train_kernel(_conf(tmp_path))
    # flight dump: the postmortem carries the failure AND the last
    # clean checksums (the step-2 numerics.checksum record)
    assert dump.exists()
    recs = _read(dump)
    nans = [r for r in recs if r.get("ev") == "numerics.nan"]
    assert nans and nans[0]["step"] == 4           # chunk 2 boundary
    cks = [r for r in recs if r.get("ev") == "numerics.checksum"]
    clean = [r for r in cks if r["clean"]]
    assert clean and clean[-1]["step"] == 2
    assert all(np.isfinite(v) for v in clean[-1]["checksums"].values())
    # ledger: row 0 (chunk 1) clean, row 1 (chunk 2) carries the NaN
    rows = [r for r in _read(tmp_path / "led.jsonl")
            if r["ev"] == "ledger.round"]
    assert rows[0]["nan"] == 0
    assert rows[1]["nan"] == 1
    assert any(v != v for v in rows[1]["checksums"].values())


def test_nan_injection_warn_continues(tmp_path, monkeypatch):
    from hpnn_tpu.train import driver

    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.setenv("HPNN_NUMERICS", "warn")
    monkeypatch.setenv("HPNN_FUSE_CHUNK", "2")
    obs._reset_for_tests()
    _poison_second_chunk(monkeypatch)
    assert driver.train_kernel(_conf(tmp_path)) is True
    evs = [r["ev"] for r in _read(tmp_path / "m.jsonl")]
    assert "numerics.nan" in evs
    assert "round.end" in evs                      # the round finished
    assert probes.last_verdict()["clean"] is False


def test_bad_mode_falls_back_to_warn(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("HPNN_NUMERICS", "explode")
    obs._reset_for_tests()
    assert probes.mode() == "warn"
    assert "unknown HPNN_NUMERICS" in capsys.readouterr().err


# ------------------------------------------------ divergence sentinel
def test_divergence_check_verdicts():
    from hpnn_tpu.parallel import dist, dp

    # single process: identity gather, no findings possible
    assert dp.divergence_check(["w0"], [1.0], [1e-12]) == []
    orig = dist.allgather_checksums
    try:
        # two simulated ranks disagreeing on w1 only
        dist.allgather_checksums = lambda v: np.stack([
            np.asarray(v, float),
            np.asarray(v, float) + np.array([0.0, 1e-6])])
        found = dp.divergence_check(["w0", "w1"], [1.0, 2.0],
                                    [1e-12, 1e-12])
        assert [f["tensor"] for f in found] == ["w1"]
        assert found[0]["spread"] == pytest.approx(1e-6)
        assert found[0]["values"] == pytest.approx([2.0, 2.0 + 1e-6])
        # within tolerance: clean
        dist.allgather_checksums = lambda v: np.stack([
            np.asarray(v, float), np.asarray(v, float) + 1e-14])
        assert dp.divergence_check(["w0"], [1.0], [1e-12]) == []
        # all-NaN column agrees (numerics.nan covers it); mixed diverges
        dist.allgather_checksums = lambda v: np.array(
            [[np.nan], [np.nan]])
        assert dp.divergence_check(["w0"], [np.nan], [1e-12]) == []
        dist.allgather_checksums = lambda v: np.array([[1.0], [np.nan]])
        found = dp.divergence_check(["w0"], [1.0], [1e-12])
        assert found and found[0]["spread"] != found[0]["spread"]
    finally:
        dist.allgather_checksums = orig


def test_divergence_sentinel_aborts(tmp_path, monkeypatch):
    from hpnn_tpu.parallel import dist

    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.setenv("HPNN_FLIGHT", str(tmp_path / "flight.jsonl"))
    monkeypatch.setenv("HPNN_NUMERICS", "abort")
    obs._reset_for_tests()
    monkeypatch.setattr(
        dist, "allgather_checksums",
        lambda v: np.stack([np.asarray(v, float),
                            np.asarray(v, float) + 1e-6]))
    with pytest.raises(NumericsError, match="divergence"):
        probes.check_weights(_kernel().weights, step=1, where="unit")
    recs = _read(tmp_path / "m.jsonl")
    div = [r for r in recs if r["ev"] == "numerics.divergence"]
    assert div and set(div[0]["tensors"]) == {"w0", "w1"}
    assert div[0]["detail"][0]["tol"] == 1e-12
    assert (tmp_path / "flight.jsonl").exists()
    assert probes.last_verdict()["divergent"] is True


# ----------------------------------------------------------- CLI path
def test_cli_abort_exits_nonzero(tmp_path):
    """AC: HPNN_NUMERICS=abort exits non-zero through the real CLI."""
    _conf(tmp_path)     # writes tmp_path/samples
    (tmp_path / "nn.conf").write_text(
        "[name] T\n[type] ANN\n[init] generate\n[seed] 1\n"
        "[input] 8\n[hidden] 5\n[output] 2\n[train] BP\n"
        "[sample_dir] ./samples\n[test_dir] ./samples\n")
    script = tmp_path / "drive.py"
    script.write_text(textwrap.dedent("""\
        import sys
        sys.path.insert(0, sys.argv[2])
        import jax.numpy as jnp
        from hpnn_tpu.train import loop

        orig = loop.train_epoch_lax
        calls = {"n": 0}

        def poisoned(w, m0, Xc, Tc, *args, **kwargs):
            out_w, stats = orig(w, m0, Xc, Tc, *args, **kwargs)
            calls["n"] += 1
            if calls["n"] == 2:
                out_w = (out_w[0].at[0, 0].set(jnp.nan),) \\
                    + tuple(out_w[1:])
            return out_w, stats

        loop.train_epoch_lax = poisoned
        from hpnn_tpu.cli import train_nn
        sys.exit(train_nn.main(
            ["--numerics", "abort", "--ledger", "led.jsonl",
             sys.argv[1]]))
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu", HPNN_FUSE_CHUNK="2",
               HPNN_FLIGHT="flight.jsonl")
    env.pop("HPNN_METRICS", None)
    env.pop("HPNN_NUMERICS", None)
    proc = subprocess.run(
        [sys.executable, str(script), "nn.conf", ROOT],
        cwd=tmp_path, env=env, capture_output=True, text=True,
        timeout=240)
    assert proc.returncode != 0
    assert "numerics sentinel abort" in proc.stderr
    assert "Traceback" not in proc.stderr
    # the postmortem artifacts landed: flight dump + partial ledger
    assert (tmp_path / "flight.jsonl").exists()
    rows = [r for r in _read(tmp_path / "led.jsonl")
            if r["ev"] == "ledger.round"]
    assert rows and rows[0]["nan"] == 0


def test_run_nn_ledger_flag_writes_eval_row(tmp_path, monkeypatch):
    """run_nn carries the same --ledger/--numerics twins: an eval run
    appends the eval checksum row."""
    from hpnn_tpu.cli import run_nn
    from hpnn_tpu.train import driver

    conf = _conf(tmp_path)
    work = tmp_path / "work"
    work.mkdir()
    monkeypatch.chdir(work)
    assert driver.train_kernel(conf)
    (work / "kernel.opt").write_text("")
    with open(work / "kernel.opt", "w") as fp:
        from hpnn_tpu import config as config_mod

        config_mod.dump_kernel(conf, fp)
    (work / "nn.conf").write_text(
        "[name] T\n[type] ANN\n[init] kernel.opt\n[seed] 1\n"
        "[input] 8\n[hidden] 5\n[output] 2\n[train] BP\n"
        f"[sample_dir] {conf.samples}\n[test_dir] {conf.tests}\n")
    try:
        assert run_nn.main(
            ["--ledger", str(work / "eval.jsonl"), "--numerics", "warn",
             "nn.conf"]) == 0
    finally:
        # the CLI twins write the env vars; clear them for later tests
        probes.configure_mode(None)
        ledger.configure(None)
    obs._reset_for_tests()
    rows = [r for r in _read(work / "eval.jsonl")
            if r["ev"] == "ledger.round"]
    assert rows and rows[-1]["where"] == "eval"


def test_cli_rejects_bad_numerics_mode():
    from hpnn_tpu.cli import common

    assert common.validate_long_opts({"numerics": "warn"})
    assert common.validate_long_opts({"numerics": "abort"})
    assert not common.validate_long_opts({"numerics": "explode"})
    assert not common.validate_long_opts({"numerics": True})


# -------------------------------------------------------------- serve
def test_serve_health_carries_numerics_verdict(tmp_path, monkeypatch):
    from hpnn_tpu import serve

    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.setenv("HPNN_PROBES", "1")
    obs._reset_for_tests()
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    try:
        sess.register_kernel("k", _kernel())
        sess.infer("k", np.zeros(8))
        out = sess.infer("k", np.full(8, np.nan))
        assert np.isnan(np.asarray(out)).any()
        doc = sess.health()
        num = doc["numerics"]
        assert num["mode"] == "warn" and num["probes"] is True
        kv = num["kernels"]["k"]
        assert kv["rows"] == 2 and kv["nan"] > 0 and kv["clean"] is False
    finally:
        sess.close()
    recs = _read(tmp_path / "m.jsonl")
    nan_counts = [r for r in recs if r["ev"] == "numerics.serve_nan"]
    assert nan_counts and nan_counts[0]["kernel"] == "k"


def test_serve_health_numerics_off_by_default(tmp_path, monkeypatch):
    from hpnn_tpu import serve

    for knob in ("HPNN_PROBES", "HPNN_NUMERICS", "HPNN_LEDGER"):
        monkeypatch.delenv(knob, raising=False)
    obs._reset_for_tests()
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    try:
        sess.register_kernel("k", _kernel())
        sess.infer("k", np.full(8, np.nan))     # census not armed
        assert sess.health()["numerics"] == {"mode": "off"}
    finally:
        sess.close()


# ------------------------------------------------------ export plumbing
def test_probe_gauges_reach_export(tmp_path, monkeypatch):
    from hpnn_tpu.obs import export

    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.setenv("HPNN_PROBES", "1")
    obs._reset_for_tests()
    probes.check_weights(_kernel().weights, step=1, where="unit")
    snap = obs.snapshot_state()
    assert "numerics.absmax" in snap["gauges"]
    assert snap["gauges"]["numerics.nan_count"] == 0
    body = export.render_prometheus(snap)
    assert "hpnn_numerics_absmax" in body
    health = export.health()
    assert health["numerics"]["clean"] is True
    assert health["numerics"]["where"] == "unit"


def test_obs_report_numerics_section(tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.setenv("HPNN_NUMERICS", "warn")
    obs._reset_for_tests()
    k = _kernel()
    probes.check_weights(k.weights, step=1, where="unit")
    bad = (np.asarray(k.weights[0]).copy(),) + tuple(k.weights[1:])
    bad[0][0, 0] = np.nan
    probes.check_weights(bad, step=2, where="unit")
    obs.flush()
    rep_mod = _load_tool("obs_report")
    rep = rep_mod.summarize(_read(tmp_path / "m.jsonl"))
    assert rep["numerics"]["checks"] == 2
    assert len(rep["numerics"]["alerts"]) == 1
    assert rep["numerics"]["alerts"][0]["ev"] == "numerics.nan"
    text = rep_mod.render(rep)
    assert "-- numerics --" in text
    assert "ALERT numerics.nan" in text


def test_configure_twins(tmp_path, monkeypatch):
    monkeypatch.delenv("HPNN_NUMERICS", raising=False)
    monkeypatch.delenv("HPNN_LEDGER", raising=False)
    obs._reset_for_tests()
    probes.configure_mode("abort")
    assert probes.mode() == "abort"
    ledger.configure(str(tmp_path / "led.jsonl"))
    assert ledger.enabled()
    assert probes.enabled()     # the ledger alone arms the checks
    probes.configure_mode(None)
    ledger.configure(None)
    assert not ledger.enabled()
    assert probes.mode() == "off"
