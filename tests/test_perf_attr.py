"""Performance attribution: spans, compiled-cost introspection, MFU
gauges, the serve compile-cache census, the span report, and the
Prometheus label escaping.

Spans (obs/spans.py) give the stream causality — parent/child ids make
queue wait separable from device time inside one request; cost
introspection (obs/cost.py) turns dispatch wall time into FLOP/s and
MFU via jax's AOT ``cost_analysis``.  Both ride the usual obs
contract: unset ⇒ no-ops, never stdout."""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import export
from hpnn_tpu.serve.batcher import Batcher

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _kernel():
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    return k


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _spans(path):
    return [r for r in _read(path) if r["ev"] == "span.end"]


# -------------------------------------------------------------- spans
def test_spans_disabled_everything_noops(tmp_path, monkeypatch):
    monkeypatch.delenv("HPNN_SPANS", raising=False)
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    obs._reset_for_tests()
    assert not obs.spans.enabled()
    sp = obs.spans.start("unit.x")
    assert sp is obs.spans._NULL_SPAN and sp.id is None
    obs.spans.finish(sp)                    # no raise
    obs.spans.finish(None)
    with obs.spans.span("unit.y") as s:
        assert s.id is None                 # shared null span
    assert list(tmp_path.iterdir()) == []


def test_span_ambient_nesting_and_record_shape(tmp_path, monkeypatch):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_SPANS", "1")
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    with obs.spans.span("unit.outer", tag="o"):
        with obs.spans.span("unit.inner"):
            pass
    recs = _spans(sink)
    # the inner span finishes (and emits) first
    assert [r["name"] for r in recs] == ["unit.inner", "unit.outer"]
    inner, outer = recs
    assert outer["parent"] is None
    assert inner["parent"] == outer["span"]
    assert outer["tag"] == "o"
    for r in recs:
        assert isinstance(r["span"], int) and r["span"] >= 1
        assert r["dt"] >= 0.0 and r["t0"] >= 0.0
    # honest nesting: the child's interval sits inside the parent's
    assert inner["t0"] >= outer["t0"] - 2e-6
    assert inner["t0"] + inner["dt"] <= outer["t0"] + outer["dt"] + 2e-6
    # ... and each finished span fed its span.<name> aggregate
    aggs = obs.snapshot_state()["aggregates"]
    assert aggs["span.unit.outer"]["n"] == 1
    assert aggs["span.unit.inner"]["n"] == 1


def test_span_failed_field_on_exception(tmp_path, monkeypatch):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_SPANS", "1")
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    with pytest.raises(RuntimeError):
        with obs.spans.span("unit.bad"):
            raise RuntimeError("boom")
    (rec,) = _spans(sink)
    assert rec["failed"] == "RuntimeError"


def test_span_cross_thread_handoff(tmp_path, monkeypatch):
    """start/finish never touch the ambient stack — a child opened on
    another thread parents to the explicitly-passed root."""
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_SPANS", "1")
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    root = obs.spans.start("unit.root")

    def worker():
        child = obs.spans.start("unit.child", parent=root)
        obs.spans.finish(child)

    t = threading.Thread(target=worker)
    t.start()
    t.join(timeout=10)
    obs.spans.finish(root, extra=1)
    by = {r["name"]: r for r in _spans(sink)}
    assert by["unit.child"]["parent"] == by["unit.root"]["span"]
    assert by["unit.root"]["extra"] == 1
    # finish is idempotent: closing again emits nothing new
    obs.spans.finish(root)
    assert len(_spans(sink)) == 2


def test_spans_fileless_activation(monkeypatch):
    """HPNN_SPANS alone (no metrics sink) arms in-memory aggregation,
    like HPNN_FLIGHT does — spans must not need HPNN_METRICS."""
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    monkeypatch.setenv("HPNN_SPANS", "1")
    obs._reset_for_tests()
    assert obs.enabled() and obs.sink_path() is None
    with obs.spans.span("unit.fileless"):
        pass
    assert obs.snapshot_state()["aggregates"]["span.unit.fileless"][
        "n"] == 1


# --------------------------------------------------------------- cost
def _mm(a, b):
    return a @ b


def test_cost_catalog_and_perf_gauges(tmp_path, monkeypatch):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_COST", "1")
    monkeypatch.setenv("HPNN_PEAK_FLOPS", "1e9")
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    a = np.zeros((8, 16), np.float32)
    b = np.zeros((16, 8), np.float32)
    obs.cost.analyze_fn("unit.mm", _mm, a, b, units=8, body="xla")
    cat = obs.cost.catalog()
    assert cat["unit.mm"]["flops"] and cat["unit.mm"]["flops"] > 0
    assert cat["unit.mm"]["units"] == 8
    # a second analyze is a memo hit — ONE compile.cost record total
    obs.cost.analyze_fn("unit.mm", _mm, a, b, units=8)
    costs = [r for r in _read(sink) if r["ev"] == "compile.cost"]
    assert len(costs) == 1
    rec = costs[0]
    assert rec["exe"] == "unit.mm" and rec["kind"] == "event"
    assert rec["flops"] == cat["unit.mm"]["flops"]
    assert rec["units"] == 8 and rec["body"] == "xla"
    assert rec["compile_s"] >= 0.0

    assert obs.cost.peak_flops() == 1e9
    obs.cost.record_dispatch("unit.mm", 0.01)
    gauges = obs.snapshot_state()["gauges"]
    fps = cat["unit.mm"]["flops"] / 0.01
    assert gauges["perf.flops_per_s"] == pytest.approx(fps)
    assert gauges["perf.mfu"] == pytest.approx(fps / 1e9)
    if cat["unit.mm"]["bytes"]:
        assert gauges["perf.bytes_per_s"] == pytest.approx(
            cat["unit.mm"]["bytes"] / 0.01)
    # the gauge records carry the attributing exe field
    perf = [r for r in _read(sink) if r["ev"].startswith("perf.")]
    assert perf and all(r["exe"] == "unit.mm" for r in perf)
    # units scale the cataloged cost: double the work, double the rate
    obs.cost.record_dispatch("unit.mm", 0.01, units=16)
    assert obs.snapshot_state()["gauges"][
        "perf.flops_per_s"] == pytest.approx(2 * fps)


def test_cost_error_is_cached_never_raised(tmp_path, monkeypatch):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_COST", "1")
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()

    def hostile(x):
        return float(x)        # TracerConversionError under jit

    obs.cost.analyze_fn("unit.bad", hostile, np.zeros(4), units=2)
    assert obs.cost.catalog()["unit.bad"]["flops"] is None
    obs.cost.analyze_fn("unit.bad", hostile, np.zeros(4), units=2)
    costs = [r for r in _read(sink) if r["ev"] == "compile.cost"]
    assert len(costs) == 1 and "error" in costs[0]
    # a costless entry produces no perf gauges and never raises
    obs.cost.record_dispatch("unit.bad", 0.01)
    obs.cost.record_dispatch("unit.unknown", 0.01)
    assert "perf.flops_per_s" not in (obs.snapshot_state() or
                                      {"gauges": {}})["gauges"]


def test_cost_disabled_noop(monkeypatch):
    monkeypatch.delenv("HPNN_COST", raising=False)
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    obs._reset_for_tests()
    obs.cost.analyze_fn("unit.off", _mm, np.zeros((2, 2)),
                        np.zeros((2, 2)))
    obs.cost.record_dispatch("unit.off", 0.01)
    assert obs.cost.catalog() == {}


# -------------------------------------------------------------- serve
def test_serve_request_span_lifecycle(tmp_path, monkeypatch):
    """One infer = a serve.request root with serve.queue and
    serve.dispatch children; queue wait and device time are separable
    and their sum stays inside the request."""
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_SPANS", "1")
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("k", _kernel())
    sess.infer("k", np.zeros(8))
    sess.close()
    by = {}
    for r in _spans(sink):
        by.setdefault(r["name"], r)
    req = by["serve.request"]
    assert by["serve.queue"]["parent"] == req["span"]
    assert by["serve.dispatch"]["parent"] == req["span"]
    assert by["serve.dispatch"]["rows"] == 1
    assert (by["serve.queue"]["dt"] + by["serve.dispatch"]["dt"]
            <= req["dt"] + 5e-5)


def test_serve_queue_deadline_span(tmp_path, monkeypatch):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_SPANS", "1")
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    now = [100.0]
    b = Batcher(lambda payloads: [None] * len(payloads),
                clock=lambda: now[0], start=False)
    b.submit("x", timeout_s=1.0)
    now[0] = 102.0                      # expire in queue
    assert b.drain_once() == 0
    b.close()
    (rec,) = [r for r in _spans(sink) if r["name"] == "serve.queue"]
    assert rec["failed"] == "DeadlineExceeded"


def test_engine_cache_stats_and_healthz(tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    obs._reset_for_tests()
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("k", _kernel())    # warmup fills every bucket
    stats = sess.engine.cache_stats()
    assert len(stats) == len(sess.engine.buckets)
    for key, s in stats.items():
        assert key.startswith("k/v0/b")
        assert s["misses"] == 1 and s["compile_s"] >= 0.0
    hits_before = sum(s["hits"] for s in stats.values())
    sess.infer("k", np.zeros(8))            # cache hit, no new miss
    stats = sess.engine.cache_stats()
    assert sum(s["hits"] for s in stats.values()) == hits_before + 1
    assert sum(s["misses"] for s in stats.values()) == len(stats)
    assert sess.health()["compile_cache"] == stats
    sess.close()


def test_serve_cost_gauges_reach_metrics(tmp_path, monkeypatch):
    """Compiled-mode serve: each bucket executable is cost-cataloged
    at warmup (compile.cost) and every dispatch updates the perf
    gauges, visible in a /metrics render."""
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_COST", "1")
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0,
                         mode="compiled")
    sess.register_kernel("k", _kernel())
    sess.infer("k", np.zeros(8))
    sess.close()
    exes = {r["exe"] for r in _read(sink) if r["ev"] == "compile.cost"}
    assert exes == {f"serve.k.v0.b{b}" for b in sess.engine.buckets}
    body = export.metrics_body().decode()
    assert "hpnn_perf_flops_per_s" in body
    assert "hpnn_perf_mfu" in body


# ------------------------------------------------------------- report
def test_obs_report_spans_on_recorded_run(tmp_path, monkeypatch,
                                          capsys):
    """The acceptance read-back: record a serve+train run with spans
    on, then --spans renders a tree where every parent's children sum
    to ≤ its own duration and queue wait is its own line."""
    from hpnn_tpu.train import driver

    from tests.test_obs import _conf

    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_SPANS", "1")
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("k", _kernel())
    sess.infer("k", np.zeros(8))
    sess.close()
    assert driver.train_kernel(_conf(tmp_path))
    obs.flush()

    rpt = _load_tool("obs_report")
    events = rpt.load_events(str(sink))
    spans = rpt.collect_spans(events)
    names = {s["name"] for s in spans}
    assert {"serve.request", "serve.queue", "serve.dispatch",
            "train.round", "train.chunk"} <= names

    def walk(node):
        assert node["child_s"] <= node["dt"] + 1e-4, node
        for c in node["children"]:
            walk(c)

    roots = rpt.span_tree(spans)
    assert roots
    for r in roots:
        walk(r)
    text = rpt.render_spans(events, top=5)
    assert "serve.queue" in text and "train.chunk" in text
    assert "-- slowest" in text
    assert rpt.main([str(sink), "--spans"]) == 0
    out = capsys.readouterr().out
    assert "== span report ==" in out

    # the recorded sink also satisfies the span/cost schema lint
    lint = _load_tool("check_obs_catalog")
    assert lint.lint_perf(str(sink)) == []


# ------------------------------------------------------------- export
def _parse_label_value(s):
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\":
            out.append({"n": "\n", "r": "\r", '"': '"',
                        "\\": "\\"}[s[i + 1]])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def test_export_label_escaping_round_trip(tmp_path, monkeypatch):
    assert export._metric_name("perf.mfu") == "hpnn_perf_mfu"
    val = 'a"b\\c\nd\re'
    esc = export._escape_label_value(val)
    assert "\n" not in esc                  # exposition is line-based
    assert "\r" not in esc                  # splitlines() splits on \r
    assert _parse_label_value(esc) == val
    rendered = export._render_labels({"exe": val, "quantile": 0.5})
    assert rendered.startswith("{") and rendered.endswith("}")
    assert export._render_labels({}) == ""

    # full exposition round trip: the default 0.0.4 body must stay
    # exemplar-free (that format has no exemplar syntax — a suffix
    # breaks real Prometheus scrapes); the negotiated OpenMetrics
    # body carries the tail-sampler mark on a histogram bucket line.
    # Parse every sample line of both back, worst-case trace id
    # included.
    import re

    from hpnn_tpu.obs import registry

    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    obs._reset_for_tests()
    obs.gauge("perf.mfu", 0.25)
    obs.observe("unit.lat", [1.0, 2.0])
    trace = 'tr"ace\r1'                     # worst-case id round-trips
    registry.exemplar("unit.lat", 2.0, trace)
    snap = obs.snapshot_state()
    sample = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
        r'(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*)\})?'
        r' (-?[0-9.eE+-]+|NaN)'
        r'(?: # \{trace_id="((?:[^"\\]|\\.)*)"\} (-?[0-9.eE+-]+|NaN))?$')

    text = export.render_prometheus(snap)
    assert "hpnn_perf_mfu 0.25" in text
    parsed = 0
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert line.startswith("# TYPE "), line
            continue
        m = sample.match(line)
        assert m, line
        assert m.group(4) is None, line     # 0.0.4: never an exemplar
        float(m.group(3))
        for lab in re.finditer(r'="((?:[^"\\]|\\.)*)"',
                               m.group(2) or ""):
            _parse_label_value(lab.group(1))
        parsed += 1
    assert parsed >= 5

    om = export.render_openmetrics(snap)
    assert om.endswith("# EOF\n")
    parsed = exemplars = 0
    for line in om.strip().splitlines():
        if line.startswith("#"):
            assert (line.startswith("# TYPE ")
                    or line == "# EOF"), line
            continue
        m = sample.match(line)
        assert m, line
        float(m.group(3))
        for lab in re.finditer(r'="((?:[^"\\]|\\.)*)"',
                               m.group(2) or ""):
            _parse_label_value(lab.group(1))
        if m.group(4) is not None:
            assert m.group(1).endswith("_bucket")   # legal carrier
            assert _parse_label_value(m.group(4)) == trace
            assert float(m.group(5)) == 2.0
            exemplars += 1
        parsed += 1
    assert parsed >= 5
    assert exemplars >= 1
