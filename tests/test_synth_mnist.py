"""synth_mnist generator + the act-gradient stability it exposed.

The 60k-scale MNIST protocol runs on unnormalized 0-255 pixels
(ref: prepare_mnist.c:49-52), which drive first-layer pre-activations
to |z| ~ 300 — any exp in the backward pass overflows f32 there.
"""

import struct

import numpy as np

from hpnn_tpu.tools import synth_mnist


def test_idx_files_roundtrip_through_pmnist(tmp_path, capsys, monkeypatch):
    synth_mnist.main([str(tmp_path), "--train", "30", "--test", "10",
                      "--seed", "3"])
    with open(tmp_path / "train_images", "rb") as fp:
        magic, n, r, c = struct.unpack(">IIII", fp.read(16))
    assert (magic, n, r, c) == (0x803, 30, 28, 28)
    with open(tmp_path / "test_labels", "rb") as fp:
        magic, n = struct.unpack(">II", fp.read(8))
    assert (magic, n) == (0x801, 10)

    # the real pmnist converter consumes them unmodified
    from hpnn_tpu.tools import pmnist

    (tmp_path / "samples").mkdir()
    (tmp_path / "tests").mkdir()
    monkeypatch.chdir(tmp_path)
    assert pmnist.main(["samples", "tests"]) == 0
    assert len(list((tmp_path / "samples").iterdir())) == 30
    assert len(list((tmp_path / "tests").iterdir())) == 10
    s1 = (tmp_path / "samples" / "s00001.txt").read_text()
    assert s1.startswith("[input] 784\n")
    assert "[output] 10" in s1


def test_generator_deterministic(tmp_path):
    a = tmp_path / "a"
    b = tmp_path / "b"
    synth_mnist.main([str(a), "--train", "20", "--test", "5", "--seed", "9"])
    synth_mnist.main([str(b), "--train", "20", "--test", "5", "--seed", "9"])
    for f in ("train_images", "train_labels", "test_images", "test_labels"):
        assert (a / f).read_bytes() == (b / f).read_bytes()


def test_classes_distinguishable():
    """Mean rendered image per class differs clearly across classes —
    the task is learnable."""
    rng = np.random.RandomState(0)
    means = []
    for d in range(10):
        imgs = np.stack([synth_mnist.render(d, rng) for _ in range(12)])
        means.append(imgs.mean(axis=0).ravel() / 255.0)
    means = np.stack(means)
    for i in range(10):
        for j in range(i + 1, 10):
            assert np.abs(means[i] - means[j]).mean() > 0.01


def test_act_grad_finite_at_pixel_scale_f32():
    """grad(act) stays finite for |z| ~ 300 in f32 (custom_jvp uses the
    reference's dact identity, ref: src/ann.c:883-888); the naive exp
    backward would be NaN at z=-212."""
    import jax
    import jax.numpy as jnp

    from hpnn_tpu.models import ann
    from hpnn_tpu.parallel import dp

    z = jnp.asarray([-300.0, -88.5, 0.0, 88.5, 300.0], dtype=jnp.float32)
    g = jax.grad(lambda v: jnp.sum(ann.act(v)))(z)
    assert bool(jnp.isfinite(g).all())
    # full batch-step gradient on pixel-scale inputs
    rng = np.random.RandomState(0)
    w = (
        jnp.asarray(rng.uniform(-0.036, 0.036, (16, 64)), dtype=jnp.float32),
        jnp.asarray(rng.uniform(-0.1, 0.1, (4, 16)), dtype=jnp.float32),
    )
    X = jnp.asarray(rng.uniform(0, 255, (8, 64)), dtype=jnp.float32)
    T = jnp.asarray(np.full((8, 4), -1.0), dtype=jnp.float32)
    grads = jax.grad(dp.batch_loss)(w, X, T, model="ann")
    assert all(bool(jnp.isfinite(g).all()) for g in grads)


def test_act_value_bit_identical():
    """custom_jvp must not change the primal: same bits as the raw
    exp form (parity mode depends on it)."""
    import jax.numpy as jnp

    from hpnn_tpu.models import ann

    x = jnp.linspace(-30, 30, 1001, dtype=jnp.float64) \
        if jnp.zeros(1).dtype == jnp.float64 else \
        jnp.linspace(-30, 30, 1001, dtype=jnp.float32)
    raw = 2.0 / (1.0 + jnp.exp(-x)) - 1.0
    np.testing.assert_array_equal(np.asarray(ann.act(x)), np.asarray(raw))
