"""Tail-latency forensics: head sampling + retro-promotion
(obs/forensics.py), capture capsules (obs/triggers.py), histogram
exemplars, the slowest-N blame report (tools/tail_report.py), and the
``--forensics`` schema lint.

The plane's contract is the usual obs one — unset ⇒ constant-time
no-ops, never stdout — plus its own: the coin flip may miss a slow
request but the promotion path must still emit its root; a capture
runs at most one at a time and never reuses a capsule path."""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import forensics, registry, spans, triggers

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _kernel():
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    return k


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _arm(monkeypatch, tmp_path, **env):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    for key, val in env.items():
        monkeypatch.setenv(key, str(val))
    obs._reset_for_tests()
    return tmp_path / "m.jsonl"


# ------------------------------------------------------------- sampler
def test_sampler_disabled_everything_noops(monkeypatch):
    monkeypatch.delenv("HPNN_SAMPLE", raising=False)
    obs._reset_for_tests()
    assert not forensics.enabled()
    sp = forensics.request_span("serve.request")
    assert sp is spans._NULL_SPAN
    forensics.finish(sp)                    # no raise
    forensics.finish(None)
    assert forensics.health_doc() == {"armed": False}


def test_sampler_bad_rate_disarms_with_warning(monkeypatch, capsys):
    monkeypatch.setenv("HPNN_SAMPLE", "2.0")
    obs._reset_for_tests()
    assert not forensics.enabled()
    assert "HPNN_SAMPLE" in capsys.readouterr().err
    # memoized: the second call never re-reads the env
    monkeypatch.setenv("HPNN_SAMPLE", "0.5")
    assert not forensics.enabled()


def test_sampler_bad_secondary_knob_keeps_defaults(monkeypatch,
                                                   capsys):
    """A malformed HPNN_SAMPLE_RING / _SLOW_MS must not disarm a
    valid rate: the warning names the offending variable and the knob
    falls back to its documented default."""
    monkeypatch.setenv("HPNN_SAMPLE", "0.5")
    monkeypatch.setenv("HPNN_SAMPLE_RING", "many")
    monkeypatch.setenv("HPNN_SAMPLE_SLOW_MS", "soon")
    obs._reset_for_tests()
    assert forensics.enabled()
    doc = forensics.health_doc()
    assert doc["armed"] and doc["rate"] == 0.5
    cfg = forensics._config()
    assert cfg["ring_n"] == forensics.DEFAULT_RING
    assert cfg["slow_s"] == 0.0
    err = capsys.readouterr().err
    assert "HPNN_SAMPLE_RING" in err and "'many'" in err
    assert "HPNN_SAMPLE_SLOW_MS" in err


def test_sampled_request_emits_root_and_exemplar(tmp_path, monkeypatch):
    """rate=1 ⇒ every request gets a real span tree (sampled tag) and
    marks a histogram exemplar; the root lands in the capsule ring."""
    sink = _arm(monkeypatch, tmp_path, HPNN_SAMPLE="1")
    sp = forensics.request_span("serve.request", trace="tr1")
    assert isinstance(sp, spans.Span)
    forensics.finish(sp)
    (rec,) = [r for r in _read(sink) if r["ev"] == "span.end"]
    assert rec["name"] == "serve.request"
    assert rec["sampled"] is True
    assert forensics.recent_spans()[-1]["span"] == rec["span"]
    snap = obs.snapshot_state()
    ex = snap["aggregates"]["span.serve.request"]["exemplars"]
    assert any(v["trace_id"] == "tr1" for v in ex.values())
    # the bare name has no timer feeding it here, so no degenerate
    # all-zero aggregate may be minted for the exemplar alone
    assert "serve.request" not in snap["aggregates"]
    assert forensics.health_doc()["recent_spans"] >= 1


def test_unsampled_probe_promotes_when_slow(tmp_path, monkeypatch):
    """A probe (coin flip lost) slower than the HPNN_SAMPLE_SLOW_MS
    floor is retro-promoted: a backdated root with ``promoted`` set
    plus a forensics.tail_promote count."""
    sink = _arm(monkeypatch, tmp_path, HPNN_SAMPLE="0.000001",
                HPNN_SAMPLE_SLOW_MS="1")
    fast = forensics.request_span("serve.request")
    assert isinstance(fast, forensics._Probe)
    forensics.finish(fast)                  # under the floor: silent
    slow = forensics.request_span("serve.request", trace="tr2")
    time.sleep(0.01)
    forensics.finish(slow)
    recs = _read(sink)
    (root,) = [r for r in recs if r["ev"] == "span.end"]
    assert root["promoted"] is True
    assert root["dt"] >= 0.01
    (promote,) = [r for r in recs
                  if r["ev"] == "forensics.tail_promote"]
    assert promote["root"] == "serve.request"
    assert forensics.recent_spans()[-1]["promoted"] is True


def test_double_finish_is_idempotent(tmp_path, monkeypatch):
    sink = _arm(monkeypatch, tmp_path, HPNN_SAMPLE="1")
    sp = forensics.request_span("serve.request")
    forensics.finish(sp)
    forensics.finish(sp)
    assert len([r for r in _read(sink)
                if r["ev"] == "span.end"]) == 1


def test_exemplar_noop_when_inactive_or_traceless(monkeypatch):
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    obs._reset_for_tests()
    registry.exemplar("unit.lat", 1.0, "tr")     # inactive: no raise
    monkeypatch.setenv("HPNN_SAMPLE", "1")
    obs._reset_for_tests()
    obs.observe("unit.lat", 1.0)
    registry.exemplar("unit.lat", 1.0, "")       # empty trace ignored
    agg = obs.snapshot_state()["aggregates"]["unit.lat"]
    assert not agg.get("exemplars")


def test_metrics_exemplars_need_openmetrics_negotiation(tmp_path,
                                                        monkeypatch):
    """The default 0.0.4 body must stay exemplar-free (the format has
    no exemplar syntax — a suffix breaks real Prometheus scrapes);
    the negotiated OpenMetrics body carries the mark on the histogram
    bucket line it landed in and terminates with ``# EOF``."""
    from hpnn_tpu.obs import export

    _arm(monkeypatch, tmp_path, HPNN_SAMPLE="1")
    obs.observe("serve.request", [0.01, 0.02, 0.04])
    registry.exemplar("serve.request", 0.04, "abc123")
    snap = obs.snapshot_state()
    text = export.render_prometheus(snap)
    assert " # {" not in text
    om = export.render_openmetrics(snap)
    tagged = [ln for ln in om.splitlines()
              if ' # {trace_id="abc123"} ' in ln]
    assert tagged and 'le=' in tagged[0] and "_bucket" in tagged[0]
    assert om.endswith("# EOF\n")
    # negotiation: the Accept header picks the body + content type
    assert not export.wants_openmetrics("text/plain")
    body, ctype = export.metrics_response("application/openmetrics-text")
    assert ctype == export.OPENMETRICS_CONTENT_TYPE
    assert b'trace_id="abc123"' in body
    body, ctype = export.metrics_response(None)
    assert ctype == export.TEXT_CONTENT_TYPE
    assert b" # {" not in body


# ------------------------------------------------------------ capsules
def _arm_capsules(monkeypatch, tmp_path, **extra):
    capdir = tmp_path / "capsules"
    env = {"HPNN_SAMPLE": "1", "HPNN_CAPSULE_DIR": str(capdir),
           "HPNN_CAPSULE_PROFILE_MS": "0",
           "HPNN_CAPSULE_COOLDOWN_S": "0"}
    env.update(extra)
    sink = _arm(monkeypatch, tmp_path, **env)
    return sink, capdir


def test_capture_capsule_contents_and_census(tmp_path, monkeypatch):
    sink, capdir = _arm_capsules(monkeypatch, tmp_path)
    sp = forensics.request_span("serve.request", trace="tr3")
    forensics.finish(sp)
    man = triggers.capture("unit")
    assert man is not None
    assert set(man["files"]) >= {"spans.jsonl", "gauges.json",
                                 "health.json"}
    assert man["spans"] == 1
    assert man["profile"] is None           # PROFILE_MS=0 skips it
    ring = _read(os.path.join(man["capsule"], "spans.jsonl"))
    assert ring[0]["name"] == "serve.request"
    census = triggers.health_doc()
    assert census["captures"] == 1 and not census["in_flight"]
    recs = _read(sink)
    (begin,) = [r for r in recs if r["ev"] == "forensics.capture"]
    (done,) = [r for r in recs if r["ev"] == "forensics.capture_done"]
    assert begin["capsule"] == done["capsule"] == man["capsule"]
    assert done["spans"] == 1


def test_capture_cooldown_skips_and_counts(tmp_path, monkeypatch):
    sink, _capdir = _arm_capsules(monkeypatch, tmp_path,
                                  HPNN_CAPSULE_COOLDOWN_S="3600")
    first = triggers.capture("unit")
    assert first is not None
    assert triggers.capture("unit") is None      # cooling down
    census = triggers.health_doc()
    assert census["skipped"].get("cooldown") == 1
    (skip,) = [r for r in _read(sink)
               if r["ev"] == "forensics.capture_skipped"]
    assert skip["reason"] == "cooldown"


def test_capsule_paths_never_reused(tmp_path, monkeypatch):
    _sink, _capdir = _arm_capsules(monkeypatch, tmp_path)
    paths = {triggers.capture("unit")["capsule"] for _ in range(3)}
    assert len(paths) == 3


def test_capsule_assembly_crash_releases_in_flight(tmp_path,
                                                   monkeypatch):
    """An unexpected exception mid-assembly must not wedge the
    at-most-one-in-flight slot forever — the alert path assembles on
    a daemon thread nobody joins, so a leaked slot would silently
    suppress every future capture as ``in_flight``."""
    _sink, _capdir = _arm_capsules(monkeypatch, tmp_path)

    def _boom(_reason):
        raise RuntimeError("flight ring exploded")

    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(triggers.flight, "dump", _boom)
        with pytest.raises(RuntimeError):
            triggers.capture("unit")
    assert not triggers.health_doc()["in_flight"]
    assert triggers.capture("unit") is not None   # slot released


def test_capsule_spans_survive_nonserializable_field(tmp_path,
                                                     monkeypatch):
    """spans.jsonl dumps with ``default=str`` — an exotic span field
    (anything the sink's own ``_to_py`` stringified) must not kill the
    capsule assembly."""
    _sink, _capdir = _arm_capsules(monkeypatch, tmp_path)
    sp = forensics.request_span("serve.request", trace="tr9",
                                blob=object())
    forensics.finish(sp)
    man = triggers.capture("unit")
    assert man is not None and "spans.jsonl" in man["files"]
    ring = _read(os.path.join(man["capsule"], "spans.jsonl"))
    assert ring[0]["name"] == "serve.request"


def test_http_capture_status_codes(tmp_path, monkeypatch):
    monkeypatch.delenv("HPNN_CAPSULE_DIR", raising=False)
    obs._reset_for_tests()
    status, body = triggers.http_capture(None)
    assert status == 404 and "error" in body
    _sink, _capdir = _arm_capsules(monkeypatch, tmp_path,
                                   HPNN_CAPSULE_COOLDOWN_S="3600")
    status, body = triggers.http_capture({"reason": "why so slow"})
    assert status == 200
    assert body["manifest"]["reason"].startswith("manual:")
    status, body = triggers.http_capture(None)   # cooling down
    assert status == 429 and body["skipped"].get("cooldown") == 1


def test_alert_fire_triggers_capture(tmp_path, monkeypatch):
    """The wired loop without HTTP: an armed threshold rule breached
    by a gauge call admits an async capsule."""
    _sink, capdir = _arm_capsules(
        monkeypatch, tmp_path,
        HPNN_ALERTS="hot@unit.temp>10:for=0,cooldown=0,severity=warn")
    obs.gauge("unit.temp", 99.0)
    deadline = time.monotonic() + 5.0
    man_path = None
    while time.monotonic() < deadline and man_path is None:
        for dirpath, _dirs, files in os.walk(capdir):
            if "manifest.json" in files:
                man_path = os.path.join(dirpath, "manifest.json")
        time.sleep(0.02)
    assert man_path is not None
    with open(man_path) as fp:
        man = json.load(fp)
    assert man["reason"] == "alert:hot"
    assert man["alert"]["gauge"] == "unit.temp"


# --------------------------------------------------------- tail report
def test_tail_report_blames_the_slow_phase(tmp_path, monkeypatch):
    """Sampled serve traffic through a real Session: every request is
    a root, and the analyzer's per-phase split covers the root time
    (no phase, including gap, goes negative)."""
    sink = _arm(monkeypatch, tmp_path, HPNN_SAMPLE="1")
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=0.5)
    sess.register_kernel("k", _kernel())
    for _ in range(4):
        sess.infer("k", np.zeros(8))
    sess.close()
    obs.configure(None)
    tr = _load_tool("tail_report")
    rep = tr.analyze(tr.load_spans([str(sink)]), top=10)
    assert rep["requests"] == 4
    assert all(v >= 0.0 for v in rep["blame_pct"].values())
    assert abs(sum(rep["blame_pct"].values()) - 100.0) < 1.0
    slowest = rep["slowest"][0]
    assert slowest["sampled"] is True
    assert slowest["phases"]["dispatch"] >= 0.0


# --------------------------------------------------------------- lint
def _forensics_sink(tmp_path, monkeypatch):
    """A real armed run: one sampled root, one promotion, one capture
    — the accept fixture for lint_forensics."""
    sink, _capdir = _arm_capsules(monkeypatch, tmp_path,
                                  HPNN_SAMPLE_SLOW_MS="1")
    sp = forensics.request_span("serve.request", trace="tr4")
    forensics.finish(sp)
    assert triggers.capture("unit") is not None
    obs.configure(None)
    return sink


def test_lint_forensics_accepts_a_real_run(tmp_path, monkeypatch):
    sink = _forensics_sink(tmp_path, monkeypatch)
    lint = _load_tool("check_obs_catalog")
    assert lint.lint_forensics(str(sink)) == []


@pytest.mark.parametrize("mutate,needle", [
    (lambda r: r.update(ev="forensics.capture_skipped", reason="nap"),
     "reason"),
    (lambda r: r.update(ev="forensics.tail_promote", n=0, dt=0.1,
                        root="serve.request"),
     "n"),
    (lambda r: r.update(ev="forensics.capture_done", reason="x",
                        capsule="/nowhere", duration_s=0.1, files=1,
                        spans=0, profile=False),
     "paired"),
])
def test_lint_forensics_break_ladder(tmp_path, monkeypatch, mutate,
                                     needle):
    sink = _forensics_sink(tmp_path, monkeypatch)
    bad = {"kind": "count", "n": 1}
    mutate(bad)
    with open(sink, "a") as fp:
        fp.write(json.dumps(bad) + "\n")
    lint = _load_tool("check_obs_catalog")
    failures = lint.lint_forensics(str(sink))
    assert failures and any(needle in f for f in failures)


def test_lint_forensics_rejects_nonfinite_exemplar(tmp_path,
                                                   monkeypatch):
    sink = _forensics_sink(tmp_path, monkeypatch)
    rec = {"ev": "obs.summary", "kind": "summary", "uptime_s": 1.0,
           "counters": {}, "gauges": {},
           "aggregates": {"serve.request": {"n": 1, "exemplars": {
               "7": {"trace_id": "t", "value": "NaN"}}}}}
    with open(sink, "a") as fp:
        fp.write(json.dumps(rec) + "\n")
    lint = _load_tool("check_obs_catalog")
    assert any("finite" in f
               for f in lint.lint_forensics(str(sink)))


def test_lint_forensics_wants_an_armed_run(tmp_path):
    empty = tmp_path / "empty.jsonl"
    empty.write_text('{"ev": "obs.open", "kind": "meta"}\n')
    lint = _load_tool("check_obs_catalog")
    assert any("HPNN_SAMPLE" in f
               for f in lint.lint_forensics(str(empty)))
