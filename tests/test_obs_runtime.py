"""Runtime health telemetry: device samples, the flight recorder, the
live Prometheus export, and the extended serve health surface.

The JSONL sink (test_obs.py) answers "what happened"; this file covers
the *while-it-runs* and *after-it-died* surfaces — device gauges at
round boundaries, `/metrics` scraped under live traffic, `/healthz`
queue staleness, the bounded flight ring and its crash dumps, and the
cross-rank merge that joins per-rank sinks into one timeline."""

import http.client
import json
import os
import re
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import export, flight
from hpnn_tpu.serve.batcher import Batcher

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _kernel():
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    return k


# ------------------------------------------------------------- device
def test_device_sample_emits_gauges(tmp_path, monkeypatch):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    obs.device.sample("unit", step=3)
    recs = _read(sink)
    by = {r["ev"]: r for r in recs}
    # the always-available census gauges (HBM stats are backend-gated)
    for name in ("device.live_arrays", "device.live_array_bytes",
                 "device.compile_events", "device.compile_time_s"):
        assert name in by, sorted(by)
        assert by[name]["kind"] == "gauge"
        assert by[name]["phase"] == "unit"
        assert by[name]["step"] == 3


def test_device_sample_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    monkeypatch.delenv("HPNN_FLIGHT", raising=False)
    obs._reset_for_tests()
    obs.device.sample("unit")          # no sink, no raise, no files
    assert list(tmp_path.iterdir()) == []


def test_driver_round_samples_device_telemetry(tmp_path, monkeypatch):
    """The fused driver samples at round_start / chunk / round_end."""
    from hpnn_tpu.train import driver

    from tests.test_obs import _conf

    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    assert driver.train_kernel(_conf(tmp_path))
    recs = [r for r in _read(sink) if r["ev"] == "device.live_arrays"]
    phases = {r["phase"] for r in recs}
    assert {"round_start", "chunk", "round_end"} <= phases


# ------------------------------------------------------------- export
def test_snapshot_state_and_prometheus_grammar(tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    obs._reset_for_tests()
    obs.count("unit.hits", n=3)
    obs.gauge("unit.depth", 7.0)
    obs.observe("unit.lat", [1.0, 2.0, 3.0, 10.0])
    with obs.timer("unit.block"):
        pass
    snap = obs.snapshot_state()
    assert snap["counters"] == {"unit.hits": 3}
    assert snap["gauges"] == {"unit.depth": 7.0}
    assert snap["aggregates"]["unit.lat"]["n"] == 4

    text = export.render_prometheus(snap)
    assert "# TYPE hpnn_unit_hits_total counter" in text
    assert "hpnn_unit_hits_total 3" in text
    assert "# TYPE hpnn_unit_depth gauge" in text
    assert "hpnn_unit_depth 7" in text
    assert "# TYPE hpnn_unit_lat summary" in text
    assert "hpnn_unit_lat_sum 16" in text
    assert "hpnn_unit_lat_count 4" in text

    # exposition-format grammar: every sample line is NAME{labels} VALUE
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+-]+$")
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert line.startswith("# TYPE "), line
        else:
            assert sample.match(line), line

    # quantile estimates are monotone and inside [min, max]
    qs = [float(m.group(1)) for m in re.finditer(
        r'hpnn_unit_lat\{quantile="[0-9.]+"\} ([0-9.eE+-]+)', text)]
    assert len(qs) == 3
    assert qs == sorted(qs)
    assert 1.0 <= qs[0] and qs[-1] <= 10.0


def test_render_inactive_is_a_comment(monkeypatch):
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    obs._reset_for_tests()
    text = export.render_prometheus(obs.snapshot_state())
    assert text.startswith("#")


def test_standalone_export_server_fileless(monkeypatch):
    """--export-port without --metrics: the server activates in-memory
    aggregation; scrapes see data, /healthz reports no sink."""
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    obs._reset_for_tests()
    server = export.start_export_server(port=0)
    try:
        assert obs.enabled() and obs.sink_path() is None
        obs.count("unit.fileless", n=2)
        host, port = server.server_address[:2]
        cn = http.client.HTTPConnection(host, port, timeout=10)
        cn.request("GET", "/metrics")
        resp = cn.getresponse()
        assert resp.status == 200
        assert "version=0.0.4" in resp.getheader("Content-Type")
        body = resp.read().decode()
        assert "hpnn_unit_fileless_total 2" in body
        # export.listen itself lands in the aggregates? no — it is a
        # point event; but the health doc must see the active registry
        cn.request("GET", "/healthz")
        health = json.loads(cn.getresponse().read())
        assert health["metrics_active"] is True
        assert health["sink"] is None
        cn.request("GET", "/nope")
        assert cn.getresponse().read() and True
        cn.close()
    finally:
        export.stop_export_server(server)


def test_serve_metrics_round_trip_under_traffic(tmp_path, monkeypatch):
    """GET /metrics on the serving server returns valid exposition
    while requests flow, and the serve.request count matches."""
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    obs._reset_for_tests()
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("k", _kernel())
    sess.infer("k", np.zeros(8))   # at least one completed request
    server = serve.make_server(sess, port=0)
    host, port = server.server_address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                sess.infer("k", np.zeros(8))

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            cn = http.client.HTTPConnection(host, port, timeout=10)
            cn.request("GET", "/metrics")
            resp = cn.getresponse()
            assert resp.status == 200
            assert "version=0.0.4" in resp.getheader("Content-Type")
            live = resp.read().decode()
            cn.close()
        finally:
            stop.set()
            t.join(timeout=10)
        assert "# TYPE hpnn_serve_request summary" in live
        # a fresh scrape after traffic stopped: exact request count
        n = 5
        for _ in range(n):
            sess.infer("k", np.zeros(8))
        body = export.metrics_body().decode()
        m = re.search(r"^hpnn_serve_request_count (\d+)$", body,
                      re.MULTILINE)
        assert m and int(m.group(1)) >= n
    finally:
        server.shutdown()
        server.server_close()
        sess.close()


def test_serve_healthz_reports_queue_state(tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    obs._reset_for_tests()
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("k", _kernel())
    sess.infer("k", np.zeros(8))       # materialize the batcher
    server = serve.make_server(sess, port=0)
    host, port = server.server_address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        cn = http.client.HTTPConnection(host, port, timeout=10)
        cn.request("GET", "/healthz")
        health = json.loads(cn.getresponse().read())
        cn.close()
        assert health["status"] == "ok"
        assert health["kernels"] == ["k"]
        assert health["compiled"] == len(sess.engine.buckets)
        b = health["batchers"]["k"]
        assert b["depth"] == 0
        assert b["oldest_wait_s"] is None    # idle queue
        assert health["obs"]["metrics_active"] is True
    finally:
        server.shutdown()
        server.server_close()
        sess.close()


def test_export_health_carries_last_round(tmp_path, monkeypatch):
    from hpnn_tpu.train import driver

    from tests.test_obs import _conf

    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    obs._reset_for_tests()
    assert driver.train_kernel(_conf(tmp_path))
    h = export.health()
    assert h["last_round"]["ok"] is True
    assert h["last_round"]["mode"] == "fused"
    assert h["last_round"]["samples"] == 6


def test_batcher_oldest_age_fake_clock():
    now = [100.0]
    b = Batcher(lambda payloads: [None] * len(payloads),
                clock=lambda: now[0], start=False)
    assert b.oldest_age() is None
    b.submit("a", timeout_s=60.0)
    now[0] = 101.5
    b.submit("b", timeout_s=60.0)
    assert b.oldest_age() == pytest.approx(1.5)
    b.drain_once()
    assert b.oldest_age() is None
    b.close()


# ------------------------------------------------------------- flight
def test_flight_ring_bounded_and_fileless(tmp_path, monkeypatch):
    dump = tmp_path / "flight.jsonl"
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    monkeypatch.setenv("HPNN_FLIGHT", str(dump))
    monkeypatch.setenv("HPNN_FLIGHT_N", "8")
    obs._reset_for_tests()
    # arming the recorder activates the registry file-less
    assert obs.enabled()
    assert obs.sink_path() is None
    assert flight.enabled() and flight.dump_path() == str(dump)
    for i in range(30):
        obs.event("unit.tick", i=i)
    assert not dump.exists()           # memory-only until a trigger
    path = obs.flight.dump("manual")
    assert path == str(dump)
    recs = _read(dump)
    header = recs[0]
    assert header["ev"] == "flight.dump"
    assert header["reason"] == "manual"
    assert header["capacity"] == 8
    assert header["events"] == 8
    ticks = [r for r in recs[1:] if r["ev"] == "unit.tick"]
    # the ring kept exactly the LAST 8 events, oldest first
    assert [r["i"] for r in ticks] == list(range(22, 30))


def test_flight_cap_floor(tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_FLIGHT", str(tmp_path / "f.jsonl"))
    monkeypatch.setenv("HPNN_FLIGHT_N", "2")     # below the floor
    obs._reset_for_tests()
    obs.event("unit.one")
    obs.flight.dump("floor")
    assert _read(tmp_path / "f.jsonl")[0]["capacity"] == 8


def test_flight_rank_placeholder(tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_FLIGHT", str(tmp_path / "f.{rank}.jsonl"))
    obs._reset_for_tests()
    assert flight.dump_path() == str(tmp_path / "f.0.jsonl")


def test_flight_dump_failure_warns_not_raises(tmp_path, monkeypatch,
                                              capsys):
    monkeypatch.setenv(
        "HPNN_FLIGHT", str(tmp_path / "no" / "dir" / "f.jsonl"))
    obs._reset_for_tests()
    obs.event("unit.x")
    assert obs.flight.dump("broken") is None
    out = capsys.readouterr()
    assert out.out == ""
    assert "flight dump failed" in out.err


def test_postmortem_recovers_preabort_events(tmp_path, monkeypatch):
    """The acceptance postmortem: a dispatch crash (the in-process
    stand-in for a SIGKILL'd worker) aborts the round; the flight dump
    must contain the pre-abort story — round.start, the failed
    dispatch, the halving, the abort — even with NO metrics sink."""
    import jax

    from hpnn_tpu.train import driver, loop

    from tests.test_obs import _conf

    dump = tmp_path / "flight.jsonl"
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    monkeypatch.setenv("HPNN_FLIGHT", str(dump))
    monkeypatch.setenv("HPNN_FUSE_STATE", str(tmp_path / "st.npz"))
    monkeypatch.setenv("HPNN_FUSE_CHUNK", "128")
    obs._reset_for_tests()

    real = loop.train_epoch_lax
    boom = {"armed": True}

    def crash_once(*a, **k):
        if boom["armed"]:
            boom["armed"] = False
            raise jax.errors.JaxRuntimeError("worker died (simulated)")
        return real(*a, **k)

    monkeypatch.setattr(loop, "train_epoch_lax", crash_once)
    with pytest.raises(jax.errors.JaxRuntimeError):
        driver.train_kernel(_conf(tmp_path))

    assert dump.exists()
    recs = _read(dump)
    assert recs[0]["ev"] == "flight.dump"
    assert recs[0]["reason"] == "round.abort"
    names = [r["ev"] for r in recs[1:]]
    assert "round.start" in names
    i_fail = names.index("driver.chunk_dispatch")
    # JaxRuntimeError may surface under its concrete XLA name
    assert recs[1:][i_fail]["failed"].endswith("RuntimeError")
    assert "fuse.chunk_halved" in names
    assert names.index("round.abort") > i_fail


def test_sigterm_flushes_sink_and_dumps_flight(tmp_path):
    """A SIGTERM'd process must leave a flushed sink (obs.signal +
    final obs.summary) and a flight dump with reason "signal" — and
    still die with the honest SIGTERM exit status."""
    sink = tmp_path / "m.jsonl"
    dump = tmp_path / "f.jsonl"
    script = (
        "import os, signal\n"
        "from hpnn_tpu import obs\n"
        "obs.event('unit.work', i=1)\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "raise SystemExit('unreachable')\n"
    )
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "PALLAS_", "AXON_", "TPU_"))
           and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ROOT
    env["HPNN_METRICS"] = str(sink)
    env["HPNN_FLIGHT"] = str(dump)
    p = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == -signal.SIGTERM, (p.returncode, p.stderr)
    assert p.stdout == ""              # stdout stays byte-frozen

    recs = _read(sink)
    names = [r["ev"] for r in recs]
    assert "unit.work" in names
    i_sig = names.index("obs.signal")
    assert recs[i_sig]["reason"] == "SIGTERM"
    assert names.index("obs.summary") > i_sig   # final summary flushed
    drecs = _read(dump)
    assert drecs[0]["ev"] == "flight.dump"
    assert drecs[0]["reason"] == "signal"
    assert any(r["ev"] == "unit.work" for r in drecs[1:])


def test_flight_ring_concurrent_serve_and_train_writers(tmp_path,
                                                        monkeypatch):
    """The ring under real concurrent producers: a serve session
    hammered from client threads (its drain thread is a third writer)
    while a train round emits from the main thread.  A dump taken
    after the dust settles must hold exactly-capacity intact records —
    no interleaved/torn lines — and never drop the newest event."""
    from hpnn_tpu.train import driver

    from tests.test_obs import _conf

    dump = tmp_path / "flight.jsonl"
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    monkeypatch.setenv("HPNN_FLIGHT", str(dump))
    monkeypatch.setenv("HPNN_FLIGHT_N", "64")
    obs._reset_for_tests()

    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("k", _kernel())
    errors = []

    def hammer():
        try:
            for _ in range(40):    # x3 threads: >> ring capacity
                sess.infer("k", np.zeros(8))
        except Exception as exc:  # surface thread crashes in the test
            errors.append(exc)

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        assert driver.train_kernel(_conf(tmp_path))
    finally:
        for t in threads:
            t.join(timeout=60)
    assert not errors
    sess.close()

    obs.event("unit.newest")           # the event a dump may not drop
    assert obs.flight.dump("concurrency") == str(dump)
    recs = _read(dump)                 # every line parses = no tearing
    header = recs[0]
    assert header["ev"] == "flight.dump"
    assert header["capacity"] == 64
    assert header["events"] == 64      # ring full after all that
    assert len(recs) == 65
    assert all(isinstance(r, dict) and "ev" in r for r in recs[1:])
    assert recs[-1]["ev"] == "unit.newest"


# -------------------------------------------------------------- merge
def test_merge_events_skew_tolerance(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(ROOT, "tools", "obs_report.py"))
    rpt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rpt)

    # rank 0's host clock steps BACKWARDS mid-run (ts 10 -> 5 -> 20)
    r0 = tmp_path / "run.0.jsonl"
    r0.write_text("\n".join(json.dumps(r) for r in [
        {"ts": 1.0, "ev": "obs.open", "kind": "event", "rank": 0},
        {"ts": 10.0, "ev": "a.first", "kind": "event"},
        {"ts": 5.0, "ev": "a.second", "kind": "event"},
        {"ts": 20.0, "ev": "a.third", "kind": "event"},
    ]) + "\n")
    r1 = tmp_path / "run.1.jsonl"
    r1.write_text("\n".join(json.dumps(r) for r in [
        {"ts": 1.5, "ev": "obs.open", "kind": "event", "rank": 1},
        {"ts": 11.0, "ev": "b.first", "kind": "event"},
        {"ts": 12.0, "ev": "b.second", "kind": "event"},
    ]) + "\n")

    merged = rpt.merge_events([str(r0), str(r1)])
    assert all("rank" in r for r in merged)
    # a rank is never reordered against itself (clamped monotone) ...
    evs0 = [r["ev"] for r in merged if r["rank"] == 0]
    assert evs0 == ["obs.open", "a.first", "a.second", "a.third"]
    # ... and the peers interleave by (clamped) timestamp: rank 1's
    # 11.0/12.0 land between rank 0's 10.0 and 20.0
    evs = [r["ev"] for r in merged]
    assert evs.index("a.first") < evs.index("b.first")
    assert evs.index("b.second") < evs.index("a.third")

    # the CLI: --merge + --out writes the merged timeline
    out = tmp_path / "merged.jsonl"
    rc = rpt.main(["--merge", str(r0), str(r1), "--out", str(out),
                   "--json"])
    assert rc == 0
    assert len(_read(out)) == 7
    # several paths without --merge is a usage error
    assert rpt.main([str(r0), str(r1)]) == 2


# ---------------------------------------------------------- train_nn
def _train_workdir(tmp_path):
    from tests.test_obs import _conf

    _conf(tmp_path)                    # writes tmp_path/samples
    (tmp_path / "nn.conf").write_text(
        "[name] XP\n[type] ANN\n[init] generate\n[seed] 1234\n"
        "[input] 8\n[hidden] 5\n[output] 2\n[train] BP\n"
        "[sample_dir] ./samples\n[test_dir] ./samples\n")


def test_train_nn_export_port_flag(tmp_path, monkeypatch, capsys):
    """--export-port 0 binds an ephemeral /metrics endpoint for the
    run's duration (stderr names it; stdout stays token-only)."""
    from hpnn_tpu.cli import train_nn

    _train_workdir(tmp_path)
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    obs._reset_for_tests()
    rc = train_nn.main(["--export-port", "0", "nn.conf"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "train_nn: metrics export on http://" in err
    assert (tmp_path / "kernel.opt").exists()


def test_train_nn_export_port_validation(capsys):
    from hpnn_tpu.cli import train_nn

    assert train_nn.main(["--export-port", "99999", "nn.conf"]) == -1
    assert "bad --export-port" in capsys.readouterr().err
    assert train_nn.main(["--export-port", "abc", "nn.conf"]) == -1
    assert "bad --export-port" in capsys.readouterr().err
