"""Conf-file parser/dumper behavior, incl. the reference's quirks."""

import io

from hpnn_tpu import config
from hpnn_tpu.config import NNTrain, NNType

MNIST_CONF = """[name] MNIST
[type] ANN
[init] generate
[seed] 10958
[input] 784
[hidden] 300
[output] 10
[train] BP
[sample_dir] ./samples
[test_dir] ./tests
"""


def _write(tmp_path, text, name="nn.conf"):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_parse_mnist_conf(tmp_path):
    # small topology so generate is fast; same grammar as the tutorial conf
    text = MNIST_CONF.replace("784", "12").replace("300", "7")
    conf = config.load_conf(_write(tmp_path, text))
    assert conf is not None
    assert conf.name == "MNIST"
    assert conf.type == NNType.ANN
    assert conf.need_init is True
    assert conf.seed == 10958
    assert conf.train == NNTrain.BP
    assert conf.samples == "./samples"
    assert conf.tests == "./tests"
    assert conf.kernel.n_inputs == 12
    assert conf.kernel.hidden_sizes == (7,)
    assert conf.kernel.n_outputs == 10


def test_type_first_letter_only(tmp_path):
    base = MNIST_CONF.replace("784", "4").replace("300", "3")
    conf = config.load_conf(_write(tmp_path, base.replace("ANN", "SOMETHING")))
    assert conf.type == NNType.SNN  # 'S' wins
    conf = config.load_conf(_write(tmp_path, base.replace("ANN", "XYZ")))
    assert conf.type == NNType.ANN  # default


def test_train_modes(tmp_path):
    base = MNIST_CONF.replace("784", "4").replace("300", "3")
    for txt, mode in [
        ("BP", NNTrain.BP),
        ("BPM", NNTrain.BPM),
        ("CG", NNTrain.CG),
        ("SPLX", NNTrain.SPLX),
    ]:
        conf = config.load_conf(_write(tmp_path, base.replace("] BP", f"] {txt}")))
        assert conf.train == mode, txt


def test_multi_hidden(tmp_path):
    text = MNIST_CONF.replace("784", "6").replace("[hidden] 300", "[hidden] 5 4 3")
    conf = config.load_conf(_write(tmp_path, text))
    assert conf.kernel.hidden_sizes == (5, 4, 3)


def test_missing_type_fails(tmp_path):
    text = MNIST_CONF.replace("784", "4").replace("300", "3")
    text = text.replace("[type] ANN\n", "")
    assert config.load_conf(_write(tmp_path, text)) is None


def test_comment_strip(tmp_path):
    text = MNIST_CONF.replace("784", "4").replace("300", "3")
    text = text.replace("[sample_dir] ./samples", "[sample_dir] ./samples #comment")
    conf = config.load_conf(_write(tmp_path, text))
    assert conf.samples == "./samples"


def test_dump_conf_format(tmp_path):
    text = MNIST_CONF.replace("784", "4").replace("300", "3")
    conf = config.load_conf(_write(tmp_path, text))
    buf = io.StringIO()
    config.dump_conf(conf, buf)
    out = buf.getvalue()
    # byte-format parity: plural tags, trailing space after hiddens list
    assert "[name] MNIST\n" in out
    assert "[type] ANN\n" in out
    assert "[init] generate\n" in out
    assert "[seed] 10958\n" in out
    assert "[inputs] 4\n" in out
    assert "[hiddens] 3 \n" in out
    assert "[outputs] 10\n" in out
    assert "[train] BP\n" in out


def test_load_kernel_roundtrip_through_conf(tmp_path):
    text = MNIST_CONF.replace("784", "4").replace("300", "3")
    conf = config.load_conf(_write(tmp_path, text))
    kpath = tmp_path / "kernel.opt"
    with open(kpath, "w") as fp:
        config.dump_kernel(conf, fp)
    text2 = text.replace("[init] generate", f"[init] {kpath}")
    conf2 = config.load_conf(_write(tmp_path, text2, "nn2.conf"))
    assert conf2 is not None
    assert conf2.need_init is False
    assert conf2.kernel.n_inputs == 4
