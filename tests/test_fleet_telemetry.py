"""Fleet telemetry plane (obs/propagate.py, obs/collector.py,
obs/alerts.py; docs/observability.md "Fleet telemetry").

Acceptance bar (ISSUE 12): a single loadgen request against a
2-replica Router server running in ANOTHER process reconstructs —
via ``tools/obs_report.py`` span merging — into ONE tree spanning
both processes' sinks (client → edge/router → replica).  Plus: the
push client sheds with counted drops and never blocks the emitting
thread, the collector round-trips batches into ``/fleetz`` and
``/metrics``, and the alert rule engine fires/resolves with
cooldown, ``for=``, EWMA z-score, and malformed-term tolerance.
"""

import http.client
import importlib
import io
import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from hpnn_tpu import obs
from hpnn_tpu.obs import alerts, collector, propagate, spans

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _import_tool(name):
    tools = os.path.join(ROOT, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    return importlib.import_module(name)


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _free_dead_port() -> int:
    """A port nothing listens on (bound once, then released)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ------------------------------------------------------- propagation
def test_propagate_disabled_is_inert(tmp_path, monkeypatch):
    monkeypatch.delenv("HPNN_SPANS", raising=False)
    obs.configure(str(tmp_path / "a.jsonl"))
    sp = spans.start("x")
    assert propagate.ctx_from(sp) is None
    headers = propagate.inject({}, None)
    assert propagate.HDR_TRACE not in headers
    assert propagate.extract({}) is None


def test_propagate_inject_extract_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_SPANS", "1")
    sink = tmp_path / "a.jsonl"
    obs.configure(str(sink))
    sp = spans.start("edge")
    ctx = propagate.ctx_from(sp)
    assert ctx is not None and ctx.trace
    ref = propagate.ref(sp)
    assert ref == ctx.parent and ref.startswith(f"{os.getpid():x}:")
    headers = propagate.inject({}, ctx)
    assert headers[propagate.HDR_TRACE] == ctx.trace
    assert headers[propagate.HDR_PARENT] == ref
    got = propagate.extract(headers)
    assert got is not None
    assert (got.trace, got.parent) == (ctx.trace, ctx.parent)
    # span fields for the receiving side
    f = propagate.fields(got)
    assert f == {"trace": ctx.trace, "remote_parent": ref}
    assert propagate.fields(None) == {}
    # thread-slot note/peek for causal chains (ingest -> trainer)
    propagate.note("ingest", got)
    assert propagate.peek("ingest") is got
    propagate.note("ingest", None)          # None never clears:
    assert propagate.peek("ingest") is got  # latest *real* ctx wins
    spans.finish(sp)
    obs.flush()
    # each adoption counts
    assert any(r.get("ev") == "trace.adopt" for r in _read(sink))


SERVER_SCRIPT = """\
import sys, threading
sys.path.insert(0, {root!r})
from hpnn_tpu import obs, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.serve.server import make_server

k, _ = kernel_mod.generate(7, 8, [5], 2)
router = serve.Router(2, max_batch=8, max_wait_ms=0.5)
router.register_kernel("k", k)
server = make_server(router, port=0)
print(server.server_address[1], flush=True)
threading.Thread(target=server.serve_forever, daemon=True).start()
sys.stdin.readline()          # parent closes stdin to stop
server.shutdown()
router.close()
obs.flush()
"""


def test_one_request_reconstructs_across_two_process_sinks(
        tmp_path, monkeypatch):
    """THE cross-process proof: one loadgen request, client sink +
    server sink, obs_report stitches ONE tree spanning both pids."""
    sink_a = tmp_path / "client.jsonl"     # this process
    sink_b = tmp_path / "server.jsonl"     # the server subprocess
    script = tmp_path / "server.py"
    script.write_text(SERVER_SCRIPT.format(root=ROOT))
    env = dict(os.environ, JAX_PLATFORMS="cpu", HPNN_SPANS="1",
               HPNN_METRICS=str(sink_b))
    proc = subprocess.Popen(
        [sys.executable, str(script)], env=env, text=True,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE)
    row = None
    try:
        line = proc.stdout.readline().strip()
        assert line.isdigit(), (
            f"server did not start: {proc.stderr.read()[-2000:]}")
        port = int(line)

        monkeypatch.setenv("HPNN_SPANS", "1")
        obs.configure(str(sink_a))
        lg = _import_tool("loadgen")
        lg._TRACE_MODS = None          # re-read the armed knob
        cli = lg._Client(f"127.0.0.1:{port}", timeout_s=30.0)
        body = json.dumps({"kernel": "k",
                           "inputs": [0.1] * 8}).encode()
        try:
            row = cli.request("k", 1, body)
        finally:
            cli.close()
            lg._TRACE_MODS = None
        assert row["status"] == "ok" and row["req_id"]
        assert row["trace"]            # the client minted the trace
        obs.flush()
    finally:
        try:
            proc.stdin.close()
            proc.wait(timeout=30)
        except Exception:
            proc.kill()
            proc.wait(timeout=10)

    report = _import_tool("obs_report")
    events = report.merge_events([str(sink_a), str(sink_b)])
    all_spans = report.collect_spans(events)
    sub = report.filter_spans_req(all_spans, row["req_id"])
    roots = report.span_tree(sub)
    assert len(roots) == 1, [s["name"] for s in sub]
    root = roots[0]
    assert root["name"] == "loadgen.request"
    assert root["pid"] == os.getpid()

    def walk(node):
        yield node
        for c in node["children"]:
            yield from walk(c)

    nodes = list(walk(root))
    names = {n["name"] for n in nodes}
    pids = {n["pid"] for n in nodes}
    # the stitched tree crosses the process boundary and covers the
    # whole path: client -> edge/router fan-out -> replica dispatch
    assert len(pids) >= 2, nodes
    assert proc.pid in pids
    assert "router.request" in names and "serve.request" in names
    remote = [n for n in nodes if n["pid"] == proc.pid]
    assert all(n["fields"].get("trace") == row["trace"]
               for n in remote if "trace" in n["fields"])
    # rendering tags spans with their pid once >1 process contributed
    text = report.render_spans(events, req_id=row["req_id"])
    assert f"@{proc.pid:x}" in text


# --------------------------------------------------------- collector
def test_push_client_sheds_and_never_blocks(tmp_path, monkeypatch):
    dead = _free_dead_port()
    monkeypatch.setenv("HPNN_COLLECTOR", f"http://127.0.0.1:{dead}")
    monkeypatch.setenv("HPNN_COLLECTOR_QUEUE", "8")
    monkeypatch.setenv("HPNN_COLLECTOR_FLUSH_S", "60")  # no auto-drain
    obs.configure(str(tmp_path / "s.jsonl"))
    t0 = time.perf_counter()
    for i in range(200):
        obs.event("lint.burst", i=i)
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.0                    # O(1) offers, no I/O
    st = collector.client_stats()
    assert st["queued"] <= 8                # cap floor is 8
    assert st["dropped_full"] >= 180
    # a dead collector sheds the batch, counted, instead of retrying
    collector.flush()
    st = collector.client_stats()
    assert st["dropped_push"] >= 1 and st["pushed"] == 0
    # only the drop's own self-telemetry may trickle back in
    assert st["queued"] <= 4


def test_collector_roundtrip_fleetz_metrics(tmp_path):
    out = tmp_path / "merged.jsonl"
    server = collector.start_collector(path=str(out))
    try:
        port = server.server_address[1]
        lines = [
            json.dumps({"ts": 1.0, "ev": "serve.request",
                        "kind": "timer", "dt": 0.004}),
            json.dumps({"ts": 1.1, "ev": "obs.summary",
                        "kind": "summary",
                        "counters": {"serve.requests": 5},
                        "gauges": {"slo.p99_ms": 4.0},
                        "aggregates": {"serve.request": {
                            "n": 5, "total": 0.02, "min": 0.001,
                            "max": 0.008,
                            "log2_buckets": {"-8": 5}}}}),
        ]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/v1/telemetry",
                     body=json.dumps({"pid": 4242, "rank": 0,
                                      "lines": lines}).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        doc = json.loads(resp.read().decode())
        assert doc["ok"] is True and doc["queued"] == 2

        deadline = time.monotonic() + 5.0
        while (server.collector.records_total < 2
               and time.monotonic() < deadline):
            time.sleep(0.02)
        fz = server.collector.fleetz()
        assert fz["totals"]["records"] == 2
        w = fz["workers"]["4242:0"]
        assert w["records"] == 2 and w["has_summary"]
        # merged log2 summaries give a fleet p99 per aggregate
        assert fz["fleet"]["p99"]["serve.request"] > 0.0
        assert fz["fleet"]["counters"]["serve.requests"] == 5

        conn.request("GET", "/fleetz")
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(
            resp.read().decode())["totals"]["records"] == 2
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.status == 200 and "# TYPE" in body
        assert "hpnn_fleet_records_total 2" in body
        assert "hpnn_fleet_workers 1" in body
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read().decode())["status"] == "ok"
        conn.close()
        # merged stream on disk, tagged with the sender's identity
        recs = _read(out)
        assert len(recs) == 2
        assert all(r["pid"] == 4242 for r in recs)
    finally:
        collector.stop_collector(server)


def test_collector_recv_queue_sheds_not_stalls():
    c = collector.Collector(queue_max=8)
    try:
        c._stop.set()                       # park the consumer
        c._consumer.join(timeout=5.0)
        ok = sum(c.submit(1, 0, ["{}"]) for _ in range(50))
        assert 0 < ok <= 8                  # bounded, never blocking
        assert c.recv_dropped >= 42
    finally:
        c.close()


# ------------------------------------------------------------ alerts
def _gauge_sink(tmp_path, monkeypatch, spec):
    monkeypatch.setenv("HPNN_ALERTS", spec)
    sink = tmp_path / "alerts.jsonl"
    obs.configure(str(sink))
    return sink


def test_alert_threshold_fires_and_resolves(tmp_path, monkeypatch):
    sink = _gauge_sink(
        tmp_path, monkeypatch,
        "down@g.ready<1.5:for=0,cooldown=0,severity=crit")
    obs.gauge("g.ready", 2.0)
    assert alerts.health_doc()["active"] == 0
    obs.gauge("g.ready", 1.0)
    doc = alerts.health_doc()
    assert doc["active"] == 1 and doc["fired_total"] == 1
    obs.gauge("g.ready", 2.0)
    doc = alerts.health_doc()
    assert doc["active"] == 0 and doc["fired_total"] == 1
    obs.flush()
    evs = [r for r in _read(sink) if str(r.get("ev", "")).startswith(
        "alert.")]
    assert [r["ev"] for r in evs] == ["alert.fire", "alert.resolve"]
    fire, resolve = evs
    assert fire["rule"] == "down" and fire["severity"] == "crit"
    assert fire["value"] == 1.0 and fire["threshold"] == 1.5
    assert resolve["duration_s"] >= 0.0
    # the stream lints clean under the --fleet schema check
    cat = _import_tool("check_obs_catalog")
    assert cat.lint_fleet(str(sink)) == []


def test_alert_fire_attaches_flight_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_FLIGHT", str(tmp_path / "flight.jsonl"))
    sink = _gauge_sink(tmp_path, monkeypatch,
                       "hot@g.t>10:for=0,cooldown=0")
    obs.gauge("g.t", 99.0)
    obs.flush()
    fires = [r for r in _read(sink) if r.get("ev") == "alert.fire"]
    assert fires and os.path.exists(fires[0]["flight"])


def test_alert_cooldown_suppresses_refire(tmp_path, monkeypatch):
    _gauge_sink(tmp_path, monkeypatch,
                "flap@g.f>5:for=0,cooldown=3600")
    for v in (6.0, 1.0, 7.0, 1.0, 8.0):     # three breaches, resolves
        obs.gauge("g.f", v)
    doc = alerts.health_doc()
    assert doc["fired_total"] == 1          # later fires cooled down


def test_alert_for_requires_sustained_breach(tmp_path, monkeypatch):
    _gauge_sink(tmp_path, monkeypatch, "slow@g.s>5:for=3600")
    obs.gauge("g.s", 10.0)
    obs.gauge("g.s", 10.0)
    assert alerts.health_doc()["fired_total"] == 0


def test_alert_zscore_fires_on_anomaly(tmp_path, monkeypatch):
    _gauge_sink(tmp_path, monkeypatch,
                "anom@g.z:z=4,warmup=5,cooldown=0")
    for _ in range(10):
        obs.gauge("g.z", 10.0)              # flat warmup
    assert alerts.health_doc()["fired_total"] == 0
    obs.gauge("g.z", 1000.0)                # way out of band
    assert alerts.health_doc()["fired_total"] == 1


def test_alert_malformed_term_skipped_rest_armed(tmp_path, monkeypatch,
                                                capsys):
    _gauge_sink(tmp_path, monkeypatch,
                "bad@no.operator.here, ok@g.ok>1:cooldown=0")
    obs.gauge("g.ok", 2.0)
    doc = alerts.health_doc()
    assert [r["rule"] for r in doc["rules"]] == ["ok"]
    assert doc["fired_total"] == 1
    assert "term skipped" in capsys.readouterr().err


# --------------------------------------------------------- obs_report
def test_obs_report_follow_tails_a_growing_sink(tmp_path):
    report = _import_tool("obs_report")
    path = tmp_path / "tail.jsonl"

    def writer():
        time.sleep(0.1)                     # file appears late
        with open(path, "w") as fp:
            fp.write(json.dumps({"ts": 1.0, "ev": "round.start",
                                 "kind": "event", "mode": "fused"})
                     + "\n")
            fp.flush()
            time.sleep(0.1)
            fp.write(json.dumps({"ts": 2.0, "ev": "round.end",
                                 "kind": "event"}) + "\n")

    t = threading.Thread(target=writer)
    t.start()
    buf = io.StringIO()
    n = report.follow(str(path), duration_s=0.8, out=buf, poll_s=0.02)
    t.join()
    text = buf.getvalue()
    assert n == 2
    assert "round.start" in text and "round.end" in text
    assert "mode=fused" in text


def test_obs_report_follow_cli_flag_validation(tmp_path):
    report = _import_tool("obs_report")
    # --follow wants exactly one path and no other mode
    assert report.main(["--follow", "a.jsonl", "b.jsonl"]) == 2
    assert report.main(["--follow", "a.jsonl", "--spans"]) == 2
    assert report.main(["--for", "1", "a.jsonl"]) == 2
