"""Readiness, graceful drain, the loadgen ``lost`` class, the drill
gate metrics, and the kill-9 acceptance drill (docs/resilience.md).

The liveness/readiness split: ``/healthz`` answers whenever the
process does, ``/readyz`` and the POST routes answer 503 +
``Retry-After`` while the session is warming / replaying the WAL /
draining.  SIGTERM's drain handler and the obs signal handler must
flush the postmortem exactly once between them.  ``tools/loadgen.py``
classifies connection-level failures as ``lost`` — distinct from
``shed`` (429/503 after retries) and ``timeout`` (504) — which is what
the drills measure.  The kill9 drill is the acceptance E2E: SIGKILL a
live child mid-traffic, restart it on the same WAL dir, and prove the
resident weights came back bitwise while goodput recovered.
"""

import http.client
import importlib.util
import json
import os
import signal
import socket
import threading
import time

from hpnn_tpu import obs, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import registry as obs_registry
from hpnn_tpu.serve.server import install_drain, make_server

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _kernel(seed=7):
    k, _ = kernel_mod.generate(seed, 8, [5], 2)
    return k


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = json.loads(resp.read().decode())
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, body, headers


def _post(port, path, payload):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read().decode())
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, body, headers


def test_readiness_gates_the_post_routes(tmp_path):
    sink = str(tmp_path / "sink.jsonl")
    obs_registry._reset_for_tests()
    obs.configure(sink)
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("k", _kernel())
    server = make_server(sess, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        # born ready (the embed-and-go default)
        code, body, _ = _get(port, "/readyz")
        assert code == 200 and body == {"ready": True, "reason": None}

        sess.mark_unready("warming")
        code, body, headers = _get(port, "/readyz")
        assert code == 503
        assert headers.get("Retry-After") == "1"
        assert body["ready"] is False and body["reason"] == "warming"
        assert body["retriable"] is True
        # liveness is unaffected...
        code, body, _ = _get(port, "/healthz")
        assert code == 200
        # ...but work is refused, retriably, on every POST route
        code, body, headers = _post(port, "/v1/infer",
                                    {"kernel": "k",
                                     "inputs": [0.0] * 8})
        assert code == 503 and body["retriable"] is True
        assert headers.get("Retry-After") == "1"
        code, body, _ = _post(port, "/ingest",
                              {"inputs": [0.0] * 8,
                               "targets": [0.0, 0.0]})
        assert code == 503

        sess.mark_ready()
        code, body, _ = _get(port, "/readyz")
        assert code == 200
        code, body, _ = _post(port, "/v1/infer",
                              {"kernel": "k", "inputs": [0.0] * 8})
        assert code == 200 and len(body["outputs"]) == 2
    finally:
        server.shutdown()
        server.server_close()
        sess.close()
        obs.configure(None)
    with open(sink) as fp:
        evs = [json.loads(ln) for ln in fp if ln.strip()]
    unready = [e for e in evs if e.get("ev") == "serve.unready"]
    assert [e["reason"] for e in unready] == ["warming"]
    assert any(e.get("ev") == "serve.ready" for e in evs)


def test_drain_flushes_postmortem_exactly_once(tmp_path):
    sink = str(tmp_path / "sink.jsonl")
    flight = str(tmp_path / "flight.jsonl")
    obs_registry._reset_for_tests()
    os.environ["HPNN_FLIGHT"] = flight
    prev_handler = signal.getsignal(signal.SIGTERM)
    obs.configure(sink)
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("k", _kernel())
    server = make_server(sess, port=0)
    serve_thread = threading.Thread(target=server.serve_forever,
                                    daemon=True)
    serve_thread.start()
    try:
        obs.event("resilience.marker")  # give the flight ring a record
        handler = install_drain(server, sess)
        assert signal.getsignal(signal.SIGTERM) is handler
        handler(signal.SIGTERM, None)
        # idempotent: a second delivery and the chained obs signal
        # handler both find the postmortem already flushed
        handler(signal.SIGTERM, None)
        obs_registry._crash_flush("obs.signal", "SIGTERM", "signal")
        serve_thread.join(timeout=10)
        assert not serve_thread.is_alive()
        assert sess.is_ready() is False
        assert sess.ready_doc()["reason"] == "draining"
    finally:
        server.shutdown()
        server.server_close()
        sess.close()
        obs.configure(None)
        os.environ.pop("HPNN_FLIGHT", None)
        signal.signal(signal.SIGTERM, prev_handler)
        obs_registry._reset_for_tests()
    with open(sink) as fp:
        evs = [json.loads(ln) for ln in fp if ln.strip()]
    assert len([e for e in evs
                if e.get("ev") == "serve.drain"]) == 1
    assert len([e for e in evs
                if e.get("ev") == "obs.signal"]) == 1
    assert len([e for e in evs
                if e.get("ev") == "obs.summary"]) == 1
    assert os.path.exists(flight)  # the ring dumped, once


def test_loadgen_shields_sigpipe_left_by_cli_mains():
    """The CLIs install SIGPIPE=SIG_DFL for shell-pipeline manners; a
    host that ran one of their mains in-process would then die with
    rc=141 the moment a drill's target is killed mid-write.  loadgen
    and the drills re-arm Python's default (ignore) on entry, so a
    torn write surfaces as BrokenPipeError -> a ``lost`` record."""
    loadgen = _load_tool("loadgen")
    prev = signal.getsignal(signal.SIGPIPE)
    try:
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
        loadgen.shield_sigpipe()
        assert signal.getsignal(signal.SIGPIPE) is signal.SIG_IGN
        # run_open_loop arms it itself — callers need no ritual
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        loadgen.run_open_loop(
            f"http://127.0.0.1:{port}", rate_rps=20.0, duration_s=0.1,
            n_workers=2, max_retries=0, timeout_s=0.5)
        assert signal.getsignal(signal.SIGPIPE) is signal.SIG_IGN
    finally:
        signal.signal(signal.SIGPIPE, prev)


def test_loadgen_classifies_connection_loss_as_lost():
    loadgen = _load_tool("loadgen")
    with socket.socket() as s:  # find a port nothing listens on
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    summary = loadgen.run_open_loop(
        f"http://127.0.0.1:{port}", rate_rps=40.0, duration_s=0.25,
        n_workers=4, max_retries=0, timeout_s=0.5)
    assert summary["lost"] == summary["requests"] > 0
    assert summary["ok"] == summary["shed"] == summary["error"] == 0
    assert summary["lost_rate"] == 1.0


def test_loadgen_retries_503_then_records_shed():
    loadgen = _load_tool("loadgen")
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("default", _kernel())
    sess.mark_unready("warming")
    server = make_server(sess, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        summary = loadgen.run_open_loop(
            f"http://127.0.0.1:{port}", rate_rps=30.0,
            duration_s=0.25, n_workers=4, max_retries=1,
            retry_cap_s=0.01, timeout_s=2.0)
        # every arrival was answered (nothing lost), refused politely
        # (503 retried, then recorded as shed), served nothing
        assert summary["shed"] == summary["requests"] > 0
        assert summary["lost"] == summary["ok"] == 0
    finally:
        server.shutdown()
        server.server_close()
        sess.close()


def test_loadgen_stop_event_ends_the_run_early():
    loadgen = _load_tool("loadgen")
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("default", _kernel())
    server = make_server(sess, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    stop = threading.Event()
    seen = []

    def on_record(rec):
        seen.append(rec)
        if len(seen) >= 3:
            stop.set()

    try:
        t0 = time.perf_counter()
        summary = loadgen.run_open_loop(
            f"http://127.0.0.1:{port}", rate_rps=50.0,
            duration_s=30.0, n_workers=4, stop=stop,
            on_record=on_record)
        wall = time.perf_counter() - t0
        assert wall < 10.0          # nowhere near the 30s schedule
        assert summary["duration_s"] < 10.0
        assert len(seen) >= 3
        assert summary["requests"] == len(seen)
    finally:
        server.shutdown()
        server.server_close()
        sess.close()


def test_bench_gate_covers_the_drill_metrics():
    gate = _load_tool("bench_gate")
    for metric in ("drill_recovery_s", "drill_goodput_dip_pct",
                   "drill_lost_requests"):
        direction, tol = gate.GATE_METRICS[metric]
        assert direction == "lower" and tol >= 1.0
    base = [{"drill_recovery_s": 1.0, "drill_lost_requests": 0}] * 3
    # 4x the baseline recovery: past the 150% tolerance -> regression
    bad = gate.gate(gate.flatten({"drill_recovery_s": 4.0}),
                    gate.baseline(base, 5))
    assert [r["metric"] for r in bad] == ["drill_recovery_s"]
    ok = gate.gate(gate.flatten({"drill_recovery_s": 2.0}),
                   gate.baseline(base, 5))
    assert ok == []
    # the zero-baseline rule: a 0-lost baseline cannot ratio-gate, so
    # lost stays un-gated until some baseline run records a loss
    skipped = gate.gate(gate.flatten({"drill_lost_requests": 25}),
                        gate.baseline(base, 5))
    assert skipped == []


def test_drill_kill9_end_to_end(tmp_path):
    """The acceptance drill: a real ``online_nn`` child under live
    loadgen traffic is SIGKILLed after a WAL-committed promotion and
    restarted on the same port + WAL dir.  The restarted resident
    weights must equal the supervisor's own read of the last committed
    checkpoint bitwise, and goodput must recover."""
    chaos_drill = _load_tool("chaos_drill")
    res = chaos_drill.drill_kill9(workdir=str(tmp_path), rate=30.0)
    assert res["ok"], res
    assert res["restored_bitwise"] is True
    assert res["wal_version"] >= 1
    assert res["recovery_s"] is not None and res["recovery_s"] >= 0.0
    assert res["lost"] >= 0 and res["requests"] > res["lost"]
    # the catalog lint accepts the row it just produced
    lint = _load_tool("check_obs_catalog")
    row_path = tmp_path / "drill.jsonl"
    row_path.write_text(json.dumps(res) + "\n")
    assert lint.lint_chaos(str(row_path)) == []
