"""Test env: force CPU with 8 virtual devices (the reference's DEBUG
3-GPU-contexts-on-one-device trick, SURVEY.md §4.3, done the JAX way)
and enable x64 so CPU parity tests run in the reference's f64."""

import os

# Must be set before jax initializes.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
