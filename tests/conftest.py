"""Test env: force CPU with 8 virtual devices (the reference's DEBUG
3-GPU-contexts-on-one-device trick, SURVEY.md §4.3, done the JAX way)
and enable x64 so CPU parity tests run in the reference's f64."""

import os

# Must be set before jax initializes.  Force CPU even when the outer
# environment selects an accelerator platform (e.g. JAX_PLATFORMS=axon):
# the suite is written for 8 virtual f64 CPU devices.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# jax may already be imported at interpreter startup (site hook) with an
# accelerator platform selected; the backend only initializes on first
# use, so overriding the config here still wins.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_verbosity():
    """Module-global verbosity must not leak across tests (a failing
    test that set it would otherwise cascade 'NN:' output into
    unrelated tests)."""
    yield
    from hpnn_tpu.utils import logging as log

    log.set_verbose(0)


@pytest.fixture(autouse=True)
def _reset_obs_memos():
    """The HPNN_TRACE memo (utils/trace.py) and the HPNN_METRICS sink
    (obs/registry.py) are read-once process state; tests flip those env
    vars per-test, so both memos reset around every test."""
    from hpnn_tpu import obs
    from hpnn_tpu.utils import trace

    trace._reset_enabled_cache()
    obs._reset_for_tests()
    yield
    trace._reset_enabled_cache()
    obs._reset_for_tests()


@pytest.fixture(autouse=True)
def _lockwatch_cycle_gate():
    """With HPNN_LOCKWATCH=1 exported, every test doubles as a
    lock-order probe: any cycle the test's lock traffic added to the
    acquisition-order graph fails THAT test with both stacks
    (docs/analysis.md).  Declared after _reset_obs_memos so this
    teardown runs before the reset clears the graph.  Unarmed: no-op."""
    yield
    from hpnn_tpu.obs import lockwatch

    if lockwatch.enabled():
        lockwatch.check()
