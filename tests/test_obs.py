"""hpnn_tpu.obs — the structured metrics side channel.

The registry must be invisible when ``HPNN_METRICS`` is unset, and when
set it must record the fused-round story — dispatch timers, chunk
timeline, fallback/resume counters, n_iter histograms — in emission
order, without ever touching the stdout token stream."""

import json
import os

import numpy as np
import pytest

from hpnn_tpu import obs
from hpnn_tpu.config import NNConf, NNTrain, NNType
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.train import driver, loop
from hpnn_tpu.utils import logging as log


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _conf(tmp_path, n=6):
    rng = np.random.RandomState(0)
    sdir = tmp_path / "samples"
    sdir.mkdir(exist_ok=True)
    for i in range(n):
        c = i % 2
        x = (1 - 2 * c) * np.r_[np.ones(4), -np.ones(4)] \
            + 0.1 * rng.normal(size=8)
        t = np.full(2, -1.0)
        t[c] = 1.0
        with open(sdir / f"s{i:05d}.txt", "w") as fp:
            fp.write("[input] 8\n" + " ".join(f"{v:.5f}" for v in x) + "\n")
            fp.write("[output] 2\n" + " ".join(f"{v:.1f}" for v in t) + "\n")
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    return NNConf(name="t", type=NNType.ANN, seed=1, kernel=k,
                  train=NNTrain.BP, samples=str(sdir), tests=str(sdir))


# ---------------------------------------------------------------- registry

def test_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    obs._reset_for_tests()
    assert not obs.enabled()
    assert obs.sink_path() is None
    obs.event("x")
    obs.count("x")
    obs.gauge("x", 1.0)
    obs.observe("x", [1, 2])
    with obs.timer("x"):
        pass
    obs.summary()
    obs.flush()
    assert list(tmp_path.iterdir()) == []  # nothing written anywhere


def test_timer_disabled_is_shared_noop(monkeypatch):
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    obs._reset_for_tests()
    assert obs.timer("a") is obs.timer("b")  # the shared _NULL_CTX


def test_emit_kinds_and_totals(tmp_path, monkeypatch):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    assert obs.enabled()
    assert obs.sink_path() == str(sink)
    obs.event("round.start", mode="test")
    obs.count("c", n=2)
    obs.count("c", n=3, reason="again")
    obs.gauge("g", 7.5)
    obs.observe("h", [1, 2, 3, 4], tag="t")
    with obs.timer("t1", size=4):
        pass
    obs.summary()
    recs = _read(sink)
    by = {}
    for r in recs:
        by.setdefault(r["ev"], []).append(r)
    assert by["round.start"][0]["kind"] == "event"
    assert by["round.start"][0]["mode"] == "test"
    # counter lines carry increment + running total, in order
    assert [(r["n"], r["total"]) for r in by["c"]] == [(2, 2), (3, 5)]
    assert by["g"][0]["value"] == 7.5
    h = by["h"][0]
    assert (h["kind"], h["n"], h["min"], h["max"]) == ("hist", 4, 1.0, 4.0)
    t = by["t1"][0]
    assert t["kind"] == "timer" and t["dt"] >= 0 and t["size"] == 4
    s = by["obs.summary"][0]
    assert s["counters"] == {"c": 5}
    assert s["gauges"] == {"g": 7.5}
    assert s["aggregates"]["h"]["n"] == 4
    assert s["aggregates"]["h"]["total"] == 10.0
    # log2 buckets: 1->bucket 1 (frexp exp), 2->2, 3,4->... just check sum
    assert sum(s["aggregates"]["h"]["log2_buckets"].values()) == 4
    assert s["aggregates"]["t1"]["n"] == 1


def test_timer_tags_failures(tmp_path, monkeypatch):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    with pytest.raises(ValueError):
        with obs.timer("boom"):
            raise ValueError("x")
    recs = _read(sink)
    assert recs[-1]["ev"] == "boom" and recs[-1]["failed"] == "ValueError"


def test_rank_placeholder(tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.{rank}.jsonl"))
    obs._reset_for_tests()
    assert obs.sink_path() == str(tmp_path / "m.0.jsonl")


def test_configure_points_and_clears(tmp_path, monkeypatch):
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    sink = tmp_path / "c.jsonl"
    obs.configure(str(sink))
    assert obs.enabled() and os.environ["HPNN_METRICS"] == str(sink)
    obs.event("hello")
    obs.configure(None)
    assert not obs.enabled() and "HPNN_METRICS" not in os.environ
    assert any(r["ev"] == "hello" for r in _read(sink))


def test_bad_sink_path_disables_not_crashes(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv(
        "HPNN_METRICS", str(tmp_path / "no" / "such" / "dir" / "m.jsonl"))
    obs._reset_for_tests()
    assert not obs.enabled()
    obs.event("x")  # still a no-op, no raise
    out = capsys.readouterr()
    assert out.out == ""          # stdout untouched, always
    assert "metrics disabled" in out.err


# ------------------------------------------------------- instrumented round

def test_fused_round_emits_the_tentpole_events(tmp_path, monkeypatch):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    conf = _conf(tmp_path)
    assert driver.train_kernel(conf)
    driver.run_kernel(conf)
    recs = _read(sink)
    names = [r["ev"] for r in recs]
    # acceptance events: dispatch latency, chunk timeline, n_iter hist
    assert "driver.chunk_dispatch" in names
    assert "fuse.chunk_size" in names
    assert "train.n_iter" in names
    assert "eval.round" in names
    start = next(r for r in recs if r["ev"] == "round.start")
    end = next(r for r in recs if r["ev"] == "round.end")
    assert start["mode"] == "fused" and start["samples"] == 6
    assert end["samples"] == 6
    hist = next(r for r in recs if r["ev"] == "train.n_iter")
    assert hist["n"] == 6 and hist["min"] >= 1
    cnt = next(r for r in recs if r["ev"] == "train.samples")
    assert cnt["total"] == 6
    summaries = [r for r in recs if r["ev"] == "obs.summary"]
    assert summaries and summaries[-1]["aggregates"]["train.n_iter"]["n"] == 6
    assert summaries[-1]["aggregates"]["driver.chunk_dispatch"]["n"] >= 1


def test_round_stdout_is_byte_identical_with_metrics_on(
        tmp_path, monkeypatch, capsys):
    log.set_verbose(2)
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    obs._reset_for_tests()
    conf = _conf(tmp_path)
    assert driver.train_kernel(conf)
    plain = capsys.readouterr().out
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    obs._reset_for_tests()
    conf = _conf(tmp_path)
    assert driver.train_kernel(conf)
    assert capsys.readouterr().out == plain
    assert plain.count("TRAINING FILE") == 6


def test_mosaic_refusal_event_order(tmp_path, monkeypatch):
    """A Mosaic refusal mid-round must leave this exact story in the
    sink: pallas round.start -> failed dispatch timer -> one
    fallback.mosaic_refusal -> successful lax dispatches -> round.end
    on the lax body."""
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()

    # pretend the Mosaic epoch body is eligible, then have it refuse
    monkeypatch.setattr(loop, "_pallas_epoch_default", lambda w: True)
    from hpnn_tpu.ops import pallas_train

    def refuse(*a, **k):
        raise RuntimeError("Mosaic lowering failed (simulated)")

    monkeypatch.setattr(pallas_train, "train_epoch_fused", refuse)

    conf = _conf(tmp_path)
    assert driver.train_kernel(conf)

    recs = _read(sink)
    names = [r["ev"] for r in recs]
    assert names.count("fallback.mosaic_refusal") == 1
    start = next(r for r in recs if r["ev"] == "round.start")
    assert start["body"] == "pallas"
    i_fail = names.index("driver.chunk_dispatch")
    assert recs[i_fail]["failed"] == "RuntimeError"
    assert recs[i_fail]["body"] == "pallas"
    i_fb = names.index("fallback.mosaic_refusal")
    assert i_fail < i_fb
    fb = recs[i_fb]
    assert fb["total"] == 1 and fb["exc"] == "RuntimeError"
    # the retried dispatch (lax body) lands AFTER the fallback marker
    ok_dispatches = [
        r for r in recs if r["ev"] == "driver.chunk_dispatch"
        and "failed" not in r
    ]
    assert ok_dispatches and all(r["body"] == "lax" for r in ok_dispatches)
    assert recs.index(ok_dispatches[0]) > i_fb
    end = next(r for r in recs if r["ev"] == "round.end")
    assert end["body"] == "lax"


def test_chunk_halving_and_resume_events(tmp_path, monkeypatch):
    """A dispatch crash (JaxRuntimeError) under HPNN_FUSE_STATE must
    emit fuse.chunk_halved + round.abort in the crashing run, and the
    resumed run must emit resume.restore with the HALVED chunk."""
    import jax

    sink = tmp_path / "m.jsonl"
    state = tmp_path / "fuse_state.npz"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    monkeypatch.setenv("HPNN_FUSE_CHUNK", "128")  # halving floor is 64
    obs._reset_for_tests()

    real = loop.train_epoch_lax
    boom = {"armed": True}

    def crash_once(*a, **k):
        if boom["armed"]:
            boom["armed"] = False
            raise jax.errors.JaxRuntimeError("worker crashed (simulated)")
        return real(*a, **k)

    monkeypatch.setattr(loop, "train_epoch_lax", crash_once)
    conf = _conf(tmp_path)
    with pytest.raises(jax.errors.JaxRuntimeError):
        driver.train_kernel(conf)

    recs = _read(sink)
    names = [r["ev"] for r in recs]
    halv = recs[names.index("fuse.chunk_halved")]
    assert halv["reason"] == "dispatch_crash"
    assert (halv["old"], halv["new"]) == (128, 64)
    assert names.index("fuse.chunk_halved") < names.index("round.abort")

    # second attempt: resumes from the checkpoint at the halved chunk
    obs._reset_for_tests()  # fresh stream position (append mode)
    conf2 = _conf(tmp_path)
    assert driver.train_kernel(conf2)
    recs2 = _read(sink)[len(recs):]
    names2 = [r["ev"] for r in recs2]
    res = recs2[names2.index("resume.restore")]
    assert res["done"] == 0 and res["chunk"] == 64
    assert names2.index("resume.restore") < names2.index("round.start")
    start2 = next(r for r in recs2 if r["ev"] == "round.start")
    assert start2["resumed"] is True
    assert next(r for r in recs2 if r["ev"] == "round.end")["samples"] == 6
    assert not state.exists()  # completed round dropped its checkpoint


# ----------------------------------------------------------------- report

def test_obs_report_renders_a_round(tmp_path, monkeypatch):
    import importlib.util

    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    conf = _conf(tmp_path)
    assert driver.train_kernel(conf)

    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "obs_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rep = mod.summarize(mod.load_events(str(sink)))
    assert rep["counters"]["train.samples"] == 6
    assert rep["histograms"]["train.n_iter"]["n"] == 6
    assert rep["chunk_timeline"] and rep["chunk_timeline"][0]["size"] == 6
    assert rep["summary"] is not None
    text = mod.render(rep)
    assert "driver.chunk_dispatch" in text
    assert "histogram train.n_iter" in text
    assert "fused chunk timeline" in text


def test_cli_metrics_flag_maps_to_configure(tmp_path, monkeypatch):
    """--metrics PATH on the CLIs is obs.configure(PATH)."""
    monkeypatch.delenv("HPNN_METRICS", raising=False)
    obs._reset_for_tests()
    from hpnn_tpu.cli import common

    argv, opts = common.extract_long_opts(
        ["--metrics", str(tmp_path / "m.jsonl"), "nn.conf"],
        valued=("batch", "epochs", "mesh", "profile", "lr", "metrics"),
    )
    assert argv == ["nn.conf"] and opts["metrics"].endswith("m.jsonl")
    obs.configure(opts["metrics"])
    assert obs.enabled() and obs.sink_path() == opts["metrics"]
