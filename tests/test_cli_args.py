"""CLI flag grammar: the byte-compatible single-dash grammar
(ref: /root/reference/tests/train_nn.c:33-58) plus the TPU-side
``--name`` extensions (cli/common.py)."""

import pytest

from hpnn_tpu import runtime
from hpnn_tpu.cli import common


@pytest.fixture(autouse=True)
def _fresh_runtime():
    runtime.init_runtime()
    yield
    runtime.init_runtime()


def test_combined_short_flags():
    # the reference accepts combined flags: -vvx
    assert common.parse_args(["-vvx", "nn.conf"], "t") == "nn.conf"
    assert runtime.return_verbose() == 2
    assert runtime.runtime().nn_dry is True


def test_numeric_flags_inline_and_split():
    assert common.parse_args(["-O4", "-B", "2", "-S8", "f.conf"], "t") == "f.conf"
    assert runtime.get_omp_threads() == 4
    assert runtime.get_omp_blas() == 2
    assert runtime.get_cuda_streams() == 8


def test_bad_numeric_parameter_errors(capsys):
    assert common.parse_args(["-O", "x", "f.conf"], "t") is None
    assert "bad -O parameter" in capsys.readouterr().err
    assert common.parse_args(["-O"], "t") is None  # missing value
    assert common.parse_args(["-O", "0", "f.conf"], "t") is None  # zero


def test_stream_zero_clamps_to_one():
    # -S 0 parses (the reference treats 0 streams as "no slicing");
    # the advisory setter clamps to 1
    assert common.parse_args(["-S", "0", "f.conf"], "t") == "f.conf"
    assert runtime.get_cuda_streams() == 1


def test_unknown_flag_and_double_filename(capsys):
    assert common.parse_args(["-q", "f.conf"], "t") is None
    assert common.parse_args(["a.conf", "b.conf"], "t") is None


def test_default_conf_filename():
    # no positional arg: the reference defaults to ./nn.conf
    assert common.parse_args([], "t") == "./nn.conf"


def test_help_returns_none(capsys):
    assert common.parse_args(["-h"], "t") is None
    # help goes to stdout, like the reference's printf help
    assert "usage" in capsys.readouterr().out.lower()


def test_extract_long_opts_forms():
    rest, opts = common.extract_long_opts(
        ["-v", "--batch", "64", "--mesh=2x4", "x.conf"],
        valued=("batch", "mesh"),
    )
    assert rest == ["-v", "x.conf"]
    assert opts == {"batch": "64", "mesh": "2x4"}


def test_extract_long_opts_errors(capsys):
    rest, opts = common.extract_long_opts(["--nope"], valued=("batch",))
    assert rest is None and opts is None
    rest, opts = common.extract_long_opts(["--batch"], valued=("batch",))
    assert rest is None  # missing value


def test_validate_long_opts():
    assert common.validate_long_opts({"batch": "64", "mesh": "2x4",
                                      "lr": "0.5"})
    assert not common.validate_long_opts({"batch": "0"})
    assert not common.validate_long_opts({"mesh": "2x"})
    assert not common.validate_long_opts({"lr": "-1"})
    assert not common.validate_long_opts({"lr": "abc"})


def test_tp_mesh_rejects_data_axis():
    with pytest.raises(ValueError, match="1xM"):
        common.tp_mesh("2x4")
    m = common.tp_mesh("1x4")
    assert m.shape == {"data": 1, "model": 4}
