"""Resident serving subsystem (hpnn_tpu/serve/, docs/serving.md).

Acceptance bar (ISSUE): a CPU Session serves 64 concurrent mixed-size
requests through the bucket menu with exactly one compile per
(kernel, bucket) after warmup — proven via the obs ``serve.compile``
counter — and every served output is **bitwise-equal** to a direct
``models.ann.forward`` of the same rows.  Batcher semantics
(coalescing / deadlines / backpressure) are asserted with a fake
clock and the public ``drain_once`` — no sleeps.
"""

import http.client
import json
import sys
import threading

import numpy as np
import pytest

from hpnn_tpu import serve
from hpnn_tpu.models import ann, kernel as kernel_mod, snn
from hpnn_tpu.serve import batcher as batcher_mod, engine as engine_mod
from hpnn_tpu.serve.registry import Registry, RegistryError


def _kernel(seed=7, n_in=8, hiddens=(5,), n_out=2):
    k, _ = kernel_mod.generate(seed, n_in, list(hiddens), n_out)
    return k


def _direct_ann(kernel, rows):
    """Reference outputs: the per-sample forward, row by row."""
    return np.stack([np.asarray(ann.run(kernel.weights, x))
                     for x in np.atleast_2d(rows)])


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --------------------------------------------------------------- import
def test_serve_import_is_jax_free():
    """import hpnn_tpu.serve must not drag jax in (obs discipline);
    asserted in a subprocess so this file's own jax use can't mask it."""
    import subprocess

    code = ("import sys; import hpnn_tpu.serve; "
            "sys.exit(1 if 'jax' in sys.modules else 0)")
    proc = subprocess.run([sys.executable, "-c", code],
                          cwd="/root/repo", capture_output=True)
    assert proc.returncode == 0, proc.stderr.decode()


# ------------------------------------------------------------- registry
def test_registry_register_validate_and_versions():
    reg = Registry()
    e0 = reg.register("k", _kernel())
    assert (e0.version, e0.model, e0.path) == (0, "ann", None)
    assert e0.n_inputs == 8 and e0.n_outputs == 2
    e1 = reg.register("k", _kernel(seed=8))
    assert e1.version == 1           # replace bumps the version
    assert reg.get("k") is e1
    assert reg.names() == ["k"]
    with pytest.raises(KeyError):
        reg.get("nope")
    # a broken layer chain must never become resident
    bad = kernel_mod.Kernel((np.zeros((5, 8)), np.zeros((2, 6))))
    with pytest.raises(RegistryError):
        reg.register("bad", bad)
    with pytest.raises(RegistryError):
        reg.register("k", _kernel(), model="cnn")


def test_registry_load_and_hot_reload(tmp_path):
    import os

    path = tmp_path / "kernel.opt"
    with open(path, "w") as fp:
        kernel_mod.dump("t", _kernel(seed=1), fp)
    reg = Registry()
    e0 = reg.load("k", str(path))
    assert e0.version == 0 and e0.path == str(path)
    # same mtime → no reload
    assert reg.maybe_reload("k") is False
    assert reg.get("k").version == 0
    # overwrite with new weights, force a new mtime
    with open(path, "w") as fp:
        kernel_mod.dump("t", _kernel(seed=2), fp)
    os.utime(path, (e0.mtime + 10, e0.mtime + 10))
    assert reg.maybe_reload("k") is True
    e1 = reg.get("k")
    assert e1.version == 1
    assert not np.array_equal(np.asarray(e1.kernel.weights[0]),
                              np.asarray(e0.kernel.weights[0]))
    # a torn overwrite keeps the resident version (counted, not raised)
    path.write_text("[name] broken\n")
    os.utime(path, (e0.mtime + 20, e0.mtime + 20))
    assert reg.maybe_reload("k") is False
    assert reg.get("k") is e1
    # vanished file: same — serving must not drop the kernel
    path.unlink()
    assert reg.maybe_reload("k") is False
    assert reg.get("k") is e1
    # memory-registered kernels have no reload source
    reg.register("m", _kernel())
    assert reg.maybe_reload("m") is False
    with pytest.raises(RegistryError):
        reg.reload("m")


# -------------------------------------------------------------- batcher
def test_batcher_coalesces_within_max_batch():
    clock = FakeClock()
    batches = []
    b = batcher_mod.Batcher(lambda p: batches.append(p) or list(p),
                            max_batch=16, clock=clock, start=False)
    reqs = [b.submit(i, rows=2) for i in range(3)]
    assert b.drain_once() == 3       # all three in ONE dispatch
    assert batches == [[0, 1, 2]]
    assert [b.result(r, timeout_s=0) for r in reqs] == [0, 1, 2]
    assert b.depth() == 0


def test_batcher_splits_on_row_budget():
    clock = FakeClock()
    batches = []
    b = batcher_mod.Batcher(lambda p: batches.append(p) or list(p),
                            max_batch=16, clock=clock, start=False)
    b.submit("a", rows=10)
    b.submit("b", rows=10)           # 20 rows > max_batch: next batch
    b.submit("c", rows=6)
    assert b.drain_once() == 1       # "a" alone (b would overflow)
    assert b.drain_once() == 2       # "b" + "c" = 16 rows exactly
    assert batches == [["a"], ["b", "c"]]
    # an oversized single request still dispatches (engine chunks it)
    b.submit("huge", rows=40)
    assert b.drain_once() == 1
    assert batches[-1] == ["huge"]


def test_batcher_deadline_expires_in_queue():
    clock = FakeClock()
    served = []
    b = batcher_mod.Batcher(lambda p: served.extend(p) or list(p),
                            max_batch=16, clock=clock, start=False)
    dead = b.submit("late", timeout_s=1.0)
    clock.advance(2.0)
    live = b.submit("fresh", timeout_s=5.0)
    assert b.drain_once() == 1       # only the live request dispatched
    assert served == ["fresh"]
    assert b.result(live, timeout_s=0) == "fresh"
    with pytest.raises(batcher_mod.DeadlineExceeded) as ei:
        b.result(dead, timeout_s=0)
    assert ei.value.retriable is True


def test_batcher_backpressure_queue_full():
    clock = FakeClock()
    b = batcher_mod.Batcher(lambda p: list(p), max_batch=4,
                            max_depth=2, clock=clock, start=False)
    b.submit("a")
    b.submit("b")
    with pytest.raises(batcher_mod.QueueFull) as ei:
        b.submit("c")
    assert ei.value.retriable is True
    assert b.drain_once() == 2       # draining frees the queue again
    b.submit("c")


def test_batcher_dispatch_error_fails_whole_batch():
    clock = FakeClock()

    def boom(payloads):
        raise RuntimeError("device fell over")

    b = batcher_mod.Batcher(boom, max_batch=16, clock=clock, start=False)
    r1, r2 = b.submit("a"), b.submit("b")
    assert b.drain_once() == 2
    for r in (r1, r2):
        with pytest.raises(RuntimeError, match="device fell over"):
            b.result(r, timeout_s=0)


def test_batcher_close_fails_parked_requests():
    clock = FakeClock()
    b = batcher_mod.Batcher(lambda p: list(p), clock=clock, start=False)
    r = b.submit("parked")
    b.close()
    with pytest.raises(RuntimeError, match="closed"):
        b.result(r, timeout_s=0)
    with pytest.raises(RuntimeError, match="closed"):
        b.submit("x")


# --------------------------------------------------------------- engine
def test_bucket_menu_and_bucket_for():
    assert engine_mod.bucket_menu(64, 4) == (8, 16, 32, 64)
    assert engine_mod.bucket_menu(48, 4) == (8, 16, 32, 64)  # round up
    assert engine_mod.bucket_menu(16, 3) == (4, 8, 16)
    assert engine_mod.bucket_menu(1, 4) == (1,)   # stops at bucket 1
    with pytest.raises(ValueError):
        engine_mod.bucket_menu(0)
    menu = (8, 16, 32, 64)
    assert engine_mod.bucket_for(menu, 1) == 8
    assert engine_mod.bucket_for(menu, 8) == 8
    assert engine_mod.bucket_for(menu, 9) == 16
    assert engine_mod.bucket_for(menu, 64) == 64
    assert engine_mod.bucket_for(menu, 200) == 64  # caller chunks


@pytest.mark.parametrize("rows", [1, 3, 8, 11, 16, 40])
def test_engine_padded_outputs_bitwise_equal_direct_forward(rows):
    """The acceptance numerics: padding/chunking through the bucket
    menu must not perturb a single bit vs the per-sample forward —
    rows=11 pads into the 16 bucket, rows=40 chunks through the top
    bucket twice."""
    k = _kernel(seed=3)
    reg = Registry()
    entry = reg.register("k", k)
    eng = engine_mod.Engine(reg, max_batch=16, n_buckets=3)
    rng = np.random.RandomState(rows)
    X = rng.uniform(-1.0, 1.0, size=(rows, 8))
    out = eng.run_rows(entry, X)
    want = _direct_ann(k, X)
    assert out.dtype == want.dtype == np.float64
    assert np.array_equal(out, want)  # bitwise, not allclose


def test_engine_snn_outputs_bitwise_equal_direct_forward():
    k = _kernel(seed=5)
    reg = Registry()
    entry = reg.register("k", k, model="snn")
    eng = engine_mod.Engine(reg, max_batch=8, n_buckets=2)
    rng = np.random.RandomState(0)
    X = rng.uniform(-1.0, 1.0, size=(5, 8))
    out = eng.run_rows(entry, X)
    want = np.stack([np.asarray(snn.run(k.weights, x)) for x in X])
    assert np.array_equal(out, want)


def test_engine_warmup_compiles_menu_once():
    reg = Registry()
    reg.register("k", _kernel())
    eng = engine_mod.Engine(reg, max_batch=16, n_buckets=3)
    assert eng.compiled_count() == 0
    assert eng.warmup() == 3
    assert eng.compiled_count() == 3
    eng.warmup()                      # idempotent: cache hits only
    assert eng.compiled_count() == 3


def test_engine_compiled_mode_aot_executables():
    """The compiled mode (TPU/GPU default) is CPU-testable: real AOT
    executables per bucket, padded dispatch, ulp-level agreement with
    the per-sample path (bitwise is parity mode's contract — XLA does
    not promise codegen-stable numerics across program shapes)."""
    k = _kernel(seed=3)
    reg = Registry()
    entry = reg.register("k", k)
    eng = engine_mod.Engine(reg, max_batch=16, n_buckets=3,
                            mode="compiled")
    assert eng.mode == "compiled"
    assert eng.warmup() == 3
    rng = np.random.RandomState(2)
    X = rng.uniform(-1, 1, size=(11, 8))
    out = eng.run_rows(entry, X)      # pads into the 16 bucket
    np.testing.assert_allclose(out, _direct_ann(k, X),
                               rtol=0, atol=1e-12)
    assert eng.compiled_count() == 3  # dispatch compiled nothing new


def test_engine_mode_selection(monkeypatch):
    monkeypatch.setenv("HPNN_SERVE_MODE", "compiled")
    eng = engine_mod.Engine(Registry(), max_batch=8, n_buckets=2)
    assert eng.mode == "compiled"
    monkeypatch.delenv("HPNN_SERVE_MODE")
    eng2 = engine_mod.Engine(Registry(), max_batch=8, n_buckets=2)
    assert eng2.mode == "parity"      # CPU backend default
    with pytest.raises(ValueError, match="serve mode"):
        engine_mod.Engine(Registry(), mode="jitted")


def test_engine_dispatch_splits_results_per_payload():
    k = _kernel()
    reg = Registry()
    reg.register("k", k)
    eng = engine_mod.Engine(reg, max_batch=16, n_buckets=3)
    rng = np.random.RandomState(1)
    blocks = [rng.uniform(-1, 1, size=(r, 8)) for r in (1, 3, 2)]
    outs = eng.dispatch("k", blocks)
    assert [o.shape for o in outs] == [(1, 2), (3, 2), (2, 2)]
    for blk, out in zip(blocks, outs):
        assert np.array_equal(out, _direct_ann(k, blk))
    with pytest.raises(ValueError, match="n_inputs"):
        eng.dispatch("k", [np.zeros((2, 5))])


def test_engine_evict_keeps_requested_version():
    reg = Registry()
    reg.register("k", _kernel(seed=1))
    eng = engine_mod.Engine(reg, max_batch=8, n_buckets=2)
    eng.warmup()
    reg.register("k", _kernel(seed=2))   # version 1
    eng.warmup()
    assert eng.compiled_count() == 4     # both versions resident
    eng.evict("k", keep_version=1)
    assert eng.compiled_count() == 2


# -------------------------------------------------- session acceptance
def _read_sink(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def test_session_64_concurrent_requests_one_compile_per_bucket(tmp_path):
    """THE acceptance test: 64 concurrent mixed-size requests through
    ≤4 buckets, exactly one compile per (kernel, bucket) after warmup
    (obs serve.compile counter), outputs bitwise-equal to direct
    ann.forward."""
    from hpnn_tpu import obs

    sink = tmp_path / "obs.jsonl"
    obs.configure(str(sink))
    try:
        k = _kernel(seed=9)
        sess = serve.Session(max_batch=64, n_buckets=4, max_wait_ms=2.0)
        sess.register_kernel("k", k)          # warmup inside
        assert list(sess.engine.buckets) == [8, 16, 32, 64]
        n_buckets = len(sess.engine.buckets)
        assert sess.engine.compiled_count() == n_buckets

        rng = np.random.RandomState(42)
        inputs = [rng.uniform(-1.0, 1.0, size=((i % 8) + 1, 8))
                  for i in range(64)]
        outs: list = [None] * 64
        errs: list = []

        def client(i):
            try:
                outs[i] = sess.infer("k", inputs[i], timeout_s=30.0)
            except Exception as exc:  # collected, asserted empty below
                errs.append((i, repr(exc)))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(64)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        for x, out in zip(inputs, outs):
            assert np.array_equal(out, _direct_ann(k, x))
        # steady state: serving compiled NOTHING beyond the menu
        assert sess.engine.compiled_count() == n_buckets
        sess.close()
    finally:
        obs.configure(None)

    recs = _read_sink(sink)
    compiles = [r for r in recs if r["ev"] == "serve.compile"]
    assert len(compiles) == n_buckets
    assert sorted(r["bucket"] for r in compiles) == [8, 16, 32, 64]
    assert all(r["kind"] == "count" for r in compiles)


def test_session_single_vector_and_unknown_kernel():
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    k = _kernel()
    sess.register_kernel("k", k)
    out = sess.infer("k", np.zeros(8))
    assert out.shape == (2,)
    assert np.array_equal(out, _direct_ann(k, np.zeros(8))[0])
    with pytest.raises(KeyError):
        sess.infer("nope", np.zeros(8))
    sess.close()


def test_session_hot_reload_changes_outputs(tmp_path):
    import os

    path = tmp_path / "kernel.opt"
    with open(path, "w") as fp:
        kernel_mod.dump("t", _kernel(seed=1), fp)
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    e0 = sess.load_kernel("k", str(path))
    x = np.ones(8)
    out0 = sess.infer("k", x)
    with open(path, "w") as fp:
        kernel_mod.dump("t", _kernel(seed=2), fp)
    os.utime(path, (e0.mtime + 10, e0.mtime + 10))
    assert sess.maybe_reload("k") is True
    assert sess.registry.get("k").version == 1
    out1 = sess.infer("k", x)
    assert not np.array_equal(out0, out1)
    # the old version's executables were evicted: menu-sized cache
    assert sess.engine.compiled_count() == len(sess.engine.buckets)
    assert sess.maybe_reload("k") is False   # unchanged mtime
    sess.close()


def test_obs_event_schema(tmp_path):
    """Every serve.* record carries the obs envelope (ts/ev/kind) and
    the subsystem emits its catalog events during one served round."""
    from hpnn_tpu import obs

    sink = tmp_path / "obs.jsonl"
    obs.configure(str(sink))
    try:
        sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
        sess.register_kernel("k", _kernel())
        sess.infer("k", np.zeros((3, 8)))
        sess.close()
    finally:
        obs.configure(None)
    recs = [r for r in _read_sink(sink) if r["ev"].startswith("serve.")]
    assert recs
    for r in recs:
        assert {"ts", "ev", "kind"} <= set(r)
        assert r["kind"] in ("event", "count", "gauge", "timer",
                             "hist", "summary")
    names = {r["ev"] for r in recs}
    for want in ("serve.kernel_load", "serve.warmup", "serve.compile",
                 "serve.compile_time", "serve.queue_depth",
                 "serve.wait_ms", "serve.batch_size",
                 "serve.bucket_hit", "serve.forward", "serve.request"):
        assert want in names, f"missing {want} in {sorted(names)}"


# ------------------------------------------------------------ HTTP/CLI
def test_serve_nn_http_round_trip(workdir_conf, capsys):
    from hpnn_tpu import config
    from hpnn_tpu.cli import serve_nn

    conf = config.load_conf(workdir_conf)
    session, server = serve_nn.build_from_conf(conf, max_batch=8,
                                               n_buckets=2, port=0)
    host, port = server.server_address[:2]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        cn = http.client.HTTPConnection(host, port, timeout=10)
        cn.request("GET", "/healthz")
        health = json.loads(cn.getresponse().read())
        assert health["kernels"] == ["E2E"]
        assert health["buckets"] == [4, 8]

        x = np.linspace(-1, 1, 8)
        body = json.dumps({"kernel": "E2E", "inputs": x.tolist()})
        cn.request("POST", "/v1/infer", body=body,
                   headers={"Content-Type": "application/json"})
        resp = cn.getresponse()
        assert resp.status == 200
        out = np.asarray(json.loads(resp.read())["outputs"])
        assert np.array_equal(out, _direct_ann(conf.kernel, x)[0])

        def roundtrip(path, body):
            cn.request("POST", path, body=body)
            resp = cn.getresponse()
            resp.read()  # drain: keep-alive needs the body consumed
            return resp.status

        assert roundtrip(
            "/v1/infer",
            json.dumps({"kernel": "nope", "inputs": [0.0]})) == 404
        assert roundtrip("/v1/infer", b"not json") == 400
        # memory-registered kernel: reload is a clean client error
        assert roundtrip(
            "/v1/reload", json.dumps({"kernel": "E2E"})) == 400
        cn.close()
    finally:
        server.shutdown()
        server.server_close()
        session.close()
    # the token protocol stays silent: no stdout from serving
    assert capsys.readouterr().out == ""


@pytest.fixture
def workdir_conf(tmp_path, monkeypatch):
    """A minimal generate-init conf (no samples needed for serving)."""
    p = tmp_path / "nn.conf"
    p.write_text(
        "[name] E2E\n[type] ANN\n[init] generate\n[seed] 1234\n"
        "[input] 8\n[hidden] 6\n[output] 2\n[train] BP\n"
        "[sample_dir] ./samples\n[test_dir] ./samples\n")
    monkeypatch.chdir(tmp_path)
    return str(p)


def test_build_from_conf_rejects_unservable(workdir_conf):
    from hpnn_tpu import config
    from hpnn_tpu.cli import serve_nn

    conf = config.load_conf(workdir_conf)
    conf.kernel = None
    with pytest.raises(ValueError, match="no kernel"):
        serve_nn.build_from_conf(conf)


def test_validate_long_opts_serving_knobs(capsys):
    from hpnn_tpu.cli import common

    assert common.validate_long_opts({"port": "8700"}) is True
    assert common.validate_long_opts({"port": "70000"}) is False
    assert "bad --port" in capsys.readouterr().err
    assert common.validate_long_opts({"port": "nope"}) is False
    assert common.validate_long_opts({"max-batch": "16"}) is True
    assert common.validate_long_opts({"max-batch": "0"}) is False
    assert common.validate_long_opts({"max-wait-ms": "2.5"}) is True
    assert common.validate_long_opts({"max-wait-ms": "0"}) is True
    assert common.validate_long_opts({"max-wait-ms": "-1"}) is False
    assert common.validate_long_opts({"max-wait-ms": "soon"}) is False


def test_bench_serve_smoke_reports_latency_and_compile_census():
    sys.path.insert(0, "/root/repo/tools")
    try:
        import bench_serve
    finally:
        sys.path.pop(0)
    out = bench_serve.run_serve_bench(
        n_in=8, hiddens=[5], n_out=2, n_clients=4, n_requests=3,
        max_batch=8, n_buckets=2, max_wait_ms=1.0)
    assert "errors" not in out, out
    assert out["requests_served"] == 12
    assert out["latency_ms"]["p50"] is not None
    assert out["latency_ms"]["p99"] >= out["latency_ms"]["p50"]
    assert out["throughput_rps"] > 0
    # the steady-state invariant, reported by the bench itself
    assert (out["compiled_after_load"] == out["compiled_after_warmup"]
            == len(out["buckets"]))
