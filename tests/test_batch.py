"""Batched DP training/eval mode (train/batch.py).

Acceptance bar is the mode's own (SURVEY.md §7.6): accuracy on a
separable problem, plus exact agreement between the vectorized eval and
the per-sample driver's argmax quirks.
"""

import numpy as np
import pytest

from hpnn_tpu.config import NNConf, NNTrain, NNType
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.train import batch as batch_mod, driver


def _write_samples(d, n, n_in=8, n_out=2, snn=False, seed=0):
    rng = np.random.RandomState(seed)
    centers = np.stack([np.r_[np.ones(n_in // 2), -np.ones(n_in // 2)],
                        np.r_[-np.ones(n_in // 2), np.ones(n_in // 2)]])
    for i in range(n):
        c = i % 2
        x = centers[c] + 0.1 * rng.normal(size=n_in)
        lo = 0.0 if snn else -1.0
        t = np.full(n_out, lo)
        t[c] = 1.0
        with open(d / f"s{i:05d}.txt", "w") as fp:
            fp.write(f"[input] {n_in}\n" + " ".join(f"{v:.5f}" for v in x) + "\n")
            fp.write(f"[output] {n_out}\n" + " ".join(f"{v:.1f}" for v in t) + "\n")


def _conf(tmp_path, *, snn=False, train=NNTrain.BP, n=24):
    sdir = tmp_path / "samples"
    sdir.mkdir()
    _write_samples(sdir, n, snn=snn)
    k, _ = kernel_mod.generate(777, 8, [6], 2)
    return NNConf(
        name="t",
        type=NNType.SNN if snn else NNType.ANN,
        seed=1,
        kernel=k,
        train=train,
        samples=str(sdir),
        tests=str(sdir),
    )


@pytest.mark.parametrize("snn,train", [
    (False, NNTrain.BP), (False, NNTrain.BPM), (True, NNTrain.BP),
    (True, NNTrain.BPM),
])
def test_batched_training_learns(tmp_path, snn, train):
    conf = _conf(tmp_path, snn=snn, train=train)
    w0 = [np.asarray(w).copy() for w in conf.kernel.weights]
    assert batch_mod.train_kernel_batched(conf, batch_size=8, epochs=60)
    assert any(
        not np.allclose(np.asarray(a), b)
        for a, b in zip(conf.kernel.weights, w0)
    )
    # learned: batched eval counts all samples correct
    names, X, T = __import__("hpnn_tpu.fileio.samples", fromlist=["read_dir"]).read_dir(conf.samples)
    import jax.numpy as jnp

    ev = batch_mod.make_eval_fn(model="snn" if snn else "ann")
    weights = tuple(jnp.asarray(np.asarray(w)) for w in conf.kernel.weights)
    out = np.asarray(ev(weights, jnp.asarray(X)))
    ok = batch_mod.accuracy_counts(out, T, "snn" if snn else "ann")
    assert ok == len(names)


def test_batched_eval_matches_per_sample(tmp_path, capsys, monkeypatch):
    """run_kernel_batched emits the SAME stream as the per-sample
    driver — same verdicts in the same seeded shuffle order (ref order
    contract: src/libhpnn.c:1218-1229) — including the header-only line
    for an unreadable file.  HPNN_NO_BATCH_EVAL pins run_kernel to its
    TRUE per-sample forward so the comparison is between independent
    numeric paths, not the shared vmapped eval."""
    from hpnn_tpu.utils import logging as log

    log.set_verbose(2)
    conf = _conf(tmp_path, n=12)
    (tmp_path / "samples" / "s99999.txt").write_text("[input] zero\n")
    monkeypatch.setenv("HPNN_NO_BATCH_EVAL", "1")
    driver.run_kernel(conf)
    monkeypatch.delenv("HPNN_NO_BATCH_EVAL")
    per_sample = capsys.readouterr().out
    (tmp_path / "b").mkdir()
    conf2 = _conf(tmp_path / "b", n=12)
    (tmp_path / "b" / "samples" / "s99999.txt").write_text("[input] zero\n")
    conf2.kernel = conf.kernel
    batch_mod.run_kernel_batched(conf2)
    batched = capsys.readouterr().out
    assert "TESTING FILE:" in per_sample
    assert batched == per_sample


def test_batch_wrap_warns(tmp_path, capsys):
    """The tail wrap that re-trains some samples per epoch is logged
    (no silent caps)."""
    from hpnn_tpu.utils import logging as log

    log.set_verbose(1)
    conf = _conf(tmp_path, n=10)
    assert batch_mod.train_kernel_batched(conf, batch_size=8, epochs=1)
    captured = capsys.readouterr()
    # warnings go to stderr — stdout is the metrics token stream
    assert "batch wrap: 6 duplicate sample slots per epoch" in captured.err
    assert "batch wrap" not in captured.out

    log.set_verbose(1)
    (tmp_path / "b").mkdir()
    conf2 = _conf(tmp_path / "b", n=16)
    assert batch_mod.train_kernel_batched(conf2, batch_size=8, epochs=1)
    captured = capsys.readouterr()
    assert "batch wrap" not in captured.err + captured.out


def test_accuracy_counts_quirks():
    """C quirks: all-below-threshold ANN target -> class index 1;
    SNN all-nonpositive output -> guess 0."""
    out = np.array([[0.9, 0.1], [0.1, 0.9]])
    T = np.array([[-1.0, -1.0], [-1.0, 1.0]])  # row0: no target above 0.5
    # row0: is_ok=1 (quirk), guess=0 -> wrong; row1: is_ok=1, guess=1 -> ok
    assert batch_mod.accuracy_counts(out, T, "ann") == 1
    out2 = np.array([[-0.5, -0.2]])
    T2 = np.array([[1.0, 0.0]])
    # SNN: no positive output -> guess stays 0 == is_ok 0
    assert batch_mod.accuracy_counts(out2, T2, "snn") == 1


def test_lr_override_threads_through(tmp_path):
    """--lr equivalent: a huge lr changes the trajectory vs default."""
    conf_a = _conf(tmp_path, n=8)
    conf_b = _conf_copy(conf_a)  # same data + kernel, different lr
    assert batch_mod.train_kernel_batched(conf_a, batch_size=8, epochs=3)
    assert batch_mod.train_kernel_batched(conf_b, batch_size=8, epochs=3,
                                          lr=5.0)
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
        for a, b in zip(conf_a.kernel.weights, conf_b.kernel.weights)
    )


def _conf_copy(conf):
    k = kernel_mod.Kernel(tuple(np.asarray(w).copy() for w in conf.kernel.weights))
    return NNConf(name=conf.name, type=conf.type, seed=conf.seed, kernel=k,
                  train=conf.train, samples=conf.samples, tests=conf.tests)


def test_cli_lr_requires_batch(tmp_path, capsys, monkeypatch):
    from hpnn_tpu.cli import train_nn as cli

    monkeypatch.chdir(tmp_path)
    assert cli.main(["--lr", "0.4", "nn.conf"]) == -1
    assert "requires --batch" in capsys.readouterr().err
    assert cli.main(["--batch", "8", "--lr", "bogus", "nn.conf"]) == -1
    assert "bad --lr" in capsys.readouterr().err


def test_device_count_matches_accuracy_counts():
    """On-device count (multi-epoch fused trainer) == the numpy
    accuracy_counts quirks, including the no-positive-output and
    no-hot-target edge cases, for both models."""
    import jax.numpy as jnp

    from hpnn_tpu.models import kernel as kernel_mod

    rng = np.random.RandomState(11)
    k, _ = kernel_mod.generate(5, 6, [5], 4)
    weights = tuple(jnp.asarray(np.asarray(w), jnp.float32) for w in k.weights)
    for model in ("ann", "snn"):
        lo = 0.0 if model == "snn" else -1.0
        X = rng.uniform(-2, 2, (32, 6)).astype(np.float32)
        T = np.full((32, 4), lo, dtype=np.float32)
        hot = rng.randint(0, 4, 32)
        T[np.arange(32), hot] = 1.0
        T[0, :] = lo  # no hot target at all (is_ok quirk default)
        ev = batch_mod.make_eval_fn(model=model)
        out = np.asarray(ev(weights, jnp.asarray(X)))
        want = batch_mod.accuracy_counts(out, T, model)
        cf = batch_mod.make_device_count_fn(model=model)
        got = int(cf(weights, jnp.asarray(X), jnp.asarray(T)))
        assert got == want, (model, got, want)


def test_multi_epoch_fn_matches_epoch_loop(tmp_path):
    """The multi-epoch fused dispatch produces the same stream content
    (per-epoch losses and counts) as epoch-by-epoch training."""
    import jax.numpy as jnp

    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.parallel import dp

    rng = np.random.RandomState(2)
    k, _ = kernel_mod.generate(9, 6, [5], 3)
    weights = tuple(jnp.asarray(np.asarray(w), jnp.float32) for w in k.weights)
    n, B, E = 24, 8, 3
    X = jnp.asarray(rng.uniform(-1, 1, (n, 6)), jnp.float32)
    T = np.full((n, 3), -1.0, dtype=np.float32)
    T[np.arange(n), rng.randint(0, 3, n)] = 1.0
    T = jnp.asarray(T)
    idx = jnp.asarray(
        np.stack([np.random.RandomState(s).permutation(n).reshape(-1, B)
                  for s in range(E)]), jnp.int32)

    def step_fn(w, m, Xb, Tb):
        return dp.train_step_math(w, m, Xb, Tb, model="ann",
                                  momentum=False, lr=0.05, alpha=0.2)

    mf = batch_mod.make_multi_epoch_fn(
        step_fn, batch_mod.make_device_count_fn(model="ann"))
    w_all, _, losses, counts = mf(weights, (), X, T, idx)

    w = weights
    for e in range(E):
        for s in range(idx.shape[1]):
            w, _, l = step_fn(w, (), X[idx[e, s]], T[idx[e, s]])
            np.testing.assert_allclose(float(l), float(losses[e, s]),
                                       rtol=1e-5)
        cf = batch_mod.make_device_count_fn(model="ann")
        assert int(cf(w, X, T)) == int(counts[e])
    for a, b in zip(w_all, w):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_batch_crash_resume(tmp_path, capsys, monkeypatch):
    """HPNN_FUSE_STATE in batch mode: a run killed mid-protocol resumes
    from the per-dispatch checkpoint — remaining epoch tokens continue
    the numbering and final weights match an uninterrupted run."""
    import jax

    from hpnn_tpu.parallel import dp

    from hpnn_tpu.utils import logging as log

    epochs = 6
    conf = _conf(tmp_path)
    log.set_verbose(2)  # epoch tokens print at NN_OUT (autouse reset)
    assert batch_mod.train_kernel_batched(conf, batch_size=8, epochs=epochs)
    want = capsys.readouterr().out
    want_w = [np.asarray(w).copy() for w in conf.kernel.weights]

    state = tmp_path / "batch.state"
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    # crash the 4th epoch dispatch (the suite's 8-device mesh takes the
    # per-epoch non-gather path, so one epoch = one dispatch)
    real_make = dp.make_gspmd_epoch_fn
    calls = {"n": 0}

    def make_dying(*a, **kw):
        real = real_make(*a, **kw)

        def fn(*fa, **fkw):
            calls["n"] += 1
            if calls["n"] == 4:
                raise jax.errors.JaxRuntimeError(
                    "UNAVAILABLE: TPU worker process crashed (simulated)")
            return real(*fa, **fkw)

        return fn

    monkeypatch.setattr(dp, "make_gspmd_epoch_fn", make_dying)
    (tmp_path / "run2").mkdir()
    conf2 = _conf(tmp_path / "run2")
    with pytest.raises(jax.errors.JaxRuntimeError):
        batch_mod.train_kernel_batched(conf2, batch_size=8, epochs=epochs)
    part1 = capsys.readouterr().out
    assert state.exists()
    z = np.load(state, allow_pickle=False)
    assert int(z["done"]) == 3  # three epochs survived the crash

    monkeypatch.setattr(dp, "make_gspmd_epoch_fn", real_make)
    (tmp_path / "run3").mkdir()
    conf3 = _conf(tmp_path / "run3")
    assert batch_mod.train_kernel_batched(conf3, batch_size=8, epochs=epochs)
    part2 = capsys.readouterr().out

    def epoch_lines(s):
        return [ln for ln in s.splitlines() if "BATCH EPOCH" in ln]

    # crashed run printed epochs 1-3, the resume 4-6; together = baseline
    assert len(epoch_lines(want)) == epochs  # tokens actually emitted
    assert epoch_lines(part1) + epoch_lines(part2) == epoch_lines(want)
    assert not state.exists()  # completed run cleans up
    for a, b in zip(conf3.kernel.weights, want_w):
        np.testing.assert_allclose(np.asarray(a), b, atol=1e-12)


def test_batch_pallas_compile_fallback(tmp_path, capsys, monkeypatch):
    """A fused-kernel compile failure on the first dispatch falls back
    to the XLA step instead of aborting (advisor r3): forcing the
    Pallas gate open on the CPU backend makes the first dispatch fail
    exactly like an unsupported topology would on TPU."""
    import jax

    from hpnn_tpu.utils import logging as log

    # f32 both runs: the Pallas gate requires f32 (the suite default is
    # x64), and the baseline must share the compute dtype for its
    # tokens to be comparable
    monkeypatch.setenv("HPNN_DTYPE", "float32")
    conf = _conf(tmp_path)
    log.set_verbose(2)  # epoch tokens print at NN_OUT (autouse reset)
    assert batch_mod.train_kernel_batched(
        conf, batch_size=8, epochs=2, mesh_spec="1x1")
    want = capsys.readouterr().out
    want_w = [np.asarray(w).copy() for w in conf.kernel.weights]

    monkeypatch.setenv("HPNN_PALLAS", "1")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    (tmp_path / "run2").mkdir()
    conf2 = _conf(tmp_path / "run2")
    assert batch_mod.train_kernel_batched(
        conf2, batch_size=8, epochs=2, mesh_spec="1x1")
    captured = capsys.readouterr()
    got = captured.out
    # the fused dispatch really was attempted and really fell back
    assert "falling back to the XLA step" in captured.err
    # same token stream and identical weights as the clean XLA run
    want_lines = [ln for ln in want.splitlines() if "BATCH EPOCH" in ln]
    assert len(want_lines) == 2
    assert [ln for ln in got.splitlines() if "BATCH EPOCH" in ln] == \
        want_lines
    for a, b in zip(conf2.kernel.weights, want_w):
        np.testing.assert_allclose(np.asarray(a), b, atol=1e-12)


def test_batch_stall_halves_dispatch_cap(tmp_path, capsys, monkeypatch):
    """A batch dispatch killed WITHOUT any handler running (tutorial
    timeout SIGKILL) must shrink the gather-path epochs-per-dispatch
    cap on each zero-progress resume, like the fused-round chunk."""
    from hpnn_tpu.utils import logging as log

    conf = _conf(tmp_path)
    state = tmp_path / "batch.state"
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    log.set_verbose(2)  # epoch tokens print at NN_OUT (autouse reset)

    def killed_make(*a, **kw):
        def fn(*fa, **fkw):
            raise KeyboardInterrupt  # models SIGKILL: no handler runs

        return fn

    monkeypatch.setattr(batch_mod, "make_multi_epoch_fn", killed_make)
    monkeypatch.setattr(batch_mod, "make_multi_epoch_bank_fn", killed_make)
    # n=24, B=8 -> n_steps=3 -> heuristic cap 65536//3 = 21845, rounded
    # down to whole bank-refresh groups (R=8 default); each stalled
    # resume halves then re-rounds
    expect = [21840, 10920, 5456]
    for want_cap in expect:
        with pytest.raises(KeyboardInterrupt):
            batch_mod.train_kernel_batched(
                _conf_copy(conf), batch_size=8, epochs=6, mesh_spec="1x1")
        z = np.load(state, allow_pickle=False)
        assert int(z["chunk"]) == want_cap
        assert int(z["done"]) == 0
    capsys.readouterr()

    # a surviving attempt completes from the shrunken cap; tokens match
    # an uninterrupted run
    monkeypatch.undo()
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    c2 = _conf_copy(conf)
    assert batch_mod.train_kernel_batched(
        c2, batch_size=8, epochs=6, mesh_spec="1x1")
    got = capsys.readouterr().out
    monkeypatch.delenv("HPNN_FUSE_STATE")
    c3 = _conf_copy(conf)
    assert batch_mod.train_kernel_batched(
        c3, batch_size=8, epochs=6, mesh_spec="1x1")
    want = capsys.readouterr().out
    got_lines = [ln for ln in got.splitlines() if "BATCH EPOCH" in ln]
    want_lines = [ln for ln in want.splitlines() if "BATCH EPOCH" in ln]
    assert len(want_lines) == 6 and got_lines == want_lines
    for a, b in zip(c2.kernel.weights, c3.kernel.weights):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-12)
    assert not state.exists()


@pytest.mark.parametrize("snn,train", [
    (False, NNTrain.BP), (True, NNTrain.BPM),
])
def test_bank_matches_gather_trajectory(tmp_path, capsys, monkeypatch, snn,
                                        train):
    """The bank data path at refresh=1 (fresh device permute every
    epoch + sequential blocks) trains on the SAME batches as the
    per-step gather path — token streams and final kernels must match
    bitwise (the parity configuration of the r05 roofline lever)."""
    from hpnn_tpu.utils import logging as log

    conf = _conf(tmp_path, snn=snn, train=train)
    log.set_verbose(2)

    monkeypatch.setenv("HPNN_BANK", "0")
    c1 = _conf_copy(conf)
    assert batch_mod.train_kernel_batched(c1, batch_size=8, epochs=6)
    gather_out = capsys.readouterr().out

    monkeypatch.setenv("HPNN_BANK", "1")
    monkeypatch.setenv("HPNN_BANK_REFRESH", "1")
    c2 = _conf_copy(conf)
    assert batch_mod.train_kernel_batched(c2, batch_size=8, epochs=6)
    bank_out = capsys.readouterr().out

    assert "BATCH EPOCH" in gather_out
    assert gather_out == bank_out
    for a, b in zip(c1.kernel.weights, c2.kernel.weights):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_bank_refresh_groups_match_explicit_loop():
    """make_multi_epoch_bank_fn with refresh groups (G=2, R=2) ==
    an explicit host loop over the same permutations/orders, for both
    the XLA block-indexed step and (interpret-mode) the banked Pallas
    kernel path's math twin."""
    import jax.numpy as jnp

    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.parallel import dp

    rng = np.random.RandomState(4)
    k, _ = kernel_mod.generate(9, 6, [5], 3)
    weights = tuple(jnp.asarray(np.asarray(w), jnp.float32) for w in k.weights)
    n, B, S, G, R = 24, 8, 3, 2, 2
    X = jnp.asarray(rng.uniform(-1, 1, (n, 6)), jnp.float32)
    T = np.full((n, 3), -1.0, dtype=np.float32)
    T[np.arange(n), rng.randint(0, 3, n)] = 1.0
    T = jnp.asarray(T)
    perms = np.stack([np.random.RandomState(s).permutation(n)
                      for s in range(G)]).astype(np.int32)
    orders = np.stack([
        np.stack([np.random.RandomState(10 + g * R + r).permutation(S)
                  for r in range(R)]) for g in range(G)
    ]).astype(np.int32)

    def step_fn(w, m, Xb, Tb):
        return dp.train_step_math(w, m, Xb, Tb, model="ann",
                                  momentum=False, lr=0.05, alpha=0.2)

    mf = batch_mod.make_multi_epoch_bank_fn(
        step_fn, batch_mod.make_device_count_fn(model="ann"), S,
        banked=False)
    w_all, _, losses, counts = mf(weights, (), X, T,
                                  jnp.asarray(perms), jnp.asarray(orders))
    assert losses.shape == (G * R, S) and counts.shape == (G * R,)

    w = weights
    e = 0
    cf = batch_mod.make_device_count_fn(model="ann")
    for g in range(G):
        Xp, Tp = X[perms[g]], T[perms[g]]
        for r in range(R):
            for kk in orders[g, r]:
                Xb = Xp[kk * B:(kk + 1) * B]
                Tb = Tp[kk * B:(kk + 1) * B]
                w, _, l = step_fn(w, (), Xb, Tb)
            assert int(cf(w, X, T)) == int(counts[e])
            e += 1
    for a, b in zip(w_all, w):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_bank_sub_refresh_cap_resumes_exactly(tmp_path, capsys, monkeypatch):
    """A survival cap shrunk below the refresh period R (stall
    halving) must dispatch sub-group blocks — aligned sub-R draws a
    fresh bank permutation, a mid-group continuation reuses the
    group's cur_perm and never straddles the boundary — and still
    reproduce the uninterrupted run's token stream exactly."""
    from hpnn_tpu.parallel import dp
    from hpnn_tpu.train.driver import _save_fuse_state
    from hpnn_tpu.utils import logging as log

    conf = _conf(tmp_path)
    log.set_verbose(2)
    epochs = 20
    c1 = _conf_copy(conf)
    assert batch_mod.train_kernel_batched(c1, batch_size=8, epochs=epochs,
                                          mesh_spec="1x1")
    want = capsys.readouterr().out

    state = tmp_path / "b.state"
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    c2 = _conf_copy(conf)
    # plant a checkpoint at done=0 with a sub-R cap hint (chunk=3): the
    # run adopts cap=3 < R=8 and must walk blocks 3/3/2 | 3/3/2 | 3/1
    key = batch_mod._batch_state_key(
        conf.samples, "ann", False,
        tuple(tuple(int(d) for d in np.asarray(w).shape)
              for w in c2.kernel.weights),
        8, dp.default_lr("ann", False), epochs,
        "xla-bank8/generate",
        names=[f"s{i:05d}.txt" for i in range(24)])
    _save_fuse_state(str(state), key, conf.seed, 0, 3,
                     [np.asarray(w) for w in c2.kernel.weights])
    assert batch_mod.train_kernel_batched(c2, batch_size=8, epochs=epochs,
                                          mesh_spec="1x1")
    got = capsys.readouterr().out

    def lines(s):
        return [ln for ln in s.splitlines() if "BATCH EPOCH" in ln]

    assert len(lines(want)) == epochs
    assert lines(got) == lines(want)
    for a, b in zip(c1.kernel.weights, c2.kernel.weights):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_fast_count_env_gates_precision(monkeypatch):
    """HPNN_FAST_COUNT=1 relaxes only the in-training progress count;
    on well-separated data the counts agree with the pinned counter
    (the knob may wobble near-tie counts only)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(11)
    k, _ = kernel_mod.generate(5, 6, [5], 4)
    weights = tuple(jnp.asarray(np.asarray(w), jnp.float32) for w in k.weights)
    X = rng.uniform(-2, 2, (32, 6)).astype(np.float32)
    T = np.full((32, 4), -1.0, dtype=np.float32)
    T[np.arange(32), rng.randint(0, 4, 32)] = 1.0

    pinned = batch_mod.make_device_count_fn(model="ann")
    monkeypatch.setenv("HPNN_FAST_COUNT", "1")
    fast = batch_mod.make_device_count_fn(model="ann")
    a = int(pinned(weights, jnp.asarray(X), jnp.asarray(T)))
    b = int(fast(weights, jnp.asarray(X), jnp.asarray(T)))
    # CPU lowers both precisions identically — the knob must at least
    # produce the same verdicts there (the relaxation is TPU-observable)
    assert a == b
    # the gate itself is visible in the traced computation: the pinned
    # counter's dots carry HIGHEST precision, the fast one's must not
    import jax

    args = (weights, jnp.asarray(X), jnp.asarray(T))
    assert "HIGHEST" in str(jax.make_jaxpr(pinned)(*args))
    assert "HIGHEST" not in str(jax.make_jaxpr(fast)(*args))


def test_census_carries_readable_count(tmp_path, monkeypatch):
    """The multi-process census hashes the raw listing PLUS a readable
    count marker: ranks agreeing on the listing but not on what they
    could READ (torn write, permission skew) must disagree at the
    census, not diverge in the sharded batch math downstream."""
    from hpnn_tpu.parallel import dist

    conf = _conf(tmp_path, n=6)
    # one listed-but-unreadable sample
    (tmp_path / "samples" / "s00099.txt").write_text("[input] zero\n")
    seen = {}
    real = dist.census_consistent

    def spy(names):
        seen["census"] = list(names)
        return real(names)

    monkeypatch.setattr(dist, "census_consistent", spy)
    assert batch_mod.train_kernel_batched(conf, batch_size=4, epochs=1)
    census = seen["census"]
    assert len(census) == 8                  # 7 listed files + marker
    assert census[-1] == "\x00readable=6"    # 6 of 7 actually read
    assert all("\x00" not in n for n in census[:-1])

    # the eval census carries the same marker
    seen.clear()
    batch_mod.run_kernel_batched(conf)
    assert seen["census"][-1] == "\x00readable=6"


def test_fused_vmem_bytes_banked_double_buffer_term():
    """The VMEM gate must count the banked grid kernel's in-flight NEXT
    block (4·B·(n_in+n_out)): underestimating it let near-limit shapes
    pass the gate and then demote silently at Mosaic compile time."""
    k, _ = kernel_mod.generate(1, 8, [6], 2)
    w = [np.asarray(a, dtype=np.float32) for a in k.weights]
    B = 128
    n_in, n_out, n_outs = 8, 2, 6 + 2
    n_w = 6 * 8 + 2 * 6
    base = batch_mod.fused_vmem_bytes(w, B, momentum=False,
                                      use_bank=False)
    assert base == 4 * (B * (n_in + n_out) + 2 * B * n_outs + n_w)
    banked = batch_mod.fused_vmem_bytes(w, B, momentum=False,
                                        use_bank=True)
    assert banked - base == 4 * B * (n_in + n_out)
    mom = batch_mod.fused_vmem_bytes(w, B, momentum=True,
                                     use_bank=False)
    assert mom - base == 4 * n_w  # momentum doubles the weight term
