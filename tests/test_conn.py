"""Connection-plane guards (hpnn_tpu/serve/conn.py, docs/serving.md
"Connection plane").

Acceptance bar (ISSUE): the guard edges behave — a deadline hit
mid-header is distinguishable from one hit mid-body in the close
record's ``phase``; the per-IP cap refuses the N+1th connection as a
fully counted ``guard``/``admit`` close and re-admits once one of the
N closes; ``drain_server`` closes idle keep-alive connections with
reason ``drain`` while leaving nothing unaccounted; and the bounded
census table degrades gracefully past ``HPNN_CONN_TABLE`` (rows
capped, overflow counted as untracked, aggregates still exact).
"""

import json
import os
import socket
import threading
import time

import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.serve import conn


KNOBS = (conn.ENV_HDR_MS, conn.ENV_BODY_MS, conn.ENV_PER_IP,
         conn.ENV_MIN_BPS, conn.ENV_TABLE)


def _wait(pred, timeout_s=8.0, interval_s=0.05):
    """Poll ``pred`` until truthy; returns its last value."""
    deadline = time.monotonic() + timeout_s
    val = pred()
    while not val and time.monotonic() < deadline:
        time.sleep(interval_s)
        val = pred()
    return val


def _records(sink, ev):
    if not os.path.exists(sink):
        return []
    out = []
    with open(sink) as fp:
        for ln in fp:
            if not ln.strip():
                continue
            try:
                r = json.loads(ln)
            except ValueError:
                continue  # a torn tail line mid-write
            if r.get("ev") == ev:
                out.append(r)
    return out


def _recv_eof(sock, timeout_s=8.0):
    """True when the server closed this connection (EOF / reset)."""
    sock.settimeout(timeout_s)
    try:
        while True:
            if not sock.recv(4096):
                return True
    except (ConnectionResetError, BrokenPipeError):
        return True
    except socket.timeout:
        return False


def _get(sock, path="/healthz"):
    """One keep-alive GET over a raw socket; returns the status line."""
    sock.sendall(f"GET {path} HTTP/1.1\r\nHost: t\r\n\r\n".encode())
    sock.settimeout(8.0)
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            raise AssertionError(f"EOF before response headers: {buf!r}")
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    n = 0
    for ln in head.split(b"\r\n"):
        if ln.lower().startswith(b"content-length:"):
            n = int(ln.split(b":", 1)[1])
    while len(rest) < n:
        rest += sock.recv(4096)
    return head.split(b"\r\n")[0].decode()


@pytest.fixture
def conn_server(tmp_path):
    """Factory fixture: arm the given ``HPNN_CONN_*`` knobs plus a
    JSONL sink, boot ``make_server`` over an empty Session on a
    thread, return ``(server, port, sink)``.  Teardown closes the
    server (pairing every open in the sink), the session, the sink,
    and restores the knob env + the module memo."""
    saved = {k: os.environ.pop(k, None) for k in KNOBS}
    booted = []
    sink = str(tmp_path / "conn_sink.jsonl")

    def boot(**knobs):
        for k, v in knobs.items():
            os.environ[k] = str(v)
        conn._reset_for_tests()
        obs.configure(sink)
        sess = serve.Session(max_batch=4, n_buckets=1,
                             max_wait_ms=0.5)
        server = serve.make_server(sess, port=0)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        booted.append((server, sess))
        return server, server.server_address[1], sink

    yield boot
    for server, sess in booted:
        server.shutdown()
        server.server_close()
        sess.close()
    obs.configure(None)
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    conn._reset_for_tests()


# ---------------------------------------------------------- deadlines
def test_deadline_phase_header_vs_body(conn_server):
    """The same ``timeout`` reason, two distinguishable deaths: a
    client stalled mid-HEADER closes with ``phase == "header"``, one
    stalled mid-BODY with ``phase == "body"`` — the close record says
    where the deadline hit, not just that one did."""
    _, port, sink = conn_server(HPNN_CONN_HDR_MS=300,
                                HPNN_CONN_BODY_MS=300,
                                HPNN_CONN_TABLE=64)
    # mid-header: request line complete, header block never finishes
    hdr = socket.create_connection(("127.0.0.1", port), timeout=8.0)
    hdr.sendall(b"GET /healthz HTTP/1.1\r\nHost: t\r\nX-Stall")
    # mid-body: headers complete, 64-byte claim, 7 bytes delivered
    body = socket.create_connection(("127.0.0.1", port), timeout=8.0)
    body.sendall(b"POST /v1/infer HTTP/1.1\r\nHost: t\r\n"
                 b"Content-Type: application/json\r\n"
                 b"Content-Length: 64\r\n\r\n"
                 b'{"kern')
    try:
        assert _recv_eof(hdr), "header deadline never closed the conn"
        assert _recv_eof(body), "body deadline never closed the conn"
        closes = _wait(lambda: (lambda c: c if len(c) >= 2 else None)(
            _records(sink, "conn.close")))
        assert closes, "no conn.close records reached the sink"
        assert all(r["reason"] == "timeout" for r in closes), closes
        assert sorted(r["phase"] for r in closes) == \
            ["body", "header"], closes
        # the body-phase record proves bytes were counted on arrival
        by_phase = {r["phase"]: r for r in closes}
        assert by_phase["body"]["bytes_in"] > \
            by_phase["header"]["bytes_in"]
    finally:
        hdr.close()
        body.close()


def test_torn_body_is_not_a_timeout(conn_server):
    """A client that vanishes mid-upload (short read vs its own
    Content-Length) is a ``torn_body`` close in phase ``body`` — a
    different forensic signature from the stalled-but-connected
    ``timeout``."""
    _, port, sink = conn_server(HPNN_CONN_HDR_MS=2000,
                                HPNN_CONN_BODY_MS=2000,
                                HPNN_CONN_TABLE=64)
    s = socket.create_connection(("127.0.0.1", port), timeout=8.0)
    s.sendall(b"POST /v1/infer HTTP/1.1\r\nHost: t\r\n"
              b"Content-Type: application/json\r\n"
              b"Content-Length: 400\r\n\r\n"
              b'{"kernel": "nope"')
    time.sleep(0.05)
    s.close()
    closes = _wait(lambda: _records(sink, "conn.close") or None)
    assert closes, "no conn.close record reached the sink"
    assert closes[0]["reason"] == "torn_body", closes
    assert closes[0]["phase"] == "body", closes


# ------------------------------------------------------------ per-IP
def test_per_ip_cap_refuses_then_readmits(conn_server):
    """With ``HPNN_CONN_PER_IP=2``: two live connections hold the cap,
    the third is refused at admit time (a fully counted
    ``guard``/``per_ip_cap`` close in phase ``admit``, zero bytes ever
    read) — and the moment one of the two closes, the next connection
    is admitted and served."""
    server, port, sink = conn_server(HPNN_CONN_PER_IP=2,
                                     HPNN_CONN_HDR_MS=60000,
                                     HPNN_CONN_TABLE=64)
    c1 = socket.create_connection(("127.0.0.1", port), timeout=8.0)
    c2 = socket.create_connection(("127.0.0.1", port), timeout=8.0)
    c3 = c4 = None
    try:
        assert _get(c1).endswith("200 OK")
        assert _get(c2).endswith("200 OK")
        assert _wait(lambda: conn.connz_doc(server)["active"] == 2
                     or None), conn.connz_doc(server)
        # third connection from the same IP: refused at the door
        c3 = socket.create_connection(("127.0.0.1", port),
                                      timeout=8.0)
        assert _recv_eof(c3), "per-IP cap never closed the 3rd conn"
        refusals = _wait(lambda: [
            r for r in _records(sink, "conn.close")
            if r["reason"] == "guard"] or None)
        assert refusals, "refusal was not a counted close"
        assert refusals[0]["phase"] == "admit", refusals
        assert refusals[0]["detail"] == "per_ip_cap", refusals
        assert refusals[0]["bytes_in"] == 0, refusals
        # free one slot; the handler notices the EOF and finishes
        c1.close()
        assert _wait(lambda: conn.connz_doc(server)["active"] == 1
                     or None), conn.connz_doc(server)
        # ...and the next connection is admitted and served
        c4 = socket.create_connection(("127.0.0.1", port),
                                      timeout=8.0)
        assert _get(c4).endswith("200 OK")
    finally:
        for c in (c1, c2, c3, c4):
            if c is not None:
                c.close()


# ------------------------------------------------------------- drain
def test_drain_closes_idle_keepalive_with_reason(conn_server):
    """``drain_server`` sweeps idle keep-alive holders: the parked
    connection is closed with reason ``drain`` (phase ``idle``), the
    client sees EOF, and nothing is left unaccounted."""
    server, port, sink = conn_server(HPNN_CONN_HDR_MS=60000,
                                     HPNN_CONN_TABLE=64)
    s = socket.create_connection(("127.0.0.1", port), timeout=8.0)
    try:
        assert _get(s).endswith("200 OK")
        # the handler is back on its keep-alive readline; wait for the
        # census to show the connection parked idle
        doc = _wait(lambda: (lambda d: d if d["conns"] and all(
            c["phase"] == "idle" for c in d["conns"]) else None)(
            conn.connz_doc(server)))
        assert doc, conn.connz_doc(server)
        assert conn.drain_server(server) == 1
        assert _recv_eof(s), "drain never closed the idle conn"
        closes = _wait(lambda: _records(sink, "conn.close") or None)
        assert closes, "no conn.close record reached the sink"
        assert closes[0]["reason"] == "drain", closes
        assert closes[0]["phase"] == "idle", closes
        assert closes[0]["requests"] == 1, closes
        assert _wait(lambda: conn.connz_doc(server)["active"] == 0
                     or None) is not None
    finally:
        s.close()


# ------------------------------------------------------------- census
def test_connz_bounded_table_degrades_gracefully(conn_server):
    """With ``HPNN_CONN_TABLE=2`` and three live connections: the
    census keeps exact aggregates (active/opened) while the row table
    stays capped at 2 with the overflow counted as ``untracked`` —
    and untracked connections are still served and still close
    counted."""
    server, port, sink = conn_server(HPNN_CONN_TABLE=2,
                                     HPNN_CONN_HDR_MS=60000)
    socks = [socket.create_connection(("127.0.0.1", port),
                                      timeout=8.0) for _ in range(3)]
    try:
        for s in socks:
            assert _get(s).endswith("200 OK")
        doc = _wait(lambda: (lambda d: d
                             if d["active"] == 3 else None)(
            conn.connz_doc(server)))
        assert doc, conn.connz_doc(server)
        assert doc["opened"] == 3
        assert doc["table"]["max"] == 2
        assert doc["table"]["rows"] <= 2
        assert doc["table"]["untracked"] >= 1
        assert len(doc["conns"]) <= 2
        # the /connz route itself serves the same census (this GET is
        # a 4th connection — the aggregates move, the cap holds)
        assert _get(socks[0], "/connz").endswith("200 OK")
        # every open is gauge-visible even past the table bound
        gauges = _records(sink, "conn.active")
        assert gauges and max(g["value"] for g in gauges) >= 3
    finally:
        for s in socks:
            s.close()
        # every close — including the untracked connection's — must
        # still be counted
        assert _wait(
            lambda: len(_records(sink, "conn.close")) >= 3 or None)
