"""pmnist / pdif / gen_ann converter tests (byte-level format checks)."""

import struct
import subprocess
import sys

import numpy as np

from hpnn_tpu.fileio import samples as sample_io
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.tools import gen_ann, pdif, pmnist


def _write_idx(tmp, images, labels):
    n, rows, cols = images.shape
    with open(tmp / "train_images", "wb") as fp:
        fp.write(struct.pack(">IIII", 0x803, n, rows, cols))
        fp.write(images.astype(np.uint8).tobytes())
    with open(tmp / "train_labels", "wb") as fp:
        fp.write(struct.pack(">II", 0x801, n))
        fp.write(labels.astype(np.uint8).tobytes())


def test_pmnist_format(tmp_path, monkeypatch, capsys):
    rng = np.random.RandomState(3)
    images = rng.randint(0, 256, (4, 28, 28))
    labels = np.array([3, 0, 9, 7])
    _write_idx(tmp_path, images, labels)
    # test set = same files under the test names
    (tmp_path / "test_images").write_bytes((tmp_path / "train_images").read_bytes())
    (tmp_path / "test_labels").write_bytes((tmp_path / "train_labels").read_bytes())
    (tmp_path / "samples").mkdir()
    (tmp_path / "tests").mkdir()
    monkeypatch.chdir(tmp_path)
    assert pmnist.main(["samples", "tests"]) == 0

    # byte-level format of the first sample
    text = (tmp_path / "samples" / "s00001.txt").read_text().splitlines()
    assert text[0] == "[input] 784"
    assert text[1].startswith("%7.5f" % float(images[0].ravel()[0]))
    assert text[2] == "[output] 10  #3"
    assert text[3].split() == [
        "1.0" if i == 3 else "-1.0" for i in range(10)
    ]
    # readable by the framework reader, pixels unnormalized
    x, t = sample_io.read_sample(str(tmp_path / "samples" / "s00001.txt"))
    assert x.shape == (784,) and t.shape == (10,)
    np.testing.assert_allclose(x, images[0].ravel().astype(float), atol=1e-5)
    # index continues into the test set (reference quirk kept)
    names = sorted(p.name for p in (tmp_path / "tests").iterdir())
    assert names[0] == "s00005.txt"
    # conscious fix: test labels NOT shifted (label[i] with image[i])
    _, t = sample_io.read_sample(str(tmp_path / "tests" / "s00005.txt"))
    assert np.argmax(t) == 3


DIF_TEXT = """Quartz
   Sample: T = 25 C

      CELL PARAMETERS:   4.913   4.913   5.405   90.0   90.0  120.0
      SPACE GROUP: P3_221

           ATOM        X         Y         Z     OCCUPANCY  ISO(B)
            Si     0.46970   0.00000   0.00000     1.000     1.000
            O      0.41350   0.26690   0.11910     1.000     1.000

            X-RAY WAVELENGTH:     1.541838

               2-THETA      INTENSITY
                20.86        21.84
                26.64       100.00
"""

RAW_TEXT = """##direct scan
10.0 5.0
20.0 7.0
40.0 11.0
88.0 3.0
"""


def test_pdif_pipeline(tmp_path, capsys):
    (tmp_path / "rruff" / "dif").mkdir(parents=True)
    (tmp_path / "rruff" / "raw").mkdir()
    (tmp_path / "rruff" / "dif" / "R000001").write_text(DIF_TEXT)
    (tmp_path / "rruff" / "raw" / "R000001").write_text(RAW_TEXT)
    (tmp_path / "samples").mkdir()
    assert pdif.main(
        [str(tmp_path / "rruff"), "-i", "4", "-o", "230",
         "-s", str(tmp_path / "samples")]
    ) == 0
    out = (tmp_path / "samples" / "R000001").read_text().splitlines()
    assert out[0] == "[input] 5"  # 4 bins + temperature
    vals = out[1].split()
    # temperature: (25+273.15)/273.15
    assert vals[0] == "%7.5f" % (298.15 / 273.15)
    # bins over [5,90): width 21.25 -> [5,26.25):5+7=12, [26.25,47.5):11,
    # [47.5,68.75):0, [68.75,90):3; normalized by 12
    np.testing.assert_allclose(
        [float(v) for v in vals[1:]], [1.0, 11 / 12, 0.0, 3 / 12], atol=1e-5
    )
    assert out[2] == "[output] 230"
    hot = out[3].split()
    # P3_221 is space group 154 -> one-hot index 153
    assert hot[153] == "1.0" and hot.count("1.0") == 1


def test_pdif_skips_mo_radiation(tmp_path, capsys):
    txt = DIF_TEXT.replace("1.541838", "0.710730")
    (tmp_path / "rruff" / "dif").mkdir(parents=True)
    (tmp_path / "rruff" / "raw").mkdir()
    (tmp_path / "rruff" / "dif" / "R000002").write_text(txt)
    (tmp_path / "rruff" / "raw" / "R000002").write_text(RAW_TEXT)
    (tmp_path / "samples").mkdir()
    assert pdif.main(
        [str(tmp_path / "rruff"), "-i", "4", "-o", "230",
         "-s", str(tmp_path / "samples")]
    ) == 0
    assert not (tmp_path / "samples" / "R000002").exists()


def test_pdif_json_mode(tmp_path, capsys):
    """--json captures the whole text protocol and emits one report
    document: params, written/skipped censuses, the buffered stdout."""
    import json

    (tmp_path / "rruff" / "dif").mkdir(parents=True)
    (tmp_path / "rruff" / "raw").mkdir()
    (tmp_path / "rruff" / "dif" / "R000001").write_text(DIF_TEXT)
    (tmp_path / "rruff" / "raw" / "R000001").write_text(RAW_TEXT)
    # a second file that trips the Mo-radiation skip
    (tmp_path / "rruff" / "dif" / "R000002").write_text(
        DIF_TEXT.replace("1.541838", "0.710730"))
    (tmp_path / "rruff" / "raw" / "R000002").write_text(RAW_TEXT)
    (tmp_path / "samples").mkdir()
    assert pdif.main(
        [str(tmp_path / "rruff"), "--json", "-i", "4", "-o", "230",
         "-s", str(tmp_path / "samples")]
    ) == 0
    out = capsys.readouterr().out
    report = json.loads(out)          # exactly one JSON document
    assert report["ok"] is True and report["exit_code"] == 0
    # n_inputs is the effective count: 4 spectrum bins + temperature
    assert report["params"] == {
        "rruff_dir": str(tmp_path / "rruff"), "n_inputs": 5,
        "n_outputs": 230, "sample_dir": str(tmp_path / "samples")}
    assert report["written"] == ["R000001"]
    assert report["skipped"] == [
        {"file": "R000002", "reason": "mo_radiation"}]
    # the text protocol was captured, not printed
    assert out.count("\n") == 1
    assert any(">> received:" in ln for ln in report["stdout_lines"])
    # the written sample is byte-identical to a plain-mode run
    plain = tmp_path / "plain"
    plain.mkdir()
    assert pdif.main(
        [str(tmp_path / "rruff"), "-i", "4", "-o", "230",
         "-s", str(plain)]
    ) == 0
    capsys.readouterr()
    assert (plain / "R000001").read_bytes() == \
        (tmp_path / "samples" / "R000001").read_bytes()


def test_pdif_json_reports_failure(tmp_path, capsys):
    import json

    assert pdif.main(
        ["--json", str(tmp_path / "nowhere"), "-i", "4", "-o", "230",
         "-s", str(tmp_path / "nowhere")]
    ) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] is False and report["exit_code"] == 1


def test_gen_ann_loadable(tmp_path, capsys):
    assert gen_ann.main(["--seed", "42", "8", "6", "4"]) == 0
    text = capsys.readouterr().out
    kfile = tmp_path / "k.txt"
    kfile.write_text(text)
    name, k = kernel_mod.load(str(kfile))
    assert name == "auto"
    assert k.n_inputs == 8 and k.hidden_sizes == (6,) and k.n_outputs == 4
    # per-layer scale quirk: the bash tool divides by sqrt(CURRENT
    # layer width) — sqrt(6) then sqrt(4) here — not sqrt(fan-in)
    # (awk list[1] == $param, ref: scripts/gen_ann.bash:38-47)
    for w, width in zip(k.weights, (6, 4)):
        w = np.asarray(w).ravel()
        assert w.min() >= -1.0 / np.sqrt(width) - 1e-9
        assert w.max() <= 2 * (65535 / 100000 - 0.5) / np.sqrt(width) + 1e-9
    # the output layer (width 4, fan-in 6) must actually use sqrt(4):
    # with 24 u16 draws the max |w| exceeds the 1/sqrt(6) bound w.h.p.
    out = np.abs(np.asarray(k.weights[-1])).ravel()
    assert out.max() > 1.0 / np.sqrt(6)


def test_gen_ann_cli_roundtrip(tmp_path):
    """Console entry point output feeds train-able kernels."""
    res = subprocess.run(
        [sys.executable, "-m", "hpnn_tpu.tools.gen_ann",
         "--seed", "7", "4", "3", "2"],
        capture_output=True, text=True,
    )
    assert res.returncode == 0
    kfile = tmp_path / "g.txt"
    kfile.write_text(res.stdout)
    _, k = kernel_mod.load(str(kfile))
    assert k.n_inputs == 4


def test_synth_rruff_pdif_pipeline(tmp_path, capsys):
    """synth_rruff emits dif/raw pairs the real pdif converts: every
    good file becomes an 851-in/230-out sample one-hot on its space
    group; quirk files are skipped; generation is deterministic."""
    from hpnn_tpu.tools import synth_rruff

    out = tmp_path / "rruff"
    assert synth_rruff.main(
        [str(out), "--per-class", "2", "--classes", "5", "--quirks",
         "--seed", "11"]
    ) == 0
    sdir = tmp_path / "samples"
    sdir.mkdir()
    assert pdif.main([str(out), "-i", "850", "-o", "230",
                      "-s", str(sdir)]) == 0
    err = capsys.readouterr().err
    # Mo-radiation + first-line "5.000" quirks skipped like the reference
    assert err.count("SKIP") == 2
    made = sorted(p.name for p in sdir.iterdir())
    # 10 good samples + the unknown-SG file (all −1 outputs, kept)
    assert len(made) == 11
    for g in range(1, 6):
        for j in range(2):
            name = "R%06i" % ((g - 1) * 2 + j + 1)
            lines = (sdir / name).read_text().splitlines()
            assert lines[0] == "[input] 851"
            x = np.array([float(v) for v in lines[1].split()])
            assert x.shape == (851,) and np.all(x <= 1.3)
            t = [float(v) for v in lines[3].split()]
            assert len(t) == 230 and t.index(1.0) == g - 1
    # unknown space group -> all −1 target (reference space==0 path)
    tq = [float(v)
          for v in (sdir / "RQ00003").read_text().splitlines()[3].split()]
    assert 1.0 not in tq
    # determinism: same seed regenerates byte-identical files
    out2 = tmp_path / "rruff2"
    synth_rruff.main([str(out2), "--per-class", "2", "--classes", "5",
                      "--quirks", "--seed", "11"])
    for sub in ("dif", "raw"):
        for p in sorted((out / sub).iterdir()):
            assert p.read_bytes() == (out2 / sub / p.name).read_bytes()


def test_pdif_lead_float_accepts_strtod_special_forms():
    """GET_DOUBLE is strtod: inf/infinity/nan/nan(n-char-seq) in any
    case, with optional sign, are valid parses and must consume."""
    for s in ("inf", "INF", "-inf", "+Infinity", "iNfInItY",
              "nan", "NAN", "-nan", "nan(0x7ff)", "NaN(box_1)",
              " \tnan"):
        m = pdif._LEAD_FLOAT.match(s)
        assert m is not None and m.end() == len(s), s
    # prefixes that are NOT a number still fail...
    assert pdif._LEAD_FLOAT.match("in") is None
    assert pdif._LEAD_FLOAT.match("na") is None
    assert pdif._LEAD_FLOAT.match("bogus") is None
    # ...and strtod's longest-valid-prefix rule holds
    m = pdif._LEAD_FLOAT.match("inferior")
    assert m is not None and m.group(1) == "inf"
    m = pdif._LEAD_FLOAT.match("nan(abc) rest")
    assert m is not None and m.group(1) == "nan(abc)"


def test_pdif_atom_row_accepts_nan_occupancy():
    """An ATOM row whose occupancy column reads "nan" (real RRUFF
    exports do this) is a valid strtod parse — the row must consume
    as an atom, not FAIL the whole file."""
    assert pdif._parse_atom_row("O  0.5 0.5 nan 1.0 0.8") == "atom"
    assert pdif._parse_atom_row("O  0.5 0.5 inf 1.0 0.8") == "atom"
    assert pdif._parse_atom_row("O  0.5 0.5 bogus 1.0 0.8") == "fail"
