"""HPNN_TRACE — the DBG_TRACE twin (utils/trace.py).

The reference's cross-backend oracle instrument (abs-sum traces,
ref: include/libhpnn/ann.h:29-33) must emit per-sample weight traces
and per-file output traces whose values equal the numpy abs-sums of
the arrays the drivers actually used."""

import re

import numpy as np
import pytest

from hpnn_tpu.config import NNConf, NNTrain, NNType
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.train import batch as batch_mod, driver
from hpnn_tpu.utils import logging as log


def _conf(tmp_path, n=6):
    rng = np.random.RandomState(0)
    sdir = tmp_path / "samples"
    sdir.mkdir()
    for i in range(n):
        c = i % 2
        x = (1 - 2 * c) * np.r_[np.ones(4), -np.ones(4)] \
            + 0.1 * rng.normal(size=8)
        t = np.full(2, -1.0)
        t[c] = 1.0
        with open(sdir / f"s{i:05d}.txt", "w") as fp:
            fp.write("[input] 8\n" + " ".join(f"{v:.5f}" for v in x) + "\n")
            fp.write("[output] 2\n" + " ".join(f"{v:.1f}" for v in t) + "\n")
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    return NNConf(name="t", type=NNType.ANN, seed=1, kernel=k,
                  train=NNTrain.BP, samples=str(sdir), tests=str(sdir))


def _parse(out):
    return {
        m.group(1): float(m.group(2))
        for m in re.finditer(r"#DBG: acc\[(.+?)\]=([0-9.]+)", out)
    }


def test_trace_off_by_default(tmp_path, capsys):
    conf = _conf(tmp_path)
    log.set_verbose(2)
    assert driver.train_kernel(conf)
    assert "#DBG" not in capsys.readouterr().out


def test_train_and_eval_traces(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("HPNN_TRACE", "1")
    conf = _conf(tmp_path)
    log.set_verbose(2)
    assert driver.train_kernel(conf)
    traces = _parse(capsys.readouterr().out)
    # final-weights trace equals the numpy abs-sum of the round result
    for l, w in enumerate(conf.kernel.weights):
        want = float(np.abs(np.asarray(w)).sum())
        got = traces[f"w@6/{l}"]
        assert got == pytest.approx(want, rel=1e-12)

    driver.run_kernel(conf)
    ev = _parse(capsys.readouterr().out)
    assert len([k for k in ev if k.startswith("out@")]) == 6

    # batched eval traces the same per-file abs-sums (shared oracle)
    batch_mod.run_kernel_batched(conf)
    evb = _parse(capsys.readouterr().out)
    for key, v in ev.items():
        assert evb[key] == pytest.approx(v, rel=1e-6)


def test_batch_trace_per_block(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("HPNN_TRACE", "1")
    conf = _conf(tmp_path)
    log.set_verbose(2)
    assert batch_mod.train_kernel_batched(conf, batch_size=4, epochs=3,
                                          mesh_spec="1x1")
    traces = _parse(capsys.readouterr().out)
    for l, w in enumerate(conf.kernel.weights):
        want = float(np.abs(np.asarray(w)).sum())
        assert traces[f"w@3/{l}"] == pytest.approx(want, rel=1e-12)
