"""Kernel text format: round-trips and generation reproducibility."""

import io

import numpy as np
import pytest

from hpnn_tpu.fileio.kernel_format import KernelFormatError, load_kernel
from hpnn_tpu.models import kernel as kernel_mod


def test_generate_deterministic():
    k1, s1 = kernel_mod.generate(10958, 4, [3], 2)
    k2, s2 = kernel_mod.generate(10958, 4, [3], 2)
    assert s1 == s2 == 10958
    for a, b in zip(k1.weights, k2.weights):
        np.testing.assert_array_equal(a, b)
    assert k1.weights[0].shape == (3, 4)
    assert k1.weights[1].shape == (2, 3)
    # scaling bound: |w| <= 1/sqrt(M)
    assert np.abs(k1.weights[0]).max() <= 1.0 / np.sqrt(4.0)
    assert np.abs(k1.weights[1]).max() <= 1.0 / np.sqrt(3.0)


def test_roundtrip(tmp_path):
    k, _ = kernel_mod.generate(7, 5, [4, 3], 2)
    p = tmp_path / "k.txt"
    with open(p, "w") as fp:
        kernel_mod.dump("test_net", k, fp)
    name, k2 = kernel_mod.load(str(p))
    assert name == "test_net"
    assert len(k2.weights) == 3
    for a, b in zip(k.weights, k2.weights):
        # %17.15f keeps 15 decimals; values are < 1 so this is ~1e-15
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-15)


def test_dump_format_tokens(tmp_path):
    k, _ = kernel_mod.generate(1, 2, [3], 2)
    buf = io.StringIO()
    kernel_mod.dump("net", k, buf)
    text = buf.getvalue()
    lines = text.splitlines()
    assert lines[0] == "[name] net"
    assert lines[1] == "[param] 2 3 2"
    assert lines[2] == "[input] 2"
    assert lines[3] == "[hidden 1] 3"
    assert lines[4] == "[neuron 1] 2"
    assert "[output] 2" in lines
    # weight rows: %17.15f formatting
    row = lines[5].split()
    assert all(len(tok.split(".")[1]) == 15 for tok in row)


def test_load_rejects_bad_param(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("[name] x\n[param] 4\n")
    with pytest.raises(KernelFormatError):
        load_kernel(str(p))


def test_validate():
    k, _ = kernel_mod.generate(3, 4, [5], 2)
    assert kernel_mod.validate(k)
    bad = kernel_mod.Kernel((np.zeros((5, 4)), np.zeros((2, 9))))
    assert not kernel_mod.validate(bad)


def test_load_salvages_strtod_prefix(tmp_path):
    """The GET_DOUBLE walk salvages the numeric prefix of a
    junk-suffixed token ("0.25x" -> 0.25) and keeps scanning after it
    (ref: src/ann.c:438-444, common.h:272-274)."""
    p = tmp_path / "junk.txt"
    p.write_text(
        "[name] j\n[param] 2 2 1\n[input] 2\n"
        "[hidden 1] 2\n"
        "[neuron 1] 2\n0.125 0.25x\n"
        "[neuron 2] 2\n-0.5x 0.75 trailing ignored\n"
        "[output] 1\n"
        "[neuron 1] 2\n1.0 -1.0\n"
    )
    _, ws = load_kernel(str(p))
    assert np.allclose(ws[0], [[0.125, 0.25], [-0.5, 0.75]])


def test_load_junk_token_reads_zero(tmp_path):
    """A junk token reads as 0.0 and a short row zero-fills: the
    reference's ASSERT_GOTO(end,FAIL) is a NULL check strtod can never
    trigger, so ann_load cannot reject a weight row
    (ref: src/ann.c:438-444, common.h:290-295)."""
    p = tmp_path / "zeros.txt"
    p.write_text(
        "[name] j\n[param] 2 2 1\n[input] 2\n"
        "[hidden 1] 2\n"
        "[neuron 1] 2\nx 0.5\n"
        "[neuron 2] 2\n0.25\n"
        "[output] 1\n[neuron 1] 2\n1.0 -1.0\n"
    )
    _, ws = load_kernel(str(p))
    assert np.allclose(ws[0], [[0.0, 0.5], [0.25, 0.0]])
