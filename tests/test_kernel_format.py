"""Kernel text format: round-trips and generation reproducibility."""

import io

import numpy as np
import pytest

from hpnn_tpu.fileio.kernel_format import KernelFormatError, load_kernel
from hpnn_tpu.models import kernel as kernel_mod


def test_generate_deterministic():
    k1, s1 = kernel_mod.generate(10958, 4, [3], 2)
    k2, s2 = kernel_mod.generate(10958, 4, [3], 2)
    assert s1 == s2 == 10958
    for a, b in zip(k1.weights, k2.weights):
        np.testing.assert_array_equal(a, b)
    assert k1.weights[0].shape == (3, 4)
    assert k1.weights[1].shape == (2, 3)
    # scaling bound: |w| <= 1/sqrt(M)
    assert np.abs(k1.weights[0]).max() <= 1.0 / np.sqrt(4.0)
    assert np.abs(k1.weights[1]).max() <= 1.0 / np.sqrt(3.0)


def test_roundtrip(tmp_path):
    k, _ = kernel_mod.generate(7, 5, [4, 3], 2)
    p = tmp_path / "k.txt"
    with open(p, "w") as fp:
        kernel_mod.dump("test_net", k, fp)
    name, k2 = kernel_mod.load(str(p))
    assert name == "test_net"
    assert len(k2.weights) == 3
    for a, b in zip(k.weights, k2.weights):
        # %17.15f keeps 15 decimals; values are < 1 so this is ~1e-15
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-15)


def test_dump_format_tokens(tmp_path):
    k, _ = kernel_mod.generate(1, 2, [3], 2)
    buf = io.StringIO()
    kernel_mod.dump("net", k, buf)
    text = buf.getvalue()
    lines = text.splitlines()
    assert lines[0] == "[name] net"
    assert lines[1] == "[param] 2 3 2"
    assert lines[2] == "[input] 2"
    assert lines[3] == "[hidden 1] 3"
    assert lines[4] == "[neuron 1] 2"
    assert "[output] 2" in lines
    # weight rows: %17.15f formatting
    row = lines[5].split()
    assert all(len(tok.split(".")[1]) == 15 for tok in row)


def test_load_rejects_bad_param(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("[name] x\n[param] 4\n")
    with pytest.raises(KernelFormatError):
        load_kernel(str(p))


def test_validate():
    k, _ = kernel_mod.generate(3, 4, [5], 2)
    assert kernel_mod.validate(k)
    bad = kernel_mod.Kernel((np.zeros((5, 4)), np.zeros((2, 9))))
    assert not kernel_mod.validate(bad)
