"""Verify the glibc random() clone against the real glibc on this host.

The golden values come from compiling and running a tiny C program that
calls srandom()/random() — the very libc functions the reference uses —
so this checks seed-for-seed behavioral parity, not a copied table.
"""

import subprocess
import sys
import textwrap

import pytest

from hpnn_tpu.utils.glibc_random import RAND_MAX, GlibcRandom, shuffled_order

C_SRC = textwrap.dedent(
    """
    #include <stdio.h>
    #include <stdlib.h>
    int main(int argc, char **argv) {
        unsigned seed = (unsigned)strtoul(argv[1], 0, 10);
        int n = atoi(argv[2]);
        srandom(seed);
        for (int i = 0; i < n; i++) printf("%ld\\n", random());
        return 0;
    }
    """
)


@pytest.fixture(scope="module")
def c_random(tmp_path_factory):
    d = tmp_path_factory.mktemp("crand")
    src = d / "r.c"
    src.write_text(C_SRC)
    exe = d / "r"
    subprocess.run(["gcc", "-O2", "-o", str(exe), str(src)], check=True)

    def run(seed, n):
        out = subprocess.run(
            [str(exe), str(seed), str(n)], capture_output=True, text=True, check=True
        )
        return [int(x) for x in out.stdout.split()]

    return run


@pytest.mark.parametrize("seed", [1, 2, 10958, 123456789, 0, 2**31 - 1, 2**32 - 1])
def test_matches_glibc(c_random, seed):
    golden = c_random(seed, 200)
    rng = GlibcRandom(seed)
    ours = [rng.random() for _ in range(200)]
    assert ours == golden


def test_uniform_range():
    rng = GlibcRandom(42)
    for _ in range(1000):
        u = rng.uniform()
        assert 0.0 <= u <= 1.0
    assert RAND_MAX == 2147483647


def test_shuffled_order_is_permutation():
    order = shuffled_order(10958, 257)
    assert sorted(order) == list(range(257))


def test_shuffled_order_deterministic():
    assert shuffled_order(7, 64) == shuffled_order(7, 64)
    assert shuffled_order(7, 64) != shuffled_order(8, 64)
