"""Driver hooks: entry() compile-check and multi-chip dry run on the
8-virtual-device CPU mesh (what the external driver does)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import __graft_entry__ as graft


def test_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (10,)


def test_dryrun_multichip_8(capsys):
    graft.dryrun_multichip(8)
    out = capsys.readouterr().out
    assert "dryrun gspmd: mesh=2x4" in out
    assert "dryrun tp:" in out


def test_dryrun_multichip_2(capsys):
    graft.dryrun_multichip(2)
    out = capsys.readouterr().out
    assert "dryrun gspmd: mesh=1x2" in out


def test_dryrun_without_cpu_shield():
    """Reproduce the DRIVER's environment (round-1 RED gate): no forced
    JAX_PLATFORMS=cpu, so the default platform may resolve to a real
    accelerator client.  The dryrun must still run entirely on the
    virtual CPU devices and never initialize/touch the default client."""
    import subprocess

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("JAX_ENABLE_X64", None)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"]
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import __graft_entry__ as g; g.dryrun_multichip(8)",
        ],
        cwd=repo,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, f"stderr:\n{proc.stderr}\nstdout:\n{proc.stdout}"
    assert "dryrun gspmd: mesh=2x4" in proc.stdout
    assert "dryrun tp:" in proc.stdout
