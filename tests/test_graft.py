"""Driver hooks: entry() compile-check and multi-chip dry run on the
8-virtual-device CPU mesh (what the external driver does)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import __graft_entry__ as graft


def test_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (10,)


def test_dryrun_multichip_8(capsys):
    graft.dryrun_multichip(8)
    out = capsys.readouterr().out
    assert "dryrun gspmd: mesh=2x4" in out
    assert "dryrun tp:" in out


def test_dryrun_multichip_2(capsys):
    graft.dryrun_multichip(2)
    out = capsys.readouterr().out
    assert "dryrun gspmd: mesh=1x2" in out
