"""Worker for the 2-process distributed test (tests/test_dist.py).

Each OS process runs this script with JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES / JAX_PROCESS_ID set — the JAX-distributed analogue
of one rank of ``mpirun -np 2 train_nn`` (ref MPI init:
/root/reference/src/libhpnn.c:182-200).  It joins the cluster through
``runtime.init_dist``, builds the slice-aware ``dist.hybrid_mesh``,
runs ONE GSPMD DP training step over the global 4-device (2 procs x 2
local CPU devices) mesh, and prints one token line through the rank-0
-only logger (the reference's ``_OUT``, common.h:81-91).
"""

import sys

import numpy as np


def main() -> int:
    import jax

    from hpnn_tpu import runtime
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.parallel import dist, dp, mesh as mesh_mod
    from hpnn_tpu.utils import logging as log

    runtime.init_runtime()
    log.set_verbose(2)  # NN_OUT prints at -vv (ref: include/libhpnn.h:95-122)
    assert runtime.init_dist()
    assert jax.process_count() == 2, jax.process_count()
    assert runtime.get_capabilities() & runtime.NNCap.MPI
    assert runtime.get_mpi_tasks() == 2

    # EVERY rank emits obs events (obs is per-process, unlike the
    # rank-0-only token logger): when the driver sets HPNN_METRICS with
    # a {rank} placeholder, each process gets its own sink file and
    # test_dist.py asserts the streams never interleave
    from hpnn_tpu import obs

    obs.event("round.start", mode="dist", rank=jax.process_index())

    mesh = dist.hybrid_mesh(n_model=1)
    n_data = mesh.shape[mesh_mod.DATA_AXIS]
    assert n_data == jax.device_count() == 4

    # seed-0 materialization must agree across ranks (rank-0 clock
    # broadcast): every rank would otherwise generate a different
    # kernel at conf load
    from jax.experimental import multihost_utils

    s = dist.resolve_time_seed(0)
    all_s = np.asarray(multihost_utils.process_allgather(np.int64(s)))
    assert (all_s == all_s[0]).all(), all_s

    import jax.numpy as jnp

    k, _ = kernel_mod.generate(7, 6, [5], 3)
    weights = tuple(jnp.asarray(np.asarray(w)) for w in k.weights)
    step = dp.make_gspmd_train_step(mesh, weights, model="ann",
                                    momentum=False)
    w_sh = dp.place_kernel(weights, mesh)

    # the same global batch on every process; dp.shard_batch places it
    # multi-process-safely (each device takes its row block via the
    # shard callback)
    B = 2 * n_data
    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, (B, 6))
    T = np.full((B, 3), -1.0)
    T[np.arange(B), rng.randint(0, 3, B)] = 1.0
    Xs, Ts = dp.shard_batch(X, T, mesh)

    w_sh, _, loss = step(w_sh, (), Xs, Ts)
    jax.block_until_ready(loss)
    obs.event("round.end", mode="dist", rank=jax.process_index(),
              loss=float(loss))
    obs.summary()
    obs.flush()
    # rank-0-only token: exactly one process may emit this line
    log.nn_out(sys.stdout, "DIST STEP loss= %.10f tasks=%i\n",
               float(loss), runtime.get_mpi_tasks())
    log.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
