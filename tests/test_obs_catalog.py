"""tools/check_obs_catalog.py — the event-catalog drift lint, tier-1.

Every literal event name emitted under ``hpnn_tpu/`` must appear
(backticked) in the docs catalog pages.  Running the lint here turns a
forgotten docs row into a test failure; the crafted-tree case proves
the lint actually bites.
"""

import importlib.util
import os

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _load():
    spec = importlib.util.spec_from_file_location(
        "check_obs_catalog",
        os.path.join(ROOT, "tools", "check_obs_catalog.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_no_catalog_drift_on_the_real_tree():
    mod = _load()
    assert mod.check(os.path.abspath(ROOT)) == []


def test_lint_detects_an_undocumented_name(tmp_path):
    """A crafted mini-tree with one undocumented emission must fail,
    and adding the docs row must clear it."""
    mod = _load()
    pkg = tmp_path / "hpnn_tpu"
    pkg.mkdir()
    (pkg / "thing.py").write_text(
        'from hpnn_tpu import obs\n'
        'def f():\n'
        '    obs.count("thing.mystery_event", step=1)\n'
        '    obs.gauge("thing.known", 2.0)\n'
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "catalog: `thing.known` is the only documented event\n")
    (docs / "serving.md").write_text("nothing here\n")

    failures = mod.check(str(tmp_path))
    assert len(failures) == 1
    assert "thing.mystery_event" in failures[0]
    assert "thing.py:3" in failures[0]

    # a wildcard row covers the family
    (docs / "observability.md").write_text(
        "catalog: `thing.known` and the `thing.*` family\n")
    assert mod.check(str(tmp_path)) == []


def test_call_site_regex_matches_every_emitter_style(tmp_path):
    """obs.timer / bare event() / raw {"ev": ...} records all count."""
    mod = _load()
    pkg = tmp_path / "hpnn_tpu"
    pkg.mkdir()
    (pkg / "styles.py").write_text(
        'with obs.timer("a.timer", tag=1):\n'
        '    pass\n'
        'event("b.bare")\n'
        'rec = {"ev": "c.raw", "kind": "event"}\n'
    )
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text("``\n")
    (tmp_path / "docs" / "serving.md").write_text("\n")
    emitted = mod.emitted_names(str(tmp_path))
    assert set(emitted) == {"a.timer", "b.bare", "c.raw"}
