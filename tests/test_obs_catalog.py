"""tools/check_obs_catalog.py — the event-catalog drift lint, tier-1.

Every literal event name emitted under ``hpnn_tpu/`` must appear
(backticked) in the docs catalog pages.  Running the lint here turns a
forgotten docs row into a test failure; the crafted-tree case proves
the lint actually bites.
"""

import importlib.util
import os

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _load():
    spec = importlib.util.spec_from_file_location(
        "check_obs_catalog",
        os.path.join(ROOT, "tools", "check_obs_catalog.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_no_catalog_drift_on_the_real_tree():
    mod = _load()
    assert mod.check(os.path.abspath(ROOT)) == []


def test_lint_detects_an_undocumented_name(tmp_path):
    """A crafted mini-tree with one undocumented emission must fail,
    and adding the docs row must clear it."""
    mod = _load()
    pkg = tmp_path / "hpnn_tpu"
    pkg.mkdir()
    (pkg / "thing.py").write_text(
        'from hpnn_tpu import obs\n'
        'def f():\n'
        '    obs.count("thing.mystery_event", step=1)\n'
        '    obs.gauge("thing.known", 2.0)\n'
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "catalog: `thing.known` is the only documented event\n")
    (docs / "serving.md").write_text("nothing here\n")

    failures = mod.check(str(tmp_path))
    assert len(failures) == 1
    assert "thing.mystery_event" in failures[0]
    assert "thing.py:3" in failures[0]

    # a wildcard row covers the family
    (docs / "observability.md").write_text(
        "catalog: `thing.known` and the `thing.*` family\n")
    assert mod.check(str(tmp_path)) == []


def _write_ledger(path, rows):
    import json

    with open(path, "w") as fp:
        fp.write(json.dumps({"ts": 0, "ev": "ledger.open",
                             "path": str(path), "pid": 1, "rank": 0})
                 + "\n")
        for rec in rows:
            fp.write(json.dumps(rec) + "\n")


def _round(row, **over):
    rec = {"ts": 0.0, "ev": "ledger.round", "row": row, "step": row + 1,
           "where": "fused_chunk", "rank": 0, "nan": 0, "inf": 0,
           "checksums": {"w0": 12.5, "w1": 3.25},
           "shapes": {"w0": [5, 8], "w1": [2, 5]}}
    rec.update(over)
    return rec


def test_ledger_lint_accepts_a_well_formed_ledger(tmp_path):
    mod = _load()
    path = tmp_path / "ledger.jsonl"
    _write_ledger(path, [_round(0), _round(1), _round(2)])
    assert mod.lint_ledger(str(path)) == []


def test_ledger_lint_catches_every_schema_break(tmp_path):
    """Each frozen-contract clause bites: missing key, broken row
    monotonicity, shapes/checksums key mismatch, non-numeric checksum,
    bad shape entries, negative censuses, and a wrong header."""
    mod = _load()
    path = tmp_path / "ledger.jsonl"

    bad = _round(0)
    del bad["where"]
    _write_ledger(path, [bad])
    assert any("missing keys" in f for f in mod.lint_ledger(str(path)))

    _write_ledger(path, [_round(0), _round(5)])
    assert any("not monotone" in f for f in mod.lint_ledger(str(path)))

    _write_ledger(path, [_round(0, shapes={"w0": [5, 8]})])
    assert any("shapes keys" in f for f in mod.lint_ledger(str(path)))

    _write_ledger(path, [_round(0, checksums={"w0": True, "w1": 1.0})])
    assert mod.lint_ledger(str(path))  # bool is not a checksum number

    _write_ledger(path, [_round(0, shapes={"w0": [5, 0], "w1": [2, 5]})])
    assert mod.lint_ledger(str(path))  # non-positive dim

    _write_ledger(path, [_round(0, nan=-1)])
    assert any("nan census" in f for f in mod.lint_ledger(str(path)))

    import json

    with open(path, "w") as fp:
        fp.write(json.dumps(_round(0)) + "\n")   # no ledger.open header
    assert any("ledger.open" in f for f in mod.lint_ledger(str(path)))

    assert mod.lint_ledger(str(tmp_path / "missing.jsonl"))  # unreadable


def test_main_ledger_flag_exit_codes(tmp_path, capsys):
    mod = _load()
    path = tmp_path / "ledger.jsonl"
    _write_ledger(path, [_round(0)])
    assert mod.main(["--ledger", str(path)]) == 0
    _write_ledger(path, [_round(3)])
    assert mod.main(["--ledger", str(path)]) == 1
    assert mod.main(["--ledger"]) == 2
    capsys.readouterr()


def _span(sid, parent, name, t0, dt, **over):
    rec = {"ts": 0.0, "ev": "span.end", "kind": "event", "span": sid,
           "parent": parent, "name": name, "t0": t0, "dt": dt}
    rec.update(over)
    return rec


def _cost(exe, **over):
    rec = {"ts": 0.0, "ev": "compile.cost", "kind": "event",
           "exe": exe, "units": 8, "flops": 1e6,
           "bytes_accessed": 2e5}
    rec.update(over)
    return rec


def _perf(name, value, **over):
    rec = {"ts": 0.0, "ev": name, "kind": "gauge", "value": value,
           "exe": "unit.mm"}
    rec.update(over)
    return rec


def _write_sink(path, recs):
    import json

    with open(path, "w") as fp:
        for rec in recs:
            fp.write(json.dumps(rec) + "\n")


def test_perf_lint_accepts_a_well_formed_sink(tmp_path):
    mod = _load()
    path = tmp_path / "m.jsonl"
    _write_sink(path, [
        _span(1, None, "serve.request", 10.0, 1.0),
        _span(2, 1, "serve.queue", 10.1, 0.2),
        _span(3, 1, "serve.dispatch", 10.4, 0.5),
        _cost("serve.k.v0.b8", compile_s=0.01),
        _cost("unit.err", flops=None, bytes_accessed=None,
              error="TracerConversionError"),
        _perf("perf.flops_per_s", 1e8),
        _perf("perf.mfu", 0.001),
        {"ts": 0.0, "ev": "round.start", "kind": "event"},  # bystander
    ])
    assert mod.lint_perf(str(path)) == []


def test_perf_lint_catches_every_schema_break(tmp_path):
    """Each clause bites: missing keys, duplicate span id, a child
    escaping its parent's interval, duplicate cost entry, bad units,
    a non-gauge perf record, a negative rate, a missing exe, and an
    empty sink."""
    mod = _load()
    path = tmp_path / "m.jsonl"

    bad = _span(1, None, "a.b", 0.0, 1.0)
    del bad["t0"]
    _write_sink(path, [bad])
    assert any("missing keys" in f for f in mod.lint_perf(str(path)))

    _write_sink(path, [_span(1, None, "a.b", 0.0, 1.0),
                       _span(1, None, "a.c", 0.5, 0.1)])
    assert any("twice" in f for f in mod.lint_perf(str(path)))

    # child [0.5, 2.5] escapes parent [0.0, 1.0]
    _write_sink(path, [_span(1, None, "a.b", 0.0, 1.0),
                       _span(2, 1, "a.c", 0.5, 2.0)])
    assert any("escapes parent" in f for f in mod.lint_perf(str(path)))

    _write_sink(path, [_cost("x.y"), _cost("x.y")])
    assert any("duplicate" in f for f in mod.lint_perf(str(path)))

    _write_sink(path, [_cost("x.y", units=0)])
    assert any("units" in f for f in mod.lint_perf(str(path)))

    _write_sink(path, [_perf("perf.mfu", 0.5, kind="event")])
    assert any("gauge" in f for f in mod.lint_perf(str(path)))

    _write_sink(path, [_perf("perf.flops_per_s", -1.0)])
    assert any("non-negative" in f for f in mod.lint_perf(str(path)))

    rec = _perf("perf.mfu", 0.5)
    del rec["exe"]
    _write_sink(path, [rec])
    assert any("unattributable" in f for f in mod.lint_perf(str(path)))

    _write_sink(path, [{"ts": 0.0, "ev": "round.start",
                        "kind": "event"}])
    assert any("no span.end" in f for f in mod.lint_perf(str(path)))

    assert mod.lint_perf(str(tmp_path / "missing.jsonl"))


def test_main_perf_flag_exit_codes(tmp_path, capsys):
    mod = _load()
    path = tmp_path / "m.jsonl"
    _write_sink(path, [_span(1, None, "a.b", 0.0, 1.0)])
    assert mod.main(["--perf", str(path)]) == 0
    _write_sink(path, [_span(1, None, "a.b", 0.0, 1.0),
                       _span(2, 1, "a.c", 0.5, 2.0)])
    assert mod.main(["--perf", str(path)]) == 1
    assert mod.main(["--perf"]) == 2
    capsys.readouterr()


def test_call_site_regex_matches_every_emitter_style(tmp_path):
    """obs.timer / bare event() / raw {"ev": ...} records all count."""
    mod = _load()
    pkg = tmp_path / "hpnn_tpu"
    pkg.mkdir()
    (pkg / "styles.py").write_text(
        'with obs.timer("a.timer", tag=1):\n'
        '    pass\n'
        'event("b.bare")\n'
        'rec = {"ev": "c.raw", "kind": "event"}\n'
    )
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text("``\n")
    (tmp_path / "docs" / "serving.md").write_text("\n")
    emitted = mod.emitted_names(str(tmp_path))
    assert set(emitted) == {"a.timer", "b.bare", "c.raw"}


def _chaos_rows():
    return [
        {"ev": "chaos.inject", "kind": "count", "n": 2,
         "seam": "serve.dispatch", "action": "kill"},
        {"ev": "wal.commit", "ts": 1.0, "kernel": "k", "version": 3,
         "model": "ann", "reason": "promote", "step": 9,
         "ckpt": "k.v3.ckpt", "sig": [171717, 424242]},
        {"ev": "wal.skip", "kind": "count", "n": 1, "kernel": "k",
         "reason": "torn"},
        {"ev": "online.checkpoint", "kind": "event", "kernel": "k",
         "version": 3, "reason": "promote", "ckpt": "k.v3.ckpt"},
        {"ev": "online.restore", "kind": "event", "kernel": "k",
         "wal_version": 3, "version": 1, "ckpt": "k.v3.ckpt"},
        {"ev": "online.checkpoint_failed", "kind": "count", "n": 1,
         "kernel": "k", "reason": "OSError"},
        {"ev": "serve.unready", "kind": "event", "reason": "warming"},
        {"ev": "serve.drain", "kind": "event", "signal": 15},
        {"ev": "drill.kill9", "ok": True, "restored_bitwise": True,
         "recovery_s": 1.4, "lost": 3, "requests": 120},
        {"ev": "drill.sentinel", "ok": True, "lost": 0,
         "requests": 75},
    ]


def test_chaos_lint_accepts_a_well_formed_trail(tmp_path):
    mod = _load()
    path = tmp_path / "trail.jsonl"
    _write_sink(path, _chaos_rows())
    assert mod.lint_chaos(str(path)) == []


def test_chaos_lint_catches_every_schema_break(tmp_path):
    mod = _load()
    path = tmp_path / "trail.jsonl"
    breaks = [
        ({"ev": "chaos.inject", "kind": "count", "n": 1,
          "seam": "s", "action": "explode"}, "action"),
        ({"ev": "chaos.inject", "kind": "count", "n": 1,
          "seam": "", "action": "kill"}, "seam"),
        ({"ev": "wal.commit", "kernel": "k", "version": 0,
          "reason": "promote", "ckpt": "k.v0.ckpt",
          "sig": [1, 2]}, "version"),
        ({"ev": "wal.commit", "kernel": "k", "version": 1,
          "reason": "promote", "ckpt": "k.v1.ckpt",
          "sig": [1.5, "x"]}, "sig"),
        ({"ev": "wal.commit", "kernel": "k", "version": 1,
          "reason": "promote", "ckpt": "not-a-checkpoint",
          "sig": [1, 2]}, "ckpt"),
        ({"ev": "wal.skip", "kind": "count", "n": 1,
          "reason": "gremlins"}, "reason"),
        ({"ev": "online.restore", "kernel": "k", "wal_version": "3",
          "ckpt": "k.v3.ckpt"}, "wal_version"),
        ({"ev": "serve.drain", "signal": "SIGTERM"}, "signal"),
        ({"ev": "serve.unready", "reason": ""}, "reason"),
        ({"ev": "drill.kill9", "ok": True, "restored_bitwise": False,
          "recovery_s": 1.0, "lost": 0, "requests": 5},
         "restored_bitwise"),
        ({"ev": "drill.kill9", "ok": True, "restored_bitwise": True,
          "recovery_s": None, "lost": 0, "requests": 5},
         "recovery_s"),
        ({"ev": "drill.sentinel", "ok": "yes"}, "ok"),
        ({"ev": "drill.sentinel", "ok": True, "lost": -1}, "lost"),
        ({"ev": "drill.mystery", "ok": True}, "unknown drill"),
    ]
    for rec, needle in breaks:
        _write_sink(path, [rec])
        failures = mod.lint_chaos(str(path))
        assert failures, f"schema break not caught: {rec}"
        assert any(needle in f for f in failures), (needle, failures)


def test_chaos_lint_fails_an_empty_trail(tmp_path):
    mod = _load()
    path = tmp_path / "not_a_trail.jsonl"
    _write_sink(path, [{"ev": "obs.summary", "kind": "summary"}])
    assert any("no chaos" in f for f in mod.lint_chaos(str(path)))


def test_main_chaos_flag_exit_codes(tmp_path, capsys):
    mod = _load()
    path = tmp_path / "trail.jsonl"
    _write_sink(path, _chaos_rows())
    assert mod.main(["--chaos", str(path)]) == 0
    _write_sink(path, [{"ev": "wal.skip", "kind": "count", "n": 1,
                        "reason": "gremlins"}])
    assert mod.main(["--chaos", str(path)]) == 1
    capsys.readouterr()


def _fleet_rows():
    return [
        {"ev": "trace.adopt", "kind": "count", "n": 1},
        {"ev": "collector.listen", "kind": "event",
         "host": "127.0.0.1", "port": 9464},
        {"ev": "collector.push", "kind": "count", "n": 3},
        {"ev": "collector.recv", "kind": "count", "n": 3,
         "pid": 4242},
        {"ev": "collector.drop", "kind": "count", "n": 1,
         "reason": "queue_full"},
        {"ev": "alert.fire", "rule": "down", "gauge": "router.ready",
         "severity": "crit", "value": 0.0},
        {"ev": "alert.resolve", "rule": "down",
         "gauge": "router.ready", "severity": "crit", "value": 2.0,
         "duration_s": 1.5},
    ]


def test_fleet_lint_accepts_a_well_formed_sink(tmp_path):
    mod = _load()
    path = tmp_path / "fleet.jsonl"
    _write_sink(path, _fleet_rows())
    assert mod.lint_fleet(str(path)) == []


def test_fleet_lint_catches_every_schema_break(tmp_path):
    mod = _load()
    path = tmp_path / "fleet.jsonl"
    breaks = [
        ({"ev": "alert.fire", "rule": "", "gauge": "g",
          "severity": "warn", "value": 1.0}, "non-empty string"),
        ({"ev": "alert.fire", "rule": "r", "gauge": "g",
          "severity": "fatal", "value": 1.0}, "info|warn|crit"),
        ({"ev": "alert.fire", "rule": "r", "gauge": "g",
          "severity": "info", "value": "high"},
         "is not a finite number"),
        ({"ev": "alert.resolve", "rule": "r", "gauge": "g",
          "severity": "info", "value": 1.0, "duration_s": -1.0},
         "finite non-negative"),
        ({"ev": "alert.resolve", "rule": "r", "gauge": "g",
          "severity": "info", "value": 1.0, "duration_s": 0.5},
         "with no unresolved alert.fire before it"),
        ({"ev": "collector.push", "kind": "event", "n": 1},
         "!= 'count'"),
        ({"ev": "trace.adopt", "kind": "count", "n": 0},
         "positive int"),
        ({"ev": "collector.drop", "kind": "count", "n": 1},
         "collector.drop reason"),
        ({"ev": "collector.recv", "kind": "count", "n": 1,
          "pid": 3.5}, "non-negative int"),
        ({"ev": "collector.listen", "kind": "event", "host": "",
          "port": 9464}, "host"),
        ({"ev": "collector.listen", "kind": "event",
          "host": "127.0.0.1", "port": 0}, "port"),
    ]
    for rec, needle in breaks:
        _write_sink(path, [rec])
        failures = mod.lint_fleet(str(path))
        assert failures, f"schema break not caught: {rec}"
        assert any(needle in f for f in failures), (needle, failures)


def test_fleet_lint_rejects_a_double_fire(tmp_path):
    mod = _load()
    path = tmp_path / "fleet.jsonl"
    fire = {"ev": "alert.fire", "rule": "r", "gauge": "g",
            "severity": "warn", "value": 9.0}
    _write_sink(path, [fire, dict(fire)])
    assert any("while already active" in f
               for f in mod.lint_fleet(str(path)))


def test_fleet_lint_fails_a_sink_with_no_fleet_records(tmp_path):
    mod = _load()
    path = tmp_path / "quiet.jsonl"
    _write_sink(path, [{"ev": "obs.summary", "kind": "summary"}])
    assert any("has no trace." in f for f in mod.lint_fleet(str(path)))


def test_main_fleet_flag_exit_codes(tmp_path, capsys):
    mod = _load()
    path = tmp_path / "fleet.jsonl"
    _write_sink(path, _fleet_rows())
    assert mod.main(["--fleet", str(path)]) == 0
    _write_sink(path, [{"ev": "collector.drop", "kind": "count",
                        "n": 1}])
    assert mod.main(["--fleet", str(path)]) == 1
    capsys.readouterr()


def _drift_rows():
    return [
        {"ev": "drift.score", "kind": "gauge", "value": 0.4,
         "detector": "ingest", "kernel": "stream"},
        {"ev": "drift.score", "kind": "gauge", "value": 1.6,
         "detector": "eval", "kernel": "k"},
        {"ev": "drift.pred_shift", "kind": "gauge", "value": 0.12,
         "kernel": "k"},
        {"ev": "drift.eval_decay", "kind": "gauge", "value": -0.8,
         "kernel": "k"},
        {"ev": "online.drift", "kind": "event", "detector": "eval",
         "kernel": "k", "score": 1.6, "window": 64, "raw": 1.97},
        {"ev": "online.eval_resident", "kind": "gauge", "value": 0.43,
         "kernel": "k"},
        {"ev": "alert.fire", "rule": "drift", "gauge": "drift.score",
         "severity": "warn", "value": 1.6},
    ]


def test_drift_lint_accepts_a_well_formed_sink(tmp_path):
    mod = _load()
    path = tmp_path / "drift.jsonl"
    _write_sink(path, _drift_rows())
    assert mod.lint_drift(str(path)) == []


def test_drift_lint_catches_every_schema_break(tmp_path):
    """Each clause bites: non-gauge score, NaN score, unknown
    detector, empty kernel, negative PSI, non-numeric z, a
    below-bound online.drift, bad window, missing raw, and a NaN
    resident eval."""
    mod = _load()
    path = tmp_path / "drift.jsonl"
    breaks = [
        ({"ev": "drift.score", "kind": "event", "value": 0.4,
          "detector": "ingest", "kernel": "stream"}, "!= 'gauge'"),
        ({"ev": "drift.score", "kind": "gauge", "value": float("nan"),
          "detector": "ingest", "kernel": "stream"},
         "finite non-negative"),
        ({"ev": "drift.score", "kind": "gauge", "value": 0.4,
          "detector": "vibes", "kernel": "stream"}, "detector"),
        ({"ev": "drift.score", "kind": "gauge", "value": 0.4,
          "detector": "ingest", "kernel": ""}, "kernel"),
        ({"ev": "drift.pred_shift", "kind": "gauge", "value": -0.1,
          "kernel": "k"}, "finite non-negative"),
        ({"ev": "drift.eval_decay", "kind": "gauge", "value": "low",
          "kernel": "k"}, "finite number"),
        ({"ev": "online.drift", "kind": "event", "detector": "eval",
          "kernel": "k", "score": 0.4, "window": 64, "raw": 0.5},
         "breach edge"),
        ({"ev": "online.drift", "kind": "event", "detector": "eval",
          "kernel": "k", "score": 1.6, "window": 0, "raw": 0.5},
         "int >= 1"),
        ({"ev": "online.drift", "kind": "event", "detector": "eval",
          "kernel": "k", "score": 1.6, "window": 64}, "raw"),
        ({"ev": "online.eval_resident", "kind": "gauge", "value": None,
          "kernel": "k"}, "finite number"),
    ]
    for rec, needle in breaks:
        _write_sink(path, [rec])
        failures = mod.lint_drift(str(path))
        assert failures, f"schema break not caught: {rec}"
        assert any(needle in f for f in failures), (needle, failures)


def test_drift_lint_fails_an_unarmed_sink(tmp_path):
    mod = _load()
    path = tmp_path / "quiet.jsonl"
    _write_sink(path, [{"ev": "obs.summary", "kind": "summary"}])
    assert any("no drift records" in f
               for f in mod.lint_drift(str(path)))


def test_drift_lint_checks_the_capsule_artifact(tmp_path):
    """A capsule captured for a drift-rule alert must contain
    drift.json; writing the artifact clears the failure."""
    mod = _load()
    path = tmp_path / "drift.jsonl"
    cap = tmp_path / "capsule-1-alert-drift"
    cap.mkdir()
    rows = _drift_rows() + [
        {"ev": "forensics.capture_done", "kind": "event",
         "reason": "alert:drift", "capsule": str(cap),
         "files": ["spans.jsonl"]},
    ]
    _write_sink(path, rows)
    assert any("drift.json" in f for f in mod.lint_drift(str(path)))
    (cap / "drift.json").write_text("{}")
    assert mod.lint_drift(str(path)) == []


def test_main_drift_flag_exit_codes(tmp_path, capsys):
    mod = _load()
    path = tmp_path / "drift.jsonl"
    _write_sink(path, _drift_rows())
    assert mod.main(["--drift", str(path)]) == 0
    _write_sink(path, [{"ev": "drift.score", "kind": "gauge",
                        "value": -2.0, "detector": "ingest",
                        "kernel": "stream"}])
    assert mod.main(["--drift", str(path)]) == 1
    assert mod.main(["--drift"]) == 2
    capsys.readouterr()


# ------------------------------------------------- connection plane
def _conn_rows():
    """A well-formed conn-armed run: one clean keep-alive connection,
    one guard-killed slowloris, one per-IP-cap refusal (admit-time
    close with no byte ledger)."""
    return [
        {"ev": "conn.open", "kind": "count", "n": 1, "total": 1,
         "id": "p-c1", "ip": "127.0.0.1", "port": 40001,
         "plane": "serve"},
        {"ev": "conn.active", "kind": "gauge", "value": 1,
         "plane": "serve"},
        {"ev": "conn.oldest_s", "kind": "gauge", "value": 0.0,
         "plane": "serve"},
        {"ev": "conn.open", "kind": "count", "n": 1, "total": 2,
         "id": "p-c2", "ip": "10.0.0.9", "port": 40002,
         "plane": "serve"},
        {"ev": "conn.open", "kind": "count", "n": 1, "total": 3,
         "id": "p-c3", "ip": "10.0.0.9", "port": 40003,
         "plane": "serve"},
        {"ev": "conn.close", "kind": "count", "n": 1, "total": 1,
         "id": "p-c3", "reason": "guard", "detail": "per_ip_cap",
         "plane": "serve", "bytes_in": 0, "bytes_out": 0,
         "requests": 0, "duration_s": 0.0, "phase": "admit"},
        {"ev": "conn.guard_kill", "kind": "count", "n": 1, "total": 1,
         "reason": "slowloris", "id": "p-c2", "ip": "10.0.0.9",
         "plane": "serve"},
        {"ev": "conn.guard_kills", "kind": "gauge", "value": 1,
         "plane": "serve"},
        {"ev": "conn.close", "kind": "count", "n": 1, "total": 2,
         "id": "p-c2", "reason": "guard", "plane": "serve",
         "bytes_in": 41, "bytes_out": 0, "requests": 0,
         "duration_s": 2.04, "phase": "header"},
        {"ev": "conn.close", "kind": "count", "n": 1, "total": 3,
         "id": "p-c1", "reason": "eof", "plane": "serve",
         "bytes_in": 380, "bytes_out": 912, "requests": 3,
         "duration_s": 1.5, "phase": "idle"},
        {"ev": "conn.active", "kind": "gauge", "value": 0,
         "plane": "serve"},
    ]


def test_conn_lint_accepts_a_well_formed_sink(tmp_path):
    mod = _load()
    path = tmp_path / "conn.jsonl"
    _write_sink(path, _conn_rows())
    assert mod.lint_conn(str(path)) == []


def test_conn_lint_catches_every_schema_break(tmp_path):
    """Each clause bites: wrong kind, bad increment, empty id, reused
    open id, orphan close, double close, unknown close reason,
    negative byte ledger, NaN duration, unknown kill reason, a kill
    naming no open, and a NaN gauge."""
    mod = _load()
    path = tmp_path / "conn.jsonl"
    base = _conn_rows()
    breaks = [
        ({"ev": "conn.open", "kind": "gauge", "n": 1, "id": "p-x",
          "ip": "1.2.3.4", "plane": "serve"}, "!= 'count'"),
        ({"ev": "conn.open", "kind": "count", "n": 0, "id": "p-x",
          "ip": "1.2.3.4", "plane": "serve"}, "positive int"),
        ({"ev": "conn.open", "kind": "count", "n": 1, "id": "",
          "ip": "1.2.3.4", "plane": "serve"}, "non-empty string"),
        ({"ev": "conn.open", "kind": "count", "n": 1, "id": "p-c1",
          "ip": "1.2.3.4", "plane": "serve"}, "reused"),
        ({"ev": "conn.close", "kind": "count", "n": 1, "id": "ghost",
          "reason": "eof", "bytes_in": 0, "bytes_out": 0,
          "requests": 0, "phase": "idle"}, "unadmitted"),
        ({"ev": "conn.close", "kind": "count", "n": 1, "id": "p-c1",
          "reason": "eof", "bytes_in": 0, "bytes_out": 0,
          "requests": 0, "phase": "idle"}, "closed twice"),
        ({"ev": "conn.close", "kind": "count", "n": 1, "id": "p-c2",
          "reason": "vibes", "bytes_in": 0, "bytes_out": 0,
          "requests": 0, "phase": "idle"}, "reason"),
        ({"ev": "conn.close", "kind": "count", "n": 1, "id": "p-c2",
          "reason": "eof", "bytes_in": -4, "bytes_out": 0,
          "requests": 0, "phase": "idle"}, "non-negative int"),
        ({"ev": "conn.close", "kind": "count", "n": 1, "id": "p-c2",
          "reason": "eof", "bytes_in": 0, "bytes_out": 0,
          "requests": 0, "duration_s": float("nan"),
          "phase": "idle"}, "duration_s"),
        ({"ev": "conn.guard_kill", "kind": "count", "n": 1,
          "reason": "vibes", "id": "p-c1", "plane": "serve"},
         "slowloris/stall"),
        ({"ev": "conn.guard_kill", "kind": "count", "n": 1,
          "reason": "stall", "id": "ghost", "plane": "serve"},
         "names no opened"),
        ({"ev": "conn.active", "kind": "gauge",
          "value": float("nan"), "plane": "serve"},
         "finite non-negative"),
    ]
    for rec, needle in breaks:
        # appended after a valid run so the pairing state is primed
        # (a double close needs p-c1 already closed, etc.)
        _write_sink(path, base + [rec])
        failures = mod.lint_conn(str(path))
        assert failures, f"schema break not caught: {rec}"
        assert any(needle in f for f in failures), (needle, failures)


def test_conn_lint_fails_a_leaked_open(tmp_path):
    """An open with no paired close means the sink lost a death —
    server shutdown drains leftovers, so a leak is a real bug."""
    mod = _load()
    path = tmp_path / "conn.jsonl"
    _write_sink(path, _conn_rows() + [
        {"ev": "conn.open", "kind": "count", "n": 1, "id": "p-c9",
         "ip": "127.0.0.1", "port": 40009, "plane": "serve"},
    ])
    assert any("without a paired conn.close" in f
               for f in mod.lint_conn(str(path)))


def test_conn_lint_fails_an_unarmed_sink(tmp_path):
    mod = _load()
    path = tmp_path / "quiet.jsonl"
    _write_sink(path, [{"ev": "obs.summary", "kind": "summary"}])
    assert any("no conn.* records" in f
               for f in mod.lint_conn(str(path)))


def test_main_conn_flag_exit_codes(tmp_path, capsys):
    mod = _load()
    path = tmp_path / "conn.jsonl"
    _write_sink(path, _conn_rows())
    assert mod.main(["--conn", str(path)]) == 0
    _write_sink(path, [{"ev": "conn.close", "kind": "count", "n": 1,
                        "id": "ghost", "reason": "eof",
                        "phase": "idle"}])
    assert mod.main(["--conn", str(path)]) == 1
    assert mod.main(["--conn"]) == 2
    capsys.readouterr()
