"""Fleet batching (hpnn_tpu/train/fleet.py + serve fleet dispatch,
docs/fleet.md).

Acceptance bar (ISSUE 6): a same-seed 8-member fleet trained in ONE
vmapped dispatch produces ledgers that ``tools/ledger_diff.py``
reports clean against 8 sequential per-kernel runs (reference
1e-14/1e-12 tolerances — on the f64 CPU path the weights are in fact
bitwise equal), and serve-side fleet dispatch in parity mode returns
outputs bitwise equal to the per-kernel ``engine.dispatch`` path.
Also covers: the double-buffered banked epoch's interpret-mode parity
with the grid epoch, topology validation/fallback rules, the
pad-waste / fleet.* obs emissions, the Session fleet mode round trip,
and the ``--perf`` lint's fleet record rules.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.models import ann, kernel as kernel_mod
from hpnn_tpu.serve.engine import Engine, fleet_key
from hpnn_tpu.serve.registry import Registry
from hpnn_tpu.train import fleet

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _kernels(n, seed0=7, n_in=8, hiddens=(5,), n_out=2):
    return [kernel_mod.generate(seed0 + i, n_in, list(hiddens), n_out)[0]
            for i in range(n)]


def _data(n_rows=8, n_in=8, n_out=2, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.uniform(-1, 1, (n_rows, n_in))
    T = np.full((n_rows, n_out), -1.0)
    T[np.arange(n_rows), rng.randint(0, n_out, n_rows)] = 1.0
    return X, T


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


# ---------------------------------------------------------- stacking
def test_stack_unstack_roundtrip_and_topology_validation():
    ks = _kernels(3)
    stacked = fleet.stack_kernels(ks)
    assert stacked[0].shape == (3, 5, 8) and stacked[1].shape == (3, 2, 5)
    back = fleet.unstack_kernels(stacked)
    for a, b in zip(ks, back):
        for wa, wb in zip(a.weights, b.weights):
            assert np.array_equal(np.asarray(wa), np.asarray(wb))
    odd = kernel_mod.generate(1, 8, [6], 2)[0]  # different hidden width
    with pytest.raises(ValueError, match="topology"):
        fleet.stack_kernels(ks + [odd])
    with pytest.raises(ValueError, match="at least one"):
        fleet.stack_kernels([])


def test_member_plan_shapes_and_refresh_degrade():
    perms, orders = fleet.member_plan(5, n_rows=8, batch=2, epochs=16,
                                      refresh=8)
    assert perms.shape == (2, 8) and orders.shape == (2, 8, 4)
    # refresh that does not divide epochs degrades to 1 (fresh
    # permutation every epoch), never silently truncates
    perms, orders = fleet.member_plan(5, n_rows=8, batch=2, epochs=3,
                                      refresh=8)
    assert perms.shape == (3, 8) and orders.shape == (3, 1, 4)
    # per-member streams differ, same seed reproduces
    p2, _ = fleet.member_plan(6, n_rows=8, batch=2, epochs=3)
    p1, _ = fleet.member_plan(5, n_rows=8, batch=2, epochs=3)
    assert not np.array_equal(p1, p2)
    assert np.array_equal(p1, fleet.member_plan(5, n_rows=8, batch=2,
                                                epochs=3)[0])


# ---------------------------------------------- fleet vs sequential
def test_fleet_vs_sequential_bitwise_and_ledger_diff_clean(
        tmp_path, monkeypatch):
    """AC: same-seed 8-member fleet vs 8 sequential runs — weights
    bitwise equal on the f64 CPU path, and the two parity ledgers
    diff clean under the reference tolerances."""
    ks = _kernels(8)
    X, T = _data()
    seeds = list(range(8))
    led_f = tmp_path / "fleet.jsonl"
    led_s = tmp_path / "seq.jsonl"

    monkeypatch.setenv("HPNN_LEDGER", str(led_f))
    obs._reset_for_tests()
    out_f, loss_f, cnt_f = fleet.train_fleet(
        ks, X, T, epochs=2, batch=2, seeds=seeds)

    monkeypatch.setenv("HPNN_LEDGER", str(led_s))
    obs._reset_for_tests()
    out_s, loss_s, cnt_s = fleet.train_sequential(
        ks, X, T, epochs=2, batch=2, seeds=seeds)

    monkeypatch.delenv("HPNN_LEDGER", raising=False)
    obs._reset_for_tests()  # close the ledger files

    assert loss_f.shape == (8, 2, 4) and cnt_f.shape == (8, 2)
    for kf, ks_ in zip(out_f, out_s):
        for wa, wb in zip(kf.weights, ks_.weights):
            assert np.array_equal(np.asarray(wa), np.asarray(wb))
    assert np.array_equal(loss_f, loss_s)
    assert np.array_equal(cnt_f, cnt_s)

    ld = _load_tool("ledger_diff")
    rows_f = ld.load_rounds(str(led_f))
    rows_s = ld.load_rounds(str(led_s))
    assert len(rows_f) == 8 and len(rows_s) == 8  # one row per member
    assert {r["where"] for r in rows_f} == {"fleet_round"}
    report = ld.compare(rows_f, rows_s)
    assert report["clean"], report["divergent"]
    assert ld.main([str(led_f), str(led_s)]) == 0
    # the fleet ledger also passes the frozen-schema lint
    cat = _load_tool("check_obs_catalog")
    assert cat.lint_ledger(str(led_f)) == []


def test_train_fleet_validates_seed_count():
    ks = _kernels(2)
    X, T = _data()
    with pytest.raises(ValueError, match="seeds"):
        fleet.train_fleet(ks, X, T, epochs=1, batch=2, seeds=[1])


# ------------------------------------------- double-buffered epoch
@pytest.mark.parametrize("momentum", [False, True])
def test_dbuf_epoch_matches_grid_epoch_interpret(momentum):
    """The explicit DMA pipeline computes the exact same epoch as the
    grid kernel (interpret mode; bitwise f32)."""
    import jax.numpy as jnp

    from hpnn_tpu.ops import pallas_train

    k = _kernels(1)[0]
    w = tuple(jnp.asarray(np.asarray(wl), jnp.float32) for wl in k.weights)
    dw = tuple(jnp.zeros_like(wl) for wl in w) if momentum else ()
    X, T = _data(n_rows=12)
    Xb = jnp.asarray(X, jnp.float32)
    Tb = jnp.asarray(T, jnp.float32)
    order = jnp.asarray(np.random.RandomState(0).permutation(3),
                        jnp.int32)  # S=3 blocks of B=4
    wg, dwg, lg = pallas_train.train_epoch_grid_banked(
        w, dw, Xb, Tb, order, batch=4, momentum=momentum,
        interpret=True)
    wd, dwd, ldb = pallas_train.train_epoch_dbuf_banked(
        w, dw, Xb, Tb, order, batch=4, momentum=momentum,
        interpret=True)
    for a, b in zip(wg, wd):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(dwg, dwd):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert np.array_equal(np.asarray(lg), np.asarray(ldb))


def test_bank_fn_dbuf_convention_matches_per_step_path():
    """make_multi_epoch_bank_fn(banked="dbuf") hands the WHOLE epoch
    to the step fn (the grid/dbuf call convention); with a pure-jnp
    epoch body it must reproduce the banked=False per-step trajectory
    bitwise."""
    import jax.numpy as jnp
    from jax import lax

    from hpnn_tpu.parallel import dp
    from hpnn_tpu.train import batch as batch_mod

    k = _kernels(1)[0]
    w = tuple(jnp.asarray(np.asarray(wl)) for wl in k.weights)
    X, T = _data(n_rows=8)
    X, T = jnp.asarray(X), jnp.asarray(T)
    S, lr = 4, dp.default_lr("ann", False)

    def math_step(w2, m2, Xb, Tb):
        return dp.train_step_math(w2, m2, Xb, Tb, model="ann",
                                  momentum=False, lr=lr, alpha=0.2)

    def epoch_fn(w2, m2, Xp, Tp, ord_e):
        Xs = Xp.reshape(S, -1, Xp.shape[1])
        Ts = Tp.reshape(S, -1, Tp.shape[1])

        def body(c, kk):
            w3, m3 = c
            w3, m3, l = math_step(w3, m3, Xs[kk], Ts[kk])
            return (w3, m3), l

        (w2, m2), losses = lax.scan(body, (w2, m2), ord_e)
        return w2, m2, losses

    count_fn = batch_mod.make_device_count_fn(model="ann")
    fn_dbuf = batch_mod.make_multi_epoch_bank_fn(
        epoch_fn, count_fn, S, banked="dbuf")
    fn_base = batch_mod.make_multi_epoch_bank_fn(
        math_step, count_fn, S, banked=False)
    perms, orders = fleet.member_plan(3, n_rows=8, batch=2, epochs=2)
    wa, _, la, ca = fn_dbuf(w, (), X, T, perms, orders)
    wb, _, lb, cb = fn_base(w, (), X, T, perms, orders)
    for a, b in zip(wa, wb):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert np.array_equal(np.asarray(la), np.asarray(lb))
    assert np.array_equal(np.asarray(ca), np.asarray(cb))


# ------------------------------------------------------ serve fleet
def _engine(names_kernels, **kw):
    reg = Registry()
    for name, k in names_kernels:
        reg.register(name, k)
    return Engine(reg, max_batch=8, n_buckets=2, **kw)


def test_fleet_key_groups_by_topology():
    a, b = _kernels(2)
    odd = kernel_mod.generate(1, 8, [6], 2)[0]
    reg = Registry()
    reg.register("a", a)
    reg.register("b", b)
    reg.register("odd", odd)
    assert fleet_key(reg.get("a")) == fleet_key(reg.get("b"))
    assert fleet_key(reg.get("a")) != fleet_key(reg.get("odd"))


def test_dispatch_fleet_parity_matches_per_kernel_dispatch():
    """AC: fleet dispatch in parity mode is bitwise identical to the
    per-kernel engine.dispatch path, with results in payload order."""
    a, b = _kernels(2)
    eng = _engine([("a", a), ("b", b)], mode="parity")
    rng = np.random.RandomState(1)
    pa1, pb, pa2 = (rng.uniform(-1, 1, (2, 8)), rng.uniform(-1, 1, (3, 8)),
                    rng.uniform(-1, 1, (1, 8)))
    results = eng.dispatch_fleet([("a", pa1), ("b", pb), ("a", pa2)])
    assert [r.shape for r in results] == [(2, 2), (3, 2), (1, 2)]
    ref_a = eng.dispatch("a", [pa1, pa2])
    ref_b = eng.dispatch("b", [pb])
    assert np.array_equal(results[0], ref_a[0])
    assert np.array_equal(results[2], ref_a[1])
    assert np.array_equal(results[1], ref_b[0])
    # and both equal the direct per-sample reference forward
    direct = np.stack([np.asarray(ann.run(a.weights, x)) for x in pa1])
    assert np.array_equal(results[0], direct)


def test_dispatch_fleet_fallbacks():
    """Singleton groups, mixed topologies, and oversize batches take
    the per-kernel path — same answers, no fleet executable."""
    a, b = _kernels(2)
    odd = kernel_mod.generate(1, 8, [6], 2)[0]
    eng = _engine([("a", a), ("b", b), ("odd", odd)], mode="parity")
    rng = np.random.RandomState(2)
    ra = rng.uniform(-1, 1, (2, 8))
    rodd = rng.uniform(-1, 1, (2, 8))
    # mixed topology: "odd" can never join a's group
    res = eng.dispatch_fleet([("a", ra), ("odd", rodd)])
    assert np.array_equal(res[0], eng.dispatch("a", [ra])[0])
    assert np.array_equal(res[1], eng.dispatch("odd", [rodd])[0])
    # oversize: rows above the top bucket chunk via the per-kernel path
    big = rng.uniform(-1, 1, (11, 8))  # top bucket is 8
    rb = rng.uniform(-1, 1, (2, 8))
    res = eng.dispatch_fleet([("a", big), ("b", rb)])
    assert np.array_equal(res[0], eng.dispatch("a", [big])[0])
    assert np.array_equal(res[1], eng.dispatch("b", [rb])[0])


def test_dispatch_fleet_compiled_mode_close_to_parity():
    a, b = _kernels(2)
    par = _engine([("a", a), ("b", b)], mode="parity")
    comp = _engine([("a", a), ("b", b)], mode="compiled")
    rng = np.random.RandomState(4)
    pa = rng.uniform(-1, 1, (3, 8))
    pb = rng.uniform(-1, 1, (2, 8))
    rp = par.dispatch_fleet([("a", pa), ("b", pb)])
    rc = comp.dispatch_fleet([("a", pa), ("b", pb)])
    for x, y in zip(rp, rc):
        np.testing.assert_allclose(x, y, atol=1e-12, rtol=0)


def test_fleet_obs_emissions(tmp_path, monkeypatch):
    """One coalesced fleet group emits serve.fleet_group, the
    fleet.size gauge (where=serve), the serve.fleet_dispatch span,
    and a per-member serve.pad_waste gauge tagged fleet=True."""
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    monkeypatch.setenv("HPNN_SPANS", "1")
    obs._reset_for_tests()
    a, b = _kernels(2)
    eng = _engine([("a", a), ("b", b)], mode="parity")
    rng = np.random.RandomState(6)
    eng.dispatch_fleet([("a", rng.uniform(-1, 1, (2, 8))),
                        ("b", rng.uniform(-1, 1, (3, 8)))])
    monkeypatch.delenv("HPNN_SPANS", raising=False)
    obs._reset_for_tests()
    recs = _read(sink)
    by = {}
    for r in recs:
        by.setdefault(r["ev"], []).append(r)
    grp = by["serve.fleet_group"][0]
    assert grp["members"] == 2 and grp["rows"] == 5
    sizes = [r for r in by["fleet.size"] if r.get("where") == "serve"]
    assert sizes and sizes[0]["value"] == 2
    waste = [r for r in by["serve.pad_waste"] if r.get("fleet")]
    assert {r["kernel"] for r in waste} == {"a", "b"}
    assert all(r["value"] == 0.0 for r in waste)  # parity never pads
    spans = [r for r in by["span.end"]
             if r.get("name") == "serve.fleet_dispatch"]
    assert spans and spans[0]["members"] == 2
    # the sink also passes the --perf fleet rules
    cat = _load_tool("check_obs_catalog")
    assert cat.lint_perf(str(sink)) == []


def test_session_fleet_mode_roundtrip():
    """End to end: Session(fleet=True) serves two same-topology
    kernels through ONE shared batcher, answers bitwise-equal to the
    direct forward (CPU parity mode)."""
    a, b = _kernels(2)
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0,
                         fleet=True)
    try:
        sess.register_kernel("a", a)
        sess.register_kernel("b", b)
        x = np.random.RandomState(9).uniform(-1, 1, 8)
        ya = sess.infer("a", x)
        yb = sess.infer("b", x)
        assert np.array_equal(ya, np.asarray(ann.run(a.weights, x)))
        assert np.array_equal(yb, np.asarray(ann.run(b.weights, x)))
        # one shared batcher, named for the fleet
        assert sess.batcher_for("a") is sess.batcher_for("b")
        assert list(sess.health()["batchers"]) == ["(fleet)"]
    finally:
        sess.close()


# -------------------------------------------------- --perf fleet lint
def test_lint_perf_fleet_rules(tmp_path):
    cat = _load_tool("check_obs_catalog")
    bad = tmp_path / "bad.jsonl"
    rows = [
        {"ts": 1.0, "ev": "span.end", "kind": "event", "span": 1,
         "parent": None, "name": "serve.fleet_dispatch", "t0": 0.0,
         "dt": 0.1},                                  # no members
        {"ts": 1.0, "ev": "fleet.size", "kind": "gauge", "value": 0,
         "where": "serve"},                           # empty fleet
    ]
    bad.write_text("".join(json.dumps(r) + "\n" for r in rows))
    fails = cat.lint_perf(str(bad))
    assert any("members" in f for f in fails)
    assert any("fleet.size" in f for f in fails)
    good = tmp_path / "good.jsonl"
    rows = [
        {"ts": 1.0, "ev": "span.end", "kind": "event", "span": 1,
         "parent": None, "name": "serve.fleet_dispatch", "t0": 0.0,
         "dt": 0.1, "members": 2, "bucket": 8},
        {"ts": 1.0, "ev": "fleet.size", "kind": "gauge", "value": 2,
         "where": "serve"},
    ]
    good.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert cat.lint_perf(str(good)) == []
