"""Load harness + SLO observability (obs/slo.py, batcher shedding,
tools/loadgen.py; docs/observability.md "SLOs and load").

Acceptance bar (ISSUE): a tier-1 test drives the HTTP loadgen against
an in-process server (CPU, small kernel) and asserts the ``slo.*``
gauges, ``serve.shed`` events and ``/healthz`` shed counters appear —
and that the sink lints clean under ``check_obs_catalog.py --slo``.
The tracker, the admission control, and the deadline-vs-submit race
are asserted with fake clocks and zero sleeps.
"""

import http.client
import importlib
import importlib.util
import json
import math
import os
import sys
import threading
import time

import numpy as np
import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import slo
from hpnn_tpu.serve import batcher as batcher_mod
from hpnn_tpu.serve.server import make_server

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _import_tool(name):
    """Import a tool as a real module (shared ``sys.modules`` entry,
    so cross-tool ``from loadgen import ...`` resolves to the same
    object — the helper-sharing identity test needs that)."""
    tools = os.path.join(ROOT, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    return importlib.import_module(name)


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _kernel(seed=7):
    k, _ = kernel_mod.generate(seed, 8, [5], 2)
    return k


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def slo_env():
    """Leave no SLO/shed env state behind (``slo.configure`` writes
    ``os.environ`` directly, so monkeypatch can't track it)."""
    yield
    for key in (slo.ENV_KNOB, slo.ENV_WINDOW, slo.ENV_TARGET,
                "HPNN_SHED_AGE_MS", "HPNN_SHED_P99_MS"):
        os.environ.pop(key, None)
    slo._reset_for_tests()


# -------------------------------------------------------------- tracker
def test_tracker_percentiles_attainment_burn():
    clock = FakeClock()
    tr = slo.Tracker(50.0, window_s=100.0, target=0.9, clock=clock)
    lats_ms = list(range(1, 101))            # 1..100 ms, half within
    for ms in lats_ms:
        tr.record("ok", latency_s=ms / 1e3)
    snap = tr.snapshot()
    assert snap["requests"] == snap["served"] == 100
    assert snap["shed"] == 0
    assert snap["p50_ms"] == pytest.approx(
        float(np.percentile(lats_ms, 50)), abs=1e-6)
    assert snap["p99_ms"] == pytest.approx(
        float(np.percentile(lats_ms, 99)), abs=1e-6)
    assert snap["attainment"] == pytest.approx(0.5)
    assert snap["burn_rate"] == pytest.approx(0.5 / 0.1, rel=1e-4)
    assert snap["verdict"] == "breach"
    # shed outcomes are excluded from both percentiles and attainment
    for _ in range(10):
        tr.record("shed")
    snap = tr.snapshot()
    assert (snap["requests"], snap["served"], snap["shed"]) \
        == (110, 100, 10)
    assert snap["attainment"] == pytest.approx(0.5)
    # an expired request is a completed miss
    tr.record("expired")
    snap = tr.snapshot()
    assert snap["attainment"] == pytest.approx(50 / 101)


def test_tracker_window_prunes_and_empty_window_is_ok():
    clock = FakeClock()
    tr = slo.Tracker(50.0, window_s=10.0, clock=clock)
    tr.record("ok", latency_s=0.010)
    clock.advance(5.0)
    tr.record("ok", latency_s=0.020)
    assert tr.snapshot()["requests"] == 2
    clock.advance(6.0)                       # t=11: the t=0 entry ages out
    tr.record("ok", latency_s=0.030)
    assert tr.snapshot()["requests"] == 2
    clock.advance(20.0)                      # everything ages out
    snap = tr.snapshot()
    assert snap["requests"] == 0 and snap["served"] == 0
    assert snap["p50_ms"] is None and snap["p99_ms"] is None
    assert snap["attainment"] == 1.0         # vacuous window: no breach
    assert snap["verdict"] == "ok"


def test_tracker_validates_arguments():
    with pytest.raises(ValueError):
        slo.Tracker(0.0)
    with pytest.raises(ValueError):
        slo.Tracker(50.0, target=1.0)


def test_slo_disabled_is_noop(monkeypatch):
    monkeypatch.delenv(slo.ENV_KNOB, raising=False)
    slo._reset_for_tests()
    assert not slo.enabled()
    slo.record("ok", 0.001)                  # must not build a tracker
    assert slo._tracker is None
    assert slo.current_p99_ms() is None
    assert slo.health_doc() == {"mode": "off"}


def test_configure_publish_gauges_and_current_p99(tmp_path, monkeypatch,
                                                  slo_env):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    clock = FakeClock()
    slo.configure(50.0, window_s=30.0, target=0.9, clock=clock)
    assert slo.enabled()
    slo.record("ok", latency_s=0.2)          # first record publishes
    assert slo.current_p99_ms() == pytest.approx(200.0)
    doc = slo.health_doc()
    assert doc["mode"] == "on" and doc["slo_ms"] == 50.0
    assert doc["verdict"] == "breach"
    obs.flush()
    evs = {r["ev"] for r in _read(sink)}
    assert {"slo.p50_ms", "slo.p99_ms", "slo.attainment",
            "slo.burn_rate", "slo.window_requests"} <= evs
    # disarm: back to the no-op contract
    slo.configure(None)
    assert not slo.enabled()
    assert slo.health_doc() == {"mode": "off"}


# ---------------------------------------------- quantile interpolation
def test_quantile_estimate_round_trips_through_the_registry(
        tmp_path, monkeypatch):
    """Observe a latency-shaped sample through the real registry, then
    recover quantiles from its log2 buckets: each estimate stays
    within the landing bucket (≤2x of exact, vs the old upper-bound
    answer), is monotone in q, and collapses exactly for point
    distributions (the [min, max] clamp)."""
    from hpnn_tpu.obs import export as export_mod

    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    rng = np.random.RandomState(3)
    samples = rng.lognormal(mean=1.0, sigma=1.2, size=4000)
    obs.observe("h", samples)
    obs.summary()
    obs.flush()
    agg = next(r for r in _read(sink)
               if r["ev"] == "obs.summary")["aggregates"]["h"]
    ests = []
    for q in (0.5, 0.9, 0.99):
        est = export_mod._quantile_estimate(agg, q)
        exact = float(np.percentile(samples, q * 100))
        assert 0.5 <= est / exact <= 2.0, (q, est, exact)
        ests.append(est)
    assert ests == sorted(ests)
    assert agg["min"] <= ests[0] and ests[-1] <= agg["max"]
    # point distribution: interpolation + clamp answer the value itself
    point = {"n": 9, "min": 17.0, "max": 17.0,
             "log2_buckets": {str(math.frexp(17.0)[1]): 9}}
    for q in (0.5, 0.99):
        assert export_mod._quantile_estimate(point, q) == 17.0


# ------------------------------------------------------------- shedding
def test_batcher_sheds_on_queue_age(tmp_path, monkeypatch):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    obs._reset_for_tests()
    clock = FakeClock()
    b = batcher_mod.Batcher(lambda p: list(p), shed_age_ms=10.0,
                            clock=clock, start=False, name="aged")
    first = b.submit("a")                    # empty queue always admits
    clock.advance(0.005)
    b.submit("b")                            # 5 ms < threshold: admitted
    clock.advance(0.006)
    with pytest.raises(batcher_mod.Shed) as ei:
        b.submit("c", req_id="r-1")
    assert ei.value.reason == "queue_age"
    assert ei.value.retriable and ei.value.retry_after_s > 0
    assert isinstance(ei.value, batcher_mod.QueueFull)  # same 429 path
    assert b.shed_counts() == {"queue_age": 1}
    assert b.drain_once() == 2               # the admitted ones survive
    assert b.result(first, timeout_s=0) == "a"
    obs.flush()
    shed = [r for r in _read(sink) if r["ev"] == "serve.shed"]
    assert len(shed) == 1
    assert shed[0]["kind"] == "count"
    assert (shed[0]["batcher"], shed[0]["reason"], shed[0]["req_id"]) \
        == ("aged", "queue_age", "r-1")
    b.close()


def test_batcher_sheds_on_windowed_p99(slo_env):
    clock = FakeClock()
    slo.configure(50.0, clock=clock)
    slo.record("ok", latency_s=0.2)          # published p99 = 200 ms
    assert slo.current_p99_ms() == pytest.approx(200.0)
    b = batcher_mod.Batcher(lambda p: list(p), shed_p99_ms=100.0,
                            clock=clock, start=False, name="p99")
    with pytest.raises(batcher_mod.Shed) as ei:
        b.submit("x")                        # even an empty queue sheds
    assert ei.value.reason == "slo_p99"
    assert b.shed_counts() == {"slo_p99": 1}
    slo.configure(None)                      # tracker off → p99 unknown
    b.submit("y")                            # → admission resumes
    assert b.drain_once() == 1
    b.close()


def test_batcher_shed_knobs_read_env_once(monkeypatch):
    monkeypatch.setenv("HPNN_SHED_AGE_MS", "7.5")
    monkeypatch.setenv("HPNN_SHED_P99_MS", "120")
    b = batcher_mod.Batcher(lambda p: list(p), start=False)
    assert (b.shed_age_ms, b.shed_p99_ms) == (7.5, 120.0)
    b2 = batcher_mod.Batcher(lambda p: list(p), shed_age_ms=0,
                             shed_p99_ms=0, start=False)
    assert (b2.shed_age_ms, b2.shed_p99_ms) == (0.0, 0.0)  # explicit off
    b.close()
    b2.close()


def test_queue_full_lands_in_the_shed_census():
    clock = FakeClock()
    b = batcher_mod.Batcher(lambda p: list(p), max_depth=1,
                            clock=clock, start=False)
    b.submit("a")
    with pytest.raises(batcher_mod.QueueFull) as ei:
        b.submit("b")
    assert not isinstance(ei.value, batcher_mod.Shed)
    assert b.shed_counts() == {"queue_full": 1}
    b.close()


# --------------------------------------------------------- expiry race
def test_deadline_expiry_races_a_concurrent_submit(tmp_path,
                                                   monkeypatch):
    """A request expiring in-queue while another submit lands
    mid-dispatch (dispatch runs outside the lock, so a concurrent
    submit is legal there): the expired ticket fails with
    DeadlineExceeded and a closed ``serve.queue`` span, the live one
    is served, and the raced submit is admitted and served next —
    fake clock, no sleeps."""
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    monkeypatch.setenv("HPNN_SPANS", "1")
    obs._reset_for_tests()
    clock = FakeClock()
    holder, raced = [], []

    def dispatch(payloads):
        if not raced:                        # submit DURING dispatch
            raced.append(holder[0].submit("raced", timeout_s=5.0))
        return list(payloads)

    b = batcher_mod.Batcher(dispatch, clock=clock, start=False,
                            name="race")
    holder.append(b)
    r1 = b.submit("doomed", timeout_s=1.0, req_id="race-1")
    r2 = b.submit("alive", timeout_s=10.0, req_id="race-2")
    clock.advance(2.0)                       # r1 past its deadline
    assert b.drain_once() == 1               # r2 only; r1 never dispatched
    with pytest.raises(batcher_mod.DeadlineExceeded):
        b.result(r1, timeout_s=0)
    assert b.result(r2, timeout_s=0) == "alive"
    assert b.expired_total() == 1
    assert b.drain_once() == 1               # the raced request survives
    assert b.result(raced[0], timeout_s=0) == "raced"
    obs.flush()
    recs = _read(sink)
    qspans = [r for r in recs
              if r["ev"] == "span.end" and r["name"] == "serve.queue"]
    assert len(qspans) == 3
    by_req = {r.get("req_id"): r for r in qspans}
    assert by_req["race-1"]["failed"] == "DeadlineExceeded"
    assert "failed" not in by_req["race-2"]
    assert any(r["ev"] == "serve.deadline_exceeded" for r in recs)
    b.close()


# ------------------------------------------------------- HTTP contract
def _post(port, body, timeout=10.0):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    try:
        conn.request("POST", "/v1/infer", body=json.dumps(body).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return (resp.status, dict(resp.getheaders()),
                json.loads(resp.read() or b"{}"))
    finally:
        conn.close()


def test_http_retry_contract_and_request_ids():
    """429-shed carries Retry-After + reason, 504 carries Retry-After,
    and every response echoes X-Request-Id (client-sent ids honored,
    else edge-minted).  The session runs drainless on a fake clock;
    the test steps the batcher by hand."""
    clock = FakeClock()
    session = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0,
                            shed_age_ms=10.0, clock=clock, start=False)
    session.register_kernel("k", _kernel())
    b = session.batcher_for("k")
    server = make_server(session, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    results = {}

    def post_bg(key, body):
        results[key] = _post(port, body)

    try:
        # 200: a client-sent req_id round-trips header and body
        t = threading.Thread(target=post_bg, args=(
            "ok", {"kernel": "k", "inputs": [0.1] * 8,
                   "req_id": "abc-1"}))
        t.start()
        deadline = time.monotonic() + 5.0
        while b.depth() < 1 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert b.drain_once() == 1
        t.join(timeout=5.0)
        code, headers, body = results["ok"]
        assert code == 200
        assert headers.get("X-Request-Id") == "abc-1"
        assert body["req_id"] == "abc-1"

        # 429 shed: park one request, age it past the threshold
        t = threading.Thread(target=post_bg, args=(
            "parked", {"kernel": "k", "inputs": [0.1] * 8,
                       "timeout_s": 5.0}))
        t.start()
        deadline = time.monotonic() + 5.0
        while b.depth() < 1 and time.monotonic() < deadline:
            time.sleep(0.002)
        clock.advance(0.02)                  # 20 ms ≥ shed_age_ms
        code, headers, body = _post(
            port, {"kernel": "k", "inputs": [0.1] * 8,
                   "req_id": "cafe"})
        assert code == 429
        assert int(headers["Retry-After"]) >= 1
        assert headers.get("X-Request-Id") == "cafe"
        assert body["reason"] == "queue_age" and body["retriable"]
        assert session.health()["batchers"]["k"]["shed"] \
            == {"queue_age": 1}

        # 504: expire the parked request in-queue
        clock.advance(10.0)
        assert b.drain_once() == 0           # all-expired batch
        t.join(timeout=5.0)
        code, headers, body = results["parked"]
        assert code == 504
        assert headers["Retry-After"] == "1"
        assert headers.get("X-Request-Id")   # edge-minted, non-empty
        assert body["retriable"]
        assert session.health()["batchers"]["k"]["expired"] == 1
    finally:
        server.shutdown()
        server.server_close()
        session.close()


# ------------------------------------------------- loadgen helpers/CLI
def test_bench_serve_shares_loadgen_percentiles():
    loadgen = _import_tool("loadgen")
    bench_serve = _import_tool("bench_serve")
    assert bench_serve.percentile_ms is loadgen.percentile_ms
    assert bench_serve.latency_summary is loadgen.latency_summary


def test_loadgen_summaries_and_arrivals(tmp_path):
    loadgen = _import_tool("loadgen")
    assert loadgen.percentile_ms([0.001, 0.002, 0.003], 50) == 2.0
    recs = ([{"status": "ok", "latency_ms": 10.0}] * 8
            + [{"status": "shed", "latency_ms": 1.0}] * 2)
    s = loadgen.summarize(recs, 2.0, offered_rps=10.0)
    assert (s["requests"], s["ok"], s["shed"]) == (10, 8, 2)
    assert s["goodput_rps"] == 4.0
    assert s["goodput_vs_offered"] == pytest.approx(0.4)
    assert s["shed_rate"] == pytest.approx(0.2)
    assert s["latency_ms"]["p50"] == 10.0    # served latencies only
    empty = loadgen.summarize([], 1.0)
    assert empty["latency_ms"]["p99"] is None
    # arrivals: rates hit the long-run average, stay sorted + in-range
    rng = np.random.RandomState(0)
    arr = loadgen.poisson_arrivals(200.0, 10.0, rng)
    assert arr == sorted(arr) and 0 < arr[-1] < 10.0
    assert len(arr) == pytest.approx(2000, rel=0.15)
    brr = loadgen.burst_arrivals(200.0, 10.0, rng)
    assert len(brr) == pytest.approx(2000, rel=0.15)
    with pytest.raises(ValueError):
        loadgen.make_arrivals("nope", 1.0, 1.0, rng)
    out = tmp_path / "r.jsonl"
    loadgen.write_jsonl(str(out), recs, s)
    rows = _read(out)
    assert len(rows) == 11 and rows[-1]["summary"]["ok"] == 8


# ----------------------------------------------------- acceptance (e2e)
def test_loadgen_against_live_server_slo_observability(
        tmp_path, monkeypatch, slo_env, capsys):
    """The ISSUE acceptance test: loadgen drives an in-process HTTP
    server (CPU, 8-5-2 kernel) with the SLO tracker and queue-age
    shedding armed.  Requests are both served and shed; the ``slo.*``
    gauges, ``serve.shed`` events and ``/healthz`` shed counters all
    appear; the sink lints clean under ``--slo``; and a served
    request's X-Request-Id reconstructs its span tree via
    ``obs_report --spans --req``."""
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    monkeypatch.setenv("HPNN_SPANS", "1")
    obs._reset_for_tests()
    slo.configure(50.0, window_s=60.0)
    loadgen = _import_tool("loadgen")
    session = serve.Session(max_batch=16, n_buckets=3, max_wait_ms=1.0,
                            max_depth=64, shed_age_ms=0.05)
    session.register_kernel("k", _kernel())
    server = make_server(session, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    run_path = tmp_path / "run.jsonl"
    try:
        summary = loadgen.run_closed_loop(
            f"http://127.0.0.1:{port}", n_clients=4, duration_s=1.0,
            kernels=("k",), rows_choices=(1, 2), n_in=8, timeout_s=2.0,
            max_retries=0, out_path=str(run_path))
        assert summary["ok"] > 0, summary
        assert summary["shed"] > 0, summary
        assert summary["latency_ms"]["p99"] is not None

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", "/healthz")
            health = json.loads(conn.getresponse().read())
            conn.request("GET", "/metrics")
            metrics = conn.getresponse().read()
        finally:
            conn.close()
        assert health["batchers"]["k"]["shed"].get("queue_age", 0) > 0
        assert health["slo"]["mode"] == "on"
        assert health["slo"]["requests"] > 0
        assert b"hpnn_slo_attainment" in metrics
        assert b"hpnn_serve_shed" in metrics
    finally:
        server.shutdown()
        server.server_close()
        session.close()

    obs.flush()
    recs = _read(sink)
    assert any(r["ev"] == "slo.p99_ms" for r in recs)
    assert any(r["ev"] == "serve.shed" for r in recs)
    # every outcome row carries the server-minted id
    rows = [r for r in _read(run_path) if "summary" not in r]
    ok_ids = [r["req_id"] for r in rows if r["status"] == "ok"]
    assert ok_ids and all(ok_ids)
    # the sink lints clean under the --slo schema lint
    cat = _load_tool("check_obs_catalog")
    assert cat.lint_slo(str(sink)) == []
    assert cat.main(["--slo", str(sink)]) == 0
    # request-id reconstruction: the span report narrows to one request
    rep = _load_tool("obs_report")
    assert rep.main([str(sink), "--spans", "--req", ok_ids[0]]) == 0
    out = capsys.readouterr().out
    assert f"req_id: {ok_ids[0]}" in out
    assert "serve.request" in out


# ------------------------------------------------------- --slo lint
def _slo_gauge(name, value, **over):
    rec = {"ts": 0.0, "ev": name, "kind": "gauge", "value": value}
    rec.update(over)
    return rec


def _shed_rec(**over):
    rec = {"ts": 0.0, "ev": "serve.shed", "kind": "count", "n": 1,
           "total": 1, "batcher": "k", "reason": "queue_age"}
    rec.update(over)
    return rec


def _write_sink(path, recs):
    with open(path, "w") as fp:
        for rec in recs:
            fp.write(json.dumps(rec) + "\n")


def test_slo_lint_accepts_a_well_formed_sink(tmp_path):
    cat = _load_tool("check_obs_catalog")
    path = tmp_path / "m.jsonl"
    _write_sink(path, [
        _slo_gauge("slo.p50_ms", 1.5),
        _slo_gauge("slo.p99_ms", 12.0),
        _slo_gauge("slo.attainment", 0.995),
        _slo_gauge("slo.burn_rate", 0.5),
        _slo_gauge("slo.window_requests", 40),
        _shed_rec(),
        _shed_rec(reason="slo_p99", req_id="a-1"),
        {"ts": 0.0, "ev": "span.end", "kind": "event", "span": 1,
         "parent": None, "name": "serve.queue", "t0": 0.0, "dt": 0.1,
         "req_id": "a-1"},
        {"ts": 0.0, "ev": "round.start", "kind": "event"},  # bystander
    ])
    assert cat.lint_slo(str(path)) == []
    assert cat.main(["--slo", str(path)]) == 0


def test_slo_lint_catches_every_schema_break(tmp_path):
    """Each clause bites: out-of-range attainment, negative latency,
    wrong kinds, empty reason/req_id, an unarmed sink, and an
    unreadable path."""
    cat = _load_tool("check_obs_catalog")
    path = tmp_path / "m.jsonl"

    _write_sink(path, [_slo_gauge("slo.attainment", 1.5), _shed_rec()])
    assert any("outside [0, 1]" in f for f in cat.lint_slo(str(path)))

    _write_sink(path, [_slo_gauge("slo.p99_ms", -2.0), _shed_rec()])
    assert any("negative" in f for f in cat.lint_slo(str(path)))

    _write_sink(path, [_slo_gauge("slo.burn_rate", 1.0, kind="count"),
                       _shed_rec()])
    assert any("'gauge'" in f for f in cat.lint_slo(str(path)))

    _write_sink(path, [_slo_gauge("slo.p50_ms", None), _shed_rec()])
    assert any("finite" in f for f in cat.lint_slo(str(path)))

    _write_sink(path, [_shed_rec(reason="")])
    assert any("reason" in f for f in cat.lint_slo(str(path)))

    _write_sink(path, [_shed_rec(kind="gauge")])
    assert any("'count'" in f for f in cat.lint_slo(str(path)))

    _write_sink(path, [_shed_rec(req_id="")])
    assert any("req_id" in f for f in cat.lint_slo(str(path)))

    _write_sink(path, [{"ts": 0.0, "ev": "span.end", "kind": "event",
                        "name": "serve.request", "req_id": ""},
                       _shed_rec()])
    assert any("span req_id" in f for f in cat.lint_slo(str(path)))

    _write_sink(path, [{"ts": 0.0, "ev": "round.start",
                        "kind": "event"}])
    assert any("no slo.*" in f for f in cat.lint_slo(str(path)))

    assert cat.lint_slo(str(tmp_path / "missing.jsonl"))
    assert cat.main(["--slo"]) == 2
