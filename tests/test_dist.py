"""Distributed helpers on the faked 8-device single-host platform,
plus a REAL 2-OS-process cluster test (the `mpirun -np 2` equivalent,
ref MPI init: /root/reference/src/libhpnn.c:182-200)."""

import os
import socket
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np

from hpnn_tpu.parallel import dist, dp, tp


def test_hybrid_mesh_single_slice():
    m = dist.hybrid_mesh(n_model=2)
    assert m.shape == {"data": 4, "model": 2}
    assert m.devices.size == 8


def test_hybrid_mesh_runs_step():
    from hpnn_tpu.models import kernel as kernel_mod

    m = dist.hybrid_mesh(n_model=2)
    k, _ = kernel_mod.generate(5, 6, [8], 4)
    weights = tuple(jnp.asarray(np.asarray(w)) for w in k.weights)
    step = dp.make_gspmd_train_step(m, weights, model="ann", donate=False)
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.uniform(-1, 1, (8, 6)))
    T = jnp.asarray(np.where(rng.randint(0, 4, (8, 1)) == np.arange(4), 1.0, -1.0))
    w_sh = dp.place_kernel(weights, m)
    Xs, Ts = dp.shard_batch(X, T, m)
    new_w, _, loss = step(w_sh, (), Xs, Ts)
    assert np.isfinite(float(loss))
    assert new_w[0].shape == weights[0].shape


def test_process_summary():
    s = dist.process_summary()
    assert "process 0/1" in s
    assert "global_devices=8" in s


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_cluster(tmp_path):
    """Spawn TWO OS processes (coordinator + worker) that join one JAX
    cluster through runtime.init_dist, build dist.hybrid_mesh over the
    global 4-device mesh, run one GSPMD DP step, and print through the
    rank-0-only logger — `mpirun -np 2` end to end, CPU-backed."""
    worker = os.path.join(os.path.dirname(__file__), "dist_worker.py")
    port = _free_port()
    # clean CPU interpreters: strip the accelerator plugin's env
    # (PALLAS_AXON_* + its sitecustomize on PYTHONPATH) so the workers
    # don't grab the single real TPU or pre-register a backend
    env_base = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_", "PALLAS_", "AXON_", "TPU_"))
        and k != "PYTHONPATH"
    }
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    # repo root only (cwd is tmp_path; the plugin's sitecustomize dir
    # stripped above must NOT come back)
    env_base["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env_base["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    env_base["JAX_NUM_PROCESSES"] = "2"
    procs = []
    for rank in (0, 1):
        env = dict(env_base, JAX_PROCESS_ID=str(rank))
        procs.append(
            subprocess.Popen(
                [sys.executable, worker],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=str(tmp_path),
            )
        )
    outs = []
    try:
        for rank, p in enumerate(procs):
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"rank {rank} failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        # never leak a worker blocked on a dead coordinator
        for q in procs:
            if q.poll() is None:
                q.kill()
    # rank-0-only logging (_OUT, ref: common.h:81-91): the token line
    # appears exactly once, on the coordinator
    assert "NN: DIST STEP loss= " in outs[0]
    assert "tasks=2" in outs[0]
    assert "DIST STEP" not in outs[1]
