"""Distributed helpers on the faked 8-device single-host platform."""

import jax
import jax.numpy as jnp
import numpy as np

from hpnn_tpu.parallel import dist, dp, tp


def test_hybrid_mesh_single_slice():
    m = dist.hybrid_mesh(n_model=2)
    assert m.shape == {"data": 4, "model": 2}
    assert m.devices.size == 8


def test_hybrid_mesh_runs_step():
    from hpnn_tpu.models import kernel as kernel_mod

    m = dist.hybrid_mesh(n_model=2)
    k, _ = kernel_mod.generate(5, 6, [8], 4)
    weights = tuple(jnp.asarray(np.asarray(w)) for w in k.weights)
    step = dp.make_gspmd_train_step(m, weights, model="ann", donate=False)
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.uniform(-1, 1, (8, 6)))
    T = jnp.asarray(np.where(rng.randint(0, 4, (8, 1)) == np.arange(4), 1.0, -1.0))
    w_sh = dp.place_kernel(weights, m)
    Xs, Ts = dp.shard_batch(X, T, m)
    new_w, _, loss = step(w_sh, (), Xs, Ts)
    assert np.isfinite(float(loss))
    assert new_w[0].shape == weights[0].shape


def test_process_summary():
    s = dist.process_summary()
    assert "process 0/1" in s
    assert "global_devices=8" in s
