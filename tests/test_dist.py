"""Distributed helpers on the faked 8-device single-host platform,
plus a REAL 2-OS-process cluster test (the `mpirun -np 2` equivalent,
ref MPI init: /root/reference/src/libhpnn.c:182-200)."""

import os
import socket
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpnn_tpu.parallel import dist, dp, tp

# The two-process cluster tests need CPU cross-process collectives,
# which this jaxlib line does not ship: distributed.initialize comes
# up but the worker's first cross-process collective fails, so the
# child exits non-zero.  Version-guarded skip (not xfail — nothing to
# fix in this repo); re-enables automatically once jaxlib >= 0.5
# lands in the image.
import jaxlib.version

_JAXLIB = tuple(int(p) for p in jaxlib.version.__version__.split(".")[:2])
two_process = pytest.mark.skipif(
    _JAXLIB < (0, 5),
    reason=(f"jaxlib {jaxlib.version.__version__} lacks multi-process "
            "CPU collectives; two-process cluster tests need "
            "jaxlib >= 0.5"),
)


def test_hybrid_mesh_single_slice():
    m = dist.hybrid_mesh(n_model=2)
    assert m.shape == {"data": 4, "model": 2}
    assert m.devices.size == 8


def test_hybrid_mesh_runs_step():
    from hpnn_tpu.models import kernel as kernel_mod

    m = dist.hybrid_mesh(n_model=2)
    k, _ = kernel_mod.generate(5, 6, [8], 4)
    weights = tuple(jnp.asarray(np.asarray(w)) for w in k.weights)
    step = dp.make_gspmd_train_step(m, weights, model="ann", donate=False)
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.uniform(-1, 1, (8, 6)))
    T = jnp.asarray(np.where(rng.randint(0, 4, (8, 1)) == np.arange(4), 1.0, -1.0))
    w_sh = dp.place_kernel(weights, m)
    Xs, Ts = dp.shard_batch(X, T, m)
    new_w, _, loss = step(w_sh, (), Xs, Ts)
    assert np.isfinite(float(loss))
    assert new_w[0].shape == weights[0].shape


class _StubDev:
    """Minimal device stand-in carrying ``slice_index`` — enough for
    dist.hybrid_mesh's multi-slice branch (it only reads the attribute)
    and for mesh_utils' physical-coords layout."""

    def __init__(self, i, n_per_slice):
        self.id = i
        self.slice_index = i // n_per_slice
        self.process_index = self.slice_index
        self.platform = "tpu"
        self.device_kind = "stub"
        j = i % n_per_slice
        self.coords = (j % 2, j // 2, 0)
        self.core_on_chip = 0

    def __repr__(self):
        return f"Stub(id={self.id},slice={self.slice_index})"


def test_hybrid_mesh_multi_slice():
    """2 slices x 4 devices: the data axis must ride DCN (cross-slice)
    and the model axis must stay inside a slice (ICI) — the bandwidth
    hierarchy hybrid_mesh exists to respect."""
    devs = [_StubDev(i, 4) for i in range(8)]
    m = dist.hybrid_mesh(n_model=2, devices=devs)
    assert m.shape == {"data": 4, "model": 2}
    grid = np.asarray(m.devices)
    slices = np.vectorize(lambda d: d.slice_index)(grid)
    # model axis (columns): same slice everywhere
    assert (slices[:, 0] == slices[:, 1]).all()
    # data axis (rows): spans both slices
    assert set(slices[:, 0]) == {0, 1}
    # every stub appears exactly once
    assert sorted(d.id for d in grid.ravel()) == list(range(8))


def test_hybrid_mesh_multi_slice_non_divisible():
    """A model axis that cannot fit inside a slice must be refused
    (the model axis never spans slices)."""
    devs = [_StubDev(i, 3) for i in range(6)]  # 2 slices x 3 devices
    with pytest.raises(ValueError, match="divisible by the slice"):
        dist.hybrid_mesh(n_model=2, devices=devs)


def test_process_summary():
    s = dist.process_summary()
    assert "process 0/1" in s
    assert "global_devices=8" in s


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@two_process
def test_two_process_cluster(tmp_path):
    """Spawn TWO OS processes (coordinator + worker) that join one JAX
    cluster through runtime.init_dist, build dist.hybrid_mesh over the
    global 4-device mesh, run one GSPMD DP step, and print through the
    rank-0-only logger — `mpirun -np 2` end to end, CPU-backed.

    The cluster runs with ``HPNN_METRICS`` pointed at a ``{rank}``
    path: each process must expand its own sink file, the two streams
    must never interleave, and ``tools/obs_report.py --merge`` must
    reconstruct one cross-rank timeline from them."""
    worker = os.path.join(os.path.dirname(__file__), "dist_worker.py")
    port = _free_port()
    # clean CPU interpreters: strip the accelerator plugin's env
    # (PALLAS_AXON_* + its sitecustomize on PYTHONPATH) so the workers
    # don't grab the single real TPU or pre-register a backend
    env_base = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_", "PALLAS_", "AXON_", "TPU_"))
        and k != "PYTHONPATH"
    }
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    # repo root only (cwd is tmp_path; the plugin's sitecustomize dir
    # stripped above must NOT come back)
    env_base["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env_base["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    env_base["JAX_NUM_PROCESSES"] = "2"
    # per-rank obs sinks: the literal {rank} expands inside each worker
    env_base["HPNN_METRICS"] = str(tmp_path / "run.{rank}.jsonl")
    procs = []
    for rank in (0, 1):
        env = dict(env_base, JAX_PROCESS_ID=str(rank))
        procs.append(
            subprocess.Popen(
                [sys.executable, worker],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=str(tmp_path),
            )
        )
    outs = []
    try:
        for rank, p in enumerate(procs):
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"rank {rank} failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        # never leak a worker blocked on a dead coordinator
        for q in procs:
            if q.poll() is None:
                q.kill()
    # rank-0-only logging (_OUT, ref: common.h:81-91): the token line
    # appears exactly once, on the coordinator
    assert "NN: DIST STEP loss= " in outs[0]
    assert "tasks=2" in outs[0]
    assert "DIST STEP" not in outs[1]

    # --- {rank} sink expansion: one file per process, no interleaving
    import json

    assert not (tmp_path / "run.{rank}.jsonl").exists()
    per_rank = []
    for rank in (0, 1):
        sink = tmp_path / f"run.{rank}.jsonl"
        assert sink.exists(), f"rank {rank} sink missing"
        recs = [json.loads(ln)
                for ln in sink.read_text().splitlines() if ln.strip()]
        assert recs, f"rank {rank} sink empty"
        opens = [r for r in recs if r.get("ev") == "obs.open"]
        assert opens and opens[0]["rank"] == rank
        # every rank-tagged record in this file carries THIS rank —
        # a foreign tag would mean the streams interleaved
        for r in recs:
            if "rank" in r:
                assert r["rank"] == rank, r
        names = {r.get("ev") for r in recs}
        # the host-collective comms timeline (dist.resolve_time_seed)
        assert "coll.seed_broadcast" in names
        assert {"round.start", "round.end", "obs.summary"} <= names
        per_rank.append(recs)

    # --- cross-rank reconstruction via tools/obs_report.py --merge
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "obs_report.py"))
    rpt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rpt)
    merged = rpt.merge_events(
        [str(tmp_path / f"run.{r}.jsonl") for r in (0, 1)])
    assert len(merged) == len(per_rank[0]) + len(per_rank[1])
    assert all("rank" in r for r in merged)
    assert {r["rank"] for r in merged} == {0, 1}
    # the merge must preserve each rank's own emission order exactly
    for rank in (0, 1):
        evs = [r["ev"] for r in merged if r["rank"] == rank]
        assert evs == [r.get("ev") for r in per_rank[rank]]


# --------------------------------------------------------------------------
# The flagship multi-process mode: the UNMODIFIED CLIs under
# JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES — the reference's
# `mpirun -np 2 train_nn file.conf` (every rank enters main, loads the
# conf, trains; rank 0 alone writes files and prints,
# ref: /root/reference/src/libhpnn.c:182-200, src/ann.c:557-615).


def _write_sample(path, x, t):
    with open(path, "w") as fp:
        fp.write(f"[input] {len(x)}\n")
        fp.write(" ".join("%7.5f" % v for v in x) + "\n")
        fp.write(f"[output] {len(t)}\n")
        fp.write(" ".join("%.1f" % v for v in t) + "\n")


def _make_workdir(root, name):
    """A self-contained conf + 20-sample two-class dir (same content
    every call, so separate workdirs are comparable)."""
    work = root / name
    samples = work / "samples"
    samples.mkdir(parents=True)
    rng = np.random.RandomState(42)
    centers = np.array([[1.0] * 4 + [-1.0] * 4, [-1.0] * 4 + [1.0] * 4])
    for i in range(20):
        c = i % 2
        x = centers[c] + 0.1 * rng.standard_normal(8)
        t = np.full(2, -1.0)
        t[c] = 1.0
        _write_sample(samples / f"s{i:05d}.txt", x, t)
    (work / "nn.conf").write_text(
        "[name] MP\n[type] ANN\n[init] generate\n[seed] 1234\n"
        "[input] 8\n[hidden] 6\n[output] 2\n[train] BP\n"
        "[sample_dir] ./samples\n[test_dir] ./samples\n"
    )
    return work


def _clean_env(n_local_devices):
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_", "PALLAS_", "AXON_", "TPU_"))
        and k != "PYTHONPATH"
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_local_devices}"
    )
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    return env


def _tokens(stdout: str) -> str:
    """The framework's stdout protocol only — the distributed backend's
    own banners (e.g. `[Gloo] Rank 0 is connected ...`) are not part of
    the grep-able token stream."""
    return "".join(
        ln for ln in stdout.splitlines(keepends=True)
        if not ln.startswith("[Gloo]")
    )


def _run_cli(module, args, cwd, env):
    p = subprocess.run(
        [sys.executable, "-m", module] + args,
        env=env,
        cwd=str(cwd),
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert p.returncode == 0, f"{module} failed:\n{p.stdout}\n{p.stderr}"
    return p.stdout


def _run_cli_cluster(module, args, cwd, nproc=2):
    """Spawn `nproc` OS processes all running the same CLI invocation
    (each with ONE local CPU device, `nproc` global)."""
    port = _free_port()
    procs = []
    for rank in range(nproc):
        env = _clean_env(1)
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = str(nproc)
        env["JAX_PROCESS_ID"] = str(rank)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", module] + args,
                env=env,
                cwd=str(cwd),
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for rank, p in enumerate(procs):
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"rank {rank} failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
    return outs


@two_process
def test_two_process_train_nn_cli(tmp_path):
    """`train_nn --batch` runs UNMODIFIED as a 2-process cluster over a
    real sample dir and produces (on rank 0 only) the same token stream
    and byte-identical kernel.tmp/kernel.opt as a single-process run
    over the same 2-device global mesh."""
    single = _make_workdir(tmp_path, "single")
    multi = _make_workdir(tmp_path, "multi")
    args = ["-v", "-v", "--batch", "4", "--epochs", "5", "--lr", "0.1",
            "nn.conf"]

    out_single = _run_cli("hpnn_tpu.cli.train_nn", args, single, _clean_env(2))
    outs = _run_cli_cluster("hpnn_tpu.cli.train_nn", args, multi)

    # same global mesh (2 devices, data axis) → same SPMD program →
    # identical epoch tokens and identical %17.15f weight dumps
    assert "NN: BATCH EPOCH" in out_single
    assert _tokens(outs[0]) == _tokens(out_single)
    # rank-0-only: the non-coordinator prints no tokens
    assert "BATCH EPOCH" not in outs[1]
    # rank 0 alone writes the kernel files (ref rank-0 ann_dump)
    assert (multi / "kernel.opt").read_text() == (
        single / "kernel.opt").read_text()
    assert (multi / "kernel.tmp").read_text() == (
        single / "kernel.tmp").read_text()

    # eval: run_nn --batch under the same 2-process cluster
    for work in (single, multi):
        (work / "cont.conf").write_text(
            (work / "nn.conf").read_text().replace(
                "[init] generate", "[init] kernel.opt")
        )
    ev_args = ["-v", "-v", "--batch", "cont.conf"]
    ev_single = _run_cli("hpnn_tpu.cli.run_nn", ev_args, single, _clean_env(2))
    ev_outs = _run_cli_cluster("hpnn_tpu.cli.run_nn", ev_args, multi)
    assert "TESTING FILE" in ev_single and "[PASS]" in ev_single
    assert _tokens(ev_outs[0]) == _tokens(ev_single)
    assert "TESTING FILE" not in ev_outs[1]


@two_process
def test_two_process_cli_model_sharded(tmp_path):
    """`--mesh 1x2` under 2 processes: layer rows sharded ACROSS
    processes — every weight fetch must cross-process all-gather
    (dp.host_fetch) and the rank-0 kernel.opt must still be
    byte-identical to a single-process run over the same mesh."""
    single = _make_workdir(tmp_path, "single")
    multi = _make_workdir(tmp_path, "multi")
    args = ["-v", "-v", "--batch", "4", "--epochs", "3", "--lr", "0.1",
            "--mesh", "1x2", "nn.conf"]
    out_single = _run_cli("hpnn_tpu.cli.train_nn", args, single, _clean_env(2))
    outs = _run_cli_cluster("hpnn_tpu.cli.train_nn", args, multi)
    assert "NN: BATCH EPOCH" in out_single
    assert _tokens(outs[0]) == _tokens(out_single)
    assert "BATCH EPOCH" not in outs[1]  # rank-0-only tokens
    for fname in ("kernel.opt", "kernel.tmp"):
        assert (multi / fname).read_text() == (single / fname).read_text()


@two_process
def test_two_process_cli_per_sample_tp(tmp_path):
    """The reference's FLAGSHIP mode distributed: per-sample
    convergence training with layer rows split across ranks
    (`mpirun -np X train_nn`, ref: /root/reference/src/ann.c:912-936)
    — `train_nn --mesh 1x2` (no --batch) as a 2-process cluster, each
    process holding half of every layer's rows, must reproduce the
    single-process 2-device run's token stream and kernel.opt byte for
    byte (fused TP rounds: the shard_map scan runs over the
    cross-process mesh)."""
    single = _make_workdir(tmp_path, "single")
    multi = _make_workdir(tmp_path, "multi")
    args = ["-v", "-v", "--mesh", "1x2", "nn.conf"]
    out_single = _run_cli("hpnn_tpu.cli.train_nn", args, single, _clean_env(2))
    outs = _run_cli_cluster("hpnn_tpu.cli.train_nn", args, multi)
    assert "N_ITER=" in out_single and "TRAINING FILE" in out_single
    assert _tokens(outs[0]) == _tokens(out_single)
    assert "TRAINING FILE" not in outs[1]  # rank-0-only tokens
    assert (multi / "kernel.opt").read_text() == (
        single / "kernel.opt").read_text()

    # sharded eval under the same cluster
    for work in (single, multi):
        (work / "cont.conf").write_text(
            (work / "nn.conf").read_text().replace(
                "[init] generate", "[init] kernel.opt")
        )
    ev_args = ["-v", "-v", "--mesh", "1x2", "cont.conf"]
    ev_single = _run_cli("hpnn_tpu.cli.run_nn", ev_args, single, _clean_env(2))
    ev_outs = _run_cli_cluster("hpnn_tpu.cli.run_nn", ev_args, multi)
    assert "[PASS]" in ev_single
    assert _tokens(ev_outs[0]) == _tokens(ev_single)
    assert "TESTING FILE" not in ev_outs[1]
