"""Fused Pallas trainer vs the lax while_loop trainer (interpret mode).

Both run in f32 so trajectories are bitwise-comparable; the oracle is
the reference's cross-backend consistency criterion (SURVEY.md §4.2)
applied to our two TPU execution paths.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.ops import pallas_train
from hpnn_tpu.train import loop


def _setup(seed, n_in, hiddens, n_out, snn=False, hot=2):
    k, _ = kernel_mod.generate(seed, n_in, hiddens, n_out)
    weights = tuple(jnp.asarray(np.asarray(w), dtype=jnp.float32) for w in k.weights)
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.uniform(-1, 1, n_in), dtype=jnp.float32)
    lo = 0.0 if snn else -1.0
    t = jnp.asarray(np.where(np.arange(n_out) == hot, 1.0, lo), dtype=jnp.float32)
    return weights, x, t


@pytest.mark.parametrize("model,momentum", [
    ("ann", False), ("ann", True), ("snn", False), ("snn", True),
])
def test_fused_matches_lax(model, momentum):
    weights, x, t = _setup(99, 12, [16, 8], 8, snn=(model == "snn"))
    dw = tuple(jnp.zeros_like(w) for w in weights) if momentum else ()
    kw = dict(model=model, momentum=momentum, min_iter=5, max_iter=60)

    ref = loop.train_sample_lax(weights, dw, x, t, 0.2, 1e-6, **kw)
    got = pallas_train.train_sample_fused(
        weights, dw, x, t, 0.2, 1e-6, interpret=True, **kw
    )

    assert int(got.n_iter) == int(ref.n_iter)
    assert bool(got.first_ok) == bool(ref.first_ok)
    assert bool(got.final_ok) == bool(ref.final_ok)
    np.testing.assert_allclose(float(got.ep0), float(ref.ep0), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(ref.out), atol=1e-6
    )
    for a, b in zip(got.weights, ref.weights):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    if momentum:
        for a, b in zip(got.dw, ref.dw):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_fused_deep_kernel():
    """Three hidden layers exercise the static depth unrolling."""
    weights, x, t = _setup(7, 10, [12, 8, 6], 4)
    ref = loop.train_sample_lax(
        weights, (), x, t, 0.2, 1e-6,
        model="ann", momentum=False, min_iter=3, max_iter=30,
    )
    got = pallas_train.train_sample_fused(
        weights, (), x, t, 0.2, 1e-6,
        model="ann", momentum=False, min_iter=3, max_iter=30, interpret=True,
    )
    assert int(got.n_iter) == int(ref.n_iter)
    for a, b in zip(got.weights, ref.weights):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.parametrize("model,momentum", [
    ("ann", False), ("ann", True), ("snn", False), ("snn", True),
])
def test_batch_step_matches_train_step_math(model, momentum):
    """Fused batched step == dp.train_step_math, interpret mode.

    SNN targets deliberately use the ±1 container convention here so
    the kernel's clamp is exercised against dp's."""
    from hpnn_tpu.parallel import dp

    weights, _, _ = _setup(42, 12, [16], 6)
    dw = tuple(jnp.zeros_like(w) for w in weights) if momentum else ()
    rng = np.random.RandomState(5)
    B = 16
    X = jnp.asarray(rng.uniform(-1, 1, (B, 12)), dtype=jnp.float32)
    T = np.full((B, 6), -1.0, dtype=np.float32)
    T[np.arange(B), rng.randint(0, 6, B)] = 1.0
    T = jnp.asarray(T)

    lr = 0.05
    rw, rdw, rloss = dp.train_step_math(
        weights, dw, X, T, model=model, momentum=momentum, lr=lr, alpha=0.2
    )
    gw, gdw, gloss = pallas_train.train_step_fused_batch(
        weights, dw, X, T, model=model, momentum=momentum, lr=lr, alpha=0.2,
        interpret=True,
    )
    np.testing.assert_allclose(float(gloss), float(rloss), rtol=1e-5)
    for a, b in zip(gw, rw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    if momentum:
        for a, b in zip(gdw, rdw):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_pallas_epoch_matches_gspmd_epoch():
    """Scan-per-epoch over the fused batch kernel == the XLA epoch."""
    from hpnn_tpu.parallel import dp, mesh as mesh_mod

    weights, _, _ = _setup(13, 10, [12], 4)
    rng = np.random.RandomState(3)
    n, B, steps = 64, 16, 4
    X = jnp.asarray(rng.uniform(-1, 1, (n, 10)), dtype=jnp.float32)
    T = np.full((n, 4), -1.0, dtype=np.float32)
    T[np.arange(n), rng.randint(0, 4, n)] = 1.0
    T = jnp.asarray(T)
    idx = jnp.asarray(rng.permutation(n)[: steps * B].reshape(steps, B))

    mesh = mesh_mod.make_mesh(n_data=1, n_model=1)
    ref_fn = dp.make_gspmd_epoch_fn(mesh, weights, model="ann",
                                    momentum=False, lr=0.05, gather=True,
                                    donate=False)
    rw, _, rlosses = ref_fn(weights, (), X, T, idx)

    pal_fn = pallas_train.make_pallas_epoch_fn(weights, momentum=False,
                                               lr=0.05, interpret=True)
    gw, _, glosses = pal_fn(weights, (), X, T, idx)
    np.testing.assert_allclose(
        np.asarray(glosses), np.asarray(rlosses), rtol=1e-5
    )
    for a, b in zip(gw, rw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.parametrize("model,momentum", [
    ("ann", False), ("ann", True), ("snn", False), ("snn", True),
])
def test_banked_step_matches_direct(model, momentum):
    """Banked fused step (HBM bank + scalar-prefetch block index) is
    BITWISE the direct fused step on every block — the bank data path
    must not change trajectories (train/batch.py's roofline lever)."""
    weights, _, _ = _setup(21, 16, [12], 5)
    dw = tuple(jnp.zeros_like(w) for w in weights) if momentum else ()
    rng = np.random.RandomState(7)
    B, S = 8, 4
    X = jnp.asarray(rng.uniform(-1, 1, (S * B, 16)), dtype=jnp.float32)
    T = np.full((S * B, 5), -1.0, dtype=np.float32)
    T[np.arange(S * B), rng.randint(0, 5, S * B)] = 1.0
    T = jnp.asarray(T)

    w1, m1 = weights, dw
    w2, m2 = weights, dw
    for k in range(S):
        w1, m1, l1 = pallas_train.train_step_fused_batch(
            w1, m1, X[k * B:(k + 1) * B], T[k * B:(k + 1) * B],
            model=model, momentum=momentum, lr=0.05, interpret=True,
        )
        w2, m2, l2 = pallas_train.train_step_fused_banked(
            w2, m2, X, T, jnp.int32(k), batch=B,
            model=model, momentum=momentum, lr=0.05, interpret=True,
        )
        assert float(l1) == float(l2)
    for a, b in zip(w1, w2):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(m1, m2):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("model,momentum", [
    ("ann", False), ("ann", True), ("snn", False), ("snn", True),
])
def test_epoch_fused_matches_epoch_lax(model, momentum):
    """Scan-over-kernel fused epoch (the r05 TPU round body) ==
    train_epoch_lax stats/weights in interpret mode, including the
    momentum raz (fresh dw0 per sample)."""
    from hpnn_tpu.train import loop

    weights, _, _ = _setup(3, 10, [8], 4)
    dw0 = tuple(jnp.zeros_like(w) for w in weights) if momentum else ()
    rng = np.random.RandomState(9)
    n = 5
    X = jnp.asarray(rng.uniform(-1, 1, (n, 10)), dtype=jnp.float32)
    T = np.full((n, 4), -1.0, dtype=np.float32)
    T[np.arange(n), rng.randint(0, 4, n)] = 1.0
    T = jnp.asarray(T)
    kw = dict(model=model, momentum=momentum, min_iter=3, max_iter=40)

    w_l, st_l = loop.train_epoch_lax(
        weights, dw0, X, T, 0.2, 1e-6, **kw)
    w_p, st_p = pallas_train.train_epoch_fused(
        weights, dw0, X, T, 0.2, 1e-6, interpret=True, **kw)
    assert [int(v) for v in st_p[1]] == [int(v) for v in st_l[1]]  # n_iter
    for a, b in zip(st_p, st_l):
        np.testing.assert_allclose(np.asarray(a, dtype=np.float64),
                                   np.asarray(b, dtype=np.float64),
                                   rtol=1e-5, atol=1e-7)
    for a, b in zip(w_p, w_l):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_train_epoch_dispatch_gates(monkeypatch):
    """loop.train_epoch picks the kernel body only on TPU/f32 and
    HPNN_PALLAS!=0; on this CPU suite it must route to the lax body."""
    from hpnn_tpu.train import loop

    weights, _, _ = _setup(3, 6, [5], 3)
    assert not loop._pallas_epoch_default(weights)  # CPU platform
    called = {}
    real = loop.train_epoch_lax

    def spy(*a, **kw):
        called["lax"] = True
        return real(*a, **kw)

    monkeypatch.setattr(loop, "train_epoch_lax", spy)
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.uniform(-1, 1, (2, 6)), dtype=jnp.float32)
    T = jnp.asarray(np.eye(3, dtype=np.float32)[[0, 1]] * 2 - 1)
    loop.train_epoch(weights, (), X, T, 0.2, 1e-6,
                     model="ann", momentum=False, min_iter=1, max_iter=5)
    assert called.get("lax")


@pytest.mark.parametrize("model,momentum", [
    ("ann", False), ("ann", True), ("snn", False), ("snn", True),
])
def test_grid_epoch_matches_banked_steps(model, momentum):
    """One grid-epoch Mosaic launch == S successive banked steps in a
    shuffled block order (the r05 production batch dispatch), bitwise
    in interpret mode."""
    weights, _, _ = _setup(31, 12, [10], 4)
    dw = tuple(jnp.zeros_like(w) for w in weights) if momentum else ()
    rng = np.random.RandomState(2)
    B, S = 8, 5
    X = jnp.asarray(rng.uniform(-1, 1, (S * B, 12)), dtype=jnp.float32)
    T = np.full((S * B, 4), -1.0, dtype=np.float32)
    T[np.arange(S * B), rng.randint(0, 4, S * B)] = 1.0
    T = jnp.asarray(T)
    order = jnp.asarray(rng.permutation(S).astype(np.int32))

    w1, m1 = weights, dw
    losses_ref = []
    for k in np.asarray(order):
        w1, m1, l = pallas_train.train_step_fused_banked(
            w1, m1, X, T, jnp.int32(k), batch=B,
            model=model, momentum=momentum, lr=0.05, interpret=True,
        )
        losses_ref.append(float(l))
    w2, m2, losses = pallas_train.train_epoch_grid_banked(
        weights, dw, X, T, order, batch=B,
        model=model, momentum=momentum, lr=0.05, interpret=True,
    )
    assert [float(v) for v in losses] == losses_ref
    for a, b in zip(w2, w1):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(m2, m1):
        assert np.array_equal(np.asarray(a), np.asarray(b))
