"""Fused Pallas trainer vs the lax while_loop trainer (interpret mode).

Both run in f32 so trajectories are bitwise-comparable; the oracle is
the reference's cross-backend consistency criterion (SURVEY.md §4.2)
applied to our two TPU execution paths.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.ops import pallas_train
from hpnn_tpu.train import loop


def _setup(seed, n_in, hiddens, n_out, snn=False, hot=2):
    k, _ = kernel_mod.generate(seed, n_in, hiddens, n_out)
    weights = tuple(jnp.asarray(np.asarray(w), dtype=jnp.float32) for w in k.weights)
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.uniform(-1, 1, n_in), dtype=jnp.float32)
    lo = 0.0 if snn else -1.0
    t = jnp.asarray(np.where(np.arange(n_out) == hot, 1.0, lo), dtype=jnp.float32)
    return weights, x, t


@pytest.mark.parametrize("model,momentum", [
    ("ann", False), ("ann", True), ("snn", False), ("snn", True),
])
def test_fused_matches_lax(model, momentum):
    weights, x, t = _setup(99, 12, [16, 8], 8, snn=(model == "snn"))
    dw = tuple(jnp.zeros_like(w) for w in weights) if momentum else ()
    kw = dict(model=model, momentum=momentum, min_iter=5, max_iter=60)

    ref = loop.train_sample_lax(weights, dw, x, t, 0.2, 1e-6, **kw)
    got = pallas_train.train_sample_fused(
        weights, dw, x, t, 0.2, 1e-6, interpret=True, **kw
    )

    assert int(got.n_iter) == int(ref.n_iter)
    assert bool(got.first_ok) == bool(ref.first_ok)
    assert bool(got.final_ok) == bool(ref.final_ok)
    np.testing.assert_allclose(float(got.ep0), float(ref.ep0), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(ref.out), atol=1e-6
    )
    for a, b in zip(got.weights, ref.weights):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    if momentum:
        for a, b in zip(got.dw, ref.dw):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_fused_deep_kernel():
    """Three hidden layers exercise the static depth unrolling."""
    weights, x, t = _setup(7, 10, [12, 8, 6], 4)
    ref = loop.train_sample_lax(
        weights, (), x, t, 0.2, 1e-6,
        model="ann", momentum=False, min_iter=3, max_iter=30,
    )
    got = pallas_train.train_sample_fused(
        weights, (), x, t, 0.2, 1e-6,
        model="ann", momentum=False, min_iter=3, max_iter=30, interpret=True,
    )
    assert int(got.n_iter) == int(ref.n_iter)
    for a, b in zip(got.weights, ref.weights):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
