"""Real-format ingestion (VERDICT r04 item 4).

The tutorials' protocol numbers run on synthetic stand-ins (no
egress), but the converters must handle REAL container bytes: genuine
big-endian idx files through ``pmnist`` and realistic RRUFF ``.dif``
headers — including the atom-row corner of ``file_dif.c:166-268`` —
through ``pdif``, each followed by an actual train/eval round so the
whole drop-real-files-in pipeline is a tested path, not an untested
branch."""

import struct

import numpy as np
import pytest

from hpnn_tpu.tools import pdif, pmnist


# ---------------------------------------------------------------------------
# RRUFF .dif atom-row mechanism (file_dif.c:166-268 / atom.def)
# ---------------------------------------------------------------------------

DIF_HEADER = """\
R050031 Quartz
      Sample T = 25 C
   CELL PARAMETERS:   4.9137   4.9137   5.4047  90.000  90.000 120.000
   SPACE GROUP: P3_221
"""

DIF_TAIL = """\
   X-RAY WAVELENGTH:     1.541838
   MAX. ABS. INTENSITY / VOLUME**2:      32.88
           2-THETA      INTENSITY    D-SPACING   H   K   L
             20.86         21.66        4.2549    1   0   0
             26.64        100.00        3.3435    1   0   1
"""


def _dif(tmp_path, atoms: str):
    p = tmp_path / "R050031"
    p.write_text(DIF_HEADER + "   ATOM\n" + atoms + "\n" + DIF_TAIL)
    return str(p)


def test_atom_rows_counted_like_reference(tmp_path):
    """Proper element rows (1- and 2-char symbols, incl. the Si-vs-S
    and In-vs-I lookalikes) count; the special 'atomic' types OH/Wa/
    Ow/Oh match NO element and are silently skipped — the reference's
    O-substitution arms are dead code behind ``if(idx<0)`` with UINT
    idx (file_dif.c:46,214)."""
    d = pdif.read_dif(_dif(tmp_path, "\n".join([
        "Si 0.46970 0.00000 0.66667 1.00000 0.46000",
        "O 0.41350 0.26690 0.78540 1.00000 0.93000",
        "Fe 0.12345 0.50000 0.25000 0.50000 1.00000",
        "In 0.00000 0.00000 0.00000 1.00000 0.30000",
        "OH 0.10000 0.20000 0.30000 1.00000 0.50000",  # skipped (dead arm)
        "Wa 0.10000 0.20000 0.30000 1.00000 0.50000",  # skipped
    ])))
    assert d is not None
    assert d.natoms == 4
    assert d.space == 154  # P3221


def test_malformed_matched_atom_row_fails_file(tmp_path):
    """A row that MATCHES an element but can't GET_DOUBLE its five
    fields aborts the whole file (ASSERT_GOTO -> read_dif NULL)."""
    assert pdif.read_dif(_dif(
        tmp_path, "Fe 0.5 junk 0.5 1.0 0.9")) is None
    # too few fields fails too
    assert pdif.read_dif(_dif(tmp_path, "Fe 0.5 0.5")) is None
    # ...but an unmatched symbol with garbage is just a skipped row
    d = pdif.read_dif(_dif(tmp_path, "Qq nonsense row"))
    assert d is not None and d.natoms == 0


def test_atom_symbol_walk_matches_table():
    """ATM_IS_EQ semantics: 1-char symbol needs a trailing blank
    (so 'In' never matches 'I', 'Si' never matches 'S'); 2-char
    matches on both chars; descending walk."""
    assert pdif._match_atom("I 0 0 0 1 1") == 53
    assert pdif._match_atom("In 0 0 0 1 1") == 49
    assert pdif._match_atom("S 0 0 0 1 1") == 16
    assert pdif._match_atom("Si 0 0 0 1 1") == 14
    assert pdif._match_atom("B 0 0 0 1 1") == 5
    assert pdif._match_atom("Be 0 0 0 1 1") == 4
    assert pdif._match_atom("Og 0 0 0 1 1") == 118
    assert pdif._match_atom("OH 0 0 0 1 1") is None
    assert pdif._match_atom("Xx 0 0 0 1 1") is None


def test_pdif_realistic_corpus_end_to_end(tmp_path, capsys, monkeypatch):
    """Two realistic dif+raw pairs (real RRUFF header shapes, atom
    sections with odd chemistry) convert into trainable samples, and a
    batch round over them learns — the drop-real-files-in path."""
    rruff = tmp_path / "rruff"
    (rruff / "dif").mkdir(parents=True)
    (rruff / "raw").mkdir()
    sdir = tmp_path / "samples"
    sdir.mkdir()
    rng = np.random.RandomState(3)
    for name, sg, center in (("R050031", "P1", 30.0), ("R040031", "P2", 60.0)):
        (rruff / "dif" / name).write_text(
            f"{name} Mineral\n      Sample T = 25 C\n"
            "   CELL PARAMETERS:   4.9137   4.9137   5.4047  "
            "90.000  90.000 120.000\n"
            f"   SPACE GROUP: {sg}\n"
            "   ATOM\n"
            "Si 0.46970 0.00000 0.66667 1.00000 0.46000\n"
            "OH 0.41350 0.26690 0.78540 1.00000 0.93000\n"
            "\n"
            "   X-RAY WAVELENGTH:     1.541838\n"
            "           2-THETA      INTENSITY    D-SPACING\n"
            "             20.86         21.66        4.2549\n")
        two_theta = np.linspace(5.0, 90.0, 400)
        inten = np.exp(-0.5 * ((two_theta - center) / 2.0) ** 2) * 100.0
        (rruff / "raw" / name).write_text(
            f"##{name} raw header\n" + "\n".join(
                "%.4f %12.4f" % (t, v + rng.uniform(0, 0.5))
                for t, v in zip(two_theta, inten)) + "\n")
    assert pdif.main([str(rruff), "-i", "20", "-o", "8",
                      "-s", str(sdir)]) == 0
    capsys.readouterr()
    names = sorted(p.name for p in sdir.iterdir())
    assert names == ["R040031", "R050031"]

    from hpnn_tpu.config import NNConf, NNTrain, NNType
    from hpnn_tpu.fileio import samples as sample_io
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.train import batch as batch_mod

    _, X, T = sample_io.read_dir(str(sdir))
    assert X.shape == (2, 21) and T.shape == (2, 8)
    assert float(X[:, 1:].max()) == pytest.approx(1.0)  # normalized bins
    assert float(X[0, 0]) == pytest.approx(298.15 / 273.15, abs=1e-4)  # T input
    k, _ = kernel_mod.generate(5, 21, [10], 8)
    conf = NNConf(name="xrd", type=NNType.ANN, seed=1, kernel=k,
                  train=NNTrain.BPM, samples=str(sdir), tests=str(sdir))
    assert batch_mod.train_kernel_batched(conf, batch_size=2, epochs=40,
                                          lr=0.4)
    ev = batch_mod.make_eval_fn(model="ann")
    import jax.numpy as jnp

    out = np.asarray(ev(tuple(jnp.asarray(np.asarray(w), jnp.float32)
                              for w in conf.kernel.weights),
                        jnp.asarray(X.astype(np.float32))))
    assert batch_mod.accuracy_counts(out, T, "ann") == 2


# ---------------------------------------------------------------------------
# Genuine idx containers through pmnist
# ---------------------------------------------------------------------------

def _write_idx(tmp_path, prefix, images, labels):
    n = len(labels)
    with open(tmp_path / f"{prefix}_images", "wb") as fp:
        fp.write(struct.pack(">iiii", 0x803, n, 28, 28))
        for im in images:
            fp.write(im.astype(np.uint8).tobytes())
    with open(tmp_path / f"{prefix}_labels", "wb") as fp:
        fp.write(struct.pack(">ii", 0x801, n))
        fp.write(bytes(labels))


def _digit_images(labels, seed=0):
    """Simple genuine-format 28x28 grayscale digits: a filled disc for
    0, a vertical bar for 1 (shape-bearing, not noise)."""
    rng = np.random.RandomState(seed)
    out = []
    yy, xx = np.mgrid[0:28, 0:28]
    for lb in labels:
        im = np.zeros((28, 28))
        if lb == 0:
            r2 = (yy - 14) ** 2 + (xx - 14) ** 2
            im[(r2 < 100) & (r2 > 30)] = 200
        else:
            im[4:24, 12:16] = 220
        im += rng.uniform(0, 20, im.shape)
        out.append(np.clip(im, 0, 255))
    return out


def test_pmnist_idx_to_training_round(tmp_path, capsys, monkeypatch):
    """Genuine big-endian idx containers -> pmnist -> sample dirs ->
    one per-sample training round + eval, PASS on every test file."""
    monkeypatch.chdir(tmp_path)
    train_lb = [0, 1] * 4
    test_lb = [0, 1] * 2
    _write_idx(tmp_path, "train", _digit_images(train_lb, 1), train_lb)
    _write_idx(tmp_path, "test", _digit_images(test_lb, 2), test_lb)
    (tmp_path / "samples").mkdir()
    (tmp_path / "tests").mkdir()
    assert pmnist.main(["samples", "tests"]) == 0
    capsys.readouterr()

    from hpnn_tpu.config import NNConf, NNTrain, NNType
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.train import driver
    from hpnn_tpu.utils import logging as log

    k, _ = kernel_mod.generate(10958, 784, [16], 10)
    conf = NNConf(name="mnist", type=NNType.ANN, seed=1, kernel=k,
                  train=NNTrain.BP, samples="samples", tests="tests")
    log.set_verbose(2)
    assert driver.train_kernel(conf)
    driver.run_kernel(conf)
    out = capsys.readouterr().out
    assert out.count("TRAINING FILE:") == 8
    assert out.count("SUCCESS!") == 8
    assert out.count("[PASS]") == 4
