"""The embedding-API flow documented in docs/api.md — the reference's
"train an ANN on the fly" story (`/root/reference/README.md:10-34`,
`_NN(a,b)` surface `include/libhpnn.h:58-215`): a host program
generates a kernel, trains it over samples it produced itself, queries
it, dumps it, and a NEXT run loads and reuses it."""

import numpy as np

import hpnn_tpu
from hpnn_tpu.utils import logging as nn_log


def _write_samples(d, n=16):
    rng = np.random.default_rng(1)
    for i in range(n):
        c = i % 2
        x = (1 - 2 * c) * np.r_[np.ones(4), -np.ones(4)] \
            + 0.1 * rng.normal(size=8)
        t = np.full(2, -1.0)
        t[c] = 1.0
        with open(d / f"s{i:05d}.txt", "w") as fp:
            fp.write("[input] 8\n" + " ".join(f"{v:.5f}" for v in x) + "\n")
            fp.write("[output] 2\n" + " ".join(f"{v:.1f}" for v in t) + "\n")


def test_embedded_train_run_dump_load(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    hpnn_tpu.runtime.init_all(0)
    nn_log.set_verbose(2)

    conf = hpnn_tpu.NNConf(
        name="embedded", type=hpnn_tpu.NNType.ANN,
        train=hpnn_tpu.NNTrain.BP, seed=10958,
    )
    assert hpnn_tpu.generate_kernel(conf, n_in=8, hiddens=[6], n_out=2)
    assert conf.kernel.n_inputs == 8
    assert conf.kernel.n_outputs == 2
    assert conf.kernel.hidden_sizes == (6,)

    sdir = tmp_path / "samples"
    sdir.mkdir()
    _write_samples(sdir)
    conf.samples = conf.tests = str(sdir)

    assert hpnn_tpu.train_kernel(conf)
    hpnn_tpu.run_kernel(conf)
    out = capsys.readouterr().out
    assert out.count("TRAINING FILE:") == 16
    assert out.count("SUCCESS!") == 16
    assert out.count("[PASS]") == 16

    with open("kernel.opt", "w") as fp:
        hpnn_tpu.dump_kernel(conf, fp)

    # "next program run": a fresh handle loads the dumped kernel and
    # queries it in memory (the doc's run_sample snippet)
    conf2 = hpnn_tpu.NNConf(
        type=hpnn_tpu.NNType.ANN, f_kernel="kernel.opt",
    )
    assert hpnn_tpu.load_kernel(conf2)
    import jax.numpy as jnp

    from hpnn_tpu.train import loop

    x, t = hpnn_tpu.read_sample(str(sdir / "s00000.txt"))
    o = np.asarray(loop.run_sample(
        tuple(jnp.asarray(w) for w in conf2.kernel.weights),
        jnp.asarray(x), model="ann",
    ))
    assert int(np.argmax(o)) == int(np.argmax(t))
    # the dumped text round-trips bit-for-bit through %17.15f
    for a, b in zip(conf.kernel.weights, conf2.kernel.weights):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=1e-15)


def test_import_is_light(tmp_path):
    """``import hpnn_tpu`` must not pull the training stack (host
    programs may only manipulate confs/kernels); the execute-ops
    resolve lazily.  (Asserting on 'jax' itself would be vacuous here:
    this environment's sitecustomize imports jax at interpreter
    startup.)"""
    import subprocess
    import sys

    code = (
        "import sys; import hpnn_tpu; "
        "assert 'hpnn_tpu.train.driver' not in sys.modules, 'eager driver'; "
        "assert 'hpnn_tpu.train.loop' not in sys.modules, 'eager loop'; "
        "hpnn_tpu.train_kernel; "
        "assert 'hpnn_tpu.train.driver' in sys.modules"
    )
    subprocess.run([sys.executable, "-c", code], check=True)
