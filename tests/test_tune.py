"""The self-tuning remediation plane (hpnn_tpu/tune/, ``HPNN_TUNE``):
the pure :func:`decide` verdict matrix, the env-twinned
:class:`Policy`, every actuator's apply/veto/rollback, the bounded
post-apply watch, the audit trail (ledger + ``tune.*`` events), and
the ``--tune`` schema lint over a real armed run.

The plane's own contract on top of the usual obs one: every applied
move carries the prior it displaced (rollback restores it bitwise),
one move per cooldown, and a verdict for every tick — including all
the explicit do-nothing ones."""

import importlib.util
import json
import os

import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import blame
from hpnn_tpu.tenant.quota import QuotaEnforcer, TenantSpec
from hpnn_tpu.tune import engine

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

P = engine.Policy


def _read(path):
    if not os.path.exists(path):
        return []
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _arm(monkeypatch, tmp_path, **env):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.setenv("HPNN_BLAME", "1")
    for key, val in env.items():
        monkeypatch.setenv(key, str(val))
    obs._reset_for_tests()
    return tmp_path / "m.jsonl"


def _sensor(phase="queue", pct=80.0, roots=32):
    pcts = {p: 0.0 for p in blame.PHASES}
    pcts[phase] = pct
    pcts["gap"] = 100.0 - sum(v for k, v in pcts.items() if k != "gap")
    return {"roots": roots, "pct": pcts}


_CHILD_OF = {"queue": "serve.batch.queue", "dispatch": "serve.dispatch",
             "spill": "serve.spill_reload", "shed_retry": "serve.retry"}


def _feed_phase(phase, n=16, kernel="k"):
    """n synthetic request roots whose tail is 90% one phase — the
    online sensor reads dominant ``phase`` afterwards."""
    refs = iter(range(1, 10 * n + 10, 2))
    for _ in range(n):
        child_ref, root_ref = next(refs), next(refs)
        child = {"span": child_ref, "parent": root_ref,
                 "name": _CHILD_OF[phase], "t0": 0.0, "dt": 0.9}
        if phase == "shed_retry":
            child["failed"] = "Shed"
        blame.note_record(child)
        blame.note_record({"span": root_ref, "parent": None,
                           "name": "serve.request", "t0": 0.0,
                           "dt": 1.0, "kernel": kernel})


class _FakeScaler:
    """request_up/request_down recorder standing in for
    fleet/autoscaler.py (whose own push API has its own tests)."""

    def __init__(self, to=(1, 2)):
        self.ups, self.downs, self._to = [], [], to

    def request_up(self, *, reason):
        self.ups.append(reason)
        return self._to

    def request_down(self, to_width, *, reason):
        self.downs.append((int(to_width), reason))
        return (self._to[1] if self._to else 0, int(to_width))


def _tuner(clock, p99, *, burn=3.0, policy=None, **kw):
    return engine.Tuner(
        kw.pop("session", None),
        policy=policy if policy is not None else P(),
        clock=lambda: clock["t"], p99_fn=lambda: p99["v"],
        burn_fn=lambda: burn, **kw)


# ------------------------------------------------------ decide() core
def test_decide_no_sensor():
    d = engine.decide(None, 3.0, policy=P(), now=0.0)
    assert d["verdict"] == "no_sensor" and d["action"] is None


def test_decide_watch_active_blocks_everything():
    d = engine.decide(_sensor(roots=4), 3.0, policy=P(), now=0.0,
                      watch_active=True)
    assert d["verdict"] == "watch_active"


def test_decide_thin_window():
    d = engine.decide(_sensor(roots=15), 3.0, policy=P(), now=0.0)
    assert d["verdict"] == "thin_window"


@pytest.mark.parametrize("burn", [None, 0.0, 0.99])
def test_decide_burn_ok_when_slo_healthy(burn):
    d = engine.decide(_sensor(), burn, policy=P(), now=0.0)
    assert d["verdict"] == "burn_ok" and d["action"] is None


def test_decide_no_dominant():
    d = engine.decide(_sensor(pct=39.9), 3.0, policy=P(), now=0.0)
    assert d["verdict"] == "no_dominant" and d["phase"] == "queue"


def test_decide_cooldown():
    d = engine.decide(_sensor(), 3.0, policy=P(cooldown_s=30.0),
                      now=100.0, last_apply_t=80.0)
    assert d["verdict"] == "cooldown"
    d = engine.decide(_sensor(), 3.0, policy=P(cooldown_s=30.0),
                      now=120.0, last_apply_t=80.0)
    assert d["verdict"] == "apply"


@pytest.mark.parametrize("phase,action", list(engine.RULE_OF.items()))
def test_decide_apply_maps_phase_to_knob(phase, action):
    d = engine.decide(_sensor(phase), 3.0, policy=P(), now=0.0)
    assert d["verdict"] == "apply"
    assert d["phase"] == phase and d["action"] == action


def test_decide_ignores_unactionable_phases():
    """gap/other can dwarf everything — they have no knob, so the
    dominant ACTIONABLE phase names the action."""
    sensor = {"roots": 32, "pct": {"queue": 45.0, "dispatch": 1.0,
                                   "spill": 0.0, "shed_retry": 0.0,
                                   "other": 0.0, "gap": 54.0}}
    d = engine.decide(sensor, 3.0, policy=P(), now=0.0)
    assert d["verdict"] == "apply" and d["action"] == "scale_up"


# --------------------------------------------------------------- policy
def test_policy_from_env_parses_all_knobs():
    pol = P.from_env({"HPNN_TUNE_DOMINANT_PCT": "55",
                      "HPNN_TUNE_BURN": "2.5",
                      "HPNN_TUNE_COOLDOWN_S": "7",
                      "HPNN_TUNE_WATCH_S": "3",
                      "HPNN_TUNE_QUANT_ERR": "1e-3",
                      "HPNN_TUNE_DRY": "1"})
    assert pol.dominant_pct == 55.0 and pol.burn_gate == 2.5
    assert pol.cooldown_s == 7.0 and pol.watch_s == 3.0
    assert pol.quant_err_max == 1e-3 and pol.dry is True
    assert P.from_env({}) == P()
    assert P.from_env({"HPNN_TUNE_BURN": "9"},
                      burn_gate=1.5).burn_gate == 1.5  # overrides win


def test_policy_from_env_rejects_junk():
    with pytest.raises(ValueError, match="HPNN_TUNE_COOLDOWN_S"):
        P.from_env({"HPNN_TUNE_COOLDOWN_S": "soon"})


def test_policy_validation():
    with pytest.raises(ValueError):
        P(dominant_pct=0.0)
    with pytest.raises(ValueError):
        P(cooldown_s=-1.0)


# ------------------------------------------------------ tick + actuate
def test_tick_no_sensor_when_blame_unarmed(monkeypatch, tmp_path):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.delenv("HPNN_BLAME", raising=False)
    obs._reset_for_tests()
    t = _tuner({"t": 0.0}, {"v": 1.0}, autoscaler=_FakeScaler())
    assert t.tick()["verdict"] == "no_sensor"


def test_tick_applies_scale_up_and_audits(monkeypatch, tmp_path):
    sink = _arm(monkeypatch, tmp_path)
    _feed_phase("queue")
    clock, p99 = {"t": 100.0}, {"v": 50.0}
    scaler = _FakeScaler(to=(1, 2))
    t = _tuner(clock, p99, autoscaler=scaler)
    d = t.tick()
    assert d["verdict"] == "apply" and d["action"] == "scale_up"
    assert d["id"] == "t1" and d["applied"] == 2
    assert scaler.ups == ["tune:queue"]
    assert t.stats["applied"] == 1
    (ap,) = [r for r in _read(sink) if r["ev"] == "tune.apply"]
    assert ap["id"] == "t1" and ap["phase"] == "queue"
    assert ap["prior"] == 1 and ap["applied"] == 2
    assert ap["pct"] == pytest.approx(90.0)
    assert ap["cooldown_s"] == t.policy.cooldown_s
    # a second tick inside the watch: one change at a time
    assert t.tick()["verdict"] == "watch_active"
    assert t.census()["watch"]["id"] == "t1"


def test_watch_passes_then_cooldown_holds(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path)
    _feed_phase("queue")
    clock, p99 = {"t": 100.0}, {"v": 50.0}
    t = _tuner(clock, p99, autoscaler=_FakeScaler(),
               policy=P(cooldown_s=30.0, watch_s=10.0))
    assert t.tick()["verdict"] == "apply"
    clock["t"] += 10.1                      # survive the watch...
    d = t.tick()
    assert d["verdict"] == "cooldown"       # ...but the cooldown holds
    assert any(r["verdict"] == "watch_pass" and r["id"] == "t1"
               for r in t.census()["ledger"])
    assert t.census()["watch"] is None
    clock["t"] += 30.0                      # cooldown over: re-apply
    assert t.tick()["verdict"] == "apply"


def test_watch_regression_rolls_back(monkeypatch, tmp_path):
    sink = _arm(monkeypatch, tmp_path)
    _feed_phase("queue")
    clock, p99 = {"t": 100.0}, {"v": 50.0}
    scaler = _FakeScaler(to=(1, 2))
    t = _tuner(clock, p99, autoscaler=scaler,
               policy=P(cooldown_s=30.0, watch_s=10.0))
    assert t.tick()["verdict"] == "apply"
    clock["t"] += 5.0
    p99["v"] = 50.0 * engine.ROLLBACK_P99_RATIO + 1.0
    assert t.check_watch() == "scale_up"
    assert scaler.downs == [(1, "tune:rollback")]
    assert t.stats["rolled_back"] == 1
    (rb,) = [r for r in _read(sink) if r["ev"] == "tune.rollback"]
    assert rb["id"] == "t1" and rb["restored"] == 1
    assert rb["reason"] == "p99_regression"
    # the rollback is itself a move: the cooldown re-armed
    assert t.tick()["verdict"] == "cooldown"
    assert t.rollback("again") is None      # nothing watched now


def test_veto_lands_in_ledger_not_apply(monkeypatch, tmp_path):
    sink = _arm(monkeypatch, tmp_path)
    _feed_phase("queue")
    t = _tuner({"t": 0.0}, {"v": 1.0}, autoscaler=_FakeScaler(to=None))
    d = t.tick()
    assert d["verdict"] == "veto" and d["reason"] == "at_max"
    assert t.stats["vetoed"] == 1 and t.census()["watch"] is None
    recs = _read(sink)
    assert not [r for r in recs if r["ev"] == "tune.apply"]
    (dec,) = [r for r in recs if r["ev"] == "tune.decision"]
    assert dec["verdict"] == "veto" and dec["reason"] == "at_max"


def test_dry_run_decides_but_never_actuates(monkeypatch, tmp_path):
    sink = _arm(monkeypatch, tmp_path)
    _feed_phase("queue")
    scaler = _FakeScaler()
    t = _tuner({"t": 0.0}, {"v": 1.0}, autoscaler=scaler,
               policy=P(dry=True))
    assert t.tick()["verdict"] == "dry_run"
    assert not scaler.ups and t.stats["applied"] == 0
    assert not [r for r in _read(sink) if r["ev"] == "tune.apply"]


def test_no_actuator_when_knob_not_wired(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path)
    _feed_phase("queue")
    t = _tuner({"t": 0.0}, {"v": 1.0})      # no autoscaler wired
    assert t.tick()["verdict"] == "no_actuator"


def test_quota_squeeze_applies_and_restores_bitwise(monkeypatch,
                                                    tmp_path):
    _arm(monkeypatch, tmp_path)
    _feed_phase("shed_retry")
    quota = QuotaEnforcer({"bronze": TenantSpec("bronze", "bronze",
                                                rate_rps=40.0)})
    before = quota.spec("bronze")
    t = _tuner({"t": 0.0}, {"v": 1.0}, quota=quota)
    d = t.tick()
    assert d["verdict"] == "apply" and d["action"] == "quota_squeeze"
    assert quota.spec("bronze").rate_rps == pytest.approx(
        40.0 * engine.QUOTA_SQUEEZE_FACTOR)
    assert t.rollback("unit") == "quota_squeeze"
    assert quota.spec("bronze") == before   # the exact tuple


def test_quota_squeeze_vetoes_without_rate_caps(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path)
    _feed_phase("shed_retry")
    t = _tuner({"t": 0.0}, {"v": 1.0}, quota=QuotaEnforcer({}))
    d = t.tick()
    assert d["verdict"] == "veto" and d["reason"] == "no_rate_caps"


def _session(mode="compiled"):
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=0.5,
                         mode=mode)
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    sess.register_kernel("k", k)
    return sess


def test_grow_buckets_applies_and_restores(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path)
    _feed_phase("spill")
    sess = _session()
    try:
        prior = tuple(sess.engine.buckets)
        t = _tuner({"t": 0.0}, {"v": 1.0}, session=sess)
        d = t.tick()
        assert d["verdict"] == "apply" and d["action"] == "grow_buckets"
        assert len(sess.engine.buckets) == len(prior) + 1
        assert t.rollback("unit") == "grow_buckets"
        assert tuple(sess.engine.buckets) == prior
    finally:
        sess.close()


def test_precision_down_applies_one_notch(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path)
    _feed_phase("dispatch")
    sess = _session()
    try:
        v0 = sess.registry.get("k").version
        t = _tuner({"t": 0.0}, {"v": 1.0}, session=sess)
        d = t.tick()
        assert d["verdict"] == "apply"
        assert d["action"] == "precision_down" and d["target"] == "k"
        entry = sess.registry.get("k")
        assert entry.precision == engine.DOWNSHIFT[
            sess.engine.default_precision or "native"]
        assert entry.version > v0           # a retag is a new version
        assert t.rollback("unit") == "precision_down"
        assert sess.registry.get("k").precision is None
    finally:
        sess.close()


def test_precision_down_vetoes_in_parity_mode(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path)
    _feed_phase("dispatch")
    sess = _session(mode="parity")
    try:
        t = _tuner({"t": 0.0}, {"v": 1.0}, session=sess)
        d = t.tick()
        assert d["verdict"] == "veto" and d["reason"] == "parity_mode"
    finally:
        sess.close()


def test_precision_down_vetoes_at_floor(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path)
    _feed_phase("dispatch")
    sess = _session()
    try:
        sess.registry.set_precision("k", "bf16")  # already at floor
        t = _tuner({"t": 0.0}, {"v": 1.0}, session=sess)
        d = t.tick()
        assert d["verdict"] == "veto" and d["reason"] == "at_floor"
        assert sess.registry.get("k").precision == "bf16"
    finally:
        sess.close()


def test_precision_down_vetoes_on_quant_err_and_reverts(monkeypatch,
                                                        tmp_path):
    """A downshift whose MEASURED error breaches the bound reverts
    immediately — and the revert is a fresh version, never a reuse."""
    _arm(monkeypatch, tmp_path)
    _feed_phase("dispatch")
    sess = _session()

    class _BigErr(dict):
        def get(self, key, default=None):
            return 1.0                      # any bound is breached

    try:
        monkeypatch.setattr(sess.engine, "_quant_err", _BigErr())
        v0 = sess.registry.get("k").version
        t = _tuner({"t": 0.0}, {"v": 1.0}, session=sess)
        d = t.tick()
        assert d["verdict"] == "veto" and d["reason"] == "quant_err"
        entry = sess.registry.get("k")
        assert entry.precision is None      # displaced policy restored
        assert entry.version == v0 + 2      # downshift + revert
    finally:
        sess.close()


# --------------------------------------------------------------- audit
def test_decision_stream_records_edges_not_steady_state(monkeypatch,
                                                        tmp_path):
    sink = _arm(monkeypatch, tmp_path)
    _feed_phase("queue")
    t = _tuner({"t": 0.0}, {"v": 1.0}, burn=0.0)
    for _ in range(5):
        assert t.tick()["verdict"] == "burn_ok"
    decisions = [r for r in _read(sink) if r["ev"] == "tune.decision"]
    assert len(decisions) == 1              # the edge, not the hour
    assert t.stats["ticks"] == 5


def test_for_session_and_census_docs(monkeypatch, tmp_path):
    monkeypatch.delenv("HPNN_TUNE", raising=False)
    obs._reset_for_tests()
    assert engine.for_session(object()) is None
    assert engine.tunez_doc() is None
    assert engine.health_doc() == {"armed": False}
    monkeypatch.setenv("HPNN_TUNE", "1")
    _arm(monkeypatch, tmp_path)
    t = engine.for_session(object(), autoscaler=_FakeScaler())
    assert t is not None
    assert sorted(t._actuators) == ["grow_buckets", "precision_down",
                                    "scale_up"]
    t.activate()
    doc = engine.tunez_doc()
    assert doc["armed"] and doc["rules"] == engine.RULE_OF
    assert doc["policy"]["dominant_pct"] == t.policy.dominant_pct
    health = engine.health_doc()
    assert health["armed"] and health["active"]
    assert "ledger" not in health           # /tunez carries the ledger
    t.stop()
    assert engine.tunez_doc() is None


# ---------------------------------------------------------------- lint
def _tune_sink(monkeypatch, tmp_path):
    """A real armed run: one apply, one regression rollback, decision
    edges — the accept fixture for lint_tune."""
    sink = _arm(monkeypatch, tmp_path)
    _feed_phase("queue")
    blame.flush()
    clock, p99 = {"t": 100.0}, {"v": 50.0}
    t = _tuner(clock, p99, autoscaler=_FakeScaler(),
               policy=P(cooldown_s=30.0, watch_s=10.0))
    assert t.tick()["verdict"] == "apply"
    clock["t"] += 5.0
    p99["v"] = 500.0
    assert t.check_watch() == "scale_up"
    obs.configure(None)
    return sink


def test_lint_tune_accepts_a_real_run(monkeypatch, tmp_path):
    sink = _tune_sink(monkeypatch, tmp_path)
    lint = _load_tool("check_obs_catalog")
    assert lint.lint_tune(str(sink)) == []


@pytest.mark.parametrize("bad,needle", [
    ({"ev": "tune.apply", "id": "", "action": "scale_up",
      "phase": "queue", "pct": 50.0, "prior": 1, "applied": 2,
      "cooldown_s": 1.0, "watch_s": 1.0}, "non-empty"),
    ({"ev": "tune.apply", "id": "tx", "action": "overclock",
      "phase": "queue", "pct": 50.0, "prior": 1, "applied": 2,
      "cooldown_s": 1.0, "watch_s": 1.0}, "action"),
    ({"ev": "tune.apply", "id": "ty", "action": "scale_up",
      "phase": "queue", "pct": 150.0, "applied": 2,
      "cooldown_s": 1.0, "watch_s": 1.0}, "prior"),
    ({"ev": "tune.rollback", "id": "never-applied",
      "action": "scale_up", "reason": "x", "restored": 1},
     "pairs no"),
    ({"ev": "tune.decision", "verdict": "vibes", "roots": 1},
     "closed enum"),
    ({"ev": "tune.decision", "verdict": "apply", "roots": -1},
     "roots"),
    ({"ev": "blame.queue_pct", "kind": "gauge", "value": 120.0},
     "[0, 100]"),
    ({"ev": "blame.window_roots", "kind": "gauge", "value": -3},
     "non-negative"),
])
def test_lint_tune_break_ladder(monkeypatch, tmp_path, bad, needle):
    sink = _tune_sink(monkeypatch, tmp_path)
    with open(sink, "a") as fp:
        fp.write(json.dumps(bad) + "\n")
    lint = _load_tool("check_obs_catalog")
    failures = lint.lint_tune(str(sink))
    assert failures and any(needle in f for f in failures), failures


def test_lint_tune_wants_an_armed_run(tmp_path):
    quiet = tmp_path / "quiet.jsonl"
    quiet.write_text('{"ev": "obs.open", "kind": "meta"}\n')
    lint = _load_tool("check_obs_catalog")
    assert any("HPNN_TUNE" in f for f in lint.lint_tune(str(quiet)))
