"""TP/DP parallelism tests on the 8-virtual-CPU-device mesh.

The oracle is the reference's own consistency criterion: every backend
combination must agree numerically (abs-sum ≤1e-12-ish in f64,
ref: /root/reference/ChangeLog:33-38).  Here the "backends" are the
single-device jitted path (tests/test_ann_numerics.py's subject) and
the sharded TP/DP paths over a faked 8-device mesh — the JAX version of
the reference's DEBUG 3-GPU-contexts-on-one-device trick (SURVEY.md §4.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpnn_tpu.models import ann, kernel as kernel_mod, snn
from hpnn_tpu.parallel import dp, mesh as mesh_mod, tp
from hpnn_tpu.train import loop


def _make_kernel(seed, n_in, hiddens, n_out):
    k, _ = kernel_mod.generate(seed, n_in, hiddens, n_out)
    return tuple(jnp.asarray(w) for w in k.weights)


def _sample(seed, n_in, n_out, hot=3):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.uniform(-1, 1, n_in))
    t = jnp.asarray(np.where(np.arange(n_out) == hot, 1.0, -1.0))
    return x, t


def _sample_snn(seed, n_in, n_out, hot=3):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.uniform(-1, 1, n_in))
    t = jnp.asarray(np.where(np.arange(n_out) == hot, 1.0, 0.0))
    return x, t


@pytest.fixture(scope="module")
def mesh4():
    return mesh_mod.make_mesh(n_data=2, n_model=4)


@pytest.mark.parametrize("model,momentum", [
    ("ann", False), ("ann", True), ("snn", False), ("snn", True),
])
def test_tp_train_sample_matches_single_device(mesh4, model, momentum):
    """TP over 4 model shards == single-device trainer, bit-for-bit-ish."""
    n_in, hiddens, n_out = 12, [16, 8], 8  # divisible by 4
    weights = _make_kernel(1234, n_in, hiddens, n_out)
    x, t = (_sample_snn if model == "snn" else _sample)(7, n_in, n_out)
    min_it, max_it = 5, 40  # keep runtimes small; same loop structure

    dw = tuple(jnp.zeros_like(w) for w in weights) if momentum else ()
    ref = loop.train_sample(
        weights, dw, x, t, 0.2, 1e-6,
        model=model, momentum=momentum, min_iter=min_it, max_iter=max_it,
    )

    fn = tp.make_train_fn(
        mesh4, len(weights), model=model, momentum=momentum,
        min_iter=min_it, max_iter=max_it, n_out=n_out,
    )
    w_sh = tp.shard_kernel(weights, mesh4)
    dw_sh = tp.shard_kernel(dw, mesh4) if momentum else ()
    got = fn(w_sh, dw_sh, tp.replicate(x, mesh4), tp.replicate(t, mesh4),
             jnp.asarray(0.2), jnp.asarray(1e-6))

    assert int(got.n_iter) == int(ref.n_iter)
    assert bool(got.first_ok) == bool(ref.first_ok)
    assert bool(got.final_ok) == bool(ref.final_ok)
    np.testing.assert_allclose(float(got.ep0), float(ref.ep0), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(got.out), np.asarray(ref.out),
                               atol=1e-11)
    for a, b in zip(got.weights, ref.weights):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-11)


def test_tp_padded_kernel_equivalence(mesh4):
    """Padding layer dims to mesh multiples doesn't change the math."""
    n_in, hiddens, n_out = 10, [7, 5], 3  # nothing divisible by 4
    weights = _make_kernel(99, n_in, hiddens, n_out)
    x, t = _sample(3, n_in, n_out, hot=1)
    min_it, max_it = 3, 25

    ref = loop.train_sample(
        weights, (), x, t, 0.2, 1e-6,
        model="ann", momentum=False, min_iter=min_it, max_iter=max_it,
    )

    k = 4
    padded, orig_rows = mesh_mod.pad_kernel(weights, k)
    t_pad = mesh_mod.pad_vector(np.asarray(t), k)
    # ANN target padding uses 0 (outside the argmax mask anyway)
    fn = tp.make_train_fn(
        mesh4, len(weights), model="ann", momentum=False,
        min_iter=min_it, max_iter=max_it, n_out=n_out,
    )
    got = fn(
        tp.shard_kernel(padded, mesh4), (),
        tp.replicate(x, mesh4), tp.replicate(jnp.asarray(t_pad), mesh4),
        jnp.asarray(0.2), jnp.asarray(1e-6),
    )
    assert int(got.n_iter) == int(ref.n_iter)
    un = mesh_mod.unpad_kernel(got.weights, orig_rows)
    for a, b in zip(un, ref.weights):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-11)
    np.testing.assert_allclose(
        np.asarray(got.out)[:n_out], np.asarray(ref.out), atol=1e-11
    )


def test_tp_padded_snn_equivalence(mesh4):
    """SNN softmax masking: padded logits must not pollute dv."""
    n_in, hiddens, n_out = 10, [6], 5
    weights = _make_kernel(4242, n_in, hiddens, n_out)
    x, t = _sample_snn(11, n_in, n_out, hot=2)
    min_it, max_it = 3, 20

    ref = loop.train_sample(
        weights, (), x, t, 0.2, 1e-6,
        model="snn", momentum=False, min_iter=min_it, max_iter=max_it,
    )
    k = 4
    padded, orig_rows = mesh_mod.pad_kernel(weights, k)
    t_pad = mesh_mod.pad_vector(np.asarray(t), k)
    fn = tp.make_train_fn(
        mesh4, len(weights), model="snn", momentum=False,
        min_iter=min_it, max_iter=max_it, n_out=n_out,
    )
    got = fn(
        tp.shard_kernel(padded, mesh4), (),
        tp.replicate(x, mesh4), tp.replicate(jnp.asarray(t_pad), mesh4),
        jnp.asarray(0.2), jnp.asarray(1e-6),
    )
    assert int(got.n_iter) == int(ref.n_iter)
    un = mesh_mod.unpad_kernel(got.weights, orig_rows)
    for a, b in zip(un, ref.weights):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-11)


def test_tp_run_fn(mesh4):
    n_in, hiddens, n_out = 12, [8], 4
    weights = _make_kernel(7, n_in, hiddens, n_out)
    x, _ = _sample(5, n_in, n_out)
    ref = ann.run(weights, x)
    fn = tp.make_run_fn(mesh4, len(weights), model="ann", n_out=n_out)
    got = fn(tp.shard_kernel(weights, mesh4), tp.replicate(x, mesh4))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-14)


@pytest.mark.parametrize("model", ["ann", "snn"])
def test_tp_batched_run_fn(mesh4, model):
    """Batched TP eval (one dispatch per chunk) == per-sample forward."""
    n_in, hiddens, n_out = 12, [8], 4
    weights = _make_kernel(7, n_in, hiddens, n_out)
    mk = _sample_snn if model == "snn" else _sample
    X = np.stack([np.asarray(mk(i, n_in, n_out)[0]) for i in range(6)])
    fn = tp.make_batched_run_fn(mesh4, len(weights), model=model, n_out=n_out)
    got = np.asarray(
        fn(tp.shard_kernel(weights, mesh4), tp.replicate(jnp.asarray(X), mesh4))
    )
    mod = snn if model == "snn" else ann
    for i in range(X.shape[0]):
        np.testing.assert_allclose(
            got[i], np.asarray(mod.run(weights, jnp.asarray(X[i]))), atol=1e-13
        )


# ---------------------------------------------------------------- DP


def _batch(seed, B, n_in, n_out, snn_targets=False):
    rng = np.random.RandomState(seed)
    X = rng.uniform(-1, 1, (B, n_in))
    hots = rng.randint(0, n_out, B)
    lo = 0.0 if snn_targets else -1.0
    T = np.full((B, n_out), lo)
    T[np.arange(B), hots] = 1.0
    return jnp.asarray(X), jnp.asarray(T)


@pytest.mark.parametrize("gather,momentum", [
    (True, False), (True, True), (False, False), (False, True),
])
def test_epoch_fn_matches_per_step_loop(gather, momentum):
    """ONE scan-epoch dispatch == the per-step jit loop, same math
    (both paths run dp.train_step_math), on a single-shard mesh
    (gather strategy) and a 4-way data mesh (stream strategy)."""
    mesh = mesh_mod.make_mesh(n_data=1 if gather else 4, n_model=1)
    weights = _make_kernel(99, 6, [10], 4)
    B, n_steps = 8, 3
    rng = np.random.RandomState(3)
    Xe = rng.uniform(-1, 1, (n_steps, B, 6))
    Te = np.where(
        rng.randint(0, 4, (n_steps, B, 1)) == np.arange(4), 1.0, -1.0
    )

    # reference: per-step jit
    step = dp.make_gspmd_train_step(
        mesh, weights, model="ann", momentum=momentum, donate=False
    )
    w_ref = dp.place_kernel(weights, mesh)
    dw_ref = dp.place_kernel(
        tuple(np.zeros_like(np.asarray(w)) for w in weights), mesh
    ) if momentum else ()
    losses_ref = []
    for s in range(n_steps):
        Xs, Ts = dp.shard_batch(Xe[s], Te[s], mesh)
        w_ref, dw_ref, l = step(w_ref, dw_ref, Xs, Ts)
        losses_ref.append(float(l))

    # scan epoch
    epoch_fn = dp.make_gspmd_epoch_fn(
        mesh, weights, model="ann", momentum=momentum, donate=False,
        gather=gather,
    )
    w_sh = dp.place_kernel(weights, mesh)
    dw_sh = dp.place_kernel(
        tuple(np.zeros_like(np.asarray(w)) for w in weights), mesh
    ) if momentum else ()
    if gather:
        X_all = jnp.asarray(Xe.reshape(-1, 6))
        T_all = jnp.asarray(Te.reshape(-1, 4))
        idx = jnp.arange(n_steps * B, dtype=jnp.int32).reshape(n_steps, B)
        w_sh, dw_sh, losses = epoch_fn(w_sh, dw_sh, X_all, T_all, idx)
    else:
        Xs, Ts = dp.shard_batch_steps(Xe, Te, mesh)
        w_sh, dw_sh, losses = epoch_fn(w_sh, dw_sh, Xs, Ts)

    np.testing.assert_allclose(np.asarray(losses), losses_ref, atol=1e-12)
    for a, b in zip(w_sh, w_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-12)
    if momentum:
        for a, b in zip(dw_sh, dw_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-12)


def test_dp_step_matches_host_math():
    """Explicit shard_map+pmean step == single-device batched grad step."""
    m = mesh_mod.make_mesh(n_data=8, n_model=1)
    weights = _make_kernel(555, 6, [10], 4)
    X, T = _batch(1, 16, 6, 4)

    step = dp.make_dp_train_step(m, model="ann", momentum=False)
    Xs, Ts = dp.shard_batch(X, T, m)
    w_rep = dp.replicate_kernel(weights, m)
    got_w, _, got_loss = step(w_rep, (), Xs, Ts)

    grads = jax.grad(dp.batch_loss)(weights, X, T, model="ann")
    want_w = dp.sgd_step(weights, grads, ann.BP_LEARN_RATE)
    for a, b in zip(got_w, want_w):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-12)
    want_loss = dp.batch_loss(want_w, X, T, model="ann")
    np.testing.assert_allclose(float(got_loss), float(want_loss), rtol=1e-12)


def test_dp_grad_equals_delta_rule():
    """-∇Ep reproduces the reference's hand-derived delta updates."""
    weights = _make_kernel(77, 5, [6], 3)
    x, t = _sample(9, 5, 3, hot=0)
    acts = ann.forward(weights, x)
    ds = ann.deltas(weights, acts, t)
    manual = ann.bp_update(weights, acts, ds, ann.BP_LEARN_RATE)
    grads = jax.grad(dp.sample_loss)(weights, x, t, model="ann")
    auto = dp.sgd_step(weights, grads, ann.BP_LEARN_RATE)
    for a, b in zip(manual, auto):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-14)


def test_dp_grad_equals_delta_rule_snn():
    weights = _make_kernel(78, 5, [6], 3)
    x, t = _sample_snn(10, 5, 3, hot=1)
    acts = snn.forward(weights, x)
    ds = snn.deltas(weights, acts, t)
    manual = ann.bp_update(weights, acts, ds, snn.SNN_LEARN_RATE / t.shape[0])
    # CE error divides by N; the reference's δ=t−o absorbs it (the C code
    # uses the un-normalized δ with η — SURVEY.md §2.4 S3/S4), so the
    # autodiff gradient of (Ep = CE/N) equals δ/N.
    grads = jax.grad(dp.sample_loss)(weights, x, t, model="snn")
    auto = dp.sgd_step(weights, grads, snn.SNN_LEARN_RATE)
    for a, b in zip(manual, auto):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-13)


def test_gspmd_hybrid_step(mesh4):
    """DP×TP sharded jit compiles, runs, and matches replicated math."""
    n_in, hiddens, n_out = 12, [16, 8], 8
    weights = _make_kernel(2024, n_in, hiddens, n_out)
    X, T = _batch(2, 8, n_in, n_out)

    step = dp.make_gspmd_train_step(
        mesh4, weights, model="ann", momentum=False, donate=False
    )
    w_sh = dp.place_kernel(weights, mesh4)
    Xs, Ts = dp.shard_batch(X, T, mesh4)
    got_w, _, got_loss = step(w_sh, (), Xs, Ts)

    grads = jax.grad(dp.batch_loss)(weights, X, T, model="ann")
    want_w = dp.sgd_step(weights, grads, ann.BP_LEARN_RATE)
    for a, b in zip(got_w, want_w):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-12)


def test_gspmd_uneven_snn_unpadded(mesh4):
    """GSPMD shards non-divisible dims itself — no pad_kernel, and the
    unmasked snn.forward stays correct because no pad rows exist."""
    n_in, hiddens, n_out = 7, [10], 5  # nothing divisible by 4
    weights = _make_kernel(61, n_in, hiddens, n_out)
    X, T = _batch(4, 8, n_in, n_out, snn_targets=True)

    step = dp.make_gspmd_train_step(
        mesh4, weights, model="snn", momentum=False, donate=False
    )
    w_sh = dp.place_kernel(weights, mesh4)
    Xs, Ts = dp.shard_batch(X, T, mesh4)
    got_w, _, _ = step(w_sh, (), Xs, Ts)

    # oracle: the reference's hand delta (δ=t−o), not autodiff — see
    # dp.batch_grads (the f32 softmax-saturation rationale)
    grads = dp.batch_grads(weights, X, T, model="snn")
    want_w = dp.sgd_step(weights, grads, snn.SNN_LEARN_RATE)
    for a, b in zip(got_w, want_w):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-12)


def test_gspmd_momentum_step(mesh4):
    weights = _make_kernel(31, 12, [8], 4)
    X, T = _batch(3, 8, 12, 4)
    dw = tuple(jnp.zeros_like(w) for w in weights)

    step = dp.make_gspmd_train_step(
        mesh4, weights, model="ann", momentum=True, donate=False
    )
    w_sh = dp.place_kernel(weights, mesh4)
    dw_sh = dp.place_kernel(dw, mesh4)
    Xs, Ts = dp.shard_batch(X, T, mesh4)
    got_w, got_dw, _ = step(w_sh, dw_sh, Xs, Ts)

    grads = jax.grad(dp.batch_loss)(weights, X, T, model="ann")
    want_w, want_dw = dp.momentum_step(
        weights, dw, grads, ann.BPM_LEARN_RATE, 0.2
    )
    for a, b in zip(got_w, want_w):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-12)
    for a, b in zip(got_dw, want_dw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-12)


def test_snn_batch_grads_are_mean_hand_deltas():
    """dp.batch_grads(snn) == mean over per-sample reference deltas
    (δ=t−o ⊗ v), and it stays alive where autodiff goes numerically
    dead (saturated softmax on large logits — the raw-pixel regime)."""
    weights = _make_kernel(17, 6, [5], 3)
    X, T = _batch(9, 4, 6, 3, snn_targets=True)
    grads = dp.batch_grads(weights, X, T, model="snn")
    # oracle: accumulate per-sample δ⊗v by hand
    want = [np.zeros(np.asarray(w).shape) for w in weights]
    for b in range(X.shape[0]):
        acts = snn.forward(weights, X[b])
        ds = snn.deltas(weights, acts, T[b])
        for l in range(len(weights)):
            want[l] += -np.outer(np.asarray(ds[l]), np.asarray(acts[l]))
    for g, w in zip(grads, want):
        np.testing.assert_allclose(
            np.asarray(g), w / X.shape[0], atol=1e-6
        )

    # saturation regression: once training has driven the f32 softmax
    # hard-one-hot with the target class below the TINY clamp (the
    # measured 60k-MNIST freeze: CE with the pmnist ±1 targets
    # actively saturates it, then loss pins at ≈0.9·log(TINY) and
    # accuracy at chance), the true (autodiff) gradient dies: the
    # log(o+TINY) slope for the target class collapses to o/TINY ≈ 0
    # and the confident class has (1−o) == 0 exactly in f32.  The hand
    # delta still sees δ = t−o = O(1).  Construct the state directly:
    # one logit ~61 above the rest, target on a DIFFERENT class.
    w1 = jnp.ones((4, 2), jnp.float32)        # h ≈ 0.762 each
    w2 = jnp.asarray(np.array([[20.0, 20, 20, 20],
                               [0.0, 0, 0, 0],
                               [0.0, 0, 0, 0]]), jnp.float32)
    wsat = (w1, w2)
    Xs = jnp.ones((1, 2), jnp.float32)
    Ts = jnp.asarray(np.array([[0.0, 1.0, 0.0]]), jnp.float32)
    auto = jax.grad(dp.batch_loss)(wsat, Xs, Ts, model="snn")
    hand = dp.batch_grads(wsat, Xs, Ts, model="snn")
    auto_max = max(float(np.abs(np.asarray(g)).max()) for g in auto)
    hand_max = max(float(np.abs(np.asarray(g)).max()) for g in hand)
    assert auto_max < 1e-10
    assert hand_max > 0.1
